"""Closed-loop elasticity: the autoscale control plane (round 22).

Every ingredient for autoscaling has existed as a MANUAL step since
round 21 — self-registration/drain (round 16), AOT warm-boot artifacts
(round 18), SLO burn-rate gauges and metrics federation (round 19), and
a router fast enough to carry the resulting traffic (round 21) — but a
human still decided when to add or remove a backend.  This module
closes the loop: a controller that polls the federation plane, decides,
and acts — the TensorFlow-Serving framing ("the serving system is the
product", arXiv:1605.08695) applied to fleet sizing, with the
idle-accelerator economics making scale-DOWN exactly as first-class as
scale-up.

Pieces, each independently testable:

- ``parse_exposition`` / ``FleetSignals``: a small Prometheus
  text-format reader over the router's ``GET /v1/metrics/fleet``
  federation output.  The controller consumes ONLY that surface — the
  same bytes an operator's monitoring stack reads — so embedded and
  sidecar deployments see identical signals: multi-window SLO burn
  rates (``router_slo_burn_rate{slo=,window=}``), per-backend job
  pressure (``deconv_jobs_active{backend=}``), per-tenant device-ms
  counters, per-backend warm-hit counters, and scrape health.

- ``DecisionEngine``: pure decision function over signals + clock.
  Scale-up on a SUSTAINED hot signal (burn or queue depth over
  threshold for ``up_consecutive`` polls), scale-down on a sustained
  cold signal, with independent direction cooldowns and an
  up-recent guard — the hysteresis that keeps an oscillating signal
  from flapping the fleet.  Scale-down is additionally gated by the
  per-tenant QoS budget: the device-ms demand rate the fleet is
  actually carrying must still fit on N-1 backends, or the decision is
  blocked with ``reason=qos-budget`` (capacity follows the round 13
  fairness contract, not just latency).

- ``ArrivalHistory``: bounded per-tenant arrival buckets (the round 8
  cardinality rule — tenants beyond ``max_tenants`` fold into
  ``other``) feeding a short-horizon least-squares rate forecast.  A
  projected ramp (``forecast >= predict_ramp x current``) pre-warms ONE
  backend ahead of the load instead of waiting for the burn signal —
  predictive pre-scaling from the fleet's own arrival history.

- ``DecisionJournal``: every decision fsync'd to JSONL before it acts
  (the round 11 job-journal idiom: append-only, one line per edge,
  torn-tail-tolerant replay).  A restarted controller replays the
  journal to restore its cooldown anchors — it never forgets that it
  just scaled.

- ``BackendLauncher``: the pluggable actuator.  ``AdvisoryLauncher``
  (default) only records intents — the dry-run rollout mode where the
  controller publishes decisions on the federation plane and an
  operator (or a real cluster scheduler behind this interface) acts.
  ``SubprocessLauncher`` spawns real processes from an argv template
  (``{port}`` substituted) — the drill/drill-sized-deployment actuator.

- ``AutoscaleController``: owns the loop.  One ``tick()`` = poll →
  parse → decide → journal → act, wrapped fail-STATIC: any error
  (including the ``autoscale.decision_error`` chaos site) increments
  ``autoscaler_errors_total`` and changes NOTHING — a crashing
  controller must never flap the fleet it manages.  Scale-up measures
  **boot-to-first-warm-hit** end-to-end (launch → self-registration →
  first warm counter increment on the federation plane) as the
  ``autoscaler_boot_to_warm_seconds`` histogram — the warm-boot path
  (AOT store + L2 hotset, round 18) is the thing being exploited, so
  its latency is the controller's first-class success metric.
  Scale-down is a zero-loss citizen: drain-announce (round 16), wait
  for in-flight work AND the jobs tier — a backend whose ``/v1/jobs``
  still shows ``running``/``parked`` jobs is NEVER reaped (the round
  11 drain contract covered requests; this extends it to the round 6
  job tier) — then reap, leaving the L2 directory in place for the
  next boot.

Metric families (own ``autoscaler_`` registry, appended to the
router's exposition): ``autoscaler_decisions_total{action=,reason=}``,
``autoscaler_fleet_size``, ``autoscaler_pending_launches``,
``autoscaler_boot_to_warm_seconds`` (histogram),
``autoscaler_errors_total``, ``autoscaler_launch_failures_total``,
``autoscaler_reap_blocked_total``.

``--autoscale off`` (the default) is the escape hatch with the same
contract every round has shipped: no controller object, no arrival
recording, no config/readyz block, no metric families — the router is
byte-identical to round 21 behavior.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import re
import shlex
import socket
import subprocess
import time
from typing import Callable

from deconv_api_tpu.serving import durable
from deconv_api_tpu.serving import faults as faults_mod
from deconv_api_tpu.serving import fleet as fleet_mod
from deconv_api_tpu.serving.metrics import Metrics
from deconv_api_tpu.utils import slog

_log = logging.getLogger("deconv.autoscale")

MODES = ("off", "advisory", "enforce")

# ------------------------------------------------------------- signals

# One exposition sample line: name, optional {labels}, value.  NaN/Inf
# spellings are accepted by float() directly; timestamps (a third
# field) are not emitted by this stack and are rejected by the \s*$.
_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)"
    r"(?:\{([^}]*)\})?"
    r"\s+([^\s]+)\s*$"
)
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return v.replace('\\"', '"').replace("\\n", "\n").replace("\\\\", "\\")


def parse_exposition(text: str) -> list[tuple[str, dict, float]]:
    """Prometheus text format -> ``[(family, labels, value), ...]``.

    Deliberately forgiving: comment/TYPE/HELP lines and anything
    unparseable are skipped, not errors — the controller reads a
    federation surface that splices N backends' expositions together,
    and one backend's malformed line must not blind it to the rest."""
    out: list[tuple[str, dict, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group(3))
        except ValueError:
            continue
        labels = {
            k: _unescape(v) for k, v in _LABEL_RE.findall(m.group(2) or "")
        }
        out.append((m.group(1), labels, value))
    return out


# The warm-hit vocabulary: any of these counters moving on a freshly
# launched backend means the warm-boot path (round 18 AOT artifacts +
# round 16 L2 hotset) delivered — the boot-to-first-warm-hit clock
# stops on the first one.
WARM_HIT_FAMILIES = (
    "deconv_cache_hits_total",
    "deconv_cache_l2_hits_total",
    "deconv_aot_cache_hits_total",
)


class FleetSignals:
    """One poll's view of the federation plane, pre-digested for the
    decision engine.  All fields are plain data — the parse is the only
    logic, so a canned exposition text IS a full test fixture."""

    __slots__ = (
        "burn", "queue_depth", "jobs_running", "jobs_parked",
        "device_ms", "scrape_ok", "backends_scraped", "requests_total",
        "warm_hits",
    )

    def __init__(self) -> None:
        self.burn: dict[tuple[str, str], float] = {}
        self.queue_depth: dict[str, float] = {}
        self.jobs_running: dict[str, float] = {}
        self.jobs_parked: dict[str, float] = {}
        self.device_ms: dict[str, float] = {}
        self.scrape_ok: dict[str, bool] = {}
        self.backends_scraped: int = 0
        self.requests_total: float = 0.0
        self.warm_hits: dict[str, float] = {}

    @classmethod
    def from_exposition(cls, text: str) -> "FleetSignals":
        s = cls()
        for family, labels, value in parse_exposition(text):
            backend = labels.get("backend", "")
            if family == "router_slo_burn_rate":
                slo = labels.get("slo", "")
                window = labels.get("window", "")
                # N SO_REUSEPORT workers export one gauge each; the
                # fleet's burn is the WORST worker's view
                key = (slo, window)
                s.burn[key] = max(s.burn.get(key, 0.0), value)
            elif family == "deconv_jobs_active" and backend:
                s.queue_depth[backend] = value
            elif family == "deconv_jobs_running" and backend:
                s.jobs_running[backend] = value
            elif family == "deconv_jobs_parked" and backend:
                s.jobs_parked[backend] = value
            elif family == "deconv_tenant_device_ms_total":
                tenant = labels.get("tenant", "default")
                s.device_ms[tenant] = s.device_ms.get(tenant, 0.0) + value
            elif family == "fleet_scrape_ok" and backend:
                s.scrape_ok[backend] = value >= 1.0
            elif family == "fleet_backends_scraped":
                s.backends_scraped = int(value)
            elif family == "router_requests_total" and not labels:
                s.requests_total += value
            elif family in WARM_HIT_FAMILIES and backend:
                s.warm_hits[backend] = s.warm_hits.get(backend, 0.0) + value
        return s

    def burn_max(self, window: str = "5m") -> float:
        """Worst burn rate across SLOs for one window (0.0 when no SLOs
        are configured — burn then never drives a decision and queue
        depth is the only hot signal)."""
        vals = [v for (_slo, w), v in self.burn.items() if w == window]
        return max(vals, default=0.0)

    def queue_mean(self) -> float:
        """Mean per-backend job pressure over backends the federation
        actually scraped OK this round — a vanished backend's last-good
        splice must not drag the mean."""
        vals = [
            v for b, v in self.queue_depth.items()
            if self.scrape_ok.get(b, True)
        ]
        if not vals:
            return 0.0
        return sum(vals) / len(vals)


# ------------------------------------------------------------- arrivals


class ArrivalHistory:
    """Bounded per-tenant arrival counts in fixed wall buckets, feeding
    the short-horizon rate forecast.

    Memory is explicitly bounded (the round 8 tenant-cardinality rule):
    at most ``max_buckets`` buckets, and per bucket at most
    ``max_tenants`` distinct tenants — the long tail folds into
    ``other``.  ``record`` is O(1) and runs on the proxy hot path, so
    it must stay an append/increment, nothing more."""

    def __init__(
        self,
        *,
        bucket_s: float = 5.0,
        max_buckets: int = 64,
        max_tenants: int = 32,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.bucket_s = max(0.5, float(bucket_s))
        self.max_buckets = max(4, int(max_buckets))
        self.max_tenants = max(1, int(max_tenants))
        self._clock = clock
        # bucket index -> {tenant: count}; plain dict ordered by insert,
        # trimmed from the front — deque-of-dicts without the dance
        self._buckets: dict[int, dict[str, int]] = {}

    def record(self, tenant: str, n: int = 1) -> None:
        idx = int(self._clock() / self.bucket_s)
        b = self._buckets.get(idx)
        if b is None:
            b = self._buckets[idx] = {}
            while len(self._buckets) > self.max_buckets:
                self._buckets.pop(next(iter(self._buckets)))
        if tenant not in b and len(b) >= self.max_tenants:
            tenant = "other"
        b[tenant] = b.get(tenant, 0) + n

    def _rates(self, n: int) -> list[float]:
        """Total req/s for the last ``n`` COMPLETE buckets (the current
        partial bucket would read artificially low)."""
        cur = int(self._clock() / self.bucket_s)
        out = []
        for idx in range(cur - n, cur):
            counts = self._buckets.get(idx, {})
            out.append(sum(counts.values()) / self.bucket_s)
        return out

    def rate(self, n: int = 3) -> float:
        """Current arrival rate: mean over the last n complete buckets."""
        rates = self._rates(n)
        if not rates:
            return 0.0
        return sum(rates) / len(rates)

    def forecast(self, horizon_s: float, n: int = 6) -> tuple[float, float]:
        """(current rate, projected rate at now+horizon): least-squares
        slope over the last ``n`` complete bucket rates, extrapolated
        ``horizon_s`` ahead and clamped at zero.  Coarse on purpose —
        the decision only needs "a ramp is coming", not its shape."""
        rates = self._rates(n)
        cur = self.rate()
        if len(rates) < 3:
            return cur, cur
        xs = [i * self.bucket_s for i in range(len(rates))]
        mean_x = sum(xs) / len(xs)
        mean_y = sum(rates) / len(rates)
        sxx = sum((x - mean_x) ** 2 for x in xs)
        if sxx <= 0:
            return cur, cur
        slope = sum(
            (x - mean_x) * (y - mean_y) for x, y in zip(xs, rates)
        ) / sxx
        projected = max(0.0, cur + slope * float(horizon_s))
        return cur, projected


class TsdbArrivalHistory:
    """ArrivalHistory's interface backed by the router's embedded TSDB
    (round 23: "the forecaster reads the same history the operator
    queries").

    ``record`` increments a per-tenant ``arrivals_total`` counter in
    the ROUTER's metrics registry (bounded cardinality: the tenant tail
    folds into ``other``, the round 8 rule); the router's self-scrape
    tick turns that into rate series, and ``rate``/``forecast`` read
    those series back — so ``GET /v1/metrics/history?family=
    arrivals_total`` shows exactly the per-tenant arrival curves the
    predictive scale-up acted on, and the decision journal's forecast
    numbers are reproducible from the same query after the fact.

    The private accumulator (ArrivalHistory) remains the tsdb=off
    fallback: byte-parity demands the off path not grow new state."""

    def __init__(
        self,
        tsdb,
        metrics: Metrics,
        *,
        bucket_s: float = 5.0,
        max_tenants: int = 32,
    ):
        self.tsdb = tsdb
        self.metrics = metrics
        # a forecast bucket can't be finer than the scrape tick
        self.bucket_s = max(float(bucket_s), tsdb.interval_s)
        self.max_tenants = max(1, int(max_tenants))
        self._tenants: set[str] = set()
        # pre-register so the family exists from the first scrape
        self.metrics.inc_labeled("arrivals_total", "tenant", "default", 0)

    def record(self, tenant: str, n: int = 1) -> None:
        t = tenant or "default"
        if t not in self._tenants:
            if len(self._tenants) >= self.max_tenants:
                t = "other"
            self._tenants.add(t)
        self.metrics.inc_labeled("arrivals_total", "tenant", t, n)

    def _rates(self, n: int) -> list[float]:
        """Aggregate req/s per forecast bucket for the last ``n``
        complete buckets, oldest first — ArrivalHistory._rates'
        contract, reconstructed from the TSDB's raw rate ticks."""
        tick = self.tsdb.interval_s
        series = self.tsdb.query(
            "arrivals_total", None, range_s=(n + 1) * self.bucket_s
        )
        # sum across tenant series per scrape tick (ages within one
        # query share the same fractional offset, so the rounded tick
        # ordinal is a stable join key)
        per_tick: dict[int, float] = {}
        for ent in series:
            for p in ent["points"]:
                key = round(p[0] / tick)
                per_tick[key] = per_tick.get(key, 0.0) + p[1]
        # fold ticks into buckets; bucket 0 is the current partial one
        # and is skipped, like ArrivalHistory's current wall bucket
        per_bucket: dict[int, list[float]] = {}
        for key, rate in per_tick.items():
            per_bucket.setdefault(int(key * tick / self.bucket_s), []).append(
                rate
            )
        out = []
        for b in range(n, 0, -1):
            vals = per_bucket.get(b)
            out.append(sum(vals) / len(vals) if vals else 0.0)
        return out

    def rate(self, n: int = 3) -> float:
        rates = self._rates(n)
        if not rates:
            return 0.0
        return sum(rates) / len(rates)

    def forecast(self, horizon_s: float, n: int = 6) -> tuple[float, float]:
        """Same least-squares extrapolation as ArrivalHistory.forecast,
        over TSDB-reconstructed bucket rates."""
        rates = self._rates(n)
        cur = self.rate()
        if len(rates) < 3:
            return cur, cur
        xs = [i * self.bucket_s for i in range(len(rates))]
        mean_x = sum(xs) / len(xs)
        mean_y = sum(rates) / len(rates)
        sxx = sum((x - mean_x) ** 2 for x in xs)
        if sxx <= 0:
            return cur, cur
        slope = sum(
            (x - mean_x) * (y - mean_y) for x, y in zip(xs, rates)
        ) / sxx
        projected = max(0.0, cur + slope * float(horizon_s))
        return cur, projected


# ------------------------------------------------------------- journal


class DecisionJournal(durable.Journal):
    """Append-only fsync'd JSONL of every decision, on the shared
    ``durable.Journal`` body since round 24: the record is DURABLE
    before the action runs, so a controller that dies mid-action can
    never have acted on a decision it has no memory of.  FAIL-LOUD
    durable surface — an append that cannot fsync raises
    ``DurableWriteError`` out of the controller tick rather than
    acting on an unremembered decision; a journal written by a NEWER
    binary raises ``FutureVersionError`` at replay (refuse rather than
    misparse)."""

    _FORMAT = "autoscale.journal"
    _VERSION = 1

    def __init__(self, path: str, *, metrics=None):
        super().__init__(
            path,
            durable.Surface("autoscale.journal", metrics=metrics),
            fmt=self._FORMAT,
            version=self._VERSION,
        )

    @staticmethod
    def replay(path: str) -> list[dict]:
        """All intact data records; a torn tail (the crash-mid-append
        case) or an interleaved bad line is skipped, never an error.
        The version-header record is validated and excluded."""
        records, _torn = durable.Journal.replay(
            path, DecisionJournal._FORMAT, DecisionJournal._VERSION
        )
        return records


# ------------------------------------------------------------- engine


class Decision:
    """One evaluation's verdict: ``action`` in (up|down|hold), a
    closed-vocabulary ``reason`` (the decisions_total label — bounded
    cardinality by construction), and free-form detail for the journal."""

    __slots__ = ("action", "reason", "detail")

    def __init__(self, action: str, reason: str, **detail):
        self.action = action
        self.reason = reason
        self.detail = detail

    def to_dict(self) -> dict:
        return {"action": self.action, "reason": self.reason, **self.detail}


class DecisionEngine:
    """Pure scale decision over (signals, fleet size, clock): all the
    hysteresis lives here, none of the actuation.

    Hot = burn >= ``up_burn`` OR mean queue >= ``up_queue``; cold =
    burn <= ``down_burn`` AND mean queue <= ``down_queue``.  A decision
    fires only after ``up_consecutive``/``down_consecutive`` SUSTAINED
    polls, then arms the direction's cooldown; scale-down additionally
    refuses while a scale-up is recent (a spike that just added
    capacity must not be un-added the moment it passes) and while the
    measured device-ms demand would not fit on N-1 backends (the QoS
    budget gate)."""

    def __init__(
        self,
        *,
        up_burn: float = 0.9,
        up_queue: float = 4.0,
        down_burn: float = 0.2,
        down_queue: float = 0.5,
        up_consecutive: int = 2,
        down_consecutive: int = 5,
        cooldown_up_s: float = 30.0,
        cooldown_down_s: float = 120.0,
        min_backends: int = 1,
        max_backends: int = 4,
        qos_device_ms_budget: float = 800.0,
        predict_horizon_s: float = 30.0,
        predict_ramp: float = 2.0,
        predict_min_rate: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.up_burn = float(up_burn)
        self.up_queue = float(up_queue)
        self.down_burn = float(down_burn)
        self.down_queue = float(down_queue)
        self.up_consecutive = max(1, int(up_consecutive))
        self.down_consecutive = max(1, int(down_consecutive))
        self.cooldown_up_s = float(cooldown_up_s)
        self.cooldown_down_s = float(cooldown_down_s)
        self.min_backends = max(1, int(min_backends))
        self.max_backends = max(self.min_backends, int(max_backends))
        self.qos_device_ms_budget = float(qos_device_ms_budget)
        self.predict_horizon_s = float(predict_horizon_s)
        self.predict_ramp = float(predict_ramp)
        self.predict_min_rate = float(predict_min_rate)
        self._clock = clock
        self._up_streak = 0
        self._down_streak = 0
        # cooldown anchors; restored from the journal on restart
        self.last_up_ts = float("-inf")
        self.last_down_ts = float("-inf")
        # previous per-tenant cumulative device-ms sample, for rates
        self._last_device_ms: tuple[float, dict[str, float]] | None = None

    # -- demand rate ---------------------------------------------------

    def device_ms_rates(self, signals: FleetSignals) -> dict[str, float]:
        """Per-tenant device-ms/s from cumulative counter deltas.  A
        negative delta (backend restart / membership change reset the
        sum) clamps to zero — one poll of under-reading beats a bogus
        spike."""
        now = self._clock()
        prev = self._last_device_ms
        self._last_device_ms = (now, dict(signals.device_ms))
        if prev is None:
            return {}
        dt = now - prev[0]
        if dt <= 0:
            return {}
        return {
            tenant: max(0.0, (cum - prev[1].get(tenant, 0.0)) / dt)
            for tenant, cum in signals.device_ms.items()
        }

    # -- evaluation ----------------------------------------------------

    def evaluate(
        self,
        signals: FleetSignals,
        fleet_size: int,
        *,
        pending: int = 0,
        arrivals: ArrivalHistory | None = None,
    ) -> Decision:
        now = self._clock()
        effective = fleet_size + pending
        burn = signals.burn_max("5m")
        qmean = signals.queue_mean()
        rates = self.device_ms_rates(signals)
        demand_ms = sum(rates.values())
        hot = burn >= self.up_burn or qmean >= self.up_queue
        cold = burn <= self.down_burn and qmean <= self.down_queue
        self._up_streak = self._up_streak + 1 if hot else 0
        self._down_streak = self._down_streak + 1 if cold else 0

        base = {
            "burn_5m": round(burn, 4),
            "queue_mean": round(qmean, 3),
            "fleet_size": fleet_size,
            "pending": pending,
            "demand_device_ms_s": round(demand_ms, 1),
        }

        if self._up_streak >= self.up_consecutive:
            if effective >= self.max_backends:
                return Decision("hold", "at-max", **base)
            if now - self.last_up_ts < self.cooldown_up_s:
                return Decision("hold", "cooldown-up", **base)
            self.last_up_ts = now
            self._up_streak = 0
            reason = "burn" if burn >= self.up_burn else "queue"
            return Decision("up", reason, **base)

        # predictive pre-scale: one backend ahead of a projected ramp,
        # under the same cooldown as a reactive up — never a second one
        if (
            arrivals is not None
            and effective < self.max_backends
            and now - self.last_up_ts >= self.cooldown_up_s
        ):
            cur, projected = arrivals.forecast(self.predict_horizon_s)
            if (
                cur >= self.predict_min_rate
                and projected >= self.predict_ramp * cur
            ):
                self.last_up_ts = now
                return Decision(
                    "up", "predictive",
                    rate=round(cur, 2), projected=round(projected, 2),
                    **base,
                )

        if self._down_streak >= self.down_consecutive:
            if effective <= self.min_backends:
                return Decision("hold", "at-min", **base)
            if now - self.last_down_ts < self.cooldown_down_s:
                return Decision("hold", "cooldown-down", **base)
            if now - self.last_up_ts < self.cooldown_down_s:
                # just scaled up: the signal going quiet does not prove
                # the added capacity is surplus yet
                return Decision("hold", "up-recent", **base)
            if effective > 1 and (
                demand_ms / (effective - 1) > self.qos_device_ms_budget
            ):
                return Decision("hold", "qos-budget", **base)
            self.last_down_ts = now
            self._down_streak = 0
            return Decision("down", "idle", **base)

        return Decision("hold", "steady", **base)

    def restore(self, records: list[dict], now: float) -> None:
        """Restore cooldown anchors from replayed journal records.  A
        recorded clock ahead of OUR clock (the previous process lived
        on a different monotonic epoch) clamps to now — the conservative
        read: a full cooldown after restart, never a skipped one."""
        for rec in records:
            ts = rec.get("clock")
            if not isinstance(ts, (int, float)):
                continue
            ts = min(float(ts), now)
            if rec.get("action") == "up":
                self.last_up_ts = max(self.last_up_ts, ts)
            elif rec.get("action") == "down":
                self.last_down_ts = max(self.last_down_ts, ts)


# ------------------------------------------------------------ launchers


class LaunchError(RuntimeError):
    """A launch attempt failed before the backend existed — retryable,
    and by construction never counted as fleet capacity."""


class LaunchedBackend:
    __slots__ = ("name", "handle", "t_launch")

    def __init__(self, name: str, handle=None, t_launch: float = 0.0):
        self.name = name          # host:port
        self.handle = handle      # actuator-private (subprocess.Popen)
        self.t_launch = t_launch  # controller clock at launch


class BackendLauncher:
    """The actuator interface a real deployment implements: ``launch``
    returns the new backend's ``host:port`` (or None for an advisory
    actuator that only records intent); ``reap`` tears one down AFTER
    the controller has drained it and proven the jobs tier empty."""

    async def launch(self) -> LaunchedBackend | None:
        raise NotImplementedError

    async def reap(self, name: str, handle=None) -> None:
        raise NotImplementedError


class AdvisoryLauncher(BackendLauncher):
    """Dry-run actuator: records every intent, changes nothing.  The
    rollout mode — run the controller against production signals,
    read its journal/metrics, and only then hand it a real launcher."""

    def __init__(self) -> None:
        self.intents: list[str] = []

    async def launch(self) -> LaunchedBackend | None:
        self.intents.append("launch")
        return None

    async def reap(self, name: str, handle=None) -> None:
        self.intents.append(f"reap {name}")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class SubprocessLauncher(BackendLauncher):
    """Real-process actuator for drills and single-host deployments:
    ``argv_template`` elements are ``str.format``-ed with ``port`` (and
    ``host``), e.g.::

        python -m deconv_api_tpu.cli serve --port {port} \\
            --aot-dir /srv/aot --l2-dir /srv/l2/b{port} \\
            --fleet-routers 127.0.0.1:8100 --fleet-token T

    The launched process is expected to self-register (round 16) — the
    launcher's job ends at a live PID; registration, warmth, and reap
    gating are the controller's."""

    def __init__(
        self,
        argv_template: list[str] | str,
        *,
        host: str = "127.0.0.1",
        env: dict | None = None,
        cwd: str | None = None,
    ):
        if isinstance(argv_template, str):
            argv_template = shlex.split(argv_template)
        if not argv_template:
            raise ValueError("launch command must not be empty")
        self.argv_template = list(argv_template)
        self.host = host
        self.env = env
        self.cwd = cwd
        self.procs: dict[str, subprocess.Popen] = {}

    async def launch(self) -> LaunchedBackend:
        port = _free_port()
        argv = [
            a.format(port=port, host=self.host) for a in self.argv_template
        ]
        try:
            proc = subprocess.Popen(
                argv,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                env=self.env,
                cwd=self.cwd,
            )
        except OSError as e:
            raise LaunchError(f"spawn failed: {e}") from e
        await asyncio.sleep(0.05)
        if proc.poll() is not None:
            raise LaunchError(
                f"backend exited rc={proc.returncode} before serving"
            )
        name = f"{self.host}:{port}"
        self.procs[name] = proc
        return LaunchedBackend(name, handle=proc)

    async def reap(self, name: str, handle=None) -> None:
        proc = handle or self.procs.pop(name, None)
        self.procs.pop(name, None)
        if proc is None:
            return
        proc.terminate()
        for _ in range(50):
            if proc.poll() is not None:
                return
            await asyncio.sleep(0.1)
        proc.kill()
        proc.wait(timeout=5)


# ----------------------------------------------------------- controller


class AutoscaleController:
    """The loop: poll the federation plane, decide, journal, act.

    Embedded (``router=`` set): polls the router's own
    ``_metrics_fleet`` handler in-process, drains via the router's
    member state, counts fleet size from the live ring.  Sidecar
    (``router_addr=`` set): the exact same loop over HTTP — the
    federation scrape, and drain announcements through the
    token-authenticated ``POST /v1/internal/register`` surface.

    ``mode`` is ``advisory`` (decide + journal + publish, never act) or
    ``enforce`` (act through the launcher).  Construction with
    ``mode="off"`` is a caller bug — the escape hatch is the ABSENCE of
    this object (fleet.py holds ``autoscaler=None``), not a disabled
    instance."""

    def __init__(
        self,
        *,
        mode: str = "advisory",
        router=None,
        router_addr: str = "",
        fleet_token: str = "",
        interval_s: float = 5.0,
        journal_path: str = "",
        launch_cmd: str = "",
        launcher: BackendLauncher | None = None,
        engine: DecisionEngine | None = None,
        engine_opts: dict | None = None,
        faults: "faults_mod.FaultRegistry | None" = None,
        metrics: Metrics | None = None,
        launch_retries: int = 3,
        retry_backoff_s: float = 1.0,
        warm_timeout_s: float = 120.0,
        drain_grace_s: float = 60.0,
        drain_settle_s: float = 1.0,
        jobs_poll_timeout_s: float = 5.0,
        arrival_bucket_s: float = 5.0,
        tsdb=None,
        tsdb_metrics: Metrics | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if mode not in ("advisory", "enforce"):
            raise ValueError(
                f"autoscale mode {mode!r}: expected advisory|enforce "
                "(off means: do not construct a controller)"
            )
        if router is None and not router_addr:
            raise ValueError(
                "controller needs a router (embedded) or router_addr "
                "(sidecar)"
            )
        self.mode = mode
        self.router = router
        self.router_addr = router_addr
        self.fleet_token = fleet_token
        self.interval_s = max(0.05, float(interval_s))
        self._clock = clock
        self.faults = faults
        self.metrics = metrics or Metrics(prefix="autoscaler", core=False)
        self.engine = engine or DecisionEngine(
            clock=clock, **(engine_opts or {})
        )
        if launcher is None:
            launcher = (
                SubprocessLauncher(launch_cmd)
                if launch_cmd
                else AdvisoryLauncher()
            )
        self.launcher = launcher
        self.launch_retries = max(0, int(launch_retries))
        self.retry_backoff_s = max(0.0, float(retry_backoff_s))
        self.warm_timeout_s = float(warm_timeout_s)
        self.drain_grace_s = float(drain_grace_s)
        self.drain_settle_s = max(0.0, float(drain_settle_s))
        self.jobs_poll_timeout_s = float(jobs_poll_timeout_s)
        # Round 23: with a router-side TSDB, the forecaster reads
        # per-tenant arrival history from it (TsdbArrivalHistory) — the
        # same series an operator queries at /v1/metrics/history — and
        # the private accumulator stays the tsdb=off fallback.
        if tsdb is not None:
            self.arrivals = TsdbArrivalHistory(
                tsdb, tsdb_metrics or self.metrics,
                bucket_s=arrival_bucket_s,
            )
        else:
            self.arrivals = ArrivalHistory(
                bucket_s=arrival_bucket_s, clock=clock
            )
        self.journal = (
            DecisionJournal(journal_path, metrics=self.metrics)
            if journal_path else None
        )
        if journal_path:
            self.engine.restore(
                DecisionJournal.replay(journal_path), clock()
            )
        # launches awaiting first warm hit: name -> LaunchedBackend.
        # ONE launch in flight at a time — a retry replaces, never
        # stacks, so fleet size is never double-counted.
        self.pending: dict[str, LaunchedBackend] = {}
        # drain watchers: name -> asyncio.Task
        self.draining: dict[str, asyncio.Task] = {}
        self._task: asyncio.Task | None = None
        self._last_decision: dict | None = None
        self._last_signals: FleetSignals | None = None
        self.ticks_total = 0
        # pre-register every counter family at zero (the round 21
        # idiom): the lint and rate() queries must see them from the
        # first scrape, fired or not
        for fam in ("errors_total", "launch_failures_total",
                    "reap_blocked_total", "reaped_total"):
            self.metrics.inc_counter(fam, 0)
        self.metrics.inc_labeled(
            "decisions_total", ("action", "reason"), ("hold", "steady"), 0
        )
        self.metrics.set_gauge("fleet_size", 0)
        self.metrics.set_gauge("pending_launches", 0)

    # -- surfaces ------------------------------------------------------

    def record_arrival(self, tenant: str) -> None:
        """Proxy hot-path hook (fleet.py): one O(1) bucket increment."""
        self.arrivals.record(tenant or "default")

    def config_block(self) -> dict:
        e = self.engine
        return {
            "mode": self.mode,
            "interval_s": self.interval_s,
            "min_backends": e.min_backends,
            "max_backends": e.max_backends,
            "up_burn": e.up_burn,
            "up_queue": e.up_queue,
            "down_burn": e.down_burn,
            "down_queue": e.down_queue,
            "up_consecutive": e.up_consecutive,
            "down_consecutive": e.down_consecutive,
            "cooldown_up_s": e.cooldown_up_s,
            "cooldown_down_s": e.cooldown_down_s,
            "qos_device_ms_budget": e.qos_device_ms_budget,
            "predict_horizon_s": e.predict_horizon_s,
            "predict_ramp": e.predict_ramp,
            "journal": self.journal.path if self.journal else None,
            "launcher": type(self.launcher).__name__,
        }

    def ready_block(self) -> dict:
        s = self._last_signals
        return {
            "mode": self.mode,
            "ticks": self.ticks_total,
            "pending_launches": len(self.pending),
            "draining": sorted(self.draining),
            "burn_5m_max": round(s.burn_max("5m"), 4) if s else None,
            "queue_mean": round(s.queue_mean(), 3) if s else None,
            "last_decision": self._last_decision,
            "errors_total": self.metrics.counter("errors_total"),
        }

    # -- polling -------------------------------------------------------

    async def _poll_text(self) -> str:
        if self.router is not None:
            resp = await self.router._metrics_fleet(None)
            body = resp.body
            return body.decode() if isinstance(body, bytes) else str(body)
        host, _, port = self.router_addr.rpartition(":")
        status, _h, body = await fleet_mod.raw_request(
            host, int(port), "GET", "/v1/metrics/fleet", {}, b"",
            self.jobs_poll_timeout_s,
        )
        if status != 200:
            raise RuntimeError(f"federation scrape: HTTP {status}")
        return body.decode(errors="replace")

    def _fleet_size(self, signals: FleetSignals) -> int:
        if self.router is not None:
            return sum(
                1 for m in self.router.members.values()
                if m.in_ring and not m.announced_drain
            )
        # sidecar: the scraped-OK backends ARE the live fleet, minus
        # the ones we are currently draining
        return sum(
            1 for b, ok in signals.scrape_ok.items()
            if ok and b not in self.draining
        )

    # -- the loop ------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            await self.tick()
            await asyncio.sleep(self.interval_s)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        for t in list(self.draining.values()):
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass
        self.draining.clear()
        if self.journal is not None:
            self.journal.close()

    async def tick(self) -> None:
        """One control iteration, fail-STATIC: any error — a scrape
        gone wrong, a parse surprise, the ``autoscale.decision_error``
        chaos site — counts ``autoscaler_errors_total`` and changes
        nothing.  The fleet a broken controller manages keeps its last
        size; flapping is strictly worse than stasis."""
        try:
            await self._tick_inner()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — fail-static by contract
            self.metrics.inc_counter("errors_total")
            slog.event(
                _log, "autoscale_tick_error", level=logging.WARNING,
                error=str(e),
            )

    async def _tick_inner(self) -> None:
        self.ticks_total += 1
        if self.faults is not None:
            act = self.faults.check("autoscale.decision_error")
            if act is not None:
                raise RuntimeError("injected decision error")
        signals = FleetSignals.from_exposition(await self._poll_text())
        self._last_signals = signals
        fleet_size = self._fleet_size(signals)
        self.metrics.set_gauge("fleet_size", fleet_size)
        self.metrics.set_gauge("pending_launches", len(self.pending))
        self._check_pending_warm(signals)
        decision = self.engine.evaluate(
            signals, fleet_size,
            pending=len(self.pending) + len(self.draining),
            arrivals=self.arrivals,
        )
        self._last_decision = decision.to_dict()
        if decision.action != "hold" or decision.reason != "steady":
            # every decision that is (or blocks) an action is journaled
            # and counted; the steady-state hold is neither
            self.metrics.inc_labeled(
                "decisions_total", ("action", "reason"),
                (decision.action, decision.reason),
            )
            self._journal({
                "kind": "decision", **decision.to_dict(),
                "mode": self.mode, "clock": self._clock(),
            })
        if self.mode != "enforce":
            return
        if decision.action == "up":
            await self._scale_up(decision)
        elif decision.action == "down":
            await self._scale_down(decision)

    def _journal(self, record: dict) -> None:
        if self.journal is not None:
            self.journal.append(record)
        if self.router is not None and self.router.recorder is not None:
            # decision spans on the router's flight-recorder spine: the
            # controller's actions are debuggable next to the requests
            # they were taken for
            tr = fleet_mod.RequestTrace(
                f"autoscale-{self.ticks_total:06d}", "autoscale"
            )
            tr.annotate(**{
                k: v for k, v in record.items() if k != "kind"
            })
            tr.finish(200)
            self.router.recorder.record(tr)

    # -- scale up ------------------------------------------------------

    async def _scale_up(self, decision: Decision) -> None:
        if self.pending:
            return  # one launch in flight; never stack (no double-count)
        lb: LaunchedBackend | None = None
        for attempt in range(self.launch_retries + 1):
            try:
                if self.faults is not None:
                    act = self.faults.check("autoscale.launch_fail")
                    if act is not None:
                        raise LaunchError("injected launch failure")
                lb = await self.launcher.launch()
                break
            except Exception as e:  # noqa: BLE001 — retry with backoff
                self.metrics.inc_counter("launch_failures_total")
                self._journal({
                    "kind": "launch_failed", "attempt": attempt,
                    "error": str(e), "clock": self._clock(),
                })
                if attempt >= self.launch_retries:
                    self.metrics.inc_counter("errors_total")
                    return
                await asyncio.sleep(
                    self.retry_backoff_s * (2 ** attempt)
                )
        if lb is None:
            return  # advisory launcher: intent recorded, nothing to track
        lb.t_launch = self._clock()
        self.pending[lb.name] = lb
        self.metrics.set_gauge("pending_launches", len(self.pending))
        self._journal({
            "kind": "launched", "backend": lb.name,
            "reason": decision.reason, "clock": self._clock(),
        })

    def _check_pending_warm(self, signals: FleetSignals) -> None:
        """Stop the boot-to-first-warm-hit clock: the launched backend
        has self-registered (it appears on the federation plane) AND a
        warm-hit counter moved.  Registration is part of the measured
        path on purpose — the metric is the operator's answer to "how
        long until a launch actually absorbs load warm"."""
        for name, lb in list(self.pending.items()):
            registered = signals.scrape_ok.get(name, False)
            if self.router is not None:
                m = self.router.members.get(name)
                registered = m is not None and m.in_ring
            if registered and signals.warm_hits.get(name, 0.0) > 0:
                dt = self._clock() - lb.t_launch
                self.metrics.observe_hist(
                    "boot_to_warm_seconds", "backend", name, dt
                )
                self.metrics.set_gauge("last_boot_to_warm_seconds", dt)
                self._journal({
                    "kind": "warm", "backend": name,
                    "boot_to_warm_s": round(dt, 3),
                    "clock": self._clock(),
                })
                del self.pending[name]
            elif self._clock() - lb.t_launch > self.warm_timeout_s:
                self.metrics.inc_counter("errors_total")
                self._journal({
                    "kind": "warm_timeout", "backend": name,
                    "clock": self._clock(),
                })
                del self.pending[name]
        self.metrics.set_gauge("pending_launches", len(self.pending))

    # -- scale down ----------------------------------------------------

    def _pick_victim(self, signals: FleetSignals) -> str | None:
        """Lowest job pressure wins; prefer backends this controller's
        launcher owns a handle for (it can actually reap those)."""
        if self.router is not None:
            candidates = [
                m.name for m in self.router.members.values()
                if m.in_ring and not m.announced_drain
                and m.name not in self.draining
            ]
        else:
            candidates = [
                b for b, ok in signals.scrape_ok.items()
                if ok and b not in self.draining
            ]
        candidates = [c for c in candidates if c not in self.pending]
        if not candidates:
            return None
        owned = getattr(self.launcher, "procs", {})
        candidates.sort(
            key=lambda n: (n not in owned, signals.queue_depth.get(n, 0.0))
        )
        return candidates[0]

    async def _scale_down(self, decision: Decision) -> None:
        if self.draining:
            return  # one drain at a time: losses compound, savings don't
        signals = self._last_signals
        victim = self._pick_victim(signals) if signals else None
        if victim is None:
            return
        await self._announce_drain(victim)
        self._journal({
            "kind": "drain_announced", "backend": victim,
            "reason": decision.reason, "clock": self._clock(),
        })
        self.draining[victim] = asyncio.create_task(
            self._drain_and_reap(victim)
        )

    async def _announce_drain(self, name: str) -> None:
        if self.router is not None:
            m = self.router.members.get(name)
            if m is not None:
                self.router._mark_announced_drain(m, "autoscale")
                self.router._persist_membership()
            return
        host, _, port = self.router_addr.rpartition(":")
        await fleet_mod.raw_request(
            host, int(port), "POST", "/v1/internal/register",
            {
                "x-fleet-token": self.fleet_token,
                "content-type": "application/x-www-form-urlencoded",
            },
            f"backend={name}&action=drain".encode(),
            self.jobs_poll_timeout_s,
        )

    async def _jobs_clear(self, name: str) -> bool:
        """The jobs-tier reap gate: ``/v1/jobs`` must show ZERO
        running/parked jobs.  Unreachable or malformed reads as NOT
        clear — a backend that cannot prove its jobs are terminal or
        re-claimed is never reaped on a guess."""
        host, _, port = name.rpartition(":")
        try:
            status, _h, body = await fleet_mod.raw_request(
                host, int(port), "GET", "/v1/jobs", {}, b"",
                self.jobs_poll_timeout_s,
            )
            if status != 200:
                return False
            counts = json.loads(body).get("counts", {})
        except Exception:  # noqa: BLE001 — unreachable = cannot prove
            return False
        return (
            counts.get("running", 0) + counts.get("parked", 0)
        ) == 0

    async def _drain_and_reap(self, name: str) -> None:
        try:
            deadline = self._clock() + self.drain_grace_s
            clear = False
            while self._clock() < deadline:
                clear = await self._jobs_clear(name)
                if clear:
                    break
                await asyncio.sleep(min(1.0, self.interval_s))
            if not clear:
                # fail static: the backend keeps running (and keeps its
                # drain announcement — no new keyed traffic), the
                # operator sees the blocked reap on the plane
                self.metrics.inc_counter("reap_blocked_total")
                self._journal({
                    "kind": "reap_blocked", "backend": name,
                    "clock": self._clock(),
                })
                return
            # in-flight settle: the jobs tier is provably empty; give
            # already-accepted responses a beat to flush before SIGTERM
            await asyncio.sleep(self.drain_settle_s)
            lb = self.pending.pop(name, None)
            await self.launcher.reap(
                name, lb.handle if lb is not None else None
            )
            self.metrics.inc_counter("reaped_total")
            self._journal({
                "kind": "reaped", "backend": name, "clock": self._clock(),
            })
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — fail-static
            self.metrics.inc_counter("errors_total")
            slog.event(
                _log, "autoscale_reap_error", level=logging.WARNING,
                backend=name, error=str(e),
            )
        finally:
            self.draining.pop(name, None)


# -------------------------------------------------------------- sidecar


def main(argv: list[str] | None = None) -> int:
    """``deconv-api-tpu autoscaler`` — the sidecar entrypoint: the SAME
    controller the router embeds, run out-of-process against a router's
    federation surface.  Advisory by default; ``--mode enforce`` with a
    ``--launch-cmd`` makes it a real actuator."""
    import argparse

    p = argparse.ArgumentParser(description="deconv fleet autoscaler")
    p.add_argument(
        "--router", required=True, metavar="HOST:PORT",
        help="router whose /v1/metrics/fleet federation surface to poll",
    )
    p.add_argument(
        "--mode", choices=("advisory", "enforce"), default="advisory",
        help="advisory: decide+journal only; enforce: act via launcher",
    )
    p.add_argument("--interval-s", type=float, default=5.0)
    p.add_argument(
        "--journal", default="", metavar="PATH",
        help="fsync'd JSONL decision journal (replayed on restart)",
    )
    p.add_argument(
        "--launch-cmd", default="",
        help="backend launch argv template, {port} substituted "
        "(enforce mode)",
    )
    p.add_argument(
        "--fleet-token", default=os.environ.get("FLEET_TOKEN", ""),
        help="shared secret for drain announcements "
        "(env FLEET_TOKEN)",
    )
    p.add_argument("--min-backends", type=int, default=1)
    p.add_argument("--max-backends", type=int, default=4)
    p.add_argument("--up-burn", type=float, default=0.9)
    p.add_argument("--up-queue", type=float, default=4.0)
    p.add_argument("--down-burn", type=float, default=0.2)
    p.add_argument("--down-queue", type=float, default=0.5)
    p.add_argument("--cooldown-up-s", type=float, default=30.0)
    p.add_argument("--cooldown-down-s", type=float, default=120.0)
    p.add_argument("--qos-budget-ms", type=float, default=800.0)
    p.add_argument(
        "--once", action="store_true",
        help="single tick; print the decision as JSON and exit "
        "(cron-mode / smoke test)",
    )
    args = p.parse_args(argv)

    ctl = AutoscaleController(
        mode=args.mode,
        router_addr=args.router,
        fleet_token=args.fleet_token,
        interval_s=args.interval_s,
        journal_path=args.journal,
        launch_cmd=args.launch_cmd,
        engine_opts={
            "min_backends": args.min_backends,
            "max_backends": args.max_backends,
            "up_burn": args.up_burn,
            "up_queue": args.up_queue,
            "down_burn": args.down_burn,
            "down_queue": args.down_queue,
            "cooldown_up_s": args.cooldown_up_s,
            "cooldown_down_s": args.cooldown_down_s,
            "qos_device_ms_budget": args.qos_budget_ms,
        },
    )

    async def _run() -> int:
        if args.once:
            await ctl.tick()
            print(json.dumps(ctl.ready_block(), sort_keys=True))
            if ctl.journal is not None:
                ctl.journal.close()
            return 0
        slog.configure()
        slog.event(
            _log, "autoscaler_start", router=args.router, mode=args.mode,
        )
        import signal

        stop_ev = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop_ev.set)
            except NotImplementedError:  # pragma: no cover — non-unix
                pass
        ctl.start()
        await stop_ev.wait()
        await ctl.stop()
        return 0

    return asyncio.run(_run())


if __name__ == "__main__":
    raise SystemExit(main())
