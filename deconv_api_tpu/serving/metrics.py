"""Request/batch metrics with a Prometheus-style text exposition.

The reference pins prometheus-client but never uses it and has no metrics
at all (SURVEY §5 metrics row: health endpoint + stdout prints only).  This
registry feeds the `/metrics` endpoint and the bench harness: request
latency quantiles (p50/p99), batch sizes, images/sec.
"""

from __future__ import annotations

import bisect
import collections
import threading
import time


def escape_label(value: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote and
    newline must be escaped inside the quoted value (exposition format
    spec).  Error codes and stage names are identifiers today, but the
    exposition must stay parseable even if a future code carries one."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class _Reservoir:
    """Bounded sliding-window sample for quantiles (lock-protected).

    Cost decision (round-1 review): the deque eviction is O(1); the sorted
    list's insort/pop are O(n) *memmoves* — at cap 4096 that is a ~32 KB
    C-level move, ~1 µs per sample, against requests measured in
    milliseconds.  A skip-list/t-digest would save nothing observable."""

    def __init__(self, cap: int = 4096):
        self._cap = cap
        self._sorted: list[float] = []
        self._ring: collections.deque[float] = collections.deque()

    def add(self, v: float) -> None:
        if len(self._ring) >= self._cap:
            old = self._ring.popleft()
            i = bisect.bisect_left(self._sorted, old)
            self._sorted.pop(i)
        self._ring.append(v)
        bisect.insort(self._sorted, v)

    def quantile(self, q: float) -> float:
        if not self._sorted:
            return 0.0
        i = min(len(self._sorted) - 1, int(q * len(self._sorted)))
        return self._sorted[i]

    def __len__(self) -> int:
        return len(self._sorted)


class Metrics:
    def __init__(self, prefix: str = "deconv", *, core: bool = True):
        # core=False (round 14, the fleet router): the registry carries
        # only the generic counter/gauge/labeled/stage families — the
        # fixed request/batch pipeline families are a batching SERVER's
        # shape, and rendering them at zero from a router would be noise
        # (and would collide with a labeled `requests_total{backend=}`
        # family under the same prefix: two TYPE lines, lint failure).
        self._prefix = prefix
        self._core = core
        self._lock = threading.Lock()
        self._started = time.time()
        self.requests_total = 0
        self.errors_total: dict[str, int] = {}
        self.images_total = 0
        self.batches_total = 0
        self._latency = _Reservoir()
        self._batch_size = _Reservoir()
        self._compute = _Reservoir()
        self._cadence = _Reservoir()
        self._queue_wait = _Reservoir()
        self._stage: dict[str, _Reservoir] = {}
        self._gauges: dict[str, float] = {}
        self._counters: dict[str, int] = {}
        # family -> (label name, {label value: count}) — round 9's
        # per-site fault and per-task restart accounting; one label name
        # per family, like errors_total{code=...}
        self._labeled: dict[str, tuple[str, dict[str, int]]] = {}
        # family -> (label name, {label value: gauge}) — round 10's
        # per-lane pipeline state (lane_inflight{lane=},
        # lane_breaker_state{lane=}); same shape as labeled counters
        self._labeled_gauges: dict[str, tuple[str, dict[str, float]]] = {}

    def observe_request(self, latency_s: float, error_code: str | None = None) -> None:
        with self._lock:
            self.requests_total += 1
            self._latency.add(latency_s)
            if error_code:
                self.errors_total[error_code] = self.errors_total.get(error_code, 0) + 1

    def observe_batch(self, size: int, compute_s: float, queue_s: float) -> int:
        """Record one executed batch; returns the BATCH ID — the monotone
        ordinal of this batch on this metrics stream.  The dispatcher
        stamps it onto every member request's trace (round 8), so a
        flight-recorder trace and the batch-level metrics join on it."""
        with self._lock:
            self.batches_total += 1
            self.images_total += size
            self._batch_size.add(float(size))
            self._compute.add(compute_s)
            self._queue_wait.add(queue_s)
            return self.batches_total

    def observe_cadence(self, cadence_s: float) -> None:
        """Interval between consecutive batch COMPLETIONS while more work
        was in flight — the dispatcher's true sustained per-batch rate.
        Under pipelining this is shorter than compute_p50 (whose window
        spans overlapping dispatch->fetch walls), so the load-shed
        estimator prefers it (serving/batcher.py)."""
        with self._lock:
            self._cadence.add(cadence_s)

    def cadence_p50(self) -> float:
        with self._lock:
            return self._cadence.quantile(0.50)

    def compute_p50(self) -> float:
        """Median per-batch compute seconds — the load-shedding estimator's
        input (serving/batcher.py).  Cheap: one lock + one indexed read, no
        snapshot dict."""
        with self._lock:
            return self._compute.quantile(0.50)

    def batch_size_p50(self) -> float:
        """Median EXECUTED batch size.  The shed estimator divides queue
        depth by this rather than max_batch: under heterogeneous keys a
        drain window splits into per-key serial executions, and the
        observed size reflects that splitting where max_batch would
        underestimate drain time by up to max_batch x."""
        with self._lock:
            return self._batch_size.quantile(0.50)

    def observe_stage(self, stage: str, seconds: float) -> None:
        """Per-stage request timing (decode/preprocess/compute/encode) —
        the structured-tracing counterpart of SURVEY §5's tracing row."""
        with self._lock:
            self._stage.setdefault(stage, _Reservoir()).add(seconds)

    def inc_counter(self, name: str, n: int = 1) -> None:
        """Named monotonic counters (round 7: the response cache's
        hit/miss/coalesced/eviction accounting).  Exposed in the JSON
        snapshot under "counters" and as `# TYPE <prefix>_<name> counter`
        lines in the Prometheus text."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def inc_labeled(
        self, family: str, label, value, n: float = 1
    ) -> None:
        """Labeled monotonic counters (round 9: the robustness layer's
        ``faults_injected_total{site=...}`` and
        ``task_restarts_total{task=...}`` accounting) — one counter
        family, one sample line per label value, exactly like
        ``errors_total{code=...}``.

        Round 13 generalised the label to a TUPLE for multi-label
        families (``tenant_requests_total{tenant=...,class=...}``):
        pass matching tuples for ``label`` and ``value``; single-label
        callers keep passing strings.  Increments may be fractional
        (``tenant_device_ms_total`` accumulates measured milliseconds —
        float counters are valid exposition)."""
        if isinstance(label, tuple) != isinstance(value, tuple):
            raise TypeError("label and value must both be str or both tuple")
        if isinstance(label, tuple) and len(label) != len(value):
            # a short value tuple would zip-truncate at exposition time
            # into an ambiguous sample missing labels — fail like the
            # type mismatch does
            raise ValueError(
                f"labeled family {family!r}: {len(label)} label names "
                f"but {len(value)} values"
            )
        with self._lock:
            stored_label, series = self._labeled.setdefault(
                family, (label, {})
            )
            if stored_label != label:
                raise ValueError(
                    f"labeled family {family!r} already uses label "
                    f"{stored_label!r}"
                )
            series[value] = series.get(value, 0) + n

    def labeled(self, family: str) -> dict:
        """{label value(s): count} for one labeled-counter family
        (tuple keys for multi-label families)."""
        with self._lock:
            _, series = self._labeled.get(family, ("", {}))
            return dict(series)

    def set_labeled_gauge(
        self, family: str, label: str, value: str, v: float
    ) -> None:
        """Labeled instantaneous gauges (round 10: the lane pool's
        ``lane_inflight{lane=...}`` and ``lane_breaker_state{lane=...}``)
        — one gauge family, one sample line per label value."""
        with self._lock:
            _, series = self._labeled_gauges.setdefault(family, (label, {}))
            series[value] = float(v)

    def labeled_gauge(self, family: str) -> dict[str, float]:
        """{label value: gauge} for one labeled-gauge family."""
        with self._lock:
            _, series = self._labeled_gauges.get(family, ("", {}))
            return dict(series)

    def set_gauge(self, name: str, value: float) -> None:
        """Instantaneous pipeline-state gauges (queue depths, inflight
        batches — round 6's three-stage pipeline observability).  Updated
        at stage boundaries by the dispatcher and the codec worker pool."""
        with self._lock:
            self._gauges[name] = float(value)

    def snapshot(self, *, _join_labeled: bool = True) -> dict:
        # _join_labeled=False is prometheus()'s private view: "labeled"
        # keeps its raw tuple keys (copied under the SAME lock as the
        # rest of the snapshot) instead of paying for the JSON-able
        # comma-join that the text exposition would only have to undo
        with self._lock:
            up = time.time() - self._started
            return {
                "uptime_s": up,
                "requests_total": self.requests_total,
                "errors_total": dict(self.errors_total),
                "images_total": self.images_total,
                "batches_total": self.batches_total,
                "images_per_sec": self.images_total / up if up > 0 else 0.0,
                "latency_p50_s": self._latency.quantile(0.50),
                "latency_p99_s": self._latency.quantile(0.99),
                "batch_size_p50": self._batch_size.quantile(0.50),
                "compute_p50_s": self._compute.quantile(0.50),
                "batch_cadence_p50_s": self._cadence.quantile(0.50),
                "queue_wait_p50_s": self._queue_wait.quantile(0.50),
                "stages": {
                    k: {"p50_s": r.quantile(0.5), "p99_s": r.quantile(0.99)}
                    for k, r in self._stage.items()
                },
                "gauges": dict(self._gauges),
                "counters": dict(self._counters),
                # multi-label families (round 13) keep the snapshot
                # JSON-able: tuple label names become lists, tuple value
                # keys join on ',' (in-process consumers that need exact
                # tuples use the labeled() accessor instead)
                "labeled": (
                    {
                        fam: (
                            list(label) if isinstance(label, tuple) else label,
                            {
                                (",".join(k) if isinstance(k, tuple) else k): v
                                for k, v in series.items()
                            },
                        )
                        for fam, (label, series) in self._labeled.items()
                    }
                    if _join_labeled
                    else {
                        fam: (label, dict(series))
                        for fam, (label, series) in self._labeled.items()
                    }
                ),
                "labeled_gauges": {
                    fam: (label, dict(series))
                    for fam, (label, series) in self._labeled_gauges.items()
                },
            }

    def prometheus(self) -> str:
        p = self._prefix
        s = self.snapshot(_join_labeled=False)
        lines = [] if not self._core else [
            f"# TYPE {p}_requests_total counter",
            f"{p}_requests_total {s['requests_total']}",
            f"# TYPE {p}_images_total counter",
            f"{p}_images_total {s['images_total']}",
            f"# TYPE {p}_batches_total counter",
            f"{p}_batches_total {s['batches_total']}",
            f"# TYPE {p}_request_latency_seconds summary",
            f'{p}_request_latency_seconds{{quantile="0.5"}} {s["latency_p50_s"]:.6f}',
            f'{p}_request_latency_seconds{{quantile="0.99"}} {s["latency_p99_s"]:.6f}',
            f"# TYPE {p}_images_per_sec gauge",
            f"{p}_images_per_sec {s['images_per_sec']:.3f}",
            f"# TYPE {p}_batch_size summary",
            f'{p}_batch_size{{quantile="0.5"}} {s["batch_size_p50"]:.1f}',
            # HELP: dispatch->fetch-completion wall per batch.  Under the
            # pipelined dispatcher this window OVERLAPS other batches, so
            # it overstates per-batch device time; use batch_cadence_seconds
            # for the sustained per-batch rate (ADVICE r3)
            f"# HELP {p}_batch_compute_seconds dispatch-to-fetch wall; "
            "overlaps other batches when pipelined — see batch_cadence_seconds",
            f"# TYPE {p}_batch_compute_seconds summary",
            f'{p}_batch_compute_seconds{{quantile="0.5"}} {s["compute_p50_s"]:.6f}',
            # inter-completion interval under sustained load — the
            # pipelined dispatcher's true per-batch rate (batcher.py)
            f"# TYPE {p}_batch_cadence_seconds summary",
            f'{p}_batch_cadence_seconds{{quantile="0.5"}} '
            f'{s["batch_cadence_p50_s"]:.6f}',
            f"# TYPE {p}_queue_wait_seconds summary",
            f'{p}_queue_wait_seconds{{quantile="0.5"}} {s["queue_wait_p50_s"]:.6f}',
        ]
        if s["errors_total"]:
            # untyped-series fix (round 8): these labeled lines shipped
            # headerless, so Prometheus ingested them as untyped and the
            # exposition lint had nothing to hold them to
            lines.append(f"# HELP {p}_errors_total requests failed, by taxonomy code")
            lines.append(f"# TYPE {p}_errors_total counter")
            for code, n in sorted(s["errors_total"].items()):
                lines.append(
                    f'{p}_errors_total{{code="{escape_label(code)}"}} {n}'
                )
        if s["stages"]:
            lines.append(
                f"# HELP {p}_stage_seconds per-request pipeline stage wall time"
            )
            lines.append(f"# TYPE {p}_stage_seconds summary")
            for stage, q in sorted(s["stages"].items()):
                esc = escape_label(stage)
                lines.append(
                    f'{p}_stage_seconds{{stage="{esc}",quantile="0.5"}} {q["p50_s"]:.6f}'
                )
                lines.append(
                    f'{p}_stage_seconds{{stage="{esc}",quantile="0.99"}} {q["p99_s"]:.6f}'
                )
        # named counters (round 7): cache hit/miss/coalesced/eviction totals
        for name, n in sorted(s["counters"].items()):
            lines.append(f"# TYPE {p}_{name} counter")
            lines.append(f"{p}_{name} {n}")
        # labeled counters (round 9): per-site fault injections, per-task
        # supervisor restarts — one TYPE header per family.  Round 13:
        # multi-label families (tenant_requests_total{tenant=,class=})
        # render from the snapshot's raw tuple-key view.
        for fam, (label, series) in sorted(s["labeled"].items()):
            lines.append(f"# TYPE {p}_{fam} counter")
            names = label if isinstance(label, tuple) else (label,)
            for value, n in sorted(series.items()):
                values = value if isinstance(value, tuple) else (value,)
                block = ",".join(
                    f'{k}="{escape_label(v)}"' for k, v in zip(names, values)
                )
                # ints render exact (no %g six-significant-digit loss on
                # a large counter); float accumulators round to 3dp —
                # monotone either way
                num = f"{int(n)}" if float(n).is_integer() else f"{n:.3f}"
                lines.append(f"{p}_{fam}{{{block}}} {num}")
        # labeled gauges (round 10): per-lane in-flight depth and breaker
        # state — one TYPE header per family, one line per lane
        for fam, (label, series) in sorted(s["labeled_gauges"].items()):
            lines.append(f"# TYPE {p}_{fam} gauge")
            for value, v in sorted(series.items()):
                lines.append(
                    f'{p}_{fam}{{{label}="{escape_label(value)}"}} {v:g}'
                )
        # pipeline-state gauges (round 6): collect/dispatch queue depths,
        # inflight batches, codec-pool pending jobs; cache resident bytes /
        # entries / hit ratio (round 7)
        for name, v in sorted(s["gauges"].items()):
            lines.append(f"# TYPE {p}_{name} gauge")
            lines.append(f"{p}_{name} {v:g}")
        return "\n".join(lines) + "\n"
