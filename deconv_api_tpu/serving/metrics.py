"""Request/batch metrics with a Prometheus-style text exposition.

The reference pins prometheus-client but never uses it and has no metrics
at all (SURVEY §5 metrics row: health endpoint + stdout prints only).  This
registry feeds the `/metrics` endpoint and the bench harness: request
latency quantiles (p50/p99), batch sizes, images/sec.
"""

from __future__ import annotations

import bisect
import collections
import re
import threading
import time
from typing import Callable

# Fixed latency-histogram bucket bounds in SECONDS (round 19).  One
# fleet-wide vocabulary, chosen once: the quantile reservoirs above
# give an exact per-process p99 but cannot be AGGREGATED (quantiles of
# quantiles are meaningless), so the fleet had no true p99 on any
# federated surface.  Fixed buckets merge across processes by simple
# addition — the same reason Prometheus histograms use le= buckets —
# and the spread (5 ms .. 60 s) covers the cache-hit floor through the
# dream/sweep ceiling.  The +Inf bucket is implicit (index len(BUCKETS)).
HIST_BUCKETS_S = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def escape_label(value: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote and
    newline must be escaped inside the quoted value (exposition format
    spec).  Error codes and stage names are identifiers today, but the
    exposition must stay parseable even if a future code carries one."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class _Reservoir:
    """Bounded sliding-window sample for quantiles (lock-protected).

    Cost decision (round-1 review): the deque eviction is O(1); the sorted
    list's insort/pop are O(n) *memmoves* — at cap 4096 that is a ~32 KB
    C-level move, ~1 µs per sample, against requests measured in
    milliseconds.  A skip-list/t-digest would save nothing observable."""

    def __init__(self, cap: int = 4096):
        self._cap = cap
        self._sorted: list[float] = []
        self._ring: collections.deque[float] = collections.deque()

    def add(self, v: float) -> None:
        if len(self._ring) >= self._cap:
            old = self._ring.popleft()
            i = bisect.bisect_left(self._sorted, old)
            self._sorted.pop(i)
        self._ring.append(v)
        bisect.insort(self._sorted, v)

    def quantile(self, q: float) -> float:
        if not self._sorted:
            return 0.0
        i = min(len(self._sorted) - 1, int(q * len(self._sorted)))
        return self._sorted[i]

    def __len__(self) -> int:
        return len(self._sorted)


class Metrics:
    def __init__(self, prefix: str = "deconv", *, core: bool = True):
        # core=False (round 14, the fleet router): the registry carries
        # only the generic counter/gauge/labeled/stage families — the
        # fixed request/batch pipeline families are a batching SERVER's
        # shape, and rendering them at zero from a router would be noise
        # (and would collide with a labeled `requests_total{backend=}`
        # family under the same prefix: two TYPE lines, lint failure).
        self._prefix = prefix
        self._core = core
        self._lock = threading.Lock()
        self._started = time.time()
        self.requests_total = 0
        self.errors_total: dict[str, int] = {}
        self.images_total = 0
        self.batches_total = 0
        self._latency = _Reservoir()
        self._batch_size = _Reservoir()
        self._compute = _Reservoir()
        self._cadence = _Reservoir()
        self._queue_wait = _Reservoir()
        self._stage: dict[str, _Reservoir] = {}
        self._gauges: dict[str, float] = {}
        self._counters: dict[str, int] = {}
        # family -> (label name, {label value: count}) — round 9's
        # per-site fault and per-task restart accounting; one label name
        # per family, like errors_total{code=...}
        self._labeled: dict[str, tuple[str, dict[str, int]]] = {}
        # family -> (label name, {label value: gauge}) — round 10's
        # per-lane pipeline state (lane_inflight{lane=},
        # lane_breaker_state{lane=}); same shape as labeled counters
        self._labeled_gauges: dict[str, tuple[str, dict[str, float]]] = {}
        # family -> (label names, {label values: [per-bucket counts,
        # sum, count]}) — round 19's fixed-bucket latency histograms.
        # Counts are stored NON-cumulative per bucket (one increment per
        # observation) and cumulated at render, so every exposition is
        # trivially le-monotone and counters stay monotone across
        # snapshots.  Same label tuple discipline as inc_labeled.
        self._hists: dict[str, tuple[tuple, dict]] = {}

    def observe_request(self, latency_s: float, error_code: str | None = None) -> None:
        with self._lock:
            self.requests_total += 1
            self._latency.add(latency_s)
            if error_code:
                self.errors_total[error_code] = self.errors_total.get(error_code, 0) + 1

    def observe_batch(self, size: int, compute_s: float, queue_s: float) -> int:
        """Record one executed batch; returns the BATCH ID — the monotone
        ordinal of this batch on this metrics stream.  The dispatcher
        stamps it onto every member request's trace (round 8), so a
        flight-recorder trace and the batch-level metrics join on it."""
        with self._lock:
            self.batches_total += 1
            self.images_total += size
            self._batch_size.add(float(size))
            self._compute.add(compute_s)
            self._queue_wait.add(queue_s)
            return self.batches_total

    def observe_cadence(self, cadence_s: float) -> None:
        """Interval between consecutive batch COMPLETIONS while more work
        was in flight — the dispatcher's true sustained per-batch rate.
        Under pipelining this is shorter than compute_p50 (whose window
        spans overlapping dispatch->fetch walls), so the load-shed
        estimator prefers it (serving/batcher.py)."""
        with self._lock:
            self._cadence.add(cadence_s)

    def cadence_p50(self) -> float:
        with self._lock:
            return self._cadence.quantile(0.50)

    def compute_p50(self) -> float:
        """Median per-batch compute seconds — the load-shedding estimator's
        input (serving/batcher.py).  Cheap: one lock + one indexed read, no
        snapshot dict."""
        with self._lock:
            return self._compute.quantile(0.50)

    def batch_size_p50(self) -> float:
        """Median EXECUTED batch size.  The shed estimator divides queue
        depth by this rather than max_batch: under heterogeneous keys a
        drain window splits into per-key serial executions, and the
        observed size reflects that splitting where max_batch would
        underestimate drain time by up to max_batch x."""
        with self._lock:
            return self._batch_size.quantile(0.50)

    def observe_stage(self, stage: str, seconds: float) -> None:
        """Per-stage request timing (decode/preprocess/compute/encode) —
        the structured-tracing counterpart of SURVEY §5's tracing row."""
        with self._lock:
            self._stage.setdefault(stage, _Reservoir()).add(seconds)

    def inc_counter(self, name: str, n: int = 1) -> None:
        """Named monotonic counters (round 7: the response cache's
        hit/miss/coalesced/eviction accounting).  Exposed in the JSON
        snapshot under "counters" and as `# TYPE <prefix>_<name> counter`
        lines in the Prometheus text."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def inc_labeled(
        self, family: str, label, value, n: float = 1
    ) -> None:
        """Labeled monotonic counters (round 9: the robustness layer's
        ``faults_injected_total{site=...}`` and
        ``task_restarts_total{task=...}`` accounting) — one counter
        family, one sample line per label value, exactly like
        ``errors_total{code=...}``.

        Round 13 generalised the label to a TUPLE for multi-label
        families (``tenant_requests_total{tenant=...,class=...}``):
        pass matching tuples for ``label`` and ``value``; single-label
        callers keep passing strings.  Increments may be fractional
        (``tenant_device_ms_total`` accumulates measured milliseconds —
        float counters are valid exposition)."""
        if isinstance(label, tuple) != isinstance(value, tuple):
            raise TypeError("label and value must both be str or both tuple")
        if isinstance(label, tuple) and len(label) != len(value):
            # a short value tuple would zip-truncate at exposition time
            # into an ambiguous sample missing labels — fail like the
            # type mismatch does
            raise ValueError(
                f"labeled family {family!r}: {len(label)} label names "
                f"but {len(value)} values"
            )
        with self._lock:
            stored_label, series = self._labeled.setdefault(
                family, (label, {})
            )
            if stored_label != label:
                raise ValueError(
                    f"labeled family {family!r} already uses label "
                    f"{stored_label!r}"
                )
            series[value] = series.get(value, 0) + n

    def labeled(self, family: str) -> dict:
        """{label value(s): count} for one labeled-counter family
        (tuple keys for multi-label families)."""
        with self._lock:
            _, series = self._labeled.get(family, ("", {}))
            return dict(series)

    def observe_hist(
        self, family: str, label, value, seconds: float,
        exemplar: str | None = None,
    ) -> None:
        """Fixed-bucket latency histogram observation (round 19).

        ``label``/``value`` follow the ``inc_labeled`` tuple discipline
        (both strings, or matching tuples — ``("route", "qos_class")``
        for the request-duration family).  Buckets are the module-level
        ``HIST_BUCKETS_S`` vocabulary for EVERY histogram family, which
        is what makes the fleet federation sum them meaningfully.
        O(1): one bisect + three increments under the registry lock.

        ``exemplar`` (round 23) is a request id: each bucket keeps the
        MOST RECENT id that landed in it, rendered as an OpenMetrics
        exemplar on the exposition — the metrics→trace join (a bad p99
        bucket names a request you can fetch at /v1/debug/trace/{id}).
        One tuple store per observation; bounded by construction (one
        slot per bucket per labelset, newest wins)."""
        if isinstance(label, tuple) != isinstance(value, tuple):
            raise TypeError("label and value must both be str or both tuple")
        if isinstance(label, tuple) and len(label) != len(value):
            raise ValueError(
                f"histogram family {family!r}: {len(label)} label names "
                f"but {len(value)} values"
            )
        i = bisect.bisect_left(HIST_BUCKETS_S, seconds)
        with self._lock:
            stored_label, series = self._hists.setdefault(
                family, (label, {})
            )
            if stored_label != label:
                raise ValueError(
                    f"histogram family {family!r} already uses label "
                    f"{stored_label!r}"
                )
            h = series.get(value)
            if h is None:
                h = series[value] = [
                    [0] * (len(HIST_BUCKETS_S) + 1), 0.0, 0,
                    [None] * (len(HIST_BUCKETS_S) + 1),
                ]
            h[0][i] += 1
            h[1] += seconds
            h[2] += 1
            if exemplar is not None:
                h[3][i] = (exemplar, seconds)

    def hist_series(self, family: str) -> dict:
        """{label values: {"buckets": non-cumulative counts, "sum":
        seconds, "count": n}} for one histogram family (tuple keys for
        multi-label families) — the in-process test/SLO accessor."""
        with self._lock:
            _, series = self._hists.get(family, ((), {}))
            return {
                k: {"buckets": list(h[0]), "sum": h[1], "count": h[2]}
                for k, h in series.items()
            }

    def set_labeled_gauge(
        self, family: str, label: str, value: str, v: float
    ) -> None:
        """Labeled instantaneous gauges (round 10: the lane pool's
        ``lane_inflight{lane=...}`` and ``lane_breaker_state{lane=...}``)
        — one gauge family, one sample line per label value."""
        with self._lock:
            _, series = self._labeled_gauges.setdefault(family, (label, {}))
            series[value] = float(v)

    def labeled_gauge(self, family: str) -> dict[str, float]:
        """{label value: gauge} for one labeled-gauge family."""
        with self._lock:
            _, series = self._labeled_gauges.get(family, ("", {}))
            return dict(series)

    def set_gauge(self, name: str, value: float) -> None:
        """Instantaneous pipeline-state gauges (queue depths, inflight
        batches — round 6's three-stage pipeline observability).  Updated
        at stage boundaries by the dispatcher and the codec worker pool."""
        with self._lock:
            self._gauges[name] = float(value)

    def snapshot(self, *, _join_labeled: bool = True) -> dict:
        # _join_labeled=False is prometheus()'s private view: "labeled"
        # keeps its raw tuple keys (copied under the SAME lock as the
        # rest of the snapshot) instead of paying for the JSON-able
        # comma-join that the text exposition would only have to undo
        with self._lock:
            up = time.time() - self._started
            return {
                "uptime_s": up,
                "requests_total": self.requests_total,
                "errors_total": dict(self.errors_total),
                "images_total": self.images_total,
                "batches_total": self.batches_total,
                "images_per_sec": self.images_total / up if up > 0 else 0.0,
                "latency_p50_s": self._latency.quantile(0.50),
                "latency_p99_s": self._latency.quantile(0.99),
                "batch_size_p50": self._batch_size.quantile(0.50),
                "compute_p50_s": self._compute.quantile(0.50),
                "batch_cadence_p50_s": self._cadence.quantile(0.50),
                "queue_wait_p50_s": self._queue_wait.quantile(0.50),
                "stages": {
                    k: {"p50_s": r.quantile(0.5), "p99_s": r.quantile(0.99)}
                    for k, r in self._stage.items()
                },
                "gauges": dict(self._gauges),
                "counters": dict(self._counters),
                # multi-label families (round 13) keep the snapshot
                # JSON-able: tuple label names become lists, tuple value
                # keys join on ',' (in-process consumers that need exact
                # tuples use the labeled() accessor instead)
                "labeled": (
                    {
                        fam: (
                            list(label) if isinstance(label, tuple) else label,
                            {
                                (",".join(k) if isinstance(k, tuple) else k): v
                                for k, v in series.items()
                            },
                        )
                        for fam, (label, series) in self._labeled.items()
                    }
                    if _join_labeled
                    else {
                        fam: (label, dict(series))
                        for fam, (label, series) in self._labeled.items()
                    }
                ),
                "labeled_gauges": {
                    fam: (label, dict(series))
                    for fam, (label, series) in self._labeled_gauges.items()
                },
                # fixed-bucket histograms (round 19): same tuple-key
                # join rule as "labeled" — exact tuples via hist_series
                "histograms": (
                    {
                        fam: (
                            list(label) if isinstance(label, tuple) else label,
                            {
                                (",".join(k) if isinstance(k, tuple) else k): {
                                    "buckets": list(h[0]),
                                    "sum": round(h[1], 6),
                                    "count": h[2],
                                }
                                for k, h in series.items()
                            },
                        )
                        for fam, (label, series) in self._hists.items()
                    }
                    if _join_labeled
                    else {
                        fam: (
                            label,
                            {
                                k: [list(h[0]), h[1], h[2], list(h[3])]
                                for k, h in series.items()
                            },
                        )
                        for fam, (label, series) in self._hists.items()
                    }
                ),
            }

    def prometheus(self) -> str:
        p = self._prefix
        s = self.snapshot(_join_labeled=False)
        lines = [] if not self._core else [
            f"# TYPE {p}_requests_total counter",
            f"{p}_requests_total {s['requests_total']}",
            f"# TYPE {p}_images_total counter",
            f"{p}_images_total {s['images_total']}",
            f"# TYPE {p}_batches_total counter",
            f"{p}_batches_total {s['batches_total']}",
            f"# TYPE {p}_request_latency_seconds summary",
            f'{p}_request_latency_seconds{{quantile="0.5"}} {s["latency_p50_s"]:.6f}',
            f'{p}_request_latency_seconds{{quantile="0.99"}} {s["latency_p99_s"]:.6f}',
            f"# TYPE {p}_images_per_sec gauge",
            f"{p}_images_per_sec {s['images_per_sec']:.3f}",
            f"# TYPE {p}_batch_size summary",
            f'{p}_batch_size{{quantile="0.5"}} {s["batch_size_p50"]:.1f}',
            # HELP: dispatch->fetch-completion wall per batch.  Under the
            # pipelined dispatcher this window OVERLAPS other batches, so
            # it overstates per-batch device time; use batch_cadence_seconds
            # for the sustained per-batch rate (ADVICE r3)
            f"# HELP {p}_batch_compute_seconds dispatch-to-fetch wall; "
            "overlaps other batches when pipelined — see batch_cadence_seconds",
            f"# TYPE {p}_batch_compute_seconds summary",
            f'{p}_batch_compute_seconds{{quantile="0.5"}} {s["compute_p50_s"]:.6f}',
            # inter-completion interval under sustained load — the
            # pipelined dispatcher's true per-batch rate (batcher.py)
            f"# TYPE {p}_batch_cadence_seconds summary",
            f'{p}_batch_cadence_seconds{{quantile="0.5"}} '
            f'{s["batch_cadence_p50_s"]:.6f}',
            f"# TYPE {p}_queue_wait_seconds summary",
            f'{p}_queue_wait_seconds{{quantile="0.5"}} {s["queue_wait_p50_s"]:.6f}',
        ]
        if s["errors_total"]:
            # untyped-series fix (round 8): these labeled lines shipped
            # headerless, so Prometheus ingested them as untyped and the
            # exposition lint had nothing to hold them to
            lines.append(f"# HELP {p}_errors_total requests failed, by taxonomy code")
            lines.append(f"# TYPE {p}_errors_total counter")
            for code, n in sorted(s["errors_total"].items()):
                lines.append(
                    f'{p}_errors_total{{code="{escape_label(code)}"}} {n}'
                )
        if s["stages"]:
            lines.append(
                f"# HELP {p}_stage_seconds per-request pipeline stage wall time"
            )
            lines.append(f"# TYPE {p}_stage_seconds summary")
            for stage, q in sorted(s["stages"].items()):
                esc = escape_label(stage)
                lines.append(
                    f'{p}_stage_seconds{{stage="{esc}",quantile="0.5"}} {q["p50_s"]:.6f}'
                )
                lines.append(
                    f'{p}_stage_seconds{{stage="{esc}",quantile="0.99"}} {q["p99_s"]:.6f}'
                )
        # named counters (round 7): cache hit/miss/coalesced/eviction totals
        for name, n in sorted(s["counters"].items()):
            lines.append(f"# TYPE {p}_{name} counter")
            lines.append(f"{p}_{name} {n}")
        # labeled counters (round 9): per-site fault injections, per-task
        # supervisor restarts — one TYPE header per family.  Round 13:
        # multi-label families (tenant_requests_total{tenant=,class=})
        # render from the snapshot's raw tuple-key view.
        for fam, (label, series) in sorted(s["labeled"].items()):
            lines.append(f"# TYPE {p}_{fam} counter")
            names = label if isinstance(label, tuple) else (label,)
            for value, n in sorted(series.items()):
                values = value if isinstance(value, tuple) else (value,)
                block = ",".join(
                    f'{k}="{escape_label(v)}"' for k, v in zip(names, values)
                )
                # ints render exact (no %g six-significant-digit loss on
                # a large counter); float accumulators round to 3dp —
                # monotone either way
                num = f"{int(n)}" if float(n).is_integer() else f"{n:.3f}"
                lines.append(f"{p}_{fam}{{{block}}} {num}")
        # fixed-bucket histograms (round 19): one TYPE header per
        # family, cumulative le= buckets + _sum/_count per labelset —
        # the exposition shape Prometheus aggregates across processes,
        # which is exactly what the fleet federation endpoint does.
        # Round 23: each bucket carries its most-recent request id as an
        # OpenMetrics exemplar (``... N # {trace_id="..."} <seconds>``)
        # so a bad bucket is joinable against /v1/debug/trace/{id}.
        for fam, (label, series) in sorted(s["histograms"].items()):
            lines.append(
                f"# HELP {p}_{fam} fixed-bucket latency histogram "
                "(seconds)"
            )
            lines.append(f"# TYPE {p}_{fam} histogram")
            names = label if isinstance(label, tuple) else (label,)
            for value, (buckets, total, count, exem) in sorted(
                series.items()
            ):
                values = value if isinstance(value, tuple) else (value,)
                block = ",".join(
                    f'{k}="{escape_label(v)}"' for k, v in zip(names, values)
                )
                cum = 0
                for i, (bound, n) in enumerate(zip(HIST_BUCKETS_S, buckets)):
                    cum += n
                    line = f'{p}_{fam}_bucket{{{block},le="{bound:g}"}} {cum}'
                    if exem[i] is not None:
                        rid, obs = exem[i]
                        line += (
                            f' # {{trace_id="{escape_label(rid)}"}} {obs:.6f}'
                        )
                    lines.append(line)
                line = f'{p}_{fam}_bucket{{{block},le="+Inf"}} {count}'
                if exem[-1] is not None:
                    rid, obs = exem[-1]
                    line += f' # {{trace_id="{escape_label(rid)}"}} {obs:.6f}'
                lines.append(line)
                lines.append(f"{p}_{fam}_sum{{{block}}} {total:.6f}")
                lines.append(f"{p}_{fam}_count{{{block}}} {count}")
        # labeled gauges (round 10): per-lane in-flight depth and breaker
        # state — one TYPE header per family, one line per lane
        for fam, (label, series) in sorted(s["labeled_gauges"].items()):
            lines.append(f"# TYPE {p}_{fam} gauge")
            for value, v in sorted(series.items()):
                lines.append(
                    f'{p}_{fam}{{{label}="{escape_label(value)}"}} {v:g}'
                )
        # pipeline-state gauges (round 6): collect/dispatch queue depths,
        # inflight batches, codec-pool pending jobs; cache resident bytes /
        # entries / hit ratio (round 7)
        for name, v in sorted(s["gauges"].items()):
            lines.append(f"# TYPE {p}_{name} gauge")
            lines.append(f"{p}_{name} {v:g}")
        return "\n".join(lines) + "\n"


# ----------------------------------------------------------------- SLOs

# Burn-rate windows (round 19): the classic fast/slow multiwindow pair.
# The 5m window catches a sharp regression within minutes; the 1h
# window catches a slow bleed that never trips the fast alarm.  A burn
# rate of 1.0 means the error budget is being spent exactly at the rate
# that exhausts it over the SLO period; >1 is overspend.
SLO_WINDOWS: dict[str, float] = {"5m": 300.0, "1h": 3600.0}

# Route-agnostic marker: an SLO with no route constraint.
_SLO_ANY_ROUTE = ""


class SloTracker:
    """One latency SLO: ``objective_pct`` of requests must finish under
    ``threshold_ms`` (5xx responses count as breaches regardless of
    latency — a fast 500 is not "within objective").

    Burn rates come from time-bucketed good/bad counters (10 s buckets,
    pruned past the longest window) under an injectable clock, so the
    math is deterministic in tests: over a window,

        burn = (bad / total) / (1 - objective)

    i.e. the observed error rate as a multiple of the rate that spends
    the error budget exactly.  An empty window reports 0.0 — no
    traffic, no burn.  Single-consumer like LatencyDigest: the serving
    event loop feeds and reads it; cumulative totals are plain ints."""

    def __init__(
        self,
        name: str,
        threshold_ms: float,
        objective_pct: float,
        route: str = _SLO_ANY_ROUTE,
        *,
        clock: Callable[[], float] = time.monotonic,
        bucket_s: float = 10.0,
    ):
        if not 0 < objective_pct < 100:
            raise ValueError(
                f"slo {name!r}: objective_pct must be in (0, 100), "
                f"got {objective_pct!r}"
            )
        if threshold_ms <= 0:
            raise ValueError(
                f"slo {name!r}: threshold_ms must be positive, "
                f"got {threshold_ms!r}"
            )
        self.name = name
        self.threshold_ms = float(threshold_ms)
        self.objective_pct = float(objective_pct)
        self.route = route
        self._budget = 1.0 - self.objective_pct / 100.0
        self._clock = clock
        self._bucket_s = float(bucket_s)
        # (bucket ordinal, total, bad), append-only at the right edge
        self._buckets: collections.deque[list] = collections.deque()
        self.requests_total = 0
        self.breaches_total = 0

    def matches(self, route: str) -> bool:
        return self.route == _SLO_ANY_ROUTE or self.route == route

    def observe(self, latency_s: float, status: int) -> None:
        bad = status >= 500 or latency_s * 1e3 > self.threshold_ms
        self.requests_total += 1
        if bad:
            self.breaches_total += 1
        ordinal = int(self._clock() / self._bucket_s)
        if self._buckets and self._buckets[-1][0] == ordinal:
            b = self._buckets[-1]
        else:
            self._buckets.append([ordinal, 0, 0])
            b = self._buckets[-1]
            self._prune(ordinal)
        b[1] += 1
        if bad:
            b[2] += 1

    def _prune(self, now_ordinal: int) -> None:
        horizon = max(SLO_WINDOWS.values()) / self._bucket_s
        while self._buckets and self._buckets[0][0] < now_ordinal - horizon:
            self._buckets.popleft()

    def _window_counts(self, window_s: float) -> tuple[int, int]:
        now_ordinal = int(self._clock() / self._bucket_s)
        cut = now_ordinal - window_s / self._bucket_s
        total = bad = 0
        for ordinal, t, b in reversed(self._buckets):
            if ordinal <= cut:
                break
            total += t
            bad += b
        return total, bad

    def burn_rates(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for name, window_s in SLO_WINDOWS.items():
            total, bad = self._window_counts(window_s)
            out[name] = (
                round((bad / total) / self._budget, 4) if total else 0.0
            )
        return out

    def snapshot(self) -> dict:
        return {
            "threshold_ms": self.threshold_ms,
            "objective_pct": self.objective_pct,
            "route": self.route or "*",
            "requests_total": self.requests_total,
            "breaches_total": self.breaches_total,
            "burn": self.burn_rates(),
        }


def parse_slos(
    spec: str,
    clock: Callable[[], float] = time.monotonic,
    observable_routes: "frozenset[str] | None" = None,
) -> list[SloTracker]:
    """Parse the ``slos`` config knob: comma-separated
    ``name=<threshold_ms>:<objective_pct>[:<route>]`` entries, e.g.
    ``api=250:99,deconv=100:99.9:/v1/deconv``.  A route-qualified SLO
    observes only that route family; unqualified ones observe every
    request on the surface.  Raises ValueError on any malformed entry —
    validated at boot, never silently dropped.  ``observable_routes``
    (when the caller knows its observation vocabulary) extends that
    promise to route scopes: an SLO pinned to a route the surface never
    observes would burn 0.0 forever while the route breaches — a typo'd
    route is a boot error, not a dead objective."""
    trackers: list[SloTracker] = []
    seen: set[str] = set()
    for entry in (e.strip() for e in spec.split(",") if e.strip()):
        name, sep, rest = entry.partition("=")
        name = name.strip()
        if not sep or not re.fullmatch(r"[A-Za-z0-9_\-]{1,64}", name):
            raise ValueError(
                f"slo entry {entry!r}: expected "
                "name=<threshold_ms>:<objective_pct>[:<route>]"
            )
        if name in seen:
            raise ValueError(f"duplicate slo name {name!r}")
        seen.add(name)
        parts = rest.split(":", 2)
        if len(parts) < 2:
            raise ValueError(
                f"slo {name!r}: expected <threshold_ms>:<objective_pct>"
            )
        try:
            threshold_ms = float(parts[0])
            objective_pct = float(parts[1])
        except ValueError:
            raise ValueError(
                f"slo {name!r}: threshold/objective must be numeric, "
                f"got {rest!r}"
            ) from None
        route = parts[2].strip() if len(parts) == 3 else _SLO_ANY_ROUTE
        if route and not route.startswith("/"):
            raise ValueError(
                f"slo {name!r}: route must start with '/', got {route!r}"
            )
        if route and observable_routes is not None and (
            route not in observable_routes
        ):
            raise ValueError(
                f"slo {name!r}: route {route!r} is never observed on "
                f"this surface (observable: "
                f"{', '.join(sorted(observable_routes))})"
            )
        trackers.append(
            SloTracker(name, threshold_ms, objective_pct, route, clock=clock)
        )
    return trackers


def slo_prometheus(trackers: list[SloTracker], prefix: str) -> str:
    """Exposition block for a set of SLO trackers: monotone
    good/breach totals plus the multi-window burn-rate gauges — lints
    clean next to any registry's output.  Empty list renders nothing."""
    if not trackers:
        return ""
    p = prefix
    lines = [
        f"# HELP {p}_slo_requests_total requests observed per SLO",
        f"# TYPE {p}_slo_requests_total counter",
    ]
    for t in trackers:
        lines.append(
            f'{p}_slo_requests_total{{slo="{escape_label(t.name)}"}} '
            f"{t.requests_total}"
        )
    lines.append(
        f"# HELP {p}_slo_breaches_total requests over threshold or 5xx"
    )
    lines.append(f"# TYPE {p}_slo_breaches_total counter")
    for t in trackers:
        lines.append(
            f'{p}_slo_breaches_total{{slo="{escape_label(t.name)}"}} '
            f"{t.breaches_total}"
        )
    lines.append(
        f"# HELP {p}_slo_burn_rate error-budget spend rate per window "
        "(1.0 = spending exactly the budget)"
    )
    lines.append(f"# TYPE {p}_slo_burn_rate gauge")
    for t in trackers:
        for window, rate in sorted(t.burn_rates().items()):
            lines.append(
                f'{p}_slo_burn_rate{{slo="{escape_label(t.name)}",'
                f'window="{window}"}} {rate:g}'
            )
    return "\n".join(lines) + "\n"
