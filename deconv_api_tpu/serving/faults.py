"""Fault injection registry: named failure sites, armed on demand (round 9).

Large-scale serving systems treat partial failure as a first-class input —
TensorFlow Serving's health-checked worker recovery (arXiv:1605.08695) and
the TPU serving comparison's tail-under-faults methodology (PAPERS.md) both
assume the failure paths are EXERCISABLE.  Ours were not: a codec worker
death, a crashed dispatch task, a flaky device — each could only be
observed by waiting for production to produce it.  This module makes every
such path a named, armable injection site:

- ``SITES``: the registry of known sites.  Each production call site
  consults the registry through the module-level ``check(site)`` hook,
  which is ZERO-COST while disabled — one global load and an ``is None``
  test, no lock, no dict lookup (pinned by tests/test_faults.py).

- ``FaultSpec`` / ``parse_fault_specs``: the arm grammar, shared by the
  ``--fault site=spec`` CLI flag, the ``DECONV_FAULTS`` env var, and the
  ``POST /v1/debug/faults`` one-shot endpoint.  ``spec`` is
  ``p<prob>``/``<prob>`` (fire with that probability per consultation),
  or ``n<count>`` (fire on the next <count> consultations, then
  self-disarm — the "burst" form), optionally ``:<param>`` for
  parameterized sites (milliseconds for the delay/hang/slow-write
  sites), optionally ``@<target>`` (round 17) restricting the spec to
  one consulting identity — a backend name for the router-side
  ``fleet.*`` sites, a ``fleet_advertise`` name for the device sites.
  Multiple ``site=spec`` pairs join with commas.

- ``FaultRegistry``: lock-protected armed-spec table with a SEEDED
  ``random.Random`` so probabilistic chaos runs are reproducible, and
  per-site injection counters published as
  ``faults_injected_total{site=...}`` through the Metrics registry.

The registry is owned by the service (``DeconvService.faults``) and
installed into the module hook only when ``fault_injection`` is enabled;
a default-configured server never pays more than the disabled hook.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass

from deconv_api_tpu import errors
from deconv_api_tpu.utils import slog

_log = slog.get_logger("deconv.faults")

# Every known injection site; arming an unknown one is a config error
# (a typo'd site would otherwise arm nothing and the drill would
# silently measure a healthy server).
SITES = (
    "codec.worker_raise",      # codec-pool worker dies mid-task
    "codec.worker_hang",       # codec-pool worker stalls for :param ms
    "dispatch.worker_raise",   # batcher dispatch-worker dies mid-task
    "dispatch.worker_hang",    # batcher dispatch-worker stalls :param ms
    "batcher.dispatch_raise",  # batcher dispatch-stage task crashes
    "device.dispatch_error",   # device batch dispatch raises (:param = lane)
    "device.dispatch_delay_ms",  # device batch dispatch stalls :param ms
    "http.slow_write",         # response write stalls :param ms
    "jobs.runner_crash",       # job runner dies at a checkpoint boundary
    "jobs.journal_write_error",  # LEGACY: alias of fs.fsync_error@jobs.journal
    "qos.admission_raise",     # QoS admission layer crashes (fails OPEN
                               # to the default tenant — availability
                               # over accounting; serving/qos.py)
    # Router-side NETWORK fault sites (round 17, serving/fleet.py): the
    # gray failures the backend-side device sites cannot manufacture —
    # a sick NIC, a congested cross-rack path, a half-dead conntrack
    # entry — live between the router and one backend, not inside the
    # backend's dispatch.  All are armable per backend via the
    # ``@<host:port>`` target selector below.
    "fleet.connect_delay_ms",  # router->backend connect stalls :param ms
    "fleet.head_delay_ms",     # response head arrives :param ms late
    "fleet.body_trickle",      # body trickles (:param ms per 64 KiB)
    "fleet.torn_body",         # response torn mid-body (infra failure)
    "fleet.blackhole",         # backend accepts, never answers (timeout)
    # Autoscale control-plane sites (round 22, serving/autoscale.py):
    # the controller's failure contract is fail-STATIC — a crashing
    # decision loop degrades to no-op (autoscaler_errors_total, fleet
    # keeps its size), a failed launch retries with backoff without
    # ever double-counting fleet capacity.  Both are drill-armable.
    "autoscale.decision_error",  # decision tick raises mid-evaluation
    "autoscale.launch_fail",     # backend launch attempt fails
    # Alert-engine site (round 23, serving/alerts.py): the evaluator's
    # failure contract is fail-STATIC — a crashing rule evaluation
    # increments alerts_eval_errors_total and leaves every rule's
    # lifecycle state EXACTLY where it was (a firing alert never flaps
    # to resolved because the evaluator died).  Drill-armable.
    "alerts.eval_error",         # alert rule evaluation raises mid-tick
    # Filesystem fault sites (round 24, serving/durable.py): every
    # durable write and verified read consults these with
    # ``who=<surface>`` (jobs.journal, jobs.spill, cache.l2,
    # fleet.membership, aot.store, autoscale.journal, alerts.incidents,
    # quant.calib), so ``fs.enospc=p1@cache.l2`` starves exactly one
    # surface and leaves the rest of the disk "healthy".
    "fs.enospc",        # write raises ENOSPC before any byte lands
    "fs.eio_read",      # read raises EIO (reads as absent by contract)
    "fs.short_write",   # write silently truncates (digest catches it)
    "fs.fsync_error",   # fsync raises EIO (data not durable)
    "fs.crash_point",   # SIGKILL self at crashpoint :param (durable.CRASH_*)
)

# Legacy spelling of the one pre-round-24 disk fault site.  Arming it
# rewrites to ``fs.fsync_error@jobs.journal`` (see FaultRegistry.arm)
# so old drill scripts and OPERATIONS recipes keep working while the
# fault vocabulary has one owner — durable.py consults only ``fs.*``.
_LEGACY_ALIASES = {
    "jobs.journal_write_error": ("fs.fsync_error", "jobs.journal"),
}


@dataclass
class FaultSpec:
    """One armed site: probability per consultation, optional one-shot
    remaining count (None = until disarmed), optional site parameter,
    optional ``@<target>`` selector (round 17) restricting the spec to
    one consulting identity — the fleet router consults its sites with
    ``who=<backend host:port>``, so ``fleet.head_delay_ms=p1:150@b0:8000``
    grays exactly one backend's network path and leaves its peers
    untouched (the per-backend analogue of the lane-targeted ``where``)."""

    p: float = 1.0
    n: int | None = None
    param: float | None = None
    target: str | None = None

    def __str__(self) -> str:
        s = f"n{self.n}" if self.n is not None else f"p{self.p:g}"
        if self.param is not None:
            s += f":{self.param:g}"
        if self.target is not None:
            s += f"@{self.target}"
        return s


@dataclass
class FaultAction:
    """A fired fault, handed back to the call site (carries the spec's
    parameter, e.g. the delay in ms)."""

    site: str
    param: float | None = None


def parse_spec(raw: str) -> FaultSpec:
    """``p0.05`` / ``0.05`` / ``n3`` with an optional ``:<param>`` and an
    optional ``@<target>`` selector.  The target splits FIRST (it may
    itself contain ``:`` — backend targets are ``host:port``)."""
    head, at, target = raw.partition("@")
    head, _, param_s = head.partition(":")
    head = head.strip()
    spec = FaultSpec()
    if at:
        target = target.strip()
        if not target:
            raise ValueError(f"bad fault spec {raw!r}: empty @target")
        spec.target = target
    try:
        if head.startswith("n"):
            spec.n = int(head[1:])
            if spec.n <= 0:
                raise ValueError
        else:
            spec.p = float(head[1:] if head.startswith("p") else head)
            if not 0.0 < spec.p <= 1.0:
                raise ValueError
        if param_s:
            spec.param = float(param_s)
    except ValueError:
        raise ValueError(
            f"bad fault spec {raw!r}: want p<0..1], n<count>, or <0..1], "
            "optionally :<param>, optionally @<target>"
        ) from None
    return spec


def parse_fault_specs(raw: str) -> dict[str, FaultSpec]:
    """``site=spec,site=spec,...`` -> validated {site: FaultSpec}."""
    out: dict[str, FaultSpec] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        site, eq, spec = part.partition("=")
        site = site.strip()
        if not eq:
            raise ValueError(f"bad fault arm {part!r}: want site=spec")
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r}; known: {', '.join(SITES)}"
            )
        out[site] = parse_spec(spec.strip())
    return out


class FaultRegistry:
    """Armed-fault table + deterministic RNG + injection accounting.

    ``check(site)`` is the only hot-path surface: returns a
    ``FaultAction`` when the site fires (decrementing one-shot counts,
    self-disarming at zero) and ``None`` otherwise.  All state is
    lock-protected — sites are consulted from the event loop, codec
    worker threads, and the dispatch worker thread."""

    def __init__(self, seed: int = 0, metrics=None):
        self._lock = threading.Lock()
        self._armed: dict[str, FaultSpec] = {}
        self._rng = random.Random(seed)
        self._injected: dict[str, int] = {}
        self._metrics = metrics

    def arm(self, site: str, spec: FaultSpec | str) -> None:
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r}; known: {', '.join(SITES)}"
            )
        if isinstance(spec, str):
            spec = parse_spec(spec)
        if site in _LEGACY_ALIASES:
            # round 24: the legacy disk-fault spelling rewrites onto the
            # fs.* vocabulary (an explicit @target on the old spelling
            # is preserved — it can only have meant the same surface)
            site, target = _LEGACY_ALIASES[site]
            if spec.target is None:
                spec.target = target
        with self._lock:
            self._armed[site] = spec
        slog.event(_log, "fault_armed", site=site, spec=str(spec))
        if self._metrics is not None:
            # the armed site's counter is present at zero from the
            # first scrape after arming (round 24 exposition lint)
            self._metrics.inc_labeled("faults_injected_total", "site", site, 0)
        self._publish()

    def arm_string(self, raw: str) -> None:
        """Arm every ``site=spec`` pair of a CLI/env/endpoint string."""
        for site, spec in parse_fault_specs(raw).items():
            self.arm(site, spec)

    def disarm(self, site: str | None = None) -> None:
        """Disarm one site, or every site (None)."""
        with self._lock:
            if site is None:
                self._armed.clear()
            else:
                self._armed.pop(site, None)
        slog.event(_log, "fault_disarmed", site=site or "all")
        self._publish()

    def check(
        self,
        site: str,
        where: int | None = None,
        who: str | None = None,
    ) -> FaultAction | None:
        """``where`` is the call site's locality (round 10: the executor
        LANE consulting a device site).  A spec armed with a ``:<param>``
        on a lane-targetable site fires only when the param matches —
        ``device.dispatch_error=n8:1`` bursts lane 1 and leaves the rest
        of the pool untouched; non-matching consultations don't consume
        one-shot counts.  ``who`` (round 17) is the call site's string
        identity — the fleet router's backend name, or a backend's own
        fleet-advertise name — matched against the spec's ``@<target>``
        selector the same way: a targeted spec never fires (and never
        consumes one-shot counts) for anyone else."""
        disarmed = False
        with self._lock:
            spec = self._armed.get(site)
            if spec is None:
                return None
            if spec.target is not None and who != spec.target:
                return None
            if (
                where is not None
                and spec.param is not None
                and int(spec.param) != where
            ):
                return None
            if spec.p < 1.0 and self._rng.random() >= spec.p:
                return None
            if spec.n is not None:
                spec.n -= 1
                if spec.n <= 0:
                    del self._armed[site]
                    disarmed = True
            self._injected[site] = self._injected.get(site, 0) + 1
        if self._metrics is not None:
            self._metrics.inc_labeled("faults_injected_total", "site", site)
        if disarmed:
            # the armed-count gauge only moves when a one-shot spec
            # self-disarms; publishing on every fire would pay an extra
            # lock round-trip per injection on sustained chaos
            self._publish()
        return FaultAction(site, spec.param)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "armed": {s: str(spec) for s, spec in self._armed.items()},
                "injected": dict(self._injected),
            }

    def _publish(self) -> None:
        if self._metrics is not None:
            with self._lock:
                n = len(self._armed)
            self._metrics.set_gauge("faults_armed", n)


# ------------------------------------------------------- module-level hook

# The zero-cost-when-disabled hook: production call sites do
# ``faults.check(site)`` unconditionally; with no registry installed that
# is one module-global load and an ``is None`` branch.  The service
# installs its registry only when cfg.fault_injection is on.
_REGISTRY: FaultRegistry | None = None


def install(registry: FaultRegistry) -> None:
    global _REGISTRY
    _REGISTRY = registry


def uninstall(registry: FaultRegistry | None = None) -> None:
    """Remove the installed registry.  Pass the registry you installed so
    a service tearing down cannot evict one installed after it."""
    global _REGISTRY
    if registry is None or _REGISTRY is registry:
        _REGISTRY = None


def installed() -> FaultRegistry | None:
    return _REGISTRY


def check(
    site: str, where: int | None = None, who: str | None = None
) -> FaultAction | None:
    reg = _REGISTRY
    if reg is None:
        return None
    return reg.check(site, where, who)


def raise_if_armed(
    site: str, where: int | None = None, who: str | None = None
) -> None:
    """Shared raise-form consultation: the site fires -> FaultInjected."""
    act = check(site, where, who)
    if act is not None:
        raise errors.FaultInjected(f"injected fault at {site}")
