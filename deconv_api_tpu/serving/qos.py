"""Multi-tenant QoS: identity, priority classes, device-time budgets, and
deficit-round-robin fair queueing (round 13, ROADMAP open item 4).

"Millions of users" means noisy neighbors: until this round every route
fed ONE FIFO per dispatcher, so a single abusive key or bulk client
starved everyone behind it, and nothing in the stack could even say which
tenant the queue belonged to.  The TensorFlow systems paper and the TPU
serving comparison (PAPERS.md) both treat DEVICE TIME — not request
count — as the scarce resource to meter; the PR 5 per-lane EWMA batch
cost is exactly that meter, already measured per batch and waiting to be
charged to someone.  This module charges it:

- **Tenant identity** — ``tenant_of(headers)``: the ``x-api-key`` or
  ``x-tenant`` header, validated against the request-id grammar
  (``RID_RE``); anonymous or malformed identity maps to the DEFAULT
  tenant rather than a 400 — identity is metering metadata, and failing
  a request over it would punish the victim of a proxy bug.  An
  ``x-api-key`` that is not a configured tenant name is pseudonymized
  to ``key-<digest>`` before it can reach a metric label or log line
  (keys are credentials; labels are operator surfaces), and past
  ``MAX_TENANTS`` live tenants unconfigured names collapse to the
  default tenant so attacker-chosen headers cannot grow state or
  metric cardinality without bound.

- **Priority classes** — ``interactive`` > ``standard`` > ``bulk``.  A
  class is a DRR weight (how much of the queue a tenant's traffic gets
  per rotation), a shed rank (overload evicts bulk first), and a
  deadline-jump privilege (a near-deadline interactive item pops ahead
  of the rotation; bulk never jumps).

- **Token-bucket rate limits in device-milliseconds** — each metered
  tenant's bucket refills at ``rate_ms`` device-milliseconds per wall
  second and holds at most ``burst_ms``.  Admission debits the tenant's
  EWMA-measured per-request device cost (seeded at 1 ms until the
  batcher has measured one); an empty bucket 429s ``tenant_over_quota``
  with a Retry-After derived from the bucket's actual refill rate.  The
  batcher reports every executed item's measured share of its batch
  wall back through ``charge()``, which is what keeps the EWMA honest —
  tenants are charged by what their batches COST, not by how many
  requests they sent.  Cache hits refund the provisional debit but keep
  a small fixed ``hit_cost_ms`` so a hot-key tenant cannot launder
  unlimited traffic through the PR 2 hit path.

- **In-flight budgets** — ``max_inflight`` concurrent admitted requests
  per tenant; the cheap backstop against a tenant that opens ten
  thousand sockets before its bucket can drain.

- **DRR queues** — ``DrrQueue`` replaces the batcher's single FIFO with
  per-(tenant, class) queues served deficit-round-robin, quantum scaled
  by class weight.  A zipf-abusive tenant's backlog sits in ITS queue;
  the victim's queue keeps its weighted share of every drain window.
  Single consumer by contract (the batcher's one collect loop).

- **Fail-open admission** — the ``qos.admission_raise`` fault site (and
  any unexpected admission crash) degrades to the default tenant with
  no metering, pinned by test: availability over accounting.

Everything is inert unless ``cfg.qos`` is on: the batcher keeps its
plain ``asyncio.Queue`` and the routes skip the admission wrap entirely,
so the qos-off hot path is byte- and cost-identical to round 12 (the
``qos`` bench token pins the ≤3% budget; byte parity is pinned by
tests/test_qos.py).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from deconv_api_tpu import errors
from deconv_api_tpu.serving import faults
from deconv_api_tpu.serving.trace import RID_RE
from deconv_api_tpu.utils import slog

_log = slog.get_logger("deconv.qos")

# Priority classes, strongest first.  The weight is the DRR quantum
# multiplier: per rotation an interactive queue may serve 8 items for
# every 1 a bulk queue serves (when both are backlogged).
CLASSES = ("interactive", "standard", "bulk")
DEFAULT_WEIGHTS = {"interactive": 8, "standard": 4, "bulk": 1}

# The identity every anonymous (or unparseable) request maps onto.
DEFAULT_TENANT = "default"

# Provisional device-cost debit for a tenant nobody has measured yet
# (the EWMA replaces it after the first executed batch).
SEED_COST_MS = 1.0

# Cardinality guard: tenant names arrive in attacker-chosen headers, and
# every distinct name would otherwise pin a _Tenant, a DRR queue slot,
# and a label series in three metric families FOREVER.  Past this many
# live tenants, unconfigured identities collapse to the default tenant —
# configured tenants and anyone already metered keep their own state.
MAX_TENANTS = 1024

# EWMA smoothing for a tenant's per-request device cost — same constant
# family as the lane cost signal (serving/batcher.py _EWMA_ALPHA).
_EWMA_ALPHA = 0.2


def parse_weights(raw: str) -> dict[str, int]:
    """``interactive=8,standard=4,bulk=1`` -> validated weights dict.
    Unnamed classes keep their defaults; unknown class names or weights
    < 1 are config errors (a zero weight would starve that class's DRR
    rotation forever — that is what shed order is for)."""
    weights = dict(DEFAULT_WEIGHTS)
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, eq, val = part.partition("=")
        name = name.strip()
        if not eq or name not in CLASSES:
            raise ValueError(
                f"bad qos weight {part!r}: want <class>=<int> with class in "
                f"{', '.join(CLASSES)}"
            )
        try:
            w = int(val)
        except ValueError:
            raise ValueError(f"qos weight for {name!r} must be an int") from None
        if w < 1:
            raise ValueError(f"qos weight for {name!r} must be >= 1")
        weights[name] = w
    return weights


@dataclass
class TenantSpec:
    """One tenant's policy.  0 disables the respective limit — the
    default tenant ships unmetered so turning qos on without a tenant
    file changes scheduling (fair queues) but rejects nobody."""

    tclass: str = "standard"
    rate_ms: float = 0.0      # bucket refill, device-ms per wall second
    burst_ms: float = 0.0     # bucket capacity (0 with rate>0 = rate*1s)
    max_inflight: int = 0     # concurrent admitted requests
    max_jobs: int = 0         # queued+running async jobs (round 11 tier)


def parse_tenant_specs(raw: str) -> dict[str, TenantSpec]:
    """The ``tenants`` knob: inline JSON (starts with ``{``) or a path
    to a JSON file.  Shape: ``{"name": {"class": "bulk", "rate_ms": 50,
    "burst_ms": 200, "max_inflight": 32, "max_jobs": 4}, ...}``.  A
    ``"*"`` entry is the template for tenants not named explicitly
    (anonymous traffic still maps to ``default``).  Unknown keys,
    unknown classes, and negative budgets are boot-time config errors —
    a typo'd quota must not silently admit everything."""
    if not raw:
        return {}
    text = raw
    if not raw.lstrip().startswith("{"):
        if not os.path.exists(raw):
            raise ValueError(f"tenants spec file {raw!r} does not exist")
        with open(raw) as f:
            text = f.read()
    try:
        doc = json.loads(text)
    except ValueError as e:
        raise ValueError(f"unparseable tenants spec: {e}") from None
    if not isinstance(doc, dict):
        raise ValueError("tenants spec must be a JSON object")
    out: dict[str, TenantSpec] = {}
    for name, entry in doc.items():
        if name != "*" and not RID_RE.match(name):
            raise ValueError(
                f"tenant name {name!r} must match [A-Za-z0-9._-]{{1,64}}"
            )
        if not isinstance(entry, dict):
            raise ValueError(f"tenant {name!r} spec must be an object")
        spec = TenantSpec()
        for key, value in entry.items():
            if key == "class":
                if value not in CLASSES:
                    raise ValueError(
                        f"tenant {name!r}: class must be one of "
                        f"{', '.join(CLASSES)}, got {value!r}"
                    )
                spec.tclass = value
            elif key in ("rate_ms", "burst_ms"):
                if isinstance(value, bool) or not isinstance(
                    value, (int, float)
                ):
                    raise ValueError(
                        f"tenant {name!r}: {key} must be a number, "
                        f"got {value!r}"
                    )
                if value < 0:
                    raise ValueError(f"tenant {name!r}: {key} must be >= 0")
                setattr(spec, key, float(value))
            elif key in ("max_inflight", "max_jobs"):
                # int(value) would silently truncate a fractional quota
                # (3.9 jobs -> 3) — the docstring promises a boot-time
                # error instead
                if isinstance(value, bool) or not isinstance(value, int):
                    raise ValueError(
                        f"tenant {name!r}: {key} must be an integer, "
                        f"got {value!r}"
                    )
                if value < 0:
                    raise ValueError(f"tenant {name!r}: {key} must be >= 0")
                setattr(spec, key, value)
            else:
                raise ValueError(f"tenant {name!r}: unknown key {key!r}")
        if spec.rate_ms > 0 and spec.burst_ms <= 0:
            spec.burst_ms = spec.rate_ms  # one second of burst by default
        out[name] = spec
    return out


class TokenBucket:
    """Device-time token bucket (injectable clock, so refill tests never
    sleep).  Tokens are device-milliseconds; refill is continuous."""

    def __init__(
        self,
        rate_ms: float,
        burst_ms: float,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.rate_ms = float(rate_ms)
        self.burst_ms = float(burst_ms)
        self._clock = clock
        self.tokens = self.burst_ms
        self._t = clock()

    def _refill(self) -> None:
        now = self._clock()
        if now > self._t:
            self.tokens = min(
                self.burst_ms, self.tokens + (now - self._t) * self.rate_ms
            )
        self._t = now

    def take(self, ms: float) -> tuple[bool, float]:
        """(admitted?, seconds until the deficit refills when not)."""
        self._refill()
        if self.tokens >= ms:
            self.tokens -= ms
            return True, 0.0
        deficit = ms - self.tokens
        return False, deficit / self.rate_ms if self.rate_ms > 0 else 60.0

    def credit(self, ms: float) -> None:
        """Refund (cache hit: the provisional device debit never ran)."""
        self._refill()
        self.tokens = min(self.burst_ms, self.tokens + ms)


class _Tenant:
    """One tenant's live state: policy, bucket, in-flight count, and the
    EWMA-measured per-request device cost the admission debit uses."""

    __slots__ = ("name", "spec", "bucket", "inflight", "ewma_ms", "device_ms")

    def __init__(self, name: str, spec: TenantSpec, clock):
        self.name = name
        self.spec = spec
        self.bucket = (
            TokenBucket(spec.rate_ms, spec.burst_ms, clock)
            if spec.rate_ms > 0
            else None
        )
        self.inflight = 0
        self.ewma_ms = 0.0
        self.device_ms = 0.0

    def est_cost_ms(self) -> float:
        return self.ewma_ms if self.ewma_ms > 0 else SEED_COST_MS


@dataclass
class Grant:
    """One admitted request's accounting handle: who it is, what was
    provisionally debited, and whether admission actually metered it
    (fail-open grants release as no-ops)."""

    tenant: str
    tclass: str
    charged_ms: float = 0.0
    metered: bool = False
    failed_open: bool = False
    _released: bool = field(default=False, repr=False)


class QosPolicy:
    """The tenant registry + admission/accounting surface the service
    owns (one per process, shared by every dispatcher and route).

    Thread-safe: admission and release run on the event loop, but
    ``charge`` is called from the batcher's resolve path which can run
    inside fetch tasks racing on the loop, and tests drive it from
    worker threads."""

    def __init__(
        self,
        tenants: str = "",
        *,
        default_class: str = "standard",
        weights: str = "",
        hit_cost_ms: float = 0.05,
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if default_class not in CLASSES:
            raise ValueError(
                f"qos_default_class must be one of {', '.join(CLASSES)}, "
                f"got {default_class!r}"
            )
        self.default_class = default_class
        self.weights = parse_weights(weights)
        self.hit_cost_ms = float(hit_cost_ms)
        self._specs = parse_tenant_specs(tenants)
        self._wildcard = self._specs.pop("*", None)
        self._metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: dict[str, _Tenant] = {}
        # fairness-gauge accumulators: device_ms only grows and tenants
        # are never evicted, so max/count/sum maintained per charge()
        # equal the full scan exactly without an O(tenants) walk on the
        # batcher's per-item resolve path
        self._dev_sum = 0.0
        self._dev_max = 0.0
        self._dev_n = 0

    # ------------------------------------------------------- identity

    def tenant_of(self, headers: dict[str, str]) -> str:
        """``x-api-key`` wins over ``x-tenant``; anything failing the
        request-id grammar maps to the default tenant (metering
        metadata, not an auth surface — see module docstring).

        An ``x-api-key`` value that is NOT a configured tenant name is a
        credential by convention — it must never appear verbatim in
        metric labels, log lines, or /v1/config, all of which are
        operator surfaces wider than the key's audience.  Unconfigured
        keys are pseudonymized to a stable ``key-<10 hex>`` digest
        (still one tenant per key, so metering works; the operator can
        recompute the digest from a suspect key when chasing a noisy
        neighbor).  Configured names and ``x-tenant`` values are
        operator-/client-chosen LABELS and pass through verbatim."""
        raw = headers.get("x-api-key") or ""
        from_key = bool(raw)
        if not raw:
            raw = headers.get("x-tenant") or ""
        if not raw or not RID_RE.match(raw):
            return DEFAULT_TENANT
        if from_key and raw not in self._specs:
            digest = hashlib.blake2b(raw.encode(), digest_size=5).hexdigest()
            return f"key-{digest}"
        return raw

    def _state(self, name: str) -> _Tenant:
        if not name:
            # jobs journaled before qos was enabled carry tenant="" —
            # that is default-tenant work, not a tenant named ""
            name = DEFAULT_TENANT
        state = self._tenants.get(name)
        if state is None:
            spec = self._specs.get(name)
            if (
                spec is None
                and name != DEFAULT_TENANT
                and len(self._tenants) >= MAX_TENANTS
            ):
                # MAX_TENANTS cardinality guard: an unconfigured name
                # past the cap is metered as default-tenant traffic
                # rather than pinning new state/label series
                return self._state(DEFAULT_TENANT)
            if spec is None:
                if name != DEFAULT_TENANT and self._wildcard is not None:
                    spec = self._wildcard
                else:
                    spec = TenantSpec(tclass=self.default_class)
            state = self._tenants[name] = _Tenant(name, spec, self._clock)
        return state

    def class_of(self, tenant: str) -> str:
        with self._lock:
            return self._state(tenant).spec.tclass

    # ------------------------------------------------------ admission

    def admit(self, headers: dict[str, str]) -> Grant:
        """Identity + in-flight budget + token-bucket debit, in that
        order.  Raises ``TenantOverQuota`` (429 + Retry-After from the
        bucket's refill) when a budget is exhausted.  An admission-layer
        CRASH — the ``qos.admission_raise`` fault site, or any
        unexpected exception — fails OPEN to an unmetered default-tenant
        grant: a broken accounting layer must degrade to round-12
        behaviour, not take the service down (availability over
        accounting; pinned by tests/test_qos.py)."""
        try:
            faults.raise_if_armed("qos.admission_raise")
            return self._admit_inner(headers)
        except errors.TenantOverQuota:
            raise
        except Exception as e:  # noqa: BLE001 — fail open by design
            slog.event(
                _log, "qos_admission_failed_open", level=logging.ERROR,
                error=f"{type(e).__name__}: {e}",
            )
            if self._metrics is not None:
                self._metrics.inc_counter("qos_admission_errors_total")
            return Grant(
                DEFAULT_TENANT, self.default_class,
                metered=False, failed_open=True,
            )

    def _admit_inner(self, headers: dict[str, str]) -> Grant:
        name = self.tenant_of(headers)
        with self._lock:
            state = self._state(name)
            name = state.name  # may have collapsed (MAX_TENANTS guard)
            spec = state.spec
            if spec.max_inflight > 0 and state.inflight >= spec.max_inflight:
                self._shed_locked(name)
                raise errors.TenantOverQuota(
                    f"tenant {name!r} at its in-flight budget "
                    f"({state.inflight}/{spec.max_inflight})",
                    retry_after_s=state.est_cost_ms() / 1e3,
                    tenant=name,
                )
            est = state.est_cost_ms()
            charged = 0.0
            if state.bucket is not None:
                # a single debit can never exceed the bucket's capacity:
                # a tenant whose measured cost outgrows its burst (one
                # contended batch can inflate the EWMA past a small
                # burst_ms) must degrade to ~rate/burst admissions per
                # second, not starve FOREVER because take(est) can no
                # longer succeed at any token level (standard
                # token-bucket practice; pinned by tests/test_qos.py)
                est = min(est, state.bucket.burst_ms)
                ok, wait_s = state.bucket.take(est)
                if not ok:
                    self._shed_locked(name)
                    raise errors.TenantOverQuota(
                        f"tenant {name!r} over its device-time budget "
                        f"({spec.rate_ms:g} ms/s)",
                        retry_after_s=wait_s,
                        tenant=name,
                    )
                charged = est
            state.inflight += 1
            tclass = spec.tclass
        if self._metrics is not None:
            self._metrics.inc_labeled(
                "tenant_requests_total", ("tenant", "class"), (name, tclass)
            )
        return Grant(name, tclass, charged_ms=charged, metered=True)

    def release(self, grant: Grant) -> None:
        """End of the request: drop the in-flight slot.  Idempotent, and
        a no-op for fail-open grants (nothing was ever counted)."""
        if grant.failed_open or grant._released:
            return
        grant._released = True
        with self._lock:
            state = self._tenants.get(grant.tenant)
            if state is not None:
                state.inflight = max(0, state.inflight - 1)

    def charge_hit(self, grant: Grant) -> None:
        """Cache hit or coalesced waiter: the provisional device debit
        never runs on the device (a waiter's work is the LEADER's batch
        item, charged by the batcher) — refund it, keep a small fixed
        cost so the hit path is metered traffic, not free laundering
        (module docstring).  Idempotent: the refund drains to zero once
        ``charged_ms`` reaches the hit cost."""
        if grant.failed_open or not grant.metered:
            return
        with self._lock:
            state = self._tenants.get(grant.tenant)
            if state is None or state.bucket is None:
                return
            refund = grant.charged_ms - self.hit_cost_ms
            if refund > 0:
                state.bucket.credit(refund)
            grant.charged_ms = min(grant.charged_ms, self.hit_cost_ms)

    # ----------------------------------------------------- accounting

    def charge(self, tenant: str, cost_s: float) -> None:
        """One executed request's measured share of its batch wall (the
        batcher calls this per item at resolve).  Updates the tenant's
        device-time ledger, its admission-debit EWMA, the
        ``tenant_device_ms_total`` counter, and the fairness gauge."""
        ms = cost_s * 1e3
        with self._lock:
            state = self._state(tenant or DEFAULT_TENANT)
            if ms > 0 and state.device_ms == 0.0:
                self._dev_n += 1
            state.device_ms += ms
            self._dev_sum += ms
            if state.device_ms > self._dev_max:
                self._dev_max = state.device_ms
            state.ewma_ms = (
                ms
                if state.ewma_ms == 0.0
                else (1 - _EWMA_ALPHA) * state.ewma_ms + _EWMA_ALPHA * ms
            )
            fairness = self._fairness_locked()
        if self._metrics is not None:
            self._metrics.inc_labeled(
                "tenant_device_ms_total", "tenant", state.name, round(ms, 3)
            )
            self._metrics.set_gauge("tenant_fairness", fairness)

    def record_shed(self, tenant: str) -> None:
        """Any rejection charged to a tenant — quota 429, overload 503,
        bulk eviction — lands in ``tenant_shed_total{tenant=}``: the
        split the noisy-neighbor drill pins (all shed traffic must be
        charged to the abuser)."""
        with self._lock:
            # through _state so a past-the-cap name sheds as default
            # instead of minting a fresh label series
            self._shed_locked(self._state(tenant).name)

    def _shed_locked(self, tenant: str) -> None:
        if self._metrics is not None:
            self._metrics.inc_labeled("tenant_shed_total", "tenant", tenant)

    def _fairness_locked(self) -> float:
        """max/mean of per-tenant device time across tenants that have
        run anything — 1.0 is a perfectly fair split, like the lane
        imbalance gauge (one reading for "is someone hogging").  Served
        from the per-charge accumulators, so reading it (and charging)
        never walks the tenant table."""
        if self._dev_n == 0 or self._dev_sum <= 0:
            return 1.0
        return round(self._dev_max * self._dev_n / self._dev_sum, 4)

    def drop_tenant(self, name: str) -> None:
        """Forget a tenant's live state — bucket, EWMA, device ledger,
        in-flight count.  Drill/test surgery only (the qos drill
        installs a calibrated budget mid-run; a real fleet reboots or
        reloads): the fairness accumulators assume tenants are never
        evicted, so this is the one place that rebuilds them."""
        with self._lock:
            if self._tenants.pop(name, None) is None:
                return
            used = [
                t.device_ms for t in self._tenants.values() if t.device_ms > 0
            ]
            self._dev_n = len(used)
            self._dev_sum = sum(used)
            self._dev_max = max(used, default=0.0)

    # ----------------------------------------------------- jobs tier

    def job_budget(self, tenant: str) -> int:
        """0 = unlimited; the round-11 jobs tier checks queued+running
        jobs for the tenant against this before admitting a submit."""
        with self._lock:
            return self._state(tenant).spec.max_jobs

    # -------------------------------------------------------- surface

    def new_queue(self, clock=time.perf_counter) -> "DrrQueue":
        """One DRR queue per dispatcher (deconv/dream/sweep each own
        their submit queue, exactly like the FIFO they replace)."""
        return DrrQueue(self.weights, clock=clock)

    def snapshot(self) -> dict:
        """Live per-tenant occupancy for /v1/config."""
        with self._lock:
            return {
                "default_class": self.default_class,
                "weights": dict(self.weights),
                "hit_cost_ms": self.hit_cost_ms,
                "tenants": {
                    t.name: {
                        "class": t.spec.tclass,
                        "inflight": t.inflight,
                        "device_ms": round(t.device_ms, 3),
                        "ewma_cost_ms": round(t.ewma_ms, 4),
                        "tokens_ms": (
                            round(t.bucket.tokens, 3)
                            if t.bucket is not None
                            else None
                        ),
                        "rate_ms": t.spec.rate_ms or None,
                    }
                    for t in self._tenants.values()
                },
                "fairness": self._fairness_locked(),
            }

    def counts(self) -> dict:
        with self._lock:
            return {
                "tenants_active": len(self._tenants),
                "inflight": sum(t.inflight for t in self._tenants.values()),
            }


class DrrQueue:
    """Deficit-round-robin multi-queue keyed by (tenant, class), wire-
    compatible with the slice of ``asyncio.Queue`` the batcher uses
    (``put``/``get``/``get_nowait``/``qsize``/``empty``).

    SINGLE CONSUMER by contract: the batcher's one collect loop is the
    only ``get`` caller (puts may come from any task on the loop), which
    is what lets readiness be a bare Event instead of a waiter queue.

    Pop order:
    1. **Deadline jump** — a head-of-queue INTERACTIVE item within
       ``jump_s`` of its deadline pops ahead of the rotation.  Bulk
       (and standard) never jump: the privilege is exactly what the
       interactive class buys.  Expired items still go through the
       batcher's reap boundaries — the jump saves the savable, the reap
       504s the dead, and a jumped-then-expired item is never
       dispatched (pinned by tests/test_qos.py).
    2. **DRR** — the active queue at the front of the rotation serves
       while its deficit lasts (quantum × class weight added when the
       rotation reaches it), then rotates to the back.  An emptied
       queue leaves the rotation and forfeits its deficit — the
       standard DRR rule that stops an idle tenant banking credit.

    ``evict_bulk`` is the shed-order hook: overload evicts the NEWEST
    item of the deepest bulk queue (the request that would have waited
    longest anyway) so a higher-class arrival can take its place."""

    def __init__(
        self,
        weights: dict[str, int] | None = None,
        *,
        quantum: int = 1,
        jump_s: float = 0.25,
        clock: Callable[[], float] = time.perf_counter,
    ):
        self._weights = dict(weights or DEFAULT_WEIGHTS)
        self._quantum = max(1, int(quantum))
        self._jump_s = float(jump_s)
        self._clock = clock
        self._queues: dict[tuple[str, str], deque] = {}
        self._active: deque[tuple[str, str]] = deque()
        self._in_active: set[tuple[str, str]] = set()
        # insertion-ordered set of the ACTIVE interactive keys — the
        # only class the jump scan can ever select, so the per-pop scan
        # is bounded by interactive tenants, not every active (tenant,
        # class) in the rotation (up to MAX_TENANTS under qos with no
        # spec, all on the collect loop's hot path)
        self._interactive: dict[tuple[str, str], None] = {}
        self._deficit: dict[tuple[str, str], float] = {}
        self._size = 0
        self._ready = asyncio.Event()

    @staticmethod
    def _key_of(item) -> tuple[str, str]:
        return (
            getattr(item, "tenant", "") or DEFAULT_TENANT,
            getattr(item, "tclass", "") or "standard",
        )

    def qsize(self) -> int:
        return self._size

    def empty(self) -> bool:
        return self._size == 0

    async def put(self, item) -> None:
        self.put_nowait(item)

    def put_nowait(self, item) -> None:
        key = self._key_of(item)
        q = self._queues.get(key)
        if q is None:
            q = self._queues[key] = deque()
        q.append(item)
        if key not in self._in_active:
            self._in_active.add(key)
            self._active.append(key)
            if key[1] == "interactive":
                self._interactive[key] = None
            self._deficit.setdefault(key, 0.0)
        self._size += 1
        self._ready.set()

    async def get(self):
        # single consumer: no await sits between the size check and the
        # clear, so a put on this loop cannot fall into the gap
        while True:
            if self._size:
                return self.get_nowait()
            self._ready.clear()
            await self._ready.wait()

    def get_nowait(self):
        if self._size == 0:
            raise asyncio.QueueEmpty
        item = self._pop_jump()
        if item is None:
            item = self._pop_drr()
        self._size -= 1
        if self._size == 0:
            self._ready.clear()
        return item

    def _deactivate(self, key: tuple[str, str]) -> None:
        # drop the key's queue and deficit entirely — an idle (tenant,
        # class) must not pin an empty deque per dispatcher forever
        self._in_active.discard(key)
        try:
            self._active.remove(key)
        except ValueError:
            pass
        self._interactive.pop(key, None)
        self._deficit.pop(key, None)
        self._queues.pop(key, None)

    def _pop_jump(self):
        if not self._interactive:
            return None
        now = self._clock()
        for key in self._interactive:
            q = self._queues[key]
            if (
                q
                and q[0].deadline is not None
                and q[0].deadline - now <= self._jump_s
            ):
                item = q.popleft()
                if not q:
                    self._deactivate(key)
                return item
        return None

    def _pop_drr(self):
        while True:
            key = self._active[0]
            q = self._queues.get(key)
            if not q:
                # emptied by a jump or an eviction while mid-rotation
                self._deactivate(key)
                continue
            if self._deficit[key] < 1.0:
                self._deficit[key] += self._quantum * self._weights.get(
                    key[1], 1
                )
                self._active.rotate(-1)
                continue
            self._deficit[key] -= 1.0
            item = q.popleft()
            if not q:
                self._deactivate(key)
            return item

    def evict_bulk(self):
        """Newest item of the deepest bulk queue, or None when no bulk
        traffic is queued (the caller then sheds the arrival itself)."""
        best: tuple[str, str] | None = None
        for key, q in self._queues.items():
            if key[1] == "bulk" and q and (
                best is None or len(q) > len(self._queues[best])
            ):
                best = key
        if best is None:
            return None
        q = self._queues[best]
        item = q.pop()
        if not q:
            self._deactivate(best)
        self._size -= 1
        if self._size == 0:
            self._ready.clear()
        return item

    def depths(self) -> dict[str, int]:
        """Queued items per class (operator surface, /v1/config)."""
        out: dict[str, int] = {}
        for (_, tclass), q in self._queues.items():
            if q:
                out[tclass] = out.get(tclass, 0) + len(q)
        return out
