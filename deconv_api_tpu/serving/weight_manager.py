"""HBM weight manager: multi-model serving from one device pool (round 15).

The server has always loaded exactly ONE backbone per process
(`config.model`, resolved once at boot) even though the registry ships
seven specs — a fleet serving all of them pays N× processes and N× HBM.
The two serving levers every production system pulls here are device
MEMORY and weight PRECISION (the Gemma-on-Cloud-TPU serving comparison
and TVM both frame serving cost exactly this way — PAPERS.md); this
module builds both:

- **Paged residency.**  Model params live as host-side archives; a
  per-lane LRU pages them into HBM on demand under ``hbm_budget_bytes``
  (accounting REAL per-lane ``device_put`` bytes).  Cold-model requests
  queue behind a singleflight page-in promise — one transfer per
  (model, lane), concurrent requests for the same cold model coalesce —
  and eviction is lane-aware and NEVER unloads a model with in-flight
  batches (a pin count guards every dispatched batch).  Pinned models
  (the boot-warmed set) are never evicted at all.

- **A quantized weight tier.**  ``weight_dtype`` selects what the HBM
  copy stores: ``f32`` (exact — the default), ``bf16`` (store bf16,
  cast to f32 on use: half the bytes), or ``int8`` (per-tensor
  symmetric int8 for the conv/dense kernels with f32 dequant-on-use:
  ~quarter the kernel bytes).  Dequantisation happens INSIDE the jitted
  programs (serving/models.py wraps every params-consuming entry), so
  HBM holds the quantized form and the f32 view only materialises as
  program temporaries.  Fidelity is bounded by PSNR parity tests
  (tests/test_weight_manager.py), not byte equality — the precision
  knob folds into the response-cache prefix so a dtype change
  invalidates every cached payload.

Two operating modes keep the single-model hot path untouched:

- **Inert** (one served model, f32, no budget — the default config):
  byte-for-byte the pre-manager path.  The bundle keeps its original
  params object (``lane_params(0) is params``), lanes replicate via
  ``ModelBundle.set_lanes`` exactly as before, and ``checkout`` is a
  dict lookup.  Zero new work per dispatch.

- **Managed** (any of: several served models, a quantized tier, a byte
  budget): bundle params are archived to host numpy at build time, the
  quantized form is precomputed once, and HBM residency is explicit —
  ``checkout`` pages in (or waits on the in-flight page-in), pins, and
  returns the device tree; ``release`` unpins after the batch's results
  are materialised.

Thread model: ``checkout``/``release`` run on dispatch worker threads
(page-in wait deliberately blocks the LANE's dispatch worker — that is
the "cold requests queue behind the promise" contract; other lanes and
the event loop never block).  Bundle builds are serialized by a build
lock; all bookkeeping sits under one mutex.  Page-in wall time rides
the existing metrics spine as a ``weight_page_in`` stage observation
(the wait histogram) and, via the batcher, as a ``weight_page_in`` span
on every member request's trace; because the transfer happens inside
the dispatch wall, the QoS device-time meter charges it to the
requesting tenants automatically.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from deconv_api_tpu import errors
from deconv_api_tpu.utils import slog

_log = slog.get_logger("deconv.weights")

WEIGHT_DTYPES = ("f32", "bf16", "int8")

# Reserved leaf keys marking a per-tensor symmetric int8 quantized
# kernel inside a params pytree.  The dict IS the leaf: dequantize walks
# the tree structurally, so these names must never collide with real
# parameter names (model params use layer/leaf names like 'kernel').
_Q8_KEY = "__q8__"
_Q8_SCALE = "__q8_scale__"

# The symmetric-int8 convention shared with the int8 EXECUTION tier
# (round 18): it lives in the utils layer (utils/quantize.py) beneath
# both engine and serving, re-exported here for this module's callers —
# weight-at-rest int8 (this module) and arithmetic-in-int8
# (quality=int8) agree on what a quantized tensor means.
from deconv_api_tpu.utils.quantize import Q8_LEVELS, int8_scale  # noqa: E402


def _is_q8_leaf(node: Any) -> bool:
    return isinstance(node, dict) and _Q8_KEY in node


def quantize_params(tree: Any, weight_dtype: str) -> Any:
    """Host-side quantisation of a params pytree into its stored form.

    ``f32`` passes leaves through untouched.  ``bf16`` stores every
    float leaf as bfloat16 (ml_dtypes — numpy-native, zero-copy into
    jax).  ``int8`` stores kernels (ndim >= 2 float leaves: conv HWIO
    kernels and dense matrices — where the bytes are) as per-tensor
    symmetric int8 with an f32 scale; biases/BN vectors stay f32, their
    bytes are noise and their dynamic range is not.
    """
    if weight_dtype not in WEIGHT_DTYPES:
        raise ValueError(
            f"weight_dtype must be one of {WEIGHT_DTYPES}, got {weight_dtype!r}"
        )
    if weight_dtype == "f32":
        return tree

    import ml_dtypes

    def q(node):
        if isinstance(node, dict):
            return {k: q(v) for k, v in node.items()}
        arr = np.asarray(node)
        if not np.issubdtype(arr.dtype, np.floating):
            return arr
        if weight_dtype == "bf16":
            return arr.astype(ml_dtypes.bfloat16)
        if arr.ndim >= 2:
            # per-tensor symmetric (int8_scale owns the amax→scale rule)
            amax = float(np.max(np.abs(arr))) if arr.size else 0.0
            scale = np.float32(int8_scale(amax))
            qarr = np.clip(
                np.round(arr.astype(np.float32) / scale),
                -Q8_LEVELS, Q8_LEVELS,
            ).astype(np.int8)
            return {_Q8_KEY: qarr, _Q8_SCALE: scale}
        return arr.astype(np.float32)

    return q(tree)


def dequantize_params(tree: Any) -> Any:
    """The in-program inverse of :func:`quantize_params` — pure jax ops,
    traceable, so jitted programs consume the stored tree directly and
    the f32 view exists only as program temporaries (dequant-on-use:
    HBM holds the quantized bytes).  f32 trees pass through unchanged,
    which keeps the wrapper free for the default tier."""
    import jax.numpy as jnp

    def dq(node):
        if _is_q8_leaf(node):
            return node[_Q8_KEY].astype(jnp.float32) * node[_Q8_SCALE]
        if isinstance(node, dict):
            return {k: dq(v) for k, v in node.items()}
        if hasattr(node, "dtype") and node.dtype == jnp.bfloat16:
            return node.astype(jnp.float32)
        return node

    return dq(tree)


def tree_nbytes(tree: Any) -> int:
    """Total bytes of every array leaf — for a device tree this is the
    real per-lane HBM charge (replicated mesh lanes hold one full copy
    per device; the budget is per single copy)."""
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0)) for leaf in jax.tree_util.tree_leaves(tree)
    )


@dataclass
class _Resident:
    tree: Any
    nbytes: int


class WeightManager:
    """Own every served model's host archive and HBM residency.

    ``builders`` maps model name -> zero-arg ModelBundle factory (the
    registry's entries, or injected specs in tests/tools); ``default``
    is the boot model — always served, always pinned.  ``placements``
    is one entry per executor lane (a Device, a Mesh slice, the
    whole-pool Mesh, or None for the single default-device stream).
    ``weights_loader`` is the service's per-model checkpoint hook,
    invoked once at bundle build."""

    def __init__(
        self,
        builders: dict[str, Callable[[], Any]],
        default: str,
        *,
        default_bundle: Any = None,
        pinned: tuple[str, ...] = (),
        placements: list | None = None,
        mesh=None,
        budget_bytes: int = 0,
        weight_dtype: str = "f32",
        metrics=None,
        weights_loader: Callable[[str, Any], None] | None = None,
    ):
        if weight_dtype not in WEIGHT_DTYPES:
            raise ValueError(
                f"weight_dtype must be one of {WEIGHT_DTYPES}, got "
                f"{weight_dtype!r}"
            )
        if default not in builders:
            raise ValueError(
                f"default model {default!r} missing from the served set "
                f"{sorted(builders)}"
            )
        self.builders = dict(builders)
        self.default = default
        self.served = frozenset(self.builders)
        # default is always pinned: the boot-warmed model must never pay
        # a page-in tax mid-traffic because colder models pushed it out
        self.pinned = tuple(dict.fromkeys((default, *pinned)))
        unknown = [p for p in self.pinned if p not in self.served]
        if unknown:
            raise ValueError(
                f"pinned model(s) {unknown} are not in the served set "
                f"{sorted(self.served)}"
            )
        self.placements = list(placements) if placements else [mesh]
        self.mesh = mesh
        self.budget_bytes = int(budget_bytes)
        self.weight_dtype = weight_dtype
        # Managed mode: anything beyond the classic single-model f32
        # server needs explicit residency.  Inert mode IS the pre-round-15
        # path, kept byte- and object-identical (test_lanes pins
        # ``lane_params(0) is params`` and per-lane ``set_lanes``
        # replication).
        self.managed = (
            len(self.served) > 1 or weight_dtype != "f32" or self.budget_bytes > 0
        )
        self._metrics = metrics
        self._weights_loader = weights_loader
        self._lock = threading.Lock()
        self._build_lock = threading.Lock()
        self._bundles: dict[str, Any] = {}
        self._archives: dict[str, Any] = {}  # quantized host trees (managed)
        self._resident: list[OrderedDict[str, _Resident]] = [
            OrderedDict() for _ in self.placements
        ]
        self._pins: dict[tuple[str, int], int] = {}
        self._paging: dict[tuple[str, int], threading.Event] = {}
        self.page_ins = 0
        self.page_outs = 0
        self.page_in_bytes = 0
        self.overcommits = 0
        if default_bundle is not None:
            self._adopt(default, default_bundle)

    # ------------------------------------------------------------- bundles

    @property
    def lane_count(self) -> int:
        return len(self.placements)

    def _adopt(self, name: str, bundle) -> None:
        """Register a pre-built bundle (the service builds the default —
        weights loaded, mesh attached — before the manager exists)."""
        self._prepare(name, bundle, load_weights=False)
        with self._lock:
            self._bundles[name] = bundle

    def _prepare(self, name: str, bundle, *, load_weights: bool) -> None:
        """One-time per-bundle setup: mesh, checkpoint load, and (in
        managed mode) the host archive + precomputed quantized form."""
        if self.mesh is not None and bundle.mesh is None:
            bundle.mesh = self.mesh
        if load_weights and self._weights_loader is not None:
            self._weights_loader(name, bundle)
        if not self.managed:
            # inert multi-lane: the classic boot-time replication
            if self.lane_count > 1:
                bundle.set_lanes(self.placements)
            return
        # Managed: params become a host numpy archive (jax-initialised
        # params are DEVICE arrays — without this, "paging out" would
        # free nothing because the init copy pins HBM forever), and the
        # quantized stored form is computed ONCE (page-in is then a pure
        # device_put, not a re-quantisation per transfer).
        import jax

        bundle.params = jax.tree_util.tree_map(np.asarray, bundle.params)
        bundle.weight_dtype = self.weight_dtype
        if self.lane_count > 1:
            # placement metadata only — batched_visualizer reads it to
            # shard mesh-slice lanes and _stage_batch to commit inputs;
            # the param replicas themselves live in this manager
            bundle._lane_placements = list(self.placements)
        self._archives[name] = quantize_params(bundle.params, self.weight_dtype)

    def peek_bundle(self, name: str):
        """The bundle when already built, else None — the event loop's
        fast path (builds happen on worker threads)."""
        with self._lock:
            return self._bundles.get(name)

    def bundle(self, name: str):
        """The model's host-resident bundle, built on first use (weights
        init + checkpoint load under the build lock — one build at a
        time; callers for an already-built model never wait)."""
        with self._lock:
            b = self._bundles.get(name)
        if b is not None:
            return b
        if name not in self.builders:
            raise errors.UnknownModel(
                f"unknown or unserved model {name!r}; serving: "
                f"{sorted(self.served)}"
            )
        with self._build_lock:
            with self._lock:
                b = self._bundles.get(name)
            if b is not None:
                return b
            t0 = time.perf_counter()
            b = self.builders[name]()
            self._prepare(name, b, load_weights=True)
            with self._lock:
                self._bundles[name] = b
            slog.event(
                _log, "model_built", model=name,
                ms=round((time.perf_counter() - t0) * 1e3, 1),
                managed=self.managed,
            )
            return b

    # ----------------------------------------------------------- residency

    def checkout(self, name: str, lane: int = 0):
        """The device params tree one dispatch must read, paged in if
        cold, PINNED against eviction until :meth:`release`.  Returns
        ``(tree, page_in_seconds)`` — 0.0 on the warm path.  Runs on a
        dispatch worker thread; a cold model blocks only that lane's
        worker (concurrent requests for the same cold (model, lane)
        coalesce onto ONE transfer via the paging promise)."""
        bundle = self.bundle(name)
        if not self.managed:
            return bundle.lane_params(lane), 0.0
        key = (name, lane)
        while True:
            with self._lock:
                res = self._resident[lane].get(name)
                if res is not None:
                    self._resident[lane].move_to_end(name)
                    self._pins[key] = self._pins.get(key, 0) + 1
                    return res.tree, 0.0
                ev = self._paging.get(key)
                if ev is None:
                    ev = threading.Event()
                    self._paging[key] = ev
                    break  # this thread is the page-in leader
            # a transfer for this (model, lane) is in flight: wait for
            # its promise, then re-check — if the leader failed, the
            # paging slot is empty again and a waiter takes over
            if not ev.wait(timeout=600.0):
                raise errors.Unavailable(
                    f"weight page-in for model {name!r} lane {lane} did "
                    "not complete"
                )
        t0 = time.perf_counter()
        try:
            tree = self._place(self._archives[name], self.placements[lane])
            nbytes = tree_nbytes(tree)
        except BaseException:
            with self._lock:
                self._paging.pop(key, None)
            ev.set()
            raise
        dt = time.perf_counter() - t0
        with self._lock:
            self._resident[lane][name] = _Resident(tree, nbytes)
            self._pins[key] = self._pins.get(key, 0) + 1
            self.page_ins += 1
            self.page_in_bytes += nbytes
            evicted = self._evict_locked(lane, exclude=name)
            self._paging.pop(key, None)
        ev.set()
        if self._metrics is not None:
            self._metrics.inc_counter("weight_page_ins_total")
            self._metrics.inc_counter("weight_page_bytes_total", nbytes)
            # the page-in WAIT histogram (stage quantiles + exposition)
            self._metrics.observe_stage("weight_page_in", dt)
        self._publish_gauges()
        slog.event(
            _log, "weight_page_in", model=name, lane=lane,
            mb=round(nbytes / 1e6, 2), ms=round(dt * 1e3, 1),
            evicted=evicted or None,
        )
        return tree, dt

    def release(self, name: str, lane: int = 0) -> None:
        """Drop one dispatch's eviction pin (the batch's results are
        materialised; the device is done with this replica)."""
        if not self.managed:
            return
        key = (name, lane)
        with self._lock:
            n = self._pins.get(key, 0)
            if n <= 1:
                self._pins.pop(key, None)
            else:
                self._pins[key] = n - 1
            if n > 0 and name not in self._resident[lane]:
                # invariant tripwire: a pinned model must NEVER leave
                # residency while its dispatch runs — if this fires the
                # eviction guard has a bug (the model-mix drill errors
                # loudly on this counter)
                if self._metrics is not None:
                    self._metrics.inc_counter("weight_evict_inflight_total")
                slog.event(
                    _log, "weight_evict_inflight", level=logging.ERROR,
                    model=name, lane=lane,
                )

    def _place(self, tree: Any, placement) -> Any:
        """One real device transfer: the stored (possibly quantized)
        tree onto a lane's chip / mesh slice / the default device."""
        import jax
        from jax.sharding import Mesh

        if placement is None:
            return jax.device_put(tree, jax.devices()[0])
        if isinstance(placement, Mesh):
            from deconv_api_tpu.parallel.mesh import replicated

            return jax.device_put(tree, replicated(placement))
        return jax.device_put(tree, placement)

    def _evict_locked(self, lane: int, exclude: str) -> list[str]:
        """LRU page-out down to the byte budget — called under the lock
        right after an insert.  Skips pinned models, any model with
        in-flight batches on this lane, and the entry that triggered the
        eviction (evicting the page-in we are completing would thrash).
        When nothing is evictable the budget OVERSHOOTS loudly rather
        than failing requests: availability over accounting."""
        if self.budget_bytes <= 0:
            return []
        od = self._resident[lane]
        total = sum(r.nbytes for r in od.values())
        evicted: list[str] = []
        for victim in list(od):
            if total <= self.budget_bytes:
                break
            if (
                victim == exclude
                or victim in self.pinned
                or self._pins.get((victim, lane), 0) > 0
            ):
                continue
            total -= od.pop(victim).nbytes
            self.page_outs += 1
            evicted.append(victim)
            if self._metrics is not None:
                self._metrics.inc_counter("weight_page_outs_total")
        if total > self.budget_bytes:
            self.overcommits += 1
            if self._metrics is not None:
                self._metrics.inc_counter("weight_budget_overcommit_total")
            slog.event(
                _log, "weight_budget_overcommit", level=logging.WARNING,
                lane=lane, resident_bytes=total, budget_bytes=self.budget_bytes,
                note="every resident model is pinned or in flight; "
                "eviction never unloads in-flight weights",
            )
        return evicted

    def enforce_budget(self) -> list[str]:
        """Apply the byte budget NOW: page out LRU victims on every lane
        until each is within budget (pinned and in-flight models still
        never move).  Eviction normally runs at page-in time; this is
        the hook for a budget LOWERED at runtime (drills; a future admin
        surface)."""
        out: list[str] = []
        with self._lock:
            for lane in range(self.lane_count):
                out.extend(self._evict_locked(lane, exclude=""))
        self._publish_gauges()
        return out

    def _publish_gauges(self) -> None:
        if self._metrics is None:
            return
        with self._lock:
            per_lane = [
                (i, len(od), sum(r.nbytes for r in od.values()))
                for i, od in enumerate(self._resident)
            ]
        for lane, count, nbytes in per_lane:
            self._metrics.set_labeled_gauge(
                "resident_models", "lane", str(lane), count
            )
            self._metrics.set_labeled_gauge(
                "weight_resident_bytes", "lane", str(lane), nbytes
            )

    # ------------------------------------------------------------ surfaces

    def resident_models(self, lane: int = 0) -> list[str]:
        """Models resident on one lane, LRU order (oldest first).  In
        inert mode the default model is the whole answer — its params
        are device-resident by construction."""
        if not self.managed:
            return [self.default]
        with self._lock:
            return list(self._resident[lane])

    def inflight_pins(self, name: str, lane: int = 0) -> int:
        with self._lock:
            return self._pins.get((name, lane), 0)

    def snapshot(self) -> dict:
        """Live residency for /v1/config (and the drills)."""
        with self._lock:
            lanes = {
                str(i): {
                    "resident": list(od),
                    "bytes": sum(r.nbytes for r in od.values()),
                }
                for i, od in enumerate(self._resident)
            }
            built = sorted(self._bundles)
        return {
            "managed": self.managed,
            "weight_dtype": self.weight_dtype,
            "hbm_budget_bytes": self.budget_bytes,
            "served": sorted(self.served),
            "pinned": list(self.pinned),
            "built": built,
            "lanes": lanes if self.managed else {
                str(i): {"resident": [self.default], "bytes": 0}
                for i in range(self.lane_count)
            },
            "page_ins": self.page_ins,
            "page_outs": self.page_outs,
            "page_in_bytes": self.page_in_bytes,
            "overcommits": self.overcommits,
        }

    def ready_block(self) -> dict:
        """The compact residency block /readyz carries when more than
        one model is served (operators read "which models answer warm
        right now" straight off the probe)."""
        with self._lock:
            resident = {
                str(i): list(od) for i, od in enumerate(self._resident)
            }
        if not self.managed:
            resident = {
                str(i): [self.default] for i in range(self.lane_count)
            }
        return {
            "served": len(self.served),
            "pinned": len(self.pinned),
            "resident": resident,
        }
