"""Async batching dispatcher: coalesce concurrent requests into padded
device batches.

The reference's endpoint is `async def` over seconds of blocking compute, so
its true concurrency is 1 (SURVEY §2.2.5).  Here requests enqueue a future
and a single dispatcher task drains the queue up to `max_batch` (waiting at
most `window_ms` for stragglers), groups by (layer, mode) — each group is
one compiled executable — and pads the image batch to a power-of-two bucket
so XLA never sees a new batch shape.  All device DISPATCH happens from that
one task (in dispatch order), which also removes the reference's
shared-graph thread-safety hack (`tb._SYMBOLIC_SCOPE`, app/main.py:54;
SURVEY §5 race-detection row).

Execution is PIPELINED (round 3): the dispatcher enqueues a batch's device
program without blocking and farms the result fetch (device_get + host
postprocess, ~71 ms of tunnel round trip remote — BASELINE.md tunnel
anatomy) out to a bounded set of fetch tasks, so batch N+1 executes on the
device while batch N's results stream back.  `pipeline_depth` caps
dispatched-but-unfetched batches; depth 1 restores the serial
dispatch->fetch->resolve loop.  Worker threads keep the event loop free in
both modes.

Round 6 generalised the depth-2 overlap into a THREE-STAGE pipeline:

- collect: the `_run` loop drains the submit queue GREEDILY with
  `get_nowait` before waiting out the straggler window.  The r5 loopback
  probe showed the old per-item `wait_for(queue.get(), ...)` drain paying
  one event-loop scheduling latency PER ITEM — under load it collected
  ~3 items per window while ~50 sat in the queue, so every batch ran far
  under max_batch and requests crossed a near-empty-looking queue in
  ~190 ms.
- dispatch: collected batches move through a BOUNDED handoff queue to a
  dedicated dispatch-stage task, so the collect loop never blocks on a
  device dispatch (or its pipeline-depth permit) and keeps draining while
  the device works.  The bound is the backpressure: when the device falls
  behind, the handoff queue fills, collection stalls, queue depth grows,
  and the load-shed estimator reacts.
- fetch/encode: unchanged — bounded fetch tasks materialise results while
  later batches dispatch; JPEG encode happens in the routes on the codec
  worker pool (serving/codec_pool.py).

Queue-depth gauges (`collect_queue_depth`, `dispatch_queue_depth`,
`inflight_batches`) are published through Metrics at each stage boundary.

Round 7 put the content-addressed response cache + singleflight table
(serving/cache.py) IN FRONT of this dispatcher: cache hits never reach
submit(), and with singleflight on, concurrent identical requests collapse
to one submit — the leader's finished response is published to the
coalesced waiters when its batch completes.  What this file contributes is
the shed path's actionable backoff: the 503's Retry-After derives from
`_estimated_drain_s`, the same live estimate that triggered the shed.

Round 10 broke the single-stream assumption itself: on a multi-chip host
the dispatch stage schedules each collected batch onto the LEAST-LOADED
**executor lane** (LanePool/ExecutorLane below — one device or one small
dp mesh per lane, params replicated per lane by the service), so batches
for different keys, and consecutive batches for one key when
pipeline_depth allows, execute concurrently on different chips.  Each
lane carries its own dispatch worker, its own fetch-permit budget
(pipeline_depth becomes per-lane), and its own circuit breaker — one
sick chip opens ONE lane's breaker and the pool degrades to the
survivors instead of failing fast everywhere.

Round 13 made the queue itself multi-tenant (serving/qos.py): with a
QoS policy installed, the submit FIFO becomes a deficit-round-robin
multi-queue keyed by (tenant, priority class) — quantum scaled by class
weight, near-deadline interactive items jumping the rotation, overload
evicting bulk first — and the resolve path charges every member request
its measured share of the batch wall, the device-time meter that the
admission token buckets debit.  Without a policy (the default) nothing
here changes: plain FIFO, no charging.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from deconv_api_tpu import errors
from deconv_api_tpu.serving import faults
from deconv_api_tpu.serving import trace as trace_mod
from deconv_api_tpu.utils import slog

_log = slog.get_logger("deconv.batcher")


class CircuitBreaker:
    """Consecutive-failure circuit breaker around device dispatch (round 9).

    The device's documented failure modes (wedged tunnel, dying backend)
    make EVERY dispatch fail for a while; without a breaker each doomed
    request still queues, dispatches, and burns its full timeout.  States:

    - CLOSED: normal; ``threshold`` CONSECUTIVE recorded failures open it
      (any success resets the streak).
    - OPEN: ``allow()`` answers False — callers fail fast with 503
      ``breaker_open`` + a Retry-After derived from the remaining
      cooldown — until ``cooldown_s`` elapses.
    - HALF_OPEN: after the cooldown exactly ONE caller is admitted as the
      probe; its success closes the breaker, its failure re-opens (fresh
      cooldown).  Other callers keep failing fast while the probe is in
      flight, so a recovering device sees one batch, not a stampede.

    Shared by all dispatchers that sit on one device (they fail
    together).  Lock-protected: outcomes are recorded from the event
    loop and from worker threads; state transitions publish the
    ``breaker_state`` gauge (0 closed / 1 half-open / 2 open) and a
    ``breaker_open_total`` counter through Metrics, plus slog events.
    ``clock`` is injectable so cooldown tests never sleep."""

    CLOSED, HALF_OPEN, OPEN = 0, 1, 2
    _NAMES = {0: "closed", 1: "half-open", 2: "open"}

    def __init__(
        self,
        threshold: int = 5,
        cooldown_s: float = 5.0,
        *,
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0  # consecutive, while closed
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_at = 0.0
        self._publish()

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    @property
    def state_name(self) -> str:
        return self._NAMES[self.state]

    def accepting(self) -> bool:
        """Would a request arriving now be admitted (or at least be the
        recovery probe)?  This — not raw state — is what /readyz must
        report: state only transitions OPEN→HALF_OPEN inside allow(),
        so a load balancer that pulls traffic on 'open' would starve the
        breaker of the very probe that closes it.  Reporting ready once
        the cooldown has elapsed lets one routed request run the probe."""
        with self._lock:
            if self._state != self.OPEN:
                return True
            return self._clock() >= self._opened_at + self.cooldown_s

    def admit_hint(self) -> tuple[bool, float]:
        """(would a request arriving now be admitted?, retry-after when
        not) — WITHOUT claiming the half-open probe.  The lane pool asks
        this at submit time (fail fast only when every lane is open and
        cooling); the probe itself is claimed by ``allow()`` at dispatch
        time, on the lane the scheduler actually picked."""
        with self._lock:
            if self._state != self.OPEN:
                return True, 0.0
            remaining = self._opened_at + self.cooldown_s - self._clock()
            return remaining <= 0, max(remaining, 1.0)

    def allow(self) -> tuple[bool, float]:
        """(admit this request?, retry-after seconds when not)."""
        with self._lock:
            if self._state == self.CLOSED:
                return True, 0.0
            remaining = self._opened_at + self.cooldown_s - self._clock()
            if self._state == self.OPEN and remaining <= 0:
                # cooldown over: half-open, admit exactly one probe
                self._state = self.HALF_OPEN
                self._probe_inflight = True
                self._probe_at = self._clock()
                self._transition("breaker_half_open")
                return True, 0.0
            if self._state == self.HALF_OPEN and (
                not self._probe_inflight
                # a probe that never reported back (shed, reaped, or
                # lost before dispatch) must not wedge the breaker
                # half-open forever; its claim expires after a cooldown
                or self._clock() - self._probe_at >= self.cooldown_s
            ):
                self._probe_inflight = True
                self._probe_at = self._clock()
                return True, 0.0
            return False, max(remaining, 1.0)

    def record_success(self) -> None:
        with self._lock:
            if self._state == self.OPEN:
                # a straggler dispatched BEFORE the open; the open
                # window holds until the cooldown + probe decide, so a
                # lucky straggler can never flap the breaker shut
                return
            self._failures = 0
            self._probe_inflight = False
            if self._state == self.HALF_OPEN:
                self._state = self.CLOSED
                self._transition("breaker_close")

    def record_failure(self) -> None:
        with self._lock:
            self._probe_inflight = False
            if self._state == self.HALF_OPEN:
                # failed probe: straight back to open, fresh cooldown
                self._state = self.OPEN
                self._opened_at = self._clock()
                self._transition("breaker_reopen")
                return
            if self._state == self.OPEN:
                return  # in-flight stragglers from before the open
            self._failures += 1
            if self._failures >= self.threshold:
                self._state = self.OPEN
                self._opened_at = self._clock()
                self._transition("breaker_open")

    def _transition(self, event: str) -> None:
        # called under the lock; logging/gauge publication are cheap
        slog.event(
            _log, event, level=logging.WARNING,
            state=self._NAMES[self._state], failures=self._failures,
            cooldown_s=self.cooldown_s,
        )
        if self._metrics is not None and self._state == self.OPEN:
            self._metrics.inc_counter("breaker_open_total")
        self._publish()

    def _publish(self) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge("breaker_state", self._state)


# EWMA smoothing for a lane's observed batch cost, and the seed cost a
# lane with no history pretends to have: with no observation every idle
# lane ties at load 0 and the pick's least-pick tiebreak round-robins,
# which is exactly what warms every lane.
_EWMA_ALPHA = 0.2
_EWMA_SEED_S = 1e-3


class ExecutorLane:
    """One executor lane's shared state: the load signal (in-flight depth
    + EWMA batch cost) and the lane's own circuit breaker.

    The lane is SHARED by every dispatcher that can schedule onto its
    chip (deconv/dream/sweep sit on the same devices, so their load and
    failures are correlated per chip); the per-dispatcher pieces — the
    lane's dispatch worker thread and fetch-permit budget — live on the
    dispatcher.  Lock-protected: outcomes are recorded from the event
    loop and from fetch completions racing on it."""

    def __init__(self, index: int, breaker: CircuitBreaker | None = None):
        self.index = index
        self.breaker = breaker
        self._lock = threading.Lock()
        self.inflight = 0  # dispatched-but-unfinished groups on this chip
        self.ewma_s = 0.0  # smoothed dispatch->done wall per batch
        self.batches = 0  # executed batches (the occupancy ledger)
        self.picks = 0  # scheduler picks (ties round-robin on this)

    def load(self) -> float:
        """Estimated pending seconds on this lane — the least-loaded
        scheduling signal: queued depth times what a batch has been
        costing here lately."""
        with self._lock:
            return self.inflight * (self.ewma_s or _EWMA_SEED_S)

    def note_dispatched(self) -> None:
        with self._lock:
            self.inflight += 1
            self.picks += 1

    def note_done(self, wall_s: float, ok: bool = True) -> None:
        with self._lock:
            self.inflight = max(0, self.inflight - 1)
            self.batches += 1
            if not ok:
                # a failure's wall says nothing about the lane's true
                # batch cost — fast-failing dispatches would collapse
                # the EWMA and make the SICK lane look cheapest, so the
                # scheduler would chase it (its breaker only saves the
                # pool once failures are consecutive)
                return
            self.ewma_s = (
                wall_s
                if self.ewma_s == 0.0
                else (1 - _EWMA_ALPHA) * self.ewma_s + _EWMA_ALPHA * wall_s
            )

    def note_cancelled(self) -> None:
        with self._lock:
            self.inflight = max(0, self.inflight - 1)


class LanePool:
    """The set of executor lanes one service schedules over, shared by
    all of its dispatchers.  Owns the least-loaded pick, the pool-level
    admission answer (fail fast only when EVERY lane is open and
    cooling), and the per-lane metrics: ``lane_inflight{lane=}`` /
    ``lane_breaker_state{lane=}`` gauges, ``lane_batches_total{lane=}``
    counters, and a ``lane_imbalance`` gauge (max/mean of per-lane
    executed batches — 1.0 is a perfectly balanced pool).

    A single-lane pool is the exact pre-lane serving path: one stream,
    one (optional) breaker, no placement decisions."""

    def __init__(
        self,
        n: int = 1,
        *,
        breaker_factory: Callable[[], CircuitBreaker | None] | None = None,
        breakers: list[CircuitBreaker | None] | None = None,
        metrics=None,
    ):
        if breakers is None:
            breakers = [
                breaker_factory() if breaker_factory is not None else None
                for _ in range(n)
            ]
        if len(breakers) != n:
            raise ValueError(f"{n} lanes need {n} breakers, got {len(breakers)}")
        self.lanes = [ExecutorLane(i, breakers[i]) for i in range(n)]
        self._metrics = metrics
        self._lock = threading.Lock()
        for lane in self.lanes:
            self._publish_lane(lane)
        self._publish_pool()

    @property
    def size(self) -> int:
        return len(self.lanes)

    def admit(self) -> tuple[bool, float]:
        """Pool-level fail-fast answer for submit(): admit while ANY lane
        would take the request (or run its recovery probe); when every
        lane is open and cooling, reject with the soonest lane's
        retry-after — the pool is only as dead as its healthiest lane."""
        retry = 0.0
        for lane in self.lanes:
            if lane.breaker is None:
                return True, 0.0
            ok, lane_retry = lane.breaker.admit_hint()
            if ok:
                return True, 0.0
            retry = lane_retry if retry == 0.0 else min(retry, lane_retry)
        return False, max(retry, 1.0)

    def pick(self) -> tuple[ExecutorLane | None, float]:
        """Least-loaded lane whose breaker admits the dispatch (claiming
        the half-open probe when that is what admission means).  Ties
        break on fewest picks — an idle pool round-robins, which warms
        every lane — then index.  (None, retry_after) when no lane
        admits: the group fails fast instead of burning its timeout."""
        order = sorted(
            self.lanes, key=lambda l: (l.load(), l.inflight, l.picks, l.index)
        )
        retry = 0.0
        for lane in order:
            if lane.breaker is None:
                return lane, 0.0
            ok, lane_retry = lane.breaker.allow()
            if ok:
                # allow() may have claimed the half-open probe
                # (OPEN -> HALF_OPEN); refresh the lane's state gauge
                self._publish_lane(lane)
                return lane, 0.0
            retry = lane_retry if retry == 0.0 else min(retry, lane_retry)
        return None, max(retry, 1.0)

    def record_dispatched(self, lane: ExecutorLane) -> None:
        lane.note_dispatched()
        self._publish_lane(lane)

    def record_done(
        self, lane: ExecutorLane, ok: bool, wall_s: float, n: int = 0
    ) -> None:
        """One executed group's outcome: lane load signal, lane breaker,
        and the per-lane metric series (``n`` = member requests, for the
        lane-occupancy ledger the loopback row reports)."""
        lane.note_done(wall_s, ok)
        if lane.breaker is not None:
            pre = lane.breaker.state
            if ok:
                lane.breaker.record_success()
            else:
                lane.breaker.record_failure()
            # count EVERY open transition — including a failed probe's
            # HALF_OPEN -> OPEN reopen, which a sampled edge detector
            # would miss because allow() went half-open in between
            if (
                self._metrics is not None
                and pre != CircuitBreaker.OPEN
                and lane.breaker.state == CircuitBreaker.OPEN
            ):
                self._metrics.inc_counter("breaker_open_total")
        if self._metrics is not None:
            self._metrics.inc_labeled(
                "lane_batches_total", "lane", str(lane.index)
            )
            if n:
                self._metrics.inc_labeled(
                    "lane_requests_total", "lane", str(lane.index), n
                )
        self._publish_lane(lane)
        self._publish_pool()

    def record_cancelled(self, lane: ExecutorLane) -> None:
        """A dispatched group whose outcome is unknowable (shutdown
        cancelled the await): release the lane's load signal without
        recording a breaker outcome — a drain is not a device failure."""
        lane.note_cancelled()
        self._publish_lane(lane)

    def accepting_count(self) -> int:
        return sum(
            1
            for lane in self.lanes
            if lane.breaker is None or lane.breaker.accepting()
        )

    def accepting(self) -> bool:
        """Would the pool admit a request arriving now? (the /readyz
        gate: degraded-but-serving is READY; only a pool with every
        lane open-and-cooling should be pulled from rotation)."""
        return self.accepting_count() > 0

    def state_name(self) -> str:
        """Aggregate breaker state for /v1/config: a single lane reports
        its breaker verbatim (the pre-lane contract); a pool reports
        closed / degraded (some lanes open) / open (none accepting)."""
        if not any(lane.breaker is not None for lane in self.lanes):
            return "closed"
        if self.size == 1:
            return self.lanes[0].breaker.state_name
        states = [
            lane.breaker.state for lane in self.lanes if lane.breaker is not None
        ]
        if all(s == CircuitBreaker.CLOSED for s in states):
            return "closed"
        return "degraded" if self.accepting() else "open"

    def snapshot(self) -> dict:
        """Per-lane occupancy for /v1/config and the loopback row."""
        return {
            "lanes": self.size,
            "accepting": self.accepting_count(),
            "per_lane": [
                {
                    "lane": lane.index,
                    "inflight": lane.inflight,
                    "batches": lane.batches,
                    "ewma_ms": round(lane.ewma_s * 1e3, 3),
                    "breaker": (
                        lane.breaker.state_name
                        if lane.breaker is not None
                        else "none"
                    ),
                }
                for lane in self.lanes
            ],
        }

    def _publish_lane(self, lane: ExecutorLane) -> None:
        if self._metrics is None:
            return
        self._metrics.set_labeled_gauge(
            "lane_inflight", "lane", str(lane.index), lane.inflight
        )
        if lane.breaker is not None:
            self._metrics.set_labeled_gauge(
                "lane_breaker_state", "lane", str(lane.index),
                lane.breaker.state,
            )

    def _publish_pool(self) -> None:
        if self._metrics is None:
            return
        with self._lock:
            counts = [lane.batches for lane in self.lanes]
            total = sum(counts)
            imbalance = (
                max(counts) * len(counts) / total if total > 0 else 1.0
            )
            self._metrics.set_gauge("lane_imbalance", round(imbalance, 4))
            self._metrics.set_gauge("lanes_accepting", self.accepting_count())
            # pool-aggregate breaker surface: the worst lane's state
            # (open transitions are counted in record_done, where they
            # happen — the pre-lane breaker_state/breaker_open_total
            # series live on)
            worst = 0
            for lane in self.lanes:
                if lane.breaker is not None:
                    worst = max(worst, lane.breaker.state)
            self._metrics.set_gauge("breaker_state", worst)


def _accepts_lane(fn) -> bool:
    """Does a runner take the scheduler's ``lane`` keyword?  Probed once
    at dispatcher construction so legacy 2-arg runners (tests, embedders)
    keep working unchanged on a single-lane pool."""
    if fn is None:
        return False
    try:
        return "lane" in inspect.signature(fn).parameters
    except (TypeError, ValueError):  # C callables etc.
        return False


def _to_daemon_thread(fn: Callable[[], Any]) -> asyncio.Future:
    """Run ``fn`` on a fresh DAEMON thread, resolving an asyncio future.

    asyncio.to_thread uses the default executor, whose threads are
    non-daemon and joined at interpreter exit — a device_get wedged in one
    (the documented hang-not-raise backend failure mode) blocks process
    exit forever even after the awaiting task is cancelled.  A daemon
    thread lets the interpreter exit once the event loop is done with it.
    Thread-per-call is fine at batch granularity (~100ms+ each)."""
    loop = asyncio.get_running_loop()
    fut: asyncio.Future = loop.create_future()

    def _resolve(setter, value):
        if not fut.cancelled():
            setter(value)

    def work():
        try:
            result = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to the future
            loop.call_soon_threadsafe(_resolve, fut.set_exception, e)
        else:
            loop.call_soon_threadsafe(_resolve, fut.set_result, result)

    threading.Thread(target=work, daemon=True, name="batch-worker").start()
    return fut


@dataclass
class WorkItem:
    image: Any  # (H, W, C) np/jnp array, preprocessed
    key: Any  # groupable static config, e.g. (layer_name, mode)
    # the submitting request's trace (round 8), captured at submit time:
    # the dispatcher stamps queue-wait/dispatch/fetch spans and the
    # executed batch's id onto it from _resolve
    trace: Any = None
    # absolute perf_counter deadline (round 9): expired items are reaped
    # at the queue-pop and pre-dispatch boundaries — never dispatched
    deadline: float | None = None
    # tenancy (round 13, serving/qos.py): the DRR queue keys on
    # (tenant, tclass), the resolve path charges the tenant its measured
    # share of the batch wall.  Empty = the default tenant/class (every
    # pre-QoS caller, and the whole qos-off path).
    tenant: str = ""
    tclass: str = ""
    future: asyncio.Future = field(default_factory=asyncio.Future)
    enqueued_at: float = field(default_factory=time.perf_counter)


def pad_bucket(n: int, max_batch: int) -> int:
    """Smallest power-of-two >= n, capped at max_batch — bounds the set of
    batch shapes XLA ever compiles."""
    b = 1
    while b < n and b < max_batch:
        b *= 2
    return min(b, max_batch)


class BatchingDispatcher:
    """Owns the device; callers `await submit(...)`.

    `runner(key, images) -> list[result]` executes one compiled batch; it is
    called in a worker thread, never on the event loop.
    """

    def __init__(
        self,
        runner: Callable[[Any, list[Any]], list[Any]],
        *,
        max_batch: int = 8,
        window_ms: float = 3.0,
        request_timeout_s: float = 60.0,
        metrics=None,
        shed_factor: float = 1.0,
        dispatch_runner: Callable[[Any, list[Any]], Callable[[], list[Any]]]
        | None = None,
        pipeline_depth: int = 2,
        breaker: CircuitBreaker | None = None,
        lane_pool: LanePool | None = None,
        qos=None,
    ):
        self._runner = runner
        # Multi-tenant QoS (round 13, serving/qos.py): with a policy
        # installed the single FIFO becomes a deficit-round-robin
        # multi-queue keyed by (tenant, class) — a backlogged tenant's
        # items wait in ITS queue while every other queue keeps its
        # weighted share of each drain window — and the resolve path
        # charges each tenant its measured share of the batch wall (the
        # device-time meter the admission buckets debit against).
        # qos=None keeps the exact pre-QoS FIFO path.
        self._qos = qos
        # Executor lanes (round 10): the service passes ONE pool shared
        # by all its dispatchers (their load and failures are correlated
        # per chip); a bare ``breaker=`` builds the exact pre-lane
        # single-stream pool around it.  Admission is gated pool-wide in
        # submit() (fail fast only when every lane is open and cooling);
        # the per-lane breaker claim and outcome recording happen at
        # dispatch, on the lane the scheduler picked.
        self._pool = (
            lane_pool
            if lane_pool is not None
            else LanePool(1, breakers=[breaker])
        )
        self._max_batch = max_batch
        self._window_s = window_ms / 1e3
        self._timeout_s = request_timeout_s
        # plain FIFO, or the QoS policy's DRR multi-queue — both expose
        # the same put/get/get_nowait/qsize/empty slice
        self._queue = qos.new_queue() if qos is not None else asyncio.Queue()
        self._task: asyncio.Task | None = None
        self._metrics = metrics
        self._shed_factor = shed_factor
        self._inflight = 0  # dispatched-or-pending groups not yet resolved
        # Pipelined mode (round 3): `dispatch_runner(key, images)` enqueues
        # the device program WITHOUT blocking and returns a thunk that
        # materialises results; the thunk runs in a separate fetch task so
        # the dispatcher can collect and dispatch the NEXT batch while this
        # one's results stream back to the host (the device executes
        # in-order regardless).  `pipeline_depth` bounds dispatched-but-
        # unfetched batches — the device-side working set — via a
        # semaphore; depth<=1 or dispatch_runner=None restores the fully
        # serial dispatch->fetch->resolve loop.
        self._dispatch_runner = dispatch_runner if pipeline_depth > 1 else None
        # pipeline_depth is PER LANE (round 10): each lane may hold that
        # many dispatched-but-unfetched batches, so a deep pipeline on
        # one chip never starves the others of dispatches.
        self._fetch_sems = [
            asyncio.Semaphore(max(1, pipeline_depth))
            for _ in range(self._pool.size)
        ]
        self._fetch_tasks: set[asyncio.Task] = set()
        self._last_done: float | None = None  # cadence observation anchor
        self._stopping = False
        # Three-stage handoff (round 6): collected batches queue here for
        # the dispatch-stage task.  The bound is the pipeline's
        # backpressure — when the device is behind, put() blocks the
        # collect loop, the submit queue grows, and the shed estimator
        # sees the depth.
        self._dispatch_q: asyncio.Queue[list[WorkItem]] = asyncio.Queue(
            maxsize=max(1, pipeline_depth) * self._pool.size
        )
        self._dispatch_task: asyncio.Task | None = None
        self._staged = 0  # items handed to the dispatch stage, not yet dispatched
        # One PERSISTENT dispatch worker thread PER LANE (vs a fresh
        # daemon thread per batch): device dispatch is a short async
        # enqueue, so thread spawn + first-schedule latency dominated it.
        # Per-dispatcher AND per-lane, so one stream's first-use compile
        # (an unwarmed sweep program, or a cold lane's first executable)
        # can never stall another lane's dispatches.  Fetches keep
        # thread-per-call — a wedged device_get must only ever wedge its
        # own thread.
        self._dispatch_workers: list | None = None

    async def start(self) -> None:
        if self._task is None:
            self._stopping = False  # allow a stop() -> start() restart cycle
            self._task = asyncio.create_task(
                self._supervised("collect", self._run), name="batch-dispatcher"
            )
            if self._dispatch_runner is not None:
                if self._dispatch_workers is None:
                    from deconv_api_tpu.serving.codec_pool import WorkerPool

                    # all lanes share the "dispatch" fault-site name, so
                    # dispatch.worker_raise/_hang drills hit whichever
                    # lane the scheduler picks
                    self._dispatch_workers = [
                        WorkerPool(1, name="dispatch")
                        for _ in range(self._pool.size)
                    ]
                self._dispatch_task = asyncio.create_task(
                    self._supervised("dispatch", self._dispatch_stage),
                    name="batch-dispatch-stage",
                )

    async def _supervised(self, name: str, body: Callable) -> None:
        """Self-healing supervision (round 9): a pipeline task that dies
        from an unexpected exception is logged, counted, and RESTARTED
        with exponential backoff — before this, a crashed collect or
        dispatch task silently wedged the pipeline until every queued
        request burned its full timeout.  The crashing iteration has
        already failed its in-flight futures (see the per-iteration
        guards in _run/_dispatch_stage), so the restart never strands a
        caller.  Cancellation (stop()) passes through untouched."""
        backoff = 0.05
        while True:
            try:
                await body()
                return
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — supervised restart
                slog.event(
                    _log, "task_crash", level=logging.ERROR,
                    task=name, error=f"{type(e).__name__}: {e}",
                    backoff_s=backoff,
                )
                if self._metrics is not None:
                    self._metrics.inc_labeled(
                        "task_restarts_total", "task", name
                    )
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 5.0)

    def tasks_alive(self) -> bool:
        """Both pipeline tasks running (the /readyz batcher check).  The
        supervisor restarts crashed tasks, so False means either not
        started or cancelled — a server that should not receive traffic."""
        if self._task is None or self._task.done():
            return False
        if self._dispatch_runner is not None:
            return self._dispatch_task is not None and not self._dispatch_task.done()
        return True

    async def stop(self, grace_s: float = 10.0) -> None:
        # Reject new submits immediately: a request racing stop() could
        # otherwise enqueue after the drain loop below and sit in a
        # dispatcherless queue until its full request-timeout 504.
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._dispatch_task is not None:
            # Cancel the dispatch stage AFTER the collect loop so nothing
            # new enters the handoff queue; _execute_pipelined's own
            # cancellation handling fails the in-flight group's futures.
            self._dispatch_task.cancel()
            try:
                await self._dispatch_task
            except asyncio.CancelledError:
                pass
            self._dispatch_task = None
        if self._dispatch_workers is not None:
            for w in self._dispatch_workers:
                w.close()
            self._dispatch_workers = None  # start() builds fresh ones
        # Batches still staged in the handoff queue were never dispatched:
        # fail them now or they hang to a full request-timeout 504.
        while not self._dispatch_q.empty():
            for item in self._dispatch_q.get_nowait():
                self._staged -= 1
                if not item.future.done():
                    item.future.set_exception(
                        errors.Unavailable("server shutting down")
                    )
        if self._fetch_tasks:
            # Bounded drain: a wedged remote device_get HANGS rather than
            # raises (documented backend failure mode), and an unbounded
            # gather here would stall graceful shutdown indefinitely —
            # leaving only the second-signal os._exit escape.  On timeout,
            # cancel the stragglers; _finish fails their futures.
            done, pending = await asyncio.wait(
                tuple(self._fetch_tasks), timeout=grace_s
            )
            if pending:
                _log.warning(
                    "%d in-flight fetch task(s) exceeded the %.0fs shutdown "
                    "grace; cancelling", len(pending), grace_s,
                )
                for t in pending:
                    t.cancel()
                await asyncio.gather(*pending, return_exceptions=True)
        # Items still queued (never picked up by a drain window) fail fast
        # with the same shutdown signal as the interrupted window — without
        # this they would hang to a full request-timeout 504.
        while not self._queue.empty():
            item = self._queue.get_nowait()
            if not item.future.done():
                item.future.set_exception(
                    errors.Unavailable("server shutting down")
                )

    def queued_by_class(self) -> dict[str, int]:
        """Queued items per priority class (round 13 operator surface;
        empty for the FIFO path — there are no classes to split by)."""
        depths = getattr(self._queue, "depths", None)
        return depths() if depths is not None else {}

    def _estimated_drain_s(self) -> float:
        """Time for the work ahead of a new arrival to clear.  0.0 while
        unmeasured (cold start) AND whenever the queue is empty: an
        empty-queue arrival rides the very next batch, and always accepting
        it guarantees liveness — if everything shed, no batch would ever
        run and the p50 estimate could never correct itself.

        Rate source: the batch-completion CADENCE median when observed
        (interval between consecutive completions while more work was in
        flight — the true sustained rate, which under pipelining is
        shorter than any single batch's dispatch->fetch wall), falling
        back to compute_p50 before any sustained load has been seen."""
        if self._metrics is None:
            return 0.0
        # staged items (collected, waiting in the dispatch handoff queue)
        # are work ahead of a new arrival exactly like queued ones
        depth = self._queue.qsize() + self._staged
        if depth == 0:
            return 0.0
        p50 = self._metrics.cadence_p50()
        if p50 <= 0.0:
            p50 = self._metrics.compute_p50()
        if p50 <= 0.0:
            return 0.0
        # Divide by the OBSERVED executed-batch size, not max_batch: mixed
        # keys split a drain window into per-key executions, so the
        # effective batch size can be far below max_batch.  _inflight
        # counts dispatched-or-executing groups the queue no longer shows.
        eff_batch = min(
            float(self._max_batch), max(1.0, self._metrics.batch_size_p50())
        )
        return (depth / eff_batch + self._inflight) * p50

    async def submit(
        self,
        image: Any,
        key: Any,
        deadline: float | None = None,
        tenant: str = "",
        tclass: str = "",
    ) -> Any:
        if self._stopping:
            raise errors.Unavailable("server shutting down")
        tr = trace_mod.current_trace()
        allowed, retry_s = self._pool.admit()
        if not allowed:
            # fail fast: every lane's breaker is open and cooling, so
            # every dispatch is overwhelmingly likely to fail — queueing
            # this request would only burn its timeout against dead
            # devices.  One sick lane never trips this: admit() answers
            # yes while any lane would serve (degraded, not dead).
            if tr is not None:
                tr.annotate(breaker="open")
            raise errors.BreakerOpen(
                "device circuit breaker is open on every lane; failing fast",
                retry_after_s=retry_s,
            )
        now = time.perf_counter()
        if deadline is not None:
            # the caller's x-deadline-ms budget, capped by the server's
            # own request timeout (a deadline cannot EXTEND the wait)
            deadline = min(deadline, now + self._timeout_s)
            if now >= deadline:
                self._count_deadline(tr, now, 0.0)
                raise errors.DeadlineExpired(
                    "deadline expired before the request could be queued"
                )
        # Load shedding (VERDICT r2): when the queue already needs longer
        # than the request timeout to drain, every excess request is a
        # guaranteed 504 after a full timeout's wait — reject it NOW with a
        # 503 so callers can back off / retry elsewhere.  The drain
        # estimate rides on the error so the route's 503 carries a
        # Retry-After derived from the queue's actual state.
        if self._shed_factor > 0:
            drain_s = self._estimated_drain_s()
            if drain_s > self._timeout_s * self._shed_factor:
                # Class-ordered shed (round 13): a non-bulk arrival on a
                # QoS queue EVICTS the newest queued bulk item instead
                # of being rejected — overload costs the bulk tier
                # first, and the eviction is charged to the evicted
                # item's tenant (the shed split the noisy-neighbor
                # drill pins).
                evicted = None
                if self._qos is not None and tclass != "bulk":
                    evicted = self._queue.evict_bulk()
                if evicted is not None:
                    self._qos.record_shed(evicted.tenant)
                    if evicted.trace is not None:
                        evicted.trace.add_span(
                            "queue_wait", evicted.enqueued_at,
                            time.perf_counter() - evicted.enqueued_at,
                            shed=True, evicted_for_class=tclass,
                            drain_estimate_s=round(drain_s, 3),
                        )
                    if not evicted.future.done():
                        evicted.future.set_exception(
                            errors.Overloaded(
                                "bulk request evicted under overload for a "
                                "higher-class arrival",
                                retry_after_s=drain_s,
                            )
                        )
                    # the arrival takes the evicted slot: fall through
                else:
                    if self._qos is not None:
                        self._qos.record_shed(tenant)
                    if tr is not None:
                        # a shed request never enqueues: its queue-wait
                        # span is zero-length but carries the drain
                        # estimate that shed it, so the error trace
                        # explains the 503
                        tr.add_span(
                            "queue_wait", time.perf_counter(), 0.0,
                            shed=True, drain_estimate_s=round(drain_s, 3),
                        )
                    # (route handlers record the error code; no
                    # double-count)
                    raise errors.Overloaded(
                        f"queue drain estimate {drain_s:.1f}s exceeds "
                        f"{self._timeout_s:.0f}s request timeout; shedding",
                        retry_after_s=drain_s,
                    )
        item = WorkItem(
            image=image, key=key, trace=tr, deadline=deadline,
            tenant=tenant, tclass=tclass,
        )
        await self._queue.put(item)
        wait_s = self._timeout_s
        if deadline is not None:
            wait_s = min(wait_s, max(deadline - time.perf_counter(), 0.001))
        try:
            return await asyncio.wait_for(item.future, wait_s)
        except asyncio.TimeoutError:
            now = time.perf_counter()
            if deadline is not None and now >= deadline:
                # the reap boundaries usually fail the future first; this
                # covers a deadline that lapses while work is IN FLIGHT
                self._count_deadline(tr, item.enqueued_at, now - item.enqueued_at)
                raise errors.DeadlineExpired(
                    "deadline expired while the request was in flight"
                ) from None
            if tr is not None:
                tr.add_span(
                    "queue_wait", item.enqueued_at,
                    now - item.enqueued_at, timeout=True,
                )
            raise errors.RequestTimeout(
                f"no result within {self._timeout_s:.0f}s (device saturated?)"
            ) from None

    def _count_deadline(self, tr, start_pc: float, waited_s: float) -> None:
        """Shared accounting for every deadline-expiry path: the counter
        the exposition lint pins plus the span attr the runbook names."""
        if self._metrics is not None:
            self._metrics.inc_counter("deadline_expired_total")
        if tr is not None:
            tr.add_span(
                "queue_wait", start_pc, waited_s, deadline_expired=True
            )

    def _reap_expired(self, batch: list[WorkItem]) -> list[WorkItem]:
        """Drop items nobody can receive results for: expired deadlines
        (immediate 504) and already-done futures — the submit side timed
        out, or (round 11) the caller CANCELLED, e.g. a cancelled job's
        in-flight octave.  Either way the device NEVER sees dead work.
        Called at the queue-pop boundary (collect) and again
        pre-dispatch — a deadline can lapse (and a cancel can land)
        while a batch sits in the handoff queue."""
        now = time.perf_counter()
        live: list[WorkItem] = []
        for it in batch:
            if it.future.done():
                # a done future means the submit side already timed out
                # (wait_for cancels it) and COUNTED any expiry, or the
                # caller cancelled — drop the item without
                # double-counting or double-spanning; its result is
                # undeliverable, so dispatching it would only burn
                # device time
                continue
            if it.deadline is not None and now >= it.deadline:
                self._count_deadline(
                    it.trace, it.enqueued_at, now - it.enqueued_at
                )
                it.future.set_exception(
                    errors.DeadlineExpired(
                        "deadline expired while queued; request reaped "
                        "before dispatch"
                    )
                )
            else:
                live.append(it)
        return live

    def _drain_nowait(self, batch: list[WorkItem]) -> None:
        """Move everything already queued into ``batch`` (up to max_batch)
        without touching the event loop.  The old per-item
        ``wait_for(get, ...)`` drain paid one loop-scheduling latency PER
        ITEM — under load that collected ~3 items per window while ~50 sat
        in the queue (round-6 loopback diagnosis), capping every batch far
        below max_batch."""
        while len(batch) < self._max_batch:
            try:
                batch.append(self._queue.get_nowait())
            except asyncio.QueueEmpty:
                break

    async def _run(self) -> None:
        while True:
            first = await self._queue.get()
            batch = [first]
            try:
                self._drain_nowait(batch)
                if self._dispatch_runner is not None:
                    await self._collect_and_stage(batch)
                else:
                    # serial mode: the straggler window waits per item (the
                    # pre-round-6 behaviour; depth<=1 is the compatibility
                    # fallback, not the hot path)
                    if len(batch) < self._max_batch and self._window_s > 0:
                        window_end = time.perf_counter() + self._window_s
                        while len(batch) < self._max_batch:
                            remaining = window_end - time.perf_counter()
                            if remaining <= 0:
                                break
                            try:
                                batch.append(
                                    await asyncio.wait_for(
                                        self._queue.get(), remaining
                                    )
                                )
                            except asyncio.TimeoutError:
                                break
                            self._drain_nowait(batch)
                    batch = self._reap_expired(batch)
                    if batch:
                        await self._execute(batch)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # collect-iteration crash: these items left the submit
                # queue, so nothing downstream can fail them — do it NOW
                # or they hang to a full request-timeout 504, then let
                # the supervisor restart the loop
                exc = (
                    e
                    if isinstance(e, errors.DeconvError)
                    else errors.Unavailable(
                        f"batcher collect task crashed: {type(e).__name__}: {e}"
                    )
                )
                for it in batch:
                    if not it.future.done():
                        it.future.set_exception(exc)
                raise

    async def _collect_and_stage(self, batch: list[WorkItem]) -> None:
        """Pipelined collect: adaptive straggler window + bounded handoff.

        The window is WORK-CONSERVING: when the pipeline is idle the batch
        dispatches immediately (waiting would leave the device idle for
        nothing); when batches are in flight, one sleep() lets stragglers
        accumulate — a single loop hop for the whole window, where the old
        per-item ``wait_for`` drain paid a scheduling latency per item.
        If the device falls further behind, the bounded put blocks the
        collect loop and the next greedy drain picks up everything that
        arrived meanwhile — batch size tracks load automatically."""
        busy = self._inflight > 0 or not self._dispatch_q.empty()
        if (
            busy
            and len(batch) < max(1, self._max_batch // 2)
            and self._window_s > 0
        ):
            # under-filled batch while the device works: one window's
            # sleep lets stragglers accumulate.  A batch already at half
            # of max_batch has amortised the fixed per-dispatch cost —
            # waiting longer would only add latency.
            await asyncio.sleep(self._window_s)
            self._drain_nowait(batch)
        # queue-pop reap boundary (round 9): items whose deadline lapsed
        # while queued 504 NOW instead of riding a doomed dispatch
        batch[:] = self._reap_expired(batch)
        if self._metrics is not None:
            self._metrics.set_gauge("collect_queue_depth", self._queue.qsize())
        if not batch:
            return
        self._staged += len(batch)
        try:
            await self._dispatch_q.put(batch)
        except asyncio.CancelledError:
            # stop() interrupts the handoff: these items left the submit
            # queue, so the stop() drain cannot fail them
            self._staged -= len(batch)
            for item in batch:
                if not item.future.done():
                    item.future.set_exception(
                        errors.Unavailable("server shutting down")
                    )
            raise
        if self._metrics is not None:
            self._metrics.set_gauge(
                "dispatch_queue_depth", self._dispatch_q.qsize()
            )

    async def _dispatch_stage(self) -> None:
        """Stage 2: pull collected batches off the handoff queue and
        dispatch them (in collection order — one stage task, so device
        dispatch order is preserved) while the collect loop keeps
        draining."""
        while True:
            batch = await self._dispatch_q.get()
            self._staged -= len(batch)
            # pre-dispatch reap boundary: a deadline can lapse while the
            # batch waits in the handoff queue behind a slow device
            batch = self._reap_expired(batch)
            if not batch:
                continue
            try:
                faults.raise_if_armed("batcher.dispatch_raise")
                groups: dict[Any, list[WorkItem]] = {}
                for item in batch:
                    groups.setdefault(item.key, []).append(item)
                await self._execute_pipelined(groups)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # dispatch-task crash: fail the in-flight group's futures
                # immediately (they are out of every queue — nobody else
                # can), then re-raise so the supervisor restarts the task
                exc = (
                    e
                    if isinstance(e, errors.DeconvError)
                    else errors.Unavailable(
                        f"batcher dispatch task crashed: {type(e).__name__}: {e}"
                    )
                )
                for item in batch:
                    if not item.future.done():
                        item.future.set_exception(exc)
                raise

    def _call_runner(self, key, images, lane: ExecutorLane):
        """Serial-mode runner invocation, lane keyword only for runners
        that take it (legacy 2-arg runners ride lane 0 unchanged).
        Lane-awareness is probed per call, not cached: tests and
        embedders swap the runner attributes at runtime, and the probe
        is microseconds against a batch's milliseconds."""
        fn = self._runner
        if _accepts_lane(fn):
            return fn(key, images, lane=lane.index)
        return fn(key, images)

    def _call_dispatch(self, key, images, lane: ExecutorLane):
        """Pipelined dispatch invocation; runs on the lane's dispatch
        worker thread.  Same per-call lane probe as _call_runner."""
        fn = self._dispatch_runner
        if _accepts_lane(fn):
            return fn(key, images, lane=lane.index)
        return fn(key, images)

    def _fail_group(self, items: list[WorkItem], exc: BaseException) -> None:
        for it in items:
            if not it.future.done():
                it.future.set_exception(exc)

    async def _execute(self, batch: list[WorkItem]) -> None:
        groups: dict[Any, list[WorkItem]] = {}
        for item in batch:
            groups.setdefault(item.key, []).append(item)
        if self._dispatch_runner is not None:
            await self._execute_pipelined(groups)
            return
        # Serial fallback: dispatch -> block for results -> resolve, one
        # group at a time.  Device execution is serial regardless; what the
        # pipelined mode adds is overlapping the HOST side (result
        # transfer + postprocess) of group A with the device side of
        # group B.  Mixed-key bursts complete without starvation
        # (tests/test_serving.py::test_mixed_layer_burst).
        self._inflight = len(groups)
        pending_groups = list(groups.values())
        try:
            for key, items in groups.items():
                images = [it.image for it in items]
                lane, retry_s = self._pool.pick()
                if lane is None:
                    # the pool's breakers all opened while this batch
                    # sat collected: fail the group fast, like submit()
                    # would have
                    self._inflight -= 1
                    pending_groups = pending_groups[1:]
                    self._fail_group(
                        items,
                        errors.BreakerOpen(
                            "device circuit breaker is open on every lane; "
                            "failing fast",
                            retry_after_s=retry_s,
                        ),
                    )
                    continue
                self._pool.record_dispatched(lane)
                t0 = time.perf_counter()
                try:
                    results = await _to_daemon_thread(
                        lambda key=key, images=images, lane=lane: (
                            self._call_runner(key, images, lane)
                        )
                    )
                except asyncio.CancelledError:
                    # stop() cancelled the dispatcher mid-batch: these items
                    # are already out of the queue, so the stop() drain loop
                    # cannot fail them — do it here or they 504 (r4 review)
                    self._pool.record_cancelled(lane)
                    for grp in pending_groups:
                        for it in grp:
                            if not it.future.done():
                                it.future.set_exception(
                                    errors.Unavailable("server shutting down")
                                )
                    raise
                except Exception as e:  # noqa: BLE001 — propagate to callers
                    self._pool.record_done(
                        lane, False, time.perf_counter() - t0, len(items)
                    )
                    self._fail_group(items, e)
                    continue
                finally:
                    self._inflight -= 1
                    pending_groups = pending_groups[1:]
                self._pool.record_done(
                    lane, True, time.perf_counter() - t0, len(items)
                )
                self._resolve(items, results, t0, lane=lane)
        finally:
            self._inflight = 0  # cancellation mid-drain must not leak count

    async def _execute_pipelined(self, groups: dict[Any, list[WorkItem]]) -> None:
        """Dispatch every group, farming each group's result-fetch out to
        its own task; returns as soon as all groups are DISPATCHED so the
        _run loop can collect the next window while results stream back.
        The fetch semaphore bounds dispatched-but-unfetched groups.

        On cancellation (server shutdown) every group that has not handed
        its thunk to a fetch task FAILS its futures immediately — including
        the group whose dispatch the cancellation interrupted, whose device
        results are unreachable (the cancelled await discards the worker
        thread's eventual result).  Letting them hang to a full
        request-timeout 504 would stall graceful shutdown."""
        self._inflight += len(groups)
        handed_off = 0
        group_list = list(groups.items())
        try:
            for key, items in group_list:
                images = [it.image for it in items]
                # Least-loaded lane selection (round 10): each group goes
                # to the lane with the smallest pending-seconds estimate
                # whose breaker admits it.  With one lane this degenerates
                # to the pre-lane single stream.
                lane, retry_s = self._pool.pick()
                if lane is None:
                    self._inflight -= 1
                    handed_off += 1
                    self._fail_group(
                        items,
                        errors.BreakerOpen(
                            "device circuit breaker is open on every lane; "
                            "failing fast",
                            retry_after_s=retry_s,
                        ),
                    )
                    continue
                # the LANE's fetch permit: a deep pipeline on one chip
                # blocks only further dispatches to that chip
                sem = self._fetch_sems[lane.index]
                await sem.acquire()
                self._pool.record_dispatched(lane)
                t0 = time.perf_counter()
                try:
                    thunk = await self._dispatch_workers[lane.index].run(
                        self._call_dispatch, key, images, lane
                    )
                except asyncio.CancelledError:
                    sem.release()  # held permit must not leak
                    self._pool.record_cancelled(lane)
                    raise
                except Exception as e:  # noqa: BLE001 — propagate to callers
                    sem.release()
                    self._inflight -= 1
                    handed_off += 1
                    self._pool.record_done(
                        lane, False, time.perf_counter() - t0, len(items)
                    )
                    self._fail_group(items, e)
                    continue
                handed_off += 1
                task = asyncio.create_task(
                    self._finish(items, thunk, t0, time.perf_counter(), lane),
                    name="batch-fetch",
                )
                self._fetch_tasks.add(task)
                task.add_done_callback(self._fetch_tasks.discard)
        except asyncio.CancelledError:
            for _, items in group_list[handed_off:]:
                for it in items:
                    if not it.future.done():
                        it.future.set_exception(
                            errors.Unavailable("server shutting down")
                        )
            raise
        finally:
            # groups never handed to a fetch task (failed, cancelled, or
            # unreached) must not leak the inflight count
            self._inflight -= len(group_list) - handed_off

    async def _finish(
        self,
        items: list[WorkItem],
        thunk,
        t0: float,
        dispatched_at: float | None = None,
        lane: ExecutorLane | None = None,
    ) -> None:
        try:
            results = await _to_daemon_thread(thunk)
        except asyncio.CancelledError:
            # stop()'s bounded grace cancels wedged fetches; their results
            # are unreachable (to_thread discards the worker's return on
            # cancel) so the futures must fail NOW, not 504 later
            if lane is not None:
                self._pool.record_cancelled(lane)
            for it in items:
                if not it.future.done():
                    it.future.set_exception(
                        errors.Unavailable("server shutting down")
                    )
            raise
        except Exception as e:  # noqa: BLE001 — propagate to callers
            if lane is not None:
                self._pool.record_done(
                    lane, False, time.perf_counter() - t0, len(items)
                )
            self._fail_group(items, e)
            return
        finally:
            self._inflight -= 1
            if lane is not None:
                self._fetch_sems[lane.index].release()
        if lane is not None:
            self._pool.record_done(
                lane, True, time.perf_counter() - t0, len(items)
            )
        self._resolve(
            items, results, t0, dispatched_at, lane,
            # weight page-in attribution (round 15): a cold-model
            # dispatch tags its materialise thunk with the transfer
            # wall so every member request's trace shows WHY this
            # batch's dispatch span is fat
            page_in_s=getattr(thunk, "page_in_s", None),
            page_model=getattr(thunk, "page_model", None),
        )

    def _resolve(
        self,
        items: list[WorkItem],
        results: list[Any],
        t0: float,
        dispatched_at: float | None = None,
        lane: ExecutorLane | None = None,
        page_in_s: float | None = None,
        page_model: str | None = None,
    ) -> None:
        """Shared epilogue for both execution modes: metrics + futures.
        Cadence (interval between completions while more work is in
        flight) feeds the load-shed estimator's sustained-rate input.
        Round 8: each member request's trace gets its queue-wait and
        dispatch/fetch spans here, stamped with the batch id that
        observe_batch just recorded — the join key between a single
        request's timeline and the batch-level metrics.  Round 10: the
        spans and the batch_done line carry the executing LANE, so a
        slow trace says which chip ran it."""
        now = time.perf_counter()
        lane_ix = lane.index if lane is not None else 0
        if self._qos is not None:
            # Device-time accounting (round 13): each member request is
            # charged its share of the executed batch's wall — the
            # EWMA-measured cost the admission bucket debits, so tenants
            # pay for what their batches COST, not how many requests
            # they sent (an efficient batching tenant pays less per
            # request; a sweep-heavy one pays more).
            per_s = (now - t0) / max(1, len(items))
            for it in items:
                self._qos.charge(it.tenant, per_s)
        slog.event(
            _log, "batch_done", level=10,  # DEBUG: per-request http_request
            # lines already cover the serving story at INFO
            key=str(items[0].key), size=len(items), lane=lane_ix,
            ms=round((now - t0) * 1e3, 1), inflight=self._inflight,
        )
        bid = None
        if self._metrics is not None:
            bid = self._metrics.observe_batch(
                size=len(items),
                compute_s=now - t0,
                queue_s=t0 - min(it.enqueued_at for it in items),
            )
            self._metrics.set_gauge("inflight_batches", self._inflight)
            # Cadence is only meaningful between completions under
            # SUSTAINED load; going idle clears the anchor, else the next
            # burst's first completion would record the whole idle gap as
            # an interval and inflate the shed estimator into spurious
            # 503s (r3 review finding).
            busy = self._inflight > 0 or self._queue.qsize() > 0
            if busy:
                if self._last_done is not None:
                    self._metrics.observe_cadence(now - self._last_done)
                self._last_done = now
            else:
                self._last_done = None
        for it in items:
            if it.trace is not None:
                it.trace.annotate(batch_id=bid, batch_size=len(items), lane=lane_ix)
                it.trace.add_span("queue_wait", it.enqueued_at, t0 - it.enqueued_at)
                if page_in_s:
                    # the cold-model transfer this batch waited on
                    # (round 15): starts at dispatch, rides inside the
                    # dispatch wall the QoS meter charges
                    it.trace.add_span(
                        "weight_page_in", t0, page_in_s,
                        model=page_model, lane=lane_ix,
                    )
                if dispatched_at is not None:
                    it.trace.add_span(
                        "dispatch", t0, dispatched_at - t0, batch_id=bid,
                        lane=lane_ix,
                    )
                    it.trace.add_span(
                        "fetch", dispatched_at, now - dispatched_at,
                        batch_id=bid, lane=lane_ix,
                    )
                else:
                    it.trace.add_span(
                        "device", t0, now - t0, batch_id=bid, lane=lane_ix
                    )
        for it, res in zip(items, results):
            if not it.future.done():
                it.future.set_result(res)
