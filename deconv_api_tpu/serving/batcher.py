"""Async batching dispatcher: coalesce concurrent requests into padded
device batches.

The reference's endpoint is `async def` over seconds of blocking compute, so
its true concurrency is 1 (SURVEY §2.2.5).  Here requests enqueue a future
and a single dispatcher task owns the device: it drains the queue up to
`max_batch` (waiting at most `window_ms` for stragglers), groups by
(layer, mode) — each group is one compiled executable — pads the image batch
to a power-of-two bucket so XLA never sees a new batch shape, runs the
executable in a worker thread (the event loop stays free), and resolves the
futures.  One task owning the device also removes the reference's
shared-graph thread-safety hack (`tb._SYMBOLIC_SCOPE`, app/main.py:54;
SURVEY §5 race-detection row).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable

from deconv_api_tpu import errors


@dataclass
class WorkItem:
    image: Any  # (H, W, C) np/jnp array, preprocessed
    key: Any  # groupable static config, e.g. (layer_name, mode)
    future: asyncio.Future = field(default_factory=asyncio.Future)
    enqueued_at: float = field(default_factory=time.perf_counter)


def pad_bucket(n: int, max_batch: int) -> int:
    """Smallest power-of-two >= n, capped at max_batch — bounds the set of
    batch shapes XLA ever compiles."""
    b = 1
    while b < n and b < max_batch:
        b *= 2
    return min(b, max_batch)


class BatchingDispatcher:
    """Owns the device; callers `await submit(...)`.

    `runner(key, images) -> list[result]` executes one compiled batch; it is
    called in a worker thread, never on the event loop.
    """

    def __init__(
        self,
        runner: Callable[[Any, list[Any]], list[Any]],
        *,
        max_batch: int = 8,
        window_ms: float = 3.0,
        request_timeout_s: float = 60.0,
        metrics=None,
        shed_factor: float = 1.0,
    ):
        self._runner = runner
        self._max_batch = max_batch
        self._window_s = window_ms / 1e3
        self._timeout_s = request_timeout_s
        self._queue: asyncio.Queue[WorkItem] = asyncio.Queue()
        self._task: asyncio.Task | None = None
        self._metrics = metrics
        self._shed_factor = shed_factor
        self._inflight = 0  # executing drain's remaining serial groups

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run(), name="batch-dispatcher")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def _estimated_drain_s(self) -> float:
        """Time for the work ahead of a new arrival to clear, from the
        observed per-batch compute median.  0.0 while unmeasured (cold
        start) AND whenever the queue is empty: an empty-queue arrival
        rides the very next batch, and always accepting it guarantees
        liveness — if everything shed, no batch would ever run and the p50
        estimate could never correct itself."""
        if self._metrics is None:
            return 0.0
        depth = self._queue.qsize()
        if depth == 0:
            return 0.0
        p50 = self._metrics.compute_p50()
        if p50 <= 0.0:
            return 0.0
        # Divide by the OBSERVED executed-batch size, not max_batch: mixed
        # keys split a drain window into per-key serial executions, so the
        # effective batch size can be far below max_batch.  _inflight
        # counts the executing drain's remaining groups (serial device
        # batches the queue no longer shows).
        eff_batch = min(
            float(self._max_batch), max(1.0, self._metrics.batch_size_p50())
        )
        return (depth / eff_batch + self._inflight) * p50

    async def submit(self, image: Any, key: Any) -> Any:
        # Load shedding (VERDICT r2): when the queue already needs longer
        # than the request timeout to drain, every excess request is a
        # guaranteed 504 after a full timeout's wait — reject it NOW with a
        # 503 so callers can back off / retry elsewhere.
        if (
            self._shed_factor > 0
            and self._estimated_drain_s() > self._timeout_s * self._shed_factor
        ):
            # (route handlers record the error code; no double-count here)
            raise errors.Overloaded(
                f"queue drain estimate exceeds {self._timeout_s:.0f}s "
                f"request timeout; shedding"
            )
        item = WorkItem(image=image, key=key)
        await self._queue.put(item)
        try:
            return await asyncio.wait_for(item.future, self._timeout_s)
        except asyncio.TimeoutError:
            raise errors.RequestTimeout(
                f"no result within {self._timeout_s:.0f}s (device saturated?)"
            ) from None

    async def _run(self) -> None:
        while True:
            first = await self._queue.get()
            batch = [first]
            deadline = time.perf_counter() + self._window_s
            while len(batch) < self._max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    batch.append(
                        await asyncio.wait_for(self._queue.get(), remaining)
                    )
                except asyncio.TimeoutError:
                    break
            await self._execute(batch)

    async def _execute(self, batch: list[WorkItem]) -> None:
        groups: dict[Any, list[WorkItem]] = {}
        for item in batch:
            groups.setdefault(item.key, []).append(item)
        # Distinct keys in one drain window run SERIALLY — a deliberate
        # decision (round-1 review asked): one dispatcher task owns the
        # device, and device execution is serial regardless; overlapping
        # group B's dispatch with group A's host postprocess would pipeline
        # at most a few ms of encode time per window at the cost of losing
        # the single-owner invariant that replaces the reference's
        # _SYMBOLIC_SCOPE thread hack.  Mixed-key bursts complete without
        # starvation (tests/test_serving.py::test_mixed_layer_burst).
        self._inflight = len(groups)
        try:
            for key, items in groups.items():
                images = [it.image for it in items]
                t0 = time.perf_counter()
                try:
                    results = await asyncio.to_thread(self._runner, key, images)
                except Exception as e:  # noqa: BLE001 — propagate to callers
                    for it in items:
                        if not it.future.done():
                            it.future.set_exception(e)
                    continue
                finally:
                    self._inflight -= 1
                dt = time.perf_counter() - t0
                if self._metrics is not None:
                    self._metrics.observe_batch(
                        size=len(items),
                        compute_s=dt,
                        queue_s=t0 - min(it.enqueued_at for it in items),
                    )
                for it, res in zip(items, results):
                    if not it.future.done():
                        it.future.set_result(res)
        finally:
            self._inflight = 0  # cancellation mid-drain must not leak count
