"""Model registry for serving: every model family behind one interface.

A ModelBundle packages what the HTTP layer needs: preprocessing, the set of
nameable layers, and a builder for batched jitted visualizers.  Sequential
specs (VGG16) use the bug-compat parity engine (engine/deconv.py); DAG
models (ResNet50, InceptionV3) use the autodiff engine
(engine/autodeconv.py).  The reference hardcodes exactly one model at import
time (app/main.py:17); here `DECONV_MODEL=resnet50` is a config change.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import numpy as np

from deconv_api_tpu.engine import autodeconv_visualizer, get_visualizer
from deconv_api_tpu.serving import codec


@dataclasses.dataclass
class ModelBundle:
    name: str
    params: dict
    image_size: int
    preprocess: Callable[[np.ndarray], np.ndarray]
    layer_names: tuple[str, ...]  # projectable layers
    dream_layers: tuple[str, ...]  # default DeepDream targets
    forward_fn: Callable | None  # DAG-model calling convention
    unpreprocess: Callable[[np.ndarray], np.ndarray] = codec.unpreprocess_vgg
    min_dream_size: int = 16  # smallest octave edge the trunk accepts
    spec: object = None  # ModelSpec, set for sequential models
    mesh: object = None  # jax.sharding.Mesh — set by DeconvService when
    # cfg.mesh_shape is configured; visualizers then run dp-sharded
    # Stored weight precision (round 15, serving/weight_manager.py):
    # 'f32' (exact), 'bf16' (store bf16, cast on use) or 'int8'
    # (per-tensor symmetric kernels, f32 dequant-on-use).  Set by the
    # weight manager in managed mode; every params-consuming program
    # this bundle builds then dequantises INSIDE its jitted trace, so
    # HBM holds the quantized bytes and the f32 view is a temporary.
    weight_dtype: str = "f32"
    _vis_cache: dict = dataclasses.field(default_factory=dict)
    _dream_cache: dict = dataclasses.field(default_factory=dict)
    # stable dequant-wrapped DAG forward (octave programs jit-cache by
    # forward identity — a fresh wrapper per call would recompile)
    _forward_q: Callable | None = None
    # Executor lanes (round 10): one placement (Device, or a small dp
    # Mesh) and one param replica per lane, set once by set_lanes().
    # Empty = single-stream serving with the original params.
    _lane_placements: list = dataclasses.field(default_factory=list)
    _lane_params: list = dataclasses.field(default_factory=list)

    def set_lanes(self, placements: list) -> None:
        """Replicate the params onto every lane ONCE at startup — each
        lane's dispatches then read their chip-local copy, so no
        cross-chip param traffic ever rides the serving hot path.  A
        placement is a single Device (lane == chip) or a Mesh (lane ==
        dp slice; params replicated across the slice)."""
        import jax
        from jax.sharding import Mesh

        from deconv_api_tpu.parallel.mesh import replicated

        self._lane_placements = list(placements)
        self._lane_params = [
            jax.device_put(
                self.params,
                replicated(pl) if isinstance(pl, Mesh) else pl,
            )
            for pl in self._lane_placements
        ]

    def lane_params(self, lane: int = 0):
        """The params replica a lane's dispatch must read (the original
        params when lanes are not configured)."""
        return self._lane_params[lane] if self._lane_params else self.params

    def lane_placement(self, lane: int = 0):
        """Device or Mesh backing one lane; None without lanes."""
        return self._lane_placements[lane] if self._lane_placements else None

    def sweep_layers(self, layer: str) -> tuple[str, ...]:
        """The projectable layers at/below `layer` in forward order,
        deepest first — what an all-layers sweep from `layer` projects.
        Sequential specs read their layer list; DAG models recover the
        forward (topological) order of their named activations from an
        abstract trace (no compute, no device touch).  The DAG analog of
        the reference's reversed model-layer walk
        (app/deepdream.py:431-437)."""
        self.check_layer(layer)
        if self.spec is not None:
            names = [
                l.name for l in self.spec.layers if l.kind != "input"
            ]
        else:
            # Record the acts dict's INSERTION order during tracing —
            # reading keys off eval_shape's return value would be wrong:
            # jax pytree flattening sorts dict keys, which is not forward
            # order for names like mixed10 or conv_pw_13_relu.  Trace with
            # the SAME rules the visualizer's forward runs under
            # (DECONV_RULES): if a family ever exposes rule-dependent
            # activation names, the sweep layer set must match what the
            # visualizer can actually seed (ADVICE r5).
            from deconv_api_tpu.models.blocks import DECONV_RULES

            order: list[str] = []

            def capture(p, x):
                _, acts = self.forward_fn(p, x, rules=DECONV_RULES)
                order.extend(acts)
                return 0.0

            dummy = jax.ShapeDtypeStruct(
                (1, self.image_size, self.image_size, 3), np.float32
            )
            jax.eval_shape(capture, self.params, dummy)
            known = set(self.layer_names)
            missing = [n for n in self.layer_names if n not in set(order)]
            if missing:
                raise ValueError(
                    f"model {self.name!r}: projectable layer(s) {missing} "
                    f"missing from the traced activation order {order} — "
                    "layer_names and the forward's named activations have "
                    "drifted apart"
                )
            names = [n for n in order if n in known]
        return tuple(reversed(names[: names.index(layer) + 1]))

    def reset_mesh(self) -> None:
        """Drop the mesh and EVERY compiled program built against it —
        the pod degrade path (round 25): after follower loss the sharded
        programs' collectives would wedge on a dead peer, so the next
        dispatch must re-resolve a local program from a clean cache."""
        self.mesh = None
        self._vis_cache.clear()

    def check_layer(self, layer: str) -> None:
        """Single source of truth for layer-name validation — surfaced as
        UnknownLayer (422) by the route and as a clean stderr message by
        the CLI."""
        if layer not in self.layer_names:
            raise ValueError(
                f"model {self.name!r} has no projectable layer {layer!r}; "
                f"known: {list(self.layer_names)}"
            )

    def _wrap_weight_dtype(self, fwd):
        """Compose a forward with in-program dequantisation when this
        bundle stores a quantized weight tier (round 15).  Callers must
        CACHE the result: the octave/dream jit caches key on forward
        identity, so a fresh wrapper per request would recompile."""
        if self.weight_dtype == "f32":
            return fwd
        from deconv_api_tpu.serving.weight_manager import dequantize_params

        def fwd_q(params, x, *args, **kwargs):
            return fwd(dequantize_params(params), x, *args, **kwargs)

        return fwd_q

    def dream_forward(self, layers: tuple[str, ...]):
        """A resolution-robust forward for octave dreaming: DAG models
        as-is; sequential specs truncated below their flatten/dense head.
        Cached per layer set so repeated dream requests reuse the same
        closure (and therefore the same jit cache).  When the bundle
        stores a quantized weight tier the cached forward dequantises
        in-program (the wrapper identity is stable per bundle, so the
        octave jit cache holds)."""
        if self.forward_fn is not None:
            if self.weight_dtype == "f32":
                return self.forward_fn
            if self._forward_q is None:
                self._forward_q = self._wrap_weight_dtype(self.forward_fn)
            return self._forward_q
        if layers not in self._dream_cache:
            from deconv_api_tpu.models.apply import spec_forward

            by_name = {l.name: l for l in self.spec.layers}
            for l in layers:
                if l not in by_name:
                    raise KeyError(f"model has no activation {l!r}")
                if by_name[l].kind not in ("conv", "pool"):
                    raise KeyError(
                        f"layer {l!r} ({by_name[l].kind}) is not dreamable: octave "
                        "resizing requires conv/pool layers (dense heads are "
                        "resolution-bound)"
                    )
            names = self.spec.layer_names()
            deepest = max(layers, key=names.index)
            self._dream_cache[layers] = self._wrap_weight_dtype(
                spec_forward(self.spec.truncated(deepest))
            )
        return self._dream_cache[layers]

    def batched_visualizer(
        self,
        layer: str,
        mode: str,
        top_k: int,
        bug_compat: bool = True,
        backward_dtype: str | None = None,
        post: str | None = None,
        sweep: bool = False,
        donate: bool = False,
        lane: int = 0,
        lowc_kpack: str = "off",
        quant=None,
        fused_unpool: str = "off",
    ):
        """fn(params, batch) -> {layer: {..., indices, sums, valid}} —
        jitted once per static configuration and cached.  ``bug_compat``
        only affects sequential models (the DAG autodiff path has no
        double-ReLU quirk to reproduce).  ``backward_dtype`` defaults to
        exact (None); the serving layer passes its configured policy.  The
        DAG autodiff path ignores it (its backward is a vjp over the saved
        fp32 forward residuals, so there is no separate projection chain to
        downcast) — normalised out of the cache key there.

        ``post`` fuses the device-side postprocess INTO the same program:
        ``"grid"`` adds a uint8 ``grid`` (2x2 stitch + deprocess, the
        POST / presentation), ``"tiles"`` a uint8 ``tiles`` (per-filter
        deprocess, the /v1/deconv presentation) — and drops the raw fp32
        ``images`` from the outputs.  One device dispatch per batch instead
        of two, and the full-resolution fp32 projections never round-trip
        HBM between programs (they fuse into the epilogue); only uint8
        crosses to the host.  ``post=None`` keeps the raw projections (the
        library/bench surface).

        ``sweep=True`` projects EVERY projectable layer from ``layer``
        down — the reference's always-on behaviour (SURVEY §2.2.3) as an
        explicit opt-in; the result dict then carries one entry per
        projected layer.  Sequential specs walk their D-layer chain; DAG
        models share one forward across per-layer vjp seeds
        (engine/autodeconv.py).

        ``donate=True`` donates the batch argument's device buffer into
        the program at THIS outer jit boundary (inner-jit donation would
        be ignored once the trace inlines), covering both engine
        families: outputs may reuse the input's memory, so the dispatcher
        must pass freshly staged batches (it does — the input ring,
        serving/codec_pool.py).  Inactive under a mesh
        (shard_batched_fn owns that jit boundary).

        ``lane`` selects the executor lane's program (round 10): the
        cache is keyed per lane so each chip holds its own executable
        pinned to its own param replica — a multi-device-sweeping cache
        key lookup can never route lane 1's batch through lane 0's
        compiled program.  Lanes backed by a Mesh slice run dp-sharded
        over it, exactly like the whole-pool mesh path.

        ``lowc_kpack`` (round 12) is the low-channel backward-tail
        packing policy (config.py; engine/deconv.py:resolve_kpack_chan).
        Sequential specs thread it into the engine as a kpack channel
        threshold; DAG models normalise it to "off" BEFORE the cache key
        (same rule as backward_dtype — their vjp walk has no packed
        layout, so distinct policy values must not compile duplicate
        identical executables).

        ``quant`` (round 18, quality=int8) runs the forward walk with
        int8 arithmetic: ``"dynamic"`` or a tuple of calibrated
        (entry, amax) scales (engine/quant.py) — sequential specs only;
        the serving layer normalises DAG requests down to bf16 before
        this call, and the None default keeps the exact pre-round-18
        program and cache keys.

        ``fused_unpool`` (round 20) is the fused Pallas
        unpool+flipped-conv backward-tail policy (config.py;
        ops/pallas_deconv.py:resolve_fused_unpool).  Sequential specs
        thread it into the engine; DAG models — and any backend the
        resolved mode disengages on (auto off-TPU) — normalise it to
        "off" BEFORE the cache key (the lowc_kpack rule: an inert
        policy value must not compile duplicate identical
        executables)."""
        lane_pl = self.lane_placement(lane)
        lane_mesh = None
        if lane_pl is not None:
            from jax.sharding import Mesh

            if isinstance(lane_pl, Mesh):
                lane_mesh = lane_pl
        mesh = self.mesh if self.mesh is not None else lane_mesh
        from deconv_api_tpu.engine.deconv import resolve_kpack_chan
        from deconv_api_tpu.ops.pallas_deconv import (
            fused_engaged,
            resolve_fused_unpool,
        )

        # Resolve (and thereby validate) the policies for every model
        # family; only sequential specs key their cache on the result.
        kpack_chan = resolve_kpack_chan(lowc_kpack, top_k)
        fused_unpool = resolve_fused_unpool(fused_unpool)
        if not fused_engaged(fused_unpool):
            fused_unpool = "off"
        if self.spec is None:
            backward_dtype = None
            kpack_chan = 0
            quant = None  # DAG walks have no quantized form (normalized
            # to bf16 upstream); None keeps the key from fragmenting
            fused_unpool = "off"  # vjp walk has no pool+conv triple
        if mesh is not None:
            donate = False  # sharded jit boundary; donation not threaded
        if donate:
            from deconv_api_tpu.engine.deconv import allow_unusable_donation

            allow_unusable_donation()
        # lane stays the key's TAIL — test_lanes and the warmup loop read
        # k[-1] as the lane a cached program is pinned to
        key = (layer, mode, top_k, bug_compat, backward_dtype, post, sweep,
               donate, kpack_chan, quant, fused_unpool, lane)
        if key not in self._vis_cache:
            if self.spec is not None:
                # On a dp mesh the merged-sweep batch chunking must stay
                # OFF: its (B,)->(n,chunk) reshape + sequential lax.map
                # would serialize chunks that GSPMD should spread across
                # the dp axis, and the per-device carry is already B/dp so
                # the single-chip OOM it guards against does not apply.
                raw = get_visualizer(
                    self.spec, layer, top_k, mode, bug_compat,
                    sweep=sweep, batched=True,
                    backward_dtype=backward_dtype or None,
                    kpack_chan=kpack_chan,
                    sweep_chunk=0 if mesh is not None else None,
                    quant=quant, fused_unpool=fused_unpool,
                )
            else:
                sweep_names = self.sweep_layers(layer) if sweep else None
                vmapped = jax.vmap(
                    autodeconv_visualizer(
                        self.forward_fn, layer, top_k, mode,
                        sweep_layers=sweep_names,
                    ),
                    in_axes=(None, 0),
                )
                if sweep:
                    raw = vmapped  # already {name: entry} per swept layer
                else:
                    raw = lambda params, batch: {layer: vmapped(params, batch)}  # noqa: E731

            if self.weight_dtype != "f32":
                # quantized weight tier (round 15): the program consumes
                # the STORED tree and dequantises inside its own trace —
                # HBM holds bf16/int8 bytes, the f32 view is a temporary
                from deconv_api_tpu.serving.weight_manager import (
                    dequantize_params,
                )

                inner = raw
                raw = lambda params, batch: inner(  # noqa: E731
                    dequantize_params(params), batch
                )
            fn = raw if post is None else _fuse_post(raw, post)
            if mesh is not None:
                from deconv_api_tpu.parallel.batch import shard_batched_fn

                fn = shard_batched_fn(fn, mesh)
            else:
                fn = jax.jit(fn, donate_argnums=(1,) if donate else ())
            self._vis_cache[key] = fn
        return self._vis_cache[key]


def _fuse_post(raw, post: str):
    """Compose the raw visualizer with the device postprocess under one
    trace (nested jit inlines), replacing fp32 `images` with the uint8
    presentation the route actually serves.  Applies per projected layer
    (one for the default single-layer program, many under sweep)."""
    from deconv_api_tpu.serving.codec import _deprocess_jax, _stitch_grid_traced

    def fused(params, batch):
        result = {}
        for name, entry in raw(params, batch).items():
            out = dict(entry)
            images = out.pop("images")
            if post == "grid":
                out["grid"] = _stitch_grid_traced(images, out["valid"])
            else:
                out["tiles"] = jax.vmap(jax.vmap(_deprocess_jax))(images)
            result[name] = out
        return result

    return fused


def spec_bundle(
    spec,
    params,
    *,
    dream_layers: tuple[str, ...] = (),
    preprocess: Callable[[np.ndarray], np.ndarray] = codec.preprocess_vgg,
) -> ModelBundle:
    """The one place a sequential ModelSpec becomes a ModelBundle (used by
    both the registry and injected-spec servers, so the projectable-layer
    rule cannot drift between them)."""
    return ModelBundle(
        name=spec.name,
        params=params,
        image_size=spec.input_shape[0],
        preprocess=preprocess,
        layer_names=tuple(l.name for l in spec.layers if l.kind != "input"),
        dream_layers=dream_layers,
        forward_fn=None,
        spec=spec,
    )


def _vgg_tiny_bundle() -> ModelBundle:
    """The CI/dry-run backbone (models/tiny.py) as a first-class registry
    member (round 15): multi-model serving needs a backbone that builds
    and compiles in seconds — warm-pool drills, fleet tests, and
    paging-pressure experiments all run against it on CPU hosts.  No
    pretrained weights exist (random init); it is a structural model,
    not a semantic one, and fetch_weights deliberately has no entry."""
    from deconv_api_tpu.models.tiny import vgg_tiny_init

    spec, params = vgg_tiny_init()
    return spec_bundle(
        spec, params, dream_layers=("block2_conv2", "block3_conv1")
    )


def _vgg16_bundle() -> ModelBundle:
    from deconv_api_tpu.models.vgg16 import vgg16_init

    spec, params = vgg16_init()
    return spec_bundle(
        spec, params, dream_layers=("block4_conv3", "block5_conv1")
    )


def _vgg19_bundle() -> ModelBundle:
    from deconv_api_tpu.models.vgg19 import vgg19_init

    spec, params = vgg19_init()
    return spec_bundle(
        spec, params, dream_layers=("block4_conv4", "block5_conv1")
    )


def _resnet50_bundle() -> ModelBundle:
    from deconv_api_tpu.models.resnet50 import (
        DECONV_LAYERS,
        resnet50_forward,
        resnet50_init,
    )

    params = resnet50_init(jax.random.PRNGKey(0))
    return ModelBundle(
        name="resnet50",
        params=params,
        image_size=224,
        preprocess=codec.preprocess_vgg,  # Keras resnet50 uses caffe mode too
        layer_names=DECONV_LAYERS,
        dream_layers=("conv4_block3_out", "conv4_block6_out"),
        forward_fn=resnet50_forward,
    )


def _mobilenet_v1_bundle() -> ModelBundle:
    from deconv_api_tpu.models.mobilenet_v1 import (
        DECONV_LAYERS,
        DREAM_LAYERS,
        mobilenet_v1_forward,
        mobilenet_v1_init,
    )

    params = mobilenet_v1_init(jax.random.PRNGKey(0))
    return ModelBundle(
        name="mobilenet_v1",
        params=params,
        image_size=224,
        preprocess=codec.preprocess_tf,  # Keras mobilenet uses 'tf' mode
        layer_names=DECONV_LAYERS,
        dream_layers=DREAM_LAYERS,
        forward_fn=mobilenet_v1_forward,
        unpreprocess=codec.unpreprocess_tf,
        min_dream_size=32,  # five (0,1)-padded stride-2 convs
    )


def _mobilenet_v2_bundle() -> ModelBundle:
    from deconv_api_tpu.models.mobilenet_v2 import (
        DECONV_LAYERS,
        DREAM_LAYERS,
        mobilenet_v2_forward,
        mobilenet_v2_init,
    )

    params = mobilenet_v2_init(jax.random.PRNGKey(0))
    return ModelBundle(
        name="mobilenet_v2",
        params=params,
        image_size=224,
        preprocess=codec.preprocess_tf,  # Keras mobilenet_v2 uses 'tf' mode
        layer_names=DECONV_LAYERS,
        dream_layers=DREAM_LAYERS,
        forward_fn=mobilenet_v2_forward,
        unpreprocess=codec.unpreprocess_tf,
        min_dream_size=32,
    )


def _inception_v3_bundle() -> ModelBundle:
    from deconv_api_tpu.models.inception_v3 import (
        DREAM_LAYERS,
        inception_v3_forward,
        inception_v3_init,
    )

    params = inception_v3_init(jax.random.PRNGKey(0))
    return ModelBundle(
        name="inception_v3",
        params=params,
        image_size=299,
        preprocess=codec.preprocess_tf,  # Keras inception uses 'tf' mode
        layer_names=tuple(f"mixed{i}" for i in range(11)),
        dream_layers=DREAM_LAYERS,
        forward_fn=inception_v3_forward,
        unpreprocess=codec.unpreprocess_tf,
        min_dream_size=75,  # the VALID-padded stem needs >= 75 px
    )


REGISTRY: dict[str, Callable[[], ModelBundle]] = {
    "vgg16": _vgg16_bundle,
    "vgg19": _vgg19_bundle,
    "resnet50": _resnet50_bundle,
    "inception_v3": _inception_v3_bundle,
    "mobilenet_v1": _mobilenet_v1_bundle,
    "mobilenet_v2": _mobilenet_v2_bundle,
    "vgg_tiny": _vgg_tiny_bundle,
}


def registry_info() -> list[dict]:
    """Static metadata for each registered model — no weight init, no
    device touch (the CLI's `models` listing must work instantly)."""
    from deconv_api_tpu.models import mobilenet_v1 as mb
    from deconv_api_tpu.models import mobilenet_v2 as mb2
    from deconv_api_tpu.models.inception_v3 import DREAM_LAYERS
    from deconv_api_tpu.models.resnet50 import DECONV_LAYERS
    from deconv_api_tpu.models.tiny import VGG_TINY_SPEC as spec_tiny
    from deconv_api_tpu.models.vgg16 import VGG16_SPEC as spec
    from deconv_api_tpu.models.vgg19 import VGG19_SPEC as spec19
    return [
        {
            "model": "vgg16",
            "image_size": 224,
            "engine": "switch-deconv (sequential spec)",
            "layers": [l.name for l in spec.layers if l.kind != "input"],
            "dream_layers": ["block4_conv3", "block5_conv1"],
        },
        {
            "model": "vgg19",
            "image_size": 224,
            "engine": "switch-deconv (sequential spec)",
            "layers": [l.name for l in spec19.layers if l.kind != "input"],
            "dream_layers": ["block4_conv4", "block5_conv1"],
        },
        {
            "model": "resnet50",
            "image_size": 224,
            "engine": "autodiff-deconv (DAG)",
            "layers": list(DECONV_LAYERS),
            "dream_layers": ["conv4_block3_out", "conv4_block6_out"],
        },
        {
            "model": "inception_v3",
            "image_size": 299,
            "engine": "autodiff-deconv (DAG)",
            "layers": [f"mixed{i}" for i in range(11)],
            "dream_layers": list(DREAM_LAYERS),
        },
        {
            "model": "mobilenet_v1",
            "image_size": 224,
            "engine": "autodiff-deconv (DAG, depthwise-separable)",
            "layers": list(mb.DECONV_LAYERS),
            "dream_layers": list(mb.DREAM_LAYERS),
        },
        {
            "model": "mobilenet_v2",
            "image_size": 224,
            "engine": "autodiff-deconv (DAG, inverted residuals)",
            "layers": list(mb2.DECONV_LAYERS),
            "dream_layers": list(mb2.DREAM_LAYERS),
        },
        {
            "model": "vgg_tiny",
            "image_size": spec_tiny.input_shape[0],
            "engine": "switch-deconv (sequential spec, CI-scale)",
            "layers": [
                l.name for l in spec_tiny.layers if l.kind != "input"
            ],
            "dream_layers": ["block2_conv2", "block3_conv1"],
        },
    ]
