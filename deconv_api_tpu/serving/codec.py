"""Host-side image codec: the reference's wire format, byte-compatible.

Reproduces (deliberately, for pixel parity — SURVEY §2.2):
- data-URI base64 → BGR uint8 decode via cv2 (reference app/main.py:35-39);
- resize to 224×224 with cv2's default bilinear (app/main.py:53);
- Keras "caffe" preprocessing applied to the BGR array: flip to the other
  channel order and subtract the ImageNet BGR means — reproducing the
  reference's RGB/BGR mix-up exactly (SURVEY §2.2.1);
- 2×2 grid stitch of the top-4 projections (app/main.py:67-69);
- deprocess: mean/std normalize to 0.1 std, +0.5 shift, clip, uint8
  (app/deepdream.py:483-498);
- JPEG encode + base64 + percent-quote, served under a `data:image/webp`
  prefix — the reference's mislabel, kept for wire parity (app/main.py:73-76).
"""

from __future__ import annotations

import base64

import numpy as np

try:  # cv2 is present in the image; PIL is the documented fallback.
    import cv2

    _HAVE_CV2 = True
except Exception:  # pragma: no cover
    from PIL import Image

    _HAVE_CV2 = False

# Keras caffe-mode ImageNet means, BGR order (what `preprocess_input`
# subtracts after flipping channels).
CAFFE_MEANS_BGR = np.array([103.939, 116.779, 123.68], dtype=np.float32)

EPSILON = 1e-7  # K.epsilon() in the reference's deprocess (app/deepdream.py:486)


class CodecError(ValueError):
    """Malformed image payload (bad base64 / undecodable image)."""


def decode_data_url(uri: str) -> np.ndarray:
    """data-URI (or bare base64) → BGR uint8 HWC array.

    The reference splits on ',' and takes index 1 (app/main.py:36), which
    500s on bare base64; we accept both and raise CodecError (not a server
    crash) on garbage.
    """
    payload = uri.split(",", 1)[1] if "," in uri else uri
    try:
        raw = base64.b64decode(payload, validate=False)
    except Exception as e:
        raise CodecError(f"invalid base64 image payload: {e}") from e
    if not raw:
        # b64decode(validate=False) silently drops ALL non-alphabet chars,
        # so pure garbage ('@@@@') decodes to b'' rather than raising
        raise CodecError("empty image payload after base64 decode")
    if _HAVE_CV2:
        try:
            img = cv2.imdecode(np.frombuffer(raw, np.uint8), cv2.IMREAD_COLOR)
        except Exception as e:  # OpenCV >= 5 raises cv2.error on
            # undecodable/empty buffers instead of returning None
            raise CodecError(f"could not decode image bytes: {e}") from e
        if img is None:
            raise CodecError("could not decode image bytes")
        return img
    import io

    from PIL import Image  # local import, like the sibling fallbacks —
    # the module-global form only bound when cv2 failed at import time

    try:
        pil = Image.open(io.BytesIO(raw)).convert("RGB")
    except Exception as e:
        raise CodecError(f"could not decode image bytes: {e}") from e
    return np.asarray(pil)[:, :, ::-1]  # to BGR


def resize224(img: np.ndarray, size: tuple[int, int] = (224, 224)) -> np.ndarray:
    if _HAVE_CV2:
        return cv2.resize(img, size)
    from PIL import Image

    return np.asarray(Image.fromarray(img).resize(size))


def preprocess_vgg(img_bgr: np.ndarray) -> np.ndarray:
    """Keras caffe preprocessing as the reference invokes it.

    `preprocess_input` assumes RGB input, flips to BGR, subtracts BGR means.
    The reference hands it a BGR image (SURVEY §2.2.1), so the net effect —
    reproduced here — is a channel flip plus BGR-ordered mean subtraction.
    """
    x = img_bgr.astype(np.float32)[..., ::-1]
    return x - CAFFE_MEANS_BGR


def preprocess_tf(img_bgr: np.ndarray) -> np.ndarray:
    """Keras 'tf'-mode preprocessing (InceptionV3): RGB scaled to [-1, 1].
    Input arrives BGR from the decoder, so flip first."""
    x = img_bgr.astype(np.float32)[..., ::-1]
    return x / 127.5 - 1.0


def unpreprocess_vgg(x: np.ndarray) -> np.ndarray:
    """Inverse of `preprocess_vgg`: back to BGR uint8 (for DeepDream output,
    which lives in model-input space rather than projection space)."""
    y = x.astype(np.float32) + CAFFE_MEANS_BGR
    return np.clip(y[..., ::-1], 0, 255).astype(np.uint8)


def unpreprocess_tf(x: np.ndarray) -> np.ndarray:
    """Inverse of `preprocess_tf`: back to BGR uint8."""
    y = (x.astype(np.float32) + 1.0) * 127.5
    return np.clip(y[..., ::-1], 0, 255).astype(np.uint8)


def deprocess_image(x: np.ndarray) -> np.ndarray:
    """Projection tensor → displayable uint8 (reference app/deepdream.py:483-498)."""
    x = x.astype(np.float32)
    x = x - x.mean()
    x = x / (x.std() + EPSILON)
    x = x * 0.1 + 0.5
    x = np.clip(x, 0.0, 1.0) * 255.0
    return np.clip(x, 0, 255).astype(np.uint8)


def stitch_grid(images: list[np.ndarray]) -> np.ndarray:
    """Stitch the top-4 projections into a 2×2 grid (app/main.py:67-69).

    The reference IndexErrors (→ HTTP 500) when fewer than 4 filters fired
    (SURVEY §2.2.4); we pad missing tiles with zeros instead.
    """
    if not images:
        raise CodecError("no filter projections to stitch")
    tile = np.zeros_like(images[0])
    tiles = list(images[:4]) + [tile] * max(0, 4 - len(images))
    top = np.concatenate((tiles[0], tiles[1]), axis=1)
    bottom = np.concatenate((tiles[2], tiles[3]), axis=1)
    return np.concatenate((top, bottom), axis=0)


def encode_data_url(img_uint8: np.ndarray) -> str:
    """uint8 image → the reference's response string: JPEG bytes, base64,
    percent-quoted, under a data:image/webp prefix (app/main.py:73-76).

    The percent-quote runs as two C-level bytes.replace calls instead of
    urllib's per-character ``quote`` loop: the base64 alphabet is entirely
    quote-safe except '+' and '=' ('/' is in quote's default safe set), so
    the two forms are byte-identical — pinned by
    tests/test_codec.py::test_encode_quote_matches_urllib.  quote() was
    ~40% of the encode stage's host time at KB payloads (round 6)."""
    if _HAVE_CV2:
        ok, buf = cv2.imencode(".jpg", img_uint8)
        if not ok:
            raise CodecError("JPEG encode failed")
        raw = buf.tobytes()
    else:
        import io
        from PIL import Image

        bio = io.BytesIO()
        Image.fromarray(img_uint8[:, :, ::-1]).save(bio, format="JPEG")
        raw = bio.getvalue()
    quoted = (
        base64.b64encode(raw).replace(b"+", b"%2B").replace(b"=", b"%3D")
    )
    return "data:image/webp;base64,{}".format(quoted.decode("ascii"))


# --- device-side postprocessing --------------------------------------------
# The fp32 projection stack is the largest device->host transfer of a
# request (top_k * H * W * C * 4 bytes); deprocessing — and for the compat
# route, stitching — ON DEVICE cuts the transfer 4-16x to one uint8 image.
# Semantics are bit-matched to the NumPy functions above (same truncating
# uint8 cast, same EPSILON, and the reference's stitch-THEN-deprocess
# order, app/main.py:67-72).


def _deprocess_jax(x):
    import jax.numpy as jnp

    x = x.astype(jnp.float32)
    x = x - x.mean()
    x = x / (x.std() + EPSILON)
    x = x * 0.1 + 0.5
    x = jnp.clip(x, 0.0, 1.0) * 255.0
    return jnp.clip(x, 0.0, 255.0).astype(jnp.uint8)


import functools as _functools


@_functools.cache
def _deprocess_tiles_jit():
    import jax

    return jax.jit(jax.vmap(jax.vmap(_deprocess_jax)))


def deprocess_tiles_device(images):
    """(B, K, H, W, C) projections -> uint8, each tile normalized alone
    (the /v1/deconv per-filter presentation).  The jitted callable is
    memoized — pjit's trace cache keys on function identity, so a fresh
    wrapper per call would retrace on the hot serving path."""
    return _deprocess_tiles_jit()(images)


def _stitch_grid_traced(images, valid):
    """Traceable stitch+deprocess body — also composed INTO the fused
    serving program (serving/models.py:_fuse_post), where it runs as an
    epilogue of the visualizer dispatch."""
    import jax
    import jax.numpy as jnp

    b, k = images.shape[:2]
    if k < 4:
        pad = jnp.zeros((b, 4 - k, *images.shape[2:]), images.dtype)
        images = jnp.concatenate([images, pad], axis=1)
        valid = jnp.concatenate(
            [valid, jnp.zeros((b, 4 - k), valid.dtype)], axis=1
        )
    tiles = images[:, :4] * valid[:, :4, None, None, None].astype(images.dtype)
    top = jnp.concatenate([tiles[:, 0], tiles[:, 1]], axis=2)
    bottom = jnp.concatenate([tiles[:, 2], tiles[:, 3]], axis=2)
    grid = jnp.concatenate([top, bottom], axis=1)
    return jax.vmap(_deprocess_jax)(grid)


@_functools.cache
def _stitch_grid_jit():
    import jax

    return jax.jit(_stitch_grid_traced)


def stitch_grid_device(images, valid):
    """(B, K, H, W, C) + (B, K) validity -> (B, 2H, 2W, C) uint8: zero the
    tiles that didn't fire, stitch 2x2, deprocess over the WHOLE grid —
    the reference's order (stitch at app/main.py:67-69, deprocess of the
    stitched grid at :72), which normalizes all four tiles jointly."""
    return _stitch_grid_jit()(images, valid)
