"""Embedded metric history: fixed-interval ring-buffer TSDB (round 23).

Every observability surface before this round was *instantaneous* — a
scrape sees now, and everything before the last probe tick is gone.
This module gives each process its own memory: a periodic self-scrape
task flattens the existing ``Metrics.snapshot()`` into per-series ring
buffers with two downsampling tiers,

- **raw**: one sample per scrape tick (default 1 s × 600 slots), and
- **rollup**: min/mean/max over ``ROLLUP_MULT`` raw ticks (default
  15 s × 960 slots — four hours of history),

so memory is bounded BY CONSTRUCTION: ``max_series`` series × two
fixed-length rings, no allocation growth under sustained load, no
timestamps stored per point (slot position IS the timestamp).  The
clock is injectable — every lifecycle test runs on a hand-cranked
clock, never wall sleeps (the SloTracker discipline).

Series taxonomy follows the exposition:

- counters (``requests_total``, ``errors_total{code=}``, named and
  labeled counter families, histogram ``_bucket``/``_count``/``_sum``
  series) are stored **as rates**: the ingest diffs consecutive
  cumulative values and stores delta/elapsed, so a query reads req/s
  directly and a counter reset (process restart) clamps to the new
  cumulative value rather than producing a negative spike.
- gauges (``gauges.*``, labeled gauges, latency quantile summaries,
  SLO burn rates) are stored as-is.

Queries are served from whichever tier covers the asked range/step
(``GET /v1/metrics/history`` in app.py; the fleet router federates
per-backend histories the same way ``/v1/metrics/fleet`` federates
families).  The alert engine (serving/alerts.py) evaluates its rules
over ``window_agg``/``last_age`` on the same scrape tick.

Everything here is plain-Python and lock-protected: the sampler runs on
the event loop, queries arrive from request handlers, and tests drive
both from the main thread.
"""

from __future__ import annotations

import threading
import time

from deconv_api_tpu.serving.metrics import HIST_BUCKETS_S
from deconv_api_tpu.utils import slog

_log = slog.get_logger("deconv.tsdb")

KIND_COUNTER = "counter"
KIND_GAUGE = "gauge"

# Tier geometry (ISSUE 18): 1s×600 raw, 15s×960 rolled at the default
# 1 s scrape interval.  The rollup interval is a MULTIPLE of the raw
# interval (not an independent knob) so every raw sample folds into
# exactly one rollup window and the drill can shrink both tiers
# together by shrinking one interval.
RAW_SLOTS = 600
ROLLUP_SLOTS = 960
ROLLUP_MULT = 15

# Series-universe cap: beyond this the ingest drops NEW series (and
# counts the drops) rather than growing without bound — the same
# bounded-cardinality posture as qos's tenant fold-to-other.
MAX_SERIES = 2048


class _Series:
    """One (family, label) series: raw ring + rollup ring + the
    counter-diff and rollup-fold accumulators.  Rings are parallel
    ordinal/value lists; a slot is valid for a read at ordinal ``o``
    only when its stored ordinal matches the expected one (stale
    entries from a previous wrap are self-invalidating — no sweeps)."""

    __slots__ = (
        "kind", "last_cum", "last_ord",
        "raw_ord", "raw_val",
        "roll_ord", "roll_min", "roll_mean", "roll_max",
        "acc",
    )

    def __init__(self, kind: str, raw_slots: int, roll_slots: int):
        self.kind = kind
        self.last_cum: float | None = None   # counters: last cumulative
        self.last_ord: int | None = None
        self.raw_ord = [-1] * raw_slots
        self.raw_val = [0.0] * raw_slots
        self.roll_ord = [-1] * roll_slots
        self.roll_min = [0.0] * roll_slots
        self.roll_mean = [0.0] * roll_slots
        self.roll_max = [0.0] * roll_slots
        # current rollup window accumulator: [roll_ordinal, min, sum, n, max]
        self.acc: list | None = None


class Tsdb:
    """Two-tier ring-buffer store over flattened metric samples.

    ``interval_s`` is the scrape cadence the ingest assumes; the
    sampler task ticks at this period and calls ``ingest`` with the
    flattened snapshot.  ``clock`` is monotonic-seconds-like and
    injectable."""

    def __init__(
        self,
        interval_s: float = 1.0,
        *,
        raw_slots: int = RAW_SLOTS,
        rollup_slots: int = ROLLUP_SLOTS,
        rollup_mult: int = ROLLUP_MULT,
        max_series: int = MAX_SERIES,
        clock=time.monotonic,
    ):
        if interval_s <= 0:
            raise ValueError(f"tsdb interval_s must be > 0, got {interval_s}")
        if rollup_mult < 2:
            raise ValueError(f"tsdb rollup_mult must be >= 2, got {rollup_mult}")
        self.interval_s = float(interval_s)
        self.rollup_s = self.interval_s * rollup_mult
        self._raw_slots = int(raw_slots)
        self._roll_slots = int(rollup_slots)
        self._mult = int(rollup_mult)
        self._max_series = int(max_series)
        self._clock = clock
        self._lock = threading.Lock()
        self._series: dict[tuple[str, str], _Series] = {}
        self.samples_total = 0
        self.series_clipped_total = 0
        self.scrapes_total = 0
        self.scrape_seconds_total = 0.0

    # ------------------------------------------------------------ ingest

    def ingest(
        self,
        samples: dict[tuple[str, str], tuple[str, float]],
        now: float | None = None,
    ) -> None:
        """One scrape tick: ``{(family, label): (kind, value)}`` where
        counter values are CUMULATIVE (the ingest does the rate diff).

        Idempotent per ordinal: a second ingest landing in the same
        interval slot overwrites it (last-writer-wins) rather than
        double-counting."""
        if now is None:
            now = self._clock()
        ordinal = int(now / self.interval_s)
        with self._lock:
            for key, (kind, value) in samples.items():
                s = self._series.get(key)
                if s is None:
                    if len(self._series) >= self._max_series:
                        self.series_clipped_total += 1
                        continue
                    s = self._series[key] = _Series(
                        kind, self._raw_slots, self._roll_slots
                    )
                v = float(value)
                if s.kind == KIND_COUNTER:
                    cum = v
                    if s.last_cum is None or s.last_ord is None:
                        # first sight: no rate yet, just anchor the diff
                        s.last_cum, s.last_ord = cum, ordinal
                        continue
                    if ordinal <= s.last_ord:
                        continue
                    delta = cum - s.last_cum
                    if delta < 0:
                        # counter reset (restart): the new cumulative IS
                        # the activity since the reset
                        delta = cum
                    v = delta / ((ordinal - s.last_ord) * self.interval_s)
                    s.last_cum, s.last_ord = cum, ordinal
                self._store(s, ordinal, v)
                self.samples_total += 1

    def _store(self, s: _Series, ordinal: int, v: float) -> None:
        idx = ordinal % self._raw_slots
        s.raw_ord[idx] = ordinal
        s.raw_val[idx] = v
        r_ord = ordinal // self._mult
        if s.acc is None:
            s.acc = [r_ord, v, v, 1, v]
        elif s.acc[0] == r_ord:
            acc = s.acc
            if v < acc[1]:
                acc[1] = v
            acc[2] += v
            acc[3] += 1
            if v > acc[4]:
                acc[4] = v
        else:
            self._flush_acc(s)
            s.acc = [r_ord, v, v, 1, v]

    def _flush_acc(self, s: _Series) -> None:
        if s.acc is None:
            return
        r_ord, mn, total, n, mx = s.acc
        idx = r_ord % self._roll_slots
        s.roll_ord[idx] = r_ord
        s.roll_min[idx] = mn
        s.roll_mean[idx] = total / n
        s.roll_max[idx] = mx
        s.acc = None

    # ------------------------------------------------------------- query

    def families(self) -> dict:
        """Catalog: {family: {"kind": ..., "labels": [...]}} — the
        no-param answer of /v1/metrics/history."""
        out: dict[str, dict] = {}
        with self._lock:
            for (fam, label), s in self._series.items():
                ent = out.setdefault(fam, {"kind": s.kind, "labels": []})
                if label not in ent["labels"]:
                    ent["labels"].append(label)
        for ent in out.values():
            ent["labels"].sort()
        return out

    def query(
        self,
        family: str,
        label: str | None = None,
        range_s: float = 60.0,
        step_s: float | None = None,
        now: float | None = None,
    ) -> list[dict]:
        """Series points over the trailing ``range_s`` window.

        Tier selection: the raw ring serves ranges it still covers
        unless the caller asks for a step at or beyond the rollup
        interval; everything else comes from the rollup ring.  Points
        are ``[age_s, value]`` (raw) or ``[age_s, min, mean, max]``
        (rollup), newest first, ``age_s`` relative to ``now`` — age
        addressing keeps federated per-backend histories comparable
        without trusting anyone's wall clock."""
        if now is None:
            now = self._clock()
        range_s = float(range_s)
        raw_window = self._raw_slots * self.interval_s
        use_rollup = range_s > raw_window or (
            step_s is not None and float(step_s) >= self.rollup_s
        )
        out = []
        with self._lock:
            for (fam, lab), s in self._series.items():
                if fam != family:
                    continue
                if label is not None and lab != label:
                    continue
                ent = {
                    "family": fam,
                    "label": lab,
                    "kind": s.kind,
                    "tier": "rollup" if use_rollup else "raw",
                    "interval_s": self.rollup_s if use_rollup else self.interval_s,
                    "points": [],
                }
                if use_rollup:
                    # the open accumulator window is readable too — an
                    # alert should not wait a full rollup interval to
                    # see the sample that just landed
                    newest = int(now / self.interval_s) // self._mult
                    span = max(1, int(range_s / self.rollup_s))
                    pts = ent["points"]
                    for r_ord in range(newest, newest - span - 1, -1):
                        if r_ord < 0:
                            break
                        if s.acc is not None and s.acc[0] == r_ord:
                            _, mn, total, n, mx = s.acc
                            pts.append([
                                round(now - (r_ord + 1) * self.rollup_s, 6),
                                mn, total / n, mx,
                            ])
                            continue
                        idx = r_ord % self._roll_slots
                        if s.roll_ord[idx] != r_ord:
                            continue
                        age = now - (r_ord + 1) * self.rollup_s
                        pts.append([
                            round(age, 6),
                            s.roll_min[idx], s.roll_mean[idx],
                            s.roll_max[idx],
                        ])
                else:
                    newest = int(now / self.interval_s)
                    span = max(1, int(range_s / self.interval_s))
                    pts = ent["points"]
                    for o in range(newest, newest - span - 1, -1):
                        if o < 0:
                            break
                        idx = o % self._raw_slots
                        if s.raw_ord[idx] != o:
                            continue
                        pts.append([
                            round(now - o * self.interval_s, 6),
                            s.raw_val[idx],
                        ])
                out.append(ent)
        out.sort(key=lambda e: e["label"])
        return out

    def window_agg(
        self,
        family: str,
        label: str,
        range_s: float,
        agg: str = "mean",
        now: float | None = None,
    ) -> float | None:
        """One number over the trailing window — the alert engine's
        read.  ``None`` when the window holds no samples (which is what
        the absence rule kind keys on)."""
        series = self.query(family, label, range_s=range_s, now=now)
        vals: list[float] = []
        for ent in series:
            if ent["tier"] == "raw":
                vals.extend(p[1] for p in ent["points"])
            else:
                # rollup points carry min/mean/max; pick the component
                # that keeps the aggregate conservative for its verb
                for p in ent["points"]:
                    if agg == "min":
                        vals.append(p[1])
                    elif agg == "max":
                        vals.append(p[3])
                    else:
                        vals.append(p[2])
        if not vals:
            return None
        if agg == "min":
            return min(vals)
        if agg == "max":
            return max(vals)
        if agg == "sum":
            return sum(vals)
        if agg == "last":
            return vals[0]
        return sum(vals) / len(vals)

    def last_age(
        self, family: str, label: str, now: float | None = None
    ) -> float | None:
        """Age in seconds of the newest stored sample for one series,
        ``None`` if the series has never been seen — the staleness
        primitive the absence rule kind evaluates."""
        if now is None:
            now = self._clock()
        with self._lock:
            s = self._series.get((family, label))
            if s is None:
                return None
            best: int | None = None
            if s.kind == KIND_COUNTER and s.last_ord is not None:
                best = s.last_ord
            for o in s.raw_ord:
                if o >= 0 and (best is None or o > best):
                    best = o
            if best is None:
                return None
            return max(0.0, now - best * self.interval_s)

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "max_series": self._max_series,
                "samples_total": self.samples_total,
                "series_clipped_total": self.series_clipped_total,
                "scrapes_total": self.scrapes_total,
                "scrape_seconds_total": round(self.scrape_seconds_total, 6),
                "interval_s": self.interval_s,
                "rollup_s": self.rollup_s,
                "raw_slots": self._raw_slots,
                "rollup_slots": self._roll_slots,
            }


# ------------------------------------------------------------- flatten

def flatten_snapshot(snap: dict) -> dict[tuple[str, str], tuple[str, float]]:
    """``Metrics.snapshot()`` -> ``{(family, label): (kind, value)}``.

    The flattening mirrors the text exposition's series universe so an
    operator can move between ``/v1/metrics`` and
    ``/v1/metrics/history`` without a mental renaming table: histogram
    labelsets get ``_bucket``/``_sum``/``_count`` derived families with
    an ``le=`` label component, labeled families join their tuples into
    the same ``k=v,k2=v2`` label string the federation splice uses."""
    out: dict[tuple[str, str], tuple[str, float]] = {}

    def put(fam: str, label: str, kind: str, value) -> None:
        out[(fam, label)] = (kind, float(value))

    if "requests_total" in snap:
        put("requests_total", "", KIND_COUNTER, snap["requests_total"])
        put("images_total", "", KIND_COUNTER, snap.get("images_total", 0))
        put("batches_total", "", KIND_COUNTER, snap.get("batches_total", 0))
        put("latency_p50_s", "", KIND_GAUGE, snap.get("latency_p50_s", 0.0))
        put("latency_p99_s", "", KIND_GAUGE, snap.get("latency_p99_s", 0.0))
        put(
            "queue_wait_p50_s", "", KIND_GAUGE,
            snap.get("queue_wait_p50_s", 0.0),
        )
    for code, n in (snap.get("errors_total") or {}).items():
        put("errors_total", f"code={code}", KIND_COUNTER, n)
    for name, n in (snap.get("counters") or {}).items():
        put(name, "", KIND_COUNTER, n)
    for name, v in (snap.get("gauges") or {}).items():
        put(name, "", KIND_GAUGE, v)

    def label_block(names, joined_key: str) -> str:
        ns = names if isinstance(names, (list, tuple)) else (names,)
        vs = joined_key.split(",") if len(ns) > 1 else [joined_key]
        if len(vs) != len(ns):
            # a label VALUE containing ',' would mis-split; keep the
            # raw joined form rather than guessing
            return f"{ns[0]}={joined_key}"
        return ",".join(f"{n}={v}" for n, v in zip(ns, vs))

    for fam, (names, series) in (snap.get("labeled") or {}).items():
        for key, n in series.items():
            put(fam, label_block(names, key), KIND_COUNTER, n)
    for fam, (name, series) in (snap.get("labeled_gauges") or {}).items():
        for key, v in series.items():
            put(fam, f"{name}={key}", KIND_GAUGE, v)
    for fam, (names, series) in (snap.get("histograms") or {}).items():
        for key, h in series.items():
            block = label_block(names, key)
            sep = "," if block else ""
            put(f"{fam}_count", block, KIND_COUNTER, h["count"])
            put(f"{fam}_sum", block, KIND_COUNTER, h["sum"])
            cum = 0
            for bound, n in zip(HIST_BUCKETS_S, h["buckets"]):
                cum += n
                put(
                    f"{fam}_bucket", f"{block}{sep}le={bound:g}",
                    KIND_COUNTER, cum,
                )
            put(
                f"{fam}_bucket", f"{block}{sep}le=+Inf",
                KIND_COUNTER, h["count"],
            )
    return out
