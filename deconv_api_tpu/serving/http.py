"""A minimal asyncio HTTP/1.1 server.

The reference serves over FastAPI+uvicorn (app/main.py:19-32, Dockerfile:15);
neither is in this image, so the framework carries its own dependency-free
HTTP layer: enough of HTTP/1.1 for the reference's wire surface (urlencoded
and multipart form POSTs, JSON responses, CORS with allow-all origins and no
credentials — matching app/main.py:22-32) plus keep-alive.

Handlers are `async def handler(Request) -> Response`; blocking device work
never runs on the event loop (the dispatcher hands it to a worker thread),
fixing the reference's frozen-loop concurrency of 1 (SURVEY §2.2.5).
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
import time
from dataclasses import dataclass, field
from urllib.parse import parse_qsl, unquote, urlsplit

from deconv_api_tpu.serving import faults
from deconv_api_tpu.serving.trace import deadline_from, hop_from, request_id_from
from deconv_api_tpu.utils import slog

_log = slog.get_logger("deconv.http")

MAX_BODY = 64 * 1024 * 1024  # 64 MiB: base64 images are bulky
MAX_HEADER = 64 * 1024

CORS_HEADERS = {
    # Reference CORS: allow-all origins, no credentials (app/main.py:22-32).
    "access-control-allow-origin": "*",
    "access-control-allow-methods": "*",
    "access-control-allow-headers": "*",
    # without this a browser client can SEE only the safelisted headers —
    # x-request-id (round 8) and x-cache (round 7) would be invisible to
    # the reference's React client even though curl shows them
    "access-control-expose-headers": "*",
}

_STATUS_TEXT = {
    200: "OK", 202: "Accepted", 204: "No Content", 400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout", 411: "Length Required",
    413: "Payload Too Large", 422: "Unprocessable Entity",
    429: "Too Many Requests",
    500: "Internal Server Error", 501: "Not Implemented",
    502: "Bad Gateway",
    503: "Service Unavailable", 504: "Gateway Timeout",
}


@dataclass
class Request:
    method: str
    path: str
    query: dict[str, str]
    headers: dict[str, str]
    body: bytes
    # Stable per-request id (round 8 tracing spine): a sane inbound
    # x-request-id header is honored, otherwise the server mints one at
    # parse time.  Every response echoes it back, every access/error log
    # line and flight-recorder trace carries it — the one join key
    # across client logs, server logs, metrics exemplars and traces.
    id: str = ""
    # Absolute perf_counter deadline parsed from x-deadline-ms (round 9),
    # anchored at parse time so queue wait counts against the caller's
    # budget; None = no deadline.  The batcher reaps items whose
    # deadline lapsed at the queue-pop and pre-dispatch boundaries, and
    # singleflight waiters time out on their OWN deadline independently
    # of the flight leader.
    deadline: float | None = None
    # Cross-hop trace context (round 19, fleet observability): the
    # router stamps each forward attempt with ``x-trace-hop:
    # <ordinal>:<purpose>``; parsed here (same parse-time rule as id /
    # deadline) so the backend's flight-recorder trace can annotate
    # which attempt of a retried/hedged request it served.  None for
    # direct traffic or a malformed header — never an error.
    hop: tuple[int, str] | None = None
    # Tenant identity (round 13 QoS): stamped by the admission wrap
    # (serving/qos.py resolves x-api-key / x-tenant) so the access-log
    # line, the flight-recorder trace, and the dispatcher queue all
    # carry the same identity.  Empty while QoS is off.
    tenant: str = ""
    tclass: str = ""
    # Resolved per-request model (round 15 multi-model serving): the
    # validated ``model=`` form field / ``x-model`` header, or the
    # server default.  Memoized by DeconvService._resolve_model so the
    # cache wrap, the route handler, and the trace annotation all agree
    # on one resolution per request.  Empty = not resolved yet.
    model: str = ""
    # Resolved per-request quality tier (round 18 int8 execution):
    # ``quality=`` form field / ``x-quality`` header / QoS-class
    # default / server default, validated against full|bf16|int8.
    # Memoized by DeconvService._resolve_quality — same one-resolution
    # contract as ``model``.  Empty = not resolved yet.
    quality: str = ""
    # the admission Grant (accounting handle) the QoS wrap stashes so
    # the cache wrap can refund a hit's provisional device debit
    _qos_grant: object = field(default=None, repr=False, compare=False)
    # memoized form() result — the response cache derives its key from
    # the parsed form and the route handler parses the same body again;
    # one parse serves both (round 7).  None = not parsed yet.
    _form: dict[str, str] | None = field(default=None, repr=False, compare=False)
    # memoized forward-header base (round 21 router fast path): the
    # hop-stripped client headers are identical across retry/hedge
    # attempts of one request, so the router filters them once and
    # reuses the list for every attempt.  None = not computed yet.
    _fwd_base: list | None = field(default=None, repr=False, compare=False)

    def form(self) -> dict[str, str]:
        """Parse the body as a form: urlencoded or multipart/form-data.
        Parsed once per request; repeat calls return the memoized dict
        (callers treat it as read-only)."""
        if self._form is None:
            self._form = self._parse_form_body()
        return self._form

    def _parse_form_body(self) -> dict[str, str]:
        ctype = self.headers.get("content-type", "")
        if ctype.startswith("application/x-www-form-urlencoded"):
            return {
                k: v
                for k, v in parse_qsl(
                    self.body.decode("utf-8", "replace"), keep_blank_values=True
                )
            }
        if ctype.startswith("multipart/form-data"):
            m = re.search(r'boundary="?([^";,]+)"?', ctype)
            if not m:
                raise ValueError("multipart body without boundary")
            return _parse_multipart(self.body, m.group(1).encode())
        if ctype.startswith("application/json"):
            data = json.loads(self.body.decode("utf-8"))
            if not isinstance(data, dict):
                raise ValueError("JSON form body must be an object")
            return {k: str(v) for k, v in data.items()}
        raise ValueError(f"unsupported content-type {ctype!r}")


def _parse_multipart(body: bytes, boundary: bytes) -> dict[str, str]:
    fields: dict[str, str] = {}
    delim = b"--" + boundary
    for part in body.split(delim):
        part = part.strip(b"\r\n")
        if not part or part == b"--":
            continue
        if b"\r\n\r\n" not in part:
            continue
        head, _, value = part.partition(b"\r\n\r\n")
        m = re.search(rb'name="([^"]*)"', head)
        if m:
            fields[m.group(1).decode()] = value.decode("utf-8", "replace")
    return fields


@dataclass
class Response:
    status: int = 200
    body: bytes = b""
    headers: dict[str, str] = field(default_factory=dict)
    # Progressive delivery (round 11, the jobs SSE surface): an async
    # iterator of byte chunks.  When set, the serve loop writes the head
    # (no content-length, ``connection: close``) and then streams chunks
    # as the iterator yields them — body-until-close framing, which is
    # what EventSource clients expect.  ``body`` is ignored.
    stream: object | None = field(default=None, repr=False, compare=False)

    @classmethod
    def json(cls, obj, status: int = 200) -> "Response":
        return cls(
            status=status,
            body=json.dumps(obj).encode(),
            headers={"content-type": "application/json"},
        )

    @classmethod
    def text(cls, s: str, status: int = 200, content_type: str = "text/plain") -> "Response":
        return cls(status=status, body=s.encode(), headers={"content-type": content_type})

    def encode(self, keep_alive: bool) -> bytes:
        headers = {
            **CORS_HEADERS,
            "content-length": str(len(self.body)),
            "connection": "keep-alive" if keep_alive else "close",
            **self.headers,
        }
        head = f"HTTP/1.1 {self.status} {_STATUS_TEXT.get(self.status, 'Unknown')}\r\n"
        head += "".join(f"{k}: {v}\r\n" for k, v in headers.items())
        return head.encode() + b"\r\n" + self.body


class HttpServer:
    """Route table + asyncio stream server.

    Abuse hardening (VERDICT r2): per-connection header/idle and body read
    timeouts bound how long a slowloris client can hold a socket (and up to
    MAX_BODY of buffer); ``max_connections`` caps concurrent sockets —
    excess connections get an immediate 503 and close.  Timeouts of 0
    disable the respective guard."""

    def __init__(
        self,
        *,
        idle_timeout_s: float = 30.0,
        body_timeout_s: float = 20.0,
        max_connections: int = 256,
    ):
        self._routes: dict[tuple[str, str], callable] = {}
        # prefix-matched routes (round 11: /v1/jobs/{id}[/...]): checked
        # after the exact table, longest prefix wins; the handler reads
        # the id out of req.path itself
        self._prefix_routes: list[tuple[str, str, callable]] = []
        self._server: asyncio.AbstractServer | None = None
        self._idle_timeout_s = idle_timeout_s
        self._body_timeout_s = body_timeout_s
        self._max_connections = max_connections
        self._nconn = 0
        # Drain-aware keep-alive (round 9): while True, every response on
        # a live connection carries `connection: close` and the serve
        # loop stops honoring keep-alive — clients learn the server is
        # going away from the LAST response they get, not from a TCP
        # reset mid-pipeline.  Set by the service at drain begin.
        self.draining = False

    def route(self, method: str, path: str):
        def register(fn):
            self._routes[(method.upper(), path)] = fn
            return fn

        return register

    def route_prefix(self, method: str, prefix: str):
        """Register a handler for every path under ``prefix`` (round 11:
        the per-job routes).  Exact routes win; among prefixes the
        longest match wins."""

        def register(fn):
            self._prefix_routes.append((method.upper(), prefix, fn))
            # longest prefix first, so /v1/jobs/ beats /v1/ if both exist
            self._prefix_routes.sort(key=lambda r: -len(r[1]))
            return fn

        return register

    async def start(
        self, host: str, port: int, *, reuse_port: bool = False
    ) -> int:
        # reuse_port (round 21): SO_REUSEPORT lets N independent router
        # processes share one accept queue — the kernel load-balances
        # connections across their accept loops.  Only passed through
        # when requested so the default path stays portable.
        kwargs = {"reuse_port": True} if reuse_port else {}
        self._server = await asyncio.start_server(
            self._handle_conn, host, port, **kwargs
        )
        return self._server.sockets[0].getsockname()[1]

    async def stop(self, grace_s: float = 5.0) -> None:
        self.draining = True
        if self._server is not None:
            self._server.close()
            try:
                # Python >= 3.12.1: wait_closed() waits for ALL open client
                # connections — an idle keep-alive peer would hold shutdown
                # for up to idle_timeout_s (or forever, if active).  Bound
                # it: after the grace period the remaining connection tasks
                # are abandoned (they die with the loop) so the dispatcher
                # drain behind us still runs within a container's term
                # grace window.
                await asyncio.wait_for(self._server.wait_closed(), grace_s)
            except asyncio.TimeoutError:
                pass

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        if self._max_connections > 0 and self._nconn >= self._max_connections:
            # minted id even on a connection-cap reject: the 503 body/
            # header and the http_reject log line join on it (no request
            # was parsed, so there is no inbound id to honor)
            rid = request_id_from(None)
            slog.event(
                _log, "http_reject", level=logging.WARNING,
                status=503, reason="too_many_connections", nconn=self._nconn,
                id=rid,
            )
            try:
                resp = Response.json(
                    {"error": "too many connections", "request_id": rid}, 503
                )
                resp.headers["x-request-id"] = rid
                writer.write(resp.encode(False))
                await writer.drain()
                # Drain briefly before close: closing with unread request
                # bytes in the socket buffer sends RST, which can destroy
                # the in-flight 503 before the client reads it — the
                # back-off signal would look like a server crash.
                try:
                    await asyncio.wait_for(reader.read(65536), 0.25)
                except asyncio.TimeoutError:
                    pass
            except (ConnectionResetError, BrokenPipeError):
                pass
            finally:
                writer.close()
            return
        self._nconn += 1
        try:
            await self._serve_conn(reader, writer)
        finally:
            self._nconn -= 1

    async def _serve_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                keep_alive = (
                    req.headers.get("connection", "keep-alive") != "close"
                    and not self.draining
                )
                t0 = time.perf_counter()
                resp = await self._dispatch(req)
                # draining may have BEGUN while the handler ran: this
                # response must already tell the client to stop
                # pipelining into a dying server
                if self.draining:
                    keep_alive = False
                # EVERY response carries the request id — success, 4xx,
                # shed 503, handler-crash 500 — so a client-side log line
                # joins server logs and flight-recorder traces on one key
                resp.headers.setdefault("x-request-id", req.id)
                if resp.stream is not None:
                    # progressive delivery (round 11, the jobs SSE
                    # surface): head now, chunks as they come, close at
                    # the end — body-until-close framing on a
                    # ``connection: close`` response
                    await self._write_stream(writer, req, resp, t0)
                    break
                # 500 = handler crash -> ERROR.  503/504 are DESIGNED
                # backpressure (shedding, timeouts) — WARNING, or they
                # would flood error alerting exactly at peak load.
                lvl = (
                    logging.ERROR if resp.status == 500
                    else logging.WARNING if resp.status >= 500
                    else logging.INFO
                )
                extra = {"tenant": req.tenant} if req.tenant else {}
                slog.event(
                    _log, "http_request", level=lvl,
                    method=req.method, path=req.path, status=resp.status,
                    id=req.id,
                    ms=round((time.perf_counter() - t0) * 1e3, 1),
                    **extra,
                )
                act = faults.check("http.slow_write")
                if act is not None:
                    # chaos site: a stalled response write (saturated NIC,
                    # slow proxy) — the client-observed tail grows while
                    # the handler's own spans stay healthy
                    await asyncio.sleep((act.param or 50.0) / 1e3)
                writer.write(resp.encode(keep_alive))
                await writer.drain()
                if not keep_alive:
                    break
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except _ConnExpired:
            # routine idle/slowloris reaping — DEBUG, not an error signal
            slog.event(_log, "conn_expired", level=logging.DEBUG)
        except _BadRequest as e:
            # protocol-level rejections (400/408/413/431) never reach
            # _dispatch, so they get their own structured line — these are
            # exactly the abuse signals operators grep for (r3 review).
            # A Request object may never have been built (the reject can
            # fire mid-header-parse), so the id is MINTED here; body,
            # header and log line carry the same one (round 8 contract:
            # every response joins on x-request-id).
            rid = request_id_from(None)
            slog.event(
                _log, "http_reject", level=logging.WARNING,
                status=e.status, reason=str(e), id=rid,
            )
            try:
                resp = Response.json(
                    {"error": str(e), "request_id": rid}, e.status
                )
                resp.headers["x-request-id"] = rid
                writer.write(resp.encode(False))
                await writer.drain()
            except ConnectionResetError:
                pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except ConnectionResetError:
                pass

    async def _write_stream(
        self,
        writer: asyncio.StreamWriter,
        req: Request,
        resp: Response,
        t0: float,
    ) -> None:
        """Write a streaming response: head without content-length, then
        every chunk the iterator yields.  The access log line lands when
        the stream ENDS (its ms is the stream's whole lifetime).  A
        client that disconnects mid-stream surfaces as ConnectionReset
        in the caller's handler; the generator is always closed so its
        finally blocks (subscription cleanup) run."""
        headers = {
            **CORS_HEADERS,
            "connection": "close",
            "cache-control": "no-cache",
            **resp.headers,
        }
        head = (
            f"HTTP/1.1 {resp.status} "
            f"{_STATUS_TEXT.get(resp.status, 'Unknown')}\r\n"
        )
        head += "".join(f"{k}: {v}\r\n" for k, v in headers.items())
        try:
            writer.write(head.encode() + b"\r\n")
            await writer.drain()
            async for chunk in resp.stream:
                writer.write(chunk)
                await writer.drain()
        finally:
            aclose = getattr(resp.stream, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:  # noqa: BLE001 — cleanup must not mask
                    pass
            slog.event(
                _log, "http_request", level=logging.INFO,
                method=req.method, path=req.path, status=resp.status,
                id=req.id, stream=True,
                ms=round((time.perf_counter() - t0) * 1e3, 1),
            )

    async def _read_request(self, reader: asyncio.StreamReader) -> Request | None:
        try:
            # One clock bounds both idle keep-alive waits and slow-header
            # (slowloris) sends: a client gets idle_timeout_s to deliver a
            # complete header block, then the connection is reaped.
            head = await self._timed(
                reader.readuntil(b"\r\n\r\n"), self._idle_timeout_s
            )
        except asyncio.TimeoutError:
            raise _ConnExpired from None
        except asyncio.IncompleteReadError as e:
            if not e.partial:
                return None  # clean close between keep-alive requests
            raise
        except asyncio.LimitOverrunError:
            raise _BadRequest(431, "headers too large") from None
        if len(head) > MAX_HEADER:
            raise _BadRequest(431, "headers too large")
        # Single-pass parse (round 21 fast path): walk the raw bytes with
        # one find() per boundary instead of whole-head decode + split +
        # per-line partition.  Every proxied request pays this parse on
        # the router hop, so its allocations are hop-budget dollars.
        # Semantics are unchanged: keys stripped+lowercased, values
        # stripped, colon-less non-empty lines become empty-valued keys.
        end = len(head) - 4  # drop the trailing \r\n\r\n
        eol = head.find(b"\r\n", 0, end)
        if eol < 0:
            eol = end
        reqline = head[:eol].decode("latin-1")
        try:
            method, target, _version = reqline.split(" ", 2)
        except ValueError:
            raise _BadRequest(400, f"malformed request line {reqline!r}") from None
        headers: dict[str, str] = {}
        pos = eol + 2
        while pos < end:
            nxt = head.find(b"\r\n", pos, end)
            if nxt < 0:
                nxt = end
            if nxt > pos:
                colon = head.find(b":", pos, nxt)
                if colon < 0:
                    headers[head[pos:nxt].strip().lower().decode("latin-1")] = ""
                else:
                    headers[
                        head[pos:colon].strip().lower().decode("latin-1")
                    ] = head[colon + 1 : nxt].strip().decode("latin-1")
            pos = nxt + 2
        body = b""
        if "content-length" in headers:
            try:
                n = int(headers["content-length"])
            except ValueError:
                raise _BadRequest(400, "bad content-length") from None
            if n < 0:
                # readexactly(-5) would raise an uncaught ValueError and
                # kill the connection task (same hazard as the chunked
                # path's negative chunk size below; r3 fuzz-review finding)
                raise _BadRequest(400, "bad content-length")
            if n > MAX_BODY:
                raise _BadRequest(413, "body too large")
            try:
                body = await self._timed(reader.readexactly(n), self._body_timeout_s)
            except asyncio.TimeoutError:
                raise _BadRequest(408, "body read timed out") from None
        elif headers.get("transfer-encoding", "").lower() == "chunked":
            try:
                body = await self._timed(
                    self._read_chunked(reader), self._body_timeout_s
                )
            except asyncio.TimeoutError:
                raise _BadRequest(408, "body read timed out") from None
        parts = urlsplit(target)
        query = {k: v for k, v in parse_qsl(parts.query, keep_blank_values=True)}
        return Request(
            method.upper(), unquote(parts.path), query, headers, body,
            request_id_from(headers.get("x-request-id")),
            deadline_from(headers.get("x-deadline-ms")),
            hop_from(headers.get("x-trace-hop")),
        )

    async def _read_chunked(self, reader: asyncio.StreamReader) -> bytes:
        chunks = []
        total = 0
        while True:
            try:
                # readline raises (LimitOverrun wrapped in ValueError) when
                # a "chunk-size line" exceeds the StreamReader limit — a
                # malformed or hostile request, not a server error
                size_line = (await reader.readline()).strip()
            except (asyncio.LimitOverrunError, ValueError):
                raise _BadRequest(400, "bad chunk framing") from None
            try:
                n = int(size_line.split(b";")[0], 16)
            except ValueError:
                raise _BadRequest(400, "bad chunk size") from None
            if n < 0:
                # int(b"-1", 16) parses; readexactly(-1) would raise an
                # uncaught ValueError and kill the connection task
                raise _BadRequest(400, "bad chunk size")
            if n == 0:
                await reader.readline()
                return b"".join(chunks)
            total += n
            if total > MAX_BODY:
                raise _BadRequest(413, "body too large")
            chunks.append(await reader.readexactly(n))
            await reader.readexactly(2)  # trailing CRLF

    async def _dispatch(self, req: Request) -> Response:
        if req.method == "OPTIONS":  # CORS preflight
            return Response(204)
        handler = self._routes.get((req.method, req.path))
        if handler is None:
            for method, prefix, fn in self._prefix_routes:
                if method == req.method and req.path.startswith(prefix):
                    handler = fn
                    break
        if handler is None:
            if any(p == req.path for (_, p) in self._routes) or any(
                req.path.startswith(prefix)
                for (_, prefix, _fn) in self._prefix_routes
            ):
                return Response.json({"error": "method not allowed"}, 405)
            return Response.json({"error": f"no route for {req.path}"}, 404)
        try:
            return await handler(req)
        except Exception as e:  # noqa: BLE001 — last-resort 500, never a dropped conn
            import traceback

            traceback.print_exc()
            slog.event(
                _log, "handler_crash", level=logging.ERROR,
                path=req.path, id=req.id, error=f"{type(e).__name__}: {e}",
            )
            from deconv_api_tpu import errors

            # one payload shape for every error body (errors.to_payload):
            # the base DeconvError carries internal_error/500
            return Response.json(
                errors.to_payload(
                    errors.DeconvError(f"{type(e).__name__}: {e}"), req.id
                ),
                500,
            )


    @staticmethod
    async def _timed(coro, timeout_s: float):
        """await with a timeout; 0 disables (tests, trusted meshes)."""
        if timeout_s <= 0:
            return await coro
        return await asyncio.wait_for(coro, timeout_s)


class _BadRequest(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class _ConnExpired(Exception):
    """Idle/slow-header connection reaped; closed without a response (a
    slowloris peer never reads it, an idle keep-alive peer expects none)."""
