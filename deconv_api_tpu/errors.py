"""Error taxonomy.

The reference's failure mode for bad input is `sys.exit()` — it kills the
whole server process on an unknown layer type or visualize mode
(reference: app/deepdream.py:418-421, 458-460; SURVEY §5 mandates replacing
this with an HTTP 4xx/5xx taxonomy)."""

from __future__ import annotations


class DeconvError(Exception):
    """Base class: maps to an HTTP status + machine-readable code."""

    status = 500
    code = "internal_error"

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class BadRequest(DeconvError):
    status = 400
    code = "bad_request"


class InvalidImage(BadRequest):
    code = "invalid_image"


class UnknownLayer(DeconvError):
    status = 422
    code = "unknown_layer"


class UnknownModel(DeconvError):
    status = 422
    code = "unknown_model"


class IllegalMode(DeconvError):
    status = 422
    code = "illegal_visualize_mode"


class IllegalQuality(DeconvError):
    """The per-request precision tier (``quality=`` form field /
    ``x-quality`` header, round 18) named something outside
    full|bf16|int8 — deterministic, negative-cacheable."""

    status = 422
    code = "illegal_quality"


class NoActiveFilters(DeconvError):
    """Fewer filters fired than requested; the reference IndexErrors into a
    500 here (SURVEY §2.2.4).  Serving pads the grid instead; this error is
    only raised in strict-compat mode."""

    status = 422
    code = "no_active_filters"


class ModelNotReady(DeconvError):
    """Compute routes 503 until warmup has compiled the serving
    executables — callers poll /ready instead of silently paying compile
    latency inside a request."""

    status = 503
    code = "model_not_ready"


class Overloaded(DeconvError):
    """Queue drain estimate exceeds the request timeout: shedding now with
    an immediate 503 beats making every excess caller wait out the full
    timeout for a guaranteed 504 (serving/batcher.py:submit).

    Carries the drain estimate that triggered the shed so the HTTP layer
    can emit an actionable ``Retry-After`` header — backoff guidance
    derived from the queue's actual state, not a magic constant."""

    status = 503
    code = "overloaded"

    def __init__(self, message: str, retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class RequestTimeout(DeconvError):
    status = 504
    code = "request_timeout"


def to_payload(e: DeconvError, request_id: str | None = None) -> dict:
    """The JSON error body every route serves: machine code + detail,
    plus the request id (round 8 tracing spine) so a client-side error
    log joins server logs and `/v1/debug/requests` traces on one key."""
    payload = {"error": e.code, "detail": e.message}
    tenant = getattr(e, "tenant", None)
    if tenant:
        # quota errors name WHOSE budget was hit (round 13 multi-tenant
        # QoS): a client library multiplexing keys needs the split
        payload["tenant"] = tenant
    if request_id:
        payload["request_id"] = request_id
    return payload


def code_from_body(body: bytes) -> str | None:
    """Best-effort machine error code out of a JSON error payload (the
    {"error": code, "detail": ...} shape every route emits).  One place
    for the cache's negative entries and the coalesced-waiter accounting
    to share."""
    import json

    try:
        return json.loads(body).get("error")
    except (ValueError, AttributeError):
        return None


class Unavailable(DeconvError):
    """The dispatcher is shutting down: in-flight requests whose batch can
    no longer deliver results fail immediately instead of hanging to a
    full request-timeout 504 (serving/batcher.py:_execute_pipelined)."""

    status = 503
    code = "unavailable"


class DeadlineExpired(DeconvError):
    """The request's own ``x-deadline-ms`` budget lapsed (round 9
    deadline propagation): queued work whose caller has already given up
    is reaped at the queue-pop and pre-dispatch boundaries — an
    immediate 504 instead of dispatching dead work to the device."""

    status = 504
    code = "deadline_expired"


class BreakerOpen(DeconvError):
    """The device circuit breaker is open (round 9): N consecutive batch
    failures mean new dispatches are overwhelmingly likely to fail too,
    so requests fail fast with a Retry-After derived from the breaker's
    remaining cooldown instead of queueing onto a dead device."""

    status = 503
    code = "breaker_open"

    def __init__(self, message: str, retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class JobQueueFull(DeconvError):
    """The async job queue is at capacity (round 11): admitting more
    submissions would only let them rot past their deadlines, so the
    submit 429s with a ``Retry-After`` derived from the queue depth and
    the EWMA job cost (the PR 5 lane cost signal)."""

    status = 429
    code = "job_queue_full"

    def __init__(self, message: str, retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class JobNotFound(DeconvError):
    """No such job id: never submitted, or compacted out after its
    retention window (round 11 job subsystem)."""

    status = 404
    code = "job_not_found"


class TenantOverQuota(DeconvError):
    """A tenant exhausted one of its QoS budgets (round 13,
    serving/qos.py): the device-time token bucket, the in-flight cap,
    or the async-job queue-depth budget.  429 with a ``Retry-After``
    derived from the bucket's actual refill rate — actionable backoff,
    not a magic constant — and the tenant name in the payload so a
    multi-tenant client library can tell WHOSE budget it hit."""

    status = 429
    code = "tenant_over_quota"

    def __init__(
        self,
        message: str,
        retry_after_s: float | None = None,
        tenant: str | None = None,
    ):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.tenant = tenant


def retry_after_value(retry_after_s: float | None) -> str | None:
    """The ONE formatter for ``Retry-After`` headers (round 13
    satellite): integer seconds, never below 1 (RFC 9110 delta-seconds —
    a fractional or zero value is either invalid or an instant-retry
    invitation).  Every site that emits the header — ``Overloaded``
    sheds, ``BreakerOpen`` fail-fasts, ``JobQueueFull``/
    ``TenantOverQuota`` 429s — formats through here, so the contract
    cannot drift per call site."""
    if not retry_after_s or retry_after_s <= 0:
        return None
    import math

    return str(max(1, math.ceil(retry_after_s)))


class BackendUnavailable(DeconvError):
    """The fleet router (round 14, serving/fleet.py) could not reach a
    backend for this request: the ring is empty (every backend ejected/
    draining), or the key's owner AND its failover neighbour both
    infra-failed.  502 — the gateway speaking about its upstream, as
    distinct from a backend's own 503 backpressure (which passes
    through the router untouched).  Carries a Retry-After derived from
    the ejection cooldown: by then the half-open probe has either
    re-admitted a backend or the fleet is genuinely down."""

    status = 502
    code = "backend_unavailable"

    def __init__(self, message: str, retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class UndurableWrite(DeconvError):
    """A fail-loud persistence surface could not make a pre-ack write
    durable (round 24, serving/durable.py): a job submit whose journal
    append cannot fsync, a registration whose membership persist fails.
    Answering 202/200 would acknowledge work the server cannot promise
    to remember across a crash, so the request 503s with a Retry-After
    instead — the disk fault is the server's problem, retried work is
    the client's contribution to surviving it."""

    status = 503
    code = "undurable_write"

    def __init__(self, message: str, retry_after_s: float | None = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class FaultInjected(DeconvError):
    """An armed fault-injection site fired (serving/faults.py).  Its own
    taxonomy code so a chaos run's error budget can split EXPECTED
    failures (this, breaker_open, unavailable, deadline_expired) from
    collateral ones — the split tools/loopback_load.py --chaos reports."""

    status = 500
    code = "fault_injected"
