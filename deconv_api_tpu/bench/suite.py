"""The five BASELINE benchmark configs (BASELINE.md / driver BASELINE.json).

Each config returns a flat dict of measurements.  Timing methodology matches
bench.py: device work is synchronized by fetching a scalar checksum reduced
from the outputs (block_until_ready is unreliable over the remote tunnel),
and inputs vary per iteration to defeat content-addressed result caching.

| # | config                                               | function        |
|---|------------------------------------------------------|-----------------|
| 1 | VGG16 block5_conv1 single-image deconv + PSNR parity | config1_single  |
| 2 | VGG16 all-conv-layers sweep, batch 8                 | config2_sweep   |
| 3 | DeepDream InceptionV3 mixed3-5, 10 octaves           | config3_dream   |
| 4 | ResNet50 deconv backbone (conv_transpose, no switches)| config4_resnet |
| 5 | 256-concurrent-request serving load                  | config5_load    |
| 6 | ResNet50 all-layers sweep (DAG engine, r5)           | config6_resnet_sweep |

The reference itself can run none of these as written (no batching, no
InceptionV3/ResNet50, no concurrency > 1 — SURVEY §2.2.5, §0.2); its
structural costs are catalogued in BASELINE.md instead of numbers.
"""

from __future__ import annotations

import time
from typing import Callable


def tree_checksum(out):
    """Scalar fp32 sum over every output leaf — the sync primitive: it
    cannot be produced without executing the whole program.  The ONE
    definition shared by the suite and bench.py (three drifting copies
    would let the 'sync' row tags describe incomparable quantities)."""
    import jax
    import jax.numpy as jnp

    return sum(
        jnp.sum(leaf.astype(jnp.float32))
        for leaf in jax.tree_util.tree_leaves(out)
    )


def _checksum_fn():
    import jax

    return jax.jit(tree_checksum)


def _timed(fn, batches, checksum) -> float:
    """Seconds per call, checksum-synchronized, inputs varying per call."""
    sums = [checksum(fn(b)) for b in batches]  # warm from caller
    t0 = time.perf_counter()
    sums = [checksum(fn(b)) for b in batches]
    vals = [float(s) for s in sums]
    dt = time.perf_counter() - t0
    assert all(v == v for v in vals)
    return dt / len(batches)


def _stream_sync() -> bool:
    """DECONV_SUITE_STREAM_SYNC=1 switches the throughput configs (2, 4)
    to bench.py's sync methodology: checksum reduced INSIDE the measured
    program (one dispatch per call instead of two) and ONE trailing fetch
    inside the timer.  _timed's per-call fetch charges a full tunnel RTT
    (~71 ms — BASELINE.md tunnel anatomy) plus a second program dispatch
    to every iteration, which a local-PCIe deployment would not pay —
    measured 2026-07-31, the overhead understated config 4 by ~11x
    (20.4 ms/batch device time under 228.3 ms percall) and config 2 by
    ~5x.  Default ON since then; rows record which form produced them,
    and DECONV_SUITE_STREAM_SYNC=0 restores the round-2/3 form."""
    import os

    return os.environ.get("DECONV_SUITE_STREAM_SYNC", "1") != "0"


def _timed_stream(step, batches) -> float:
    """Seconds per call for a `step` whose returned scalar is computed
    inside the measured program: dispatch every call in order, fetch one
    trailing checksum inside the timer (covers all executions plus a
    single RTT), validate the rest after the timer stops."""
    sums = [step(b) for b in batches]  # warm
    for s in sums:
        float(s)
    t0 = time.perf_counter()
    sums = [step(b) for b in batches]
    last = float(sums[-1])
    dt = time.perf_counter() - t0
    vals = [float(s) for s in sums[:-1]] + [last]
    assert all(v == v for v in vals)
    return dt / len(batches)


def _timed_either(fn, params, batches, checksum) -> tuple[float, str]:
    """(seconds per call, sync tag) under the configured sync form —
    the one branch shared by the throughput configs (2, 4)."""
    if _stream_sync():
        import jax

        step = jax.jit(lambda p, b: tree_checksum(fn(p, b)))
        return _timed_stream(lambda b: step(params, b), batches), "stream-fused"
    return _timed(lambda b: fn(params, b), batches, checksum), "percall"


def config1_single(iters: int = 10) -> dict:
    """Single-image VGG16 block5_conv1 deconv: latency + PSNR vs the
    NumPy oracle (the reference's algorithm, reimplemented fp64)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deconv_api_tpu.engine import get_visualizer
    from deconv_api_tpu.models.vgg16 import vgg16_init
    from deconv_api_tpu.serving.codec import deprocess_image

    spec, params = vgg16_init()
    fn = get_visualizer(
        spec, "block5_conv1", 8, "all", True, backward_dtype="bfloat16"
    )
    checksum = _checksum_fn()
    images = [
        jax.random.normal(jax.random.PRNGKey(i), (224, 224, 3)) * 30.0
        for i in range(iters)
    ]
    latency_s = _timed(lambda im: fn(params, im), images, checksum)

    # Per-fetch RTT baseline measured the same way in the same session: a
    # trivial program's "latency" is pure host<->device round trip (~71 ms
    # over the axon tunnel, ~0 on local PCIe — BASELINE.md tunnel anatomy),
    # so the row can report how much of the single-request latency is
    # transport rather than device work.
    triv = jax.jit(lambda im: im[0, 0, 0] + 1.0)
    rtt_s = _timed(lambda im: triv(im), images, checksum)

    # PSNR parity on a small stack vs tests/reference_numpy.py (fp64).  The
    # oracle needs minutes for full VGG16 at 224; parity at depth is covered
    # by tests/test_engine_parity.py on reduced specs, so here we measure
    # the uint8 PSNR of the mixed-precision path against the exact fp32
    # engine — the quantity the serving path actually degrades.
    exact = get_visualizer(spec, "block5_conv1", 8, "all", True)
    o_exact = exact(params, images[0])["block5_conv1"]
    o_mixed = fn(params, images[0])["block5_conv1"]
    a = np.stack([deprocess_image(np.asarray(x, np.float64)) for x in o_exact["images"]])
    b = np.stack([deprocess_image(np.asarray(x, np.float64)) for x in o_mixed["images"]])
    mse = float(np.mean((a.astype(np.float64) - b.astype(np.float64)) ** 2))
    psnr = 10 * np.log10(255.0**2 / max(mse, 1e-12))
    return {
        "config": 1,
        "latency_ms": round(latency_s * 1e3, 2),
        "fetch_rtt_floor_ms": round(rtt_s * 1e3, 2),
        "device_latency_ms_est": round(max(0.0, latency_s - rtt_s) * 1e3, 2),
        "images_per_sec": round(1.0 / latency_s, 2),
        "psnr_mixed_vs_fp32_db": round(psnr, 1),
    }


def config2_sweep(iters: int = 5) -> dict:
    """All-conv-layers sweep from block5_conv1 down, batch 8 — the
    reference's always-on behaviour (SURVEY §2.2.3), done deliberately."""
    import jax

    from deconv_api_tpu.engine import get_visualizer
    from deconv_api_tpu.models.vgg16 import vgg16_init

    spec, params = vgg16_init()
    fn = get_visualizer(
        spec, "block5_conv1", 8, "all", True,
        sweep=True, batched=True, backward_dtype="bfloat16",
    )
    checksum = _checksum_fn()
    batches = [
        jax.random.normal(jax.random.PRNGKey(i), (8, 224, 224, 3))
        for i in range(iters)
    ]
    # Count projected layers from the visualizer itself (the sweep projects
    # every conv AND pool entry from block5_conv1 down — 15 for VGG16, not
    # the 13 conv layers alone).
    layers_projected = len(jax.eval_shape(fn, params, batches[0]))
    per_batch_s, sync = _timed_either(fn, params, batches, checksum)
    return {
        "config": 2,
        "batch": 8,
        "layers_projected": layers_projected,
        "sync": sync,
        "batch_latency_ms": round(per_batch_s * 1e3, 1),
        "images_per_sec": round(8 / per_batch_s, 2),
    }


def config3_dream(iters: int = 3) -> dict:
    """InceptionV3 mixed3-mixed5 DeepDream, 10 octaves x 10 steps."""
    import jax
    import numpy as np

    from deconv_api_tpu.engine import deepdream
    from deconv_api_tpu.models.inception_v3 import (
        inception_v3_forward,
        inception_v3_init,
    )

    params = inception_v3_init(jax.random.PRNGKey(0))
    layers = ("mixed3", "mixed4", "mixed5")
    img = np.asarray(
        jax.random.uniform(jax.random.PRNGKey(1), (299, 299, 3)) * 2 - 1
    )
    # warm: compiles one executable per octave shape
    out, loss = deepdream(
        inception_v3_forward, params, img, layers=layers,
        steps_per_octave=10, num_octaves=10, min_size=75,
    )
    assert np.isfinite(float(loss))
    t0 = time.perf_counter()
    for i in range(iters):
        out, loss = deepdream(
            inception_v3_forward, params, img + i * 1e-4, layers=layers,
            steps_per_octave=10, num_octaves=10, min_size=75,
        )
        float(loss)
        np.asarray(out[:1, :1])  # force materialisation
    dt = (time.perf_counter() - t0) / iters
    return {
        "config": 3,
        "octaves": 10,
        "steps_per_octave": 10,
        "dream_latency_s": round(dt, 2),
        "dreams_per_min": round(60 / dt, 1),
    }


def config4_resnet(iters: int = 10) -> dict:
    """ResNet50 deconv backbone: strided-conv transpose path, no switches."""
    import jax

    from deconv_api_tpu.engine import autodeconv_visualizer
    from deconv_api_tpu.models.resnet50 import resnet50_forward, resnet50_init

    params = resnet50_init(jax.random.PRNGKey(0))
    single = autodeconv_visualizer(resnet50_forward, "conv4_block6_out", 8, "all")
    fn = jax.jit(jax.vmap(single, in_axes=(None, 0)))
    checksum = _checksum_fn()
    batch = 8
    batches = [
        jax.random.normal(jax.random.PRNGKey(i), (batch, 224, 224, 3))
        for i in range(iters)
    ]
    per_batch_s, sync = _timed_either(fn, params, batches, checksum)
    return {
        "config": 4,
        "batch": batch,
        "layer": "conv4_block6_out",
        "sync": sync,
        "batch_latency_ms": round(per_batch_s * 1e3, 1),
        "images_per_sec": round(batch / per_batch_s, 2),
    }


def config6_resnet_sweep(iters: int = 3) -> dict:
    """ResNet50 all-layers sweep (DAG engine, r5): every projectable layer
    from conv4_block6_out down in one program — the reference's signature
    always-on behaviour (app/deepdream.py:441-474) on a topology it could
    never express.  One shared forward, per-layer vjp seeds."""
    import jax

    from deconv_api_tpu.serving.models import REGISTRY

    bundle = REGISTRY["resnet50"]()
    layer = "conv4_block6_out"
    fn = bundle.batched_visualizer(layer, "all", 8, sweep=True)
    checksum = _checksum_fn()
    batch = 4
    batches = [
        jax.random.normal(jax.random.PRNGKey(i), (batch, 224, 224, 3))
        for i in range(iters)
    ]
    layers_projected = len(jax.eval_shape(fn, bundle.params, batches[0]))
    per_batch_s, sync = _timed_either(fn, bundle.params, batches, checksum)
    return {
        "config": 6,
        "batch": batch,
        "layer": layer,
        "layers_projected": layers_projected,
        "sync": sync,
        "batch_latency_ms": round(per_batch_s * 1e3, 1),
        "images_per_sec": round(batch / per_batch_s, 2),
    }


def config5_load(n_requests: int = 256, concurrency: int = 64) -> dict:
    """Serving load: concurrent POST / requests against a live server
    (in-process, real HTTP over loopback), exercising the batching
    dispatcher end-to-end.  On multi-chip meshes the same dispatcher runs
    dp-sharded (parallel/batch.py; validated by dryrun_multichip)."""
    import asyncio
    import base64
    import io

    import numpy as np
    from PIL import Image

    from deconv_api_tpu.config import ServerConfig
    from deconv_api_tpu.serving.app import DeconvService

    rng = np.random.default_rng(0)
    uris = []
    for _ in range(8):
        img = Image.fromarray(rng.integers(0, 255, (224, 224, 3), np.uint8), "RGB")
        buf = io.BytesIO()
        img.save(buf, "JPEG")
        uris.append(
            "data:image/jpeg;base64," + base64.b64encode(buf.getvalue()).decode()
        )

    # from_env so serving knobs under test (pipeline_depth, warmup,
    # shedding) can be A/B'd via DECONV_* without editing the harness; the
    # three fixed overrides keep rows comparable across rounds.
    cfg = ServerConfig.from_env(max_batch=32, batch_window_ms=5.0, port=0)
    service = DeconvService(cfg)

    async def drive():
        import urllib.parse

        port = await service.start(host="127.0.0.1", port=0)
        await asyncio.to_thread(service.warmup)
        sem = asyncio.Semaphore(concurrency)
        latencies: list[float] = []

        async def one(i: int):
            body = urllib.parse.urlencode(
                {"file": uris[i % len(uris)], "layer": "block5_conv1"}
            ).encode()
            async with sem:
                t0 = time.perf_counter()
                reader, writer = await asyncio.open_connection("127.0.0.1", port)
                req = (
                    b"POST / HTTP/1.1\r\nHost: x\r\nContent-Type: "
                    b"application/x-www-form-urlencoded\r\nContent-Length: "
                    + str(len(body)).encode()
                    + b"\r\nConnection: close\r\n\r\n"
                    + body
                )
                writer.write(req)
                await writer.drain()
                raw = await reader.read()
                writer.close()
                latencies.append(time.perf_counter() - t0)
                assert b" 200 " in raw.split(b"\r\n", 1)[0], raw[:80]

        t0 = time.perf_counter()
        await asyncio.gather(*(one(i) for i in range(n_requests)))
        wall = time.perf_counter() - t0
        # server-side attribution BEFORE stop(): batch sizes, per-batch
        # cadence, queue wait and decode/compute/encode stage times — the
        # breakdown that says whether the wall clock went to the device,
        # the queue, or the tunnel (VERDICT r3 item 2's "written
        # attribution of exactly where the time goes")
        snap = service.metrics.snapshot()
        await service.stop()
        lat = sorted(latencies)
        return {
            "config": 5,
            "requests": n_requests,
            "concurrency": concurrency,
            "pipeline_depth": cfg.pipeline_depth,
            "wall_s": round(wall, 2),
            "requests_per_sec": round(n_requests / wall, 1),
            "p50_ms": round(lat[len(lat) // 2] * 1e3, 1),
            "p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 1),
            "server": {
                "batches_total": snap["batches_total"],
                "batch_size_p50": round(snap["batch_size_p50"], 1),
                "batch_cadence_p50_ms": round(
                    snap["batch_cadence_p50_s"] * 1e3, 1
                ),
                "queue_wait_p50_ms": round(snap["queue_wait_p50_s"] * 1e3, 1),
                "stages_p50_ms": {
                    k: round(v["p50_s"] * 1e3, 1)
                    for k, v in snap["stages"].items()
                },
            },
        }

    return asyncio.run(drive())


CONFIGS: dict[int, Callable[[], dict]] = {
    1: config1_single,
    2: config2_sweep,
    3: config3_dream,
    4: config4_resnet,
    5: config5_load,
    6: config6_resnet_sweep,
}


def run_config(n: int) -> dict:
    return CONFIGS[n]()
