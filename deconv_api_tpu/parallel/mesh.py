"""Mesh construction and sharding rules.

One honest fact drives the layout (SURVEY §2.4): every model in the zoo
fits on a single TPU core, so serving scales by **data parallelism** over
cores, and training additionally shards parameters over a **tensor** axis.
Shardings are expressed as `NamedSharding` annotations; XLA/GSPMD inserts
the ICI collectives.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    shape: tuple[int, ...] | None = None,
    axis_names: tuple[str, ...] = ("dp", "tp"),
    devices=None,
) -> Mesh:
    """Build a Mesh over the available devices.

    Default shape: all devices on ``dp``, 1 on ``tp`` — the serving layout.
    For training, pass e.g. ``shape=(n//2, 2)``.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {shape} != device count {len(devices)}")
    arr = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(arr, axis_names)


def make_pod_mesh(
    hosts: int,
    local_devices: int,
    model_axis: int = 1,
    axis_names: tuple[str, str] = ("batch", "model"),
    devices=None,
) -> Mesh:
    """Build the pod tier's global 2-D ``(batch × model)`` mesh spanning
    every cooperating process's devices.

    ``hosts × local_devices`` is the global device count (after
    ``jax.distributed`` initialisation, ``jax.devices()`` is already the
    global list in process-major order — host 0's chips first).  The mesh
    is ``(total // model_axis, model_axis)``: batch parallelism over rows,
    optional model parallelism over columns.  The device matrix is a plain
    row-major reshape of the global list so every process constructs the
    IDENTICAL mesh without communication — a prerequisite for the
    multi-controller SPMD contract (all processes must launch the same
    sharded program over the same mesh).

    Every non-divisible shape is a loud config error, never a truncation.
    """
    if hosts < 1:
        raise ValueError(f"pod needs at least 1 host, got hosts={hosts}")
    if local_devices < 1:
        raise ValueError(
            f"pod needs at least 1 device per host, got local_devices={local_devices}"
        )
    if model_axis < 1:
        raise ValueError(f"pod model axis must be >= 1, got {model_axis}")
    total = hosts * local_devices
    if total % model_axis != 0:
        raise ValueError(
            f"pod mesh: model_axis={model_axis} does not divide the global "
            f"device count {total} ({hosts} hosts x {local_devices} devices) "
            "— pick a model axis that divides hosts*local_devices"
        )
    batch = total // model_axis
    devices = list(jax.devices()) if devices is None else list(devices)
    if len(devices) != total:
        raise ValueError(
            f"pod mesh expects {total} global devices "
            f"({hosts} hosts x {local_devices} each), jax reports "
            f"{len(devices)} — check --xla_force_host_platform_device_count "
            "and that every process joined jax.distributed"
        )
    arr = np.asarray(devices, dtype=object).reshape(batch, model_axis)
    return Mesh(arr, axis_names)


def validate_parallel_layout(
    mesh_shape: tuple[int, ...] | None,
    serve_lanes: str | int,
    pod_hosts: int = 0,
) -> None:
    """Boot-time mutual-exclusion check across the three parallel layouts.

    The rule the lanes docstring states — a whole-pool mesh and executor
    lanes cannot coexist — is enforced HERE, from config validation, so a
    bad combination dies at boot with a config error instead of surfacing
    as a lane-resolution ValueError deep in service construction.  The pod
    tier joins the same exclusion: a pod already owns every global device
    as one ``(batch × model)`` mesh, so neither a single-host ``mesh_shape``
    nor explicit lanes may be stacked on top.

    Pure argument checks — no jax import, callable from ``config.py``.
    """
    mesh_set = bool(mesh_shape)
    lanes_explicit = str(serve_lanes).strip().lower() not in ("auto", "", "0", "1", "off")
    pod_set = pod_hosts > 1
    if mesh_set and lanes_explicit:
        raise ValueError(
            f"mesh_shape={tuple(mesh_shape)} and serve_lanes={serve_lanes!r} are "
            "mutually exclusive: the whole-pool mesh already spans every "
            "device; drop one of DECONV_MESH_SHAPE / DECONV_SERVE_LANES"
        )
    if pod_set and mesh_set:
        raise ValueError(
            f"pod_hosts={pod_hosts} and mesh_shape={tuple(mesh_shape)} are "
            "mutually exclusive: the pod constructs its own global "
            "(batch x model) mesh over every host's devices; drop "
            "DECONV_MESH_SHAPE"
        )
    if pod_set and lanes_explicit:
        raise ValueError(
            f"pod_hosts={pod_hosts} and serve_lanes={serve_lanes!r} are "
            "mutually exclusive: the pod's global mesh owns every device, "
            "lanes would double-subscribe chips; drop DECONV_SERVE_LANES"
        )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str | None = None) -> NamedSharding:
    """Shard the leading (batch) axis over the data-parallel mesh axis.

    Default axis: ``dp`` when the mesh has one (the single-host serving
    layout), else the mesh's FIRST axis — the pod tier names its axes
    ``(batch, model)`` and the leading axis is the data-parallel one in
    both conventions."""
    if axis is None:
        axis = "dp" if "dp" in mesh.axis_names else mesh.axis_names[0]
    return NamedSharding(mesh, P(axis))


def param_shardings(params, mesh: Mesh, axis: str = "tp"):
    """Tensor-parallel parameter shardings as ONE tree-mapped rule: every
    array leaf shards its trailing (output-channel / feature) axis over
    ``axis`` when divisible — conv kernels their output channels, dense
    kernels their output features, biases and BN vectors likewise; any
    leaf whose trailing dim doesn't divide the axis size (or a scalar)
    stays replicated.  Generic over ANY params pytree: the sequential
    specs' 2-level dicts and the DAG families' nested block dicts alike
    (VERDICT r4 item 4).

    Returns a pytree of NamedSharding congruent with `params`.
    """
    tp = mesh.shape[axis]

    def shard_leaf(leaf):
        if tp > 1 and getattr(leaf, "ndim", 0) >= 1 and leaf.shape[-1] % tp == 0:
            return NamedSharding(mesh, P(*(None,) * (leaf.ndim - 1) + (axis,)))
        return NamedSharding(mesh, P())

    return jax.tree.map(shard_leaf, params)


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
) -> dict:
    """Multi-host entry point: bring up the JAX distributed runtime so a
    mesh can span hosts (the reference's NCCL/MPI analog is this one call
    plus GSPMD — XLA then routes collectives over ICI within a slice and
    DCN across slices; no explicit communication API exists to build,
    SURVEY §2.4).

    A no-argument call relies on jax's cluster auto-detection (TPU pods,
    well-known schedulers) and RAISES off-cluster — single-process runs
    simply never call this.  Explicit arguments are forwarded verbatim;
    none is ever silently dropped.  Idempotent: once a distributed client
    exists, further calls are no-ops.  After it returns, ``jax.devices()``
    is the GLOBAL device list and ``make_mesh()``'s default spans every
    process's chips.

    Returns {"process_index", "process_count", "global_devices",
    "local_devices"} for logging/assertions.
    """
    # Idempotency must be probed WITHOUT touching the backend:
    # jax.process_count() would itself initialise XLA, after which
    # jax.distributed.initialize() hard-errors.  The distributed client
    # handle is the one state that answers without side effects.
    try:
        from jax._src import distributed as _dist

        already = _dist.global_state.client is not None
    except Exception:  # noqa: BLE001 — private API moved; assume fresh
        already = False
    if not already:
        kwargs = {}
        if coordinator_address is not None:
            kwargs["coordinator_address"] = coordinator_address
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        if process_id is not None:
            kwargs["process_id"] = process_id
        if local_device_ids is not None:
            kwargs["local_device_ids"] = local_device_ids
        jax.distributed.initialize(**kwargs)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
    }
