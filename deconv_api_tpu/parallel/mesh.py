"""Mesh construction and sharding rules.

One honest fact drives the layout (SURVEY §2.4): every model in the zoo
fits on a single TPU core, so serving scales by **data parallelism** over
cores, and training additionally shards parameters over a **tensor** axis.
Shardings are expressed as `NamedSharding` annotations; XLA/GSPMD inserts
the ICI collectives.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    shape: tuple[int, ...] | None = None,
    axis_names: tuple[str, ...] = ("dp", "tp"),
    devices=None,
) -> Mesh:
    """Build a Mesh over the available devices.

    Default shape: all devices on ``dp``, 1 on ``tp`` — the serving layout.
    For training, pass e.g. ``shape=(n//2, 2)``.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {shape} != device count {len(devices)}")
    arr = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(arr, axis_names)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard the leading (batch) axis over the data-parallel mesh axis."""
    return NamedSharding(mesh, P(axis))


def param_shardings(params, mesh: Mesh, axis: str = "tp"):
    """Tensor-parallel parameter shardings as ONE tree-mapped rule: every
    array leaf shards its trailing (output-channel / feature) axis over
    ``axis`` when divisible — conv kernels their output channels, dense
    kernels their output features, biases and BN vectors likewise; any
    leaf whose trailing dim doesn't divide the axis size (or a scalar)
    stays replicated.  Generic over ANY params pytree: the sequential
    specs' 2-level dicts and the DAG families' nested block dicts alike
    (VERDICT r4 item 4).

    Returns a pytree of NamedSharding congruent with `params`.
    """
    tp = mesh.shape[axis]

    def shard_leaf(leaf):
        if tp > 1 and getattr(leaf, "ndim", 0) >= 1 and leaf.shape[-1] % tp == 0:
            return NamedSharding(mesh, P(*(None,) * (leaf.ndim - 1) + (axis,)))
        return NamedSharding(mesh, P())

    return jax.tree.map(shard_leaf, params)


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
) -> dict:
    """Multi-host entry point: bring up the JAX distributed runtime so a
    mesh can span hosts (the reference's NCCL/MPI analog is this one call
    plus GSPMD — XLA then routes collectives over ICI within a slice and
    DCN across slices; no explicit communication API exists to build,
    SURVEY §2.4).

    A no-argument call relies on jax's cluster auto-detection (TPU pods,
    well-known schedulers) and RAISES off-cluster — single-process runs
    simply never call this.  Explicit arguments are forwarded verbatim;
    none is ever silently dropped.  Idempotent: once a distributed client
    exists, further calls are no-ops.  After it returns, ``jax.devices()``
    is the GLOBAL device list and ``make_mesh()``'s default spans every
    process's chips.

    Returns {"process_index", "process_count", "global_devices",
    "local_devices"} for logging/assertions.
    """
    # Idempotency must be probed WITHOUT touching the backend:
    # jax.process_count() would itself initialise XLA, after which
    # jax.distributed.initialize() hard-errors.  The distributed client
    # handle is the one state that answers without side effects.
    try:
        from jax._src import distributed as _dist

        already = _dist.global_state.client is not None
    except Exception:  # noqa: BLE001 — private API moved; assume fresh
        already = False
    if not already:
        kwargs = {}
        if coordinator_address is not None:
            kwargs["coordinator_address"] = coordinator_address
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        if process_id is not None:
            kwargs["process_id"] = process_id
        if local_device_ids is not None:
            kwargs["local_device_ids"] = local_device_ids
        jax.distributed.initialize(**kwargs)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
    }
