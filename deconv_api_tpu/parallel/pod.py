"""Pod tier: multi-host sharded execution joining the fleet as ONE member.

The engine shards across chips on one host (``mesh_shape``, lanes) and the
fleet scales by *replicating* models per backend — so before this module no
request class could exceed one host's devices or HBM.  The pod tier breaks
that ceiling: N cooperating processes (one **coordinator** + N-1
**followers**) bring up ``jax.distributed``, build ONE global
``(batch × model)`` mesh over every host's devices (parallel/mesh.py
``make_pod_mesh``), and execute the engine's batched programs as ONE
sharded XLA program spanning hosts — GSPMD inserts the cross-host
collectives, exactly the SNIPPETS [3] claim that the same application code
scales from one host to a pod.

The serving layer keeps a clean split between two kinds of parallel:

- **request parallel** — the fleet router spreads request classes across
  members (hash ring), and lanes spread keys across chips WITHIN a member;
- **program parallel** — the pod mesh spreads ONE program across hosts.

A pod appears in the fleet as ONE self-announcing member (the
coordinator), advertising ``capacity=N_hosts`` for weighted ring
placement.  Followers never face the router: they run a thin dispatch
loop (``pod-worker`` CLI role) mirroring the coordinator's dispatches.

## The multi-controller SPMD contract

JAX's multi-process model is multi-controller: EVERY process must launch
the SAME sharded program in the SAME order, or the runtime deadlocks in a
collective.  The coordinator therefore serializes all pod dispatches
under one lock and feeds followers a **descriptor** (the exact
``batched_visualizer`` cache-key inputs plus the staged batch bytes) over
a plain TCP control channel — deliberately NOT a jax collective, so a
dead follower surfaces as a socket EOF within heartbeat seconds instead
of a wedged all-gather.  Both sides resolve the descriptor through the
same ``resolve_pod_program`` so the programs cannot drift.

## Failure model: degrade loudly, never wedge

Any follower loss (EOF, send failure, failed DONE ack) flips the pod to
**degraded**: gauges ``pod_hosts_connected``/``pod_degraded`` move, a
structured event fires, the ``on_degrade`` callback lets the serving
layer fall back to single-host programs and re-announce capacity=1, and
every subsequent ``run()`` raises ``PodDegraded`` immediately.  The jax
distributed runtime itself is brought up with ``shutdown_on_destruction``
OFF and an effectively-infinite service heartbeat budget — the default
client TERMINATES the process when the coordination service notices a
dead peer, which is exactly the wedge/crash this layer exists to avoid;
real failure detection lives in the control channel (seconds, not
heartbeat-budget minutes).
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time
from typing import Any, Callable

import numpy as np

from deconv_api_tpu.utils import slog

_log = slog.get_logger("deconv.pod")

# Control-channel frame: 8-byte big-endian (header_len, payload_len)
# prefix, then a JSON header, then raw payload bytes (batch data).
_FRAME = struct.Struct(">II")
_MAX_HEADER = 1 << 20
_MAX_PAYLOAD = 1 << 31

PROTOCOL_VERSION = 1


class PodError(RuntimeError):
    """Any pod control-plane failure."""


class PodDegraded(PodError):
    """The pod has lost a follower and fallen back to single-host serving.

    Raised by ``PodCoordinator.run`` so an in-flight dispatch retries on
    the local path instead of blocking on a dead peer."""


def _send_msg(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    data = json.dumps(header, separators=(",", ":")).encode()
    sock.sendall(_FRAME.pack(len(data), len(payload)) + data + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("pod control channel closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> tuple[dict, bytes]:
    hlen, plen = _FRAME.unpack(_recv_exact(sock, _FRAME.size))
    if hlen > _MAX_HEADER or plen > _MAX_PAYLOAD:
        raise PodError(f"pod frame too large: header={hlen} payload={plen}")
    header = json.loads(_recv_exact(sock, hlen))
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


def init_pod_runtime(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    *,
    init_timeout_s: int = 120,
) -> dict:
    """Bring up the jax distributed runtime for a pod process.

    Must run BEFORE any jax computation (backend initialisation).  Uses
    the gloo CPU collectives implementation so the pod is provable on a
    CPU-only host; on real TPU pods the same call binds the TPU
    coordination path.

    Unlike plain ``jax.distributed.initialize``, the client is built with
    ``shutdown_on_destruction=False`` (a degraded coordinator must exit
    CLEANLY after follower loss — the default shutdown barrier aborts the
    process) and the coordination service's heartbeat budget is made
    effectively infinite (the default callback TERMINATES the process
    ~100 s after a peer dies; the pod control channel owns failure
    detection instead).  Falls back to the plain initialize if the
    private construction path moves under a future jax.

    Idempotent; returns {"process_index", "process_count",
    "global_devices", "local_devices"}.
    """
    import jax

    if num_processes < 2:
        raise ValueError(f"a pod needs >= 2 processes, got {num_processes}")
    if not (0 <= process_id < num_processes):
        raise ValueError(
            f"pod process_id {process_id} out of range [0, {num_processes})"
        )
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover — non-CPU backends need no gloo
        pass
    try:
        from jax._src.distributed import global_state
    except Exception:  # pragma: no cover — private API moved
        global_state = None
    if global_state is not None and global_state.client is not None:
        pass  # already initialised (idempotency, same probe as mesh.py)
    elif global_state is not None:
        try:
            from jax._src.lib import xla_extension

            if process_id == 0:
                port = coordinator_address.rsplit(":", 1)[1]
                global_state.service = xla_extension.get_distributed_runtime_service(
                    f"[::]:{port}",
                    num_processes,
                    heartbeat_interval=10,
                    max_missing_heartbeats=10_000_000,
                )
            global_state.client = xla_extension.get_distributed_runtime_client(
                coordinator_address,
                process_id,
                init_timeout=init_timeout_s,
                heartbeat_interval=10,
                max_missing_heartbeats=10_000_000,
                shutdown_on_destruction=False,
                use_compression=True,
            )
            global_state.client.connect()
            global_state.process_id = process_id
            global_state.num_processes = num_processes
            global_state.coordinator_address = coordinator_address
        except Exception:
            # private construction path moved — plain initialize keeps the
            # pod functional (at the cost of the noisy exit documented in
            # docs/OPERATIONS.md)
            global_state = None
    if global_state is None:
        from deconv_api_tpu.parallel.mesh import init_distributed

        init_distributed(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
    }


def global_batch(mesh, batch: np.ndarray):
    """The full host batch -> a global array sharded over the mesh's
    leading (batch) axis.  Every process holds the SAME full host copy
    (the coordinator broadcast it) and supplies its addressable shards by
    slicing — no collective, so a degraded peer cannot wedge staging."""
    import jax

    from deconv_api_tpu.parallel.mesh import batch_sharding

    sh = batch_sharding(mesh)
    return jax.make_array_from_callback(batch.shape, sh, lambda idx: batch[idx])


def replicate_tree(mesh, tree):
    """A host params pytree -> fully-replicated global arrays over the pod
    mesh.  Built ONCE per model at boot on every process (each supplies
    its local replicas by copying its own host tree — identical across
    processes by the seeded-init/checkpoint-load contract)."""
    import jax

    from deconv_api_tpu.parallel.mesh import replicated

    rep = replicated(mesh)

    def one(leaf):
        arr = np.asarray(leaf)
        return jax.make_array_from_callback(arr.shape, rep, lambda idx: arr[idx])

    return jax.tree.map(one, tree)


def resolve_pod_program(bundle, cfg, desc: dict):
    """Descriptor -> the jitted sharded program, identically on BOTH
    sides.  The descriptor carries exactly the per-request inputs of the
    ``batched_visualizer`` cache key; process-constant policy comes from
    the (identical) config.  This shared resolution is what enforces the
    multi-controller contract — coordinator and follower cannot compile
    divergent programs from one dispatch."""
    quant = desc.get("quant")
    if quant is not None and not isinstance(quant, str):
        raise PodError(
            "pod dispatch requires a string quant policy (calibrated scale "
            "tuples are per-host state; run calibration off-pod)"
        )
    return bundle.batched_visualizer(
        desc["layer"],
        desc["mode"],
        int(desc["k"]),
        bool(cfg.bug_compat),
        cfg.backward_dtype or None,
        desc.get("post"),
        bool(desc.get("sweep", False)),
        donate=False,
        lane=0,
        lowc_kpack=cfg.lowc_kpack,
        quant=quant,
        fused_unpool=cfg.fused_unpool,
    )


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


class PodCoordinator:
    """Process 0's control plane: follower rendezvous, serialized
    dispatch broadcast, liveness, loud degrade.

    Lifecycle: ``start()`` binds the control port and blocks until all
    ``hosts - 1`` followers HELLO (they connect after building their own
    bundle, so the timeout budgets their boot); ``attach_mesh()`` pins
    the global mesh and flips the health gauges; ``run()`` broadcasts one
    descriptor + batch and executes the caller's runner under the
    dispatch lock; ``shutdown()`` sends SHUTDOWN to every follower so
    drains propagate."""

    def __init__(
        self,
        *,
        hosts: int,
        control_port: int,
        bind_host: str = "0.0.0.0",
        heartbeat_s: float = 2.0,
        metrics=None,
        on_degrade: Callable[[str], None] | None = None,
    ) -> None:
        if hosts < 2:
            raise ValueError(f"a pod needs >= 2 hosts, got {hosts}")
        self.hosts = int(hosts)
        self.control_port = int(control_port)
        self._bind_host = bind_host
        self._heartbeat_s = float(heartbeat_s)
        self._metrics = metrics
        self._on_degrade = on_degrade
        self.mesh = None
        self.degraded = False
        self.degrade_reason: str | None = None
        self._shutting_down = False
        self._lock = threading.RLock()  # THE pod dispatch serializer
        self._state_lock = threading.Lock()
        self._seq = 0
        self._listener: socket.socket | None = None
        self._followers: dict[int, socket.socket] = {}
        self._threads: list[threading.Thread] = []
        self.dispatches = 0

    # -- lifecycle ---------------------------------------------------

    def start(self, timeout_s: float = 120.0) -> None:
        """Accept all followers' HELLOs, then start reader + heartbeat
        threads.  Raises PodError if the pod does not assemble in time —
        boot fails loudly rather than serving a half pod."""
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self._bind_host, self.control_port))
        ls.listen(self.hosts)
        ls.settimeout(timeout_s)
        self._listener = ls
        deadline = time.monotonic() + timeout_s
        try:
            while len(self._followers) < self.hosts - 1:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise socket.timeout()
                ls.settimeout(remaining)
                conn, addr = ls.accept()
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                header, _ = _recv_msg(conn)
                if header.get("t") != "HELLO":
                    conn.close()
                    continue
                if header.get("v") != PROTOCOL_VERSION:
                    _send_msg(conn, {"t": "SHUTDOWN", "reason": "version"})
                    conn.close()
                    raise PodError(
                        f"pod follower protocol v{header.get('v')} != "
                        f"v{PROTOCOL_VERSION}"
                    )
                pid = int(header["process_id"])
                self._followers[pid] = conn
                slog.event(
                    _log, "pod_follower_joined", process_id=pid,
                    addr=f"{addr[0]}:{addr[1]}",
                    joined=len(self._followers), expected=self.hosts - 1,
                )
        except socket.timeout:
            self.close()
            raise PodError(
                f"pod rendezvous timed out after {timeout_s:.0f}s: "
                f"{len(self._followers)}/{self.hosts - 1} followers joined "
                f"on control port {self.control_port}"
            ) from None
        for pid, conn in self._followers.items():
            t = threading.Thread(
                target=self._reader, args=(pid, conn),
                name=f"pod-reader-{pid}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        hb = threading.Thread(
            target=self._heartbeat, name="pod-heartbeat", daemon=True
        )
        hb.start()
        self._threads.append(hb)
        self._set_gauges()

    def attach_mesh(self, mesh) -> None:
        self.mesh = mesh
        self._set_gauges()
        slog.event(
            _log, "pod_ready", hosts=self.hosts,
            mesh_shape=dict(mesh.shape), devices=mesh.devices.size,
        )

    @property
    def active(self) -> bool:
        return not self.degraded and self.mesh is not None

    def hosts_connected(self) -> int:
        """Coordinator itself + live followers — the /readyz number."""
        if self.degraded:
            return 1
        return 1 + len(self._followers)

    # -- dispatch ----------------------------------------------------

    def run(self, desc: dict, batch: np.ndarray, runner: Callable[[Any], Any]):
        """Broadcast one dispatch and execute it locally, serialized.

        ``desc`` is the program descriptor (resolve_pod_program inputs);
        ``batch`` the staged host batch (already cast to the forward
        dtype); ``runner`` receives the GLOBAL batch array and must
        launch the sharded program.  The lock orders broadcasts and local
        launches identically — the multi-controller contract."""
        with self._lock:
            if self.degraded:
                raise PodDegraded(self.degrade_reason or "pod degraded")
            t0 = time.perf_counter()
            self._seq += 1
            header = {
                "t": "DISPATCH",
                "seq": self._seq,
                "desc": desc,
                "shape": list(batch.shape),
                "dtype": str(batch.dtype),
            }
            payload = np.ascontiguousarray(batch).tobytes()
            for pid, conn in list(self._followers.items()):
                try:
                    _send_msg(conn, header, payload)
                except OSError as e:
                    self._degrade(f"send to follower {pid} failed: {e}")
                    raise PodDegraded(self.degrade_reason) from e
            t_cast = time.perf_counter()
            gx = global_batch(self.mesh, batch)
            try:
                import jax

                out = runner(gx)
                # force the launch HERE: a cross-host collective that
                # dies with a follower must fail inside this guard, not
                # later at materialise time where no fallback exists
                jax.block_until_ready(out)
            except PodDegraded:
                raise
            except Exception:
                # a peer died mid-collective: give the reader/heartbeat
                # thread a moment to flag the loss, then surface the
                # retryable degrade instead of the opaque runtime error
                deadline = time.monotonic() + 2.0
                while not self.degraded and time.monotonic() < deadline:
                    time.sleep(0.02)
                if self.degraded:
                    raise PodDegraded(self.degrade_reason) from None
                raise
            self.dispatches += 1
            if self._metrics is not None:
                self._metrics.inc_counter("pod_dispatches_total")
                self._metrics.observe_stage("pod_broadcast", t_cast - t0)
            return out

    # -- liveness / degrade ------------------------------------------

    def _reader(self, pid: int, conn: socket.socket) -> None:
        try:
            while True:
                header, _ = _recv_msg(conn)
                t = header.get("t")
                if t == "DONE":
                    if not header.get("ok", False):
                        self._degrade(
                            f"follower {pid} failed dispatch "
                            f"{header.get('seq')}: {header.get('error')}"
                        )
                        return
                    if self._metrics is not None:
                        self._metrics.inc_counter("pod_follower_acks_total")
                elif t == "PONG":
                    pass
        except (ConnectionError, OSError):
            if not self._shutting_down:
                self._degrade(f"follower {pid} connection lost")

    def _heartbeat(self) -> None:
        while not self._shutting_down and not self.degraded:
            time.sleep(self._heartbeat_s)
            with self._lock:
                if self._shutting_down or self.degraded:
                    return
                for pid, conn in list(self._followers.items()):
                    try:
                        _send_msg(conn, {"t": "PING"})
                    except OSError as e:
                        self._degrade(f"follower {pid} heartbeat failed: {e}")
                        return

    def _degrade(self, reason: str) -> None:
        with self._state_lock:
            if self.degraded or self._shutting_down:
                return
            self.degraded = True
            self.degrade_reason = reason
        slog.event(_log, "pod_degraded", level=logging.ERROR, reason=reason,
                   dispatches=self.dispatches)
        if self._metrics is not None:
            self._metrics.inc_counter("pod_follower_loss_total")
        self._set_gauges()
        for conn in self._followers.values():
            try:
                conn.close()
            except OSError:
                pass
        self._followers.clear()
        if self._on_degrade is not None:
            try:
                self._on_degrade(reason)
            except Exception:  # noqa: BLE001 — degrade must not re-raise
                _log.exception("pod on_degrade callback failed")

    def _set_gauges(self) -> None:
        if self._metrics is None:
            return
        self._metrics.set_gauge("pod_hosts_connected", self.hosts_connected())
        self._metrics.set_gauge(
            "pod_mesh_devices",
            0 if (self.degraded or self.mesh is None) else self.mesh.devices.size,
        )
        self._metrics.set_gauge("pod_degraded", 1.0 if self.degraded else 0.0)

    def close(self) -> None:
        self._shutting_down = True
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for conn in self._followers.values():
            try:
                conn.close()
            except OSError:
                pass
        self._followers.clear()

    def shutdown(self) -> None:
        """Coordinator drain: tell every follower to exit, then close.
        Part of the service stop path, so draining the pod member drains
        the whole pod."""
        with self._lock:
            self._shutting_down = True
            for pid, conn in list(self._followers.items()):
                try:
                    _send_msg(conn, {"t": "SHUTDOWN", "reason": "drain"})
                except OSError:
                    pass
        slog.event(_log, "pod_shutdown", followers=len(self._followers))
        self.close()


class PodFollower:
    """A follower's whole life: connect, HELLO, mirror dispatches.

    ``executor(desc, batch)`` must launch the SAME sharded program the
    coordinator launched (resolve_pod_program) and block until complete —
    the DONE ack is the coordinator's evidence this process is keeping up
    (an ok=False DONE degrades the pod loudly rather than desyncing)."""

    def __init__(
        self,
        coordinator_host: str,
        control_port: int,
        process_id: int,
        executor: Callable[[dict, np.ndarray], None],
        *,
        connect_timeout_s: float = 120.0,
    ) -> None:
        self.coordinator_host = coordinator_host
        self.control_port = int(control_port)
        self.process_id = int(process_id)
        self._executor = executor
        self._connect_timeout_s = connect_timeout_s

    def _connect(self) -> socket.socket:
        deadline = time.monotonic() + self._connect_timeout_s
        last: Exception | None = None
        while time.monotonic() < deadline:
            try:
                conn = socket.create_connection(
                    (self.coordinator_host, self.control_port), timeout=5.0
                )
                conn.settimeout(None)
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                _send_msg(
                    conn,
                    {
                        "t": "HELLO",
                        "v": PROTOCOL_VERSION,
                        "process_id": self.process_id,
                    },
                )
                return conn
            except OSError as e:
                last = e
                time.sleep(0.2)
        raise PodError(
            f"pod follower {self.process_id} could not reach coordinator "
            f"{self.coordinator_host}:{self.control_port}: {last}"
        )

    def run_forever(self) -> str:
        """Serve dispatches until SHUTDOWN ("drain") or coordinator loss
        ("lost").  Never raises on connection teardown — a follower exits
        quietly; the coordinator is the one that degrades loudly."""
        conn = self._connect()
        slog.event(
            _log, "pod_follower_connected",
            process_id=self.process_id,
            coordinator=f"{self.coordinator_host}:{self.control_port}",
        )
        try:
            while True:
                try:
                    header, payload = _recv_msg(conn)
                except (ConnectionError, OSError):
                    slog.event(
                        _log, "pod_coordinator_lost", level=logging.ERROR,
                        process_id=self.process_id,
                    )
                    return "lost"
                t = header.get("t")
                if t == "PING":
                    try:
                        _send_msg(conn, {"t": "PONG"})
                    except OSError:
                        return "lost"
                elif t == "SHUTDOWN":
                    slog.event(
                        _log, "pod_follower_shutdown",
                        process_id=self.process_id,
                        reason=header.get("reason"),
                    )
                    return "drain"
                elif t == "DISPATCH":
                    seq = header.get("seq")
                    t0 = time.perf_counter()
                    try:
                        batch = np.frombuffer(
                            payload, dtype=_np_dtype(header["dtype"])
                        ).reshape(header["shape"])
                        self._executor(header["desc"], batch)
                        done = {
                            "t": "DONE", "seq": seq, "ok": True,
                            "ms": round((time.perf_counter() - t0) * 1e3, 1),
                        }
                    except Exception as e:  # noqa: BLE001 — ack the failure
                        slog.event(
                            _log, "pod_follower_dispatch_failed",
                            level=logging.ERROR,
                            process_id=self.process_id, seq=seq, error=str(e),
                        )
                        done = {
                            "t": "DONE", "seq": seq, "ok": False,
                            "error": str(e)[:500],
                        }
                    try:
                        _send_msg(conn, done)
                    except OSError:
                        return "lost"
                    if not done["ok"]:
                        # a failed dispatch already degraded the pod on
                        # the coordinator; this process is out of sync
                        # and must not mirror further programs
                        return "failed"
        finally:
            try:
                conn.close()
            except OSError:
                pass


def make_follower_executor(bundle, cfg, mesh, global_params):
    """The standard follower executor: resolve the descriptor through the
    shared program resolution and launch it over the global batch,
    blocking until complete (the DONE ack contract)."""
    import jax

    def execute(desc: dict, batch: np.ndarray) -> None:
        fn = resolve_pod_program(bundle, cfg, desc)
        out = fn(global_params, global_batch(mesh, batch))
        jax.block_until_ready(out)

    return execute
