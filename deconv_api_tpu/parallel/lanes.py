"""Executor-lane topology: partition visible devices into serving lanes.

ROUND5.md closed the single-chip ledgers with one lever left: spreading
work across chips.  A **lane** is an independent execution stream with its
own device (or its own small dp mesh), its own copy of the model params,
and its own circuit-breaker state.  The serving batcher schedules each
collected batch onto the least-loaded lane (serving/batcher.py LanePool),
so batches for different (model, layer, mode) keys — and consecutive
batches for one key when pipeline_depth allows — execute concurrently on
different chips instead of serializing through one dispatch stream.

This module owns only the TOPOLOGY: how many lanes a config resolves to,
and which devices each lane gets.  Two shapes compose:

- ``serve_lanes`` == device count (the ``auto`` default on a multi-chip
  host): one whole device per lane — the many-small-mixed-key-batches
  regime the zipf loopback row measures.
- ``serve_lanes`` < device count: each lane gets an equal contiguous
  slice of devices as its own ``dp`` mesh, so big-batch keys still shard
  data-parallel WITHIN a lane while independent keys spread ACROSS lanes.

``mesh_shape`` (the whole-pool GSPMD mesh) and lanes are mutually
exclusive: a configured mesh keeps the single-stream dp-sharded path.
"""

from __future__ import annotations


def resolve_lane_count(
    serve_lanes: str | int,
    n_devices: int,
    mesh_active: bool = False,
) -> int:
    """How many executor lanes a config resolves to.

    ``auto`` (the default): one lane per visible device when no mesh is
    configured — multi-chip hosts scale out without a flag, single-chip
    hosts keep the exact single-stream path.  An explicit count must
    divide the device count evenly (equal lanes are what makes the
    least-loaded signal comparable across lanes); ``0``/``1``/``off``
    force the single-stream path.
    """
    if mesh_active:
        # the whole-pool dp mesh owns every device; lanes would double-
        # subscribe chips.  An explicit lane request on top is a config
        # error the caller surfaces, not a silent fallback.
        if str(serve_lanes) not in ("auto", "0", "1", "off"):
            raise ValueError(
                "serve_lanes and mesh_shape are mutually exclusive: the "
                "mesh already spans every device"
            )
        return 1
    raw = str(serve_lanes).strip().lower()
    if raw in ("auto", ""):
        return max(1, n_devices)
    if raw == "off":
        return 1
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"serve_lanes must be 'auto', 'off' or an integer, got "
            f"{serve_lanes!r}"
        ) from None
    if n <= 1:
        return 1
    if n > n_devices:
        raise ValueError(
            f"serve_lanes={n} needs {n} devices, have {n_devices}"
        )
    if n_devices % n != 0:
        raise ValueError(
            f"serve_lanes={n} must divide the device count ({n_devices}) "
            "evenly — unequal lanes would skew the least-loaded signal"
        )
    return n


def lane_placements(n_lanes: int, devices=None) -> list:
    """The device placement for each lane: a single Device when lanes map
    1:1 onto chips, or a ``dp`` Mesh over an equal contiguous slice when
    each lane spans several (lanes then compose with dp-sharding: the
    batcher spreads keys across lanes, GSPMD spreads each lane's batch
    across its slice).  Contiguous slices keep a lane's collectives on
    neighbouring chips (ICI locality on real TPU topologies)."""
    import jax

    devices = list(jax.devices()) if devices is None else list(devices)
    if n_lanes <= 0:
        raise ValueError(f"need at least one lane, got {n_lanes}")
    if len(devices) % n_lanes != 0:
        raise ValueError(
            f"{n_lanes} lanes cannot evenly split {len(devices)} devices"
        )
    per = len(devices) // n_lanes
    if per == 1:
        return devices[:n_lanes]
    from deconv_api_tpu.parallel.mesh import make_mesh

    return [
        make_mesh((per,), axis_names=("dp",), devices=devices[i * per : (i + 1) * per])
        for i in range(n_lanes)
    ]
