"""Data-parallel sharded execution of the deconv visualizer.

BASELINE config 5: 256 concurrent /deconv requests spread over a v5e-8.
The batched visualizer (engine/deconv.py, batched=True) is jitted with its
batch axis sharded over the mesh's ``dp`` axis and params replicated — XLA
partitions the program per-core with zero cross-core traffic in the hot
path (each image's projection is independent; the only collectives are the
initial param broadcast)."""

from __future__ import annotations

import jax

from deconv_api_tpu.engine import get_visualizer
from deconv_api_tpu.models.spec import ModelSpec
from deconv_api_tpu.parallel.mesh import batch_sharding, replicated


def shard_batched_fn(fn, mesh):
    """Wrap any ``fn(params, batch)`` whose outputs all carry a leading
    batch axis: params replicated, batch (in and out) sharded over ``dp``.

    This is THE serving sharding rule — both the standalone
    `sharded_visualizer` and the HTTP path (serving/models.py
    ModelBundle.batched_visualizer with a mesh) go through it, so the two
    cannot drift.  Per-call batch sizes must be a multiple of the dp axis
    size; the serving dispatcher rounds its buckets up to that multiple
    (serving/app.py:_bucket_for).

    Invariant for sweep callers: build the visualizer with
    ``sweep_chunk=0`` before sharding it.  The merged sweep's batch
    chunking (a single-chip OOM guard) reshapes the batch axis and runs
    lax.map over chunks — under dp sharding that serializes work GSPMD
    should spread across the mesh, and the per-device carry is already
    B/dp so the guard is unnecessary.  (serving/models.py and
    __graft_entry__.py both do this.)

    When the mesh spans processes (the pod tier), outputs are REPLICATED
    instead of batch-sharded: a batch-sharded output would leave each
    process holding only its addressable shards, and the coordinator's
    ``device_get`` would fail on the non-addressable remainder.  Fully
    replicating the outputs makes XLA emit one all-gather at program tail
    and every process materialises the complete result — the coordinator
    serves it, followers discard theirs (the cost of keeping the serving
    dispatch path process-count agnostic)."""
    spans_processes = any(
        d.process_index != jax.process_index() for d in mesh.devices.flat
    )
    out_sh = replicated(mesh) if spans_processes else batch_sharding(mesh)
    return jax.jit(
        fn,
        in_shardings=(replicated(mesh), batch_sharding(mesh)),
        out_shardings=out_sh,
    )


def sharded_visualizer(
    spec: ModelSpec,
    mesh,
    layer_name: str,
    top_k: int = 8,
    mode: str = "all",
    bug_compat: bool = True,
    backward_dtype: str | None = None,
):
    """Jitted ``fn(params, batch)`` with batch sharded over ``dp``."""
    fn = get_visualizer(
        spec, layer_name, top_k, mode, bug_compat, sweep=False, batched=True,
        backward_dtype=backward_dtype,
    )
    return shard_batched_fn(fn, mesh)
