"""Data-parallel sharded execution of the deconv visualizer.

BASELINE config 5: 256 concurrent /deconv requests spread over a v5e-8.
The batched visualizer (engine/deconv.py, batched=True) is jitted with its
batch axis sharded over the mesh's ``dp`` axis and params replicated — XLA
partitions the program per-core with zero cross-core traffic in the hot
path (each image's projection is independent; the only collectives are the
initial param broadcast)."""

from __future__ import annotations

import jax

from deconv_api_tpu.engine import get_visualizer
from deconv_api_tpu.models.spec import ModelSpec
from deconv_api_tpu.parallel.mesh import batch_sharding, replicated


def sharded_visualizer(
    spec: ModelSpec,
    mesh,
    layer_name: str,
    top_k: int = 8,
    mode: str = "all",
    bug_compat: bool = True,
):
    """Jitted ``fn(params, batch)`` with batch sharded over ``dp``.

    The per-call batch size must be a multiple of the dp axis size (the
    serving dispatcher's power-of-two padding guarantees this once
    max_batch >= dp)."""
    fn = get_visualizer(
        spec, layer_name, top_k, mode, bug_compat, sweep=False, batched=True
    )
    return jax.jit(
        fn,
        in_shardings=(replicated(mesh), batch_sharding(mesh)),
        out_shardings=batch_sharding(mesh),
    )
