"""Parallelism: device meshes, sharded serving batches, sharded training.

The reference is strictly single-process, single-device (SURVEY §2.4 —
no DP/TP/PP, no NCCL/MPI anywhere).  The TPU-native scale-out story is
`jax.sharding.Mesh` + GSPMD: annotate shardings, let XLA insert the
collectives over ICI.  Axes used here:

- ``dp`` — data parallel: serving batches and training batches shard their
  leading axis (BASELINE config 5: 256 concurrent requests over v5e-8).
- ``tp`` — tensor parallel: conv output-channel / dense feature sharding of
  the parameters during training.

There is deliberately no NCCL-style explicit communication API to build:
collectives are emitted by XLA from sharding constraints (SURVEY §5,
distributed-comm row).
"""

from deconv_api_tpu.parallel.mesh import (
    batch_sharding,
    init_distributed,
    make_mesh,
    make_pod_mesh,
    param_shardings,
    replicated,
    validate_parallel_layout,
)
from deconv_api_tpu.parallel.batch import sharded_visualizer
from deconv_api_tpu.parallel.lanes import lane_placements, resolve_lane_count

__all__ = [
    "batch_sharding",
    "init_distributed",
    "lane_placements",
    "make_mesh",
    "make_pod_mesh",
    "param_shardings",
    "replicated",
    "resolve_lane_count",
    "sharded_visualizer",
    "validate_parallel_layout",
]
