"""Tracing & profiling hooks.

The reference's only observability is debug prints of layer lists/shapes on
every request (app/deepdream.py:438,445-447; SURVEY §5 tracing row).  Here:
- `stage(...)`: lightweight per-stage wall-time spans feeding
  serving.metrics (decode / compute / encode timings behind /metrics);
- `profile_trace(...)`: a jax.profiler trace scope writing TensorBoard-
  loadable traces (XLA op-level timeline on TPU) when a profile dir is
  configured.
"""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def stage(metrics, name: str):
    """Time a pipeline stage into the metrics registry (no-op without
    one) AND onto the active request trace (round 8): the same wall-time
    window feeds the aggregate stage quantiles and the per-request span
    timeline, so the two can never disagree about where time went."""
    # lazy import: utils must stay importable without the serving layer
    from deconv_api_tpu.serving.trace import current_trace

    tr = current_trace()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if metrics is not None:
            metrics.observe_stage(name, dt)
        if tr is not None:
            tr.add_span(name, t0, dt)


@contextlib.contextmanager
def profile_trace(profile_dir: str):
    """jax.profiler trace scope; inert when profile_dir is empty."""
    if not profile_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
