"""Structured JSON-lines event logging.

SURVEY §5's tracing row calls for per-stage timings in structured logs
alongside the /metrics aggregates (the reference's only observability was
debug prints, app/deepdream.py:438,445-447).  Metrics answer "how is the
fleet doing"; these logs answer "what did THIS request/batch do" — one
JSON object per line on stderr, trivially greppable and ingestible.

Usage:
    from deconv_api_tpu.utils import slog
    log = slog.get_logger()
    slog.event(log, "batch_done", key="block5_conv1", size=8, ms=42.1)

`DECONV_LOG_LEVEL` sets the threshold (default INFO; set WARNING to
silence per-request access lines under load testing, or DEBUG for
dispatcher internals).  Lazily configured once, on the "deconv" logger —
applications embedding the library can attach their own handlers instead.
"""

from __future__ import annotations

import json
import logging
import os
import sys

_CONFIGURED = False


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload: dict = {
            "ts": round(record.created, 3),
            "level": record.levelname.lower(),
            "event": record.getMessage(),
        }
        fields = getattr(record, "fields", None)
        if fields:
            payload.update(fields)
        if "id" not in payload:
            # Round 8 tracing spine: any line emitted inside a traced
            # request's context inherits that request's id, so ad-hoc
            # handler/engine log lines join access logs, error payloads
            # and /v1/debug/requests traces without each call site
            # remembering to thread the id through.  Lazy import keeps
            # utils importable without the serving layer; formatting
            # only runs for records that passed the level threshold.
            try:
                from deconv_api_tpu.serving.trace import current_trace

                tr = current_trace()
                if tr is not None:
                    payload["id"] = tr.id
            except ImportError:  # pragma: no cover — partial installs
                pass
        if record.exc_info and record.exc_info[0] is not None:
            payload["exc"] = self.formatException(record.exc_info).splitlines()[-1]
        return json.dumps(payload, default=str)


def configure() -> None:
    """Attach the JSON stderr handler to the "deconv" logger tree.

    Called by the SERVER/CLI entrypoints only — importing library modules
    never configures logging, so an embedding application's own handlers
    and propagation rules stay in charge (its root config receives deconv
    records untouched until/unless it calls this).  Idempotent."""
    global _CONFIGURED
    if _CONFIGURED:
        return
    root = logging.getLogger("deconv")
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_JsonFormatter())
    root.addHandler(handler)
    wanted = os.environ.get("DECONV_LOG_LEVEL", "INFO").upper()
    if not isinstance(logging.getLevelName(wanted), int):
        # unknown level string must not crash the server at startup
        wanted = "INFO"
    root.setLevel(wanted)
    root.propagate = False
    _CONFIGURED = True


def get_logger(name: str = "deconv") -> logging.Logger:
    """Plain logger lookup — no configuration side effects (see
    ``configure``).  Without configure(), INFO events follow the
    application's own logging setup (and are dropped under Python's
    default WARNING root, keeping the library quiet by default)."""
    return logging.getLogger(name)


def event(
    logger: logging.Logger, name: str, level: int = logging.INFO, **fields
) -> None:
    """One structured event — `name` plus arbitrary JSON-serialisable
    fields.  Timestamps are added by the formatter; durations should be
    passed pre-rounded (e.g. ``ms=round(dt * 1e3, 1)``)."""
    if logger.isEnabledFor(level):
        logger.log(level, name, extra={"fields": fields})
