"""Checkpointing via orbax + the XLA persistent compilation cache.

The reference has no save path at all — its only persistence is the
pretrained-weight download (app/main.py:17; SURVEY §5 checkpoint row).
Here params pytrees round-trip through orbax (so fine-tuned weights from
train/ can be served), and compiled executables persist across process
restarts via JAX's compilation cache (config.enable_compilation_cache),
which matters on TPU where a cold compile of the deconv program is tens of
seconds.
"""

from __future__ import annotations

import os

import orbax.checkpoint as ocp


def save_params(path: str, params) -> None:
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, params, force=True)
    ckptr.wait_until_finished()


def restore_params(path: str, like):
    """Restore a params pytree shaped like `like` from an orbax dir."""
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path, like)


def save_train_state(path: str, state) -> None:
    """Persist a FULL TrainState (params + optimizer moments + step) so an
    interrupted run resumes exactly, not just its weights (SURVEY §5
    checkpoint/resume row; train/loop.py wires save_every/resume)."""
    save_params(path, state)


def restore_train_state(path: str, like):
    """Restore a TrainState saved by `save_train_state`; `like` is a
    matching concrete or abstract (ShapeDtypeStruct) TrainState."""
    return restore_params(path, like)
