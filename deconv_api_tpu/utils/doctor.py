"""Environment diagnostics: `deconv_api_tpu doctor`.

Operational packaging of the failure modes catalogued in BASELINE.md's
tunnel-anatomy section (SURVEY §5 failure-detection row).  The critical
design constraint: a wedged remote backend HANGS at init rather than
raising (bench.py docstring), so every device probe here runs in a CHILD
subprocess under a hard timeout — the doctor itself can never wedge.

Checks:
  backend     device discovery + one tiny matmul (liveness, platform)
  rtt         per-fetch host<->device round trip (median of 5 scalar
              fetches of pre-computed results; ~71 ms over the axon
              tunnel, microseconds on local PCIe — tells you whether the
              pipelined fetch path matters for your deployment)
  compile_cache  persistent XLA cache dir configured + writable
  selftest    jitted 8x8 deconv roundtrip through ops (engine sanity)

Output: one JSON object per check, then an overall verdict; exit 0 only
if every non-informational check passes.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_CHILD_TIMEOUT_S = 120.0


def _platform_prelude(platform: str | None) -> str:
    """Force a backend INSIDE the child, after jax import.  The env-var
    form (JAX_PLATFORMS=cpu) is NOT used: with an unhealthy axon plugin
    it still hangs at backend init (verify-skill/conftest finding); only
    the config update reliably bypasses the plugin."""
    if not platform:
        return "import jax\n"
    return (
        "import jax\n"
        f"jax.config.update('jax_platforms', {platform!r})\n"
    )


def _run_child(code: str, timeout_s: float = _CHILD_TIMEOUT_S) -> dict:
    """Run probe code in a subprocess; last JSON line of stdout wins."""
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return {
            "ok": False,
            "error": f"probe hung past {timeout_s:.0f}s (wedged backend?)",
        }
    wall = time.monotonic() - t0
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                out = json.loads(line)
                out.setdefault("wall_s", round(wall, 1))
                return out
            except json.JSONDecodeError:
                continue
    return {
        "ok": False,
        "error": f"probe rc={proc.returncode}",
        "stderr_tail": proc.stderr.decode(errors="replace")[-400:],
    }


def check_backend(platform: str | None = None) -> dict:
    return _run_child(
        _platform_prelude(platform)
        + "import json, jax.numpy as jnp\n"
        "d = jax.devices()[0]\n"
        "x = float((jnp.ones((128, 128)) @ jnp.ones((128, 128))).sum())\n"
        "print(json.dumps({'ok': x == 128.0 * 128 * 128,\n"
        "                  'device': str(d), 'platform': d.platform,\n"
        "                  'n_devices': jax.device_count()}))\n"
    )


def check_rtt(platform: str | None = None) -> dict:
    """Median per-fetch round trip for an ALREADY-COMPUTED scalar: pure
    host<->device latency, the quantity that decides whether per-leaf
    fetches and per-iteration syncs are harmless or ~71 ms each."""
    return _run_child(
        _platform_prelude(platform)
        + "import json, time, statistics, jax.numpy as jnp\n"
        "f = jax.jit(lambda i: jnp.float32(i) + 1.0)\n"
        "vals = [f(i) for i in range(6)]\n"
        "# settle ALL executions before timing: the device runs programs in\n"
        "# dispatch order, so fetching a program dispatched AFTER vals[1:]\n"
        "# guarantees they have all completed — without fetching vals\n"
        "# themselves (a fetched jax.Array caches its host copy, which would\n"
        "# make the timed re-fetch free and the RTT read ~0)\n"
        "float(f(99))\n"
        "ts = []\n"
        "for v in vals[1:]:\n"
        "    t0 = time.perf_counter()\n"
        "    float(v)\n"
        "    ts.append((time.perf_counter() - t0) * 1e3)\n"
        "print(json.dumps({'ok': True,\n"
        "                  'fetch_rtt_ms_p50': round(statistics.median(ts), 2),\n"
        "                  'hint': 'pipelined serving/bench amortize this'}))\n"
    )


def check_compile_cache(platform: str | None = None) -> dict:
    from deconv_api_tpu.config import ServerConfig

    cfg = ServerConfig.from_env()
    path = cfg.compilation_cache_dir
    if not path:
        return {"ok": True, "configured": False,
                "hint": "set DECONV_COMPILATION_CACHE_DIR to skip recompiles"}
    ok = os.path.isdir(path) and os.access(path, os.W_OK)
    if not ok:
        try:
            os.makedirs(path, exist_ok=True)
            ok = os.access(path, os.W_OK)
        except OSError:
            ok = False
    return {
        "ok": ok,
        "configured": True,
        "dir": path,
        "entries": len(os.listdir(path)) if ok else None,
    }


def check_selftest(platform: str | None = None) -> dict:
    """Tiny end-to-end engine roundtrip (jitted, one shape)."""
    return _run_child(
        _platform_prelude(platform)
        + "import json, jax.numpy as jnp\n"
        "from deconv_api_tpu.engine import get_visualizer\n"
        "from deconv_api_tpu.models.spec import Layer, ModelSpec, init_params\n"
        "spec = ModelSpec(name='doc', input_shape=(8, 8, 3), layers=(\n"
        "    Layer('input_1', 'input'),\n"
        "    Layer('c1', 'conv', activation='relu', filters=4),\n"
        "    Layer('p1', 'pool'),\n"
        "    Layer('c2', 'conv', activation='relu', filters=4),\n"
        "))\n"
        "params = init_params(spec, jax.random.PRNGKey(0))\n"
        "fn = get_visualizer(spec, 'c2', 2, 'all', True)\n"
        "out = fn(params, jnp.ones((8, 8, 3)))['c2']\n"
        "img = out['images']\n"
        "ok = img.shape == (2, 8, 8, 3) and bool(jnp.isfinite(img).all())\n"
        "print(json.dumps({'ok': ok, 'out_shape': list(img.shape)}))\n",
        timeout_s=300.0,
    )


CHECKS = {
    "backend": check_backend,
    "rtt": check_rtt,
    "compile_cache": check_compile_cache,
    "selftest": check_selftest,
}


def run_doctor(checks: list[str] | None = None,
               platform: str | None = None) -> int:
    names = checks or list(CHECKS)
    all_ok = True
    for name in names:
        result = CHECKS[name](platform)
        result = {"check": name, **result}
        all_ok = all_ok and bool(result.get("ok"))
        print(json.dumps(result), flush=True)
    print(json.dumps({"check": "overall", "ok": all_ok}), flush=True)
    return 0 if all_ok else 1
