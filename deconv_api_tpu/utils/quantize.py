"""The symmetric-int8 convention, in one dependency-free place.

Both quantization tiers — weights at rest (serving/weight_manager.py,
round 15) and arithmetic in int8 (engine/deconv.py quality=int8, round
18) — must agree on what a quantized tensor means.  The convention
lives HERE, in the utils layer beneath both, so neither engine nor
serving has to reach into the other for it: the widest value maps onto
±127 (never -128 — the asymmetric extra level would break w == -w
symmetry for the flipped backward kernels), and an all-zero tensor
keeps scale 1.0 (no div-by-zero; dequantises back to exact zeros).
"""

from __future__ import annotations

Q8_LEVELS = 127.0


def int8_scale(amax: float) -> float:
    """The symmetric-int8 scale for a tensor with max-abs ``amax`` — the
    ONE place the amax→scale rule lives."""
    return float(amax) / Q8_LEVELS if amax > 0 else 1.0
