"""Mesh construction and sharding rules.

One honest fact drives the layout (SURVEY §2.4): every model in the zoo
fits on a single TPU core, so serving scales by **data parallelism** over
cores, and training additionally shards parameters over a **tensor** axis.
Shardings are expressed as `NamedSharding` annotations; XLA/GSPMD inserts
the ICI collectives.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deconv_api_tpu.models.spec import ModelSpec


def make_mesh(
    shape: tuple[int, ...] | None = None,
    axis_names: tuple[str, ...] = ("dp", "tp"),
    devices=None,
) -> Mesh:
    """Build a Mesh over the available devices.

    Default shape: all devices on ``dp``, 1 on ``tp`` — the serving layout.
    For training, pass e.g. ``shape=(n//2, 2)``.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {shape} != device count {len(devices)}")
    arr = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(arr, axis_names)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard the leading (batch) axis over the data-parallel mesh axis."""
    return NamedSharding(mesh, P(axis))


def param_shardings(spec: ModelSpec, params, mesh: Mesh, axis: str = "tp"):
    """Tensor-parallel parameter shardings: conv kernels shard their output
    channels, dense kernels their output features, biases likewise; any leaf
    whose channel count doesn't divide the axis size stays replicated.

    Returns a pytree of NamedSharding congruent with `params`.
    """
    tp = mesh.shape[axis]

    def shard_leaf(leaf_name: str, leaf):
        dim = leaf.shape[-1]
        if tp > 1 and dim % tp == 0:
            spec_dims = (None,) * (leaf.ndim - 1) + (axis,)
            return NamedSharding(mesh, P(*spec_dims))
        return NamedSharding(mesh, P())

    return {
        layer: {leaf: shard_leaf(leaf, v) for leaf, v in leaves.items()}
        for layer, leaves in params.items()
    }
