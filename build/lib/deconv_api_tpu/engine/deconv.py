"""The deconvnet visualizer as a single jit-compiled XLA program.

Reference behaviour being reproduced (app/deepdream.py:383-476, surveyed in
SURVEY §3.2): forward through the layer stack recording max-pool switches,
rank feature maps by total activation (positive sums only, top 8), then for
each selected filter zero-mask the rest and project back to pixel space
through flipped convs, switch unpooling and backward-ReLU.

TPU-first design decisions:
- The entire up+down computation is ONE traced program: no per-layer
  round-trips, no per-request graph building (kills SURVEY §2.2.7 and hot
  loops #1/#2 of §3.2).
- The K backward projections are `jax.vmap`ed — on TPU they execute as one
  batched conv chain on the MXU rather than K sequential passes.
- Top-K selection happens in-graph (`lax.top_k` over channel sums), so the
  whole request is a single device dispatch; the positive-only filtering of
  the reference (app/deepdream.py:376-377) is surfaced as a `valid` mask
  because XLA needs static shapes.
- `layer_name`/`top_k`/`mode` are static: each combination compiles once and
  is cached; by default only the *requested* layer is projected (fixing the
  reference's all-layers waste, SURVEY §2.2.3), with the full sweep
  available as `visualize_all_layers` (BASELINE config 2).
- `bug_compat=True` reproduces the reference's double-ReLU on the backward
  conv (SURVEY §2.2.2), which the PSNR parity target is measured against;
  `False` gives the textbook Zeiler–Fergus projection.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax

from deconv_api_tpu import ops
from deconv_api_tpu.models.spec import Entry, ModelSpec, entry_chain


def _up_step(e: Entry, params, x, switches):
    l = e.layer
    if e.is_companion_act:
        return ops.apply_activation(x, l.activation)
    if l.kind == "input":
        return x
    if l.kind == "conv":
        w = params[l.name]["w"].astype(x.dtype)
        b = params[l.name]["b"].astype(x.dtype)
        y = ops.conv2d(x, w, b, strides=l.strides, padding=l.padding)
        # Keras conv layers carry a fused activation; the companion entry
        # applies it again (idempotent for relu) — reference app/deepdream.py:73.
        return ops.apply_activation(y, l.activation)
    if l.kind == "pool":
        pooled, idx = ops.maxpool_with_argmax(x, l.pool_size)
        # compact switch form: int8 window argmax + static input extent
        switches[e.name] = (idx, x.shape[1:3])
        return pooled
    if l.kind == "flatten":
        return ops.flatten(x)
    if l.kind == "dense":
        w = params[l.name]["w"].astype(x.dtype)
        b = params[l.name]["b"].astype(x.dtype)
        return ops.apply_activation(ops.dense(x, w, b), l.activation)
    raise AssertionError(l.kind)


def _down_step(e: Entry, params, x, switches, prev_shape, bug_compat: bool):
    l = e.layer
    if e.is_companion_act:
        # Deconvnet backward-ReLU: same activation on the way down
        # (reference app/deepdream.py:230-235).
        return ops.apply_activation(x, l.activation)
    if l.kind == "input":
        return x
    if l.kind == "conv":
        w = params[l.name]["w"].astype(x.dtype)
        y = ops.conv2d_input_backward(
            x, w, strides=l.strides, padding=l.padding, input_hw=prev_shape[1:3]
        )
        if bug_compat:
            # The reference's config-clone keeps the fused activation in the
            # backward conv model too (SURVEY §2.2.2).
            y = ops.apply_activation(y, l.activation)
        return y
    if l.kind == "pool":
        idx, out_hw = switches[e.name]
        return ops.unpool_with_argmax(x, idx, l.pool_size, out_hw)
    if l.kind == "flatten":
        return ops.unflatten(x, prev_shape[1:])
    if l.kind == "dense":
        # W^T, zero bias, no fused activation (reference app/deepdream.py:295).
        return ops.dense_input_backward(x, params[l.name]["w"].astype(x.dtype))
    raise AssertionError(l.kind)


def _visualize_entry(
    entries, params, ups, switches, i, top_k, mode, bug_compat, backward_dtype
):
    """Top-K selection + vmapped backward projection from entry index `i`."""
    output = ups[i]
    n_chan = output.shape[-1]
    k = min(top_k, n_chan)
    reduce_axes = tuple(range(output.ndim - 1))
    sums = jnp.sum(output, axis=reduce_axes)
    masked = jnp.where(sums > 0, sums, -jnp.inf)
    top_sums, top_idx = lax.top_k(masked, k)
    valid = top_sums > 0

    def backproject(idx):
        chan = jax.nn.one_hot(idx, n_chan, dtype=output.dtype)
        fmap = jnp.sum(output * chan, axis=-1)  # == output[..., idx]
        if mode == "max":
            # Keep only positions equal to the global max (ties all kept),
            # reference app/deepdream.py:454-457.
            fmap = fmap * (fmap == jnp.max(fmap)).astype(fmap.dtype)
        x = fmap[..., None] * chan
        if backward_dtype is not None:
            # Mixed precision: selection ran on the exact forward; the
            # projection chain (8/9 of the FLOPs) runs in e.g. bfloat16.
            x = x.astype(backward_dtype)
        j = i
        while j >= 0:
            e = entries[j]
            # Peephole: a pool followed (downward) by the deconvnet
            # backward-ReLU collapses into one fused unpool+ReLU op call.
            # Equivalent on every dispatch path; matters for the pallas
            # backend, whose opaque custom call would otherwise cost a
            # full-res HBM pass for the separate elementwise ReLU.
            if (
                not e.is_companion_act
                and e.layer.kind == "pool"
                and j > 0
                and entries[j - 1].is_companion_act
                and entries[j - 1].layer.activation == "relu"
            ):
                sw_idx, out_hw = switches[e.name]
                x = ops.unpool_with_argmax(
                    x, sw_idx, e.layer.pool_size, out_hw, fuse_relu=True
                )
                j -= 2
                continue
            prev_shape = ups[j - 1].shape if j > 0 else ups[0].shape
            x = _down_step(entries[j], params, x, switches, prev_shape, bug_compat)
            j -= 1
        return x.astype(output.dtype)

    images = jax.vmap(backproject)(top_idx)  # (K, 1, H, W, C)
    return {
        "images": images[:, 0],  # (K, H, W, C) — reference squeezes batch
        "indices": top_idx,
        "sums": top_sums,
        "valid": valid,
    }


@lru_cache(maxsize=128)
def get_visualizer(
    spec: ModelSpec,
    layer_name: str,
    top_k: int = 8,
    mode: str = "all",
    bug_compat: bool = True,
    sweep: bool = False,
    batched: bool = False,
    backward_dtype: str | None = None,
):
    """Build (and cache) the jitted visualizer for a static configuration.

    Returns ``fn(params, image)`` where image is (H, W, C) — or (B, H, W, C)
    when ``batched`` — yielding {layer_name: {images, indices, sums, valid}}.
    With ``sweep=True`` every model layer from `layer_name` down to the input
    is projected (the reference's always-on behaviour, SURVEY §2.2.3).
    ``backward_dtype`` (e.g. ``"bfloat16"``) runs only the backward
    projection chain in that dtype: filter selection and switches stay
    exact, trading a little projection precision for MXU throughput.
    """
    if mode not in ("all", "max"):
        # The reference sys.exit()s the server here (app/deepdream.py:458-460);
        # we raise instead (error taxonomy, SURVEY §5).
        raise ValueError(f"illegal visualize mode {mode!r}; expected 'all' or 'max'")
    truncated = spec.truncated(layer_name)
    entries = entry_chain(truncated)
    model_names = set(spec.layer_names())
    # Indices of model-layer entries (companion activations excluded),
    # deepest first, input dropped — reference app/deepdream.py:431-437.
    vis_indices = [i for i, e in enumerate(entries) if e.name in model_names]
    vis_indices.reverse()
    vis_indices.pop()
    if not vis_indices:
        raise ValueError(
            f"layer {layer_name!r} has no projectable output (it is the input layer)"
        )
    if not sweep:
        vis_indices = vis_indices[:1]

    bwd_dtype = jnp.dtype(backward_dtype) if backward_dtype else None

    def single(params, image):
        x = image[None]
        switches: dict[str, jnp.ndarray] = {}
        ups = []
        for e in entries:
            x = _up_step(e, params, x, switches)
            ups.append(x)
        return {
            entries[i].name: _visualize_entry(
                entries, params, ups, switches, i, top_k, mode, bug_compat,
                bwd_dtype,
            )
            for i in vis_indices
        }

    fn = jax.vmap(single, in_axes=(None, 0)) if batched else single
    return jax.jit(fn)


def visualize(
    spec: ModelSpec,
    params,
    image,
    layer_name: str,
    *,
    top_k: int = 8,
    mode: str = "all",
    bug_compat: bool = True,
):
    """Project the top-K filters of `layer_name` back to pixel space.

    Single-layer by default — the request in BASELINE config 1 — computing
    only what the API serves (unlike the reference, SURVEY §2.2.3).
    """
    fn = get_visualizer(spec, layer_name, top_k, mode, bug_compat, sweep=False)
    return fn(params, image)[layer_name]


def visualize_all_layers(
    spec: ModelSpec,
    params,
    image,
    layer_name: str,
    *,
    top_k: int = 8,
    mode: str = "all",
    bug_compat: bool = True,
):
    """Full sweep: every model layer from `layer_name` down to the input —
    wire-parity with the reference's `visualize_all_layers`
    (app/deepdream.py:383-476) and BASELINE config 2."""
    fn = get_visualizer(spec, layer_name, top_k, mode, bug_compat, sweep=True)
    return fn(params, image)
