"""Analytic FLOPs model for the deconv visualization workload.

Used by bench.py's MFU line when XLA's compiled-program cost analysis is
unavailable (e.g. over the axon tunnel).  The model counts multiply-add
FLOPs (2 * MACs) for the conv/dense chain:

- forward: one pass through every conv/dense layer up to the target;
- backward: one transposed-conv chain per selected top-K filter, from the
  target layer back to pixels.  A transposed conv moving a layer's output
  gradient to its input costs the same MACs as the forward conv (the
  kernel volume is identical), so each projection ~= the forward conv
  chain cost up to that layer.

Pool/unpool, activations, and top-K selection are bandwidth-bound and
contribute <1% of FLOPs; they are ignored.  This mirrors the reference's
work shape — forward once, then top-K backward chains per layer
(app/deepdream.py:426-428, 441-474) — restricted to the single requested
layer (the repo's default; SURVEY §2.2.3).
"""

from __future__ import annotations

import math

from deconv_api_tpu.models.spec import ModelSpec, layer_output_shapes


def conv_chain_flops(spec: ModelSpec, layer_name: str | None = None) -> float:
    """Per-image forward FLOPs through conv/dense layers up to layer_name
    (inclusive; None = whole spec)."""
    shapes = layer_output_shapes(spec)
    stop = spec.index(layer_name) if layer_name is not None else len(spec.layers) - 1
    shape: tuple[int, ...] = tuple(spec.input_shape)
    total = 0.0
    for l in spec.layers[: stop + 1]:
        if l.kind == "conv":
            cin = shape[-1]
            oh, ow, cout = shapes[l.name]
            kh, kw = l.kernel_size
            total += 2.0 * oh * ow * cout * kh * kw * cin
        elif l.kind == "dense":
            din = shape[-1] if len(shape) == 1 else math.prod(shape)
            total += 2.0 * din * l.filters
        shape = shapes[l.name]
    return total


def deconv_flops_per_image(
    spec: ModelSpec, layer_name: str, top_k: int = 8
) -> float:
    """Forward + top_k backward projections from layer_name, per image."""
    fwd = conv_chain_flops(spec, layer_name)
    # Each projection runs the transposed chain from layer_name to pixels:
    # same MAC count as the forward chain to layer_name.
    return fwd * (1.0 + top_k)


def vgg16_deconv_flops(batch: int, layer_name: str, top_k: int = 8) -> float:
    """Batch FLOPs for the headline bench config (VGG16 deconv)."""
    from deconv_api_tpu.models.vgg16 import VGG16_SPEC

    return batch * deconv_flops_per_image(VGG16_SPEC, layer_name, top_k)
