from deconv_api_tpu.bench.suite import CONFIGS, run_config

__all__ = ["CONFIGS", "run_config"]
