"""Training: sharded fine-tuning of the model zoo.

The reference has no training at all (SURVEY §1: inference-only service);
this subsystem exists because a framework serving deconv visualizations of
*fine-tuned* models needs a way to produce them.  The step is one jitted
program sharded over a (dp, tp) mesh — batch over ``dp``, parameters over
``tp`` — with XLA inserting the gradient psums over ICI.
"""

from deconv_api_tpu.train.step import TrainState, make_train_step, train_state_shardings

__all__ = ["TrainState", "make_train_step", "train_state_shardings"]
