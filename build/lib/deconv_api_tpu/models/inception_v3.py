"""InceptionV3 (Keras topology) as a pure function + params pytree.

Exists for the DeepDream engine (BASELINE config 3: gradient ascent on
mixed3–mixed5) — a capability extension the reference never had (its
"deepdream.py" contains no DeepDream code, SURVEY §0.2).  Activation names
match Keras (`mixed0`..`mixed10`) so config strings port directly.

Default input 299x299x3; the conv trunk is size-agnostic (>=75 px) which the
tests exploit to keep CPU compiles small.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deconv_api_tpu import ops
from deconv_api_tpu.models import blocks as B


def _cb_init(ks, cin, cout, kernel):
    return B.conv_bn_init(ks(), cin, cout, kernel)


def inception_v3_init(key: jax.Array | None = None, num_classes: int = 1000) -> dict:
    ks = B.KeySeq(key if key is not None else jax.random.PRNGKey(0))
    p: dict = {}
    # stem
    p["stem1"] = _cb_init(ks, 3, 32, (3, 3))
    p["stem2"] = _cb_init(ks, 32, 32, (3, 3))
    p["stem3"] = _cb_init(ks, 32, 64, (3, 3))
    p["stem4"] = _cb_init(ks, 64, 80, (1, 1))
    p["stem5"] = _cb_init(ks, 80, 192, (3, 3))

    def block_a(name, cin, pool_proj):
        p[name] = {
            "b1": _cb_init(ks, cin, 64, (1, 1)),
            "b5_1": _cb_init(ks, cin, 48, (1, 1)),
            "b5_2": _cb_init(ks, 48, 64, (5, 5)),
            "b3_1": _cb_init(ks, cin, 64, (1, 1)),
            "b3_2": _cb_init(ks, 64, 96, (3, 3)),
            "b3_3": _cb_init(ks, 96, 96, (3, 3)),
            "pool": _cb_init(ks, cin, pool_proj, (1, 1)),
        }
        return 64 + 64 + 96 + pool_proj

    c = block_a("mixed0", 192, 32)
    c = block_a("mixed1", c, 64)
    c = block_a("mixed2", c, 64)

    # mixed3: grid reduction 35 -> 17
    p["mixed3"] = {
        "b3": _cb_init(ks, c, 384, (3, 3)),
        "b3d_1": _cb_init(ks, c, 64, (1, 1)),
        "b3d_2": _cb_init(ks, 64, 96, (3, 3)),
        "b3d_3": _cb_init(ks, 96, 96, (3, 3)),
    }
    c = 384 + 96 + c  # + passthrough maxpool

    def block_b(name, cin, mid):
        p[name] = {
            "b1": _cb_init(ks, cin, 192, (1, 1)),
            "b7_1": _cb_init(ks, cin, mid, (1, 1)),
            "b7_2": _cb_init(ks, mid, mid, (1, 7)),
            "b7_3": _cb_init(ks, mid, 192, (7, 1)),
            "b7d_1": _cb_init(ks, cin, mid, (1, 1)),
            "b7d_2": _cb_init(ks, mid, mid, (7, 1)),
            "b7d_3": _cb_init(ks, mid, mid, (1, 7)),
            "b7d_4": _cb_init(ks, mid, mid, (7, 1)),
            "b7d_5": _cb_init(ks, mid, 192, (1, 7)),
            "pool": _cb_init(ks, cin, 192, (1, 1)),
        }
        return 192 * 4

    c = block_b("mixed4", c, 128)
    c = block_b("mixed5", c, 160)
    c = block_b("mixed6", c, 160)
    c = block_b("mixed7", c, 192)

    # mixed8: grid reduction 17 -> 8
    p["mixed8"] = {
        "b3_1": _cb_init(ks, c, 192, (1, 1)),
        "b3_2": _cb_init(ks, 192, 320, (3, 3)),
        "b7_1": _cb_init(ks, c, 192, (1, 1)),
        "b7_2": _cb_init(ks, 192, 192, (1, 7)),
        "b7_3": _cb_init(ks, 192, 192, (7, 1)),
        "b7_4": _cb_init(ks, 192, 192, (3, 3)),
    }
    c = 320 + 192 + c

    def block_c(name, cin):
        p[name] = {
            "b1": _cb_init(ks, cin, 320, (1, 1)),
            "b3_1": _cb_init(ks, cin, 384, (1, 1)),
            "b3_2a": _cb_init(ks, 384, 384, (1, 3)),
            "b3_2b": _cb_init(ks, 384, 384, (3, 1)),
            "b3d_1": _cb_init(ks, cin, 448, (1, 1)),
            "b3d_2": _cb_init(ks, 448, 384, (3, 3)),
            "b3d_3a": _cb_init(ks, 384, 384, (1, 3)),
            "b3d_3b": _cb_init(ks, 384, 384, (3, 1)),
            "pool": _cb_init(ks, cin, 192, (1, 1)),
        }
        return 320 + 768 + 768 + 192

    c = block_c("mixed9", c)
    c = block_c("mixed10", c)
    p["predictions"] = B.dense_init(ks(), c, num_classes)
    return p


def inception_v3_forward(
    params: dict,
    x: jnp.ndarray,
    *,
    rules: B.Rules = B.INFERENCE_RULES,
    logits: bool = False,
) -> tuple[jnp.ndarray, dict[str, jnp.ndarray]]:
    p = params
    acts: dict[str, jnp.ndarray] = {}
    cb = lambda name, y, **kw: B.conv_bn(p[name], y, rules, **kw)  # noqa: E731

    y = cb("stem1", x, strides=(2, 2), padding="VALID")
    y = cb("stem2", y, padding="VALID")
    y = cb("stem3", y)
    y = B.maxpool(y, 3, 2, "VALID")
    y = cb("stem4", y, padding="VALID")
    y = cb("stem5", y, padding="VALID")
    y = B.maxpool(y, 3, 2, "VALID")

    def block_a(name, y):
        q = p[name]
        b1 = B.conv_bn(q["b1"], y, rules)
        b5 = B.conv_bn(q["b5_2"], B.conv_bn(q["b5_1"], y, rules), rules)
        b3 = B.conv_bn(q["b3_1"], y, rules)
        b3 = B.conv_bn(q["b3_3"], B.conv_bn(q["b3_2"], b3, rules), rules)
        pool = B.conv_bn(q["pool"], B.avgpool(y), rules)
        return jnp.concatenate([b1, b5, b3, pool], axis=-1)

    for name in ("mixed0", "mixed1", "mixed2"):
        y = block_a(name, y)
        acts[name] = y

    q = p["mixed3"]
    b3 = B.conv_bn(q["b3"], y, rules, strides=(2, 2), padding="VALID")
    b3d = B.conv_bn(q["b3d_2"], B.conv_bn(q["b3d_1"], y, rules), rules)
    b3d = B.conv_bn(q["b3d_3"], b3d, rules, strides=(2, 2), padding="VALID")
    y = jnp.concatenate([b3, b3d, B.maxpool(y, 3, 2, "VALID")], axis=-1)
    acts["mixed3"] = y

    def block_b(name, y):
        q = p[name]
        b1 = B.conv_bn(q["b1"], y, rules)
        b7 = B.conv_bn(q["b7_1"], y, rules)
        b7 = B.conv_bn(q["b7_3"], B.conv_bn(q["b7_2"], b7, rules), rules)
        b7d = B.conv_bn(q["b7d_1"], y, rules)
        for k in ("b7d_2", "b7d_3", "b7d_4", "b7d_5"):
            b7d = B.conv_bn(q[k], b7d, rules)
        pool = B.conv_bn(q["pool"], B.avgpool(y), rules)
        return jnp.concatenate([b1, b7, b7d, pool], axis=-1)

    for name in ("mixed4", "mixed5", "mixed6", "mixed7"):
        y = block_b(name, y)
        acts[name] = y

    q = p["mixed8"]
    b3 = B.conv_bn(q["b3_1"], y, rules)
    b3 = B.conv_bn(q["b3_2"], b3, rules, strides=(2, 2), padding="VALID")
    b7 = B.conv_bn(q["b7_1"], y, rules)
    b7 = B.conv_bn(q["b7_3"], B.conv_bn(q["b7_2"], b7, rules), rules)
    b7 = B.conv_bn(q["b7_4"], b7, rules, strides=(2, 2), padding="VALID")
    y = jnp.concatenate([b3, b7, B.maxpool(y, 3, 2, "VALID")], axis=-1)
    acts["mixed8"] = y

    def block_c(name, y):
        q = p[name]
        b1 = B.conv_bn(q["b1"], y, rules)
        b3 = B.conv_bn(q["b3_1"], y, rules)
        b3 = jnp.concatenate(
            [B.conv_bn(q["b3_2a"], b3, rules), B.conv_bn(q["b3_2b"], b3, rules)],
            axis=-1,
        )
        b3d = B.conv_bn(q["b3d_2"], B.conv_bn(q["b3d_1"], y, rules), rules)
        b3d = jnp.concatenate(
            [B.conv_bn(q["b3d_3a"], b3d, rules), B.conv_bn(q["b3d_3b"], b3d, rules)],
            axis=-1,
        )
        pool = B.conv_bn(q["pool"], B.avgpool(y), rules)
        return jnp.concatenate([b1, b3, b3d, pool], axis=-1)

    for name in ("mixed9", "mixed10"):
        y = block_c(name, y)
        acts[name] = y

    y = B.global_avg_pool(y)
    acts["avg_pool"] = y
    w, b = p["predictions"]["w"], p["predictions"]["b"]
    y = ops.dense(y, w.astype(y.dtype), b.astype(y.dtype))
    if not logits:
        y = ops.softmax(y)
    acts["predictions"] = y
    return y, acts


DREAM_LAYERS = ("mixed3", "mixed4", "mixed5")
