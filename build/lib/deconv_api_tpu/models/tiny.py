"""A scaled-down VGG-topology spec for CI / dry-runs.

Same layer kinds and naming scheme as VGG16, shrunk so CPU tests and the
driver's virtual-device dry-run compile in seconds; channel counts stay
divisible by small tp axis sizes."""

from __future__ import annotations

import jax

from deconv_api_tpu.models.spec import Layer, ModelSpec, init_params

VGG_TINY_SPEC = ModelSpec(
    name="vgg_tiny",
    input_shape=(32, 32, 3),
    layers=(
        Layer("input_1", "input"),
        Layer("block1_conv1", "conv", activation="relu", filters=16),
        Layer("block1_conv2", "conv", activation="relu", filters=16),
        Layer("block1_pool", "pool"),
        Layer("block2_conv1", "conv", activation="relu", filters=32),
        Layer("block2_conv2", "conv", activation="relu", filters=32),
        Layer("block2_pool", "pool"),
        Layer("block3_conv1", "conv", activation="relu", filters=64),
        Layer("block3_pool", "pool"),
        Layer("flatten", "flatten"),
        Layer("fc1", "dense", activation="relu", filters=256),
        Layer("predictions", "dense", activation="softmax", filters=100),
    ),
)


def vgg_tiny_init(key: jax.Array | None = None):
    if key is None:
        key = jax.random.PRNGKey(0)
    return VGG_TINY_SPEC, init_params(VGG_TINY_SPEC, key)
