"""Layer-spec IR for sequential CNN classifiers.

A `ModelSpec` is a static, hashable description of a model — the engine
closes over it at trace time, so layer structure never appears as traced
control flow (everything under jit is unrolled, static-shape XLA).

The reference derives the same information by walking `model.layers` of a
live Keras object per request (app/deepdream.py:401-423); here the walk
happens once, at spec definition.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Iterator

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Layer:
    """One model layer. ``kind`` ∈ input|conv|pool|flatten|dense."""

    name: str
    kind: str
    activation: str = "linear"
    filters: int = 0  # conv out-channels / dense units
    kernel_size: tuple[int, int] = (3, 3)
    strides: tuple[int, int] = (1, 1)
    padding: str = "SAME"
    pool_size: tuple[int, int] = (2, 2)


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    name: str
    input_shape: tuple[int, int, int]  # (H, W, C)
    layers: tuple[Layer, ...]

    def __post_init__(self):
        kinds = {"input", "conv", "pool", "flatten", "dense"}
        names = set()
        for l in self.layers:
            if l.kind not in kinds:
                raise ValueError(f"layer {l.name!r}: unknown kind {l.kind!r}")
            if l.name in names:
                raise ValueError(f"duplicate layer name {l.name!r}")
            names.add(l.name)
        if not self.layers or self.layers[0].kind != "input":
            raise ValueError("spec must start with an input layer")

    def layer_names(self) -> list[str]:
        return [l.name for l in self.layers]

    def index(self, layer_name: str) -> int:
        for i, l in enumerate(self.layers):
            if l.name == layer_name:
                return i
        raise KeyError(
            f"model {self.name!r} has no layer {layer_name!r}; "
            f"known layers: {self.layer_names()}"
        )

    def truncated(self, layer_name: str) -> "ModelSpec":
        """Spec cut after `layer_name` — the reference's stack-build stop
        condition (app/deepdream.py:422-423)."""
        i = self.index(layer_name)
        return dataclasses.replace(self, layers=self.layers[: i + 1])


@dataclasses.dataclass(frozen=True)
class Entry:
    """One up/down step of the deconv chain.

    Conv and dense layers expand to two entries — the op itself and a
    companion activation — mirroring the reference's stack build
    (app/deepdream.py:404-411).  ``layer`` points at the owning Layer;
    ``is_companion_act`` marks the companion.
    """

    name: str
    layer: Layer
    is_companion_act: bool = False


def entry_chain(spec: ModelSpec) -> tuple[Entry, ...]:
    entries: list[Entry] = []
    for l in spec.layers:
        entries.append(Entry(l.name, l))
        if l.kind in ("conv", "dense"):
            entries.append(Entry(l.name + "_activation", l, True))
    return tuple(entries)


def layer_output_shapes(spec: ModelSpec) -> dict[str, tuple[int, ...]]:
    """Static per-layer output shapes (without batch), by walking the spec."""
    shapes: dict[str, tuple[int, ...]] = {}
    shape: tuple[int, ...] = tuple(spec.input_shape)
    for l in spec.layers:
        if l.kind == "input":
            pass
        elif l.kind == "conv":
            h, w, _ = shape
            if l.padding == "SAME":
                oh = math.ceil(h / l.strides[0])
                ow = math.ceil(w / l.strides[1])
            else:
                oh = math.ceil((h - l.kernel_size[0] + 1) / l.strides[0])
                ow = math.ceil((w - l.kernel_size[1] + 1) / l.strides[1])
            shape = (oh, ow, l.filters)
        elif l.kind == "pool":
            h, w, c = shape
            shape = (h // l.pool_size[0], w // l.pool_size[1], c)
        elif l.kind == "flatten":
            shape = (math.prod(shape),)
        elif l.kind == "dense":
            shape = (l.filters,)
        shapes[l.name] = shape
    return shapes


def init_params(
    spec: ModelSpec, key: jax.Array, dtype=jnp.float32
) -> dict[str, dict[str, jnp.ndarray]]:
    """He-normal random init for every parameterised layer.

    Pretrained weights (Keras h5 → pytree) are layered on top by
    models/weights.py when available; random init keeps the framework fully
    functional with zero network egress.
    """
    params: dict[str, dict[str, jnp.ndarray]] = {}
    shape: tuple[int, ...] = tuple(spec.input_shape)
    shapes = layer_output_shapes(spec)
    for l in spec.layers:
        if l.kind == "conv":
            cin = shape[-1]
            kh, kw = l.kernel_size
            key, sub = jax.random.split(key)
            fan_in = kh * kw * cin
            params[l.name] = {
                "w": (
                    jax.random.normal(sub, (kh, kw, cin, l.filters))
                    * math.sqrt(2.0 / fan_in)
                ).astype(dtype),
                "b": jnp.zeros((l.filters,), dtype),
            }
        elif l.kind == "dense":
            din = shape[-1] if len(shape) == 1 else math.prod(shape)
            key, sub = jax.random.split(key)
            params[l.name] = {
                "w": (
                    jax.random.normal(sub, (din, l.filters))
                    * math.sqrt(2.0 / din)
                ).astype(dtype),
                "b": jnp.zeros((l.filters,), dtype),
            }
        shape = shapes[l.name]
    return params


def iter_model_layers(spec: ModelSpec) -> Iterator[Layer]:
    yield from spec.layers
