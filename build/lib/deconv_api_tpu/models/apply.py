"""Plain forward pass (classifier inference / training) over a ModelSpec.

This is the non-deconv execution path: no switch recording (pooling uses
`lax.reduce_window`, cheaper than the switch-recording pool), used by the
training step and classification serving.  The deconv engine keeps its own
forward (engine/deconv.py) because it must thread switches to the backward
half.
"""

from __future__ import annotations

import jax.numpy as jnp

from deconv_api_tpu import ops
from deconv_api_tpu.models.spec import ModelSpec


def spec_forward(spec: ModelSpec, *, logits: bool = False):
    """Adapt a sequential ModelSpec to the DAG-model calling convention
    ``forward_fn(params, x, rules=...) -> (out, acts)`` used by the
    autodiff deconv and DeepDream engines — every model family shares one
    engine interface.  With ``logits=True`` the final dense layer's softmax
    is skipped (stable cross-entropy path for training)."""
    from deconv_api_tpu.models.blocks import INFERENCE_RULES, Rules, maxpool

    last = spec.layers[-1]

    def forward_fn(params, x, rules: Rules = INFERENCE_RULES):
        acts: dict[str, jnp.ndarray] = {}
        for l in spec.layers:
            if l.kind == "input":
                pass
            elif l.kind == "conv":
                w = params[l.name]["w"].astype(x.dtype)
                b = params[l.name]["b"].astype(x.dtype)
                x = ops.conv2d(x, w, b, strides=l.strides, padding=l.padding)
                x = (
                    rules.relu(x)
                    if l.activation == "relu"
                    else ops.apply_activation(x, l.activation)
                )
            elif l.kind == "pool":
                ph, pw = l.pool_size
                x = maxpool(x, (ph, pw), (ph, pw), "VALID")
            elif l.kind == "flatten":
                x = ops.flatten(x)
            elif l.kind == "dense":
                w = params[l.name]["w"].astype(x.dtype)
                b = params[l.name]["b"].astype(x.dtype)
                x = ops.dense(x, w, b)
                if logits and l is last and l.activation == "softmax":
                    pass  # leave as logits
                elif l.activation == "relu":
                    x = rules.relu(x)
                else:
                    x = ops.apply_activation(x, l.activation)
            acts[l.name] = x
        return x, acts

    return forward_fn


def forward(
    spec: ModelSpec,
    params,
    x: jnp.ndarray,
    *,
    logits: bool = False,
) -> jnp.ndarray:
    """Classifier forward (training/inference); one interpreter with
    spec_forward so the two paths can never drift."""
    out, _ = spec_forward(spec, logits=logits)(params, x)
    return out
