"""VGG16 (ImageNet classifier topology) as a ModelSpec.

Layer names match Keras' `keras.applications.vgg16.VGG16(include_top=True)`
exactly, so requests naming reference layers ("block5_conv1", …) resolve
unchanged (the reference serves these names over HTTP, app/main.py:57,64).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deconv_api_tpu.models.spec import Layer, ModelSpec, init_params


def _conv(name: str, filters: int) -> Layer:
    return Layer(name, "conv", activation="relu", filters=filters, kernel_size=(3, 3))


def _pool(name: str) -> Layer:
    return Layer(name, "pool", pool_size=(2, 2))


VGG16_SPEC = ModelSpec(
    name="vgg16",
    input_shape=(224, 224, 3),
    layers=(
        Layer("input_1", "input"),
        _conv("block1_conv1", 64),
        _conv("block1_conv2", 64),
        _pool("block1_pool"),
        _conv("block2_conv1", 128),
        _conv("block2_conv2", 128),
        _pool("block2_pool"),
        _conv("block3_conv1", 256),
        _conv("block3_conv2", 256),
        _conv("block3_conv3", 256),
        _pool("block3_pool"),
        _conv("block4_conv1", 512),
        _conv("block4_conv2", 512),
        _conv("block4_conv3", 512),
        _pool("block4_pool"),
        _conv("block5_conv1", 512),
        _conv("block5_conv2", 512),
        _conv("block5_conv3", 512),
        _pool("block5_pool"),
        Layer("flatten", "flatten"),
        Layer("fc1", "dense", activation="relu", filters=4096),
        Layer("fc2", "dense", activation="relu", filters=4096),
        Layer("predictions", "dense", activation="softmax", filters=1000),
    ),
)

CONV_LAYER_NAMES = tuple(l.name for l in VGG16_SPEC.layers if l.kind == "conv")


def vgg16_init(key: jax.Array | None = None, dtype=jnp.float32):
    """(spec, params) with He-normal weights; see models/weights.py for
    loading pretrained Keras h5 weights into the same pytree layout."""
    if key is None:
        key = jax.random.PRNGKey(0)
    return VGG16_SPEC, init_params(VGG16_SPEC, key, dtype)
