"""Serving layer: wire-compatible HTTP surface + batching dispatcher.

Replaces the reference's FastAPI app (app/main.py) with a dependency-free
asyncio HTTP server (fastapi/uvicorn are deliberately not required), an
async batching dispatcher that coalesces concurrent requests into padded
device batches (fixing the reference's event-loop-blocking `async def`,
SURVEY §2.2.5), and a host-side image codec reproducing the reference's
wire format byte-for-byte.
"""

from deconv_api_tpu.serving.codec import (
    decode_data_url,
    deprocess_image,
    encode_data_url,
    preprocess_vgg,
    stitch_grid,
)

__all__ = [
    "decode_data_url",
    "deprocess_image",
    "encode_data_url",
    "preprocess_vgg",
    "stitch_grid",
]
