from deconv_api_tpu.cli import main

raise SystemExit(main())
