"""Utilities: tracing/profiling, checkpointing, structured logging."""
