"""Tracing & profiling hooks.

The reference's only observability is debug prints of layer lists/shapes on
every request (app/deepdream.py:438,445-447; SURVEY §5 tracing row).  Here:
- `stage(...)`: lightweight per-stage wall-time spans feeding
  serving.metrics (decode / compute / encode timings behind /metrics);
- `profile_trace(...)`: a jax.profiler trace scope writing TensorBoard-
  loadable traces (XLA op-level timeline on TPU) when a profile dir is
  configured.
"""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def stage(metrics, name: str):
    """Time a pipeline stage into the metrics registry (no-op without one)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if metrics is not None:
            metrics.observe_stage(name, time.perf_counter() - t0)


@contextlib.contextmanager
def profile_trace(profile_dir: str):
    """jax.profiler trace scope; inert when profile_dir is empty."""
    if not profile_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
