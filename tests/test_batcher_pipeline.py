"""Pipelined BatchingDispatcher (serving/batcher.py round 3): dispatch and
result-fetch are decoupled so the device-side of batch N+1 overlaps the
host-side fetch of batch N.  These tests drive the dispatcher with
synthetic runners (no JAX) and assert overlap, ordering, error
propagation, inflight accounting and shutdown draining."""

import asyncio
import threading
import time

import numpy as np
import pytest

from deconv_api_tpu.serving.batcher import BatchingDispatcher


def _img():
    return np.zeros((2, 2, 3), np.float32)


def test_fetch_overlaps_next_dispatch():
    """With pipeline_depth=2 the dispatcher must dispatch batch 2 while
    batch 1's fetch thunk is still blocking."""
    events = []
    fetch_gate = threading.Event()

    def dispatch(key, images):
        events.append(("dispatch", key))

        def thunk():
            if key == "a":
                fetch_gate.wait(5)  # block batch a's fetch
            events.append(("fetched", key))
            return [f"{key}-res"] * len(images)

        return thunk

    async def go():
        d = BatchingDispatcher(
            lambda k, i: [None], dispatch_runner=dispatch,
            pipeline_depth=2, max_batch=1, window_ms=1.0,
        )
        await d.start()
        fa = asyncio.create_task(d.submit(_img(), "a"))
        await asyncio.sleep(0.1)  # a dispatched, its fetch now blocked
        fb = asyncio.create_task(d.submit(_img(), "b"))
        rb = await asyncio.wait_for(fb, 5)  # b completes while a's fetch hangs
        assert rb == "b-res"
        assert ("dispatch", "b") in events
        assert ("fetched", "a") not in events  # a still blocked => overlap
        fetch_gate.set()
        ra = await asyncio.wait_for(fa, 5)
        assert ra == "a-res"
        await d.stop()

    asyncio.run(go())


def test_pipeline_depth_bounds_inflight():
    """A third batch must NOT dispatch while depth=2 permits are held."""
    dispatched = []
    gate = threading.Event()

    def dispatch(key, images):
        dispatched.append(key)

        def thunk():
            gate.wait(5)
            return ["ok"] * len(images)

        return thunk

    async def go():
        d = BatchingDispatcher(
            lambda k, i: [None], dispatch_runner=dispatch,
            pipeline_depth=2, max_batch=1, window_ms=1.0,
        )
        await d.start()
        futs = [asyncio.create_task(d.submit(_img(), k)) for k in "abc"]
        await asyncio.sleep(0.3)
        assert sorted(dispatched) == ["a", "b"]  # c waits for a permit
        gate.set()
        assert await asyncio.gather(*futs) == ["ok", "ok", "ok"]
        await d.stop()

    asyncio.run(go())


def test_fetch_error_propagates_and_pipeline_recovers():
    def dispatch(key, images):
        def thunk():
            if key == "bad":
                raise RuntimeError("fetch exploded")
            return ["ok"] * len(images)

        return thunk

    async def go():
        d = BatchingDispatcher(
            lambda k, i: [None], dispatch_runner=dispatch,
            pipeline_depth=2, max_batch=1, window_ms=1.0,
        )
        await d.start()
        with pytest.raises(RuntimeError, match="fetch exploded"):
            await d.submit(_img(), "bad")
        assert await d.submit(_img(), "good") == "ok"  # permit not leaked
        assert d._inflight == 0
        await d.stop()

    asyncio.run(go())


def test_dispatch_error_propagates_and_pipeline_recovers():
    def dispatch(key, images):
        if key == "bad":
            raise RuntimeError("dispatch exploded")

        def thunk():
            return ["ok"] * len(images)

        return thunk

    async def go():
        d = BatchingDispatcher(
            lambda k, i: [None], dispatch_runner=dispatch,
            pipeline_depth=2, max_batch=1, window_ms=1.0,
        )
        await d.start()
        with pytest.raises(RuntimeError, match="dispatch exploded"):
            await d.submit(_img(), "bad")
        assert await d.submit(_img(), "good") == "ok"
        assert d._inflight == 0
        await d.stop()

    asyncio.run(go())


def test_stop_drains_inflight_fetches():
    """stop() must wait for outstanding fetch tasks so no future is left
    dangling after shutdown."""
    release = threading.Event()

    def dispatch(key, images):
        def thunk():
            release.wait(5)
            return ["done"] * len(images)

        return thunk

    async def go():
        d = BatchingDispatcher(
            lambda k, i: [None], dispatch_runner=dispatch,
            pipeline_depth=2, max_batch=1, window_ms=1.0,
        )
        await d.start()
        fut = asyncio.create_task(d.submit(_img(), "x"))
        await asyncio.sleep(0.1)
        release.set()
        await d.stop()
        assert await asyncio.wait_for(fut, 1) == "done"

    asyncio.run(go())


def test_depth_one_falls_back_to_serial():
    """pipeline_depth=1 must use the serial runner path (dispatch_runner
    ignored), preserving the pre-round-3 execution model."""
    used = []

    def runner(key, images):
        used.append("serial")
        return ["s"] * len(images)

    def dispatch(key, images):  # pragma: no cover - must not be called
        used.append("pipelined")
        return lambda: ["p"] * len(images)

    async def go():
        d = BatchingDispatcher(
            runner, dispatch_runner=dispatch, pipeline_depth=1,
            max_batch=4, window_ms=1.0,
        )
        await d.start()
        assert await d.submit(_img(), "k") == "s"
        await d.stop()

    asyncio.run(go())
    assert used == ["serial"]


def test_mixed_keys_same_window_pipeline():
    """Distinct keys arriving together resolve correctly through separate
    fetch tasks, results mapped per request."""

    def dispatch(key, images):
        def thunk():
            time.sleep(0.02)
            return [f"{key}:{i}" for i in range(len(images))]

        return thunk

    async def go():
        d = BatchingDispatcher(
            lambda k, i: [None], dispatch_runner=dispatch,
            pipeline_depth=2, max_batch=8, window_ms=30.0,
        )
        await d.start()
        futs = [
            asyncio.create_task(d.submit(_img(), k))
            for k in ("a", "b", "a", "b", "a")
        ]
        res = await asyncio.gather(*futs)
        assert res == ["a:0", "b:0", "a:1", "b:1", "a:2"]
        await d.stop()

    asyncio.run(go())


def test_stop_mid_dispatch_fails_futures_fast():
    """Cancelling the dispatcher while a group's dispatch is in the worker
    thread must FAIL that group's futures immediately (503 unavailable),
    not leave them hanging to a full request-timeout 504."""
    from deconv_api_tpu import errors

    started = threading.Event()
    release = threading.Event()

    def dispatch(key, images):
        started.set()
        release.wait(5)  # hold the dispatch in the worker thread
        return lambda: ["late"] * len(images)

    async def go():
        d = BatchingDispatcher(
            lambda k, i: [None], dispatch_runner=dispatch,
            pipeline_depth=2, max_batch=1, window_ms=1.0,
            request_timeout_s=30.0,
        )
        await d.start()
        fut = asyncio.create_task(d.submit(_img(), "x"))
        await asyncio.to_thread(started.wait, 5)
        stop = asyncio.create_task(d.stop())
        await asyncio.sleep(0.1)
        release.set()  # let the worker thread finish so stop() completes
        await stop
        t0 = time.monotonic()
        with pytest.raises(errors.Unavailable):
            await fut
        assert time.monotonic() - t0 < 5  # failed fast, not a 30 s timeout

    asyncio.run(go())


def test_cadence_observed_under_sustained_load():
    """Back-to-back batches must record completion cadence so the shed
    estimator sees the sustained (pipelined) rate, not per-batch walls."""
    from deconv_api_tpu.serving.metrics import Metrics

    m = Metrics()

    def dispatch(key, images):
        def thunk():
            time.sleep(0.01)
            return ["ok"] * len(images)

        return thunk

    async def go():
        d = BatchingDispatcher(
            lambda k, i: [None], dispatch_runner=dispatch,
            pipeline_depth=2, max_batch=1, window_ms=1.0, metrics=m,
        )
        await d.start()
        futs = [asyncio.create_task(d.submit(_img(), f"k{i}")) for i in range(6)]
        await asyncio.gather(*futs)
        await d.stop()

    asyncio.run(go())
    assert m.cadence_p50() > 0.0


def test_cadence_not_contaminated_by_idle_gaps():
    """A burst after an idle period must NOT record the idle gap as a
    cadence sample (it would inflate the shed estimator into spurious
    503s — r3 review finding)."""
    from deconv_api_tpu.serving.metrics import Metrics

    m = Metrics()

    def dispatch(key, images):
        def thunk():
            time.sleep(0.01)
            return ["ok"] * len(images)

        return thunk

    async def go():
        d = BatchingDispatcher(
            lambda k, i: [None], dispatch_runner=dispatch,
            pipeline_depth=2, max_batch=1, window_ms=1.0, metrics=m,
        )
        await d.start()
        # burst 1: four back-to-back batches -> in-burst cadence samples
        # (the first completion only sets the anchor; the last completes
        # with nothing in flight and clears it)
        await asyncio.gather(*(d.submit(_img(), f"a{i}") for i in range(4)))
        await asyncio.sleep(0.5)  # idle gap
        # burst 2
        await asyncio.gather(*(d.submit(_img(), f"b{i}") for i in range(4)))
        await d.stop()

    asyncio.run(go())
    # every recorded sample must be a genuine in-burst interval, far below
    # the 0.5 s idle gap
    assert 0.0 < m.cadence_p50() < 0.25


def test_stop_fails_queued_items_fast():
    """Requests still in the queue at stop() fail with Unavailable
    immediately instead of hanging to the request timeout."""
    from deconv_api_tpu import errors

    release = threading.Event()

    def dispatch(key, images):
        def thunk():
            release.wait(5)
            return ["ok"] * len(images)

        return thunk

    async def go():
        d = BatchingDispatcher(
            lambda k, i: [None], dispatch_runner=dispatch,
            pipeline_depth=1 + 1, max_batch=1, window_ms=1.0,
            request_timeout_s=30.0,
        )
        await d.start()
        # depth permits (2) + several queued behind them
        futs = [asyncio.create_task(d.submit(_img(), f"k{i}")) for i in range(6)]
        await asyncio.sleep(0.2)
        release.set()
        stop = asyncio.create_task(d.stop())
        t0 = time.monotonic()
        results = await asyncio.gather(*futs, return_exceptions=True)
        await stop
        assert time.monotonic() - t0 < 10  # nobody waited out a 30 s timeout
        ok = [r for r in results if r == "ok"]
        failed = [r for r in results if isinstance(r, errors.Unavailable)]
        assert len(ok) + len(failed) == 6 and failed  # queued tail failed fast

    asyncio.run(go())


def test_submit_after_stop_fails_fast():
    """A request racing stop() must get an immediate Unavailable, not sit
    in a dispatcherless queue until its full request-timeout 504
    (ADVICE r3)."""
    from deconv_api_tpu import errors

    async def go():
        d = BatchingDispatcher(
            lambda k, i: ["r"] * len(i), max_batch=1, window_ms=1.0,
            request_timeout_s=30.0,
        )
        await d.start()
        await d.stop()
        t0 = time.perf_counter()
        with pytest.raises(errors.Unavailable):
            await d.submit(_img(), "a")
        assert time.perf_counter() - t0 < 1.0  # immediate, not a 504 wait

    asyncio.run(go())


def test_stop_grace_bounds_wedged_fetch():
    """A wedged device_get (hangs, never raises — the documented backend
    failure mode) must not stall graceful shutdown: stop(grace_s) cancels
    the straggler after the grace budget and fails its futures with
    Unavailable (ADVICE r3)."""
    from deconv_api_tpu import errors

    wedge = threading.Event()  # never set: the fetch thunk hangs "forever"

    def dispatch(key, images):
        def thunk():
            wedge.wait(30)  # far beyond the grace budget
            return ["late"] * len(images)

        return thunk

    async def go():
        d = BatchingDispatcher(
            lambda k, i: [None], dispatch_runner=dispatch,
            pipeline_depth=2, max_batch=1, window_ms=1.0,
        )
        await d.start()
        fut = asyncio.create_task(d.submit(_img(), "a"))
        await asyncio.sleep(0.1)  # dispatched; fetch task now wedged
        t0 = time.perf_counter()
        await d.stop(grace_s=0.5)
        stop_wall = time.perf_counter() - t0
        assert stop_wall < 5.0, f"stop() stalled {stop_wall:.1f}s on a wedged fetch"
        with pytest.raises(errors.Unavailable):
            await fut
        wedge.set()  # unblock the worker thread for clean teardown

    asyncio.run(go())


def test_serial_stop_mid_execution_fails_items_fast():
    """Serial mode (pipeline_depth=1): items inside the batch being
    executed when stop() cancels the dispatcher must fail with Unavailable
    immediately, not hang to the full request-timeout 504 (r4 review)."""
    from deconv_api_tpu import errors

    release = threading.Event()

    def runner(key, images):
        release.wait(30)  # simulate a long device call
        return ["late"] * len(images)

    async def go():
        d = BatchingDispatcher(
            runner, max_batch=1, window_ms=1.0,
            request_timeout_s=60.0, pipeline_depth=1,
        )
        await d.start()
        fut = asyncio.create_task(d.submit(_img(), "a"))
        await asyncio.sleep(0.1)  # runner now blocking in its worker thread
        t0 = time.perf_counter()
        await d.stop(grace_s=0.5)
        assert time.perf_counter() - t0 < 5.0
        with pytest.raises(errors.Unavailable):
            await asyncio.wait_for(fut, 2.0)  # fails NOW, not after 60s
        release.set()

    asyncio.run(go())


def test_wedged_worker_does_not_block_interpreter_exit():
    """A device_get wedged forever in a worker thread must not block
    process exit: workers are daemon threads, so after stop() the
    interpreter exits instead of hanging in the executor's atexit join
    (r4 review).  Runs in a subprocess to observe real interpreter exit."""
    import subprocess
    import sys

    code = """
import asyncio, threading, numpy as np
from deconv_api_tpu.serving.batcher import BatchingDispatcher

def dispatch(key, images):
    def thunk():
        threading.Event().wait()  # wedged FOREVER — never returns
    return thunk

async def go():
    d = BatchingDispatcher(lambda k, i: [None], dispatch_runner=dispatch,
                           pipeline_depth=2, max_batch=1, window_ms=1.0)
    await d.start()
    t = asyncio.create_task(d.submit(np.zeros((2, 2, 3), np.float32), "a"))
    await asyncio.sleep(0.2)
    await d.stop(grace_s=0.3)
    t.cancel()

asyncio.run(go())
print("EXITED-CLEANLY", flush=True)
"""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, timeout=60,
    )
    assert b"EXITED-CLEANLY" in proc.stdout, proc.stderr.decode()[-500:]
    assert proc.returncode == 0
