"""Unit tests for the measurement-pipeline child runner
(tools/run_bench_suite.py:run_cmd_json) — the shared path every hardware
artifact (bench suite, tunnel watcher, r4 experiments) flows through.
A regression here silently classifies real measurements as errors or
vice versa, so the error taxonomy is pinned directly."""

from __future__ import annotations

import importlib.util
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _load_suite():
    spec = importlib.util.spec_from_file_location(
        "run_bench_suite", REPO / "tools" / "run_bench_suite.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_last_json_line_wins_and_wall_time_attached():
    mod = _load_suite()
    row = mod.run_cmd_json(
        [
            sys.executable,
            "-c",
            "print('noise'); print('{\"a\": 1}'); print('{\"a\": 2}')",
        ],
        timeout_s=30,
    )
    assert row["a"] == 2
    assert row["wall_s_total"] >= 0


def test_timeout_yields_error_row():
    mod = _load_suite()
    row = mod.run_cmd_json(
        [sys.executable, "-c", "import time; time.sleep(30)"], timeout_s=1
    )
    assert row == {"error": "timeout after 1s"}


def test_nonzero_rc_yields_error_row_with_stderr_tail():
    mod = _load_suite()
    row = mod.run_cmd_json(
        [sys.executable, "-c", "import sys; print('x', file=sys.stderr); sys.exit(3)"],
        timeout_s=30,
    )
    assert row["error"] == "rc=3"
    assert "x" in row["stderr_tail"]


def test_no_json_output_is_an_error_row():
    mod = _load_suite()
    row = mod.run_cmd_json([sys.executable, "-c", "print('hello')"], timeout_s=30)
    assert row["error"] == "no JSON output"


def test_env_overrides_merge_over_parent_env(monkeypatch):
    mod = _load_suite()
    monkeypatch.setenv("BENCH_TOOLS_KEEP", "kept")
    row = mod.run_cmd_json(
        [
            sys.executable,
            "-c",
            "import json, os; print(json.dumps({"
            "'set': os.environ.get('BENCH_TOOLS_SET'),"
            "'kept': os.environ.get('BENCH_TOOLS_KEEP')}))",
        ],
        timeout_s=30,
        env={"BENCH_TOOLS_SET": "v"},
    )
    assert row["set"] == "v"  # override applied
    assert row["kept"] == "kept"  # parent env preserved


def test_run_one_error_rows_carry_config_number(monkeypatch):
    mod = _load_suite()
    monkeypatch.setattr(
        mod, "run_cmd_json", lambda cmd, t, env=None: {"error": "rc=1"}
    )
    row = mod.run_one(4, 10)
    assert row["config"] == 4 and row["error"] == "rc=1"
