"""Fused Pallas unpool+flipped-conv backward tail (round 20): fused_unpool.

Fast-lane (tier-1) coverage of ops/pallas_deconv.py at CPU-sized shapes,
so kernel/dispatch drift is caught without a TPU: interpret-mode fp32
BIT-parity of the fused op against the unfused
`unpool_with_argmax` → `conv2d_input_backward[_grouped]` pair across
C ∈ {3, 64, 128}, odd batch and odd (padded) extents, relu-fused and
plain variants, groups ∈ {1, K}; the compiled-form (mxu) kernel body
pinned in interpret mode including its row-tiled halo logic; silent
fallback on every uncertified shape; the off|auto|forced policy
resolving through `/v1/config`; and end-to-end serving byte-parity with
the knob forced vs off (deconv, sweep, dream — cache bypassed).
Headline-shape A/B *timing* lives in tools/fused_probe.py (the `fused`
bench-suite token); compiled-kernel parity on real hardware is that
probe's job, not this file's (ops/pallas_deconv.py docstring).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deconv_api_tpu import ops
from deconv_api_tpu.engine.deconv import get_visualizer
from deconv_api_tpu.models.spec import init_params
from deconv_api_tpu.ops import pallas_deconv
from deconv_api_tpu.ops.conv import (
    conv2d_input_backward,
    conv2d_input_backward_grouped,
)
from deconv_api_tpu.ops.pool import unpool_with_argmax
from tests.test_engine_parity import TINY


# ---------------------------------------------------------------- helpers


@pytest.fixture(scope="module")
def tiny_params():
    return init_params(TINY, jax.random.PRNGKey(42))


def _rand(shape, seed=0, dtype=np.float32):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape), dtype
    )


def _idx(shape, seed=0, hi=4):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, hi, shape), jnp.int8
    )


def _pair(y, idx, w, pool, out_hw, relu, groups):
    """The reference pair the fused op must be bit-identical to."""
    up = unpool_with_argmax(
        y, idx, pool, out_hw, fuse_relu=relu, groups=groups
    )
    if groups > 1:
        return conv2d_input_backward_grouped(up, w, groups)
    return conv2d_input_backward(up, w)


def _has_pallas(fn, *args) -> bool:
    """Engagement marker: the pallas_call primitive in the traced jaxpr
    (interpret mode inlines the kernel out of lowered HLO, so jaxpr
    inspection is the backend-independent check the probe also uses)."""
    return "pallas_call" in str(jax.make_jaxpr(fn)(*args))


# ------------------------------------------------------ op-level parity


class TestFusedOpParity:
    @pytest.mark.parametrize("c", [3, 64, 128])
    @pytest.mark.parametrize("relu", [False, True])
    def test_bitwise_parity_groups1(self, c, relu):
        """Interpret-mode fp32 BIT-equality with the unfused pair at the
        certified widths — including an odd batch (serving bucket
        shapes are not powers of two)."""
        b, ho, wo, cin, kh = 3, 4, 5, 7, 3
        y = _rand((b, ho, wo, c), seed=c + relu)
        idx = _idx((b, ho, wo, c), seed=c)
        w = _rand((kh, kh, cin, c), seed=c + 1)
        got = pallas_deconv.fused_unpool_backward(
            y, idx, w, (2, 2), (ho * 2, wo * 2),
            fuse_relu=relu, mode="forced",
        )
        want = _pair(y, idx, w, (2, 2), (ho * 2, wo * 2), relu, 1)
        assert got.shape == want.shape == (b, ho * 2, wo * 2, cin)
        assert jnp.array_equal(got, want)

    @pytest.mark.parametrize("groups", [4, 8])
    @pytest.mark.parametrize("relu", [False, True])
    def test_bitwise_parity_grouped(self, groups, relu):
        """The kpack grouped form: groups=K packed signal, group-
        invariant switch index, tiled shared kernel — bit-equal to the
        grouped pair."""
        b, ho, wo, c, cin = 2, 6, 4, 16, 5
        y = _rand((b, ho, wo, groups * c), seed=groups + relu)
        idx = _idx((b, ho, wo, c), seed=groups)
        w = _rand((3, 3, cin, c), seed=groups + 2)
        got = pallas_deconv.fused_unpool_backward(
            y, idx, w, (2, 2), (ho * 2, wo * 2),
            fuse_relu=relu, groups=groups, mode="forced",
        )
        want = _pair(y, idx, w, (2, 2), (ho * 2, wo * 2), relu, groups)
        assert got.shape == want.shape == (b, ho * 2, wo * 2, groups * cin)
        assert jnp.array_equal(got, want)

    def test_bitwise_parity_5x5_kernel_and_3x3_pool(self):
        """Wider odd kernels and non-2x2 pools stay certified (halo is
        ceil(kh2/ph) pooled rows) and bit-equal."""
        b, ho, wo, c, cin = 2, 4, 4, 6, 3
        y = _rand((b, ho, wo, c), seed=9)
        idx = _idx((b, ho, wo, c), seed=9, hi=9)
        w = _rand((5, 5, cin, c), seed=10)
        got = pallas_deconv.fused_unpool_backward(
            y, idx, w, (3, 3), (ho * 3, wo * 3), mode="forced"
        )
        want = _pair(y, idx, w, (3, 3), (ho * 3, wo * 3), False, 1)
        assert jnp.array_equal(got, want)

    def test_bitwise_parity_bf16(self):
        """The serving config runs the backward chain bfloat16; the
        engaged interpret body must stay bit-equal there too."""
        b, ho, wo, c, cin = 2, 4, 4, 8, 5
        y = _rand((b, ho, wo, c), seed=3).astype(jnp.bfloat16)
        idx = _idx((b, ho, wo, c), seed=3)
        w = _rand((3, 3, cin, c), seed=4).astype(jnp.bfloat16)
        got = pallas_deconv.fused_unpool_backward(
            y, idx, w, (2, 2), (ho * 2, wo * 2), fuse_relu=True,
            mode="forced",
        )
        want = _pair(y, idx, w, (2, 2), (ho * 2, wo * 2), True, 1)
        assert got.dtype == want.dtype == jnp.bfloat16
        assert jnp.array_equal(got, want)

    def test_vmap_composition_matches_pair(self):
        """The engine's two vmap axes (K projections with shared
        switches, then the request batch) must collapse into the kernel
        bit-identically to vmapping the pair."""
        k, bo = 4, 2
        yk = _rand((bo, k, 1, 4, 4, 16), seed=11)
        idx = _idx((bo, 1, 4, 4, 16), seed=11)
        w = _rand((3, 3, 7, 16), seed=12)

        def fused(ys, ii):
            return jax.vmap(
                lambda s: pallas_deconv.fused_unpool_backward(
                    s, ii, w, (2, 2), (8, 8), fuse_relu=True,
                    mode="forced",
                )
            )(ys)

        def ref(ys, ii):
            return jax.vmap(
                lambda s: _pair(s, ii, w, (2, 2), (8, 8), True, 1)
            )(ys)

        got = jax.vmap(fused)(yk, idx)
        want = jax.vmap(ref)(yk, idx)
        assert jnp.array_equal(got, want)


# ------------------------------------------------- the compiled (mxu) body


class TestMxuBody:
    """The tap-major shifted-matmul body that compiles on TPU, pinned in
    interpret mode: its scatter/halo/layout logic must reproduce the
    pair at fp32 reduction tolerance (bit-parity of the COMPILED form is
    tools/fused_probe.py's job on real hardware)."""

    @pytest.mark.parametrize("groups", [1, 4])
    @pytest.mark.parametrize("tp", [1, 2, 3])
    def test_row_tiled_halo_matches_pair(self, groups, tp):
        """Every row tiling — including tilings that need the
        neighbour-block halo — must agree with the untiled pair; this
        is the test that owns the halo index-map logic."""
        b, ho, wo, c, cin = 2, 6, 5, 8, 3
        y = _rand((b, ho, wo, groups * c), seed=tp + groups)
        idx = _idx((b, ho, wo, c), seed=tp)
        w = _rand((3, 3, cin, c), seed=tp + 5)
        got = pallas_deconv.fused_pallas_call(
            y, idx, w, (2, 2), relu=True, groups=groups,
            impl="mxu", interpret=True, rows_per_block=tp,
        )
        want = _pair(y, idx, w, (2, 2), (ho * 2, wo * 2), True, groups)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
        )

    def test_wide_kernel_halo(self):
        """kh=5 needs a full pooled halo row each side (hp=1 at ph=2):
        the boundary zeroing and interior stitching must both hold."""
        b, ho, wo, c, cin = 1, 4, 4, 6, 4
        y = _rand((b, ho, wo, c), seed=21)
        idx = _idx((b, ho, wo, c), seed=21)
        w = _rand((5, 5, cin, c), seed=22)
        got = pallas_deconv.fused_pallas_call(
            y, idx, w, (2, 2), relu=False, groups=1,
            impl="mxu", interpret=True, rows_per_block=1,
        )
        want = _pair(y, idx, w, (2, 2), (ho * 2, wo * 2), False, 1)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
        )

    def test_switch_sharing_rep(self):
        """idx batch < y batch: switch blocks replay across `rep`
        consecutive signal slices through the grid index map (the
        pallas_pool idiom) — against the pair with the broadcast
        materialised."""
        bi, rep = 2, 3
        ho, wo, c, cin = 4, 4, 8, 5
        y = _rand((bi * rep, ho, wo, c), seed=31)
        idx = _idx((bi, ho, wo, c), seed=31)
        w = _rand((3, 3, cin, c), seed=32)
        got = pallas_deconv.fused_pallas_call(
            y, idx, w, (2, 2), relu=False, groups=1,
            impl="mxu", interpret=True, rows_per_block=2,
        )
        want = _pair(
            y, jnp.repeat(idx, rep, axis=0), w, (2, 2), (ho * 2, wo * 2),
            False, 1,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
        )


# ----------------------------------------------- certification + fallback


class TestCertification:
    def test_odd_extent_falls_back_silently(self):
        """A padded out_hw (pool did not divide the activation) is
        uncertified: the public op must produce the pair's exact bytes
        with NO pallas_call in the trace."""
        b, ho, wo, c, cin = 2, 3, 3, 8, 5
        y = _rand((b, ho, wo, c), seed=41)
        idx = _idx((b, ho, wo, c), seed=41)
        w = _rand((3, 3, cin, c), seed=42)

        def op(yy, ii, ww):
            return pallas_deconv.fused_unpool_backward(
                yy, ii, ww, (2, 2), (7, 7), mode="forced"
            )

        got = op(y, idx, w)
        want = _pair(y, idx, w, (2, 2), (7, 7), False, 1)
        assert jnp.array_equal(got, want)
        assert not _has_pallas(op, y, idx, w)

    def test_even_kernel_falls_back(self):
        y = _rand((1, 4, 4, 6), seed=43)
        idx = _idx((1, 4, 4, 6), seed=43)
        w = _rand((2, 2, 3, 6), seed=44)  # even kernel: uncertified
        assert not pallas_deconv.fused_supported(
            y.shape, idx.shape, w.shape, (2, 2), (8, 8), 1
        )

    def test_off_mode_never_engages(self):
        y = _rand((1, 4, 4, 6), seed=45)
        idx = _idx((1, 4, 4, 6), seed=45)
        w = _rand((3, 3, 3, 6), seed=46)

        def op(yy, ii, ww):
            return pallas_deconv.fused_unpool_backward(
                yy, ii, ww, (2, 2), (8, 8), mode="off"
            )

        assert not _has_pallas(op, y, idx, w)

    def test_forced_engages(self):
        y = _rand((1, 4, 4, 6), seed=45)
        idx = _idx((1, 4, 4, 6), seed=45)
        w = _rand((3, 3, 3, 6), seed=46)

        def op(yy, ii, ww):
            return pallas_deconv.fused_unpool_backward(
                yy, ii, ww, (2, 2), (8, 8), mode="forced"
            )

        assert _has_pallas(op, y, idx, w)

    def test_auto_disengages_off_tpu(self):
        """auto means "the compiled kernel where it pays" — on a CPU
        host it must resolve to the unfused pair, not the interpreter."""
        assert pallas_deconv.resolve_fused_unpool("auto") == "auto"
        if jax.default_backend() != "tpu":
            assert not pallas_deconv.fused_engaged("auto")

    def test_channel_mismatch_uncertified(self):
        # y channels not groups * idx channels
        assert not pallas_deconv.fused_supported(
            (1, 4, 4, 7), (1, 4, 4, 3), (3, 3, 2, 3), (2, 2), (8, 8), 2
        )
        # idx channels != kernel out channels
        assert not pallas_deconv.fused_supported(
            (1, 4, 4, 6), (1, 4, 4, 6), (3, 3, 2, 4), (2, 2), (8, 8), 1
        )


# ------------------------------------------------------- policy resolution


class TestResolveFusedUnpool:
    @pytest.mark.parametrize(
        "policy,want",
        [
            ("off", "off"), ("", "off"), ("0", "off"), ("false", "off"),
            ("no", "off"), ("OFF", "off"), ("auto", "auto"),
            ("FORCED", "forced"),
        ],
    )
    def test_vocabulary(self, policy, want):
        assert pallas_deconv.resolve_fused_unpool(policy) == want

    @pytest.mark.parametrize("policy", ["bogus", "64", "-1", True, "1.5"])
    def test_rejects_garbage(self, policy):
        with pytest.raises(ValueError, match="fused_unpool"):
            pallas_deconv.resolve_fused_unpool(policy)


# ----------------------------------------------------- engine env plumbing


class TestEngineKnob:
    def test_env_builds_fused_program(self, tiny_params, monkeypatch):
        """DECONV_FUSED_UNPOOL=forced must actually change the traced
        program (pallas_call present), off must not, and the outputs
        must stay bit-equal either way.  Env vars resolve OUTSIDE the
        visualizer cache, so monkeypatching between calls takes
        effect."""
        batch = _rand((2, 16, 16, 3), seed=7)

        def build():
            return get_visualizer(
                TINY, "b2c1", 4, "all", True, batched=True
            )

        monkeypatch.setenv("DECONV_FUSED_UNPOOL", "forced")
        assert _has_pallas(build(), tiny_params, batch)
        fused_out = build()(tiny_params, batch)["b2c1"]
        monkeypatch.setenv("DECONV_FUSED_UNPOOL", "off")
        assert not _has_pallas(build(), tiny_params, batch)
        base = build()(tiny_params, batch)["b2c1"]
        assert jnp.array_equal(base["images"], fused_out["images"])
        assert jnp.array_equal(base["indices"], fused_out["indices"])

    def test_composes_with_kpack(self, tiny_params):
        """fused over the packed tail: the grouped (groups=K) kernel
        form engages and stays bit-equal to both the packed-unfused and
        the vmapped baselines."""
        from deconv_api_tpu.engine.deconv import KPACK_FORCED_CHAN

        batch = _rand((2, 16, 16, 3), seed=8)
        base = get_visualizer(
            TINY, "b2c1", 4, "all", True, batched=True,
            fused_unpool="off",
        )(tiny_params, batch)["b2c1"]
        packed_fused_fn = get_visualizer(
            TINY, "b2c1", 4, "all", True, batched=True,
            kpack_chan=KPACK_FORCED_CHAN, fused_unpool="forced",
        )
        assert _has_pallas(packed_fused_fn, tiny_params, batch)
        pf = packed_fused_fn(tiny_params, batch)["b2c1"]
        assert jnp.array_equal(base["images"], pf["images"])
        assert jnp.array_equal(base["indices"], pf["indices"])

    def test_sweep_bit_parity(self, tiny_params):
        batch = _rand((2, 16, 16, 3), seed=9)
        off = get_visualizer(
            TINY, "b2c1", 4, "all", True, batched=True, sweep=True,
            fused_unpool="off",
        )(tiny_params, batch)
        on = get_visualizer(
            TINY, "b2c1", 4, "all", True, batched=True, sweep=True,
            fused_unpool="forced",
        )(tiny_params, batch)
        for name in off:
            assert jnp.array_equal(off[name]["images"], on[name]["images"])

    def test_engine_rejects_garbage(self, tiny_params):
        with pytest.raises(ValueError, match="fused_unpool"):
            get_visualizer(
                TINY, "b2c1", 4, "all", True, batched=True,
                fused_unpool="bogus",
            )


# ------------------------------------------------------- DAG normalisation


class TestDagInert:
    def test_autodeconv_validates_but_ignores(self, tiny_params):
        """The vjp walk has no pool->relu->conv triple to fuse: the
        policy is accepted (and validated) but the projection is
        identical."""
        from deconv_api_tpu.engine import autodeconv_visualizer
        from deconv_api_tpu.models.apply import spec_forward

        img = _rand((16, 16, 3), seed=9)
        base = autodeconv_visualizer(
            spec_forward(TINY), "b2c1", top_k=4, fused_unpool="off"
        )(tiny_params, img)
        fused = autodeconv_visualizer(
            spec_forward(TINY), "b2c1", top_k=4, fused_unpool="forced"
        )(tiny_params, img)
        assert jnp.array_equal(base["images"], fused["images"])
        with pytest.raises(ValueError, match="fused_unpool"):
            autodeconv_visualizer(
                spec_forward(TINY), "b2c1", top_k=4, fused_unpool="bogus"
            )

    def test_bundle_normalises_policy_out_of_cache_key(self, tiny_params):
        """A DAG bundle must hand back the SAME cached program for every
        policy value — and so must any bundle on a backend where the
        resolved policy disengages (auto on CPU)."""
        from deconv_api_tpu.models.apply import spec_forward
        from deconv_api_tpu.serving.models import ModelBundle

        bundle = ModelBundle(
            name="tiny_dag",
            params=tiny_params,
            image_size=16,
            preprocess=lambda x: x,
            layer_names=("b1c1", "b1c2", "b2c1"),
            dream_layers=(),
            forward_fn=spec_forward(TINY),
        )
        off = bundle.batched_visualizer("b2c1", "all", 4, fused_unpool="off")
        forced = bundle.batched_visualizer(
            "b2c1", "all", 4, fused_unpool="forced"
        )
        assert off is forced

    def test_auto_on_cpu_shares_the_off_program(self, tiny_params):
        """Sequential bundles too: auto on a CPU host must not compile a
        duplicate of the off program."""
        if jax.default_backend() == "tpu":
            pytest.skip("auto engages on TPU")
        from deconv_api_tpu.serving.models import spec_bundle

        bundle = spec_bundle(TINY, tiny_params)
        off = bundle.batched_visualizer("b2c1", "all", 4, fused_unpool="off")
        auto = bundle.batched_visualizer(
            "b2c1", "all", 4, fused_unpool="auto"
        )
        assert off is auto


# --------------------------------------------------------- serving (e2e)


def _service(fused_unpool: str):
    from deconv_api_tpu.config import ServerConfig
    from tests.test_serving import ServiceFixture

    cfg = ServerConfig(
        image_size=16,
        max_batch=4,
        batch_window_ms=1.0,
        compilation_cache_dir="",
        fused_unpool=fused_unpool,
    )
    return ServiceFixture(cfg)


class TestServingKnob:
    @pytest.mark.parametrize(
        "policy,want",
        [("off", "off"), ("auto", "off"), ("forced", "interpret")],
    )
    def test_config_reports_resolved_engagement(self, policy, want):
        """/v1/config must say what the policy actually reaches on this
        process — on a CPU host: auto disengages, forced runs the
        interpret body (on TPU the same field reads 'kernel')."""
        import httpx

        if jax.default_backend() == "tpu":  # pragma: no cover — CI is CPU
            want = {"off": "off", "auto": "kernel", "forced": "kernel"}[
                policy
            ]
        with _service(policy) as s:
            cfg = httpx.get(s.base_url + "/v1/config").json()
            assert cfg["fused_unpool"] == policy
            assert cfg["fused_unpool_resolved"] == want

    def test_boot_rejects_bad_policy(self):
        from deconv_api_tpu.config import ServerConfig
        from deconv_api_tpu.serving.app import DeconvService

        params = init_params(TINY, jax.random.PRNGKey(3))
        with pytest.raises(ValueError, match="fused_unpool"):
            DeconvService(
                ServerConfig(
                    image_size=16, fused_unpool="bogus",
                    compilation_cache_dir="",
                ),
                spec=TINY, params=params,
            )

    def test_e2e_byte_parity_fused_vs_off(self):
        """The serving contract behind the knob: the SAME request bytes
        come back with fused_unpool forced vs off — deconv, sweep and
        dream alike (dreams are inert by design) — with the response
        cache bypassed so the device program actually runs on both
        sides.  Since `off` is the pre-round-20 program verbatim, this
        pins both the default's byte-stability and the engaged
        interpret body's parity end to end."""
        import httpx

        from tests.test_serving import _data_url

        headers = {"Cache-Control": "no-cache, no-store"}
        requests = [
            ("/v1/deconv", {"file": _data_url(5), "layer": "b2c1"}),
            (
                "/v1/deconv",
                {"file": _data_url(5), "layer": "b2c1", "sweep": "1"},
            ),
            (
                "/v1/dream",
                {
                    "file": _data_url(5), "layers": "b2c1", "steps": "2",
                    "octaves": "2", "lr": "0.05",
                },
            ),
        ]
        bodies: dict[str, list[bytes]] = {"off": [], "forced": []}
        for policy in ("off", "forced"):
            with _service(policy) as s:
                for path, form in requests:
                    r = httpx.post(
                        s.base_url + path, data=form, headers=headers,
                        timeout=120,
                    )
                    assert r.status_code == 200, r.text
                    assert r.headers["x-cache"] == "bypass"
                    bodies[policy].append(r.content)
        for (path, form), off, forced in zip(
            requests, bodies["off"], bodies["forced"]
        ):
            assert off == forced, f"{path} {form.get('sweep', '')} drifted"


# ------------------------------------------------- real backbones (slow)


@pytest.mark.slow
class TestRealBackbones:
    """VGG16 fused-vs-unfused bit parity at real channel widths (the
    C=64/128 tail at 224² the probe times), composed with the packed
    tail — the exact endgame configuration headline_fused profiles."""

    def test_fused_tail_bit_parity(self):
        from deconv_api_tpu.engine.deconv import KPACK_FORCED_CHAN
        from deconv_api_tpu.models.vgg16 import vgg16_init

        spec, params = vgg16_init()
        batch = _rand((1, 224, 224, 3), seed=11) * 30.0
        layer = "block3_conv1"
        base = get_visualizer(
            spec, layer, 8, "all", True, batched=True, kpack_chan=0,
            fused_unpool="off",
        )(params, batch)[layer]
        fused = get_visualizer(
            spec, layer, 8, "all", True, batched=True,
            kpack_chan=KPACK_FORCED_CHAN, fused_unpool="forced",
        )(params, batch)[layer]
        assert jnp.array_equal(base["indices"], fused["indices"])
        assert jnp.array_equal(base["images"], fused["images"])
