"""Driver-artifact contract for bench.py (VERDICT r3 item 1).

Round 3's BENCH artifact died rc=124 with nothing on stdout because the
driver's outer ``timeout`` killed the bench parent before its guaranteed
JSON line.  These tests pin the two defenses: the budget-derived child
schedule and the parent signal net.  They spawn ``python bench.py`` as the
driver does and assert that stdout carries exactly one machine-parseable
JSON line under each failure mode.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

BENCH = Path(__file__).resolve().parent.parent / "bench.py"
REQUIRED_KEYS = {"metric", "value", "unit", "vs_baseline"}


def _json_lines(stdout: bytes) -> list[dict]:
    out = []
    for line in stdout.decode(errors="replace").splitlines():
        line = line.strip()
        if line.startswith("{"):
            out.append(json.loads(line))
    return out


def test_sigterm_mid_run_still_emits_one_parseable_line():
    """External ``timeout`` sends SIGTERM first; the artifact must survive.

    The SIGTERM is sent once the parent logs its first "bench attempt"
    line — the signal net is installed by then and the measurement child
    (tens of seconds even on CPU) is starting, so the signal lands
    mid-measurement, the round-3 failure window.  A fixed sleep is not
    enough: this image's sitecustomize costs ~2s of interpreter startup
    before bench.py's first line executes."""
    mark = f"bench-test-{os.getpid()}-{time.monotonic_ns()}"
    env = dict(os.environ, DECONV_BENCH_TEST_MARK=mark)
    # own process group so failure paths can reap the measurement
    # grandchild too (SIGKILL to the parent bypasses its signal net,
    # which is what normally kills the child)
    proc = subprocess.Popen(
        [sys.executable, str(BENCH)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        cwd=BENCH.parent,
        env=env,
        start_new_session=True,
    )
    ready = threading.Event()
    stderr_chunks: list[bytes] = []

    def _drain_stderr() -> None:
        for raw in proc.stderr:
            stderr_chunks.append(raw)
            if b"bench attempt" in raw:
                ready.set()
        ready.set()  # EOF: unblock the waiter either way

    def _killpg() -> None:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    reader = threading.Thread(target=_drain_stderr, daemon=True)
    reader.start()
    try:
        assert ready.wait(timeout=60), "parent never reached its attempt loop"
        assert proc.poll() is None, (
            f"parent exited early: {b''.join(stderr_chunks)!r}"
        )
        time.sleep(0.5)  # let the measurement child spawn: mid-measurement
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pytest.fail("bench parent did not exit after SIGTERM")
        stdout = proc.stdout.read()  # stderr is owned by the reader thread
        reader.join(timeout=5)
        lines = _json_lines(stdout)
        assert len(lines) == 1, f"expected exactly one JSON line, got {lines!r}"
        payload = lines[0]
        assert REQUIRED_KEYS <= set(payload), payload
        assert payload["value"] is None
        assert "signal 15" in payload["error"]
        # no orphaned measurement child from THIS run (identified by the env
        # marker, so concurrent legitimate bench runs don't trip the check);
        # the scan runs BEFORE the finally's group kill, so a leak is
        # detected rather than silently reaped
        time.sleep(0.5)
        live = []
        for p in Path("/proc").iterdir():
            if not p.name.isdigit():
                continue
            try:
                environ = (p / "environ").read_bytes()
            except OSError:
                continue
            if mark.encode() in environ and int(p.name) != proc.pid:
                live.append(p.name)
        assert not live, f"orphaned bench children: {live}"
    finally:
        _killpg()  # no-op on the happy path (group is already gone)
        proc.wait()


@pytest.mark.slow
def test_budget_exhaustion_falls_back_to_cpu_line():
    """Tunnel-down shape: TPU attempts bounded by the budget, then a CPU
    fallback measurement line — all before any plausible outer timeout."""
    env = dict(os.environ)
    env.update(
        DECONV_BENCH_BUDGET="240",
        DECONV_BENCH_TIMEOUT="5",
        DECONV_BENCH_TRIES="2",
        DECONV_BENCH_BATCH="1",
        DECONV_BENCH_ITERS="1",
    )
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, str(BENCH)],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        timeout=300,
        cwd=BENCH.parent,
        env=env,
    )
    wall = time.monotonic() - t0
    lines = _json_lines(proc.stdout)
    assert len(lines) == 1, f"expected exactly one JSON line, got {lines!r}"
    payload = lines[0]
    assert REQUIRED_KEYS <= set(payload), payload
    # either the 5s "TPU" child finished (CPU test env) or the fallback ran;
    # in both cases the line is a real measurement, not an error
    assert payload.get("error") is None, payload
    assert payload["value"] is not None and payload["value"] > 0
    assert wall < 240, f"exceeded its own budget: {wall:.0f}s"
