"""The bf16-forward opt-in (`DECONV_DTYPE=bfloat16`, round 4c) on every
engine surface, at test scale.

The full-depth parity characterisation lives in the slow test
(tests/test_full_depth_parity.py: 35.3 dB deprocessed, below the 40 dB
north-star bar — which is why bf16 forward is opt-in, not default).
These fast tests pin that the opt-in *works*: selection stays stable
(fp32 ranking accumulator in the shared _select_top), projections stay
close to the fp32 engine, and the serving path accepts the config.
"""

import base64
from urllib.parse import unquote

import jax
import jax.numpy as jnp
import numpy as np

from deconv_api_tpu.config import ServerConfig
from deconv_api_tpu.engine import autodeconv_visualizer, get_visualizer
from deconv_api_tpu.models.apply import spec_forward
from deconv_api_tpu.models.spec import init_params
from tests.test_engine_parity import TINY
from tests.test_serving import ServiceFixture, _data_url


def _rel_l2(a, b):
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    return float(np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-12))


def _paired_rel_l2(got, ref):
    """Channel-paired projection error + selection-overlap floor.

    Rank ORDER under a bf16 forward is backend-dependent (near-tied
    channel sums round differently on native-TPU vs CPU-emulated bf16),
    and top-K MEMBERSHIP itself can flip for a near-threshold channel, so
    require k-1 overlap (mirroring tools/full_depth_parity.py's
    paired_count tolerance) and compare images channel-to-channel over
    the overlapping channels only, rather than rank-to-rank."""
    gi = np.asarray(got["indices"]).tolist()
    ri = np.asarray(ref["indices"]).tolist()
    overlap = set(gi) & set(ri)
    assert len(overlap) >= len(ri) - 1, (gi, ri)
    assert abs(
        int(np.asarray(got["valid"]).sum()) - int(np.asarray(ref["valid"]).sum())
    ) <= 1
    got_by_chan = {c: np.asarray(got["images"])[r] for r, c in enumerate(gi)}
    ref_by_chan = {c: np.asarray(ref["images"])[r] for r, c in enumerate(ri)}
    paired = [c for c in ri if c in overlap]
    a = np.stack([got_by_chan[c] for c in paired])
    b = np.stack([ref_by_chan[c] for c in paired])
    return _rel_l2(a, b)


def _cast_tree(params, dtype):
    return jax.tree.map(lambda p: p.astype(dtype), params)


def test_sequential_engine_bf16_forward_matches_fp32():
    params = init_params(TINY, jax.random.PRNGKey(42))
    img = jax.random.normal(jax.random.PRNGKey(7), (16, 16, 3))
    # fwd_lowc_bf16 pinned: the env fallback must not leak an exported
    # DECONV_FWD_LOWC_BF16 into the reference arms of these comparisons.
    fn = get_visualizer(
        TINY, "b2c1", 8, "all", True, backward_dtype="bfloat16",
        fwd_lowc_bf16=0,
    )

    ref = fn(params, img.astype(jnp.float32))["b2c1"]
    got = fn(
        _cast_tree(params, jnp.bfloat16), img.astype(jnp.bfloat16)
    )["b2c1"]

    assert got["images"].dtype == jnp.bfloat16
    # projections carry bf16 forward rounding, amplified at 16x16 toy scale
    # where one flipped pool switch moves a visible fraction of the norm
    # (measured 0.07 rel-L2 here; full-depth parity is pinned in dB by the
    # slow test).  The bound catches a broken chain (wrong kernel/switch
    # wiring reads ~1.0), not precision drift.
    assert _paired_rel_l2(got, ref) < 0.3


def test_sequential_engine_partial_bf16_forward():
    """DECONV_FWD_LOWC_BF16: bf16 only below the channel threshold, fp32
    above — selection set and output dtype must match the fp32 engine
    (the selection layer sits above the threshold)."""
    params = init_params(TINY, jax.random.PRNGKey(42))
    img = jax.random.normal(jax.random.PRNGKey(7), (16, 16, 3))
    ref = get_visualizer(
        TINY, "b2c1", 8, "all", True, fwd_lowc_bf16=0
    )(params, img)["b2c1"]
    got = get_visualizer(
        TINY, "b2c1", 8, "all", True, fwd_lowc_bf16=8
    )(params, img)["b2c1"]
    assert got["images"].dtype == ref["images"].dtype  # fp32 above threshold
    assert _paired_rel_l2(got, ref) < 0.3


def test_partial_bf16_never_leaks_into_outputs():
    """A requested layer whose whole truncated chain sits inside the bf16
    prefix (every conv <= threshold) must still return fp32 images and
    select on upcast activations — the prefix may not leak out of the
    forward walk."""
    params = init_params(TINY, jax.random.PRNGKey(42))
    img = jax.random.normal(jax.random.PRNGKey(7), (16, 16, 3))
    got = get_visualizer(
        TINY, "b1c2", 8, "all", True, fwd_lowc_bf16=8
    )(params, img)["b1c2"]
    assert got["images"].dtype == jnp.float32
    assert got["sums"].dtype == jnp.float32
    assert bool(np.isfinite(np.asarray(got["images"], np.float64)).all())


def test_partial_bf16_disabled_when_first_conv_too_wide():
    """Threshold below the first conv's width: no layer would run bf16,
    so the knob must be a no-op (bit-identical to fp32), not an input
    quantization for zero gain."""
    params = init_params(TINY, jax.random.PRNGKey(42))
    img = jax.random.normal(jax.random.PRNGKey(7), (16, 16, 3))
    ref = get_visualizer(
        TINY, "b2c1", 8, "all", True, fwd_lowc_bf16=0
    )(params, img)["b2c1"]
    got = get_visualizer(
        TINY, "b2c1", 8, "all", True, fwd_lowc_bf16=4
    )(params, img)["b2c1"]
    np.testing.assert_array_equal(np.asarray(got["images"]), np.asarray(ref["images"]))


def test_autodeconv_engine_bf16_forward_matches_fp32():
    params = init_params(TINY, jax.random.PRNGKey(42))
    img = jax.random.normal(jax.random.PRNGKey(7), (16, 16, 3))
    fn = autodeconv_visualizer(spec_forward(TINY), "b2c1", top_k=8)

    ref = fn(params, img.astype(jnp.float32))
    got = fn(_cast_tree(params, jnp.bfloat16), img.astype(jnp.bfloat16))

    assert _paired_rel_l2(got, ref) < 0.3


def test_serving_with_bf16_forward_config():
    import cv2
    import httpx

    cfg = ServerConfig(
        image_size=16, max_batch=2, batch_window_ms=1.0,
        compilation_cache_dir="", dtype="bfloat16",
    )
    with ServiceFixture(cfg) as s:
        r = httpx.post(
            s.base_url + "/",
            data={"file": _data_url(), "layer": "b2c1"},
            timeout=60,
        )
        assert r.status_code == 200, r.text
        raw = base64.b64decode(unquote(r.json().split(",", 1)[1]))
        img = cv2.imdecode(np.frombuffer(raw, np.uint8), cv2.IMREAD_COLOR)
        assert img.shape == (32, 32, 3)  # 2x2 grid of 16x16 tiles
        assert img.std() > 0  # not a blank grid
