"""Golden-data validation of the pretrained-weight loaders.

VERDICT r2 flagged that every weight-loader test consumed synthetic h5
fixtures built by the tests themselves — loader and fixture shared the same
layout assumptions, so a wrong assumption about real Keras file layout
would pass silently.  This module breaks that loop two ways:

1. A COMMITTED fixture (tests/fixtures/golden/) written by real Keras —
   authentic legacy-h5 group nesting and naming (`model_weights/<layer>/
   <layer>/kernel`), generated once by tools/make_golden_fixture.py and
   hash-pinned here.  Works without Keras installed.
2. LIVE golden tests (skipped when Keras is absent): build each
   keras.applications model with random seeded weights, save a genuine h5,
   load it through our loaders, and compare our forward activations
   against Keras's own `predict` on an identical input — end-to-end
   load → forward → activation parity at every major endpoint, including
   all 11 InceptionV3 mixed blocks (validating the 94-conv construction-
   order table in models/dag_weights.py against real Keras naming).

A deliberate same-shape-swap test proves the check is SENSITIVE: swapping
two identically-shaped InceptionV3 conv kernels must break activation
parity (the failure mode VERDICT called un-catchable by shape checks).

Reference parity target: the reference's startup weight load
(/root/reference/app/main.py:17).
"""

from __future__ import annotations

import hashlib
import os

import numpy as np
import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "golden")

# sha256 pins from tools/make_golden_fixture.py — a mismatch means the
# committed artifacts were regenerated or corrupted; update deliberately.
H5_SHA256 = "b0969ec43c0949b7c3ec522f752b02eca6db29780831da73b89971656e4fd397"
NPZ_SHA256 = "17de247280de4340a866b2a5952a1e3421d9e229ba45cb41d538209226d839f5"


def _rel_err(ref: np.ndarray, got: np.ndarray) -> float:
    ref = np.asarray(ref, np.float64)
    got = np.asarray(got, np.float64)
    return float(np.abs(ref - got).max()) / max(float(np.abs(ref).max()), 1e-6)


def _sha256(path: str) -> str:
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


# ------------------------------------------------------- committed fixture


class TestCommittedFixture:
    """Real-Keras-written h5 + expected activations, no Keras required."""

    def test_fixture_integrity(self):
        assert _sha256(os.path.join(FIXTURES, "vgg16_block1.h5")) == H5_SHA256
        assert (
            _sha256(os.path.join(FIXTURES, "vgg16_block1_expected.npz"))
            == NPZ_SHA256
        )

    def test_load_and_forward_matches_keras_activations(self):
        import dataclasses

        import jax

        from deconv_api_tpu.models.apply import spec_forward
        from deconv_api_tpu.models.spec import init_params
        from deconv_api_tpu.models.vgg16 import VGG16_SPEC
        from deconv_api_tpu.models.weights import load_weights

        spec = dataclasses.replace(
            VGG16_SPEC.truncated("block1_pool"), input_shape=(64, 64, 3)
        )
        params = init_params(spec, jax.random.PRNGKey(0))
        params = load_weights(
            spec, os.path.join(FIXTURES, "vgg16_block1.h5"), params
        )
        exp = np.load(os.path.join(FIXTURES, "vgg16_block1_expected.npz"))
        _, acts = spec_forward(spec)(params, exp["x"])
        assert _rel_err(exp["block1_conv1"], acts["block1_conv1"]) < 1e-4
        assert _rel_err(exp["block1_pool"], acts["block1_pool"]) < 1e-4

    def test_random_init_does_not_match(self):
        """Sensitivity: without the real weights, the same forward must NOT
        reproduce the expected activations — the comparison is not vacuous."""
        import dataclasses

        import jax

        from deconv_api_tpu.models.apply import spec_forward
        from deconv_api_tpu.models.spec import init_params
        from deconv_api_tpu.models.vgg16 import VGG16_SPEC

        spec = dataclasses.replace(
            VGG16_SPEC.truncated("block1_pool"), input_shape=(64, 64, 3)
        )
        params = init_params(spec, jax.random.PRNGKey(0))
        exp = np.load(os.path.join(FIXTURES, "vgg16_block1_expected.npz"))
        _, acts = spec_forward(spec)(params, exp["x"])
        assert _rel_err(exp["block1_conv1"], acts["block1_conv1"]) > 1e-2


# ------------------------------------------------------------ live keras

keras = pytest.importorskip("keras", reason="live golden tests need Keras")


@pytest.fixture(scope="module")
def keras_h5(tmp_path_factory):
    """Build each keras.applications model once (random seeded weights),
    save a genuine legacy h5, and capture Keras's own activations."""
    tmp = tmp_path_factory.mktemp("keras_golden")

    def build(factory, input_shape, probe_layers, rng_seed):
        keras.utils.set_random_seed(0)
        model = factory(
            weights=None, include_top=False, input_shape=input_shape
        )
        path = str(tmp / f"{factory.__name__.lower()}.h5")
        model.save(path)
        x = (
            np.random.default_rng(rng_seed)
            .normal(0, 1, (1,) + input_shape)
            .astype(np.float32)
        )
        probe = keras.Model(
            model.input, [model.get_layer(n).output for n in probe_layers]
        )
        outs = probe.predict(x, verbose=0)
        if not isinstance(outs, list):
            outs = [outs]
        return path, x, dict(zip(probe_layers, outs))

    return build


def _check_acts(expected: dict, ours: dict, tol: float = 2e-4):
    for name, ref in expected.items():
        got = np.asarray(ours[name])
        if got.ndim == ref.ndim - 1:
            got = got[None]
        err = _rel_err(ref, got)
        assert err < tol, f"{name}: rel_err {err:.2e} >= {tol}"


def test_vgg16_golden(keras_h5):
    import dataclasses

    import jax

    from deconv_api_tpu.models.apply import spec_forward
    from deconv_api_tpu.models.spec import init_params
    from deconv_api_tpu.models.vgg16 import VGG16_SPEC
    from deconv_api_tpu.models.weights import load_weights

    names = ["block1_conv1", "block2_conv2", "block3_conv3", "block5_conv1", "block5_pool"]
    path, x, expected = keras_h5(
        keras.applications.VGG16, (64, 64, 3), names, rng_seed=0
    )
    spec = dataclasses.replace(
        VGG16_SPEC.truncated("block5_pool"), input_shape=(64, 64, 3)
    )
    params = load_weights(spec, path, init_params(spec, jax.random.PRNGKey(0)))
    _, acts = spec_forward(spec)(params, x)
    _check_acts(expected, acts)


def test_vgg19_golden(keras_h5):
    """VGG19 rides the same name-keyed h5 loader as VGG16; the golden pins
    the extra block3/4/5 conv4 layers against Keras's own activations."""
    import dataclasses

    import jax

    from deconv_api_tpu.models.apply import spec_forward
    from deconv_api_tpu.models.spec import init_params
    from deconv_api_tpu.models.vgg19 import VGG19_SPEC
    from deconv_api_tpu.models.weights import load_weights

    names = ["block1_conv2", "block3_conv4", "block4_conv4", "block5_conv4", "block5_pool"]
    path, x, expected = keras_h5(
        keras.applications.VGG19, (64, 64, 3), names, rng_seed=3
    )
    spec = dataclasses.replace(
        VGG19_SPEC.truncated("block5_pool"), input_shape=(64, 64, 3)
    )
    params = load_weights(spec, path, init_params(spec, jax.random.PRNGKey(0)))
    _, acts = spec_forward(spec)(params, x)
    _check_acts(expected, acts)


def test_resnet50_golden(keras_h5):
    from deconv_api_tpu.models.dag_weights import load_resnet50_h5
    from deconv_api_tpu.models.resnet50 import resnet50_forward, resnet50_init

    names = [
        "conv1_relu", "pool1_pool", "conv2_block1_out", "conv3_block4_out",
        "conv4_block6_out", "conv5_block3_out",
    ]
    path, x, expected = keras_h5(
        keras.applications.ResNet50, (96, 96, 3), names, rng_seed=1
    )
    params = load_resnet50_h5(path, resnet50_init())
    _, acts = resnet50_forward(params, x)
    _check_acts(expected, acts)


def test_mobilenet_v1_golden(keras_h5):
    """MobileNetV1: name-keyed conv/dw/pw mapping incl. the depthwise
    kernel transpose ((kh,kw,C,1) -> feature_group_count HWIO) and the
    (0,1)-padded stride-2 grid, pinned against Keras's own activations."""
    from deconv_api_tpu.models.dag_weights import load_mobilenet_v1_h5
    from deconv_api_tpu.models.mobilenet_v1 import (
        mobilenet_v1_forward,
        mobilenet_v1_init,
    )

    names = [
        "conv1_relu", "conv_dw_1_relu", "conv_pw_2_relu", "conv_pw_6_relu",
        "conv_pw_11_relu", "conv_pw_13_relu",
    ]
    path, x, expected = keras_h5(
        keras.applications.MobileNet, (128, 128, 3), names, rng_seed=4
    )
    params = load_mobilenet_v1_h5(path, mobilenet_v1_init())
    _, acts = mobilenet_v1_forward(params, x)
    _check_acts(expected, acts)


def test_mobilenet_v2_golden(keras_h5):
    """MobileNetV2: inverted residuals with linear bottlenecks — the
    name-keyed expand/depthwise/project mapping and the residual-add
    placement pinned against Keras's own activations."""
    from deconv_api_tpu.models.dag_weights import load_mobilenet_v2_h5
    from deconv_api_tpu.models.mobilenet_v2 import (
        mobilenet_v2_forward,
        mobilenet_v2_init,
    )

    names = [
        "Conv1_relu", "expanded_conv_project_BN", "block_1_expand_relu",
        "block_3_depthwise_relu", "block_6_project_BN", "block_12_add",
        "out_relu",
    ]
    path, x, expected = keras_h5(
        keras.applications.MobileNetV2, (128, 128, 3), names, rng_seed=5
    )
    params = load_mobilenet_v2_h5(path, mobilenet_v2_init())
    _, acts = mobilenet_v2_forward(params, x)
    _check_acts(expected, acts)


@pytest.fixture(scope="module")
def inception_golden(keras_h5):
    names = [f"mixed{i}" for i in range(11)]
    return keras_h5(
        keras.applications.InceptionV3, (128, 128, 3), names, rng_seed=2
    )


def test_inception_v3_golden(inception_golden):
    """End-to-end validation of the 94-conv construction-order table in
    models/dag_weights.py against real Keras auto-indexed layer names."""
    from deconv_api_tpu.models.dag_weights import load_inception_v3_h5
    from deconv_api_tpu.models.inception_v3 import (
        inception_v3_forward,
        inception_v3_init,
    )

    path, x, expected = inception_golden
    params = load_inception_v3_h5(path, inception_v3_init())
    _, acts = inception_v3_forward(params, x)
    _check_acts(expected, acts)


def test_inception_v3_same_shape_swap_is_caught(inception_golden, tmp_path):
    """Swap two identically-shaped conv kernels in the REAL Keras h5 and
    assert activation parity breaks — the construction-order failure mode
    VERDICT r2 called un-catchable by shape checks alone is catchable by
    the golden activation comparison."""
    import shutil

    import h5py

    from deconv_api_tpu.models.dag_weights import (
        INCEPTION_V3_CONV_ORDER,
        load_inception_v3_h5,
    )
    from deconv_api_tpu.models.inception_v3 import (
        inception_v3_forward,
        inception_v3_init,
    )

    path, x, expected = inception_golden
    swapped = str(tmp_path / "swapped.h5")
    shutil.copy(path, swapped)
    # mixed4's b7d_2 and b7d_4 are both (7, 1, 128, 128) — find their
    # conv2d indices from the order table and swap the kernel datasets.
    i1 = INCEPTION_V3_CONV_ORDER.index(("mixed4", "b7d_2"))
    i2 = INCEPTION_V3_CONV_ORDER.index(("mixed4", "b7d_4"))
    with h5py.File(swapped, "r+") as f:
        root = f["model_weights"] if "model_weights" in f else f

        def kernel_ds(idx):
            name = "conv2d" if idx == 0 else f"conv2d_{idx}"
            grp = root[name]
            ds = []
            grp.visititems(
                lambda n, o: ds.append(o)
                if isinstance(o, h5py.Dataset) and "kernel" in n
                else None
            )
            assert len(ds) == 1
            return ds[0]

        d1, d2 = kernel_ds(i1), kernel_ds(i2)
        assert d1.shape == d2.shape  # same-shape: a shape check cannot catch this
        a, b = np.asarray(d1), np.asarray(d2)
        d1[...], d2[...] = b, a

    params = load_inception_v3_h5(swapped, inception_v3_init())  # loads fine
    _, acts = inception_v3_forward(params, x)
    err = _rel_err(expected["mixed4"], np.asarray(acts["mixed4"]))
    assert err > 1e-2, "same-shape swap went undetected by activation parity"
