"""End-to-end parity: the jitted deconv engine vs the independent NumPy
oracle, on a small VGG-shaped model with random weights."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deconv_api_tpu.engine import get_visualizer, visualize, visualize_all_layers
from deconv_api_tpu.models.spec import Layer, ModelSpec, init_params
from tests import reference_numpy as ref

TINY = ModelSpec(
    name="tiny_vgg",
    input_shape=(16, 16, 3),
    layers=(
        Layer("input_1", "input"),
        Layer("b1c1", "conv", activation="relu", filters=8),
        Layer("b1c2", "conv", activation="relu", filters=8),
        Layer("b1p", "pool"),
        Layer("b2c1", "conv", activation="relu", filters=12),
        Layer("b2p", "pool"),
        Layer("flatten", "flatten"),
        Layer("fc1", "dense", activation="relu", filters=20),
        Layer("predictions", "dense", activation="softmax", filters=10),
    ),
)


def _np_spec():
    return [
        {"name": "input_1", "kind": "input"},
        {"name": "b1c1", "kind": "conv", "activation": "relu"},
        {"name": "b1c2", "kind": "conv", "activation": "relu"},
        {"name": "b1p", "kind": "pool", "pool_size": (2, 2)},
        {"name": "b2c1", "kind": "conv", "activation": "relu"},
        {"name": "b2p", "kind": "pool", "pool_size": (2, 2)},
        {"name": "flatten", "kind": "flatten"},
        {"name": "fc1", "kind": "dense", "activation": "relu"},
        {"name": "predictions", "kind": "dense", "activation": "softmax"},
    ]


@pytest.fixture(scope="module")
def setup():
    params = init_params(TINY, jax.random.PRNGKey(42))
    np_params = jax.tree.map(lambda a: np.asarray(a, np.float64), params)
    img = np.asarray(
        jax.random.normal(jax.random.PRNGKey(7), (16, 16, 3)), np.float32
    )
    return params, np_params, img


@pytest.mark.parametrize("layer_name", ["b1c2", "b1p", "b2c1", "fc1", "predictions"])
@pytest.mark.parametrize("mode", ["all", "max"])
def test_single_layer_parity(setup, layer_name, mode):
    params, np_params, img = setup
    got = visualize(TINY, params, jnp.asarray(img), layer_name, mode=mode)
    want = ref.visualize_all_layers(
        _np_spec(), np_params, img[None].astype(np.float64), layer_name, mode
    )[layer_name]
    valid = np.asarray(got["valid"])
    idxs = np.asarray(got["indices"])
    images = np.asarray(got["images"])
    assert valid.sum() == len(want), (
        f"engine found {valid.sum()} positive filters, oracle {len(want)}"
    )
    oracle_idx = [
        i
        for i, _ in ref.find_top_filters(
            _oracle_output(np_params, img, layer_name), top=8
        )
    ]
    np.testing.assert_array_equal(idxs[: len(oracle_idx)], oracle_idx)
    for k in range(int(valid.sum())):
        np.testing.assert_allclose(
            images[k], want[k], rtol=1e-3, atol=1e-4,
            err_msg=f"layer {layer_name} filter rank {k}",
        )


def _oracle_output(np_params, img, layer_name):
    spec = _np_spec()
    names = [l["name"] for l in spec]
    entries = ref.build_entries(spec[: names.index(layer_name) + 1], np_params)
    x = img[None].astype(np.float64)
    for e in entries:
        x = e.up(x)
        e.up_data = x
    return next(e for e in entries if e.name == layer_name).up_data


def test_all_layers_sweep_parity(setup):
    params, np_params, img = setup
    got = visualize_all_layers(TINY, params, jnp.asarray(img), "b2c1")
    want = ref.visualize_all_layers(
        _np_spec(), np_params, img[None].astype(np.float64), "b2c1", "all"
    )
    assert set(got) == set(want)
    for name in want:
        valid = np.asarray(got[name]["valid"])
        assert valid.sum() == len(want[name])
        for k in range(len(want[name])):
            np.testing.assert_allclose(
                np.asarray(got[name]["images"][k]), want[name][k],
                rtol=1e-3, atol=1e-4, err_msg=f"{name}[{k}]",
            )


def test_bug_compat_off_differs(setup):
    """bug_compat=False drops the double-ReLU — output must differ."""
    params, _, img = setup
    a = visualize(TINY, params, jnp.asarray(img), "b2c1", bug_compat=True)
    b = visualize(TINY, params, jnp.asarray(img), "b2c1", bug_compat=False)
    assert not np.allclose(np.asarray(a["images"]), np.asarray(b["images"]))


def test_illegal_mode_raises(setup):
    params, _, img = setup
    with pytest.raises(ValueError, match="illegal visualize mode"):
        visualize(TINY, params, jnp.asarray(img), "b2c1", mode="banana")


def test_unknown_layer_raises(setup):
    params, _, img = setup
    with pytest.raises(KeyError, match="no layer"):
        visualize(TINY, params, jnp.asarray(img), "nope")


def test_mixed_precision_backward_parity():
    """bf16 backward projection must be visually indistinguishable from
    fp32 after deprocess quantisation (>40dB PSNR target; selection exact)."""
    import jax

    from deconv_api_tpu.engine import get_visualizer
    from deconv_api_tpu.models.spec import init_params
    from deconv_api_tpu.serving.codec import deprocess_image

    params = init_params(TINY, jax.random.PRNGKey(0))
    img = jax.random.normal(jax.random.PRNGKey(7), TINY.input_shape) * 5.0
    f32 = get_visualizer(TINY, "b2c1", 4, "all", True)
    mix = get_visualizer(TINY, "b2c1", 4, "all", True, backward_dtype="bfloat16")
    o32 = f32(params, img)["b2c1"]
    omx = mix(params, img)["b2c1"]
    np.testing.assert_array_equal(
        np.asarray(o32["indices"]), np.asarray(omx["indices"])
    )
    i32 = np.stack([deprocess_image(np.asarray(x, np.float64)) for x in o32["images"]])
    imx = np.stack([deprocess_image(np.asarray(x, np.float64)) for x in omx["images"]])
    mse = np.mean((i32.astype(np.float64) - imx.astype(np.float64)) ** 2)
    psnr = 10 * np.log10(255.0**2 / max(mse, 1e-12))
    assert psnr > 40.0, f"mixed-precision PSNR {psnr:.1f} dB under target"


MID = ModelSpec(
    name="mid_vgg",
    input_shape=(64, 64, 3),
    layers=(
        Layer("input_1", "input"),
        Layer("b1c1", "conv", activation="relu", filters=16),
        Layer("b1c2", "conv", activation="relu", filters=16),
        Layer("b1p", "pool"),
        Layer("b2c1", "conv", activation="relu", filters=32),
        Layer("b2c2", "conv", activation="relu", filters=32),
        Layer("b2p", "pool"),
        Layer("b3c1", "conv", activation="relu", filters=48),
        Layer("b3c2", "conv", activation="relu", filters=48),
        Layer("b3c3", "conv", activation="relu", filters=48),
        Layer("b3p", "pool"),
    ),
)


@pytest.mark.slow
def test_mid_size_depth_parity():
    """VERDICT r1 #4: oracle parity beyond the 16x16 toy — 64x64, 3 blocks,
    deepest conv, full top-8.  Run with -m slow (excluded by default); the
    FULL-depth 224x224 artifact lives in tools/full_depth_parity.py with
    results recorded in BASELINE.md."""
    spec = MID
    np_spec = []
    for l in spec.layers:
        d = {"name": l.name, "kind": l.kind}
        if l.kind in ("conv", "dense"):
            d["activation"] = l.activation
        if l.kind == "pool":
            d["pool_size"] = tuple(l.pool_size)
        np_spec.append(d)
    params = init_params(spec, jax.random.PRNGKey(11))
    np_params = jax.tree.map(lambda a: np.asarray(a, np.float64), params)
    img = np.asarray(
        jax.random.normal(jax.random.PRNGKey(5), (64, 64, 3)), np.float64
    ) * 20.0

    got = visualize(spec, params, jnp.asarray(img, jnp.float32), "b3c3")
    want = ref.visualize_all_layers(np_spec, np_params, img[None], "b3c3")["b3c3"]
    valid = int(np.asarray(got["valid"]).sum())
    assert valid == len(want)
    for k in range(valid):
        np.testing.assert_allclose(
            np.asarray(got["images"][k]), want[k], rtol=1e-3, atol=1e-3,
            err_msg=f"b3c3 filter rank {k}",
        )


class TestKPack:
    """The opt-in K-packed backward tail (engine/deconv.py kpack_chan)
    must be exactly equivalent to the vmapped chain — grouped convs with
    a per-group-identical tiled kernel reduce in the same order."""

    def test_kpack_matches_default_fp32(self, setup):
        from deconv_api_tpu.engine import get_visualizer

        params, _, img = setup
        batch = jnp.asarray(np.stack([img, img[::-1]]))
        # TINY's low-channel tail: thresholds cover b1 (8ch) and b2 (12ch)
        for layer_name, kc in [("b2c1", 8), ("b2c1", 16), ("b1c2", 16)]:
            base = get_visualizer(TINY, layer_name, 4, "all", True, batched=True,
                                  kpack_chan=0)(params, batch)[layer_name]
            pack = get_visualizer(TINY, layer_name, 4, "all", True, batched=True,
                                  kpack_chan=kc)(params, batch)[layer_name]
            np.testing.assert_array_equal(
                np.asarray(base["indices"]), np.asarray(pack["indices"])
            )
            np.testing.assert_allclose(
                np.asarray(base["images"]), np.asarray(pack["images"]),
                rtol=0, atol=1e-6,
            )

    def test_kpack_bf16_backward_close(self, setup):
        from deconv_api_tpu.engine import get_visualizer

        params, _, img = setup
        batch = jnp.asarray(img)[None]
        base = get_visualizer(TINY, "b2c1", 4, "all", True, batched=True,
                              backward_dtype="bfloat16", kpack_chan=0)(
            params, batch)["b2c1"]
        pack = get_visualizer(TINY, "b2c1", 4, "all", True, batched=True,
                              backward_dtype="bfloat16", kpack_chan=16)(
            params, batch)["b2c1"]
        a = np.asarray(base["images"], np.float32)
        b = np.asarray(pack["images"], np.float32)
        scale = max(np.abs(a).max(), 1e-6)
        assert np.abs(a - b).max() / scale < 1e-2

    def test_kpack_sweep_and_max_mode(self, setup):
        from deconv_api_tpu.engine import get_visualizer

        params, _, img = setup
        batch = jnp.asarray(img)[None]
        # sweep_merged=False on the base: kpack_chan>0 always routes the
        # separate-per-layer path, so the comparison must hold the base on
        # that same path (merged-vs-separate equivalence has its own test)
        base = get_visualizer(TINY, "b2c1", 4, "max", True, sweep=True,
                              batched=True, kpack_chan=0,
                              sweep_merged=False)(params, batch)
        pack = get_visualizer(TINY, "b2c1", 4, "max", True, sweep=True,
                              batched=True, kpack_chan=16)(params, batch)
        for name in base:
            np.testing.assert_allclose(
                np.asarray(base[name]["images"]),
                np.asarray(pack[name]["images"]), rtol=0, atol=1e-6,
            )


def test_merged_sweep_matches_separate():
    """The merged cross-layer sweep (VERDICT r3 item 7: one walk of the
    shared tail, per-layer seeds concatenated at their boundary) must
    reproduce the separate-per-layer sweep: identical selection, images
    equal up to XLA fusion reduction order, in both modes and under the
    bf16-backward serving dtype."""
    params = init_params(TINY, jax.random.PRNGKey(0))
    img = jax.random.normal(jax.random.PRNGKey(1), (16, 16, 3)) * 30
    for mode in ("all", "max"):
        sep = get_visualizer(
            TINY, "b2c1", 4, mode, True, sweep=True, sweep_merged=False
        )(params, img)
        mrg = get_visualizer(
            TINY, "b2c1", 4, mode, True, sweep=True, sweep_merged=True
        )(params, img)
        assert set(sep) == set(mrg)
        for name in sep:
            np.testing.assert_array_equal(
                np.asarray(sep[name]["indices"]), np.asarray(mrg[name]["indices"])
            )
            np.testing.assert_array_equal(
                np.asarray(sep[name]["valid"]), np.asarray(mrg[name]["valid"])
            )
            np.testing.assert_allclose(
                np.asarray(sep[name]["images"]),
                np.asarray(mrg[name]["images"]),
                rtol=1e-4, atol=1e-5, err_msg=f"{mode}/{name}",
            )
    # full dense head: merged seeds must also concatenate correctly across
    # the flatten/dense boundaries (sweep from 'predictions' is a legal
    # reference request, app/main.py:57)
    sep = get_visualizer(
        TINY, "predictions", 4, "all", True, sweep=True, sweep_merged=False
    )(params, img)
    mrg = get_visualizer(
        TINY, "predictions", 4, "all", True, sweep=True, sweep_merged=True
    )(params, img)
    assert set(sep) == set(mrg)
    for name in sep:
        np.testing.assert_allclose(
            np.asarray(sep[name]["images"]), np.asarray(mrg[name]["images"]),
            rtol=1e-4, atol=1e-5, err_msg=f"dense-head {name}",
        )
    # bf16-backward, batched (the serving sweep configuration)
    batch = img[None].repeat(3, 0)
    sep = get_visualizer(
        TINY, "b2c1", 4, "all", True, sweep=True, batched=True,
        backward_dtype="bfloat16", sweep_merged=False,
    )(params, batch)
    mrg = get_visualizer(
        TINY, "b2c1", 4, "all", True, sweep=True, batched=True,
        backward_dtype="bfloat16", sweep_merged=True,
    )(params, batch)
    for name in sep:
        np.testing.assert_allclose(
            np.asarray(sep[name]["images"], np.float32),
            np.asarray(mrg[name]["images"], np.float32),
            rtol=2e-2, atol=2e-2, err_msg=name,
        )


def test_merged_sweep_batch_chunking_matches_unchunked():
    """The batched merged sweep runs lax.map over batch chunks to bound
    peak memory (DECONV_SWEEP_CHUNK; the unchunked carry RESOURCE_EXHAUSTs
    a v5e-1 at batch 8 — config2_r4 2026-07-31).  Chunked and unchunked
    must agree exactly: same program per chunk, only the batching loop
    differs.  Also covers the remainder path when the chunk does not
    divide the batch (full chunks via lax.map + a vmapped remainder)."""
    params = init_params(TINY, jax.random.PRNGKey(0))
    batch = jax.random.normal(jax.random.PRNGKey(2), (4, 16, 16, 3)) * 30
    unchunked = get_visualizer(
        TINY, "b2c1", 4, "all", True, sweep=True, batched=True,
        sweep_merged=True, sweep_chunk=0,
    )(params, batch)
    for chunk in (1, 2, 3, 4):  # 3 does not divide 4: remainder path
        chunked = get_visualizer(
            TINY, "b2c1", 4, "all", True, sweep=True, batched=True,
            sweep_merged=True, sweep_chunk=chunk,
        )(params, batch)
        assert set(chunked) == set(unchunked)
        for name in unchunked:
            for field in ("indices", "sums", "valid", "images"):
                np.testing.assert_allclose(
                    np.asarray(unchunked[name][field], np.float32),
                    np.asarray(chunked[name][field], np.float32),
                    rtol=1e-5, atol=1e-6, err_msg=f"chunk={chunk} {name}.{field}",
                )


def test_nchw_tail_matches_default():
    """The NCHW low-channel tail (DECONV_TAIL_NCHW, VERDICT r3 item 4:
    channels-major layout for the C<128 backward segments) must reproduce
    the NHWC path: identical selection, images equal to float tolerance,
    including under the bf16-backward serving dtype."""
    params = init_params(TINY, jax.random.PRNGKey(0))
    img = jax.random.normal(jax.random.PRNGKey(1), (16, 16, 3)) * 30
    base = get_visualizer(TINY, "b2c1", 4, "all", True, nchw_chan=0)(
        params, img
    )["b2c1"]
    for thr in (8, 64):
        got = get_visualizer(TINY, "b2c1", 4, "all", True, nchw_chan=thr)(
            params, img
        )["b2c1"]
        np.testing.assert_array_equal(
            np.asarray(base["indices"]), np.asarray(got["indices"])
        )
        np.testing.assert_allclose(
            np.asarray(base["images"]), np.asarray(got["images"]),
            rtol=1e-5, atol=1e-6, err_msg=f"nchw_chan={thr}",
        )
    b0 = get_visualizer(
        TINY, "b2c1", 4, "max", True, batched=True,
        backward_dtype="bfloat16", nchw_chan=0,
    )(params, img[None].repeat(2, 0))["b2c1"]
    b1 = get_visualizer(
        TINY, "b2c1", 4, "max", True, batched=True,
        backward_dtype="bfloat16", nchw_chan=64,
    )(params, img[None].repeat(2, 0))["b2c1"]
    np.testing.assert_allclose(
        np.asarray(b0["images"], np.float32),
        np.asarray(b1["images"], np.float32), rtol=2e-2, atol=2e-2,
    )
