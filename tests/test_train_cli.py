"""The full train → checkpoint → serve loop (VERDICT r2 item 7).

`train` CLI: synthetic fine-tune for N steps on a (dp, tp) mesh → orbax
save → a server started with --weights <ckpt> serves the fine-tuned
params.  The reference's only persistence is its startup weight download
(app/main.py:17); this is the round trip it never had.
"""

import json

import jax
import numpy as np
import pytest

from deconv_api_tpu.cli import main as cli_main
from deconv_api_tpu.config import ServerConfig
from deconv_api_tpu.models.spec import init_params
from deconv_api_tpu.serving.app import DeconvService
from deconv_api_tpu.serving.models import spec_bundle
from tests.test_engine_parity import TINY


@pytest.fixture
def tiny_registry(monkeypatch):
    """Expose TINY under the CLI's --model lookup."""
    from deconv_api_tpu.serving import models as m

    params = init_params(TINY, jax.random.PRNGKey(3))
    monkeypatch.setitem(
        m.REGISTRY, "tiny_vgg", lambda: spec_bundle(TINY, params)
    )
    return params


def test_train_checkpoint_serve_roundtrip(tiny_registry, tmp_path, capsys):
    init = tiny_registry
    ckpt = str(tmp_path / "ckpt")

    # 1. train via the real CLI on a (4, 2) mesh (8 virtual CPU devices)
    rc = cli_main(
        [
            "train", "--model", "tiny_vgg", "--steps", "2", "--batch", "8",
            "--mesh", "4,2", "--lr", "1e-3", "--save", ckpt,
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["steps"] == 2 and out["mesh"] == [4, 2]
    assert np.isfinite(out["final_loss"])
    assert out["checkpoint"] == ckpt

    # 2. serve with --weights <ckpt>: the served params are the fine-tuned
    # ones (differ from init), and the model actually serves
    cfg = ServerConfig(
        image_size=16, compilation_cache_dir="", warmup_all_buckets=False,
        weights_path=ckpt,
    )
    svc = DeconvService(cfg, spec=TINY, params=init)
    served_w = np.asarray(svc.bundle.params["b1c1"]["w"])
    init_w = np.asarray(init["b1c1"]["w"])
    assert not np.allclose(served_w, init_w), "served params still the init"

    img = np.zeros((16, 16, 3), np.float32)
    result = svc._run_batch(("b2c1", "all", 4, "grid"), [img])[0]
    assert result["grid"].shape == (16 * 2, 16 * 2, 3)


def test_train_loop_loss_decreases():
    """Sanity: repeated steps on the SAME synthetic distribution reduce the
    loss (learnable labels are random, so expect drift toward uniform
    logits — loss must at least move and stay finite)."""
    from deconv_api_tpu.train.loop import train_synthetic

    params = init_params(TINY, jax.random.PRNGKey(0))
    r1 = train_synthetic(
        TINY, params, steps=1, batch=8, lr=5e-3, mesh_shape=(8,), seed=1
    )
    r8 = train_synthetic(
        TINY, params, steps=8, batch=8, lr=5e-3, mesh_shape=(8,), seed=1
    )
    assert np.isfinite(r1["final_loss"]) and np.isfinite(r8["final_loss"])
    assert r8["final_loss"] < r1["final_loss"]


def test_cli_train_dag_family(monkeypatch, tmp_path, capsys):
    """`train --model <dag family>` through the real CLI: cmd_train's DAG
    branch infers the class count from the forward's output shape and
    trains on the mesh (VERDICT r4 item 4's CLI surface)."""
    from deconv_api_tpu.models.resnet50 import resnet50_forward, resnet50_init
    from deconv_api_tpu.serving import models as m

    params = resnet50_init(jax.random.PRNGKey(0), num_classes=10)
    bundle = m.ModelBundle(
        name="resnet50_small",
        params=params,
        image_size=32,
        preprocess=lambda x: x,
        layer_names=("conv2_block1_out",),
        dream_layers=(),
        forward_fn=resnet50_forward,
    )
    monkeypatch.setitem(m.REGISTRY, "resnet50_small", lambda: bundle)

    ckpt = str(tmp_path / "dag_ckpt")
    rc = cli_main(
        [
            "train", "--model", "resnet50_small", "--steps", "2",
            "--batch", "8", "--mesh", "4,2", "--lr", "1e-3", "--save", ckpt,
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["model"] == "resnet50_small"
    assert out["steps"] == 2 and out["mesh"] == [4, 2]
    assert np.isfinite(out["final_loss"])
    assert out["checkpoint"] == ckpt
    import os

    assert os.path.isdir(ckpt)


def test_train_dag_without_args_is_clean_error():
    """spec=None needs the explicit DAG arguments, not a crash downstream."""
    from deconv_api_tpu.train.loop import train_synthetic

    with pytest.raises(ValueError, match="num_classes"):
        train_synthetic(None, {}, steps=1)


def _small_resnet():
    """ResNet50 at test scale: real DAG family topology (residuals, strided
    convs, BN), 32x32 inputs (stride-32 trunk -> 1x1 final map), 10-way
    head — the smallest configuration that still exercises every block."""
    from deconv_api_tpu.models.resnet50 import resnet50_forward, resnet50_init

    params = resnet50_init(jax.random.PRNGKey(0), num_classes=10)
    return params, resnet50_forward


def test_dag_train_step_runs_and_descends():
    """VERDICT r4 item 4: DAG families train on the (dp, tp) mesh via the
    forward_fn path — loss must fall over a few steps and the eval metrics
    must be finite."""
    from deconv_api_tpu.train.loop import train_synthetic

    params, fwd = _small_resnet()
    r = train_synthetic(
        None, params, forward_fn=fwd, model_name="resnet50",
        num_classes=10, input_shape=(32, 32, 3),
        steps=4, batch=8, lr=1e-3, mesh_shape=(4, 2), seed=1,
    )
    assert np.isfinite(r["final_loss"])
    assert np.isfinite(r["eval_loss"]) and np.isfinite(r["eval_accuracy"])
    assert r["model"] == "resnet50" and r["mesh"] == [4, 2]


@pytest.mark.slow  # three full DAG training runs (~130s); the exact-resume
# property stays in tier-1 via test_checkpoint_resume_is_exact (TINY)
def test_dag_checkpoint_resume_is_exact(tmp_path):
    """Exact interrupt-and-resume for a DAG family (VERDICT r4 item 4):
    the TrainState round-trips through orbax with the nested block pytree
    and the fold_in data keying regenerates the identical stream."""
    from deconv_api_tpu.train.loop import train_synthetic

    params, fwd = _small_resnet()
    common = dict(
        forward_fn=fwd, model_name="resnet50", num_classes=10,
        input_shape=(32, 32, 3), batch=8, lr=1e-3, mesh_shape=(8,), seed=3,
    )

    straight = train_synthetic(None, params, steps=4, **common)

    ck = str(tmp_path / "dag_run")
    train_synthetic(None, params, steps=2, save_dir=ck, save_every=2, **common)
    assert (tmp_path / "dag_run.state").is_dir()
    resumed = train_synthetic(
        None, params, steps=4, save_dir=ck, save_every=2, resume=True, **common
    )

    assert resumed["resumed_from"] == 2
    assert resumed["final_loss"] == straight["final_loss"], (
        f"resumed {resumed['final_loss']} != straight {straight['final_loss']}"
    )
    flat_s = jax.tree.leaves(straight["params"])
    flat_r = jax.tree.leaves(resumed["params"])
    assert len(flat_s) == len(flat_r)
    for a, b in zip(flat_s, flat_r):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_heldout_eval_improves():
    """VERDICT r3 weak #6: training must show a real eval metric, not just
    loss-goes-down.  The synthetic data carries a learnable per-class color
    bias, so held-out loss must fall sharply and held-out accuracy must
    beat chance after a short fine-tune (measured: 5.85 -> 1.77 loss,
    0.09 -> 0.22 accuracy at 40 steps)."""
    from tests.test_engine_parity import TINY
    from deconv_api_tpu.models.spec import init_params
    from deconv_api_tpu.train.loop import train_synthetic

    params = init_params(TINY, jax.random.PRNGKey(0))
    r = train_synthetic(TINY, params, steps=40, batch=32, lr=1e-3, mesh_shape=(8,))
    num_classes = TINY.layers[-1].filters
    chance = 1.0 / num_classes
    assert r["eval_loss"] < 0.6 * r["eval_loss_initial"], (
        f"held-out loss {r['eval_loss_initial']:.2f} -> {r['eval_loss']:.2f}"
    )
    assert r["eval_accuracy"] >= 1.5 * chance, (
        f"held-out accuracy {r['eval_accuracy']:.3f} vs chance {chance:.3f}"
    )


def test_checkpoint_resume_is_exact(tmp_path):
    """Interrupt-and-resume must reproduce the uninterrupted run EXACTLY:
    the full TrainState (params + optimizer moments + step) round-trips
    through orbax and the fold_in data keying regenerates the identical
    batch stream from the resume step (SURVEY §5 checkpoint/resume row —
    beyond the params-only train->serve plumbing)."""
    from tests.test_engine_parity import TINY
    from deconv_api_tpu.models.spec import init_params
    from deconv_api_tpu.train.loop import train_synthetic

    params = init_params(TINY, jax.random.PRNGKey(0))
    common = dict(batch=16, lr=1e-3, mesh_shape=(8,), seed=3)

    straight = train_synthetic(TINY, params, steps=8, **common)

    ck = str(tmp_path / "run")
    first = train_synthetic(
        TINY, params, steps=4, save_dir=ck, save_every=4, **common
    )
    assert (tmp_path / "run.state").is_dir()
    resumed = train_synthetic(
        TINY, params, steps=8, save_dir=ck, save_every=4, resume=True,
        **common
    )

    assert resumed["final_loss"] == straight["final_loss"], (
        f"resumed {resumed['final_loss']} != straight {straight['final_loss']}"
    )
    for name, leaf in straight["params"].items():
        for k in leaf:
            np.testing.assert_array_equal(
                np.asarray(leaf[k]), np.asarray(resumed["params"][name][k]),
                err_msg=f"{name}/{k}",
            )
    # resuming without a checkpoint is a clean error
    with pytest.raises(FileNotFoundError):
        train_synthetic(
            TINY, params, steps=8, save_dir=str(tmp_path / "none"),
            resume=True, **common
        )


def test_resume_guardrails(tmp_path):
    """Mismatched hyperparameters, completed runs, and missing --save are
    clean errors, not silent run-blending (r4 review findings)."""
    from tests.test_engine_parity import TINY
    from deconv_api_tpu.models.spec import init_params
    from deconv_api_tpu.train.loop import train_synthetic

    params = init_params(TINY, jax.random.PRNGKey(0))
    ck = str(tmp_path / "run")
    train_synthetic(
        TINY, params, steps=2, batch=16, lr=1e-3, mesh_shape=(8,),
        save_dir=ck, save_every=2,
    )
    # different lr -> config-mismatch error, not a blended run
    with pytest.raises(ValueError, match="config mismatch"):
        train_synthetic(
            TINY, params, steps=4, batch=16, lr=5e-4, mesh_shape=(8,),
            save_dir=ck, save_every=2, resume=True,
        )
    # checkpoint already at steps -> explicit error, not a NaN summary
    with pytest.raises(ValueError, match="nothing to resume"):
        train_synthetic(
            TINY, params, steps=2, batch=16, lr=1e-3, mesh_shape=(8,),
            save_dir=ck, save_every=2, resume=True,
        )
    # save_every without save_dir -> explicit error, not silent no-op
    with pytest.raises(ValueError, match="need --save"):
        train_synthetic(
            TINY, params, steps=2, batch=16, lr=1e-3, mesh_shape=(8,),
            save_every=2,
        )
