"""Executor-lane tests (round 10): lane topology resolution, the
least-loaded scheduler (injectable load signal), per-lane breaker
isolation (one sick chip degrades the pool, never kills it), byte-exact
response parity between lanes=1 and lanes=4 serving, lane-aware warmup,
and the lane-targeted fault form.  Fast-lane: the only device work is
the tiny spec on virtual CPU devices."""

from __future__ import annotations

import asyncio
import base64
import threading
import time

import httpx
import numpy as np
import pytest

import jax

from deconv_api_tpu import errors
from deconv_api_tpu.config import ServerConfig
from deconv_api_tpu.models.spec import init_params
from deconv_api_tpu.parallel.lanes import lane_placements, resolve_lane_count
from deconv_api_tpu.serving.app import DeconvService
from deconv_api_tpu.serving.batcher import (
    BatchingDispatcher,
    CircuitBreaker,
    LanePool,
)
from deconv_api_tpu.serving.faults import FaultRegistry
from deconv_api_tpu.serving.metrics import Metrics
from tests.test_engine_parity import TINY
from tests.test_serving import ServiceFixture, _data_url


# ---------------------------------------------------------------- topology


def test_resolve_lane_count_forms():
    assert resolve_lane_count("auto", 8) == 8
    assert resolve_lane_count("auto", 1) == 1
    assert resolve_lane_count("off", 8) == 1
    assert resolve_lane_count("0", 8) == 1
    assert resolve_lane_count("1", 8) == 1
    assert resolve_lane_count("4", 8) == 4
    assert resolve_lane_count(8, 8) == 8
    # a whole-pool mesh owns every device: auto degrades to one stream,
    # an explicit lane request on top is a config error
    assert resolve_lane_count("auto", 8, mesh_active=True) == 1
    with pytest.raises(ValueError, match="mutually exclusive"):
        resolve_lane_count("4", 8, mesh_active=True)
    with pytest.raises(ValueError, match="needs 16 devices"):
        resolve_lane_count("16", 8)
    with pytest.raises(ValueError, match="divide the device count"):
        resolve_lane_count("3", 8)
    with pytest.raises(ValueError, match="must be 'auto'"):
        resolve_lane_count("many", 8)


def test_lane_placements_whole_devices_and_mesh_slices():
    devs = jax.devices()
    whole = lane_placements(8, devs)
    assert whole == list(devs)
    sliced = lane_placements(2, devs)
    assert len(sliced) == 2
    from jax.sharding import Mesh

    for i, m in enumerate(sliced):
        assert isinstance(m, Mesh)
        assert m.shape["dp"] == 4
        # contiguous, non-overlapping slices
        assert set(m.devices.flat) == set(devs[i * 4 : (i + 1) * 4])
    with pytest.raises(ValueError, match="evenly split"):
        lane_placements(3, devs)


# ------------------------------------------------- least-loaded scheduling


def test_pick_prefers_smallest_pending_seconds():
    """The load signal is inflight x EWMA cost, injectable by setting
    those fields directly: a lane with 2 cheap batches in flight beats a
    lane with 1 expensive one."""
    pool = LanePool(3)
    pool.lanes[0].inflight, pool.lanes[0].ewma_s = 2, 0.010  # 20 ms pending
    pool.lanes[1].inflight, pool.lanes[1].ewma_s = 1, 0.100  # 100 ms pending
    pool.lanes[2].inflight, pool.lanes[2].ewma_s = 1, 0.005  # 5 ms pending
    lane, retry = pool.pick()
    assert (lane.index, retry) == (2, 0.0)
    pool.lanes[2].inflight = 30  # now the most loaded
    assert pool.pick()[0].index == 0


def test_idle_pool_round_robins_on_ties():
    """All lanes idle -> load ties at 0 -> fewest-picks tiebreak walks
    every lane, which is exactly what warms a cold pool."""
    pool = LanePool(4)
    picked = []
    for _ in range(8):
        lane, _ = pool.pick()
        picked.append(lane.index)
        pool.record_dispatched(lane)
        pool.record_done(lane, True, 0.01, 1)
    assert sorted(picked[:4]) == [0, 1, 2, 3]
    assert sorted(picked[4:]) == [0, 1, 2, 3]


def test_ewma_tracks_observed_cost():
    pool = LanePool(1)
    lane = pool.lanes[0]
    pool.record_dispatched(lane)
    pool.record_done(lane, True, 0.1, 1)
    assert lane.ewma_s == pytest.approx(0.1)
    pool.record_dispatched(lane)
    pool.record_done(lane, True, 0.2, 1)
    assert 0.1 < lane.ewma_s < 0.2  # smoothed, not last-sample


def test_pick_skips_open_lane_and_runs_probe_after_cooldown():
    clock = [0.0]
    pool = LanePool(
        2,
        breaker_factory=lambda: CircuitBreaker(
            1, 5.0, clock=lambda: clock[0]
        ),
    )
    lane0 = pool.lanes[0]
    pool.record_dispatched(lane0)
    pool.record_done(lane0, False, 0.01, 1)  # threshold 1: lane 0 opens
    assert lane0.breaker.state == CircuitBreaker.OPEN
    # the pool still admits (lane 1 is healthy) and never picks lane 0
    assert pool.admit() == (True, 0.0)
    for _ in range(4):
        lane, _ = pool.pick()
        assert lane.index == 1
        pool.record_dispatched(lane)
        pool.record_done(lane, True, 0.01, 1)
    assert pool.accepting_count() == 1
    assert pool.state_name() == "degraded"
    # cooldown over: lane 0 is idle (load 0) so it sorts first and the
    # pick claims its half-open probe; success closes it
    clock[0] = 6.0
    lane, _ = pool.pick()
    assert lane.index == 0
    assert lane0.breaker.state == CircuitBreaker.HALF_OPEN
    pool.record_dispatched(lane)
    pool.record_done(lane, True, 0.01, 1)
    assert lane0.breaker.state == CircuitBreaker.CLOSED
    assert pool.state_name() == "closed"


def test_admit_fails_fast_only_when_every_lane_cooling():
    clock = [0.0]
    pool = LanePool(
        2,
        breaker_factory=lambda: CircuitBreaker(
            1, 5.0, clock=lambda: clock[0]
        ),
    )
    for lane in pool.lanes:
        pool.record_dispatched(lane)
        pool.record_done(lane, False, 0.01, 1)
    ok, retry = pool.admit()
    assert not ok and retry > 0
    assert pool.state_name() == "open"
    lane, retry = pool.pick()
    assert lane is None and retry > 0
    clock[0] = 6.0  # cooldowns over: admit again (a probe can run)
    assert pool.admit() == (True, 0.0)


# ------------------------------------------------ breaker isolation (e2e)


def test_one_sick_lane_never_fails_healthy_lane_requests():
    """A runner that fails ONLY on lane 0 costs exactly the requests
    scheduled there before its breaker opens (threshold 1 -> one); every
    later submit serves from the surviving lane, and the pool never
    fail-fasts a healthy request with BreakerOpen."""
    clock = [0.0]
    pool = LanePool(
        2,
        breaker_factory=lambda: CircuitBreaker(
            1, 1000.0, clock=lambda: clock[0]
        ),
    )
    served_on = []

    def runner(key, images, lane=0):
        if lane == 0:
            raise RuntimeError("chip 0 is wedged")
        served_on.append(lane)
        return ["ok"] * len(images)

    async def go():
        d = BatchingDispatcher(
            runner, max_batch=1, window_ms=0, pipeline_depth=1,
            request_timeout_s=5.0, lane_pool=pool,
        )
        await d.start()
        failures = 0
        for i in range(10):
            try:
                assert await d.submit(_img(), f"k{i}") == "ok"
            except RuntimeError:
                failures += 1
            except errors.BreakerOpen:
                raise AssertionError(
                    "pool fail-fasted while a healthy lane was serving"
                )
        # exactly the one pre-open pick of lane 0 failed
        assert failures == 1
        assert served_on and set(served_on) == {1}
        assert pool.accepting_count() == 1
        await d.stop()

    asyncio.run(go())


def _img():
    return np.zeros((4, 4, 3), np.float32)


# ------------------------------------------------------- lane-aware faults


def test_lane_targeted_fault_spares_other_lanes_and_counts():
    reg = FaultRegistry()
    reg.arm("device.dispatch_error", "n2:1")
    # a mismatching lane's consultation neither fires nor consumes
    for _ in range(5):
        assert reg.check("device.dispatch_error", where=0) is None
    assert reg.check("device.dispatch_error", where=1) is not None
    assert reg.check("device.dispatch_error", where=1) is not None
    # n2 exhausted -> self-disarmed
    assert reg.check("device.dispatch_error", where=1) is None
    assert reg.snapshot()["injected"] == {"device.dispatch_error": 2}
    # an untargeted spec still fires for any lane
    reg.arm("device.dispatch_error", "n1")
    assert reg.check("device.dispatch_error", where=3) is not None


# ----------------------------------------------------- end-to-end serving


def _boot_service(serve_lanes: str) -> ServiceFixture:
    params = init_params(TINY, jax.random.PRNGKey(3))
    cfg = ServerConfig(
        image_size=16,
        max_batch=4,
        batch_window_ms=1.0,
        compilation_cache_dir="",
        serve_lanes=serve_lanes,
    )
    return ServiceFixture(
        cfg, service=DeconvService(cfg, spec=TINY, params=params)
    )


LAYERS = ("b1c1", "b1c2", "b2c1")


def test_lane_parity_byte_identical_responses():
    """THE parity pin: the same requests through a lanes=1 and a lanes=4
    server produce byte-identical payloads — lane replication and
    placement change WHERE a batch runs, never its bytes.  Sequential
    requests round-robin the idle pool, so all four lanes actually
    execute; a concurrent mixed-key burst then re-checks parity under
    real multi-lane scheduling."""
    with _boot_service("off") as ref, _boot_service("4") as laned:
        assert laned.service.lane_count == 4
        assert ref.service.lane_count == 1
        reqs = [
            (layer, _data_url(seed))
            for layer in LAYERS
            for seed in range(4)
        ]

        def fetch(base_url, layer, uri):
            r = httpx.post(
                base_url + "/",
                data={"file": uri, "layer": layer},
                headers={"cache-control": "no-store"},
                timeout=60,
            )
            assert r.status_code == 200, r.text
            return r.content

        expect = {
            (layer, uri): fetch(ref.base_url, layer, uri)
            for layer, uri in reqs
        }
        for layer, uri in reqs:
            assert fetch(laned.base_url, layer, uri) == expect[(layer, uri)]
        # every lane executed at least one batch during the sweep
        batches = laned.service.metrics.labeled("lane_batches_total")
        assert set(batches) == {"0", "1", "2", "3"}, batches
        # concurrent burst: mixed keys land on different lanes at once
        results: dict = {}

        def one(i):
            layer, uri = reqs[i % len(reqs)]
            results[i] = (layer, uri, fetch(laned.base_url, layer, uri))

        threads = [threading.Thread(target=one, args=(i,)) for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 12
        for layer, uri, body in results.values():
            assert body == expect[(layer, uri)]


def test_lane_warmup_compiles_every_lane_and_reports_wall():
    with _boot_service("2") as s:
        svc = s.service
        assert svc.lane_count == 2
        assert len(svc.bundle._lane_params) == 2
        svc.cfg = svc.cfg  # warmup below uses the live config
        svc.warmup("b2c1")
        # per-lane visualizer cache entries (lane is the key's tail)
        lanes_warmed = {k[-1] for k in svc.bundle._vis_cache}
        assert lanes_warmed == {0, 1}
        assert svc.warmup_wall_s is not None and svc.warmup_wall_s > 0
        r = httpx.get(s.base_url + "/v1/config")
        cfg = r.json()
        assert cfg["serve_lanes_active"] == 2
        assert cfg["warmup_wall_s"] == svc.warmup_wall_s
        assert cfg["lanes"]["lanes"] == 2
        assert len(cfg["lanes"]["per_lane"]) == 2
        assert cfg["breaker_state"] == "closed"
        r = httpx.get(s.base_url + "/readyz")
        assert r.status_code == 200
        assert r.json()["lanes"] == {"total": 2, "accepting": 2}


def test_mesh_slice_lanes_compose_with_dp_sharding():
    """serve_lanes=2 on 8 devices: each lane is a 4-device dp mesh, and
    batches round up to the lane's dp multiple so every dispatch shards
    evenly."""
    params = init_params(TINY, jax.random.PRNGKey(3))
    cfg = ServerConfig(
        image_size=16,
        max_batch=8,
        compilation_cache_dir="",
        serve_lanes="2",
        donate_inputs=False,
    )
    svc = DeconvService(cfg, spec=TINY, params=params)
    assert svc.lane_count == 2 and svc._lane_dp == 4
    assert svc._bucket_for(1) == 4  # rounded up to the lane's dp axis
    from jax.sharding import Mesh

    assert isinstance(svc.bundle.lane_placement(0), Mesh)
    # one whole dispatch through each mesh-slice lane, identical bytes
    img = svc.bundle.preprocess(
        np.zeros((16, 16, 3), np.float32)
    )
    a = svc._run_batch(("b2c1", "all", 4, "grid"), [img], lane=0)[0]
    b = svc._run_batch(("b2c1", "all", 4, "grid"), [img], lane=1)[0]
    assert np.array_equal(np.asarray(a["grid"]), np.asarray(b["grid"]))


def test_single_device_auto_resolves_single_stream():
    """The pre-lane contract: serve_lanes left at auto on a single-chip
    host (mesh_shape set here to force it) keeps one lane and the
    original params object — no replication, no placement."""
    params = init_params(TINY, jax.random.PRNGKey(3))
    cfg = ServerConfig(
        image_size=16, compilation_cache_dir="", mesh_shape=(2,)
    )
    svc = DeconvService(cfg, spec=TINY, params=params)
    assert svc.lane_count == 1
    assert svc.bundle.lane_params(0) is svc.bundle.params
    assert svc.breaker is svc.lane_pool.lanes[0].breaker


def test_cadence_omitted_not_zero_in_loopback_row():
    """The satellite fix: a metrics snapshot that never observed a
    cadence reports 0.0, and the loopback row must OMIT the field
    rather than publish a misleading 0.0 ms."""
    m = Metrics()
    m.observe_batch(size=1, compute_s=0.01, queue_s=0.0)
    snap = m.snapshot()
    assert snap["batch_cadence_p50_s"] == 0.0
    # mirror of tools/loopback_load.py's row construction
    server_row = {}
    if snap["batch_cadence_p50_s"] > 0:
        server_row["batch_cadence_p50_ms"] = round(
            snap["batch_cadence_p50_s"] * 1e3, 2
        )
    assert "batch_cadence_p50_ms" not in server_row
    m.observe_cadence(0.02)
    snap = m.snapshot()
    assert snap["batch_cadence_p50_s"] > 0
