"""Durable async job subsystem tests (round 11, serving/jobs.py).

Fast lane: CPU, a tiny conv-only spec (32px, so the dream octave ladder
has three rungs — resume/cancel tests need real checkpoint boundaries).

Covers the journal (torn-tail replay, boot compaction, retention),
retry-safe submission (idempotent resubmit onto live and completed
jobs, 429 + Retry-After at capacity), checkpointed execution (runner
crash resumes from the last checkpoint with BYTE-IDENTICAL output,
cancellation mid-octave never runs another octave), SSE progress
(Last-Event-ID reconnect replay), drain parking + boot re-claim, and
the jobs exposition lint."""

import asyncio
import base64
import io
import json
import time

import httpx
import numpy as np
import pytest

import jax

from deconv_api_tpu import errors
from deconv_api_tpu.config import ServerConfig
from deconv_api_tpu.models.spec import Layer, ModelSpec, init_params
from deconv_api_tpu.serving.app import DeconvService
from deconv_api_tpu.serving.jobs import (
    Checkpoint,
    JobJournal,
    JobManager,
    Result,
    SpillStore,
)
from tests.test_metrics_exposition import lint_exposition
from tests.test_serving import ServiceFixture

# Conv-only (no flatten/dense head), 32px: dreams work at any octave
# resolution and octave_shapes(32, 32, 3, min_size=16) is a 3-rung
# ladder — enough boundaries to crash, cancel and park between.
JOBS_SPEC = ModelSpec(
    name="jobs_tiny",
    input_shape=(32, 32, 3),
    layers=(
        Layer("input_1", "input"),
        Layer("c1", "conv", activation="relu", filters=8),
        Layer("p1", "pool"),
        Layer("c2", "conv", activation="relu", filters=8),
    ),
)

DREAM_FORM = {"type": "dream", "layers": "c2", "steps": "2", "octaves": "3"}


def _data_url(seed=0, size=32):
    from PIL import Image

    img = Image.fromarray(
        np.random.default_rng(seed).integers(0, 255, (size, size, 3), np.uint8),
        "RGB",
    )
    buf = io.BytesIO()
    img.save(buf, "JPEG")
    return "data:image/jpeg;base64," + base64.b64encode(buf.getvalue()).decode()


def _make_service(jobs_dir, **cfg_kw):
    cfg = ServerConfig(
        image_size=32,
        max_batch=4,
        batch_window_ms=1.0,
        compilation_cache_dir="",
        cache_bytes=0,
        jobs_dir=str(jobs_dir),
        fault_injection=True,
        **cfg_kw,
    )
    params = init_params(JOBS_SPEC, jax.random.PRNGKey(0))
    return ServiceFixture(
        cfg, service=DeconvService(cfg, spec=JOBS_SPEC, params=params)
    )


@pytest.fixture(scope="module")
def jobs_server(tmp_path_factory):
    with _make_service(tmp_path_factory.mktemp("jobs")) as s:
        yield s


def _wait_terminal(server, job_id, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        doc = httpx.get(server.base_url + f"/v1/jobs/{job_id}").json()
        if doc["state"] in ("done", "failed", "cancelled", "parked"):
            return doc
        time.sleep(0.03)
    raise AssertionError(f"job {job_id} never reached a terminal state: {doc}")


def _arm(server, spec):
    r = httpx.post(server.base_url + "/v1/debug/faults", data={"arm": spec})
    assert r.status_code == 200, r.text


def _disarm(server):
    r = httpx.post(
        server.base_url + "/v1/debug/faults", data={"disarm": "all"}
    )
    assert r.status_code == 200, r.text


def _sse_events(text):
    events = []
    for block in text.split("\n\n"):
        ev = {}
        for line in block.splitlines():
            if line.startswith("id: "):
                ev["id"] = int(line[4:])
            elif line.startswith("event: "):
                ev["event"] = line[7:]
            elif line.startswith("data: "):
                ev["data"] = json.loads(line[6:])
        if "event" in ev:
            events.append(ev)
    return events


# ----------------------------------------------------------- journal unit


def test_journal_replay_tolerates_torn_tail(tmp_path):
    j = JobJournal(str(tmp_path / "journal.jsonl"))
    j.append({"rec": "submitted", "job": "a", "seq": 0})
    j.append({"rec": "state", "job": "a", "state": "running", "seq": 1})
    # a crash mid-append leaves a torn, undecodable final line
    with open(j.path, "ab") as f:
        f.write(b'{"rec":"checkpoint","job":"a","se')
    recs, torn = JobJournal.replay(j.path)
    assert torn == 1
    assert [r["rec"] for r in recs] == ["submitted", "state"]


def test_journal_rewrite_is_atomic_replacement(tmp_path):
    j = JobJournal(str(tmp_path / "journal.jsonl"))
    for i in range(5):
        j.append({"rec": "state", "job": "a", "seq": i})
    j.rewrite([{"rec": "submitted", "job": "a", "seq": 0}])
    recs, torn = JobJournal.replay(j.path)
    assert torn == 0
    assert recs == [{"rec": "submitted", "job": "a", "seq": 0}]
    # the handle reopens for appends after a rewrite
    j.append({"rec": "state", "job": "a", "state": "queued", "seq": 1})
    recs, _ = JobJournal.replay(j.path)
    assert len(recs) == 2


def test_spill_digest_mismatch_reads_as_absent(tmp_path):
    s = SpillStore(str(tmp_path))
    fname, digest = s.put_arrays("job-x", 1, {"x": np.arange(8.0)})
    assert s.load_arrays(fname, digest)["x"].shape == (8,)
    import os

    with open(os.path.join(str(tmp_path), fname), "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff")
    assert s.load_arrays(fname, digest) is None


# ----------------------------------------------------- manager unit tests


def _run(coro):
    return asyncio.run(coro)


async def _wait(pred, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        await asyncio.sleep(0.01)
    return False


def test_manager_queue_full_429_with_retry_after(tmp_path):
    async def exec_(job, ckpts, load):
        yield Result(200, "application/json", b"{}")

    async def drive():
        m = JobManager(str(tmp_path), exec_, queue_depth=2, workers=1)
        m.submit("dream", {}, "idem-a")
        m.submit("dream", {}, "idem-b")
        with pytest.raises(errors.JobQueueFull) as ei:
            m.submit("dream", {}, "idem-c")
        assert ei.value.status == 429
        assert ei.value.retry_after_s >= 1.0
        # dedup onto an existing job is NOT an admission: it must
        # succeed even at capacity (retry-safe resubmission)
        job, deduped = m.submit("dream", {}, "idem-a")
        assert deduped

    _run(drive())


def test_manager_reaps_expired_job_before_device(tmp_path):
    calls = []

    async def exec_(job, ckpts, load):
        calls.append(job.id)
        yield Result(200, "application/json", b"{}")

    async def drive():
        m = JobManager(str(tmp_path), exec_, workers=1)
        job, _ = m.submit(
            "dream", {}, "idem-dead", deadline_ts=time.time() - 5.0
        )
        m.start()
        assert await _wait(lambda: job.state == "failed")
        assert job.error == "deadline_expired"
        assert calls == []  # the executor (and so the device) never ran
        await m.stop()

    _run(drive())


def test_manager_crash_resumes_from_checkpoint(tmp_path):
    attempts = []

    async def exec_(job, ckpts, load):
        attempts.append(len(ckpts))
        have = {r["index"] for r in ckpts if r["stage"] == "step"}
        if 0 not in have:
            yield Checkpoint(stage="step", index=0, total=2, data={"v": 1})
            raise RuntimeError("boom")  # crash AFTER the durable edge
        yield Checkpoint(stage="step", index=1, total=2, data={"v": 2})
        yield Result(200, "application/json", b'{"ok":true}')

    async def drive():
        m = JobManager(str(tmp_path), exec_, workers=1)
        job, _ = m.submit("dream", {}, "idem-crash")
        m.start()
        assert await _wait(lambda: job.state == "done", 10.0)
        assert job.attempts == 2 and job.resumed
        steps = [r for r in job.checkpoints if r["stage"] == "step"]
        assert [r["index"] for r in steps] == [0, 1]
        assert m.result_body(job) == b'{"ok":true}'
        await m.stop()

    _run(drive())


def test_manager_crash_budget_exhausts_to_failed(tmp_path):
    async def exec_(job, ckpts, load):
        raise RuntimeError("always boom")
        yield  # pragma: no cover — makes this an async generator

    async def drive():
        m = JobManager(str(tmp_path), exec_, workers=1, max_attempts=2)
        job, _ = m.submit("dream", {}, "idem-doom")
        m.start()
        assert await _wait(lambda: job.state == "failed", 10.0)
        assert job.attempts == 2 and job.error == "runner_crash"
        await m.stop()

    _run(drive())


def test_manager_idempotent_resubmit_live_and_completed(tmp_path):
    release = asyncio.Event()

    async def exec_(job, ckpts, load):
        await release.wait()
        yield Result(200, "application/json", b'{"done":1}')

    async def drive():
        m = JobManager(str(tmp_path), exec_, workers=1)
        job, deduped = m.submit("dream", {"k": "v"}, "idem-1")
        assert not deduped
        m.start()
        assert await _wait(lambda: job.state == "running")
        # dedup onto the LIVE job
        again, deduped = m.submit("dream", {"k": "v"}, "idem-1")
        assert deduped and again.id == job.id
        release.set()
        assert await _wait(lambda: job.state == "done")
        # dedup onto the COMPLETED job
        again, deduped = m.submit("dream", {"k": "v"}, "idem-1")
        assert deduped and again.id == job.id
        await m.stop()

    _run(drive())


def test_manager_boot_reclaims_parked_and_compacts(tmp_path):
    async def exec_(job, ckpts, load):
        yield Checkpoint(stage="step", index=0, total=1, data={"v": 1})
        yield Result(200, "application/json", b'{"ok":1}')

    async def phase1():
        m = JobManager(str(tmp_path), exec_, workers=1)
        job, _ = m.submit("dream", {}, "idem-park")
        # drain before the runners ever start: the queued job parks
        m.begin_drain()
        assert job.state == "parked"

    async def phase2():
        m = JobManager(str(tmp_path), exec_, workers=1)
        # boot re-claimed the parked job (pinned)
        assert m.reclaimed == 1
        job = m.get(m._idem["idem-park"])
        assert job.state == "queued" and job.resumed
        m.start()
        assert await _wait(lambda: job.state == "done", 10.0)
        await m.stop()

    _run(phase1())
    _run(phase2())
    # third boot: the job is terminal — compaction collapses its
    # checkpoint chain to submitted + final state
    async def phase3():
        m = JobManager(str(tmp_path), exec_, workers=1)
        job = m.get(m._idem["idem-park"])
        assert job.state == "done"
        assert m.result_body(job) == b'{"ok":1}'

    _run(phase3())
    recs, torn = JobJournal.replay(str(tmp_path / "journal.jsonl"))
    assert torn == 0
    assert [r["rec"] for r in recs] == ["submitted", "state"]


def test_manager_retention_drops_old_terminal_jobs(tmp_path):
    async def exec_(job, ckpts, load):
        yield Result(200, "application/json", b"{}")

    now = [1000.0]

    async def phase1():
        m = JobManager(
            str(tmp_path), exec_, workers=1, clock=lambda: now[0]
        )
        job, _ = m.submit("dream", {}, "idem-old")
        m.start()
        assert await _wait(lambda: job.state == "done")
        await m.stop()

    _run(phase1())
    now[0] = 1000.0 + 7200.0  # past the default 3600 s retention

    async def phase2():
        m = JobManager(
            str(tmp_path), exec_, workers=1, clock=lambda: now[0]
        )
        assert m.counts()["done"] == 0
        with pytest.raises(errors.JobNotFound):
            m.get("anything")
        # the idempotency slot is free again: a resubmit is a NEW job
        job, deduped = m.submit("dream", {}, "idem-old")
        assert not deduped

    _run(phase2())


def test_manager_runtime_eviction_and_spill_hygiene(tmp_path):
    """A LONG-RUNNING server must not grow without bound: intermediate
    checkpoint spills die when the result lands, and terminal jobs past
    retention evict (records, idem slot, result spill) at submit time —
    not only at the next boot."""
    import os

    async def exec_(job, ckpts, load):
        yield Checkpoint(
            stage="step", index=0, total=1, arrays={"x": np.arange(4.0)}
        )
        yield Result(200, "application/json", b"{}")

    now = [1000.0]

    async def drive():
        m = JobManager(
            str(tmp_path), exec_, workers=1, clock=lambda: now[0]
        )
        job, _ = m.submit(
            "dream", {}, "idem-evict",
            input_arrays={"input": np.arange(4.0)},
        )
        m.start()
        assert await _wait(lambda: job.state == "done")
        assert m.result_body(job) == b"{}"
        spill_dir = str(tmp_path / "spill")
        files = os.listdir(spill_dir)
        # result retained, intermediate checkpoint spills already gone
        assert any("result" in f for f in files)
        assert not any(f.endswith(".npz") for f in files)
        now[0] += 7200.0  # past the default 3600 s retention
        m.submit("dream", {}, "idem-other")
        assert job.id not in m._jobs
        assert not any("result" in f for f in os.listdir(spill_dir))
        # the idempotency slot is free again
        j2, deduped = m.submit("dream", {}, "idem-evict")
        assert not deduped and j2.id != job.id
        await m.stop()

    _run(drive())


# --------------------------------------------------------------- e2e HTTP


def test_job_dream_e2e_done_result_and_checkpoints(jobs_server):
    form = dict(DREAM_FORM, file=_data_url(1))
    r = httpx.post(jobs_server.base_url + "/v1/jobs", data=form, timeout=60)
    assert r.status_code == 202, r.text
    doc = r.json()
    assert doc["state"] == "queued" and not doc["deduped"]
    assert r.headers["location"] == f"/v1/jobs/{doc['id']}"
    final = _wait_terminal(jobs_server, doc["id"])
    assert final["state"] == "done", final
    # input checkpoint + one per octave-ladder rung (32px, min 16 → 3)
    assert final["checkpoints"] == 4
    assert final["last_checkpoint"] == {"stage": "octave", "index": 2, "total": 3}
    res = httpx.get(jobs_server.base_url + f"/v1/jobs/{doc['id']}/result")
    assert res.status_code == 200
    payload = res.json()
    assert payload["layers"] == ["c2"]
    assert payload["image"].startswith("data:image/")
    assert res.headers["x-job-id"] == doc["id"]


def test_job_submit_validation(jobs_server):
    url = jobs_server.base_url + "/v1/jobs"
    r = httpx.post(url, data={"type": "dream", "layers": "c2"})
    assert r.status_code == 400  # no file
    r = httpx.post(url, data={"type": "nope", "file": _data_url()})
    assert r.status_code == 400 and r.json()["error"] == "bad_request"
    r = httpx.post(url, data={"type": "deconv", "file": _data_url()})
    assert r.status_code == 400  # no layer
    r = httpx.post(
        url, data={"type": "deconv", "file": _data_url(), "layer": "nope"}
    )
    assert r.status_code == 422 and r.json()["error"] == "unknown_layer"
    r = httpx.post(
        url,
        data=dict(DREAM_FORM, file=_data_url()),
        headers={"x-idempotency-key": "has spaces!"},
    )
    assert r.status_code == 400
    r = httpx.get(jobs_server.base_url + "/v1/jobs/job-nonexistent")
    assert r.status_code == 404 and r.json()["error"] == "job_not_found"


def test_job_idempotent_resubmit_e2e(jobs_server):
    form = dict(DREAM_FORM, file=_data_url(2))
    r1 = httpx.post(jobs_server.base_url + "/v1/jobs", data=form, timeout=60)
    assert r1.status_code == 202
    # identical body → same canonical digest → same job, while live
    r2 = httpx.post(jobs_server.base_url + "/v1/jobs", data=form, timeout=60)
    assert r2.status_code == 202
    assert r2.json()["id"] == r1.json()["id"] and r2.json()["deduped"]
    final = _wait_terminal(jobs_server, r1.json()["id"])
    assert final["state"] == "done"
    # ... and onto the completed job
    r3 = httpx.post(jobs_server.base_url + "/v1/jobs", data=form, timeout=60)
    assert r3.json()["id"] == r1.json()["id"] and r3.json()["deduped"]
    # an explicit x-idempotency-key overrides the body digest
    r4 = httpx.post(
        jobs_server.base_url + "/v1/jobs", data=form,
        headers={"x-idempotency-key": "fresh-key-1"}, timeout=60,
    )
    assert r4.json()["id"] != r1.json()["id"] and not r4.json()["deduped"]
    _wait_terminal(jobs_server, r4.json()["id"])


def test_job_runner_crash_resume_byte_parity(jobs_server):
    """THE resume contract: a job that crashes mid-dream and resumes
    from its checkpoint produces a byte-identical final payload to an
    uninterrupted run of the same request."""
    form = dict(DREAM_FORM, file=_data_url(3))
    r1 = httpx.post(
        jobs_server.base_url + "/v1/jobs", data=form,
        headers={"x-idempotency-key": "parity-ref"}, timeout=60,
    )
    ref = _wait_terminal(jobs_server, r1.json()["id"])
    assert ref["state"] == "done" and ref["attempts"] == 1
    body_ref = httpx.get(
        jobs_server.base_url + f"/v1/jobs/{r1.json()['id']}/result"
    ).content
    # slow the octaves, and arm the crash only AFTER an octave
    # checkpoint provably exists — a crash armed up-front fires at the
    # FIRST boundary consult, before any octave checkpoint, and the
    # "resume" would be a full restart that proves nothing about
    # resume-from-checkpoint
    _arm(jobs_server, "device.dispatch_delay_ms=p1:200")
    try:
        r2 = httpx.post(
            jobs_server.base_url + "/v1/jobs", data=form,
            headers={"x-idempotency-key": "parity-crash"}, timeout=60,
        )
        jid = r2.json()["id"]
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            doc = httpx.get(jobs_server.base_url + f"/v1/jobs/{jid}").json()
            if doc["checkpoints"] >= 2:  # input + octave 0 durable
                break
            time.sleep(0.02)
        assert doc["checkpoints"] >= 2, doc
        _arm(jobs_server, "jobs.runner_crash=n1")
        crashed = _wait_terminal(jobs_server, jid)
    finally:
        _disarm(jobs_server)
    assert crashed["state"] == "done", crashed
    assert crashed["attempts"] == 2 and crashed["resumed"]
    # a genuine mid-dream resume records NO duplicate octave: input +
    # exactly one checkpoint per ladder rung (a restart-from-scratch
    # would re-record octave 0 → 5)
    assert crashed["checkpoints"] == 4, crashed
    events = _sse_events(
        httpx.get(
            jobs_server.base_url + f"/v1/jobs/{jid}/events", timeout=30
        ).text
    )
    octave_idx = [
        e["data"]["index"]
        for e in events
        if e["event"] == "checkpoint" and e["data"].get("stage") == "octave"
    ]
    assert octave_idx == [0, 1, 2]
    assert "queued" in [e["event"] for e in events]  # the resume edge
    body_crash = httpx.get(
        jobs_server.base_url + f"/v1/jobs/{jid}/result"
    ).content
    assert body_crash == body_ref  # byte-identical


def test_job_cancel_mid_octave(jobs_server):
    """DELETE on a running job cancels between (or inside) octaves: the
    device never runs the remaining octaves, and the job lands in
    ``cancelled`` with fewer checkpoints than the ladder."""
    _arm(jobs_server, "device.dispatch_delay_ms=p1:250")
    try:
        r = httpx.post(
            jobs_server.base_url + "/v1/jobs",
            data=dict(DREAM_FORM, file=_data_url(4)),
            headers={"x-idempotency-key": "cancel-1"}, timeout=60,
        )
        assert r.status_code == 202
        jid = r.json()["id"]
        # wait for the first octave checkpoint (input ckpt + octave 0)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            doc = httpx.get(jobs_server.base_url + f"/v1/jobs/{jid}").json()
            if doc["checkpoints"] >= 2:
                break
            time.sleep(0.02)
        assert doc["checkpoints"] >= 2, doc
        d = httpx.delete(jobs_server.base_url + f"/v1/jobs/{jid}")
        assert d.status_code == 200
        final = _wait_terminal(jobs_server, jid)
    finally:
        _disarm(jobs_server)
    assert final["state"] == "cancelled", final
    assert final["checkpoints"] < 4  # never reached the full ladder
    res = httpx.get(jobs_server.base_url + f"/v1/jobs/{jid}/result")
    assert res.status_code == 400  # no result for a cancelled job
    # cancel is idempotent on a terminal job
    d2 = httpx.delete(jobs_server.base_url + f"/v1/jobs/{jid}")
    assert d2.status_code == 200 and d2.json()["state"] == "cancelled"


def test_job_sse_stream_and_last_event_id_reconnect(jobs_server):
    form = dict(DREAM_FORM, file=_data_url(5))
    r = httpx.post(jobs_server.base_url + "/v1/jobs", data=form, timeout=60)
    jid = r.json()["id"]
    _wait_terminal(jobs_server, jid)
    s = httpx.get(
        jobs_server.base_url + f"/v1/jobs/{jid}/events", timeout=30
    )
    assert s.status_code == 200
    assert s.headers["content-type"] == "text/event-stream"
    events = _sse_events(s.text)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "submitted" and kinds[-1] == "done"
    assert kinds.count("checkpoint") == 4
    ids = [e["id"] for e in events]
    assert ids == sorted(ids)  # monotone per-job event ids
    # reconnect mid-stream: Last-Event-ID replays ONLY what was missed
    cut = ids[len(ids) // 2]
    s2 = httpx.get(
        jobs_server.base_url + f"/v1/jobs/{jid}/events",
        headers={"Last-Event-ID": str(cut)}, timeout=30,
    )
    events2 = _sse_events(s2.text)
    assert [e["id"] for e in events2] == [i for i in ids if i > cut]
    assert events2[-1]["event"] == "done"
    # a fully caught-up reconnect replays nothing and closes cleanly
    s3 = httpx.get(
        jobs_server.base_url + f"/v1/jobs/{jid}/events",
        headers={"Last-Event-ID": str(ids[-1])}, timeout=30,
    )
    assert _sse_events(s3.text) == []


def test_jobs_list_readyz_config_and_exposition(jobs_server):
    r = httpx.get(jobs_server.base_url + "/v1/jobs")
    assert r.status_code == 200
    listing = r.json()
    assert listing["jobs"] and "counts" in listing
    rz = httpx.get(jobs_server.base_url + "/readyz")
    assert "jobs" in rz.json()
    assert set(rz.json()["jobs"]) == {"running", "parked", "queued"}
    cfg = httpx.get(jobs_server.base_url + "/v1/config").json()
    assert cfg["jobs_active"] is True
    assert cfg["jobs_dir"] is True  # masked to a boolean, never the path
    assert cfg["jobs"]["queue_depth"] == 64
    # exposition lint: the jobs series are TYPEd and well-formed
    text = httpx.get(jobs_server.base_url + "/v1/metrics").text
    types, samples = lint_exposition(text)
    assert types["deconv_jobs_active"] == "gauge"
    assert types["deconv_jobs_checkpoints_total"] == "counter"
    assert types["deconv_jobs_state_total"] == "counter"
    assert any(
        name == "deconv_jobs_checkpoints_total"
        and 'job_state="running"' in labels
        for (name, labels) in samples
    )


def test_jobs_routes_absent_when_disabled():
    cfg = ServerConfig(
        image_size=16, compilation_cache_dir="", jobs_dir=""
    )
    from tests.test_engine_parity import TINY

    params = init_params(TINY, jax.random.PRNGKey(3))
    svc = DeconvService(cfg, spec=TINY, params=params)
    assert svc.jobs is None
    assert ("POST", "/v1/jobs") not in svc.server._routes
    assert not svc.server._prefix_routes


def test_job_queue_full_429_e2e(tmp_path_factory):
    with _make_service(
        tmp_path_factory.mktemp("jobs429"),
        jobs_queue_depth=1, jobs_workers=1,
    ) as s:
        _arm(s, "device.dispatch_delay_ms=p1:400")
        try:
            r1 = httpx.post(
                s.base_url + "/v1/jobs",
                data=dict(DREAM_FORM, file=_data_url(10)), timeout=60,
            )
            assert r1.status_code == 202
            r2 = httpx.post(
                s.base_url + "/v1/jobs",
                data=dict(DREAM_FORM, file=_data_url(11)), timeout=60,
            )
            assert r2.status_code == 429, r2.text
            assert r2.json()["error"] == "job_queue_full"
            assert int(r2.headers["retry-after"]) >= 1
        finally:
            _disarm(s)
        _wait_terminal(s, r1.json()["id"])


def test_job_parked_on_drain_reclaimed_on_restart(tmp_path_factory):
    """The graceful-drain satellite pin: a running job parks (with its
    checkpoints journaled) instead of being abandoned, and a RESTARTED
    process re-claims it and runs it to completion."""
    jobs_dir = tmp_path_factory.mktemp("jobs-restart")
    form = dict(DREAM_FORM, file=_data_url(20))
    with _make_service(jobs_dir, jobs_workers=1) as s:
        _arm(s, "device.dispatch_delay_ms=p1:400")
        r = httpx.post(s.base_url + "/v1/jobs", data=form, timeout=60)
        assert r.status_code == 202
        jid = r.json()["id"]
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            doc = httpx.get(s.base_url + f"/v1/jobs/{jid}").json()
            if doc["checkpoints"] >= 2:
                break
            time.sleep(0.02)
        assert doc["checkpoints"] >= 2, doc
        # fixture exit = begin_drain + stop: the running job parks
    with _make_service(jobs_dir, jobs_workers=1) as s2:
        assert s2.service.jobs.reclaimed == 1
        final = _wait_terminal(s2, jid)
        assert final["state"] == "done", final
        assert final["resumed"]
        res = httpx.get(s2.base_url + f"/v1/jobs/{jid}/result")
        assert res.status_code == 200
        assert res.json()["image"].startswith("data:image/")


def test_job_sweep_e2e_layer_checkpoints(jobs_server):
    r = httpx.post(
        jobs_server.base_url + "/v1/jobs",
        data={"type": "sweep", "file": _data_url(6), "layer": "c2",
              "top_k": "2"},
        timeout=60,
    )
    assert r.status_code == 202, r.text
    final = _wait_terminal(jobs_server, r.json()["id"])
    assert final["state"] == "done", final
    payload = httpx.get(
        jobs_server.base_url + f"/v1/jobs/{r.json()['id']}/result"
    ).json()
    assert payload["sweep"] is True
    assert list(payload["layers"])  # one entry per swept layer
    # layer checkpoints: one per swept layer, plus the input spill
    assert final["checkpoints"] == 1 + len(payload["layers"])
