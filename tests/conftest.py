"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so that multi-chip sharding
(parallel/, train/) is exercised without TPU hardware, per the driver
contract.  The env vars must be set before jax initialises its backends,
hence the assignment at module import time (pytest imports conftest before
collecting test modules, which import jax).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# jax may already be in sys.modules (pytest plugins import it before
# conftest); as long as no backend has been initialised, updating the config
# still takes effect because XLA_FLAGS/platforms are read at first backend
# construction.
import jax

jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == 8, (
    f"expected 8 virtual CPU devices for sharding tests, got "
    f"{jax.device_count()} — was a jax backend initialised before conftest?"
)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
