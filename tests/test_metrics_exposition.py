"""Pure-python Prometheus exposition lint (round 8 satellite).

Round 7 shipped `deconv_errors_total{code=…}` and the per-stage
`stage_seconds` series with NO `# TYPE`/`# HELP` header, so Prometheus
ingested them as untyped and nothing held the exposition to its own
format.  This lint walks every emitted line and asserts the contract:

- every sample line parses (name, optional label block, numeric value);
- every sampled metric family has exactly ONE `# TYPE` line, with a
  valid kind;
- label values are correctly escaped (the label block must round-trip
  through the escaping grammar);
- counter families are MONOTONE across two snapshots with traffic in
  between.

Shared by the trace-spine e2e test (tests/test_trace.py lints the live
``/v1/metrics`` output through the same walker).
"""

from __future__ import annotations

import re

import pytest

from deconv_api_tpu.serving.metrics import Metrics, escape_label
from deconv_api_tpu.serving.trace import FlightRecorder, RequestTrace

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"  # metric name
    r"(?:\{(.*)\})?"  # optional label block
    r" (-?(?:[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|NaN|[+-]?Inf))$"
)
# round 23: OpenMetrics exemplar suffix — '<sample> # {labels} value'.
# Stripped off BEFORE _SAMPLE_RE (the sample's own label block is
# greedy, so one combined pattern would mis-group); greedy (.*) binds
# to the LAST ' # {' so exemplar label values stay intact.
_EXEMPLAR_RE = re.compile(
    r"^(.*) # \{(.*)\} "
    r"(-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\["\\n])*)"')
_KINDS = ("counter", "gauge", "summary", "histogram", "untyped")


def _hist_base(name: str, families: dict[str, str]) -> str | None:
    """Resolve a ``_bucket``/``_sum``/``_count`` sample name to its
    histogram family's base name (TYPE lives on the base — the round-19
    histogram exposition shape), or None for a plain sample."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if families.get(base) == "histogram":
                return base
    return None


def lint_exposition(text: str) -> tuple[dict[str, str], dict[tuple, float]]:
    """Walk every line of a Prometheus text exposition; returns
    ``(family -> kind, (family, label-block) -> value)``.  Raises
    AssertionError on any format violation.

    Round 19 adds histogram families: ``name_bucket``/``name_sum``/
    ``name_count`` samples resolve to a base family typed ``histogram``,
    every ``_bucket`` must carry an ``le`` label, the cumulative bucket
    counts must be monotone in ``le`` per labelset, and the ``+Inf``
    bucket must equal the labelset's ``_count``."""
    families: dict[str, str] = {}
    samples: dict[tuple, float] = {}
    # (family, labels-without-le) -> [(le, cumulative count), ...]
    hist_buckets: dict[tuple, list[tuple[float, float]]] = {}
    for line in text.rstrip("\n").split("\n"):
        assert line, "blank line in exposition"
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            assert len(parts) == 4, f"malformed TYPE line {line!r}"
            _, _, name, kind = parts
            assert name not in families, f"duplicate TYPE line for {name}"
            assert kind in _KINDS, f"invalid TYPE kind {kind!r}"
            families[name] = kind
        elif line.startswith("# HELP "):
            assert len(line.split(" ")) >= 4, f"malformed HELP line {line!r}"
        elif line.startswith("#"):
            raise AssertionError(f"unknown comment line {line!r}")
        else:
            ex_labels = None
            ex = _EXEMPLAR_RE.match(line)
            if ex is not None:
                line, ex_labels, ex_value = ex.groups()
            m = _SAMPLE_RE.match(line)
            assert m, f"unparseable sample line {line!r}"
            name, labels, value = m.groups()
            if ex_labels is not None:
                # exemplars only make sense on cumulative bucket
                # samples, their label block must round-trip the same
                # escaping grammar, and the observation value must
                # parse (round 23: the metrics->trace join)
                assert name.endswith("_bucket"), (
                    f"exemplar on non-bucket sample {line!r}"
                )
                rebuilt = ",".join(
                    f'{k}="{v}"' for k, v in _LABEL_RE.findall(ex_labels)
                )
                assert rebuilt == ex_labels, (
                    f"bad exemplar label escaping in {line!r}"
                )
                float(ex_value)
            if labels:
                # the whole label block must round-trip through the
                # escaping grammar — an unescaped quote/backslash/newline
                # in a value breaks the reconstruction
                rebuilt = ",".join(
                    f'{k}="{v}"' for k, v in _LABEL_RE.findall(labels)
                )
                assert rebuilt == labels, f"bad label escaping in {line!r}"
            samples[(name, labels or "")] = float(value)
            base = _hist_base(name, families)
            if base is not None and name.endswith("_bucket"):
                pairs = dict(_LABEL_RE.findall(labels or ""))
                assert "le" in pairs, f"bucket sample without le: {line!r}"
                rest = ",".join(
                    f'{k}="{v}"' for k, v in _LABEL_RE.findall(labels or "")
                    if k != "le"
                )
                le = float("inf") if pairs["le"] == "+Inf" else float(
                    pairs["le"]
                )
                hist_buckets.setdefault((base, rest), []).append(
                    (le, float(value))
                )
    for name, _labels in samples:
        assert (
            name in families or _hist_base(name, families) is not None
        ), f"sample {name} has no TYPE header"
    for (base, rest), pairs in hist_buckets.items():
        ordered = sorted(pairs)
        assert ordered == pairs, f"{base}{{{rest}}} buckets out of le order"
        counts = [c for _le, c in ordered]
        assert counts == sorted(counts), (
            f"{base}{{{rest}}} cumulative buckets not monotone in le"
        )
        assert ordered[-1][0] == float("inf"), (
            f"{base}{{{rest}}} missing +Inf bucket"
        )
        count_key = (f"{base}_count", rest)
        assert count_key in samples, f"{base}{{{rest}}} missing _count"
        assert samples[count_key] == ordered[-1][1], (
            f"{base}{{{rest}}} +Inf bucket != _count"
        )
        assert (f"{base}_sum", rest) in samples, (
            f"{base}{{{rest}}} missing _sum"
        )
    return families, samples


def _traffic(m: Metrics) -> None:
    m.observe_request(0.012)
    m.observe_request(0.050, error_code="overloaded")
    m.observe_request(0.003, error_code="unknown_layer")
    m.observe_batch(size=4, compute_s=0.04, queue_s=0.01)
    m.observe_cadence(0.02)
    m.observe_stage("decode", 0.002)
    m.observe_stage("compute", 0.030)
    m.inc_counter("cache_hits_total", 2)
    m.set_gauge("cache_resident_bytes", 1024)
    # robustness-layer series (round 9): breaker/pool gauges, deadline
    # counter, labeled fault-injection and task-restart counters
    m.inc_counter("deadline_expired_total")
    m.set_gauge("breaker_state", 2)
    m.set_gauge("codec_workers_live", 8)
    m.inc_labeled("faults_injected_total", "site", "codec.worker_raise")
    m.inc_labeled("task_restarts_total", "task", "dispatch")
    # executor-lane series (round 10): per-lane labeled counters and
    # gauges plus the pool-level imbalance gauge
    m.inc_labeled("lane_batches_total", "lane", "0")
    m.inc_labeled("lane_requests_total", "lane", "0", 4)
    m.set_labeled_gauge("lane_inflight", "lane", "0", 1)
    m.set_labeled_gauge("lane_breaker_state", "lane", "0", 0)
    m.set_gauge("lane_imbalance", 1.0)
    # multi-tenant QoS series (round 13): a MULTI-label counter family
    # (tenant + class), a float-increment counter (measured device ms),
    # per-tenant shed accounting, and the fairness gauge
    m.inc_labeled(
        "tenant_requests_total", ("tenant", "class"), ("acme", "interactive")
    )
    m.inc_labeled(
        "tenant_requests_total", ("tenant", "class"), ("acme", "bulk"), 2
    )
    m.inc_labeled("tenant_device_ms_total", "tenant", "acme", 12.345)
    m.inc_labeled("tenant_shed_total", "tenant", "acme")
    m.set_gauge("tenant_fairness", 1.0)
    # round-19 fixed-bucket latency histogram (multi-label, le buckets)
    m.observe_hist(
        "request_duration_seconds", ("route", "qos_class"),
        ("/v1/deconv", "standard"), 0.012,
    )
    m.observe_hist(
        "request_duration_seconds", ("route", "qos_class"),
        ("/v1/deconv", "standard"), 0.3,
    )


def test_every_family_typed_once_and_labels_escape():
    m = Metrics()
    _traffic(m)
    # hostile label values must come out escaped, not exposition-breaking
    m.observe_request(0.001, error_code='we"ird\\code\nwith newline')
    m.observe_stage('sta"ge', 0.001)
    text = m.prometheus()
    families, samples = lint_exposition(text)
    assert families["deconv_errors_total"] == "counter"
    assert families["deconv_stage_seconds"] == "summary"
    assert any(name == "deconv_errors_total" for name, _ in samples)
    # round-9 robustness series carry TYPE headers and parse
    assert families["deconv_deadline_expired_total"] == "counter"
    assert families["deconv_breaker_state"] == "gauge"
    assert families["deconv_codec_workers_live"] == "gauge"
    assert families["deconv_faults_injected_total"] == "counter"
    assert samples[
        ("deconv_faults_injected_total", 'site="codec.worker_raise"')
    ] == 1.0
    assert samples[
        ("deconv_task_restarts_total", 'task="dispatch"')
    ] == 1.0
    # round-10 lane series carry TYPE headers and parse, with labeled
    # GAUGES typed gauge (not counter)
    assert families["deconv_lane_batches_total"] == "counter"
    assert families["deconv_lane_requests_total"] == "counter"
    assert families["deconv_lane_inflight"] == "gauge"
    assert families["deconv_lane_breaker_state"] == "gauge"
    assert families["deconv_lane_imbalance"] == "gauge"
    assert samples[("deconv_lane_requests_total", 'lane="0"')] == 4.0
    assert samples[("deconv_lane_inflight", 'lane="0"')] == 1.0
    # round-13 tenant series: the multi-label block parses and
    # round-trips the escaping grammar, float counters render
    assert families["deconv_tenant_requests_total"] == "counter"
    assert families["deconv_tenant_device_ms_total"] == "counter"
    assert families["deconv_tenant_shed_total"] == "counter"
    assert families["deconv_tenant_fairness"] == "gauge"
    assert samples[
        ("deconv_tenant_requests_total", 'tenant="acme",class="interactive"')
    ] == 1.0
    assert samples[
        ("deconv_tenant_requests_total", 'tenant="acme",class="bulk"')
    ] == 2.0
    assert samples[
        ("deconv_tenant_device_ms_total", 'tenant="acme"')
    ] == pytest.approx(12.345)
    # mismatched label names on an existing family are a programming
    # error, loudly
    with pytest.raises(ValueError):
        m.inc_labeled("tenant_requests_total", "tenant", "acme")
    with pytest.raises(TypeError):
        m.inc_labeled("tenant_requests_total", ("tenant", "class"), "acme")
    # a short value tuple would zip-truncate into an ambiguous sample
    # missing labels at exposition time — same loud failure
    with pytest.raises(ValueError):
        m.inc_labeled("tenant_requests_total", ("tenant", "class"), ("acme",))
    # the raw quote must not appear unescaped inside any label block
    for line in text.splitlines():
        if "we" in line and "ird" in line:
            assert '\\"' in line


def test_router_families_lint_in_non_core_registry():
    """Round-14 fleet router: its registry runs core=False — only the
    generic counter/gauge/labeled/stage families render, so the labeled
    ``router_requests_total{backend=}`` family cannot collide with the
    batching server's fixed ``requests_total`` under the same prefix."""
    m = Metrics(prefix="router", core=False)
    m.inc_labeled("requests_total", "backend", "10.0.0.1:8000", 3)
    m.inc_labeled("requests_total", "backend", 'we"ird\\host:1')
    m.set_labeled_gauge("backend_state", "backend", "10.0.0.1:8000", 0)
    m.set_labeled_gauge("backend_state", "backend", "10.0.0.2:8000", 2)
    m.inc_counter("rebalanced_keys_total", 7)
    m.set_gauge("backends_in_ring", 1)
    m.observe_stage("forward", 0.004)
    m.observe_request(0.004)
    m.observe_request(0.009, error_code="backend_unavailable")
    text = m.prometheus()
    families, samples = lint_exposition(text)
    assert families["router_requests_total"] == "counter"
    assert families["router_backend_state"] == "gauge"
    assert families["router_rebalanced_keys_total"] == "counter"
    assert families["router_backends_in_ring"] == "gauge"
    assert families["router_stage_seconds"] == "summary"
    assert families["router_errors_total"] == "counter"
    assert samples[
        ("router_requests_total", 'backend="10.0.0.1:8000"')
    ] == 3.0
    assert samples[
        ("router_backend_state", 'backend="10.0.0.2:8000"')
    ] == 2.0
    assert samples[("router_rebalanced_keys_total", "")] == 7.0
    # hostile backend label round-trips the escaping grammar
    assert any(
        '\\"' in label for name, label in samples
        if name == "router_requests_total"
    )
    # the core batching-server families are ABSENT, not rendered at zero
    for absent in (
        "router_batches_total", "router_images_total",
        "router_request_latency_seconds", "router_batch_size",
        "router_images_per_sec",
    ):
        assert absent not in families
        assert not any(name == absent for name, _ in samples)
    # default registries are unaffected by the flag's existence
    core_families, _ = lint_exposition(Metrics().prometheus())
    assert core_families["deconv_requests_total"] == "counter"


def test_counters_monotone_across_two_snapshots():
    m = Metrics()
    _traffic(m)
    _, first = lint_exposition(m.prometheus())
    _traffic(m)  # more traffic strictly increases every counter touched
    families, second = lint_exposition(m.prometheus())
    counter_families = {n for n, k in families.items() if k == "counter"}
    checked = 0
    for key, v2 in second.items():
        if key[0] in counter_families and key in first:
            assert v2 >= first[key], f"counter {key} went backwards"
            checked += 1
    assert checked >= 5  # requests/images/batches/errors/cache at least


def test_multi_stream_exposition_with_trace_block_lints():
    """The live /v1/metrics response concatenates three prefixed Metrics
    streams plus the flight recorder's trace block; family uniqueness
    must hold across the whole concatenation."""
    main, dream, sweep = Metrics(), Metrics("dream"), Metrics("sweep")
    for m in (main, dream, sweep):
        _traffic(m)
    rec = FlightRecorder(8, slow_ms=1.0, sample=1.0)
    for i in range(3):
        tr = RequestTrace(f"rid-{i}", "/")
        t0 = tr.t0
        tr.add_span("decode", t0, 0.001)
        tr.add_span("dispatch", t0 + 0.001, 0.004, batch_id=i + 1)
        tr.finish(status=200 if i else 422, error=None if i else "unknown_layer")
        rec.record(tr)
    text = (
        main.prometheus() + dream.prometheus() + sweep.prometheus()
        + rec.prometheus("deconv")
    )
    families, samples = lint_exposition(text)
    assert families["deconv_traces_total"] == "counter"
    assert families["deconv_trace_span_seconds_total"] == "counter"
    assert samples[("deconv_traces_total", 'class="all"')] == 3.0
    assert samples[("deconv_traces_total", 'class="error"')] == 1.0
    assert samples[("deconv_trace_spans_total", 'span="decode"')] == 3.0


def test_escape_label_helper():
    assert escape_label('a"b') == 'a\\"b'
    assert escape_label("a\\b") == "a\\\\b"
    assert escape_label("a\nb") == "a\\nb"
    assert escape_label("plain_code") == "plain_code"


def test_exemplar_syntax_lints_and_joins_to_request_id():
    """Round 23: ``observe_hist(..., exemplar=rid)`` renders the most
    recent request id per bucket as an OpenMetrics exemplar — the
    metrics→trace join — and the lint validates the suffix without
    disturbing the sample's own parse."""
    m = Metrics()
    _traffic(m)
    m.observe_hist(
        "request_duration_seconds", ("route", "qos_class"),
        ("/v1/deconv", "standard"), 0.012, exemplar="r-abc123",
    )
    text = m.prometheus()
    families, samples = lint_exposition(text)
    assert families["deconv_request_duration_seconds"] == "histogram"
    # the exemplar rides the matching bucket line and only that line
    ex_lines = [
        line for line in text.splitlines() if ' # {trace_id="r-abc123"}' in line
    ]
    assert ex_lines, "exemplar missing from exposition"
    for line in ex_lines:
        assert "_bucket{" in line
    # newest-wins: a later observation into the same bucket replaces it
    m.observe_hist(
        "request_duration_seconds", ("route", "qos_class"),
        ("/v1/deconv", "standard"), 0.012, exemplar="r-newer",
    )
    text2 = m.prometheus()
    lint_exposition(text2)
    assert ' # {trace_id="r-newer"}' in text2
    le_of = [
        line for line in text2.splitlines()
        if ' # {trace_id="r-newer"}' in line
    ]
    assert len(le_of) == 1
    # values without exemplars stay byte-identical to the classic shape
    assert "deconv_request_duration_seconds_sum" in text2


def test_exemplar_on_non_bucket_sample_rejected():
    with pytest.raises(AssertionError):
        lint_exposition(
            "# TYPE deconv_cache_hits_total counter\n"
            'deconv_cache_hits_total 3 # {trace_id="r-1"} 0.5\n'
        )


def test_alert_state_families_lint():
    """Round 23: the alert engine's gauge/counter families hold the
    exposition contract from the first scrape (every rule
    pre-registered, no family duplicated)."""
    import json

    from deconv_api_tpu.serving.alerts import AlertEngine, parse_alert_rules
    from deconv_api_tpu.serving.tsdb import Tsdb

    rules = parse_alert_rules(json.dumps([
        {"name": "hot", "kind": "threshold", "family": "errors_total",
         "agg": "mean", "op": ">", "value": 1.0, "range_s": 30.0,
         "for_s": 5.0, "severity": "warn"},
        {"name": "gone", "kind": "absence", "family": "requests_total",
         "stale_s": 30.0, "for_s": 0.0, "severity": "page"},
    ]))
    engine = AlertEngine(rules, Tsdb(1.0), clock=lambda: 100.0)
    families, samples = lint_exposition(engine.prometheus("deconv"))
    assert families["deconv_alert_state"] == "gauge"
    assert families["deconv_alerts_fired_total"] == "counter"
    assert families["deconv_alerts_resolved_total"] == "counter"
    assert families["deconv_alerts_eval_errors_total"] == "counter"
    assert samples[("deconv_alert_state", 'rule="hot"')] == 0.0
    assert samples[("deconv_alert_state", 'rule="gone"')] == 0.0
