"""Autodiff-deconv cross-validation and DAG-model smoke tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deconv_api_tpu.engine import autodeconv_visualizer, visualize
from deconv_api_tpu.models.apply import spec_forward
from deconv_api_tpu.models.spec import init_params
from tests.test_engine_parity import TINY


@pytest.fixture(scope="module")
def tiny_setup():
    params = init_params(TINY, jax.random.PRNGKey(42))
    img = jax.random.normal(jax.random.PRNGKey(7), (16, 16, 3))
    return params, img


@pytest.mark.parametrize("mode", ["all", "max"])
@pytest.mark.parametrize("layer", ["b1c2", "b2c1"])
def test_autodeconv_matches_sequential_engine_clean_mode(tiny_setup, layer, mode):
    """jax.vjp with deconv rules must equal the hand-built clean-mode chain
    (bug_compat=False) — two independent formulations of Zeiler–Fergus."""
    params, img = tiny_setup
    fn = autodeconv_visualizer(spec_forward(TINY), layer, top_k=8, mode=mode)
    got = fn(params, img)
    want = visualize(TINY, params, img, layer, mode=mode, bug_compat=False)
    np.testing.assert_array_equal(np.asarray(got["indices"]), np.asarray(want["indices"]))
    np.testing.assert_allclose(
        np.asarray(got["images"]), np.asarray(want["images"]), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_array_equal(np.asarray(got["valid"]), np.asarray(want["valid"]))


def test_autodeconv_illegal_mode():
    with pytest.raises(ValueError, match="illegal visualize mode"):
        autodeconv_visualizer(spec_forward(TINY), "b1c1", mode="nope")


@pytest.mark.parametrize("mode", ["all", "max"])
def test_autodeconv_sweep_matches_sequential_sweep(tiny_setup, mode):
    """The DAG all-layers sweep (one shared forward, one zero-padded vjp
    cotangent per swept layer) vs the sequential engine's sweep in clean
    mode — two independent sweep formulations must agree on every layer,
    including the pool entry, in both visualize modes."""
    from deconv_api_tpu.engine import visualize_all_layers

    params, img = tiny_setup
    names = ("b2c1", "b1p", "b1c2", "b1c1")
    fn = autodeconv_visualizer(
        spec_forward(TINY), "b2c1", top_k=8, mode=mode, sweep_layers=names
    )
    got = fn(params, img)
    want = visualize_all_layers(
        TINY, params, img, "b2c1", mode=mode, bug_compat=False
    )
    assert set(got) == set(want)
    for name in names:
        np.testing.assert_array_equal(
            np.asarray(got[name]["indices"]), np.asarray(want[name]["indices"])
        )
        np.testing.assert_allclose(
            np.asarray(got[name]["images"]), np.asarray(want[name]["images"]),
            rtol=1e-4, atol=1e-5, err_msg=name,
        )
        np.testing.assert_array_equal(
            np.asarray(got[name]["valid"]), np.asarray(want[name]["valid"])
        )


# ----------------------------------------------------------------- ResNet50


@pytest.fixture(scope="module")
def resnet():
    from deconv_api_tpu.models.resnet50 import resnet50_forward, resnet50_init

    params = resnet50_init(jax.random.PRNGKey(0), num_classes=10)
    return params, resnet50_forward


def test_resnet50_forward_shapes(resnet):
    params, fwd = resnet
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 64, 64, 3))
    probs, acts = jax.jit(lambda p, x: fwd(p, x))(params, x)
    assert probs.shape == (1, 10)
    np.testing.assert_allclose(float(probs.sum()), 1.0, rtol=1e-4)
    assert acts["conv1_relu"].shape == (1, 32, 32, 64)
    assert acts["conv2_block3_out"].shape == (1, 16, 16, 256)
    assert acts["conv5_block3_out"].shape == (1, 2, 2, 2048)


def test_resnet50_param_count(resnet):
    params, _ = resnet
    n = sum(x.size for x in jax.tree.leaves(params))
    # published ResNet50 (include_top, 1000 classes) ~= 25.6M; ours has
    # 10 classes (-2.03M head) and inference-only BN (mean/var counted too)
    assert 23e6 < n < 28e6


def test_resnet50_autodeconv_sweep(resnet):
    """All-layers sweep on a residual/strided DAG — the reference's
    signature always-on behaviour (app/deepdream.py:441-474), which its
    sequential walk could never express for this topology.  Each swept
    entry must equal the single-layer projection from that layer."""
    params, fwd = resnet
    img = jax.random.normal(jax.random.PRNGKey(2), (64, 64, 3))
    names = ("conv3_block1_out", "conv2_block3_out", "conv2_block2_out")
    fn = autodeconv_visualizer(fwd, "conv3_block1_out", top_k=2, sweep_layers=names)
    got = fn(params, img)
    assert set(got) == set(names)
    for name in names:
        single = autodeconv_visualizer(fwd, name, top_k=2)(params, img)
        np.testing.assert_array_equal(
            np.asarray(got[name]["indices"]), np.asarray(single["indices"])
        )
        np.testing.assert_allclose(
            np.asarray(got[name]["images"]), np.asarray(single["images"]),
            rtol=1e-4, atol=1e-5, err_msg=name,
        )


def test_resnet50_autodeconv_strided_path(resnet):
    """BASELINE config 4: deconv through strided convs + residuals, no
    explicit switches — impossible in the reference's sequential walk."""
    params, fwd = resnet
    img = jax.random.normal(jax.random.PRNGKey(2), (64, 64, 3))
    fn = autodeconv_visualizer(fwd, "conv3_block1_out", top_k=4)
    out = fn(params, img)
    assert out["images"].shape == (4, 64, 64, 3)
    assert bool(jnp.isfinite(out["images"]).all())
    assert bool(out["valid"].any())
    # projection is input-dependent, not constant
    img2 = jax.random.normal(jax.random.PRNGKey(3), (64, 64, 3))
    out2 = fn(params, img2)
    assert not np.allclose(np.asarray(out["images"]), np.asarray(out2["images"]))


# -------------------------------------------------------------- MobileNetV1


def test_mobilenet_v1_forward_shapes():
    from deconv_api_tpu.models.mobilenet_v1 import (
        mobilenet_v1_forward,
        mobilenet_v1_init,
    )

    params = mobilenet_v1_init(jax.random.PRNGKey(0), num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 128, 128, 3))
    probs, acts = jax.jit(lambda p, x: mobilenet_v1_forward(p, x))(params, x)
    assert probs.shape == (1, 10)
    np.testing.assert_allclose(float(probs.sum()), 1.0, rtol=1e-4)
    assert acts["conv1_relu"].shape == (1, 64, 64, 32)
    assert acts["conv_pw_6_relu"].shape == (1, 8, 8, 512)
    assert acts["conv_pw_13_relu"].shape == (1, 4, 4, 1024)
    # relu6 cap actually applies
    assert float(max(np.max(np.asarray(acts[k])) for k in acts if k != "predictions")) <= 6.0


def test_mobilenet_v1_autodeconv_depthwise_path():
    """Deconv through depthwise-separable convs + ReLU6 under the deconv
    rule — conv types and activations the other three families never
    exercise, handled by the same autodiff engine."""
    from deconv_api_tpu.models.mobilenet_v1 import (
        mobilenet_v1_forward,
        mobilenet_v1_init,
    )

    params = mobilenet_v1_init(jax.random.PRNGKey(0), num_classes=10)
    img = jax.random.normal(jax.random.PRNGKey(2), (128, 128, 3))
    fn = autodeconv_visualizer(mobilenet_v1_forward, "conv_pw_11_relu", top_k=4)
    out = fn(params, img)
    assert out["images"].shape == (4, 128, 128, 3)
    assert bool(jnp.isfinite(out["images"]).all())
    assert bool(out["valid"].any())
    img2 = jax.random.normal(jax.random.PRNGKey(3), (128, 128, 3))
    out2 = fn(params, img2)
    assert not np.allclose(np.asarray(out["images"]), np.asarray(out2["images"]))


def test_mobilenet_v2_autodeconv_inverted_residual_path():
    """Deconv through inverted residuals with LINEAR bottlenecks and
    residual adds — structures the reference exits on."""
    from deconv_api_tpu.models.mobilenet_v2 import (
        mobilenet_v2_forward,
        mobilenet_v2_init,
    )

    params = mobilenet_v2_init(jax.random.PRNGKey(0), num_classes=10)
    img = jax.random.normal(jax.random.PRNGKey(2), (128, 128, 3))
    fn = autodeconv_visualizer(mobilenet_v2_forward, "block_6_expand_relu", top_k=4)
    out = fn(params, img)
    assert out["images"].shape == (4, 128, 128, 3)
    assert bool(jnp.isfinite(out["images"]).all())
    assert bool(out["valid"].any())


# -------------------------------------------------------------- InceptionV3


def test_inception_v3_autodeconv_branching_path():
    """Deconv through the inception mixed blocks: the vjp must route
    cotangents back through CONCATENATED parallel branches (1x1 / factored
    / pool towers) and the VALID-padded stem — the branching topology no
    other family exercises.  Includes a two-layer sweep (shared forward,
    per-layer seeds) across a concat boundary."""
    from deconv_api_tpu.models.inception_v3 import (
        inception_v3_forward,
        inception_v3_init,
    )

    params = inception_v3_init(jax.random.PRNGKey(0), num_classes=10)
    img = jax.random.normal(jax.random.PRNGKey(2), (75, 75, 3))
    single = autodeconv_visualizer(inception_v3_forward, "mixed1", top_k=2)
    out = single(params, img)
    assert out["images"].shape == (2, 75, 75, 3)
    assert bool(jnp.isfinite(out["images"]).all())
    assert bool(out["valid"].any())

    swept = autodeconv_visualizer(
        inception_v3_forward, "mixed1", top_k=2,
        sweep_layers=("mixed1", "mixed0"),
    )(params, img)
    assert set(swept) == {"mixed1", "mixed0"}
    # the swept mixed1 entry must equal the single-layer projection
    np.testing.assert_array_equal(
        np.asarray(swept["mixed1"]["indices"]), np.asarray(out["indices"])
    )
    np.testing.assert_allclose(
        np.asarray(swept["mixed1"]["images"]), np.asarray(out["images"]),
        rtol=1e-4, atol=1e-5,
    )
    assert bool(jnp.isfinite(swept["mixed0"]["images"]).all())


def test_inception_v3_forward_shapes():
    from deconv_api_tpu.models.inception_v3 import (
        inception_v3_forward,
        inception_v3_init,
    )

    params = inception_v3_init(jax.random.PRNGKey(0), num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 75, 75, 3))
    probs, acts = jax.jit(lambda p, x: inception_v3_forward(p, x))(params, x)
    assert probs.shape == (1, 10)
    np.testing.assert_allclose(float(probs.sum()), 1.0, rtol=1e-4)
    # channel counts must match Keras InceptionV3 exactly
    assert acts["mixed0"].shape[-1] == 256
    assert acts["mixed2"].shape[-1] == 288
    assert acts["mixed3"].shape[-1] == 768
    assert acts["mixed7"].shape[-1] == 768
    assert acts["mixed8"].shape[-1] == 1280
    assert acts["mixed10"].shape[-1] == 2048
