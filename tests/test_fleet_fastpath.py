"""Router data-plane fast path tests (round 21, serving/fleet.py):
per-backend keep-alive pools (checkout/reuse/idle-reap/stale-retry-once,
hedge-loser destroy, ejection flush), the zero-copy streaming relay
(incremental chunks, backpressure, torn-stream semantics), SO_REUSEPORT
multi-router port sharing, pooled-vs-dialed byte parity, the RFC 9110
§7.6.1 connection-nominated strip, and exposition lint for every new
metric family."""

import asyncio
import hashlib
import json
import socket
import time

import pytest

from deconv_api_tpu.serving import fleet
from deconv_api_tpu.serving.fleet import (
    BackendMember,
    BackendPool,
    FleetRouter,
)
from deconv_api_tpu.serving.http import HttpServer, Request, Response
from deconv_api_tpu.serving.metrics import Metrics


class _FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------- raw stubs


async def _start_raw_stub(handler):
    """Minimal HTTP stub on a raw asyncio server — full control of
    framing and connection lifecycle (the pieces HttpServer abstracts
    away are exactly what these tests exercise)."""
    srv = await asyncio.start_server(handler, "127.0.0.1", 0)
    port = srv.sockets[0].getsockname()[1]
    return srv, port


async def _read_head(reader):
    head = await reader.readuntil(b"\r\n\r\n")
    lines = head[:-4].decode("latin-1").split("\r\n")
    method, target, _ = lines[0].split(" ", 2)
    headers = {}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        headers[k.strip().lower()] = v.strip()
    body = b""
    cl = headers.get("content-length")
    if cl and cl.isdigit() and int(cl):
        body = await reader.readexactly(int(cl))
    return method, target, headers, body


def _framed(payload: bytes, status: int = 200, extra: str = "") -> bytes:
    return (
        f"HTTP/1.1 {status} OK\r\ncontent-type: text/plain\r\n{extra}"
        f"content-length: {len(payload)}\r\n\r\n"
    ).encode("latin-1") + payload


async def _echo_handler(reader, writer):
    """Keep-alive echo: POST/GET any target -> 200 'ok:<body>'."""
    try:
        while True:
            _m, _t, _h, body = await _read_head(reader)
            writer.write(_framed(b"ok:" + body))
            await writer.drain()
    except (
        asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError
    ):
        pass
    finally:
        writer.close()


def _pool(port, metrics=None, **kw):
    return BackendPool(
        f"127.0.0.1:{port}", "127.0.0.1", port, metrics=metrics, **kw
    )


# --------------------------------------------------- pool unit behavior


def test_pool_checkout_reuse_and_gauges():
    async def go():
        srv, port = await _start_raw_stub(_echo_handler)
        metrics = Metrics(prefix="router", core=False)
        pool = _pool(port, metrics)
        try:
            s1, _h1, b1 = await pool.request("POST", "/", {}, b"a", 5.0)
            s2, _h2, b2 = await pool.request("POST", "/", {}, b"b", 5.0)
            assert (s1, b1) == (200, b"ok:a")
            assert (s2, b2) == (200, b"ok:b")
            # one dial, then the parked socket is reused (LIFO)
            assert pool.dials == 1 and pool.reuses == 1
            assert pool.in_use == 0 and len(pool._idle) == 1
            assert metrics.counter("pool_dial_total") == 1
            assert metrics.counter("pool_reuse_total") == 1
            name = pool.name
            assert metrics.labeled_gauge("pool_idle")[name] == 1
            assert metrics.labeled_gauge("pool_in_use")[name] == 0
            # dial wall time surfaced as the probe-RTT honesty metric
            assert metrics.labeled("connect_seconds_total")[name] > 0
        finally:
            pool.flush()
            srv.close()
            await srv.wait_closed()

    asyncio.run(go())


def test_pool_release_bound_and_flush():
    async def go():
        srv, port = await _start_raw_stub(_echo_handler)
        pool = _pool(port, size=2)
        try:
            conns = [await pool.checkout(fresh=True) for _ in range(3)]
            assert pool.in_use == 3
            for c in conns:
                pool.release(c)
            # the idle list is bounded at size; the overflow is closed
            assert len(pool._idle) == 2 and pool.in_use == 0
            pool.flush()
            assert len(pool._idle) == 0
        finally:
            pool.flush()
            srv.close()
            await srv.wait_closed()

    asyncio.run(go())


def test_pool_idle_reap_and_expired_checkout():
    async def go():
        srv, port = await _start_raw_stub(_echo_handler)
        clock = _FakeClock()
        metrics = Metrics(prefix="router", core=False)
        pool = _pool(port, metrics, idle_max_s=30.0, clock=clock)
        try:
            await pool.request("GET", "/", {}, b"", 5.0)
            assert len(pool._idle) == 1
            # within the window the reap keeps it
            clock.t += 10
            pool.reap()
            assert len(pool._idle) == 1
            # past the window the probe-tick reap closes it
            clock.t += 25
            pool.reap()
            assert len(pool._idle) == 0
            assert metrics.labeled_gauge("pool_idle")[pool.name] == 0
            # an expired socket still parked at checkout time is
            # skipped (closed), not handed out
            await pool.request("GET", "/", {}, b"", 5.0)
            clock.t += 31
            await pool.request("GET", "/", {}, b"", 5.0)
            assert pool.dials == 3 and pool.reuses == 0
        finally:
            pool.flush()
            srv.close()
            await srv.wait_closed()

    asyncio.run(go())


def test_pool_stale_retry_once_on_reused_eof():
    """A REUSED socket dying before any response byte is the keep-alive
    race: retried exactly once on a fresh dial, counted, invisible to
    the caller."""

    async def go():
        srv, port = await _start_raw_stub(_echo_handler)
        metrics = Metrics(prefix="router", core=False)
        pool = _pool(port, metrics)
        try:
            await pool.request("GET", "/", {}, b"", 5.0)  # park one
            orig = pool._roundtrip
            seen = []

            async def flaky(c, wire):
                seen.append(c.reused)
                if len(seen) == 1:
                    raise ConnectionResetError("peer reset idle socket")
                return await orig(c, wire)

            pool._roundtrip = flaky
            status, _h, body = await pool.request("GET", "/", {}, b"", 5.0)
            assert status == 200 and body == b"ok:"
            # attempt 0 drew the parked (reused) socket, the retry
            # dialed fresh
            assert seen == [True, False]
            assert pool.stale_retries == 1
            assert metrics.counter("pool_stale_retry_total") == 1
            assert pool.in_use == 0  # nothing leaked either way
        finally:
            pool.flush()
            srv.close()
            await srv.wait_closed()

    asyncio.run(go())


def test_pool_fresh_socket_failure_never_retried():
    """The retry is for the keep-alive race ONLY: a freshly dialed
    socket's reset is a real backend failure, surfaced first time."""

    async def go():
        srv, port = await _start_raw_stub(_echo_handler)
        pool = _pool(port)
        try:

            async def dead(c, wire):
                raise ConnectionResetError("backend fell over")

            pool._roundtrip = dead
            with pytest.raises(fleet._BackendError):
                await pool.request("GET", "/", {}, b"", 5.0)
            assert pool.stale_retries == 0
            assert pool.in_use == 0 and len(pool._idle) == 0
        finally:
            pool.flush()
            srv.close()
            await srv.wait_closed()

    asyncio.run(go())


def test_pool_hedge_loser_cancellation_destroys_never_leaks():
    """A hedge loser is cancelled mid-roundtrip: the socket (with an
    unread response possibly in flight) must be destroyed — returning
    it would hand the NEXT checkout a poisoned stream."""

    async def hang_handler(reader, writer):
        try:
            await _read_head(reader)
            await asyncio.sleep(3600)  # never answers
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    async def go():
        srv, port = await _start_raw_stub(hang_handler)
        pool = _pool(port)
        try:
            task = asyncio.create_task(
                pool.request("GET", "/", {}, b"", 30.0)
            )
            await asyncio.sleep(0.1)
            assert pool.in_use == 1
            task.cancel()
            with pytest.raises(asyncio.CancelledError):
                await task
            # destroyed, not parked: the loser's socket never returns
            assert pool.in_use == 0 and len(pool._idle) == 0
        finally:
            pool.flush()
            srv.close()
            await srv.wait_closed()

    asyncio.run(go())


# ------------------------------------------------- router + pool wiring


async def _boot_http_stub():
    """HttpServer stub: /readyz for probes, deterministic POST echo,
    a small cached GET — the shape the router proxies."""
    srv = HttpServer()

    async def _readyz(_req):
        return Response(
            status=200, body=b'{"ready": true}',
            headers={"content-type": "application/json"},
        )

    async def _models(_req):
        return Response(
            status=200, body=b'{"models": []}',
            headers={"content-type": "application/json", "x-cache": "hit"},
        )

    async def _echo(req):
        digest = hashlib.sha256(req.body).hexdigest().encode()
        return Response(status=200, body=digest)

    srv.route("GET", "/readyz")(_readyz)
    srv.route("GET", "/v1/models")(_models)
    srv.route("POST", "/")(_echo)
    port = await srv.start("127.0.0.1", 0)
    return srv, port


def _req(method, path, body=b"", headers=None, i="x"):
    return Request(
        method=method, path=path, query={},
        headers=dict(headers or {}), body=body, id=f"rid-fastpath-{i}",
    )


def test_router_ejection_flushes_member_pool():
    async def go():
        srv, port = await _boot_http_stub()
        name = f"127.0.0.1:{port}"
        router = FleetRouter([name], probe_interval_s=30.0)
        try:
            await router.probe_once()
            m = router.members[name]
            assert m.in_ring
            resp = await router._proxy(_req("GET", "/v1/models"))
            assert resp.status == 200
            pool = router.pools[name]
            assert len(pool._idle) >= 1  # warm socket parked
            router._set_state(m, "ejected", "test")
            # leaving the ring flushed the member's warm sockets
            assert len(pool._idle) == 0
        finally:
            for p in router.pools.values():
                p.flush()
            await srv.stop(grace_s=0.2)

    asyncio.run(go())


def test_fault_sites_fire_on_pooled_connections():
    """The fleet.* sites must keep working now that forwards ride the
    pool: connect_delay shapes wall time, torn_body still tears."""

    async def go():
        srv, port = await _boot_http_stub()
        name = f"127.0.0.1:{port}"
        router = FleetRouter(
            [name], probe_interval_s=30.0, fault_injection=True
        )
        try:
            await router.probe_once()
            router.faults.arm("fleet.connect_delay_ms", f"p1:200@{name}")
            t0 = time.perf_counter()
            resp = await router._proxy(_req("GET", "/v1/models", i="cd"))
            dt = time.perf_counter() - t0
            assert resp.status == 200 and dt >= 0.2
            router.faults.disarm("fleet.connect_delay_ms")
            router.faults.arm("fleet.torn_body", f"n1@{name}")
            await router._proxy(_req("GET", "/v1/models", i="torn"))
            fired = router.metrics.labeled("faults_injected_total")
            assert fired.get("fleet.torn_body") == 1
            # and all of it went over the pool, not a per-request dial
            assert router.pools[name].dials >= 1
        finally:
            for p in router.pools.values():
                p.flush()
            await srv.stop(grace_s=0.2)

    asyncio.run(go())


def test_byte_parity_pooled_vs_dialed_and_pool_off_pin():
    """16 sampled keys through a pooled router and a --connection-pool
    off router: byte-identical to each other and to the direct oracle;
    the dialed router never creates a pool (the escape hatch IS the
    pre-round-21 dial-per-forward dialect)."""

    async def go():
        srv, port = await _boot_http_stub()
        name = f"127.0.0.1:{port}"
        pooled = FleetRouter([name], probe_interval_s=30.0)
        dialed = FleetRouter(
            [name], probe_interval_s=30.0, connection_pool=False
        )
        try:
            await pooled.probe_once()
            await dialed.probe_once()
            bodies = [f"parity-key-{i}".encode() * 7 for i in range(16)]
            for i, body in enumerate(bodies):
                want = hashlib.sha256(body).hexdigest().encode()
                rp = await pooled._proxy(_req("POST", "/", body, i=f"p{i}"))
                rd = await dialed._proxy(_req("POST", "/", body, i=f"d{i}"))
                assert rp.status == rd.status == 200
                assert rp.body == rd.body == want
            pool = pooled.pools[name]
            assert pool.dials >= 1 and pool.reuses >= 1
            # connection_pool=False never builds a pool at all
            assert dialed.pools == {}
        finally:
            for p in pooled.pools.values():
                p.flush()
            await srv.stop(grace_s=0.2)

    asyncio.run(go())


def test_exposition_lint_every_new_family():
    """Every round-21 family renders with exactly one TYPE line and at
    least one sample — including the never-fired counters (stale retry,
    torn relay), which must read 0 rather than vanish."""

    async def go():
        srv, port = await _boot_http_stub()
        name = f"127.0.0.1:{port}"
        router = FleetRouter([name], probe_interval_s=30.0)
        try:
            await router.probe_once()
            await router._proxy(_req("GET", "/v1/models"))
            text = router.metrics.prometheus()
            for fam in (
                "router_pool_dial_total",
                "router_pool_reuse_total",
                "router_pool_stale_retry_total",
                "router_connect_seconds_total",
                "router_pool_idle",
                "router_pool_in_use",
                "router_relayed_responses_total",
                "router_relay_bytes_total",
                "router_relay_torn_total",
            ):
                assert text.count(f"# TYPE {fam} ") == 1, fam
                samples = [
                    line for line in text.splitlines()
                    if not line.startswith("#")
                    and line.partition(" ")[0].partition("{")[0] == fam
                ]
                assert samples, f"no sample line for {fam}"
        finally:
            for p in router.pools.values():
                p.flush()
            await srv.stop(grace_s=0.2)

    asyncio.run(go())


# -------------------------------------------------------- streaming relay


def test_request_stream_sse_chunks_arrive_incrementally():
    """SSE relay timing: the first event must reach the consumer while
    the server is still producing later ones — buffering to completion
    (the pre-round-21 shape) would hold everything to the end."""

    done = asyncio.Event
    state = {}

    async def sse_handler(reader, writer):
        try:
            await _read_head(reader)
            writer.write(
                b"HTTP/1.1 200 OK\r\n"
                b"content-type: text/event-stream\r\n\r\n"
            )
            await writer.drain()
            for i in range(3):
                writer.write(f"data: event-{i}\n\n".encode())
                await writer.drain()
                await asyncio.sleep(0.12)
            state["server_done"].set()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    async def go():
        state["server_done"] = done()
        srv, port = await _start_raw_stub(sse_handler)
        pool = _pool(port)
        try:
            status, headers, chunks = await pool.request_stream(
                "GET", "/events", {}, b"", 5.0
            )
            assert status == 200
            got = b""
            first_seen_early = None
            async for chunk in chunks:
                if first_seen_early is None:
                    first_seen_early = not state["server_done"].is_set()
                got += chunk
            assert first_seen_early is True
            assert got.count(b"data: event-") == 3
            # an unframed (EOF-terminated) stream spends the socket
            assert pool.in_use == 0 and len(pool._idle) == 0
        finally:
            pool.flush()
            srv.close()
            await srv.wait_closed()

    asyncio.run(go())


def test_request_stream_framed_body_returns_socket():
    async def go():
        srv, port = await _start_raw_stub(_echo_handler)
        pool = _pool(port)
        try:
            status, headers, chunks = await pool.request_stream(
                "POST", "/", {}, b"zz", 5.0
            )
            got = b"".join([c async for c in chunks])
            assert status == 200 and got == b"ok:zz"
            # exact content-length consumed -> reusable, parked
            assert len(pool._idle) == 1 and pool.in_use == 0
        finally:
            pool.flush()
            srv.close()
            await srv.wait_closed()

    asyncio.run(go())


def test_relay_backpressure_pulls_lazily():
    """The relay must not read ahead of the client: each upstream pull
    happens only when the consumer asks for the next chunk — that
    per-chunk lockstep is what propagates client backpressure to the
    upstream socket."""

    async def go():
        router = FleetRouter(["b0:8000"])
        pulls = []
        consumed = []

        async def upstream():
            for i in range(5):
                pulls.append(i)
                yield b"x" * 1024

        relay = router._relay_stream(
            upstream(), BackendMember("b0:8000"), None, 200, None, None
        )
        async for chunk in relay:
            consumed.append(chunk)
            # lazy lockstep: never more than one pull ahead of the
            # chunks the consumer has actually taken
            assert len(pulls) <= len(consumed) + 1
            await asyncio.sleep(0.01)
        assert len(consumed) == 5
        assert router.metrics.counter("relayed_responses_total") == 1
        assert router.metrics.counter("relay_bytes_total") == 5 * 1024

    asyncio.run(go())


def test_torn_stream_mid_relay_truncates_client_no_breaker_feed():
    """Upstream dies mid-relay: the client sees a short body under the
    preserved content-length (detectable truncation, not a silent
    success), the router counts relay_torn_total, and the member's
    breaker is NOT fed a second failure for a forward whose head
    already succeeded."""

    big_cl = 400_000
    sent = 100_000

    async def torn_handler(reader, writer):
        try:
            while True:
                _m, target, _h, _b = await _read_head(reader)
                if target == "/readyz":
                    writer.write(_framed(b'{"ready": true}'))
                    await writer.drain()
                    continue
                writer.write(
                    (
                        f"HTTP/1.1 200 OK\r\ncontent-type: application/"
                        f"octet-stream\r\ncontent-length: {big_cl}\r\n\r\n"
                    ).encode("latin-1")
                )
                writer.write(b"y" * sent)
                await writer.drain()
                writer.close()
                return
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass

    async def go():
        srv, port = await _start_raw_stub(torn_handler)
        name = f"127.0.0.1:{port}"
        router = FleetRouter(
            [name], probe_interval_s=30.0, stream_relay_min_bytes=1024
        )
        rport = await router.start("127.0.0.1", 0)
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", rport
            )
            writer.write(b"GET /big HTTP/1.1\r\nhost: x\r\n\r\n")
            await writer.drain()
            head = await reader.readuntil(b"\r\n\r\n")
            assert b" 200 " in head.split(b"\r\n", 1)[0]
            # content-length preserved so the client can DETECT the tear
            assert f"content-length: {big_cl}".encode() in head.lower()
            body = await reader.read()
            writer.close()
            assert 0 < len(body) < big_cl  # truncated, visibly
            await asyncio.sleep(0.1)
            assert router.metrics.counter("relay_torn_total") == 1
            m = router.members[name]
            # the tear was the body's, not the forward's: no breaker
            # feed, the member stays healthy and in the ring
            assert m.state == "healthy" and m.in_ring
        finally:
            await router.stop(grace_s=0.2)
            srv.close()
            await srv.wait_closed()

    asyncio.run(go())


# ---------------------------------------------------- SO_REUSEPORT workers


@pytest.mark.skipif(
    not hasattr(socket, "SO_REUSEPORT"), reason="no SO_REUSEPORT"
)
def test_reuseport_routers_share_port_identical_placement():
    async def go():
        srv, port = await _boot_http_stub()
        name = f"127.0.0.1:{port}"
        r0 = FleetRouter([name], probe_interval_s=30.0, worker=0)
        r1 = FleetRouter([name], probe_interval_s=30.0, worker=1)
        shared = await r0.start("127.0.0.1", 0, reuse_port=True)
        try:
            assert await r1.start(
                "127.0.0.1", shared, reuse_port=True
            ) == shared
            # stateless-by-construction: same member view => identical
            # placement, so ANY worker answering is correct
            keys = [f"{i:02d}" * 20 for i in range(32)]
            assert [r0.ring.owner(k) for k in keys] == [
                r1.ring.owner(k) for k in keys
            ]
            for i in range(8):
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", shared
                )
                writer.write(
                    b"GET /readyz HTTP/1.1\r\nhost: x\r\n"
                    b"connection: close\r\n\r\n"
                )
                await writer.drain()
                raw = await reader.read()
                writer.close()
                assert raw.split(b"\r\n", 1)[0].endswith(b"200 OK")
            # the /metrics exposition carries worker= on every sample
            # so the PR 14 federation sum over N workers stays truthful
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", shared
            )
            writer.write(
                b"GET /metrics HTTP/1.1\r\nhost: x\r\n"
                b"connection: close\r\n\r\n"
            )
            await writer.drain()
            raw = await reader.read()
            writer.close()
            text = raw.split(b"\r\n\r\n", 1)[-1].decode("latin-1")
            samples = [
                line for line in text.splitlines()
                if line and not line.startswith("#")
            ]
            assert samples and all('worker="' in s for s in samples)
        finally:
            await r0.stop(grace_s=0.2)
            await r1.stop(grace_s=0.2)
            await srv.stop(grace_s=0.2)

    asyncio.run(go())


def test_splice_worker_label_unit():
    text = (
        "# TYPE router_requests_total counter\n"
        "router_requests_total 5\n"
        'router_pool_idle{backend="b:1"} 2\n'
    )
    out = fleet._splice_worker_label(text, 3)
    assert '# TYPE router_requests_total counter' in out
    assert 'router_requests_total{worker="3"} 5' in out
    assert 'router_pool_idle{worker="3",backend="b:1"} 2' in out
    assert out.endswith("\n")


# --------------------------------------- RFC 9110 §7.6.1 nominated strip


def test_connection_nominated_headers_stripped_both_directions():
    # helper: connection-nominated names join the hop-by-hop set
    nominated = fleet._connection_nominated(
        {"connection": "close, X-Secret-Token", "x-secret-token": "s"}
    )
    assert "x-secret-token" in nominated and "connection" in nominated

    router = FleetRouter(["b0:8000"])
    # client -> backend: a client-nominated header never forwards
    req = _req(
        "GET", "/v1/models",
        headers={
            "connection": "x-bar", "x-bar": "1", "x-keep": "2",
            "te": "trailers",
        },
        i="nom",
    )
    fwd = router._forward_headers(req, None, "b0:8000")
    assert "x-bar" not in fwd and "te" not in fwd
    assert "connection" not in fwd
    assert fwd["x-keep"] == "2" and fwd["x-request-id"] == req.id
    # memoized base: the second call reuses the stripped list
    assert router._forward_headers(req, None, "b0:8000")["x-keep"] == "2"

    # backend -> client: an upstream-nominated header never relays
    m = BackendMember("b0:8000")
    resp = router._respond(
        _req("GET", "/v1/models", i="nom2"), m, 200,
        {
            "connection": "x-upstream-secret", "x-upstream-secret": "v",
            "x-cache": "hit", "content-length": "2",
        },
        b"hi", time.perf_counter(),
    )
    assert "x-upstream-secret" not in resp.headers
    assert "connection" not in resp.headers
    assert resp.headers["x-cache"] == "hit"
    assert resp.headers["x-backend"] == "b0:8000"
