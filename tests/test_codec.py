"""Codec tests: wire-format fidelity with the reference (SURVEY §2.2, L3)."""

import base64
from urllib.parse import unquote

import numpy as np
import pytest

from deconv_api_tpu.serving import codec


def _png_data_url(img_bgr: np.ndarray) -> str:
    import cv2

    ok, buf = cv2.imencode(".png", img_bgr)
    assert ok
    return "data:image/png;base64," + base64.b64encode(buf.tobytes()).decode()


def test_decode_data_url_roundtrip(rng):
    img = (rng.random((32, 32, 3)) * 255).astype(np.uint8)
    out = codec.decode_data_url(_png_data_url(img))
    assert out.shape == (32, 32, 3)
    assert out.dtype == np.uint8
    np.testing.assert_array_equal(out, img)  # PNG is lossless


def test_decode_bare_base64_accepted(rng):
    img = (rng.random((16, 16, 3)) * 255).astype(np.uint8)
    uri = _png_data_url(img).split(",", 1)[1]
    assert codec.decode_data_url(uri).shape == (16, 16, 3)


def test_decode_garbage_raises_codec_error():
    with pytest.raises(codec.CodecError):
        codec.decode_data_url("data:image/png;base64,%%%%not-base64")
    with pytest.raises(codec.CodecError):
        codec.decode_data_url("data:image/png;base64," + base64.b64encode(b"nope").decode())
    # pure non-alphabet payload: b64decode(validate=False) strips it to
    # b'', and OpenCV >= 5 RAISES on an empty buffer instead of returning
    # None — must still surface as CodecError, not a 500 (found by the
    # verify drive 2026-07-31)
    with pytest.raises(codec.CodecError):
        codec.decode_data_url("data:image/png;base64,@@@@")


def test_preprocess_vgg_flips_and_subtracts():
    img = np.zeros((2, 2, 3), np.uint8)
    img[..., 0] = 10  # B
    img[..., 2] = 30  # R
    x = codec.preprocess_vgg(img)
    # channel flip: output[...,0] is the old R channel, minus mean[0]
    np.testing.assert_allclose(x[0, 0, 0], 30 - codec.CAFFE_MEANS_BGR[0], rtol=1e-6)
    np.testing.assert_allclose(x[0, 0, 2], 10 - codec.CAFFE_MEANS_BGR[2], rtol=1e-6)


def test_deprocess_image_range_and_dtype(rng):
    x = rng.standard_normal((8, 8, 3)) * 7 + 3
    out = codec.deprocess_image(x)
    assert out.dtype == np.uint8
    # mean maps to 0.5*255
    assert 100 < out.mean() < 155


def test_stitch_grid_2x2(rng):
    tiles = [np.full((4, 4, 3), i, np.float32) for i in range(4)]
    grid = codec.stitch_grid(tiles)
    assert grid.shape == (8, 8, 3)
    assert (grid[:4, :4] == 0).all() and (grid[:4, 4:] == 1).all()
    assert (grid[4:, :4] == 2).all() and (grid[4:, 4:] == 3).all()


def test_stitch_grid_pads_missing_tiles(rng):
    tiles = [np.ones((4, 4, 3), np.float32)]
    grid = codec.stitch_grid(tiles)
    assert grid.shape == (8, 8, 3)
    assert (grid[4:, :] == 0).all()  # padded tiles are zero


def test_encode_data_url_wire_format(rng):
    img = (rng.random((8, 8, 3)) * 255).astype(np.uint8)
    url = codec.encode_data_url(img)
    # the reference's mislabeled prefix + percent-quoted base64 (app/main.py:76)
    assert url.startswith("data:image/webp;base64,")
    payload = unquote(url.split(",", 1)[1])
    raw = base64.b64decode(payload)
    assert raw[:2] == b"\xff\xd8"  # actually JPEG, as in the reference


def test_encode_quote_matches_urllib(rng):
    """The round-6 C-level percent-quote (two bytes.replace calls) must be
    byte-identical to the reference's urllib quote() over the base64
    alphabet — the wire-parity pin behind the fast path."""
    from urllib.parse import quote

    for seed in range(8):
        img = (
            np.random.default_rng(seed).random((16, 16, 3)) * 255
        ).astype(np.uint8)
        url = codec.encode_data_url(img)
        fast_quoted = url.split(",", 1)[1]
        raw = base64.b64decode(unquote(fast_quoted))
        reference = quote(base64.b64encode(raw).decode("ascii"))
        assert fast_quoted == reference


def test_device_postprocess_matches_host_reference():
    """stitch_grid_device/deprocess_tiles_device must match the NumPy path
    (same truncating uint8 cast, same stitch-then-deprocess order)."""
    import numpy as np

    from deconv_api_tpu.serving.codec import (
        deprocess_image,
        deprocess_tiles_device,
        stitch_grid,
        stitch_grid_device,
    )

    rng = np.random.default_rng(3)
    images = rng.standard_normal((2, 4, 8, 8, 3)).astype(np.float32) * 5
    valid = np.array([[True, True, True, True], [True, True, False, False]])

    got = np.asarray(stitch_grid_device(images, valid))
    for b in range(2):
        tiles = [images[b, k] for k in range(4) if valid[b, k]]
        want = deprocess_image(stitch_grid(tiles))
        np.testing.assert_array_equal(want, got[b])

    got_tiles = np.asarray(deprocess_tiles_device(images))
    for b in range(2):
        for k in range(4):
            np.testing.assert_array_equal(
                deprocess_image(images[b, k]), got_tiles[b, k]
            )


class TestPilFallback:
    """The documented cv2-less fallback paths (serving/codec.py): forced by
    monkeypatching _HAVE_CV2, which must be safe now that every fallback
    imports PIL locally."""

    def _png_bgr(self):
        import cv2

        rng = np.random.default_rng(7)
        img = (rng.random((20, 24, 3)) * 255).astype(np.uint8)
        ok, buf = cv2.imencode(".png", img)
        assert ok
        uri = "data:image/png;base64," + base64.b64encode(buf.tobytes()).decode()
        return img, uri

    def test_decode_matches_cv2_exactly(self, monkeypatch):
        img, uri = self._png_bgr()
        got_cv2 = codec.decode_data_url(uri)
        monkeypatch.setattr(codec, "_HAVE_CV2", False)
        got_pil = codec.decode_data_url(uri)
        np.testing.assert_array_equal(got_cv2, got_pil)  # PNG is lossless
        np.testing.assert_array_equal(got_cv2, img)

    def test_decode_garbage_raises_codec_error(self, monkeypatch):
        monkeypatch.setattr(codec, "_HAVE_CV2", False)
        with pytest.raises(codec.CodecError):
            codec.decode_data_url("data:image/png;base64,aGVsbG8=")

    def test_encode_roundtrips_decodably(self, monkeypatch):
        # smooth gradient, not noise: JPEG error on noise is huge by design
        yy, xx = np.mgrid[0:20, 0:24]
        img = np.stack(
            [(yy * 12) % 256, (xx * 10) % 256, ((yy + xx) * 6) % 256], axis=-1
        ).astype(np.uint8)
        monkeypatch.setattr(codec, "_HAVE_CV2", False)
        s = codec.encode_data_url(img)
        assert s.startswith("data:image/webp;base64,")
        from urllib.parse import unquote

        monkeypatch.setattr(codec, "_HAVE_CV2", True)
        # the payload is percent-quoted for wire parity (app/main.py:73-76);
        # consumers (the browser) percent-decode before base64-decoding
        back = codec.decode_data_url(unquote(s.split(",", 1)[1]))
        # JPEG is lossy; assert same shape and close content
        assert back.shape == img.shape
        assert np.abs(back.astype(int) - img.astype(int)).mean() < 16

    def test_resize_shape(self, monkeypatch):
        img, _ = self._png_bgr()
        monkeypatch.setattr(codec, "_HAVE_CV2", False)
        out = codec.resize224(img, (32, 32))
        assert out.shape == (32, 32, 3) and out.dtype == np.uint8
