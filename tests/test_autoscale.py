"""Closed-loop elasticity tests (round 22).

Covers serving/autoscale.py and its fleet.py embedding: the
federation-payload signal parse (against a REAL ``_metrics_fleet``
splice, not a hand-written fixture), the decision engine's hysteresis
and cooldowns under an injected clock (a flapping signal must never
flap the fleet), the QoS-budget scale-down gate, predictive pre-scale
from per-tenant arrival history, the fsync'd decision journal (torn
tail, replay, cooldown restoration across restarts), the jobs-aware
reap gate (a drain-announced backend holding running/parked jobs is
NEVER reaped — the round-22 fix, pinned), boot-to-first-warm-hit
measurement and its timeout, the ``autoscale.decision_error`` /
``autoscale.launch_fail`` chaos sites (fail-static decision loop;
launch retries with backoff that never double-count fleet size), the
exposition lint over every new ``autoscaler_*`` family, the
``--autoscale off`` escape hatch pinning the PR-16 surface, and a
zero-loss scale-down e2e over real subprocess backends.
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
import time

import pytest

from deconv_api_tpu.serving import autoscale, fleet
from deconv_api_tpu.serving.autoscale import (
    ArrivalHistory,
    AutoscaleController,
    BackendLauncher,
    Decision,
    DecisionEngine,
    DecisionJournal,
    FleetSignals,
    LaunchError,
    LaunchedBackend,
    parse_exposition,
)
from deconv_api_tpu.serving.faults import FaultRegistry
from deconv_api_tpu.serving.fleet import FleetRouter
from deconv_api_tpu.serving.http import Request
from deconv_api_tpu.serving.metrics import Metrics
from tests.test_metrics_exposition import lint_exposition

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _backend_exposition(
    jobs_active: float = 0.0, l2_hits: float = 0.0, device_ms=None
) -> str:
    """A canned backend /v1/metrics body built through the REAL
    registry, so it carries the TYPE headers the federation splice
    keys on."""
    m = Metrics(prefix="deconv", core=False)
    m.set_gauge("jobs_active", jobs_active)
    m.inc_counter("cache_l2_hits_total", int(l2_hits))
    for tenant, ms in (device_ms or {}).items():
        m.inc_labeled(
            "tenant_device_ms_total", ("tenant", "class"),
            (tenant, "interactive"), int(ms),
        )
    return m.prometheus()


def _script(monkeypatch, expositions: dict, jobs=None):
    """raw_request stand-in serving probe + scrape + jobs surfaces for
    a set of fake backends."""
    jobs = jobs or {}

    async def fake(host, port, method, target, headers, body, timeout_s):
        name = f"{host}:{port}"
        if target == "/readyz":
            return 200, {}, json.dumps({"ready": True}).encode()
        if target == "/v1/metrics":
            return 200, {}, expositions[name].encode()
        if target == "/v1/jobs":
            counts = jobs.get(name, {"running": 0, "parked": 0})
            return 200, {}, json.dumps({"counts": counts}).encode()
        return 200, {}, b"{}"

    monkeypatch.setattr(fleet, "raw_request", fake)


def _sig(queue=None, burn=0.0, scrape_ok=None, device_ms=None,
         warm=None) -> FleetSignals:
    s = FleetSignals()
    s.queue_depth = dict(queue or {})
    s.scrape_ok = scrape_ok if scrape_ok is not None else {
        b: True for b in s.queue_depth
    }
    if burn:
        s.burn[("api", "5m")] = burn
    s.device_ms = dict(device_ms or {})
    s.warm_hits = dict(warm or {})
    return s


class _RecLauncher(BackendLauncher):
    """Recording launcher: launches mint names, reaps are remembered."""

    def __init__(self, fail_first: int = 0):
        self.launches = 0
        self.fail_first = fail_first
        self.reaps: list[str] = []
        self.procs: dict[str, object] = {}

    async def launch(self) -> LaunchedBackend:
        if self.launches < self.fail_first:
            self.launches += 1
            raise LaunchError("boom")
        self.launches += 1
        return LaunchedBackend(f"b{self.launches}:9{self.launches:03d}")

    async def reap(self, name: str, handle=None) -> None:
        self.reaps.append(name)


# ------------------------------------------------------------- parsing


def test_parse_exposition_forgiving():
    text = "\n".join([
        "# HELP x_total help",
        "# TYPE x_total counter",
        "x_total 3",
        'y{backend="b0:8000",slo="api"} 1.5',
        "not a metric line @@",
        "z_bad_value nope",
        'esc{name="a\\"b"} 2',
        "",
    ])
    out = parse_exposition(text)
    assert ("x_total", {}, 3.0) in out
    assert ("y", {"backend": "b0:8000", "slo": "api"}, 1.5) in out
    assert ("esc", {"name": 'a"b'}, 2.0) in out
    assert all(fam != "z_bad_value" for fam, _l, _v in out)


def test_signals_from_real_federation_payload(monkeypatch):
    """FleetSignals digests the ACTUAL ``_metrics_fleet`` splice: the
    backend label added by the router, the fleet_scrape_ok gauges, and
    the per-backend queue/warm-hit/device-ms families."""
    clock = _FakeClock()
    router = FleetRouter(["b0:8000", "b1:8001"], clock=clock)
    _script(monkeypatch, {
        "b0:8000": _backend_exposition(
            jobs_active=5, l2_hits=7, device_ms={"acme": 900}
        ),
        "b1:8001": _backend_exposition(jobs_active=1),
    })

    async def go():
        await router.probe_once()
        resp = await router._metrics_fleet(None)
        s = FleetSignals.from_exposition(resp.body.decode())
        assert s.queue_depth == {"b0:8000": 5.0, "b1:8001": 1.0}
        assert s.scrape_ok == {"b0:8000": True, "b1:8001": True}
        assert s.backends_scraped == 2
        assert s.warm_hits["b0:8000"] == 7.0
        assert s.device_ms["acme"] == 900.0
        assert s.queue_mean() == 3.0

    asyncio.run(go())


def test_signals_burn_takes_worst_worker_and_skips_failed_scrapes():
    text = "\n".join([
        'router_slo_burn_rate{slo="api",window="5m"} 0.4',
        'router_slo_burn_rate{slo="api",window="5m"} 1.2',
        'router_slo_burn_rate{slo="api",window="1h"} 0.1',
        'deconv_jobs_active{backend="b0:8000"} 8',
        'deconv_jobs_active{backend="b1:8001"} 100',
        'fleet_scrape_ok{backend="b0:8000"} 1',
        'fleet_scrape_ok{backend="b1:8001"} 0',
    ])
    s = FleetSignals.from_exposition(text)
    # N SO_REUSEPORT workers export one burn gauge each: worst wins
    assert s.burn_max("5m") == 1.2
    assert s.burn_max("1h") == 0.1
    # b1's splice came from a stale cache (scrape_ok 0): its queue
    # number must not drag the mean
    assert s.queue_mean() == 8.0


# ------------------------------------------------------------ arrivals


def test_arrival_history_bounds_and_rate():
    clock = _FakeClock()
    h = ArrivalHistory(
        bucket_s=1.0, max_buckets=4, max_tenants=2, clock=clock
    )
    for _ in range(10):
        h.record("a")
    h.record("b")
    h.record("overflow-1")  # third tenant folds to "other"
    clock.t += 1.0
    assert h.rate(1) == 12.0
    bucket = h._buckets[int(1000.0)]
    assert set(bucket) == {"a", "b", "other"}
    for i in range(6):  # only 4 buckets survive
        clock.t += 1.0
        h.record("a")
    assert len(h._buckets) <= 4


def test_arrival_forecast_sees_a_ramp():
    clock = _FakeClock()
    h = ArrivalHistory(bucket_s=1.0, clock=clock)
    for n in (2, 4, 8, 12, 16, 20):  # steady climb
        for _ in range(n):
            h.record("t")
        clock.t += 1.0
    cur, projected = h.forecast(horizon_s=10.0)
    assert cur > 0
    assert projected > 2 * cur  # slope extrapolated well past current


# ------------------------------------------------------------- journal


def test_journal_append_replay_and_torn_tail(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = DecisionJournal(path)
    j.append({"action": "up", "clock": 5.0})
    j.append({"action": "down", "clock": 9.0})
    j.close()
    with open(path, "a") as f:
        f.write('{"action": "up", "cl')  # crash mid-append
    recs = DecisionJournal.replay(path)
    assert [r["action"] for r in recs] == ["up", "down"]
    assert DecisionJournal.replay(str(tmp_path / "missing.jsonl")) == []


def test_engine_restore_clamps_foreign_clock():
    eng = DecisionEngine(clock=_FakeClock())
    eng.restore(
        [{"action": "up", "clock": 500.0},
         {"action": "down", "clock": 99999.0},  # previous monotonic epoch
         {"action": "up"}],  # no clock: ignored
        now=1000.0,
    )
    assert eng.last_up_ts == 500.0
    # a future timestamp clamps to now: full cooldown after restart,
    # never a skipped one
    assert eng.last_down_ts == 1000.0


# -------------------------------------------------------------- engine


def test_engine_flapping_signal_never_flaps():
    clock = _FakeClock()
    eng = DecisionEngine(
        up_queue=4.0, down_queue=0.5, up_consecutive=2,
        down_consecutive=3, clock=clock,
    )
    hot = _sig(queue={"b0": 10.0})
    cold = _sig(queue={"b0": 0.0})
    for i in range(12):  # strict alternation: streaks never build
        d = eng.evaluate(hot if i % 2 == 0 else cold, 2)
        assert d.action == "hold"
        clock.t += 5.0


def test_engine_up_after_sustained_hot_then_cooldown():
    clock = _FakeClock()
    eng = DecisionEngine(
        up_queue=4.0, up_consecutive=2, cooldown_up_s=30.0,
        max_backends=4, clock=clock,
    )
    hot = _sig(queue={"b0": 10.0})
    assert eng.evaluate(hot, 1).action == "hold"
    clock.t += 5.0
    d = eng.evaluate(hot, 1)
    assert (d.action, d.reason) == ("up", "queue")
    # still hot, but inside the up cooldown: hysteresis holds
    for _ in range(2):
        clock.t += 5.0
        d = eng.evaluate(hot, 2)
    assert (d.action, d.reason) == ("hold", "cooldown-up")
    # cooldown expired and the signal is STILL hot: the streak kept
    # building through the held polls, so the next evaluation fires
    clock.t += 35.0
    assert eng.evaluate(hot, 2).action == "up"


def test_engine_burn_signal_scales_up():
    clock = _FakeClock()
    eng = DecisionEngine(up_burn=0.9, up_consecutive=1, clock=clock)
    d = eng.evaluate(_sig(queue={"b0": 0.0}, burn=1.5), 1)
    assert (d.action, d.reason) == ("up", "burn")


def test_engine_up_respects_max_and_counts_pending():
    clock = _FakeClock()
    eng = DecisionEngine(
        up_queue=4.0, up_consecutive=1, max_backends=3, clock=clock
    )
    hot = _sig(queue={"b0": 10.0})
    # 2 live + 1 pending launch == max: a hot signal must NOT stack
    # another launch on top (the no-double-count contract)
    d = eng.evaluate(hot, 2, pending=1)
    assert (d.action, d.reason) == ("hold", "at-max")


def test_engine_down_gates_and_qos_budget():
    clock = _FakeClock()
    eng = DecisionEngine(
        down_queue=0.5, down_consecutive=3, cooldown_down_s=60.0,
        cooldown_up_s=1.0, min_backends=1, max_backends=4,
        qos_device_ms_budget=800.0, clock=clock,
    )
    # at-min: a 1-backend fleet never scales to zero
    for _ in range(3):
        d = eng.evaluate(_sig(queue={"b0": 0.0}), 1)
        clock.t += 5.0
    assert (d.action, d.reason) == ("hold", "at-min")

    # up-recent: capacity added moments ago is not yet proven surplus
    eng2 = DecisionEngine(
        down_queue=0.5, down_consecutive=2, cooldown_down_s=60.0,
        clock=clock,
    )
    eng2.last_up_ts = clock.t - 10.0
    for _ in range(2):
        d = eng2.evaluate(_sig(queue={"b0": 0.0}), 3)
        clock.t += 5.0
    assert (d.action, d.reason) == ("hold", "up-recent")

    # qos budget: measured demand must fit on N-1 backends
    eng3 = DecisionEngine(
        down_queue=0.5, down_consecutive=2, cooldown_down_s=1.0,
        qos_device_ms_budget=800.0, clock=clock,
    )
    cold0 = _sig(queue={"b0": 0.0}, device_ms={"acme": 0.0})
    eng3.evaluate(cold0, 3)
    clock.t += 5.0
    # 10000 device-ms over 5s = 2000 ms/s; on 2 backends that is
    # 1000 ms/s each — over the 800 budget, the down is refused
    d = eng3.evaluate(
        _sig(queue={"b0": 0.0}, device_ms={"acme": 10000.0}), 3
    )
    assert (d.action, d.reason) == ("hold", "qos-budget")
    clock.t += 5.0
    # demand stops (delta 0): the same fleet may now shrink
    d = eng3.evaluate(
        _sig(queue={"b0": 0.0}, device_ms={"acme": 10000.0}), 3
    )
    assert (d.action, d.reason) == ("down", "idle")


def test_engine_predictive_prescale():
    clock = _FakeClock()
    h = ArrivalHistory(bucket_s=1.0, clock=clock)
    for n in (4, 8, 16, 24, 32, 40):
        for _ in range(n):
            h.record("t")
        clock.t += 1.0
    eng = DecisionEngine(
        up_queue=100.0, cooldown_up_s=30.0, predict_horizon_s=10.0,
        predict_ramp=2.0, predict_min_rate=1.0, clock=clock,
    )
    quiet = _sig(queue={"b0": 0.6})  # not hot, not cold
    d = eng.evaluate(quiet, 1, arrivals=h)
    assert (d.action, d.reason) == ("up", "predictive")
    assert d.detail["projected"] >= 2 * d.detail["rate"]
    # the predictive up armed the SAME cooldown a reactive up would:
    # the ramp continuing must not launch a second backend per poll
    clock.t += 1.0
    assert eng.evaluate(quiet, 2, arrivals=h).action == "hold"


# ---------------------------------------------------------- controller


def _advisory_router(monkeypatch, clock, **opts):
    router = FleetRouter(
        ["b0:8000", "b1:8001"], clock=clock, autoscale="advisory",
        autoscale_opts=opts, slos="api=250:99",
    )
    _script(monkeypatch, {
        "b0:8000": _backend_exposition(jobs_active=0),
        "b1:8001": _backend_exposition(jobs_active=0),
    })
    return router


def test_embedded_tick_surfaces(monkeypatch, tmp_path):
    clock = _FakeClock()
    jpath = str(tmp_path / "j.jsonl")
    router = _advisory_router(
        monkeypatch, clock, journal_path=jpath,
        engine_opts={"up_queue": 3.0, "up_consecutive": 1},
    )
    ctl = router.autoscaler

    async def go():
        await router.probe_once()
        await ctl.tick()
        rb = ctl.ready_block()
        assert rb["mode"] == "advisory" and rb["ticks"] == 1
        assert rb["last_decision"]["action"] == "hold"
        assert ctl.metrics.snapshot()["gauges"]["fleet_size"] == 2
        cfg = json.loads((await router._config(None)).body)
        assert cfg["autoscale"]["mode"] == "advisory"
        assert cfg["autoscale"]["journal"] == jpath
        ready = json.loads((await router._readyz(None)).body)
        assert ready["autoscale"]["ticks"] == 1
        # the autoscaler families ride the router's /v1/metrics route
        text = (await router._metrics_route(None)).body.decode()
        assert "autoscaler_decisions_total" in text
        # advisory + hot signal: the decision is journaled and counted
        # but NOTHING is acted on
        _script(monkeypatch, {
            "b0:8000": _backend_exposition(jobs_active=50),
            "b1:8001": _backend_exposition(jobs_active=50),
        })
        await ctl.tick()
        assert ctl._last_decision["action"] == "up"
        assert ctl.metrics.labeled("decisions_total")[("up", "queue")] == 1
        assert not ctl.pending and isinstance(
            ctl.launcher, autoscale.AdvisoryLauncher
        )
        recs = DecisionJournal.replay(jpath)
        assert any(r.get("action") == "up" for r in recs)

    asyncio.run(go())


def test_router_arrival_hook_uses_tenant_identity(monkeypatch):
    clock = _FakeClock()
    router = _advisory_router(monkeypatch, clock)
    ctl = router.autoscaler

    async def forward(host, port, method, target, headers, body, timeout_s):
        return 200, {}, b"{}"

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", forward)
        for headers in (
            {"x-api-key": "k1"},
            {"x-api-key": "k1", "x-tenant": "ignored"},  # api-key wins
            {"x-tenant": "t2"},
            {},
        ):
            await router._proxy(Request(
                method="POST", path="/v1/deconv", query={},
                headers={
                    "content-type": "application/x-www-form-urlencoded",
                    **headers,
                },
                body=b"layer=c3&file=a", id="rid-as",
            ))
        bucket = ctl.arrivals._buckets[int(clock.t / ctl.arrivals.bucket_s)]
        assert bucket == {"k1": 2, "t2": 1, "default": 1}

    asyncio.run(go())


def test_decision_error_fails_static(monkeypatch):
    """The ``autoscale.decision_error`` chaos site: a crashing decision
    loop degrades to a no-op tick — errors counted, fleet untouched,
    next tick clean."""
    clock = _FakeClock()
    router = FleetRouter(
        ["b0:8000"], clock=clock, autoscale="enforce",
        autoscale_opts={"launcher": _RecLauncher()},
        fault_injection=True,
    )
    _script(monkeypatch, {"b0:8000": _backend_exposition(jobs_active=99)})
    ctl = router.autoscaler
    assert ctl.faults is router.faults

    async def go():
        await router.probe_once()
        router.faults.arm("autoscale.decision_error", "n1")
        await ctl.tick()
        assert ctl.metrics.counter("errors_total") == 1
        assert ctl._last_decision is None  # never reached evaluation
        assert not ctl.pending and not ctl.launcher.launches
        # site self-disarmed: the next tick decides normally
        await ctl.tick()
        assert ctl.metrics.counter("errors_total") == 1
        assert ctl._last_decision is not None

    asyncio.run(go())


def test_launch_fail_retries_without_double_count(monkeypatch):
    clock = _FakeClock()
    launcher = _RecLauncher()
    router = FleetRouter(
        ["b0:8000"], clock=clock, autoscale="enforce",
        autoscale_opts={"launcher": launcher, "retry_backoff_s": 0.0},
        fault_injection=True,
    )
    ctl = router.autoscaler

    async def go():
        router.faults.arm("autoscale.launch_fail", "n1")
        await ctl._scale_up(Decision("up", "queue"))
        assert ctl.metrics.counter("launch_failures_total") == 1
        assert len(ctl.pending) == 1  # retry succeeded, ONE backend
        assert launcher.launches == 1
        # a second up while one launch is pending must not stack
        await ctl._scale_up(Decision("up", "queue"))
        assert len(ctl.pending) == 1 and launcher.launches == 1

    asyncio.run(go())


def test_launch_fail_exhaustion_counts_error(monkeypatch, tmp_path):
    clock = _FakeClock()
    launcher = _RecLauncher(fail_first=99)
    jpath = str(tmp_path / "j.jsonl")
    router = FleetRouter(
        ["b0:8000"], clock=clock, autoscale="enforce",
        autoscale_opts={
            "launcher": launcher, "retry_backoff_s": 0.0,
            "launch_retries": 2, "journal_path": jpath,
        },
    )
    ctl = router.autoscaler

    async def go():
        await ctl._scale_up(Decision("up", "queue"))
        assert ctl.metrics.counter("launch_failures_total") == 3
        assert ctl.metrics.counter("errors_total") == 1
        assert not ctl.pending  # failed capacity is NEVER counted
        fails = [
            r for r in DecisionJournal.replay(jpath)
            if r.get("kind") == "launch_failed"
        ]
        assert [f["attempt"] for f in fails] == [0, 1, 2]

    asyncio.run(go())


def test_boot_to_warm_measured_and_timeout(monkeypatch):
    clock = _FakeClock()
    router = FleetRouter(
        ["b0:8000", "b2:8002"], clock=clock, autoscale="enforce",
        autoscale_opts={"launcher": _RecLauncher(), "warm_timeout_s": 60.0},
    )
    _script(monkeypatch, {
        "b0:8000": _backend_exposition(),
        "b2:8002": _backend_exposition(),
    })
    ctl = router.autoscaler

    async def go():
        await router.probe_once()
        ctl.pending["b2:8002"] = LaunchedBackend(
            "b2:8002", t_launch=clock.t
        )
        # registered (in ring) but no warm hit yet: the clock keeps
        # running
        ctl._check_pending_warm(_sig(queue={}))
        assert "b2:8002" in ctl.pending
        clock.t += 2.5
        ctl._check_pending_warm(_sig(queue={}, warm={"b2:8002": 3.0}))
        assert "b2:8002" not in ctl.pending
        series = ctl.metrics.hist_series("boot_to_warm_seconds")
        (_, h), = series.items()
        assert h["count"] == 1 and abs(h["sum"] - 2.5) < 1e-6

        # never-warm: past the timeout the launch is written off loudly
        ctl.pending["b2:8002"] = LaunchedBackend(
            "b2:8002", t_launch=clock.t
        )
        clock.t += 61.0
        ctl._check_pending_warm(_sig(queue={}))
        assert not ctl.pending
        assert ctl.metrics.counter("errors_total") == 1

    asyncio.run(go())


# ----------------------------------------------------------- reap gate


def test_reap_gate_blocks_on_running_and_parked_jobs(monkeypatch, tmp_path):
    """The round-22 fix, pinned: a drain-announced backend whose jobs
    tier still shows running/parked jobs is NEVER reaped — the watcher
    gives up loudly (reap_blocked) and the process keeps running."""
    clock = _FakeClock()
    launcher = _RecLauncher()
    jpath = str(tmp_path / "j.jsonl")
    router = FleetRouter(
        ["b0:8000", "b1:8001"], clock=clock, autoscale="enforce",
        autoscale_opts={
            "launcher": launcher, "drain_grace_s": 0.2,
            "drain_settle_s": 0.0, "interval_s": 0.02,
            "journal_path": jpath,
        },
    )
    ctl = router.autoscaler
    launcher.procs["b1:8001"] = object()  # owned: preferred victim
    jobs_counts = {"running": 1, "parked": 1}

    async def fake(host, port, method, target, headers, body, timeout_s):
        name = f"{host}:{port}"
        if target == "/readyz":
            return 200, {}, json.dumps({"ready": True}).encode()
        if target == "/v1/jobs":
            clock.t += 0.06  # advance the gate's deadline clock
            return 200, {}, json.dumps(
                {"counts": dict(jobs_counts)}
            ).encode()
        return 200, {}, b"{}"

    monkeypatch.setattr(fleet, "raw_request", fake)

    async def go():
        await router.probe_once()
        ctl._last_signals = _sig(
            queue={"b0:8000": 0.0, "b1:8001": 0.0},
            scrape_ok={"b0:8000": True, "b1:8001": True},
        )
        await ctl._scale_down(Decision("down", "idle"))
        assert "b1:8001" in ctl.draining
        m = router.members["b1:8001"]
        assert m.announced_drain  # no new keyed traffic from here on
        await ctl.draining["b1:8001"]
        # the gate held: blocked, not reaped, process untouched
        assert ctl.metrics.counter("reap_blocked_total") == 1
        assert ctl.metrics.counter("reaped_total") == 0
        assert launcher.reaps == []
        kinds = [r["kind"] for r in DecisionJournal.replay(jpath)]
        assert kinds == ["drain_announced", "reap_blocked"]

        # jobs drained (terminal/re-claimed): the SAME backend now reaps
        jobs_counts.update(running=0, parked=0)
        await ctl._drain_and_reap("b1:8001")
        assert launcher.reaps == ["b1:8001"]
        assert ctl.metrics.counter("reaped_total") == 1

    asyncio.run(go())


def test_jobs_gate_never_reaps_on_a_guess(monkeypatch):
    clock = _FakeClock()
    router = FleetRouter(
        ["b0:8000"], clock=clock, autoscale="enforce",
        autoscale_opts={"launcher": _RecLauncher()},
    )
    ctl = router.autoscaler

    async def go():
        async def err(host, port, *a, **kw):
            raise fleet._BackendError("unreachable")

        monkeypatch.setattr(fleet, "raw_request", err)
        assert await ctl._jobs_clear("b0:8000") is False

        async def bad_status(host, port, *a, **kw):
            return 503, {}, b"{}"

        monkeypatch.setattr(fleet, "raw_request", bad_status)
        assert await ctl._jobs_clear("b0:8000") is False

        async def malformed(host, port, *a, **kw):
            return 200, {}, b"not json"

        monkeypatch.setattr(fleet, "raw_request", malformed)
        assert await ctl._jobs_clear("b0:8000") is False

        async def clear(host, port, *a, **kw):
            return 200, {}, json.dumps(
                {"counts": {"running": 0, "parked": 0, "queued": 4}}
            ).encode()

        monkeypatch.setattr(fleet, "raw_request", clear)
        assert await ctl._jobs_clear("b0:8000") is True

    asyncio.run(go())


# ------------------------------------------------------ restart replay


def test_journal_replay_restores_cooldowns_on_restart(tmp_path):
    jpath = str(tmp_path / "j.jsonl")
    j = DecisionJournal(jpath)
    j.append({
        "kind": "decision", "action": "up", "reason": "queue",
        "clock": 900.0,
    })
    j.close()
    clock = _FakeClock(1000.0)
    ctl = AutoscaleController(
        mode="advisory", router_addr="127.0.0.1:1",
        journal_path=jpath, clock=clock,
        engine_opts={"cooldown_up_s": 300.0},
    )
    # the restarted engine remembers the up at t=900: a down decision
    # at t=1000 is still inside the up-recent window
    assert ctl.engine.last_up_ts == 900.0


# ----------------------------------------------------- sidecar surface


def test_sidecar_polls_federation_over_http(monkeypatch):
    clock = _FakeClock()
    ctl = AutoscaleController(
        mode="advisory", router_addr="127.0.0.1:8100", clock=clock,
        engine_opts={"up_queue": 3.0, "up_consecutive": 1},
    )
    fed_text = "\n".join([
        'deconv_jobs_active{backend="b0:8000"} 9',
        'deconv_jobs_active{backend="b1:8001"} 9',
        'fleet_scrape_ok{backend="b0:8000"} 1',
        'fleet_scrape_ok{backend="b1:8001"} 1',
        "fleet_backends_scraped 2",
    ])
    polled = []

    async def fake(host, port, method, target, headers, body, timeout_s):
        polled.append((f"{host}:{port}", target))
        return 200, {}, fed_text.encode()

    monkeypatch.setattr(fleet, "raw_request", fake)

    async def go():
        await ctl.tick()
        assert polled == [("127.0.0.1:8100", "/v1/metrics/fleet")]
        # sidecar fleet size = scraped-OK backends
        assert ctl.metrics.snapshot()["gauges"]["fleet_size"] == 2
        assert ctl._last_decision["action"] == "up"
        assert ctl._last_decision["fleet_size"] == 2

    asyncio.run(go())


def test_cli_autoscaler_subcommand_exists():
    out = subprocess.run(
        [sys.executable, "-m", "deconv_api_tpu.cli", "autoscaler",
         "--help"],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0
    assert "advisory" in out.stdout and "--launch-cmd" in out.stdout


def test_fleet_router_rejects_autoscale_with_workers():
    out = subprocess.run(
        [sys.executable, "-m", "deconv_api_tpu.serving.fleet",
         "--backends", "b0:8000", "--workers", "2",
         "--autoscale", "enforce"],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 2
    assert "--autoscale requires --workers 1" in out.stderr


# ----------------------------------------------------- exposition lint


def test_autoscaler_metric_families_lint():
    clock = _FakeClock()
    ctl = AutoscaleController(
        mode="advisory", router_addr="127.0.0.1:1", clock=clock
    )
    ctl.metrics.inc_labeled(
        "decisions_total", ("action", "reason"), ("up", "queue")
    )
    ctl.metrics.observe_hist(
        "boot_to_warm_seconds", "backend", "b2:8002", 1.25
    )
    families, samples = lint_exposition(ctl.metrics.prometheus())
    assert families["autoscaler_decisions_total"] == "counter"
    assert families["autoscaler_boot_to_warm_seconds"] == "histogram"
    assert families["autoscaler_fleet_size"] == "gauge"
    assert families["autoscaler_pending_launches"] == "gauge"
    for fam in ("errors_total", "launch_failures_total",
                "reap_blocked_total", "reaped_total"):
        # pre-registered at zero: visible from the first scrape
        assert families[f"autoscaler_{fam}"] == "counter"
        assert samples[(f"autoscaler_{fam}", "")] == 0.0
    assert samples[(
        "autoscaler_decisions_total", 'action="up",reason="queue"'
    )] == 1.0


# -------------------------------------------------------- escape hatch


def test_autoscale_off_pins_pr16_surface(monkeypatch):
    clock = _FakeClock()
    router = FleetRouter(["b0:8000"], clock=clock)  # default: off
    assert router.autoscaler is None
    _script(monkeypatch, {"b0:8000": _backend_exposition()})

    async def go():
        await router.probe_once()
        # /v1/config carries NO autoscale block — byte-compatible with
        # the PR 16 surface
        cfg = json.loads((await router._config(None)).body)
        assert "autoscale" not in cfg
        ready = json.loads((await router._readyz(None)).body)
        assert "autoscale" not in ready
        text = (await router._metrics_route(None)).body.decode()
        assert "autoscaler_" not in text

    asyncio.run(go())
    with pytest.raises(ValueError, match="autoscale"):
        FleetRouter(["b0:8000"], autoscale="bogus")
    with pytest.raises(ValueError, match="advisory|enforce"):
        AutoscaleController(mode="off", router_addr="x:1")


# ------------------------------------------------- zero-loss e2e drill

_STUB_SRC = r"""
import asyncio, json, sys
from deconv_api_tpu.serving.http import HttpServer, Response
from deconv_api_tpu.serving.metrics import Metrics

port = int(sys.argv[1])


async def main():
    m = Metrics(prefix="deconv", core=False)
    m.set_gauge("jobs_active", 0)
    m.inc_counter("cache_l2_hits_total", 1)
    srv = HttpServer(max_connections=256)

    async def readyz(_req):
        return Response.json({"ready": True})

    async def metrics(_req):
        return Response.text(
            m.prometheus(), content_type="text/plain; version=0.0.4"
        )

    async def jobs(_req):
        return Response.json(
            {"counts": {"running": 0, "parked": 0, "queued": 0}}
        )

    async def work(_req):
        await asyncio.sleep(0.02)
        return Response.json({"port": port})

    srv.route("GET", "/readyz")(readyz)
    srv.route("GET", "/v1/metrics")(metrics)
    srv.route("GET", "/v1/jobs")(jobs)
    srv.route("POST", "/v1/deconv")(work)
    await srv.start("127.0.0.1", port)
    print("up", flush=True)
    await asyncio.sleep(600)


asyncio.run(main())
"""


def _spawn_stub(port: int) -> subprocess.Popen:
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"}
    p = subprocess.Popen(
        [sys.executable, "-c", _STUB_SRC, str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True,
    )
    assert p.stdout.readline().strip() == "up"
    return p


def test_scale_down_zero_loss_over_real_processes():
    """E2E over real subprocess backends and the REAL wire path: under
    continuous traffic, the controller drain-announces its victim,
    proves the jobs tier empty over HTTP, reaps the actual process —
    and not one request is lost."""
    p0 = autoscale._free_port()
    p1 = autoscale._free_port()
    b0, b1 = f"127.0.0.1:{p0}", f"127.0.0.1:{p1}"
    procs = [_spawn_stub(p0), _spawn_stub(p1)]
    launcher = _RecLauncher()

    class _ProcLauncher(_RecLauncher):
        async def reap(self, name, handle=None):
            self.reaps.append(name)
            proc = self.procs.get(name)
            proc.terminate()

    launcher = _ProcLauncher()
    launcher.procs[b1] = procs[1]
    router = FleetRouter(
        [b0, b1], probe_interval_s=0.2, probe_timeout_s=2.0,
        autoscale="enforce",
        autoscale_opts={
            "launcher": launcher, "drain_grace_s": 5.0,
            "drain_settle_s": 0.2, "interval_s": 0.5,
        },
    )
    ctl = router.autoscaler
    statuses: list[int] = []

    async def go():
        await router.probe_once()
        assert all(m.in_ring for m in router.members.values())
        await ctl.tick()  # real federation poll primes _last_signals
        stop = asyncio.Event()

        async def traffic():
            i = 0
            while not stop.is_set():
                resp = await router._proxy(Request(
                    method="POST", path="/v1/deconv", query={},
                    headers={
                        "content-type":
                        "application/x-www-form-urlencoded",
                    },
                    body=f"layer=c3&file=k{i % 16}".encode(),
                    id=f"rid-{i}",
                ))
                statuses.append(resp.status)
                i += 1
                await asyncio.sleep(0.01)

        t = asyncio.create_task(traffic())
        await asyncio.sleep(0.3)
        await ctl._scale_down(Decision("down", "idle"))
        assert list(ctl.draining) == [b1]  # owned proc preferred
        await ctl.draining[b1]
        # reaped for real: the OS process is gone
        assert launcher.reaps == [b1]
        assert procs[1].wait(timeout=10) is not None
        await asyncio.sleep(0.5)  # traffic continues on the survivor
        stop.set()
        await t
        await ctl.stop()

    try:
        asyncio.run(go())
        assert len(statuses) > 20
        assert all(s == 200 for s in statuses)  # ZERO loss
        assert router.members[b1].announced_drain
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
