"""CLI surface tests (SURVEY §5 config row): models / visualize / dream,
including pretrained-weight plumbing.  Shallow layers keep compiles cheap."""

import json

import numpy as np
import pytest

import jax

from deconv_api_tpu.cli import main


@pytest.fixture()
def png(tmp_path, rng):
    from PIL import Image

    p = tmp_path / "in.png"
    Image.fromarray(
        (rng.random((64, 64, 3)) * 255).astype(np.uint8), "RGB"
    ).save(p)
    return str(p)


def test_models_lists_registry(capsys):
    assert main(["models"]) == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert {l["model"] for l in lines} == {
        "vgg16", "vgg19", "resnet50", "inception_v3", "mobilenet_v1",
        "mobilenet_v2", "vgg_tiny",
    }
    assert all("layers" in l and "engine" in l for l in lines)


def test_visualize_writes_grid(tmp_path, png, capsys):
    out = str(tmp_path / "grid.png")
    rc = main(
        [
            "visualize", "--image", png, "--layer", "block1_conv1",
            "--output", out, "--top-k", "4",
        ]
    )
    assert rc == 0
    info = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert info["output"] == out and info["layer"] == "block1_conv1"
    from PIL import Image

    assert Image.open(out).size == (448, 448)  # 2x2 grid of 224px tiles


def test_dream_runs_one_octave(tmp_path, png, capsys):
    out = str(tmp_path / "dream.png")
    rc = main(
        [
            "dream", "--image", png, "--layers", "block1_conv1",
            "--output", out, "--steps", "1", "--octaves", "1",
        ]
    )
    assert rc == 0
    info = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert np.isfinite(info["loss"])
    from PIL import Image

    assert Image.open(out).size == (224, 224)


@pytest.mark.slow  # two full CLI visualize runs (~50s); the CLI visualize
# path stays in tier-1 via test_visualize_writes_grid
def test_visualize_honours_weights_flag(tmp_path, png, capsys):
    """--weights must actually change the served parameters."""
    from deconv_api_tpu.models.vgg16 import vgg16_init
    from deconv_api_tpu.models.weights import save_npz

    _, params = vgg16_init(jax.random.PRNGKey(9))
    # zero block1_conv1 -> its projection grid becomes flat gray
    params["block1_conv1"] = {
        "w": params["block1_conv1"]["w"] * 0,
        "b": params["block1_conv1"]["b"] * 0,
    }
    wpath = str(tmp_path / "w.npz")
    save_npz(params, wpath)
    out = str(tmp_path / "none.png")
    rc = main(
        [
            "visualize", "--image", png, "--layer", "block1_conv1",
            "--output", out, "--weights", wpath,
        ]
    )
    capsys.readouterr()
    # zero weights -> zero activations -> no positive filter sums -> rc 1
    assert rc == 1


def test_visualize_sweep_writes_one_grid_per_layer(tmp_path, monkeypatch, capsys):
    """--sweep projects every layer from --layer down, one PNG per layer
    (the reference's visualize_all_layers, app/deepdream.py:383-476)."""
    import json

    import jax
    import numpy as np
    from PIL import Image

    from deconv_api_tpu.cli import main as cli_main
    from deconv_api_tpu.models.spec import init_params
    from deconv_api_tpu.serving import models as m
    from deconv_api_tpu.serving.models import spec_bundle
    from tests.test_engine_parity import TINY

    params = init_params(TINY, jax.random.PRNGKey(3))
    monkeypatch.setitem(m.REGISTRY, "tiny_vgg", lambda: spec_bundle(TINY, params))

    src = tmp_path / "in.png"
    rng = np.random.default_rng(0)
    Image.fromarray(rng.integers(0, 255, (16, 16, 3), np.uint8), "RGB").save(src)
    out = tmp_path / "sweep.png"
    rc = cli_main(
        [
            "visualize", "--model", "tiny_vgg", "--image", str(src),
            "--layer", "b2c1", "--sweep", "--output", str(out),
        ]
    )
    assert rc == 0
    result = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert set(result["outputs"]) <= {"b2c1", "b1p", "b1c2", "b1c1"}
    assert result["outputs"], "no layers produced output"
    for path in result["outputs"].values():
        img = np.asarray(Image.open(path))
        assert img.shape == (32, 32, 3)  # 2x2 grid of 16x16 tiles


def test_visualize_sweep_on_autodiff_models(tmp_path, monkeypatch, capsys):
    """--sweep on a DAG/autodiff bundle writes one grid per swept layer —
    the r4 sequential-only restriction is lifted (engine/autodeconv.py
    sweep_layers: one shared forward, per-layer vjp seeds)."""
    import json

    import jax
    import numpy as np
    from PIL import Image

    from deconv_api_tpu.cli import main as cli_main
    from deconv_api_tpu.models.apply import spec_forward
    from deconv_api_tpu.models.spec import init_params
    from deconv_api_tpu.serving import models as m
    from tests.test_engine_parity import TINY

    params = init_params(TINY, jax.random.PRNGKey(3))
    bundle = m.ModelBundle(
        name="tiny_dag",
        params=params,
        image_size=16,
        preprocess=lambda x: x,
        layer_names=tuple(l.name for l in TINY.layers if l.kind != "input"),
        dream_layers=(),
        forward_fn=spec_forward(TINY),
    )
    monkeypatch.setitem(m.REGISTRY, "tiny_dag", lambda: bundle)

    src = tmp_path / "in.png"
    rng = np.random.default_rng(0)
    Image.fromarray(rng.integers(0, 255, (16, 16, 3), np.uint8), "RGB").save(src)
    out = tmp_path / "o.png"
    rc = cli_main(
        [
            "visualize", "--model", "tiny_dag", "--image", str(src),
            "--layer", "b2c1", "--sweep", "--output", str(out),
        ]
    )
    assert rc == 0
    result = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert set(result["outputs"]) <= {"b2c1", "b1p", "b1c2", "b1c1"}
    assert result["outputs"], "no layers produced output"
    for path in result["outputs"].values():
        img = np.asarray(Image.open(path))
        assert img.shape == (32, 32, 3)  # 2x2 grid of 16x16 tiles


def test_visualize_unknown_layer_clean_error(tmp_path, monkeypatch, capsys):
    """An unknown --layer exits 2 with a message naming the valid layers,
    not a traceback (parity with the route's UnknownLayer 422)."""
    import jax
    import numpy as np
    from PIL import Image

    from deconv_api_tpu.cli import main as cli_main
    from deconv_api_tpu.models.spec import init_params
    from deconv_api_tpu.serving import models as m
    from deconv_api_tpu.serving.models import spec_bundle
    from tests.test_engine_parity import TINY

    params = init_params(TINY, jax.random.PRNGKey(3))
    monkeypatch.setitem(m.REGISTRY, "tiny_vgg", lambda: spec_bundle(TINY, params))

    src = tmp_path / "in.png"
    Image.fromarray(np.zeros((16, 16, 3), np.uint8), "RGB").save(src)
    rc = cli_main(
        [
            "visualize", "--model", "tiny_vgg", "--image", str(src),
            "--layer", "nope", "--output", str(tmp_path / "o.png"),
        ]
    )
    assert rc == 2
    err = capsys.readouterr().err
    assert "no projectable layer" in err and "b2c1" in err


def test_doctor_cpu(capsys):
    """`doctor --platform cpu` runs its probes green without touching the
    default backend (the config-update form works even when the default
    plugin is wedged — utils/doctor.py)."""
    import json as _json

    from deconv_api_tpu.cli import main

    rc = main(["doctor", "--checks", "backend,compile_cache", "--platform", "cpu"])
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 0
    parsed = [_json.loads(l) for l in out]
    byname = {p["check"]: p for p in parsed}
    assert byname["backend"]["ok"] and byname["backend"]["platform"] == "cpu"
    assert byname["overall"]["ok"] is True


def test_doctor_unknown_check():
    from deconv_api_tpu.cli import main

    assert main(["doctor", "--checks", "nope"]) == 2
