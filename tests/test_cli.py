"""CLI surface tests (SURVEY §5 config row): models / visualize / dream,
including pretrained-weight plumbing.  Shallow layers keep compiles cheap."""

import json

import numpy as np
import pytest

import jax

from deconv_api_tpu.cli import main


@pytest.fixture()
def png(tmp_path, rng):
    from PIL import Image

    p = tmp_path / "in.png"
    Image.fromarray(
        (rng.random((64, 64, 3)) * 255).astype(np.uint8), "RGB"
    ).save(p)
    return str(p)


def test_models_lists_registry(capsys):
    assert main(["models"]) == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert {l["model"] for l in lines} == {"vgg16", "resnet50", "inception_v3"}
    assert all("layers" in l and "engine" in l for l in lines)


def test_visualize_writes_grid(tmp_path, png, capsys):
    out = str(tmp_path / "grid.png")
    rc = main(
        [
            "visualize", "--image", png, "--layer", "block1_conv1",
            "--output", out, "--top-k", "4",
        ]
    )
    assert rc == 0
    info = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert info["output"] == out and info["layer"] == "block1_conv1"
    from PIL import Image

    assert Image.open(out).size == (448, 448)  # 2x2 grid of 224px tiles


def test_dream_runs_one_octave(tmp_path, png, capsys):
    out = str(tmp_path / "dream.png")
    rc = main(
        [
            "dream", "--image", png, "--layers", "block1_conv1",
            "--output", out, "--steps", "1", "--octaves", "1",
        ]
    )
    assert rc == 0
    info = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert np.isfinite(info["loss"])
    from PIL import Image

    assert Image.open(out).size == (224, 224)


def test_visualize_honours_weights_flag(tmp_path, png, capsys):
    """--weights must actually change the served parameters."""
    from deconv_api_tpu.models.vgg16 import vgg16_init
    from deconv_api_tpu.models.weights import save_npz

    _, params = vgg16_init(jax.random.PRNGKey(9))
    # zero block1_conv1 -> its projection grid becomes flat gray
    params["block1_conv1"] = {
        "w": params["block1_conv1"]["w"] * 0,
        "b": params["block1_conv1"]["b"] * 0,
    }
    wpath = str(tmp_path / "w.npz")
    save_npz(params, wpath)
    out = str(tmp_path / "none.png")
    rc = main(
        [
            "visualize", "--image", png, "--layer", "block1_conv1",
            "--output", out, "--weights", wpath,
        ]
    )
    capsys.readouterr()
    # zero weights -> zero activations -> no positive filter sums -> rc 1
    assert rc == 1
