"""Multi-tenant QoS tests (round 13, serving/qos.py): DRR fairness,
token-bucket determinism, priority-vs-deadline composition, fail-open
admission, quota errors, and byte parity of the qos-off path."""

import asyncio
import json
import time

import httpx
import pytest

from deconv_api_tpu import errors
from deconv_api_tpu.config import ServerConfig
from deconv_api_tpu.models.spec import init_params
from deconv_api_tpu.serving.app import DeconvService
from deconv_api_tpu.serving.batcher import BatchingDispatcher
from deconv_api_tpu.serving.metrics import Metrics
from deconv_api_tpu.serving.qos import (
    DEFAULT_TENANT,
    DrrQueue,
    QosPolicy,
    TokenBucket,
    parse_tenant_specs,
    parse_weights,
)
from tests.test_engine_parity import TINY
from tests.test_metrics_exposition import lint_exposition
from tests.test_serving import ServiceFixture, _data_url

import jax


# ---------------------------------------------------------------- parsing


def test_parse_weights_defaults_and_overrides():
    assert parse_weights("") == {"interactive": 8, "standard": 4, "bulk": 1}
    assert parse_weights("bulk=2,interactive=16")["bulk"] == 2
    assert parse_weights("bulk=2,interactive=16")["interactive"] == 16
    for bad in ("premium=3", "interactive=0", "interactive", "bulk=x"):
        with pytest.raises(ValueError):
            parse_weights(bad)


def test_parse_tenant_specs_inline_file_and_errors(tmp_path):
    specs = parse_tenant_specs(
        '{"a": {"class": "bulk", "rate_ms": 50, "max_jobs": 2},'
        ' "*": {"class": "interactive", "max_inflight": 8}}'
    )
    assert specs["a"].tclass == "bulk"
    assert specs["a"].rate_ms == 50.0
    assert specs["a"].burst_ms == 50.0  # defaulted to one second of rate
    assert specs["a"].max_jobs == 2
    assert specs["*"].max_inflight == 8
    # file form
    path = tmp_path / "tenants.json"
    path.write_text('{"b": {"class": "standard"}}')
    assert parse_tenant_specs(str(path))["b"].tclass == "standard"
    # config errors fail loudly (a typo'd quota must not admit everything)
    for bad in (
        '{"a": {"class": "premium"}}',
        '{"a": {"rate_ms": -1}}',
        '{"a": {"unknown_key": 1}}',
        '{"bad name!": {}}',
        '{"a": 3}',
        "[1,2]",
        "not json and not a file",
        # fractional / bool / string quotas must error at boot, not
        # silently coerce (int(2.9) would truncate to 2 jobs)
        '{"a": {"max_jobs": 2.9}}',
        '{"a": {"max_inflight": true}}',
        '{"a": {"rate_ms": "50"}}',
        '{"a": {"burst_ms": false}}',
    ):
        with pytest.raises(ValueError):
            parse_tenant_specs(bad)
    assert parse_tenant_specs("") == {}
    # integral floats for the float knobs are fine (JSON "50" vs "50.0")
    assert parse_tenant_specs('{"a": {"rate_ms": 50.5}}')["a"].rate_ms == 50.5


def test_boot_rejects_bad_tenant_spec():
    params = init_params(TINY, jax.random.PRNGKey(0))
    cfg = ServerConfig(
        image_size=16, qos=True, tenants='{"a": {"class": "premium"}}',
        compilation_cache_dir="",
    )
    with pytest.raises(ValueError):
        DeconvService(cfg, spec=TINY, params=params)


# ----------------------------------------------------------- token bucket


def test_token_bucket_refill_deterministic_with_injected_clock():
    t = [0.0]
    b = TokenBucket(rate_ms=10.0, burst_ms=20.0, clock=lambda: t[0])
    ok, _ = b.take(20.0)
    assert ok  # full burst available at t=0
    ok, wait = b.take(5.0)
    assert not ok and wait == pytest.approx(0.5)  # 5ms deficit / 10ms-per-s
    t[0] = 0.5
    ok, _ = b.take(5.0)
    assert ok  # exactly refilled
    t[0] = 100.0
    b.take(0.0)
    assert b.tokens == pytest.approx(20.0)  # capped at burst
    # credit (the cache-hit refund) also caps at burst
    b.credit(50.0)
    assert b.tokens == pytest.approx(20.0)


def test_admission_debits_ewma_cost_not_request_count():
    t = [0.0]
    pol = QosPolicy(
        '{"a": {"class": "standard", "rate_ms": 10, "burst_ms": 100}}',
        clock=lambda: t[0],
    )
    g = pol.admit({"x-tenant": "a"})
    assert g.charged_ms == pytest.approx(1.0)  # seed cost, nothing measured
    pol.release(g)
    # the batcher reports a measured 20ms/request cost; the EWMA moves
    # and the NEXT admission debits the measured cost, not a count
    for _ in range(50):
        pol.charge("a", 0.020)
    g2 = pol.admit({"x-tenant": "a"})
    assert g2.charged_ms == pytest.approx(20.0, rel=0.05)


def test_debit_capped_at_burst_never_starves_forever():
    """A tenant whose measured EWMA cost outgrows its burst capacity
    (one contended batch can inflate it) must degrade to ~rate/burst
    admissions per second — NOT starve forever because take(est) can no
    longer succeed at any token level."""
    t = [0.0]
    pol = QosPolicy(
        '{"a": {"class": "bulk", "rate_ms": 10, "burst_ms": 20}}',
        clock=lambda: t[0],
    )
    # inflate the measured cost far past the 20ms burst
    for _ in range(50):
        pol.charge("a", 0.500)  # 500 ms/request
    t[0] = 10.0  # bucket fully refilled to burst
    g = pol.admit({"x-tenant": "a"})  # debit capped at burst: admits
    assert g.charged_ms == pytest.approx(20.0)
    pol.release(g)
    # and the NEXT admission waits ~burst/rate, not forever
    with pytest.raises(errors.TenantOverQuota) as ei:
        pol.admit({"x-tenant": "a"})
    assert ei.value.retry_after_s <= 20.0 / 10.0 + 0.01


def test_fairness_gauge_incremental_matches_full_scan():
    """charge() maintains max/count/sum accumulators instead of walking
    the tenant table per item; the gauge must equal the direct max/mean
    formula at every step (device_ms only grows and tenants are never
    evicted, so the incremental form is exact, not approximate)."""
    recorded = {}

    class _Gauges:
        def inc_labeled(self, *a, **k):
            pass

        def inc_counter(self, *a, **k):
            pass

        def set_gauge(self, name, v):
            recorded[name] = v

    pol = QosPolicy("", metrics=_Gauges())
    charges = [
        ("a", 0.010), ("b", 0.002), ("a", 0.004),
        ("c", 0.001), ("b", 0.003), ("idle", 0.0),
    ]
    def check():
        snap = pol.snapshot()
        used = [
            t["device_ms"]
            for t in snap["tenants"].values()
            if t["device_ms"] > 0
        ]
        expect = round(max(used) * len(used) / sum(used), 4) if used else 1.0
        assert recorded["tenant_fairness"] == pytest.approx(expect, abs=1e-3)
        assert snap["fairness"] == recorded["tenant_fairness"]

    for tenant, cost_s in charges:
        pol.charge(tenant, cost_s)
        check()
    # drop_tenant (the drill's calibration surgery) is the one allowed
    # eviction — it must rebuild the accumulators so later charges keep
    # matching the scan
    pol.drop_tenant("a")
    pol.charge("b", 0.002)
    check()
    pol.drop_tenant("no-such")  # no-op


def test_inflight_budget_and_release():
    pol = QosPolicy('{"a": {"max_inflight": 1}}')
    g = pol.admit({"x-api-key": "a"})
    with pytest.raises(errors.TenantOverQuota):
        pol.admit({"x-api-key": "a"})
    pol.release(g)
    pol.release(g)  # idempotent
    pol.admit({"x-api-key": "a"})  # slot free again


def test_identity_rules():
    pol = QosPolicy('{"k1": {"class": "bulk"}}')
    assert pol.tenant_of({}) == DEFAULT_TENANT
    assert pol.tenant_of({"x-tenant": "abc"}) == "abc"
    # a CONFIGURED x-api-key wins over x-tenant and passes verbatim
    # (configured names are operator-chosen labels, not secrets);
    # malformed identity maps to default, never a 400
    assert pol.tenant_of({"x-api-key": "k1", "x-tenant": "abc"}) == "k1"
    assert pol.tenant_of({"x-tenant": "bad id!"}) == DEFAULT_TENANT
    assert pol.tenant_of({"x-tenant": "x" * 65}) == DEFAULT_TENANT


def test_unconfigured_api_key_pseudonymized_never_leaks():
    """An x-api-key that is not a configured tenant name is a credential
    by convention: it must never reach metric labels / logs / /v1/config
    verbatim.  It maps to a STABLE key-<digest> pseudonym (still one
    tenant per key) and the raw value appears nowhere in the policy."""
    pol = QosPolicy()
    name = pol.tenant_of({"x-api-key": "sk-live-SECRET123"})
    assert name.startswith("key-") and "SECRET123" not in name
    # stable: the same key meters as the same tenant
    assert pol.tenant_of({"x-api-key": "sk-live-SECRET123"}) == name
    g = pol.admit({"x-api-key": "sk-live-SECRET123"})
    assert g.tenant == name
    snap = pol.snapshot()
    assert name in snap["tenants"]
    assert "sk-live-SECRET123" not in json.dumps(snap)
    pol.release(g)
    # x-tenant is a self-declared label, not a credential: verbatim
    assert pol.tenant_of({"x-tenant": "sk-ish-value"}) == "sk-ish-value"


def test_tenant_cardinality_capped_at_max_tenants():
    """Attacker-chosen headers must not grow per-tenant state or metric
    label series without bound: past MAX_TENANTS live tenants an
    UNCONFIGURED name admits/charges/sheds as the default tenant, while
    configured tenants keep their own state."""
    m = Metrics()
    pol = QosPolicy('{"vip": {"class": "interactive"}}', metrics=m)
    import deconv_api_tpu.serving.qos as qos_mod

    orig = qos_mod.MAX_TENANTS
    qos_mod.MAX_TENANTS = 4
    try:
        for i in range(10):
            pol.release(pol.admit({"x-tenant": f"t{i}"}))
        assert pol.counts()["tenants_active"] <= 4 + 1  # + default
        # overflow traffic metered as default, not dropped
        g = pol.admit({"x-tenant": "one-more"})
        assert g.tenant == DEFAULT_TENANT
        pol.charge("another-stranger", 0.005)
        pol.record_shed("yet-another")
        assert pol.counts()["tenants_active"] <= 4 + 1
        labels = {k if isinstance(k, str) else k[0]
                  for k in m.labeled("tenant_shed_total")}
        assert "yet-another" not in labels
        # a CONFIGURED tenant still gets its own state past the cap
        g2 = pol.admit({"x-tenant": "vip"})
        assert g2.tenant == "vip" and g2.tclass == "interactive"
        pol.release(g)
        pol.release(g2)
    finally:
        qos_mod.MAX_TENANTS = orig


def test_empty_tenant_name_is_default_not_phantom():
    """Jobs journaled before qos was enabled carry tenant="": class_of
    and charge must treat that as the default tenant, never mint a
    tenant literally named "" (whose class would drive queueing while
    its charges went to default)."""
    pol = QosPolicy('{"*": {"class": "bulk"}}')
    assert pol.class_of("") == pol.class_of(DEFAULT_TENANT)
    pol.charge("", 0.002)
    snap = pol.snapshot()
    assert "" not in snap["tenants"]
    assert DEFAULT_TENANT in snap["tenants"]


# -------------------------------------------------------------- DRR queue


class _Item:
    def __init__(self, tenant, tclass, deadline=None):
        self.tenant = tenant
        self.tclass = tclass
        self.deadline = deadline


def test_drr_weighted_share_convergence_under_synthetic_load():
    q = DrrQueue({"interactive": 8, "standard": 4, "bulk": 1})
    for _ in range(400):
        q.put_nowait(_Item("vic", "interactive"))
        q.put_nowait(_Item("std", "standard"))
        q.put_nowait(_Item("abu", "bulk"))
    counts = {"vic": 0, "std": 0, "abu": 0}
    for _ in range(390):  # all three stay backlogged throughout
        counts[q.get_nowait().tenant] += 1
    total = sum(counts.values())
    # shares converge to the weight ratio 8:4:1 within 10%
    assert counts["vic"] / total == pytest.approx(8 / 13, rel=0.1)
    assert counts["std"] / total == pytest.approx(4 / 13, rel=0.1)
    assert counts["abu"] / total == pytest.approx(1 / 13, rel=0.1)


def test_drr_two_tenants_same_class_split_evenly():
    q = DrrQueue()
    for _ in range(100):
        q.put_nowait(_Item("a", "standard"))
        q.put_nowait(_Item("b", "standard"))
    counts = {"a": 0, "b": 0}
    for _ in range(100):
        counts[q.get_nowait().tenant] += 1
    assert counts["a"] == pytest.approx(counts["b"], abs=8)


def test_drr_idle_tenant_banks_no_credit():
    # a queue that empties forfeits its deficit AND its bookkeeping:
    # when it next arrives it competes fresh (no banked quantum), and an
    # idle (tenant, class) key pins no state in the queue at all
    q = DrrQueue({"interactive": 8, "standard": 4, "bulk": 1})
    q.put_nowait(_Item("a", "bulk"))
    assert q.get_nowait().tenant == "a"
    assert ("a", "bulk") not in q._deficit
    assert ("a", "bulk") not in q._queues


def test_drr_fifo_within_one_tenant_and_empty_raises():
    q = DrrQueue()
    with pytest.raises(asyncio.QueueEmpty):
        q.get_nowait()
    q.put_nowait(_Item("a", "standard", deadline=1.0))
    first = q.get_nowait()
    assert first.deadline == 1.0
    assert q.empty() and q.qsize() == 0


def test_drr_near_deadline_interactive_jumps_bulk_does_not():
    now = [100.0]
    q = DrrQueue(clock=lambda: now[0])
    # rotation order would serve the bulk backlog first item by weight;
    # a near-deadline INTERACTIVE head jumps it
    for _ in range(5):
        q.put_nowait(_Item("abu", "bulk"))
    q.put_nowait(_Item("vic", "interactive", deadline=100.2))
    assert q.get_nowait().tenant == "vic"
    # a near-deadline BULK item gets no jump privilege: rotation order
    q2 = DrrQueue(clock=lambda: now[0])
    for _ in range(3):
        q2.put_nowait(_Item("vic", "interactive"))
    q2.put_nowait(_Item("abu", "bulk", deadline=100.2))
    assert q2.get_nowait().tenant == "vic"
    # a far-deadline interactive item does not jump either (plain DRR)
    q3 = DrrQueue(clock=lambda: now[0])
    q3.put_nowait(_Item("abu", "bulk"))
    q3.put_nowait(_Item("vic", "interactive", deadline=500.0))
    got = {q3.get_nowait().tenant, q3.get_nowait().tenant}
    assert got == {"abu", "vic"}


def test_drr_evict_bulk_newest_of_deepest():
    q = DrrQueue()
    q.put_nowait(_Item("a", "interactive"))
    assert q.evict_bulk() is None  # no bulk queued -> caller sheds arrival
    first, second = _Item("b", "bulk"), _Item("b", "bulk")
    q.put_nowait(first)
    q.put_nowait(second)
    assert q.evict_bulk() is second  # newest goes (waited least)
    assert q.qsize() == 2
    assert q.evict_bulk() is first
    assert q.evict_bulk() is None


# ------------------------------------------- batcher + deadline composition


def test_expired_bulk_item_never_dispatches_and_jump_composition():
    """Priority-vs-deadline interaction through the real dispatcher on a
    DRR queue: a bulk item whose deadline lapses while QUEUED is reaped
    at the queue-pop boundary (immediate 504, the device never sees it)
    while the interactive item in the same window still dispatches."""

    async def go():
        ran: list = []

        def runner(key, images):
            ran.extend(images)
            return [i for i in images]

        pol = QosPolicy()
        d = BatchingDispatcher(
            runner, max_batch=4, window_ms=1.0, request_timeout_s=5.0,
            qos=pol,
        )
        # dispatcher NOT started yet: both items enqueue; the bulk one's
        # deadline lapses in the queue before the collect loop runs
        now = time.perf_counter()
        expired = asyncio.ensure_future(
            d.submit(
                "dead", "k", deadline=now + 0.05,
                tenant="abu", tclass="bulk",
            )
        )
        live_fut = asyncio.ensure_future(
            d.submit(
                "live", "k", deadline=now + 5.0,
                tenant="vic", tclass="interactive",
            )
        )
        await asyncio.sleep(0.1)
        await d.start()
        try:
            with pytest.raises(errors.DeadlineExpired):
                await expired
            assert await live_fut == "live"
            assert "dead" not in ran  # the device never ran the dead item
        finally:
            await d.stop()

    asyncio.run(go())


def test_batcher_charges_device_time_to_tenant():
    async def go():
        m = Metrics()
        pol = QosPolicy(metrics=m)
        d = BatchingDispatcher(
            lambda key, images: list(images),
            max_batch=4, window_ms=1.0, request_timeout_s=5.0, qos=pol,
        )
        await d.start()
        try:
            await asyncio.gather(
                d.submit(1, "k", tenant="a", tclass="standard"),
                d.submit(2, "k", tenant="a", tclass="standard"),
            )
        finally:
            await d.stop()
        charged = m.labeled("tenant_device_ms_total")
        assert charged.get("a", 0) > 0
        snap = pol.snapshot()
        assert snap["tenants"]["a"]["device_ms"] > 0
        assert snap["tenants"]["a"]["ewma_cost_ms"] > 0

    asyncio.run(go())


def test_overload_evicts_bulk_first_and_charges_its_tenant():
    """A non-bulk arrival under overload evicts the newest queued bulk
    item (503 overloaded, shed charged to the bulk tenant) and takes its
    place instead of being rejected."""

    async def go():
        m = Metrics()
        pol = QosPolicy(metrics=m)
        d = BatchingDispatcher(
            lambda key, images: list(images),
            max_batch=4, window_ms=1.0, request_timeout_s=5.0,
            shed_factor=0.0, qos=pol,  # shedding off while seeding the queue
        )
        # no running collect task: items stay queued
        bulk_fut = asyncio.ensure_future(
            d.submit("b", "k", tenant="abu", tclass="bulk")
        )
        await asyncio.sleep(0)  # let the bulk item enqueue
        # now flip into overload: shed guard on, drain estimate pinned
        d._shed_factor = 1.0
        d._estimated_drain_s = lambda: 1e9
        vic_fut = asyncio.ensure_future(
            d.submit("v", "k", tenant="vic", tclass="interactive")
        )
        await asyncio.sleep(0.01)
        with pytest.raises(errors.Overloaded):
            await bulk_fut  # evicted for the interactive arrival
        assert m.labeled("tenant_shed_total") == {"abu": 1}
        assert d._queue.qsize() == 1  # the victim item took the slot
        # a BULK arrival under the same overload sheds itself
        with pytest.raises(errors.Overloaded):
            await d.submit("b2", "k", tenant="abu", tclass="bulk")
        assert m.labeled("tenant_shed_total") == {"abu": 2}
        vic_fut.cancel()

    asyncio.run(go())


# ------------------------------------------------------------ fail open


def test_admission_crash_fails_open_to_default_tenant():
    """The qos.admission_raise fault site: an admission-layer crash must
    degrade to the default tenant (availability over accounting) — the
    request is served, not 500'd, even for a tenant that would have
    been over quota."""
    from deconv_api_tpu.serving.faults import FaultRegistry, install, uninstall

    pol = QosPolicy('{"a": {"class": "bulk", "rate_ms": 0.001, "burst_ms": 0.001}}')
    reg = FaultRegistry()
    reg.arm("qos.admission_raise", "n2")
    install(reg)
    try:
        # admission armed to crash: fails OPEN to the default tenant
        g = pol.admit({"x-tenant": "a"})
        assert g.failed_open and g.tenant == DEFAULT_TENANT
        pol.release(g)  # no-op, must not underflow anyone's inflight
        g2 = pol.admit({"x-tenant": "a"})
        assert g2.failed_open
    finally:
        uninstall(reg)
    # disarmed: the real admission answers again — the first metered
    # request drains the (tiny) burst, the second hits the quota
    g3 = pol.admit({"x-tenant": "a"})
    assert not g3.failed_open
    pol.release(g3)
    with pytest.raises(errors.TenantOverQuota):
        pol.admit({"x-tenant": "a"})


def test_admission_fail_open_e2e():
    params = init_params(TINY, jax.random.PRNGKey(3))
    cfg = ServerConfig(
        image_size=16, max_batch=4, batch_window_ms=1.0,
        compilation_cache_dir="", qos=True,
        # rate far below burst: the first request's compile can take
        # over a second of wall, and at rate_ms == burst_ms that is a
        # FULL bucket refill — the second request would admit again
        tenants='{"blocked": {"class": "bulk", "rate_ms": 1e-9,'
        ' "burst_ms": 0.001}}',
        fault_injection=True,
        cache_bytes=0,
    )
    svc = DeconvService(cfg, spec=TINY, params=params)
    with ServiceFixture(cfg, service=svc) as s:
        # sanity: the quota actually rejects while admission is healthy
        # (the first request drains the tiny burst; the second 429s)
        r = httpx.post(
            s.base_url + "/",
            data={"file": _data_url(), "layer": "b2c1"},
            headers={"x-tenant": "blocked"},
            timeout=60,
        )
        assert r.status_code == 200, r.text
        r = httpx.post(
            s.base_url + "/",
            data={"file": _data_url(), "layer": "b2c1"},
            headers={"x-tenant": "blocked"},
            timeout=60,
        )
        assert r.status_code == 429, r.text
        assert r.json()["error"] == "tenant_over_quota"
        assert r.json()["tenant"] == "blocked"
        assert int(r.headers["retry-after"]) >= 1
        # arm the admission crash: the SAME request now serves, as the
        # default tenant — availability over accounting
        r = httpx.post(
            s.base_url + "/v1/debug/faults",
            data={"arm": "qos.admission_raise=n1"},
        )
        assert r.status_code == 200
        r = httpx.post(
            s.base_url + "/",
            data={"file": _data_url(), "layer": "b2c1"},
            headers={"x-tenant": "blocked"},
            timeout=60,
        )
        assert r.status_code == 200, r.text
        snap = svc.metrics.snapshot()
        assert snap["counters"].get("qos_admission_errors_total") == 1


# --------------------------------------------------------------- parity


def test_byte_parity_qos_on_vs_off_single_tenant():
    """One tenant, qos on vs off: response bytes must be IDENTICAL —
    fair queueing and metering may never change what the engine
    computes.  (Both arms recompute: cache off.)"""
    params = init_params(TINY, jax.random.PRNGKey(3))
    bodies = {}
    for qos_on in (False, True):
        cfg = ServerConfig(
            image_size=16, max_batch=4, batch_window_ms=1.0,
            compilation_cache_dir="", cache_bytes=0, qos=qos_on,
        )
        svc = DeconvService(cfg, spec=TINY, params=params)
        with ServiceFixture(cfg, service=svc) as s:
            r = httpx.post(
                s.base_url + "/v1/deconv",
                data={"file": _data_url(7), "layer": "b2c1", "top_k": "2"},
                timeout=60,
            )
            assert r.status_code == 200, r.text
            bodies[qos_on] = r.content
    assert bodies[False] == bodies[True], (
        "qos-on response bytes differ from qos-off"
    )


# ------------------------------------------------- e2e surface + metrics


@pytest.fixture(scope="module")
def qos_server():
    params = init_params(TINY, jax.random.PRNGKey(3))
    cfg = ServerConfig(
        image_size=16, max_batch=4, batch_window_ms=1.0,
        compilation_cache_dir="", qos=True,
        tenants='{"abuser": {"class": "bulk", "rate_ms": 5, "burst_ms": 10,'
        ' "max_jobs": 1}, "victim": {"class": "interactive"}}',
    )
    svc = DeconvService(cfg, spec=TINY, params=params)
    with ServiceFixture(cfg, service=svc) as s:
        yield s


def test_qos_e2e_headers_metrics_and_config(qos_server):
    s = qos_server
    for i, tenant in enumerate(("victim", "abuser", "victim")):
        r = httpx.post(
            s.base_url + "/",
            data={"file": _data_url(i), "layer": "b2c1"},
            headers={"x-tenant": tenant},
            timeout=60,
        )
        assert r.status_code == 200, r.text
    # anonymous traffic maps to the default tenant and still serves
    r = httpx.post(
        s.base_url + "/",
        data={"file": _data_url(9), "layer": "b2c1"},
        timeout=60,
    )
    assert r.status_code == 200, r.text
    # labeled tenant series exist and the exposition lints clean
    text = httpx.get(s.base_url + "/v1/metrics").text
    families, samples = lint_exposition(text)
    assert families["deconv_tenant_requests_total"] == "counter"
    assert families["deconv_tenant_device_ms_total"] == "counter"
    assert families["deconv_tenant_fairness"] == "gauge"
    assert (
        samples[("deconv_tenant_requests_total",
                 'tenant="victim",class="interactive"')] >= 2
    )
    assert (
        samples[("deconv_tenant_requests_total",
                 'tenant="abuser",class="bulk"')] >= 1
    )
    assert ("deconv_tenant_requests_total",
            f'tenant="{DEFAULT_TENANT}",class="standard"') in samples
    # device time was charged to both named tenants
    dev = {
        k[1]: v for k, v in samples.items()
        if k[0] == "deconv_tenant_device_ms_total"
    }
    assert dev.get('tenant="victim"', 0) > 0
    assert dev.get('tenant="abuser"', 0) > 0
    # /v1/config reports the live qos state (and never leaks spec paths)
    cfg = httpx.get(s.base_url + "/v1/config").json()
    assert cfg["qos_active"] is True
    assert isinstance(cfg["tenants"], bool)
    state = cfg["qos_state"]
    assert state["tenants"]["victim"]["class"] == "interactive"
    assert state["tenants"]["abuser"]["tokens_ms"] is not None
    assert "deconv" in state["queued_by_class"]
    # /readyz carries the tenant occupancy block
    r = httpx.get(s.base_url + "/readyz")
    assert r.status_code == 200
    assert "qos" in r.json()
    assert r.json()["qos"]["tenants_active"] >= 2


def test_debug_requests_tenant_filter(qos_server):
    s = qos_server
    for tenant in ("filter-a", "filter-b"):
        r = httpx.post(
            s.base_url + "/",
            data={"file": _data_url(3), "layer": "b2c1"},
            headers={"x-tenant": tenant},
            timeout=60,
        )
        assert r.status_code == 200
    r = httpx.get(s.base_url + "/v1/debug/requests?tenant=filter-a")
    assert r.status_code == 200
    got = r.json()["requests"]
    assert got, "tenant filter returned nothing"
    assert all(t["tenant"] == "filter-a" for t in got)
    # composes with the ring selectors (the "which tenant is slow" query)
    r = httpx.get(s.base_url + "/v1/debug/requests?tenant=filter-a&slow=1")
    assert r.status_code == 200
    assert all(
        t["tenant"] == "filter-a" for t in r.json()["requests"]
    )


def test_cache_hit_debits_fixed_cost_not_device_estimate():
    """A hot-key tenant cannot launder traffic through the hit path: the
    provisional device debit is refunded down to hit_cost_ms, so hits
    are cheap but METERED."""
    params = init_params(TINY, jax.random.PRNGKey(3))
    cfg = ServerConfig(
        image_size=16, max_batch=4, batch_window_ms=1.0,
        compilation_cache_dir="", qos=True,
        qos_hit_cost_ms=0.5,
        # near-zero refill (0.1 ms of tokens per second of wall) so the
        # debit arithmetic below is not drowned by refill during the
        # test's few hundred ms of HTTP round trips
        tenants='{"hot": {"class": "standard", "rate_ms": 0.1,'
        ' "burst_ms": 1000}}',
    )
    svc = DeconvService(cfg, spec=TINY, params=params)
    with ServiceFixture(cfg, service=svc) as s:
        uri = _data_url(5)
        for expect in ("miss", "hit"):
            r = httpx.post(
                s.base_url + "/",
                data={"file": uri, "layer": "b2c1"},
                headers={"x-tenant": "hot"},
                timeout=60,
            )
            assert r.status_code == 200, r.text
            assert r.headers["x-cache"] == expect
        state = svc.qos.snapshot()["tenants"]["hot"]
        # exactly one request ran on the device
        assert state["device_ms"] > 0
        tokens = state["tokens_ms"]
        # bucket: 1000 - miss_debit - hit_cost(0.5) + refill; the hit
        # must NOT have been debited the full estimate a second time.
        # Tight bound instead: run 3 more hits and check each costs
        # ~hit_cost_ms, not ~est
        t0 = tokens
        for _ in range(3):
            r = httpx.post(
                s.base_url + "/",
                data={"file": uri, "layer": "b2c1"},
                headers={"x-tenant": "hot"},
                timeout=60,
            )
            assert r.headers["x-cache"] == "hit"
        t1 = svc.qos.snapshot()["tenants"]["hot"]["tokens_ms"]
        spent = t0 - t1  # refill makes this an UNDERestimate of debits
        assert spent <= 3 * 0.5 + 0.1, (
            f"3 hits cost {spent:.3f}ms of tokens; hits must debit the "
            "fixed hit cost, not the device estimate"
        )


def test_peer_fill_refunds_to_hit_cost():
    """A peer fill (round 14) moves bytes, not device work: the
    tenant's provisional device debit must be refunded down to
    hit_cost_ms exactly like a cache hit — otherwise a ring rebalance
    drains the tenant's bucket on pure cache-transfer traffic."""
    from deconv_api_tpu.serving.http import Response

    params = init_params(TINY, jax.random.PRNGKey(3))
    cfg = ServerConfig(
        image_size=16, max_batch=4, batch_window_ms=1.0,
        compilation_cache_dir="", qos=True, qos_hit_cost_ms=0.5,
        fleet_peer_fill=True,
        tenants='{"mover": {"class": "standard", "rate_ms": 0.1,'
        ' "burst_ms": 1000}}',
    )
    svc = DeconvService(cfg, spec=TINY, params=params)

    async def fake_fill(req, key, tr):
        return Response(
            status=200, body=b'{"peer": true}',
            headers={
                "content-type": "application/json",
                "x-cache": "peer-fill",
            },
        )

    with ServiceFixture(cfg, service=svc) as s:
        # one real miss warms the device-cost estimate the admission
        # layer debits provisionally
        r = httpx.post(
            s.base_url + "/",
            data={"file": _data_url(21), "layer": "b2c1"},
            headers={"x-tenant": "mover"},
            timeout=60,
        )
        assert r.status_code == 200 and r.headers["x-cache"] == "miss"
        assert svc.qos.snapshot()["tenants"]["mover"]["device_ms"] > 0
        svc._peer_fill = fake_fill  # instance attr shadows the method
        try:
            t0 = svc.qos.snapshot()["tenants"]["mover"]["tokens_ms"]
            for i in range(3):
                r = httpx.post(
                    s.base_url + "/",
                    data={"file": _data_url(30 + i), "layer": "b2c1"},
                    headers={
                        "x-tenant": "mover",
                        "x-peer-fill": "127.0.0.1:1",
                    },
                    timeout=60,
                )
                assert r.status_code == 200, r.text
                assert r.headers["x-cache"] == "peer-fill"
            t1 = svc.qos.snapshot()["tenants"]["mover"]["tokens_ms"]
        finally:
            del svc.__dict__["_peer_fill"]
        spent = t0 - t1  # refill makes this an UNDERestimate of debits
        assert spent <= 3 * 0.5 + 0.1, (
            f"3 peer fills cost {spent:.3f}ms of tokens; a fill must "
            "debit the fixed hit cost, not the device estimate"
        )


# ------------------------------------------------------------- jobs tier


def test_jobs_tenant_budget_and_park_keeps_tenant(tmp_path):
    params = init_params(TINY, jax.random.PRNGKey(3))
    cfg = ServerConfig(
        image_size=16, max_batch=4, batch_window_ms=1.0,
        compilation_cache_dir="", qos=True, cache_bytes=0,
        tenants='{"jobber": {"class": "bulk", "max_jobs": 1}}',
        jobs_dir=str(tmp_path / "jobs"),
    )
    svc = DeconvService(cfg, spec=TINY, params=params)
    with ServiceFixture(cfg, service=svc) as s:
        # hold the runner: drain parks instead of executing (the fixture
        # starts runners; park happens at stop via begin_drain anyway)
        r1 = httpx.post(
            s.base_url + "/v1/jobs",
            data={"type": "deconv", "file": _data_url(1), "layer": "b2c1"},
            headers={"x-tenant": "jobber", "x-idempotency-key": "j1"},
            timeout=60,
        )
        assert r1.status_code == 202, r1.text
        assert r1.json()["tenant"] == "jobber"
        # second DISTINCT submit: over the tenant's max_jobs=1 budget
        # (unless j1 already finished — so check both acceptances)
        r2 = httpx.post(
            s.base_url + "/v1/jobs",
            data={"type": "deconv", "file": _data_url(2), "layer": "b2c1"},
            headers={"x-tenant": "jobber", "x-idempotency-key": "j2"},
            timeout=60,
        )
        if r2.status_code == 429:
            body = r2.json()
            assert body["error"] == "tenant_over_quota"
            assert body["tenant"] == "jobber"
            assert int(r2.headers["retry-after"]) >= 1
        else:
            assert r2.status_code == 202  # j1 drained before j2 arrived
        # idempotent resubmit of j1 is NEVER an admission (dedup wins)
        r3 = httpx.post(
            s.base_url + "/v1/jobs",
            data={"type": "deconv", "file": _data_url(1), "layer": "b2c1"},
            headers={"x-tenant": "jobber", "x-idempotency-key": "j1"},
            timeout=60,
        )
        assert r3.status_code == 202 and r3.json()["deduped"] is True
    # restart on the same journal: the reclaimed jobs kept their tenant
    svc2 = DeconvService(cfg, spec=TINY, params=params)
    jobs = list(svc2.jobs._jobs.values())
    assert jobs and all(j.tenant == "jobber" for j in jobs)
    # different tenants never dedup onto each other's job: idem is
    # tenant-scoped (checked at the index level)
    assert all(j.idem.startswith("jobber|") for j in jobs)


def test_jobs_submit_rechecks_tenant_budget_atomically(tmp_path):
    """The route's cheap pre-decode budget check races across its
    decode/spill awaits: N concurrent submits can all read the same
    depth and pass.  submit(tenant_budget=) is the authoritative
    re-check — no await sits between it and the job registering, so the
    budget can never be exceeded regardless of route-level races."""
    from deconv_api_tpu.serving.jobs import JobManager, Result

    async def exec_(job, ckpts, load):
        yield Result(200, "application/json", b"{}")

    async def drive():
        m = JobManager(str(tmp_path), exec_, queue_depth=8, workers=1)
        m.submit("dream", {}, "t|i1", tenant="t", tenant_budget=2)
        m.submit("dream", {}, "t|i2", tenant="t", tenant_budget=2)
        with pytest.raises(errors.TenantOverQuota) as ei:
            m.submit("dream", {}, "t|i3", tenant="t", tenant_budget=2)
        assert ei.value.tenant == "t"
        assert ei.value.retry_after_s >= 1.0
        # dedup is still not an admission; other tenants unaffected
        _, deduped = m.submit("dream", {}, "t|i1", tenant="t",
                              tenant_budget=2)
        assert deduped
        m.submit("dream", {}, "u|i1", tenant="u", tenant_budget=2)

    asyncio.run(drive())


# ---------------------------------------------------------- retry-after


def test_retry_after_value_shared_helper():
    assert errors.retry_after_value(None) is None
    assert errors.retry_after_value(0) is None
    assert errors.retry_after_value(-3) is None
    assert errors.retry_after_value(0.2) == "1"  # never below 1s
    assert errors.retry_after_value(1.0) == "1"
    assert errors.retry_after_value(2.3) == "3"  # integer ceil
    assert errors.retry_after_value(120.0) == "120"


def test_retry_after_header_integer_seconds_everywhere():
    """Every Retry-After-bearing error type formats through the shared
    helper: integer-second values on the wire."""
    from deconv_api_tpu.serving.app import _error_response

    for e in (
        errors.Overloaded("shed", retry_after_s=2.7),
        errors.BreakerOpen("open", retry_after_s=0.3),
        errors.JobQueueFull("full", retry_after_s=12.0),
        errors.TenantOverQuota("quota", retry_after_s=1.01, tenant="t"),
    ):
        resp = _error_response(e, "rid-1")
        header = resp.headers["retry-after"]
        assert header == str(int(header)), header  # integer string
        assert int(header) >= 1
    # no retry_after -> no header
    resp = _error_response(errors.Overloaded("shed"), "rid-1")
    assert "retry-after" not in resp.headers


def test_quota_payload_carries_tenant():
    payload = errors.to_payload(
        errors.TenantOverQuota("q", retry_after_s=1.0, tenant="abc"), "rid"
    )
    assert payload["tenant"] == "abc"
    assert payload["error"] == "tenant_over_quota"


def test_quota_429_stamps_tenant_on_request():
    """A quota-REJECTED request must still carry its tenant: the 429s
    are exactly the lines an operator greps ``tenant=`` for on the
    http_request access log (docs/API.md contract), and http.py only
    logs the field when ``req.tenant`` is set."""
    from types import SimpleNamespace

    pol = QosPolicy('{"a": {"class": "bulk", "max_inflight": 1}}')
    wrapped = DeconvService._qos_wrap(
        SimpleNamespace(qos=pol), None, Metrics()
    )

    def fresh_req():
        return SimpleNamespace(
            headers={"x-tenant": "a"}, id="rid-1",
            tenant="", tclass="", _qos_grant=None,
        )

    held = pol.admit({"x-tenant": "a"})  # occupy the one in-flight slot
    req = fresh_req()
    resp = asyncio.run(wrapped(req))
    assert resp.status == 429
    assert req.tenant == "a"
    pol.release(held)
