"""Fast-lane mixed-workload smoke (VERDICT r5 item 7's fast variant):
concurrent deconv + dream + sweep traffic against ONE server — the three
dispatchers, the shared codec pool, and the input ring loaded
simultaneously — with zero errors.  Also pins the round-6 observability
surface: /v1/metrics serves the queue-depth and stage-latency gauges."""

import asyncio
import base64
import concurrent.futures

import httpx
import jax
import numpy as np
import pytest

from deconv_api_tpu.config import ServerConfig
from deconv_api_tpu.models.spec import init_params
from deconv_api_tpu.serving.app import DeconvService
from tests.test_engine_parity import TINY
from tests.test_serving import ServiceFixture, _data_url


@pytest.fixture(scope="module")
def server():
    params = init_params(TINY, jax.random.PRNGKey(3))
    cfg = ServerConfig(
        image_size=16, max_batch=4, batch_window_ms=1.0,
        compilation_cache_dir="",
    )
    service = DeconvService(cfg, spec=TINY, params=params)
    with ServiceFixture(cfg, service=service) as s:
        yield s


def test_mixed_deconv_dream_sweep_zero_errors(server):
    """6 deconv + 2 dream + 2 sweep requests in flight at once; every
    response 200, every payload well-formed."""
    url = server.base_url

    def deconv(i):
        return httpx.post(
            url + "/", data={"file": _data_url(i), "layer": "b2c1"},
            timeout=120,
        )

    def dream(i):
        # TINY's spec_bundle has no default dream layers; name a conv
        # layer explicitly, minimal ladder so the smoke stays fast-lane
        return httpx.post(
            url + "/v1/dream",
            data={
                "file": _data_url(i), "layers": "b2c1",
                "steps": "1", "octaves": "1",
            },
            timeout=120,
        )

    def sweep(i):
        return httpx.post(
            url + "/v1/deconv",
            data={"file": _data_url(i), "layer": "b2c1", "sweep": "true"},
            timeout=120,
        )

    jobs = [(deconv, i) for i in range(6)]
    jobs += [(dream, i) for i in range(6, 8)]
    jobs += [(sweep, i) for i in range(8, 10)]
    with concurrent.futures.ThreadPoolExecutor(max_workers=10) as ex:
        results = list(ex.map(lambda j: (j[0].__name__, j[0](j[1])), jobs))

    for kind, r in results:
        assert r.status_code == 200, (kind, r.status_code, r.text[:200])
    for kind, r in results:
        body = r.json()
        if kind == "deconv":
            assert isinstance(body, str) and body.startswith("data:image/")
        elif kind == "dream":
            assert body["image"].startswith("data:image/")
            assert body["layers"] == ["b2c1"]
        else:
            assert body["sweep"] is True and body["layers"]

    # zero server-side errors across all three metrics streams
    snap = server.service.metrics.snapshot()
    dream_snap = server.service.dream_metrics.snapshot()
    sweep_snap = server.service.sweep_metrics.snapshot()
    for s in (snap, dream_snap, sweep_snap):
        assert s["errors_total"] == {}, s["errors_total"]


def test_v1_metrics_exposes_pipeline_gauges(server):
    """/v1/metrics (and the legacy /metrics) expose the three-stage
    pipeline's queue-depth gauges and per-stage latency quantiles."""
    r = httpx.get(server.base_url + "/v1/metrics")
    assert r.status_code == 200
    text = r.text
    # queue-depth gauges from the batcher and the codec pool
    assert "deconv_collect_queue_depth" in text
    assert "deconv_dispatch_queue_depth" in text
    assert "deconv_inflight_batches" in text
    assert "deconv_codec_queue_depth" in text
    # stage latency quantiles (p50 + p99)
    assert 'deconv_stage_seconds{stage="decode",quantile="0.5"}' in text
    assert 'quantile="0.99"' in text
    # alias parity: both routes serve the same exposition shape
    legacy = httpx.get(server.base_url + "/metrics")
    assert legacy.status_code == 200
    assert "deconv_collect_queue_depth" in legacy.text


def test_service_restart_rebuilds_codec_pool():
    """stop() closes the codec pool; a stop() -> start() restart (which
    the dispatchers explicitly support) must rebuild it, not leave every
    pooled decode/encode raising PoolClosed (r6 review)."""
    params = init_params(TINY, jax.random.PRNGKey(5))
    cfg = ServerConfig(
        image_size=16, max_batch=2, batch_window_ms=1.0,
        warmup_all_buckets=False, compilation_cache_dir="",
    )
    service = DeconvService(cfg, spec=TINY, params=params)

    async def go():
        await service.start("127.0.0.1", 0)
        await service.stop()
        assert service.codec_pool.closed
        await service.start("127.0.0.1", 0)
        assert not service.codec_pool.closed
        assert await service.codec_pool.run(lambda: 42) == 42
        await service.stop()

    asyncio.run(go())


def test_donation_and_ring_survive_restart_cycle(server):
    """The input ring + donated batches hold up across repeated serial
    requests (buffer reuse with donation enabled end-to-end)."""
    url = server.base_url
    first = None
    for i in range(4):
        r = httpx.post(
            url + "/", data={"file": _data_url(99), "layer": "b1c1"},
            # no-cache: identical bodies must each traverse the ring —
            # this test pins buffer reuse, not the response cache
            headers={"cache-control": "no-cache"},
            timeout=120,
        )
        assert r.status_code == 200
        if first is None:
            first = r.json()
        else:
            # identical payload in, identical response out — ring reuse
            # and donation never leak state between requests
            assert r.json() == first
