"""An independent NumPy implementation of the reference's deconvnet
semantics, used as the parity oracle for the JAX engine.

The reference (app/deepdream.py) has no tests; SURVEY.md §4 prescribes a
pure-NumPy port of its algorithm as the substitute oracle.  This module
re-implements the *semantics* documented in SURVEY.md §2 from scratch —
including the load-bearing quirks (§2.2):

- conv layers carry a fused activation that is applied in BOTH directions
  (the "double ReLU", SURVEY §2.2.2): up = act(conv(x)); down applies the
  flipped-kernel conv AND THEN the fused activation again.
- a separate activation entry follows each conv/dense and applies the same
  activation in both directions (the deconvnet backward-ReLU).
- dense backward is W^T with zero bias and NO fused activation
  (reference builds a fresh linear Dense for down, app/deepdream.py:295).
- pooling records one switch per window at the first row-major argmax and
  unpools by kron-upsample x switch.
- `find_top_filters` keeps only positive activation sums, sorts descending
  (stable), returns up to `top` pairs.
- mode 'max' zeroes everything but the positions equal to the feature map's
  global max (ties all kept); mode 'all' keeps the whole map.
- the engine deconvolves every model layer from the requested one down to
  the input (SURVEY §2.2.3) — replicated here so parity can be checked for
  the full sweep.

Everything is written directly from those behavioural descriptions with
naive loops / einsum — deliberately NOT a copy of either the reference code
or the production ops.
"""

from __future__ import annotations

import numpy as np


def np_relu(x):
    return np.maximum(x, 0.0)


def np_softmax(x):
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


ACTS = {"relu": np_relu, "softmax": np_softmax, "linear": lambda x: x}


def np_conv2d_same(x, w, b=None):
    """SAME-padded stride-1 cross-correlation via einsum over shifted pads."""
    bsz, h, wd, cin = x.shape
    kh, kw, _, cout = w.shape
    ph, pw = kh // 2, kw // 2
    xp = np.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    out = np.zeros((bsz, h, wd, cout), dtype=np.float64)
    for di in range(kh):
        for dj in range(kw):
            out += np.einsum(
                "bhwc,co->bhwo", xp[:, di : di + h, dj : dj + wd, :], w[di, dj]
            )
    if b is not None:
        out = out + b
    return out


def np_flip_kernel(w):
    return np.transpose(w, (0, 1, 3, 2))[::-1, ::-1, :, :]


def np_pool_with_switch(x, ph, pw):
    b, h, w, c = x.shape
    ho, wo = h // ph, w // pw
    pooled = np.zeros((b, ho, wo, c))
    switch = np.zeros_like(x, dtype=np.float64)
    for n in range(b):
        for ch in range(c):
            for i in range(ho):
                for j in range(wo):
                    patch = x[n, i * ph : (i + 1) * ph, j * pw : (j + 1) * pw, ch]
                    pooled[n, i, j, ch] = patch.max()
                    k = int(patch.argmax())  # first occurrence, row-major
                    switch[n, i * ph + k // pw, j * pw + k % pw, ch] = 1.0
    return pooled, switch


def np_unpool_with_switch(y, switch, ph, pw):
    b, ho, wo, c = y.shape
    up = np.repeat(np.repeat(y, ph, axis=1), pw, axis=2)
    h, w = switch.shape[1], switch.shape[2]
    full = np.zeros_like(switch)
    full[:, : up.shape[1], : up.shape[2], :] = up
    return full * switch


class _Entry:
    """One up/down step of the deconv chain (the reference's D-layer)."""

    def __init__(self, name, up, down):
        self.name = name
        self.up = up
        self.down = down
        self.up_data = None


def build_entries(spec, params):
    """Build the (name, up, down) chain from a model spec.

    `spec` is a list of dicts: {name, kind, activation?, pool_size?} with
    kinds 'input' | 'conv' | 'pool' | 'flatten' | 'dense'; `params` maps
    layer name -> {'w': ..., 'b': ...}.  Mirrors the reference's stack-build
    walk (app/deepdream.py:401-423) including the companion activation
    entries for conv/dense.
    """
    entries = []
    state = {}
    for layer in spec:
        name, kind = layer["name"], layer["kind"]
        act = layer.get("activation", "linear")
        if kind == "input":
            entries.append(_Entry(name, lambda x: x, lambda x: x))
        elif kind == "conv":
            w, bb = params[name]["w"], params[name]["b"]

            def up(x, w=w, bb=bb, act=act):
                return ACTS[act](np_conv2d_same(x, w, bb))

            def down(x, w=w, act=act):
                # flipped conv, zero bias, PLUS the fused activation — the
                # reference's double-ReLU quirk (SURVEY §2.2.2)
                return ACTS[act](np_conv2d_same(x, np_flip_kernel(w)))

            entries.append(_Entry(name, up, down))
            a = ACTS[act]
            entries.append(_Entry(name + "_activation", a, a))
        elif kind == "pool":
            ph, pw = layer.get("pool_size", (2, 2))

            def up(x, ph=ph, pw=pw, name=name):
                pooled, sw = np_pool_with_switch(x, ph, pw)
                state[name] = sw
                return pooled

            def down(x, ph=ph, pw=pw, name=name):
                return np_unpool_with_switch(x, state[name], ph, pw)

            entries.append(_Entry(name, up, down))
        elif kind == "flatten":
            shape_box = {}

            def up(x, shape_box=shape_box):
                shape_box["s"] = x.shape[1:]
                return x.reshape(x.shape[0], -1)

            def down(x, shape_box=shape_box):
                return x.reshape((x.shape[0],) + shape_box["s"])

            entries.append(_Entry(name, up, down))
        elif kind == "dense":
            w, bb = params[name]["w"], params[name]["b"]

            def up(x, w=w, bb=bb, act=act):
                return ACTS[act](x @ w + bb)

            def down(x, w=w):
                return x @ w.T  # linear, zero bias (no fused act on the way down)

            entries.append(_Entry(name, up, down))
            a = ACTS[act]
            entries.append(_Entry(name + "_activation", a, a))
        else:
            raise ValueError(f"unknown kind {kind}")
    return entries


def find_top_filters(output, top=8):
    """Positive-sum filters ranked descending; stable like list.sort
    (reference: app/deepdream.py:369-380)."""
    axes = tuple(range(output.ndim - 1))
    sums = output.sum(axis=axes)
    pairs = [(i, s) for i, s in enumerate(sums) if s > 0]
    pairs.sort(key=lambda p: p[1], reverse=True)
    return pairs[:top]


def visualize_all_layers(spec, params, data, layer_name, visualize_mode="all", top=8):
    """Full-sweep deconv oracle matching reference app/deepdream.py:383-476.

    Returns {model_layer_name: [np.ndarray, ...]} for every model layer from
    `layer_name` down to (but excluding) the input, deepest first.
    """
    model_names = [l["name"] for l in spec]
    truncated = spec[: model_names.index(layer_name) + 1]
    entries = build_entries(truncated, params)

    x = data
    for e in entries:
        x = e.up(x)
        e.up_data = x

    name_set = set(model_names)
    vis_indices = [i for i, e in enumerate(entries) if e.name in name_set]
    vis_indices.reverse()
    vis_indices.pop()  # drop the input layer

    out = {}
    for i in vis_indices:
        output = entries[i].up_data
        results = []
        for fidx, _ in find_top_filters(output, top):
            fmap = output[..., fidx]
            if visualize_mode == "max":
                fmap = fmap * (fmap == fmap.max())
            elif visualize_mode != "all":
                raise ValueError("illegal visualize mode")
            seed = np.zeros_like(output)
            seed[..., fidx] = fmap
            sig = entries[i].down(seed)
            for j in range(i - 1, -1, -1):
                sig = entries[j].down(sig)
            results.append(np.squeeze(sig))
        out[entries[i].name] = results
    return out
