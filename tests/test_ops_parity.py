"""Parity: the XLA ops against the independent NumPy oracle
(tests/reference_numpy.py), on randomized inputs."""

import jax.numpy as jnp
import numpy as np

from deconv_api_tpu import ops
from tests import reference_numpy as ref


def test_conv_forward_parity(rng):
    x = rng.standard_normal((2, 9, 9, 4)).astype(np.float32)
    w = rng.standard_normal((3, 3, 4, 6)).astype(np.float32)
    b = rng.standard_normal((6,)).astype(np.float32)
    got = np.asarray(ops.conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)))
    want = ref.np_conv2d_same(x, w, b)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_conv_backward_parity(rng):
    y = rng.standard_normal((1, 9, 9, 6)).astype(np.float32)
    w = rng.standard_normal((3, 3, 4, 6)).astype(np.float32)
    got = np.asarray(ops.conv2d_input_backward(jnp.asarray(y), jnp.asarray(w)))
    want = ref.np_conv2d_same(y, ref.np_flip_kernel(w))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_pool_unpool_parity(rng):
    x = rng.standard_normal((2, 8, 8, 3)).astype(np.float32)
    pooled, switch = ops.maxpool_with_switches(jnp.asarray(x), (2, 2))
    want_p, want_s = ref.np_pool_with_switch(x, 2, 2)
    np.testing.assert_allclose(np.asarray(pooled), want_p, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(switch), want_s)

    g = rng.standard_normal(pooled.shape).astype(np.float32)
    got_u = np.asarray(ops.unpool_with_switches(jnp.asarray(g), switch, (2, 2)))
    want_u = ref.np_unpool_with_switch(g, want_s, 2, 2)
    np.testing.assert_allclose(got_u, want_u, rtol=1e-6)


def test_find_top_filters_semantics(rng):
    out = rng.standard_normal((1, 4, 4, 10)).astype(np.float64)
    pairs = ref.find_top_filters(out, top=8)
    assert all(s > 0 for _, s in pairs)
    sums = [s for _, s in pairs]
    assert sums == sorted(sums, reverse=True)
    assert len(pairs) <= 8
