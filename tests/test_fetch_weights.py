"""tools/fetch_weights.py — the one-command weights recipe's verification
logic, exercised in-env against the committed real-Keras fixture
(VERDICT r4 item 6).  The download itself needs egress the build host
doesn't have; what CAN be tested is everything that judges the file after
download: sha256, structural load through the serving loader, the
every-leaf-replaced rule, and the forward smoke."""

from __future__ import annotations

import dataclasses
import importlib.util
import os
import pathlib
import shutil

import jax
import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURE = REPO / "tests" / "fixtures" / "golden" / "vgg16_block1.h5"

_spec = importlib.util.spec_from_file_location(
    "fetch_weights", REPO / "tools" / "fetch_weights.py"
)
fw = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(fw)


def _block1_spec_params():
    from deconv_api_tpu.models.spec import init_params
    from deconv_api_tpu.models.vgg16 import VGG16_SPEC

    spec = dataclasses.replace(
        VGG16_SPEC.truncated("block1_pool"), input_shape=(64, 64, 3)
    )
    return spec, init_params(spec, jax.random.PRNGKey(0))


def test_verify_accepts_real_keras_h5():
    """The committed Keras-written h5 passes the full verification: every
    parameter leaf replaced, finite forward."""
    spec, params = _block1_spec_params()
    report = fw.verify_h5(
        "vgg16", str(FIXTURE), spec=spec, init_params=params
    )
    assert report["replaced_fraction"] == 1.0
    assert report["forward"] == "ok"
    assert len(report["sha256"]) == 64


def test_sha256_matches_golden_pin():
    """fetch_weights' hash function agrees with the fixture pin in
    tests/test_weights_golden.py — one hash implementation, one truth."""
    from tests.test_weights_golden import H5_SHA256

    assert fw.sha256_of(str(FIXTURE)) == H5_SHA256


def test_verify_rejects_partial_load():
    """A block1-only h5 against the FULL VGG16 model must fail the
    every-leaf-replaced rule (the silently-partial-load failure mode that
    shape checks alone cannot catch)."""
    from deconv_api_tpu.models.vgg16 import vgg16_init

    spec, params = vgg16_init()
    with pytest.raises(ValueError, match="leaves were replaced"):
        fw.verify_h5(
            "vgg16", str(FIXTURE), spec=spec, init_params=params,
            forward_smoke=False,
        )


def test_verify_rejects_wrong_shape(tmp_path):
    """A kernel with the wrong shape raises through the loader, naming the
    layer — corruption is loud, not silently truncated."""
    h5py = pytest.importorskip("h5py")
    bad = tmp_path / "bad.h5"
    shutil.copy(FIXTURE, bad)
    with h5py.File(bad, "r+") as f:
        grp = f["model_weights"]["block1_conv1"]["block1_conv1"]
        data = np.asarray(grp["kernel"])[:, :, :, :32]  # drop half the filters
        del grp["kernel"]
        grp.create_dataset("kernel", data=data)
    spec, params = _block1_spec_params()
    with pytest.raises(ValueError, match="block1_conv1"):
        fw.verify_h5(
            "vgg16", str(bad), spec=spec, init_params=params,
            forward_smoke=False,
        )


def test_manifest_covers_registry():
    """Every registry family has a fetch entry — a new model family must
    ship its weights recipe.  vgg_tiny (round 15) is the one deliberate
    exception: a random-init CI/dry-run backbone with no pretrained
    artifact to fetch."""
    from deconv_api_tpu.serving.models import REGISTRY

    assert set(fw.MANIFEST) == set(REGISTRY) - {"vgg_tiny"}


def test_all_flag_covers_manifest(monkeypatch, capsys):
    """--all (round 15) prefetches + verifies EVERY manifest backbone in
    one call and prints the multi-model serve line; incompatible flags
    are argparse errors."""
    fetched, verified = [], []
    monkeypatch.setattr(
        fw, "fetch", lambda name, dest, sha=None: fetched.append(name) or f"/x/{name}.h5"
    )
    monkeypatch.setattr(
        fw,
        "verify_h5",
        lambda name, path, forward_smoke=True: verified.append(name)
        or {"model": name},
    )
    monkeypatch.setattr("sys.argv", ["fetch_weights.py", "--all", "--no-smoke"])
    assert fw.main() == 0
    assert fetched == sorted(fw.MANIFEST)
    assert verified == sorted(fw.MANIFEST)
    assert "--serve-models all" in capsys.readouterr().err

    monkeypatch.setattr("sys.argv", ["fetch_weights.py", "vgg16", "--all"])
    with pytest.raises(SystemExit) as e:
        fw.main()
    assert e.value.code == 2

    monkeypatch.setattr(
        "sys.argv", ["fetch_weights.py", "--all", "--verify-only", "/x.h5"]
    )
    with pytest.raises(SystemExit) as e:
        fw.main()
    assert e.value.code == 2

    monkeypatch.setattr("sys.argv", ["fetch_weights.py"])
    with pytest.raises(SystemExit):
        fw.main()


def test_fetch_writes_model_alias(tmp_path, monkeypatch):
    """fetch() leaves a <model>.h5 alias next to the upstream basename so
    `serve --weights <dir>` finds every model by convention."""
    src = tmp_path / "mobilenet_1_0_224_tf.h5"
    src.write_bytes(b"weights")

    def fake_retrieve(url, tmp):
        shutil.copyfile(src, tmp)

    monkeypatch.setattr(
        "urllib.request.urlretrieve", fake_retrieve, raising=False
    )
    dest = tmp_path / "dest"
    path = fw.fetch("mobilenet_v1", str(dest))
    assert os.path.basename(path) == "mobilenet_1_0_224_tf.h5"
    alias = dest / "mobilenet_v1.h5"
    assert alias.exists()
    assert alias.read_bytes() == b"weights"
