"""Weight-manager tests (round 15): quantized tier fidelity (PSNR
bounds, not byte equality), LRU paging under a byte budget, cold-model
page-in coalescing (exactly one transfer per (model, lane)), the
eviction-vs-in-flight guard, per-request model routing e2e (422 on
unknown, cache-key non-fragmentation across selector forms), and the
/v1/config + /readyz + flight-recorder surfaces."""

from __future__ import annotations

import threading
import time

import httpx
import numpy as np
import pytest

import jax

from deconv_api_tpu import errors
from deconv_api_tpu.config import ServerConfig
from deconv_api_tpu.models.spec import Layer, ModelSpec, init_params
from deconv_api_tpu.serving.app import DeconvService
from deconv_api_tpu.serving.metrics import Metrics
from deconv_api_tpu.serving.models import REGISTRY, spec_bundle
from deconv_api_tpu.serving.weight_manager import (
    WeightManager,
    dequantize_params,
    quantize_params,
    tree_nbytes,
)
from tests.test_serving import ServiceFixture, _data_url


def _psnr(a, b, peak=None):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    peak = peak if peak is not None else max(float(a.max() - a.min()), 1e-9)
    mse = float(np.mean((a - b) ** 2))
    return 99.0 if mse == 0 else 10 * np.log10(peak * peak / mse)


def _mix_spec(name: str, f1: int, f2: int) -> ModelSpec:
    return ModelSpec(
        name=name,
        input_shape=(16, 16, 3),
        layers=(
            Layer("in0", "input"),
            Layer("b1c1", "conv", activation="relu", filters=f1),
            Layer("b1p", "pool"),
            Layer("b2c1", "conv", activation="relu", filters=f2),
        ),
    )


def _mix_registry(*widths):
    """name -> builder for a family of differently-sized tiny specs
    (distinct filter counts => distinct byte sizes AND distinct output
    bytes, so routing mistakes are visible in the response)."""
    reg = {}
    for i, (f1, f2) in enumerate(widths):
        name = f"mix{chr(ord('a') + i)}"
        spec = _mix_spec(name, f1, f2)
        params = init_params(spec, jax.random.PRNGKey(100 + i))
        reg[name] = (
            lambda spec=spec, params=params: spec_bundle(spec, params)
        )
    return reg


def _fake_builders(*names, leaf_kb=4):
    """Host-only bundles for manager unit tests (no device dispatch)."""
    class FakeBundle:
        def __init__(self, name):
            self.name = name
            self.mesh = None
            self.params = {
                "l1": {
                    "kernel": np.random.default_rng(0)
                    .normal(size=(leaf_kb * 256,))
                    .astype(np.float32)
                    .reshape(-1, 16),
                    "bias": np.zeros((16,), np.float32),
                }
            }
            self.weight_dtype = "f32"
            self._lane_placements = []

        def lane_params(self, lane=0):
            return self.params

        def set_lanes(self, placements):
            self._lane_placements = list(placements)

    return {n: (lambda n=n: FakeBundle(n)) for n in names}


def _manager(names=("ma", "mb", "mc"), budget=0, dtype="f32", lanes=1,
             metrics=None, **kw):
    return WeightManager(
        _fake_builders(*names),
        names[0],
        placements=[None] * lanes if lanes > 1 else None,
        budget_bytes=budget,
        weight_dtype=dtype,
        metrics=metrics,
        **kw,
    )


# ------------------------------------------------------------ quantization


def test_quantize_f32_is_identity():
    tree = {"a": {"kernel": np.ones((4, 4), np.float32)}}
    assert quantize_params(tree, "f32") is tree


def test_quantize_int8_symmetric_roundtrip_structure():
    rng = np.random.default_rng(0)
    tree = {
        "conv": {"kernel": rng.normal(size=(3, 3, 8, 16)).astype(np.float32),
                 "bias": rng.normal(size=(16,)).astype(np.float32)},
    }
    q = quantize_params(tree, "int8")
    assert q["conv"]["kernel"]["__q8__"].dtype == np.int8
    # biases stay f32: their bytes are noise, their range matters
    assert q["conv"]["bias"].dtype == np.float32
    dq = jax.tree_util.tree_map(np.asarray, dequantize_params(q))
    # same structure back, and per-tensor symmetric error is bounded by
    # one quantisation step (scale/2 per element)
    assert set(dq["conv"]) == {"kernel", "bias"}
    scale = float(q["conv"]["kernel"]["__q8_scale__"])
    assert np.max(np.abs(dq["conv"]["kernel"] - tree["conv"]["kernel"])) <= (
        scale / 2 + 1e-7
    )
    np.testing.assert_array_equal(dq["conv"]["bias"], tree["conv"]["bias"])


def test_quantize_int8_all_zero_tensor():
    tree = {"k": np.zeros((4, 4), np.float32)}
    dq = jax.tree_util.tree_map(
        np.asarray, dequantize_params(quantize_params(tree, "int8"))
    )
    np.testing.assert_array_equal(dq["k"], tree["k"])


def test_quantize_bf16_halves_bytes():
    tree = {"k": np.random.default_rng(0).normal(size=(64, 64)).astype(np.float32)}
    q = quantize_params(tree, "bf16")
    assert tree_nbytes(q) == tree_nbytes(tree) // 2
    dq = np.asarray(dequantize_params(q)["k"])
    assert dq.dtype == np.float32
    assert _psnr(tree["k"], dq) > 60.0


def test_quantize_int8_quarters_kernel_bytes():
    tree = {"k": np.random.default_rng(0).normal(size=(64, 64)).astype(np.float32)}
    q = quantize_params(tree, "int8")
    # int8 payload + f32 scale ~= 1/4 the f32 bytes
    assert tree_nbytes(q) <= tree_nbytes(tree) // 4 + 16


def test_quantize_rejects_unknown_dtype():
    with pytest.raises(ValueError, match="weight_dtype"):
        quantize_params({}, "fp4")


# PSNR parity floors per sequential backbone (acceptance: bf16/int8
# bounded by PSNR, not byte equality).  Weights-level PSNR runs on the
# REAL backbones (vgg16/vgg19/vgg_tiny — init + numpy, no device
# programs); output-level PSNR runs the actual visualizer on vgg_tiny.
# Measured 2026-08-03: int8 weights >= 58 dB on all three, bf16 >= 69 dB;
# vgg_tiny output 46.0 dB bf16 / 27.7 dB int8.  Floors leave margin.
_WEIGHT_PSNR_FLOORS = {"bf16": 60.0, "int8": 45.0}


@pytest.mark.parametrize("backbone", ["vgg_tiny", "vgg16", "vgg19"])
@pytest.mark.parametrize("wd", ["bf16", "int8"])
def test_weight_psnr_bounds_per_sequential_backbone(backbone, wd):
    bundle = REGISTRY[backbone]()
    params = jax.tree_util.tree_map(np.asarray, bundle.params)
    dq = jax.tree_util.tree_map(
        np.asarray, dequantize_params(quantize_params(params, wd))
    )
    worst = min(
        _psnr(a, b)
        for a, b in zip(
            jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(dq)
        )
        if np.asarray(a).ndim >= 2
    )
    assert worst >= _WEIGHT_PSNR_FLOORS[wd], (backbone, wd, worst)


@pytest.mark.parametrize("wd,floor", [("bf16", 38.0), ("int8", 20.0)])
def test_output_psnr_bounds_vgg_tiny(wd, floor):
    """The actual serving program (batched visualizer, raw fp32
    projections) under a quantized weight tier stays within its PSNR
    budget of the f32 tier."""
    bundle = REGISTRY["vgg_tiny"]()
    params = jax.tree_util.tree_map(np.asarray, bundle.params)
    x = (
        np.random.default_rng(0)
        .normal(size=(2, 32, 32, 3))
        .astype(np.float32)
    )
    ref = np.asarray(
        bundle.batched_visualizer("block2_conv2", "all", 4)(params, x)[
            "block2_conv2"
        ]["images"]
    )
    qb = REGISTRY["vgg_tiny"]()
    qb.weight_dtype = wd
    q = quantize_params(
        jax.tree_util.tree_map(np.asarray, qb.params), wd
    )
    out = np.asarray(
        qb.batched_visualizer("block2_conv2", "all", 4)(q, x)[
            "block2_conv2"
        ]["images"]
    )
    got = _psnr(ref, out)
    assert got >= floor, (wd, got)


# ------------------------------------------------------------ manager unit


def test_inert_mode_is_identity():
    m = _manager(names=("ma",))
    assert not m.managed
    b = m.bundle("ma")
    tree, page_s = m.checkout("ma")
    assert tree is b.params and page_s == 0.0
    assert m.page_ins == 0
    m.release("ma")
    assert m.resident_models() == ["ma"]


def test_unknown_model_raises():
    m = _manager()
    with pytest.raises(errors.UnknownModel):
        m.bundle("nope")


def test_lru_pages_out_oldest_under_budget(monkeypatch):
    metrics = Metrics()
    m = _manager(metrics=metrics)
    # placement: keep host trees (no device put) so nbytes is stable
    monkeypatch.setattr(m, "_place", lambda tree, pl: tree)
    t, _ = m.checkout("ma")
    m.release("ma")
    size = tree_nbytes(t)
    m.budget_bytes = 2 * size + 64  # room for exactly two models
    m.pinned = ()  # let everything evict for this test
    m.checkout("mb")
    m.release("mb")
    assert m.resident_models() == ["ma", "mb"]
    m.checkout("mc")
    m.release("mc")
    # ma was least-recently-used -> paged out
    assert m.resident_models() == ["mb", "mc"]
    assert m.page_outs == 1
    assert metrics.counter("weight_page_outs_total") == 1
    assert metrics.counter("weight_page_ins_total") == 3


def test_touch_refreshes_lru_order(monkeypatch):
    m = _manager()
    monkeypatch.setattr(m, "_place", lambda tree, pl: tree)
    m.pinned = ()
    t, _ = m.checkout("ma")
    m.release("ma")
    size = tree_nbytes(t)
    m.budget_bytes = 2 * size + 64
    m.checkout("mb")
    m.release("mb")
    # touch ma: now mb is the LRU victim
    m.checkout("ma")
    m.release("ma")
    m.checkout("mc")
    m.release("mc")
    assert m.resident_models() == ["ma", "mc"]


def test_pinned_model_never_evicted(monkeypatch):
    m = _manager()
    monkeypatch.setattr(m, "_place", lambda tree, pl: tree)
    t, _ = m.checkout("ma")  # ma is the default => pinned
    m.release("ma")
    m.budget_bytes = tree_nbytes(t) + 64  # room for ~one model
    m.checkout("mb")
    m.release("mb")
    # ma (pinned) survives; the budget overshoots loudly instead
    assert "ma" in m.resident_models()
    assert m.overcommits >= 1


def test_eviction_never_unloads_inflight_model(monkeypatch):
    metrics = Metrics()
    m = _manager(budget=0, metrics=metrics)
    monkeypatch.setattr(m, "_place", lambda tree, pl: tree)
    m.pinned = ()
    t, _ = m.checkout("mb")  # mb IN FLIGHT (not released)
    m.budget_bytes = tree_nbytes(t) + 64
    m.checkout("mc")
    m.release("mc")
    # mb held its pin -> not evicted even though it is the LRU victim;
    # budget overshoots loudly
    assert "mb" in m.resident_models()
    assert m.overcommits >= 1
    assert metrics.counter("weight_budget_overcommit_total") >= 1
    # released -> next pressure evicts it
    m.release("mb")
    m.checkout("ma")
    m.release("ma")
    assert "mb" not in m.resident_models()


def test_cold_checkout_coalesces_one_transfer(monkeypatch):
    """N concurrent checkouts of one cold (model, lane) => exactly ONE
    device transfer; everyone gets the same tree."""
    m = _manager()
    calls = []
    orig_place = m._place

    def slow_place(tree, pl):
        calls.append(threading.get_ident())
        time.sleep(0.05)
        return tree

    monkeypatch.setattr(m, "_place", slow_place)
    results = []

    def worker():
        t, _ = m.checkout("mb")
        results.append(t)
        m.release("mb")

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(calls) == 1, "coalescing must issue one transfer"
    assert len(results) == 8
    assert all(r is results[0] for r in results)
    assert m.page_ins == 1


def test_per_lane_transfers_are_independent(monkeypatch):
    m = _manager(lanes=2)
    calls = []
    monkeypatch.setattr(
        m, "_place", lambda tree, pl: (calls.append(pl), tree)[1]
    )
    m.checkout("mb", lane=0)
    m.checkout("mb", lane=1)
    assert len(calls) == 2  # one transfer per (model, lane)
    assert m.resident_models(0) == ["mb"] and m.resident_models(1) == ["mb"]


def test_failed_page_in_releases_waiters(monkeypatch):
    m = _manager()

    def boom(tree, pl):
        raise RuntimeError("transfer died")

    monkeypatch.setattr(m, "_place", boom)
    with pytest.raises(RuntimeError):
        m.checkout("mb")
    # the paging promise is cleared: a retry can proceed
    monkeypatch.setattr(m, "_place", lambda tree, pl: tree)
    t, _ = m.checkout("mb")
    assert t is not None


def test_pinned_must_be_served():
    with pytest.raises(ValueError, match="pinned"):
        _manager(pinned=("ghost",))


def test_manager_rejects_bad_dtype():
    with pytest.raises(ValueError, match="weight_dtype"):
        _manager(dtype="fp4")


# ------------------------------------------------------------------- e2e


def _mix_cfg(**kw):
    base = dict(
        image_size=0,
        max_batch=4,
        batch_window_ms=1.0,
        compilation_cache_dir="",
        model="mixa",
        serve_models="mixa,mixb",
        serve_lanes="off",
        warmup_all_buckets=False,
    )
    base.update(kw)
    return ServerConfig(**base)


@pytest.fixture(scope="module")
def mix_server():
    reg = _mix_registry((8, 16), (16, 32))
    svc = DeconvService(_mix_cfg(), registry=reg)
    with ServiceFixture(None, service=svc) as s:
        yield s


def test_per_request_model_routing(mix_server):
    """model= form field and x-model header both route; the two models'
    responses differ (different widths => different grids); default
    requests keep serving the default model."""
    url = mix_server.base_url
    body = {"file": _data_url(), "layer": "b2c1"}
    r_default = httpx.post(url, data=body, timeout=60)
    assert r_default.status_code == 200
    r_field = httpx.post(url, data={**body, "model": "mixb"}, timeout=60)
    assert r_field.status_code == 200
    r_header = httpx.post(
        url, data=body, headers={"x-model": "mixb"}, timeout=60
    )
    assert r_header.status_code == 200
    assert r_field.content == r_header.content
    assert r_default.content != r_field.content
    # both models resident after serving
    snap = mix_server.service.weights.snapshot()
    assert set(snap["lanes"]["0"]["resident"]) == {"mixa", "mixb"}
    assert snap["page_ins"] >= 2


def test_unknown_model_422(mix_server):
    url = mix_server.base_url
    body = {"file": _data_url(), "layer": "b2c1"}
    r = httpx.post(url, data={**body, "model": "resnet50"}, timeout=30)
    assert r.status_code == 422
    assert r.json()["error"] == "unknown_model"
    r = httpx.post(url, data=body, headers={"x-model": "zzz"}, timeout=30)
    assert r.status_code == 422


def test_cache_key_non_fragmentation(mix_server):
    """model=<default> explicit, x-model: <default>, and a bare request
    all hash to ONE cache entry — the resolved model rides the prefix
    and the raw field is excluded from the digest."""
    url = mix_server.base_url
    body = {"file": _data_url(rng_seed=7), "layer": "b1c1"}
    r1 = httpx.post(url, data=body, timeout=60)
    assert r1.status_code == 200 and r1.headers["x-cache"] == "miss"
    r2 = httpx.post(url, data={**body, "model": "mixa"}, timeout=60)
    assert r2.status_code == 200
    assert r2.headers["x-cache"] == "hit", "explicit default must not fragment"
    r3 = httpx.post(
        url, data=body, headers={"x-model": "mixa"}, timeout=60
    )
    assert r3.headers["x-cache"] == "hit"
    assert r1.content == r2.content == r3.content
    # and a DIFFERENT model is a different key, not a poisoned hit
    r4 = httpx.post(url, data={**body, "model": "mixb"}, timeout=60)
    assert r4.status_code == 200 and r4.headers["x-cache"] == "miss"
    assert r4.content != r1.content


def test_v1_deconv_and_models_surfaces(mix_server):
    url = mix_server.base_url
    r = httpx.post(
        url + "/v1/deconv",
        data={"file": _data_url(), "layer": "b2c1", "model": "mixb",
              "top_k": "2"},
        timeout=60,
    )
    assert r.status_code == 200
    cfg = httpx.get(url + "/v1/config", timeout=30).json()
    w = cfg["weights"]
    assert w["managed"] is True
    assert w["served"] == ["mixa", "mixb"]
    assert w["pinned"] == ["mixa"]
    assert w["page_ins"] >= 1
    rz = httpx.get(url + "/readyz", timeout=30).json()
    assert "models" in rz and rz["models"]["served"] == 2


def test_debug_requests_model_filter(mix_server):
    url = mix_server.base_url
    body = {"file": _data_url(rng_seed=11), "layer": "b2c1", "model": "mixb"}
    assert httpx.post(url, data=body, timeout=60).status_code == 200
    r = httpx.get(url + "/v1/debug/requests?model=mixb", timeout=30).json()
    assert r["requests"], "model filter must find the mixb trace"
    assert all(t.get("model") == "mixb" for t in r["requests"])
    r = httpx.get(
        url + "/v1/debug/requests?model=no_such", timeout=30
    ).json()
    assert r["requests"] == []


def test_eviction_churn_stays_byte_identical():
    """Page-out -> page-in round trips must not perturb output bytes:
    the same request recomputed (no-cache) after its model was evicted
    and re-paged answers identically."""
    reg = _mix_registry((8, 16), (16, 32))
    # budget sized so the two models cannot both stay resident
    sizes = {}
    for name, builder in reg.items():
        sizes[name] = tree_nbytes(
            jax.tree_util.tree_map(np.asarray, builder().params)
        )
    cfg = _mix_cfg(
        hbm_budget_bytes=int(max(sizes.values()) * 1.2),
        pinned_models="",
        cache_bytes=0,
        singleflight=False,
    )
    svc = DeconvService(cfg, registry=reg)
    # only the default stays pinned; give eviction freedom over both
    svc.weights.pinned = ()
    with ServiceFixture(None, service=svc) as s:
        body = {"file": _data_url(rng_seed=3), "layer": "b2c1"}
        first = {}
        for model in ("mixa", "mixb"):
            r = httpx.post(
                s.base_url, data={**body, "model": model}, timeout=60
            )
            assert r.status_code == 200
            first[model] = r.content
        # churn: alternate models under the one-model budget
        for _ in range(2):
            for model in ("mixa", "mixb"):
                r = httpx.post(
                    s.base_url, data={**body, "model": model}, timeout=60
                )
                assert r.status_code == 200
                assert r.content == first[model], "churn changed bytes"
        snap = svc.weights.snapshot()
        assert snap["page_outs"] >= 1, "budget never forced paging (vacuous)"


def test_single_model_managed_parity():
    """A single-model server with paging machinery engaged (budget set)
    answers byte-identically to the plain inert server."""
    reg = _mix_registry((8, 16))
    plain = DeconvService(_mix_cfg(serve_models=""), registry=reg)
    managed = DeconvService(
        _mix_cfg(serve_models="", hbm_budget_bytes=64 * 1024 * 1024),
        registry=reg,
    )
    assert not plain.weights.managed and managed.weights.managed
    body = {"file": _data_url(rng_seed=5), "layer": "b2c1"}
    with ServiceFixture(None, service=plain) as a:
        ra = httpx.post(a.base_url, data=body, timeout=60)
    with ServiceFixture(None, service=managed) as b:
        rb = httpx.post(b.base_url, data=body, timeout=60)
    assert ra.status_code == rb.status_code == 200
    assert ra.content == rb.content


def test_weight_dtype_folds_into_cache_prefix():
    reg = _mix_registry((8, 16))
    f32 = DeconvService(_mix_cfg(serve_models=""), registry=reg)
    bf16 = DeconvService(
        _mix_cfg(serve_models="", weight_dtype="bf16"), registry=reg
    )
    assert f32._cache_prefix != bf16._cache_prefix
    assert "bf16" in bf16._cache_prefix


def test_boot_rejects_bad_config():
    reg = _mix_registry((8, 16), (16, 32))
    with pytest.raises(ValueError, match="weight_dtype"):
        DeconvService(_mix_cfg(weight_dtype="fp4"), registry=reg)
    with pytest.raises(ValueError, match="serve_models"):
        DeconvService(_mix_cfg(serve_models="mixa,ghost"), registry=reg)
    with pytest.raises(ValueError, match="pinned"):
        DeconvService(
            _mix_cfg(serve_models="mixa,mixb", pinned_models="ghost"),
            registry=reg,
        )
    # a served model named like one of the default model's layers would
    # corrupt the dispatcher key head-strip — loud config error at boot
    reg2 = {**reg, "b2c1": reg["mixa"]}
    with pytest.raises(ValueError, match="collide"):
        DeconvService(
            _mix_cfg(serve_models="mixa,mixb,b2c1"), registry=reg2
        )


def test_quantized_tier_serves_and_pages(mix_server_unused=None):
    """int8 tier end-to-end: serves 200s, output differs from f32 only
    within the PSNR budget (not asserted here — the parity tests above
    own that), and the resident bytes are ~quarter of f32."""
    reg = _mix_registry((8, 16))
    f32_bytes = tree_nbytes(
        jax.tree_util.tree_map(np.asarray, reg["mixa"]().params)
    )
    svc = DeconvService(
        _mix_cfg(serve_models="", weight_dtype="int8"), registry=reg
    )
    with ServiceFixture(None, service=svc) as s:
        r = httpx.post(
            s.base_url, data={"file": _data_url(), "layer": "b2c1"},
            timeout=60,
        )
        assert r.status_code == 200
        snap = svc.weights.snapshot()
        resident = snap["lanes"]["0"]["bytes"]
        assert 0 < resident < f32_bytes / 2, (resident, f32_bytes)


def test_trace_carries_weight_page_in_span():
    """A cold model's first request shows the page-in on its trace; the
    warm path does not."""
    reg = _mix_registry((8, 16), (16, 32))
    svc = DeconvService(_mix_cfg(cache_bytes=0, singleflight=False), registry=reg)
    with ServiceFixture(None, service=svc) as s:
        body = {"file": _data_url(rng_seed=9), "layer": "b2c1",
                "model": "mixb"}
        r = httpx.post(s.base_url, data=body, timeout=60)
        assert r.status_code == 200
        rid = r.headers["x-request-id"]
        tr = httpx.get(
            s.base_url + f"/v1/debug/requests?id={rid}", timeout=30
        ).json()["requests"][0]
        spans = {sp["name"] for sp in tr["spans"]}
        assert "weight_page_in" in spans, spans
        # warm second request: no page-in span
        r2 = httpx.post(s.base_url, data=body, timeout=60)
        tr2 = httpx.get(
            s.base_url + f"/v1/debug/requests?id={r2.headers['x-request-id']}",
            timeout=30,
        ).json()["requests"][0]
        assert "weight_page_in" not in {sp["name"] for sp in tr2["spans"]}


def test_metrics_exposition_includes_weight_families(mix_server):
    text = httpx.get(
        mix_server.base_url + "/v1/metrics", timeout=30
    ).text
    assert "deconv_weight_page_ins_total" in text
    assert 'deconv_resident_models{lane="0"}' in text
    assert "deconv_weight_page_bytes_total" in text
    # the page-in wait histogram rides the stage family
    assert 'stage="weight_page_in"' in text


def test_jobs_carry_model_and_resume_against_it(tmp_path):
    """A job submitted with model= journals it and its result matches
    the sync route's bytes for that model."""
    reg = _mix_registry((8, 16), (16, 32))
    svc = DeconvService(
        _mix_cfg(jobs_dir=str(tmp_path / "jobs"), cache_bytes=0,
                 singleflight=False),
        registry=reg,
    )
    with ServiceFixture(None, service=svc) as s:
        body = {"file": _data_url(rng_seed=4), "layer": "b2c1",
                "type": "deconv", "model": "mixb", "top_k": "2"}
        r = httpx.post(s.base_url + "/v1/jobs", data=body, timeout=60)
        assert r.status_code == 202, r.text
        job_id = r.json()["id"]
        # the model is journaled with the job (resume-after-restart
        # re-dispatches against the same backbone)
        assert svc.jobs.get(job_id).params["model"] == "mixb"
        deadline = time.time() + 60
        while time.time() < deadline:
            doc = httpx.get(
                s.base_url + f"/v1/jobs/{job_id}", timeout=30
            ).json()
            if doc["state"] in ("done", "failed"):
                break
            time.sleep(0.05)
        assert doc["state"] == "done", doc
        job_body = httpx.get(
            s.base_url + f"/v1/jobs/{job_id}/result", timeout=30
        ).content
        sync = httpx.post(
            s.base_url + "/v1/deconv",
            data={"file": _data_url(rng_seed=4), "layer": "b2c1",
                  "model": "mixb", "top_k": "2"},
            timeout=60,
        ).content
        assert job_body == sync
