"""ServerConfig environment parsing — the whole user-facing knob surface
(SURVEY §5 config row; the reference hardcodes every one of these)."""

import dataclasses

import pytest

from deconv_api_tpu.config import ServerConfig, _coerce


def test_defaults_are_consistent():
    cfg = ServerConfig()
    assert cfg.pipeline_depth == 2
    assert cfg.backward_dtype == "bfloat16"
    assert cfg.dtype == "float32"
    assert cfg.mesh_shape == ()


def test_env_overrides_every_field_kind(monkeypatch):
    monkeypatch.setenv("DECONV_PORT", "8123")  # int
    monkeypatch.setenv("DECONV_BATCH_WINDOW_MS", "7.5")  # float
    monkeypatch.setenv("DECONV_MODEL", "resnet50")  # str
    monkeypatch.setenv("DECONV_MESH_SHAPE", "4,2")  # tuple
    monkeypatch.setenv("DECONV_BUG_COMPAT", "0")  # bool
    monkeypatch.setenv("DECONV_PIPELINE_DEPTH", "3")
    cfg = ServerConfig.from_env()
    assert cfg.port == 8123
    assert cfg.batch_window_ms == 7.5
    assert cfg.model == "resnet50"
    assert cfg.mesh_shape == (4, 2)
    assert cfg.bug_compat is False
    assert cfg.pipeline_depth == 3


@pytest.mark.parametrize(
    "raw,want", [("1", True), ("true", True), ("YES", True), ("on", True),
                 ("0", False), ("false", False), ("banana", False)]
)
def test_bool_coercion(raw, want):
    assert _coerce(raw, bool, True) is want


def test_tuple_coercion_tolerates_blanks():
    assert _coerce("8,", tuple, ()) == (8,)
    assert _coerce("", tuple, ()) == ()
    assert _coerce("2,2,2", tuple, ()) == (2, 2, 2)


def test_overrides_beat_env(monkeypatch):
    monkeypatch.setenv("DECONV_TOP_K", "4")
    cfg = ServerConfig.from_env(top_k=16)
    assert cfg.top_k == 16


def test_unknown_override_raises():
    with pytest.raises(ValueError, match="unknown config field"):
        ServerConfig.from_env(no_such_field=1)


def test_every_field_has_an_env_name_without_collisions():
    names = [f"DECONV_{f.name.upper()}" for f in dataclasses.fields(ServerConfig)]
    assert len(names) == len(set(names))


def test_cache_knob_defaults_and_env(monkeypatch):
    """Round 7 response-cache knobs: default-on with escape hatches,
    every knob reachable over the same DECONV_* env surface."""
    cfg = ServerConfig()
    assert cfg.cache_bytes == 256 * 1024 * 1024  # default-on
    assert cfg.cache_ttl_s == 0.0  # until evicted
    assert cfg.cache_negative_ttl_s == 2.0
    assert cfg.cache_shards == 8
    assert cfg.singleflight is True
    monkeypatch.setenv("DECONV_CACHE_BYTES", "0")  # the escape hatch
    monkeypatch.setenv("DECONV_CACHE_TTL_S", "30.5")
    monkeypatch.setenv("DECONV_CACHE_NEGATIVE_TTL_S", "0.5")
    monkeypatch.setenv("DECONV_SINGLEFLIGHT", "0")
    cfg = ServerConfig.from_env()
    assert cfg.cache_bytes == 0
    assert cfg.cache_ttl_s == 30.5
    assert cfg.cache_negative_ttl_s == 0.5
    assert cfg.singleflight is False
