"""Serving abuse-hardening tests (VERDICT r2 item 4): slowloris reaping,
body-read timeouts, connection caps, queue load shedding, pre-warmup
readiness gating, discovery endpoint, and forward-dtype wiring.

The reference has none of these failure modes handled — its server blocks
its single event loop for seconds per request (SURVEY §2.2.5) and crashes
on bad input (§2.2.8); this module pins the replacements' behaviour.
"""

import asyncio
import dataclasses
import threading
import time

import numpy as np
import pytest

import jax

from deconv_api_tpu import errors
from deconv_api_tpu.config import ServerConfig
from deconv_api_tpu.models.spec import init_params
from deconv_api_tpu.serving.app import DeconvService
from deconv_api_tpu.serving.batcher import BatchingDispatcher
from deconv_api_tpu.serving.http import HttpServer, Response
from deconv_api_tpu.serving.metrics import Metrics
from tests.test_engine_parity import TINY


# ------------------------------------------------------------ HTTP edge


def _run_http(test_coro_factory, **server_kw):
    """Boot a bare HttpServer with one trivial route, run the test coro
    against it, tear down."""

    async def main():
        srv = HttpServer(**server_kw)

        async def ping(_req):
            return Response.json({"pong": True})

        srv.route("GET", "/ping")(ping)
        srv.route("POST", "/echo")(ping)
        port = await srv.start("127.0.0.1", 0)
        try:
            return await test_coro_factory(port)
        finally:
            await srv.stop()

    return asyncio.run(main())


def test_slowloris_header_connection_reaped():
    """A client that never finishes its header block is disconnected after
    idle_timeout_s — it cannot hold a socket open indefinitely."""

    async def scenario(port):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET /ping HTTP/1.1\r\nHost: x")  # no terminator, ever
        await writer.drain()
        t0 = time.perf_counter()
        data = await asyncio.wait_for(reader.read(), 5.0)
        elapsed = time.perf_counter() - t0
        writer.close()
        return data, elapsed

    data, elapsed = _run_http(scenario, idle_timeout_s=0.3, body_timeout_s=0.3)
    assert data == b""  # closed without a response (slowloris peers don't read)
    assert elapsed < 3.0


def test_idle_keepalive_connection_reaped():
    """A completed request does not grant an immortal keep-alive socket."""

    async def scenario(port):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET /ping HTTP/1.1\r\nHost: x\r\n\r\n")
        await writer.drain()
        head = await reader.readuntil(b"\r\n\r\n")
        assert b" 200 " in head.split(b"\r\n", 1)[0]
        body_len = int(
            [l for l in head.split(b"\r\n") if l.lower().startswith(b"content-length")][0]
            .split(b":")[1]
        )
        await reader.readexactly(body_len)
        # now idle: server must close within the idle timeout
        data = await asyncio.wait_for(reader.read(), 5.0)
        writer.close()
        return data

    assert _run_http(scenario, idle_timeout_s=0.3) == b""


def test_slow_body_times_out_408():
    """Headers complete but the body trickles: 408, not an indefinite hold
    of the connection (and its MAX_BODY buffer)."""

    async def scenario(port):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(
            b"POST /echo HTTP/1.1\r\nHost: x\r\nContent-Type: application/json\r\n"
            b"Content-Length: 1000\r\n\r\n{\"a\":"
        )
        await writer.drain()
        data = await asyncio.wait_for(reader.read(), 5.0)
        writer.close()
        return data

    data = _run_http(scenario, idle_timeout_s=5.0, body_timeout_s=0.3)
    assert b" 408 " in data.split(b"\r\n", 1)[0]


def test_connection_cap_503():
    """Connections beyond max_connections get an immediate 503 + close;
    existing connections keep working."""

    async def scenario(port):
        held = []
        for _ in range(2):
            r, w = await asyncio.open_connection("127.0.0.1", port)
            held.append((r, w))
        # cap is 2: the third connection is refused with 503
        r3, w3 = await asyncio.open_connection("127.0.0.1", port)
        refused = await asyncio.wait_for(r3.read(), 5.0)
        w3.close()
        # a held connection still serves
        r, w = held[0]
        w.write(b"GET /ping HTTP/1.1\r\nHost: x\r\n\r\n")
        await w.drain()
        served = await asyncio.wait_for(r.readuntil(b"\r\n\r\n"), 5.0)
        for _, w in held:
            w.close()
        return refused, served

    refused, served = _run_http(scenario, max_connections=2, idle_timeout_s=5.0)
    assert b" 503 " in refused.split(b"\r\n", 1)[0]
    assert b" 200 " in served.split(b"\r\n", 1)[0]


# ------------------------------------------------------- load shedding


def test_dispatcher_sheds_when_queue_exceeds_timeout():
    """With an observed batch p50 that makes the queued work exceed the
    request timeout, excess submissions 503 immediately instead of waiting
    out the timeout for a guaranteed 504.  Arrivals at an empty queue are
    never shed."""

    async def main():
        metrics = Metrics()
        for _ in range(8):
            metrics.observe_batch(size=1, compute_s=0.5, queue_s=0.0)

        def slow_runner(_key, images):
            time.sleep(0.25)
            return [0] * len(images)

        d = BatchingDispatcher(
            slow_runner,
            max_batch=1,
            window_ms=0.0,
            request_timeout_s=0.4,
            metrics=metrics,
        )
        await d.start()
        tasks = [asyncio.create_task(d.submit(i, "k")) for i in range(8)]
        results = await asyncio.gather(*tasks, return_exceptions=True)
        await d.stop()
        return results

    results = asyncio.run(main())
    shed = [r for r in results if isinstance(r, errors.Overloaded)]
    assert shed, "deep queue produced no immediate 503s"
    assert not isinstance(results[0], errors.Overloaded), (
        "the first arrival saw an empty queue and must not shed"
    )
    assert all(
        isinstance(r, (int, errors.Overloaded, errors.RequestTimeout))
        for r in results
    )


def test_dispatcher_does_not_shed_cold():
    """Before any batch has been measured (p50 unknown), nothing sheds."""

    async def main():
        d = BatchingDispatcher(
            lambda _k, imgs: [1] * len(imgs),
            max_batch=2,
            window_ms=1.0,
            request_timeout_s=5.0,
            metrics=Metrics(),
        )
        await d.start()
        out = await asyncio.gather(*(d.submit(i, "k") for i in range(8)))
        await d.stop()
        return out

    assert asyncio.run(main()) == [1] * 8


# ------------------------------------------- readiness / discovery / dtype


class _Booted:
    """Minimal service-in-a-thread harness (does NOT force ready=True,
    unlike test_serving.ServiceFixture)."""

    def __init__(self, cfg):
        params = init_params(TINY, jax.random.PRNGKey(3))
        self.service = DeconvService(cfg, spec=TINY, params=params)
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self.port = None

    def _run(self):
        asyncio.set_event_loop(self._loop)

        async def boot():
            self.port = await self.service.start("127.0.0.1", 0)
            self._started.set()

        self._loop.run_until_complete(boot())
        self._loop.run_forever()

    def __enter__(self):
        self._thread.start()
        assert self._started.wait(10)
        return self

    def __exit__(self, *exc):
        fut = asyncio.run_coroutine_threadsafe(self.service.stop(), self._loop)
        fut.result(10)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(10)

    @property
    def base_url(self):
        return f"http://127.0.0.1:{self.port}"


def _tiny_cfg(**kw):
    return ServerConfig(
        image_size=16,
        max_batch=4,
        batch_window_ms=1.0,
        compilation_cache_dir="",
        warmup_all_buckets=False,
        **kw,
    )


def test_compute_routes_503_before_warmup():
    """VERDICT r2: ModelNotReady was defined but raised nowhere — pre-warmup
    requests silently paid compile latency.  Now: 503 until ready, 200
    after; health/metrics/discovery stay available throughout."""
    import httpx

    from tests.test_serving import _data_url

    with _Booted(_tiny_cfg()) as s:
        assert not s.service.ready
        r = httpx.post(
            s.base_url + "/",
            data={"file": _data_url(0), "layer": "b2c1"},
            timeout=30,
        )
        assert r.status_code == 503
        assert r.json()["error"] == "model_not_ready"
        r = httpx.post(s.base_url + "/v1/dream", data={"file": _data_url(0)}, timeout=30)
        assert r.status_code == 503
        # liveness/observability unaffected
        assert httpx.get(s.base_url + "/health-check", timeout=30).status_code == 200
        assert httpx.get(s.base_url + "/metrics", timeout=30).status_code == 200
        assert httpx.get(s.base_url + "/ready", timeout=30).status_code == 503

        s.service.warmup()
        assert httpx.get(s.base_url + "/ready", timeout=30).status_code == 200
        r = httpx.post(
            s.base_url + "/",
            data={"file": _data_url(0), "layer": "b2c1"},
            timeout=60,
        )
        assert r.status_code == 200, r.text


def test_models_discovery_endpoint():
    """GET /v1/models returns the registry plus the live bundle, so clients
    stop hardcoding layer names (VERDICT r2 item 6)."""
    import httpx

    with _Booted(_tiny_cfg()) as s:
        r = httpx.get(s.base_url + "/v1/models", timeout=30)
        assert r.status_code == 200
        models = r.json()["models"]
        names = {m["model"] for m in models}
        assert {"vgg16", "resnet50", "inception_v3"} <= names
        active = [m for m in models if m.get("active")]
        assert len(active) == 1
        assert active[0]["model"] == TINY.name
        assert "b2c1" in active[0]["layers"]


def test_cfg_dtype_changes_serving_path():
    """DECONV_DTYPE=bfloat16 must provably change the served computation
    (VERDICT r2: cfg.dtype was consumed only by bench.py)."""
    params = init_params(TINY, jax.random.PRNGKey(3))
    img = np.random.default_rng(0).normal(0, 30, (16, 16, 3)).astype(np.float32)

    def grid(cfg):
        svc = DeconvService(cfg, spec=TINY, params=params)
        return svc._run_batch(("b2c1", "all", 4, "grid"), [img])[0]["grid"]

    g32 = grid(_tiny_cfg())
    g32b = grid(_tiny_cfg())
    g16 = grid(_tiny_cfg(dtype="bfloat16"))
    np.testing.assert_array_equal(g32, g32b)  # fp32 path is deterministic
    assert g16.shape == g32.shape and g16.dtype == g32.dtype
    assert (g16 != g32).any(), "bfloat16 forward produced bit-identical output"


def test_profile_rearm_endpoint(tmp_path):
    """POST /v1/profile re-arms the capture budget; the next batch writes a
    trace (on-demand jax.profiler capture, SURVEY §5 tracing row)."""
    import httpx

    cfg = _tiny_cfg(profile_dir=str(tmp_path / "traces"))
    with _Booted(cfg) as s:
        s.service.warmup()
        s.service._profile_remaining = 0  # startup budget spent
        r = httpx.post(s.base_url + "/v1/profile", data={"batches": "2"}, timeout=30)
        assert r.status_code == 200 and r.json()["armed"] == 2
        img = np.zeros((16, 16, 3), np.float32)
        s.service._run_batch(("b2c1", "all", 4, "grid"), [img])
        assert s.service._profile_remaining == 1
        assert any(f.is_file() for f in (tmp_path / "traces").rglob("*"))


def test_profile_rearm_disabled_400():
    import httpx

    with _Booted(_tiny_cfg()) as s:
        r = httpx.post(s.base_url + "/v1/profile", data={"batches": "2"}, timeout=30)
        assert r.status_code == 400


def test_v1_deconv_sweep_over_http():
    """sweep=1 on /v1/deconv projects every layer from the requested one
    down — the reference's always-on behaviour (SURVEY §2.2.3) as an
    explicit opt-in over the wire."""
    import httpx

    from tests.test_serving import _data_url

    with _Booted(_tiny_cfg()) as s:
        s.service.ready = True
        r = httpx.post(
            s.base_url + "/v1/deconv",
            data={"file": _data_url(0), "layer": "b2c1", "sweep": "1"},
            timeout=120,
        )
        assert r.status_code == 200, r.text
        body = r.json()
        assert body["sweep"] is True
        # TINY from b2c1 down: b2c1, b1p, b1c2, b1c1 (input excluded)
        assert set(body["layers"]) == {"b2c1", "b1p", "b1c2", "b1c1"}
        for name, entry in body["layers"].items():
            assert len(entry["filters"]) == len(entry["images"])
            assert all(u.startswith("data:image/") for u in entry["images"])

        # single-layer requests on the same server still work (cache keys
        # must not collide between sweep and non-sweep programs)
        r = httpx.post(
            s.base_url + "/v1/deconv",
            data={"file": _data_url(0), "layer": "b2c1"},
            timeout=120,
        )
        assert r.status_code == 200 and "images" in r.json()


def test_dag_sweep_layers_forward_order_not_sorted_order():
    """sweep_layers must follow the forward (topological) order of the
    acts dict, NOT sorted-key order — jax pytree flattening sorts dict
    keys, which misorders names like mixed10 (between mixed1 and mixed2)
    and conv_pw_13_relu (before conv_pw_2_relu).  A sorted-order bug
    silently drops layers from the sweep set (r5 review finding)."""
    from deconv_api_tpu.serving.models import REGISTRY

    mb = REGISTRY["mobilenet_v1"]()
    got = mb.sweep_layers("conv_pw_3_relu")
    assert got == (
        "conv_pw_3_relu", "conv_pw_2_relu", "conv_pw_1_relu", "conv1_relu"
    ), got
    deep = mb.sweep_layers("conv_pw_12_relu")
    # deepest-first: contiguous conv_pw_12 .. conv_pw_1, then the stem
    assert deep == tuple(
        f"conv_pw_{i}_relu" for i in range(12, 0, -1)
    ) + ("conv1_relu",), deep

    inc = REGISTRY["inception_v3"]()
    assert inc.sweep_layers("mixed2") == ("mixed2", "mixed1", "mixed0")
    assert inc.sweep_layers("mixed10") == tuple(
        f"mixed{i}" for i in range(10, -1, -1)
    )


def test_dag_bundle_sweep_matches_single_layer_programs():
    """A DAG bundle's sweep visualizer (one shared forward, per-layer vjp
    seeds) must reproduce the per-layer single visualizers exactly: the
    zero cotangents in the other layers' slots may not perturb the seeded
    projection."""
    import jax
    import numpy as np

    from deconv_api_tpu.models.apply import spec_forward
    from deconv_api_tpu.models.spec import init_params
    from deconv_api_tpu.serving import models as m
    from tests.test_engine_parity import TINY

    params = init_params(TINY, jax.random.PRNGKey(3))
    bundle = m.ModelBundle(
        name="tiny_dag",
        params=params,
        image_size=16,
        preprocess=lambda x: x,
        layer_names=tuple(l.name for l in TINY.layers if l.kind != "input"),
        dream_layers=(),
        forward_fn=spec_forward(TINY),
    )
    assert bundle.sweep_layers("b2c1") == ("b2c1", "b1p", "b1c2", "b1c1")

    batch = np.asarray(
        jax.random.normal(jax.random.PRNGKey(9), (2, 16, 16, 3)), np.float32
    )
    swept = bundle.batched_visualizer("b2c1", "all", 4, sweep=True)(
        bundle.params, batch
    )
    assert set(swept) == {"b2c1", "b1p", "b1c2", "b1c1"}
    for name in swept:
        single = bundle.batched_visualizer(name, "all", 4)(bundle.params, batch)
        np.testing.assert_array_equal(
            np.asarray(swept[name]["indices"]), np.asarray(single[name]["indices"])
        )
        np.testing.assert_allclose(
            np.asarray(swept[name]["images"]),
            np.asarray(single[name]["images"]),
            rtol=1e-5, atol=1e-6, err_msg=name,
        )


def test_http_parser_fuzz_never_kills_server():
    """Seeded byte-level fuzz of the request parser: random garbage,
    truncated frames, hostile chunk framing.  Every connection must end in
    a clean response or close — and the server must stay alive throughout
    (the reference dies on malformed input via sys.exit, SURVEY §2.2.8)."""
    import random

    rng = random.Random(0xDEC0)
    pieces = [
        b"POST /echo HTTP/1.1\r\n", b"GET /ping HTTP/1.1\r\n", b"\r\n\r\n",
        b"Content-Length: 10\r\n", b"Content-Length: -5\r\n",
        b"Content-Length: zz\r\n", b"Transfer-Encoding: chunked\r\n",
        b"5\r\nhello\r\n", b"0\r\n\r\n", b"-1\r\n", b"ffff\r\n",
        b"Host: x\r\n", b"\x00\xff\xfe" * 40, b"A" * 512, b": : :\r\n",
        b"HTTP/1.1 200\r\n", b"\r\n",
    ]

    async def scenario(port):
        # enforce the per-connection contract, not just final liveness: any
        # unhandled exception in a connection task (e.g. a parser crash on
        # hostile framing) fails the test even though the server survives
        unhandled: list = []
        asyncio.get_running_loop().set_exception_handler(
            lambda loop, ctx: unhandled.append(ctx.get("message"))
        )

        async def one(payload: bytes):
            try:
                reader, writer = await asyncio.open_connection("127.0.0.1", port)
            except OSError:
                return
            try:
                writer.write(payload)
                await writer.drain()
                writer.write_eof()
                await asyncio.wait_for(reader.read(4096), 5)
            except (ConnectionResetError, BrokenPipeError, asyncio.TimeoutError):
                pass
            finally:
                writer.close()
                try:
                    await writer.wait_closed()
                except (ConnectionResetError, BrokenPipeError):
                    pass

        for _ in range(60):
            n = rng.randint(1, 6)
            payload = b"".join(rng.choice(pieces) for _ in range(n))
            await one(payload[: rng.randint(1, len(payload))])

        # the server survived the whole campaign and still answers
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET /ping HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n")
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(), 5)
        writer.close()
        return raw, unhandled

    raw, unhandled = _run_http(scenario, idle_timeout_s=1.0, body_timeout_s=1.0)
    assert b" 200 " in raw.split(b"\r\n", 1)[0]
    assert not unhandled, unhandled


def test_negative_content_length_400_not_crash():
    """Content-Length: -5 must be a clean 400 — readexactly(-5) used to
    raise an uncaught ValueError that killed the connection task (r3
    fuzz-review finding; mirrors the chunked path's negative-size guard)."""

    async def scenario(port):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(
            b"POST /echo HTTP/1.1\r\nHost: x\r\nContent-Length: -5\r\n\r\n"
        )
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(), 5)
        writer.close()
        return raw

    raw = _run_http(scenario, idle_timeout_s=1.0, body_timeout_s=1.0)
    assert b" 400 " in raw.split(b"\r\n", 1)[0]
    assert b"bad content-length" in raw


def test_profile_rearm_validation():
    """/v1/profile input validation: disabled without profile_dir; bad or
    out-of-range batch counts are clean 400s."""
    import httpx

    from deconv_api_tpu.config import ServerConfig
    from tests.test_serving import ServiceFixture

    cfg = ServerConfig(
        image_size=16, max_batch=2, batch_window_ms=1.0,
        compilation_cache_dir="",  # no profile_dir
    )
    with ServiceFixture(cfg) as s:
        r = httpx.post(s.base_url + "/v1/profile", data={"batches": "2"})
        assert r.status_code == 400
        assert "profiling disabled" in r.json()["detail"]

    import dataclasses, tempfile

    with tempfile.TemporaryDirectory() as td:
        cfg2 = dataclasses.replace(cfg, profile_dir=td)
        with ServiceFixture(cfg2) as s:
            for bad in ("0", "65", "pear"):
                r = httpx.post(s.base_url + "/v1/profile", data={"batches": bad})
                assert r.status_code == 400, (bad, r.text)
            r = httpx.post(s.base_url + "/v1/profile", data={"batches": "8"})
            assert r.status_code == 200 and r.json()["armed"] == 8
