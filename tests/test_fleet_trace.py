"""Fleet observability plane tests (round 19).

Covers the router flight recorder (attempt spans with backend
attribution, hedge legs as siblings with the loser's cancellation
point, failover hops, router-side error traces for the paths that used
to vanish), cross-hop propagation (``x-trace-hop`` stamping +
``hop_from`` grammar + backend annotation), ``GET /v1/debug/trace/{id}``
assembly into one merged timeline, ``GET /v1/metrics/fleet`` federation
(backend-label rewrite through the exposition lint, last-good staleness
fallback), the fixed-bucket latency histograms (bucket monotonicity
through the lint walker), the SLO burn-rate math under an injected
clock, and the ``trace_ring=0`` pin (a trace-off router allocates zero
per-request trace state).
"""

from __future__ import annotations

import asyncio
import json
import time

import httpx
import pytest

from deconv_api_tpu.serving import fleet
from deconv_api_tpu.serving.cache import canonical_digest
from deconv_api_tpu.serving.fleet import FleetRouter, _route_family
from deconv_api_tpu.serving.http import Request
from deconv_api_tpu.serving.metrics import (
    HIST_BUCKETS_S,
    Metrics,
    SloTracker,
    parse_slos,
    slo_prometheus,
)
from deconv_api_tpu.serving.trace import assemble_timeline, hop_from
from tests.test_metrics_exposition import lint_exposition


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _ready_200():
    return 200, {}, json.dumps({"ready": True}).encode()


def _probe_script(monkeypatch, names):
    async def fake(host, port, method, target, headers, body, timeout_s):
        return _ready_200()

    monkeypatch.setattr(fleet, "raw_request", fake)


def _post_req(body: bytes, path="/v1/deconv", headers=None, **kw) -> Request:
    return Request(
        method="POST", path=path, query={},
        headers={
            "content-type": "application/x-www-form-urlencoded",
            **(headers or {}),
        },
        body=body, id=kw.pop("id", "rid-obs"), **kw,
    )


def _key_for(body: bytes, path="/v1/deconv") -> str:
    return canonical_digest(
        f"fleet|{path}", "application/x-www-form-urlencoded", body
    )


def _owned_body(router, owner_name, path="/v1/deconv"):
    """A form body whose fleet digest lands on ``owner_name``."""
    for i in range(500):
        body = f"layer=c3&file=probe{i}".encode()
        if router.ring.owner(_key_for(body, path)) == owner_name:
            return body
    raise AssertionError("no body found for owner")


# ------------------------------------------------------------- hop grammar


def test_hop_from_grammar():
    assert hop_from("1:primary") == (1, "primary")
    assert hop_from("2:hedge") == (2, "hedge")
    assert hop_from("17:failover") == (17, "failover")
    assert hop_from("3:replica") == (3, "replica")
    assert hop_from("4:canary") == (4, "canary")
    for bad in (
        None, "", "primary", "0x1:hedge", "1:unknown", "1:HEDGE",
        "1:hedge:extra", "-1:primary", "1234:primary", "1 :primary",
    ):
        assert hop_from(bad) is None, bad


def test_route_family_is_a_closed_vocabulary():
    assert _route_family("/v1/deconv") == "/v1/deconv"
    assert _route_family("/v1/jobs/abc123/events") == "/v1/jobs/{id}"
    # attacker-chosen paths collapse to one label value: label
    # cardinality is bounded by construction
    assert _route_family("/v1/%s" % ("x" * 64)) == "other"
    assert _route_family("/../../etc/passwd") == "other"


# ------------------------------------------------- histograms + SLO math


def test_histogram_bucket_monotonicity_through_the_lint():
    m = Metrics()
    for v in (0.001, 0.004, 0.012, 0.09, 0.4, 3.0, 250.0):
        m.observe_hist(
            "request_duration_seconds", ("route", "qos_class"),
            ("/v1/deconv", "default"), v,
        )
    text = m.prometheus()
    families, samples = lint_exposition(text)  # checks le-monotonicity,
    # +Inf == _count, _sum presence
    assert families["deconv_request_duration_seconds"] == "histogram"
    block = 'route="/v1/deconv",qos_class="default"'
    # cumulative counts at a few pinned bounds
    assert samples[
        ("deconv_request_duration_seconds_bucket", f'{block},le="0.005"')
    ] == 2.0
    assert samples[
        ("deconv_request_duration_seconds_bucket", f'{block},le="0.1"')
    ] == 4.0
    assert samples[
        ("deconv_request_duration_seconds_bucket", f'{block},le="+Inf"')
    ] == 7.0
    assert samples[
        ("deconv_request_duration_seconds_count", block)
    ] == 7.0
    # the in-process accessor sees the same observation set
    series = m.hist_series("request_duration_seconds")
    h = series[("/v1/deconv", "default")]
    assert h["count"] == 7
    assert sum(h["buckets"]) == 7
    assert h["buckets"][len(HIST_BUCKETS_S)] == 1  # the 250 s overflow
    # label-tuple discipline is enforced like inc_labeled's
    with pytest.raises(ValueError):
        m.observe_hist(
            "request_duration_seconds", ("route",), ("/x",), 0.1
        )
    with pytest.raises(TypeError):
        m.observe_hist(
            "request_duration_seconds", ("route", "qos_class"), "/x", 0.1
        )


def test_slo_burn_rate_math_under_injected_clock():
    clock = _FakeClock()
    t = SloTracker("api", 100.0, 99.0, clock=clock)
    # 2 bad of 10 in the window: error rate 0.2, budget 0.01 -> burn 20
    for _ in range(8):
        t.observe(0.050, 200)
    t.observe(0.500, 200)  # over threshold
    t.observe(0.001, 500)  # fast 500 still breaches
    assert t.requests_total == 10 and t.breaches_total == 2
    assert t.burn_rates() == {"5m": 20.0, "1h": 20.0}
    # 6 minutes later: the 5m window is clean, the 1h window remembers
    clock.t += 360.0
    for _ in range(10):
        t.observe(0.010, 200)
    rates = t.burn_rates()
    assert rates["5m"] == 0.0
    assert rates["1h"] == pytest.approx((2 / 20) / 0.01)
    # 2 hours later both windows are empty -> zero burn, totals keep
    clock.t += 7200.0
    assert t.burn_rates() == {"5m": 0.0, "1h": 0.0}
    assert t.requests_total == 20 and t.breaches_total == 2
    # exposition block lints next to a registry
    text = Metrics().prometheus() + slo_prometheus([t], "deconv")
    families, samples = lint_exposition(text)
    assert families["deconv_slo_burn_rate"] == "gauge"
    assert samples[("deconv_slo_requests_total", 'slo="api"')] == 20.0
    assert samples[("deconv_slo_breaches_total", 'slo="api"')] == 2.0


def test_slo_spec_validation():
    trackers = parse_slos("api=250:99,fast=100:99.9:/v1/deconv")
    assert [t.name for t in trackers] == ["api", "fast"]
    assert trackers[1].matches("/v1/deconv")
    assert not trackers[1].matches("/v1/dream")
    assert trackers[0].matches("/anything")
    for bad in (
        "noequals", "a=x:y", "a=100", "a=100:0", "a=100:100",
        "a=-5:99", "a=100:99:relative", "a=1:9,a=2:9", "=100:99",
    ):
        with pytest.raises(ValueError):
            parse_slos(bad)


# ------------------------------------------------- router flight recorder


def test_failover_trace_two_attempts_two_backends(monkeypatch):
    router = FleetRouter(["b0:8000", "b1:8001"], eject_threshold=5)
    _probe_script(monkeypatch, None)
    seen: list[tuple[str, str | None]] = []
    dead: set[str] = set()

    async def fake(host, port, method, target, headers, body, timeout_s):
        name = f"{host}:{port}"
        seen.append((name, headers.get("x-trace-hop")))
        if name in dead:
            raise fleet._BackendError("connection refused")
        return 200, {}, name.encode()

    async def go():
        await router.probe_once()
        body = _owned_body(router, "b0:8000")
        dead.add("b0:8000")
        monkeypatch.setattr(fleet, "raw_request", fake)
        seen.clear()
        resp = await router._proxy(_post_req(body, id="rid-fo"))
        assert resp.status == 200
        assert resp.headers["x-backend"] == "b1:8001"
        # the wire carried per-attempt hop stamps
        assert seen == [
            ("b0:8000", "1:primary"), ("b1:8001", "2:failover"),
        ]
        # the recorded trace shows both attempts, backend-attributed
        [tr] = router.recorder.query(trace_id="rid-fo")
        attempts = [s for s in tr["spans"] if s["name"] == "attempt"]
        assert [
            (s["backend"], s["hop"], s["purpose"]) for s in attempts
        ] == [("b0:8000", 1, "primary"), ("b1:8001", 2, "failover")]
        assert "error" in attempts[0] and attempts[1]["status"] == 200
        assert tr["backend"] == "b1:8001" and tr["status"] == 200
        picks = [s for s in tr["spans"] if s["name"] == "ring_pick"]
        assert len(picks) == 2

    asyncio.run(go())


def _seed_fleet_latency(router, ms=10.0, n=4):
    m = next(iter(router.members.values()))
    for _ in range(n):
        router._observe_latency(m, ms)


def test_hedge_trace_sibling_spans_and_loser_cancellation(monkeypatch):
    router = FleetRouter(
        ["b0:8000", "b1:8001"], slow_min_samples=2,
        hedge_min_delay_ms=20.0,
    )
    _probe_script(monkeypatch, None)
    stall: set[str] = set()

    async def fake(host, port, method, target, headers, body, timeout_s):
        name = f"{host}:{port}"
        if name in stall:
            await asyncio.sleep(30.0)
        return 200, {}, name.encode()

    async def go():
        await router.probe_once()
        body = _owned_body(router, "b0:8000")
        _seed_fleet_latency(router)
        monkeypatch.setattr(fleet, "raw_request", fake)
        stall.add("b0:8000")
        resp = await router._proxy(_post_req(body, id="rid-hedge"))
        assert resp.status == 200
        assert resp.headers["x-backend"] == "b1:8001"
        [tr] = router.recorder.query(trace_id="rid-hedge")
        assert tr["hedge_fired"] is True
        assert tr["hedge_backend"] == "b1:8001"
        attempts = {
            s["purpose"]: s
            for s in tr["spans"]
            if s["name"] == "attempt"
        }
        # both legs are sibling spans: the winner with its status, the
        # loser ending at its CANCELLATION point — recorded before the
        # trace snapshot, so it cannot vanish from the ring
        assert attempts["hedge"]["backend"] == "b1:8001"
        assert attempts["hedge"]["status"] == 200
        assert attempts["hedge"]["winner"] is True
        assert attempts["hedge"]["hop"] == 2
        loser = attempts["primary"]
        assert loser["backend"] == "b0:8000"
        assert loser["cancelled"] is True and loser["hop"] == 1
        # the loser's span ended around the hedge decision, not 30 s out
        assert loser["ms"] < 5000

    asyncio.run(go())


def test_failover_after_exhausted_hedge_not_marked_winner(monkeypatch):
    """A hedge that exhausts (both legs infra-fail) annotates
    hedge_fired on the TRACE; the non-hedged failover attempt that
    then succeeds must not inherit a winner mark — it never raced."""
    router = FleetRouter(
        ["b0:8000", "b1:8001", "b2:8002"], slow_min_samples=2,
        hedge_min_delay_ms=10.0, eject_threshold=5,
    )
    _probe_script(monkeypatch, None)

    async def go():
        await router.probe_once()
        body = _owned_body(router, "b0:8000")
        key = _key_for(body)
        o0, o1, o2 = router.ring.owners(key)
        behavior = {}

        async def fake(host, port, method, target, headers, body_,
                       timeout_s):
            name = f"{host}:{port}"
            delay, outcome = behavior[name]
            if delay:
                await asyncio.sleep(delay)
            if outcome == "fail":
                raise fleet._BackendError(f"{name}: boom")
            return 200, {}, name.encode()

        behavior[o0] = (0.2, "fail")   # slow enough to trigger a hedge
        behavior[o1] = (0.0, "fail")   # the hedge leg dies too
        behavior[o2] = (0.0, "ok")     # the plain failover serves
        _seed_fleet_latency(router)
        monkeypatch.setattr(fleet, "raw_request", fake)
        resp = await router._proxy(_post_req(body, id="rid-exh"))
        assert resp.status == 200
        assert resp.headers["x-backend"] == o2
        assert router.metrics.counter("hedges_fired_total") == 1
        [tr] = router.recorder.query(trace_id="rid-exh")
        assert tr["hedge_fired"] is True
        by_purpose = {
            s["purpose"]: s
            for s in tr["spans"]
            if s["name"] == "attempt"
        }
        assert "error" in by_purpose["primary"]
        assert "error" in by_purpose["hedge"]
        ok = by_purpose["failover"]
        assert ok["backend"] == o2 and ok["status"] == 200
        assert "winner" not in ok

    asyncio.run(go())


def test_deadline_at_router_records_error_trace_without_backend(
    monkeypatch,
):
    router = FleetRouter(["b0:8000"], eject_threshold=5)
    _probe_script(monkeypatch, None)

    async def never(host, port, method, target, headers, body, timeout_s):
        raise AssertionError("no backend may be contacted")

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", never)
        req = _post_req(
            b"layer=c3", id="rid-dead",
            deadline=time.perf_counter() - 1.0,
        )
        resp = await router._proxy(req)
        assert resp.status == 504
        assert "x-backend" not in resp.headers
        # the 504 that used to vanish without a trace now sits in the
        # error ring, annotated, with ZERO attempt spans
        errs = router.recorder.query(error=True)
        [tr] = [t for t in errs if t["id"] == "rid-dead"]
        assert tr["deadline_expired"] is True
        assert tr["status"] == 504 and tr["error"] == "deadline_expired"
        assert not [s for s in tr["spans"] if s["name"] == "attempt"]

    asyncio.run(go())


def test_unavailable_records_error_trace_with_tried_attempts(monkeypatch):
    router = FleetRouter(["b0:8000", "b1:8001"], eject_threshold=5)
    _probe_script(monkeypatch, None)

    async def refuse(host, port, method, target, headers, body, timeout_s):
        raise fleet._BackendError("connection refused")

    async def go():
        await router.probe_once()
        body = _owned_body(router, "b0:8000")
        monkeypatch.setattr(fleet, "raw_request", refuse)
        resp = await router._proxy(_post_req(body, id="rid-unavail"))
        assert resp.status == 502
        errs = router.recorder.query(error=True)
        [tr] = [t for t in errs if t["id"] == "rid-unavail"]
        assert tr["error"] == "backend_unavailable"
        attempts = [s for s in tr["spans"] if s["name"] == "attempt"]
        # both ring owners were tried and both are attributable
        assert {s["backend"] for s in attempts} == {"b0:8000", "b1:8001"}
        assert all("error" in s for s in attempts)

    asyncio.run(go())


def test_trace_off_router_allocates_zero_per_request_trace_state(
    monkeypatch,
):
    router = FleetRouter(["b0:8000"], trace_ring=0, eject_threshold=5)
    assert router.recorder is None
    _probe_script(monkeypatch, None)

    async def ok(host, port, method, target, headers, body, timeout_s):
        return 200, {}, b"{}"

    def boom(*a, **k):
        raise AssertionError("RequestTrace built with tracing off")

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", ok)
        monkeypatch.setattr(fleet, "RequestTrace", boom)
        resp = await router._proxy(_post_req(b"layer=c3", id="rid-off"))
        assert resp.status == 200
        get = Request(
            method="GET", path="/v1/models", query={}, headers={},
            body=b"", id="rid-off2",
        )
        assert (await router._proxy(get)).status == 200
        # the debug surfaces answer 400, mirroring the backend contract
        dbg = await router._debug_requests(
            Request(
                method="GET", path="/v1/debug/requests", query={},
                headers={}, body=b"", id="r",
            )
        )
        assert dbg.status == 400
        asm = await router._debug_trace(
            Request(
                method="GET", path="/v1/debug/trace/rid-off", query={},
                headers={}, body=b"", id="r",
            )
        )
        assert asm.status == 400

    asyncio.run(go())


# ---------------------------------------------------------- assembly


def test_debug_trace_assembles_backend_sides(monkeypatch):
    router = FleetRouter(["b0:8000", "b1:8001"], slow_min_samples=2,
                         hedge_min_delay_ms=20.0)
    _probe_script(monkeypatch, None)
    stall: set[str] = set()
    backend_traces = {
        "b0:8000": {
            "id": "rid-asm", "route": "/v1/deconv", "ts": 0.0,
            "status": None, "total_ms": None, "hop": 1,
            "hop_purpose": "primary",
            "spans": [{"name": "decode", "start_ms": 1.0, "ms": 2.0}],
        },
        "b1:8001": {
            "id": "rid-asm", "route": "/v1/deconv", "ts": 0.05,
            "status": 200, "total_ms": 9.0, "hop": 2,
            "hop_purpose": "hedge",
            "spans": [{"name": "device", "start_ms": 2.0, "ms": 5.0}],
        },
    }

    async def fake(host, port, method, target, headers, body, timeout_s):
        name = f"{host}:{port}"
        if target.startswith("/v1/debug/requests"):
            return 200, {}, json.dumps(
                {"requests": [backend_traces[name]]}
            ).encode()
        if name in stall:
            await asyncio.sleep(30.0)
        return 200, {}, name.encode()

    async def go():
        await router.probe_once()
        body = _owned_body(router, "b0:8000")
        _seed_fleet_latency(router)
        monkeypatch.setattr(fleet, "raw_request", fake)
        stall.add("b0:8000")
        resp = await router._proxy(_post_req(body, id="rid-asm"))
        assert resp.status == 200
        # fix the fake backend timestamps relative to the real router
        # trace's wall clock so the re-anchoring is deterministic
        [rt] = router.recorder.query(trace_id="rid-asm")
        backend_traces["b0:8000"]["ts"] = rt["ts"]
        backend_traces["b1:8001"]["ts"] = rt["ts"] + 0.05
        out = await router._debug_trace(
            Request(
                method="GET", path="/v1/debug/trace/rid-asm", query={},
                headers={}, body=b"", id="r",
            )
        )
        assert out.status == 200
        doc = json.loads(out.body)
        assert set(doc["backends"]) == {"b0:8000", "b1:8001"}
        assert doc["missing"] == []
        sources = {s["source"] for s in doc["timeline"]}
        assert sources == {"router", "b0:8000", "b1:8001"}
        # both legs visible: the hedge winner's server side with its
        # hop annotation, and the loser's router-side cancellation
        summaries = [
            s for s in doc["timeline"] if s["name"] == "backend_request"
        ]
        assert {
            (s["source"], s.get("hop"), s.get("hop_purpose"))
            for s in summaries
        } == {("b0:8000", 1, "primary"), ("b1:8001", 2, "hedge")}
        cancelled = [
            s for s in doc["timeline"]
            if s["name"] == "attempt" and s.get("cancelled")
        ]
        assert len(cancelled) == 1
        assert cancelled[0]["source"] == "router"
        assert cancelled[0]["backend"] == "b0:8000"
        # the hedge leg's backend device span is re-anchored AFTER the
        # router's trace start (offset ~50ms + its own 2ms)
        device = next(
            s for s in doc["timeline"] if s["name"] == "device"
        )
        assert device["start_ms"] == pytest.approx(52.0, abs=5.0)
        # an unknown id is an honest 404, not a 502
        miss = await router._debug_trace(
            Request(
                method="GET", path="/v1/debug/trace/never-seen",
                query={}, headers={}, body=b"", id="r",
            )
        )
        assert miss.status == 404

    asyncio.run(go())


def test_assemble_timeline_orders_and_reanchors():
    router_trace = {
        "id": "x", "ts": 1000.0,
        "spans": [
            {"name": "attempt", "start_ms": 0.5, "ms": 30.0,
             "backend": "b0:8000"},
        ],
    }
    backend = {
        "id": "x", "ts": 1000.010, "status": 200, "total_ms": 20.0,
        "hop": 1, "hop_purpose": "primary",
        "spans": [{"name": "device", "start_ms": 3.0, "ms": 9.0}],
    }
    tl = assemble_timeline(router_trace, {"b0:8000": [backend]})
    assert [s["name"] for s in tl] == [
        "attempt", "backend_request", "device",
    ]
    assert tl[1]["start_ms"] == pytest.approx(10.0)
    assert tl[2]["start_ms"] == pytest.approx(13.0)
    assert tl[0]["source"] == "router"
    assert tl[2]["source"] == "b0:8000"


# -------------------------------------------------------- federation


def _backend_metrics_text(hits: int) -> str:
    m = Metrics()
    m.observe_request(0.01)
    m.observe_request(0.2, error_code='we"ird')
    m.inc_counter("cache_hits_total", hits)
    m.inc_labeled("faults_injected_total", "site", "device.dispatch_error")
    m.observe_hist(
        "request_duration_seconds", ("route", "qos_class"),
        ("/", "default"), 0.02,
    )
    m.set_gauge("cache_resident_bytes", 123)
    return m.prometheus()


def test_metrics_federation_label_rewrite_round_trips_the_lint(
    monkeypatch,
):
    clock = _FakeClock()
    router = FleetRouter(
        ["b0:8000", "b1:8001"], eject_threshold=5, clock=clock
    )
    down: set[str] = set()

    async def fake(host, port, method, target, headers, body, timeout_s):
        name = f"{host}:{port}"
        if target == "/v1/metrics":
            if name in down:
                raise fleet._BackendError("connection refused")
            return 200, {}, _backend_metrics_text(
                3 if name == "b0:8000" else 5
            ).encode()
        return _ready_200()

    async def go():
        monkeypatch.setattr(fleet, "raw_request", fake)
        await router.probe_once()
        resp = await router._metrics_fleet(
            Request(
                method="GET", path="/v1/metrics/fleet", query={},
                headers={}, body=b"", id="r",
            )
        )
        text = resp.body.decode()
        families, samples = lint_exposition(text)
        # ONE TYPE header per family across both members; every sample
        # gained the backend label with values preserved
        assert families["deconv_cache_hits_total"] == "counter"
        assert families["deconv_request_duration_seconds"] == "histogram"
        assert samples[
            ("deconv_cache_hits_total", 'backend="b0:8000"')
        ] == 3.0
        assert samples[
            ("deconv_cache_hits_total", 'backend="b1:8001"')
        ] == 5.0
        # multi-label + hostile-value lines keep their labels intact
        # behind the spliced backend label
        assert samples[
            (
                "deconv_faults_injected_total",
                'backend="b0:8000",site="device.dispatch_error"',
            )
        ] == 1.0
        assert any(
            name == "deconv_errors_total" and 'we\\"ird' in labels
            for name, labels in samples
        )
        # histogram buckets federate per backend (the lint already
        # verified le-monotonicity per labelset)
        assert samples[
            (
                "deconv_request_duration_seconds_count",
                'backend="b1:8001",route="/",qos_class="default"',
            )
        ] == 1.0
        # rollups + scrape health
        assert samples[
            ("fleet_counter_sum", 'family="deconv_cache_hits_total"')
        ] == 8.0
        assert samples[("fleet_scrape_ok", 'backend="b0:8000"')] == 1.0
        assert samples[("fleet_backends_scraped", "")] == 2.0
        assert samples[
            ("fleet_scrape_staleness_seconds", 'backend="b0:8000"')
        ] == 0.0
        # a member going dark re-exports its LAST-GOOD text with the
        # staleness gauge climbing — not a counter reset
        down.add("b1:8001")
        clock.t += 30.0
        resp2 = await router._metrics_fleet(
            Request(
                method="GET", path="/v1/metrics/fleet", query={},
                headers={}, body=b"", id="r",
            )
        )
        families2, samples2 = lint_exposition(resp2.body.decode())
        assert samples2[
            ("deconv_cache_hits_total", 'backend="b1:8001"')
        ] == 5.0
        assert samples2[("fleet_scrape_ok", 'backend="b1:8001"')] == 0.0
        assert samples2[
            ("fleet_scrape_staleness_seconds", 'backend="b1:8001"')
        ] == 30.0
        assert samples2[("fleet_backends_scraped", "")] == 2.0

    asyncio.run(go())


def test_router_histogram_and_slo_fed_by_proxy(monkeypatch):
    router = FleetRouter(
        ["b0:8000"], eject_threshold=5, slos="api=1000:99",
    )
    _probe_script(monkeypatch, None)

    async def ok(host, port, method, target, headers, body, timeout_s):
        return 200, {}, b"{}"

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", ok)
        for _ in range(3):
            resp = await router._proxy(_post_req(b"layer=c3"))
            assert resp.status == 200
        series = router.metrics.hist_series("request_duration_seconds")
        assert series[("/v1/deconv",)]["count"] == 3
        [t] = router.slos
        assert t.requests_total == 3 and t.breaches_total == 0
        # the router's own /metrics carries the histogram + slo block
        # + recorder block, and it all lints as one exposition
        out = await router._metrics_route(None)
        families, samples = lint_exposition(out.body.decode())
        assert families["router_request_duration_seconds"] == "histogram"
        assert families["router_slo_burn_rate"] == "gauge"
        assert families["router_traces_total"] == "counter"
        assert samples[("router_slo_requests_total", 'slo="api"')] == 3.0
        # /readyz carries the slo block
        rz = await router._readyz(None)
        doc = json.loads(rz.body)
        assert doc["slo"]["api"]["ok"] is True

    asyncio.run(go())


# --------------------------------------------------------------- e2e


def test_e2e_cross_hop_trace_assembly_over_real_backends():
    """A real request through a real router: the backend's trace
    carries the hop annotation the router stamped, and the router's
    /v1/debug/trace/{id} joins both sides into one timeline whose
    backend spans (decode/device/encode) sit inside the router's
    attempt window."""
    from tests.test_fleet import FleetFixture, _data_url

    with FleetFixture(n_backends=2) as f:
        rid = "fleet-trace-e2e-1"
        resp = httpx.post(
            f.router_url + "/",
            data={"file": _data_url(31), "layer": "b2c1"},
            headers={"x-request-id": rid},
            timeout=120,
        )
        assert resp.status_code == 200, resp.text
        backend = resp.headers["x-backend"]
        # the backend's own flight recorder annotated the hop context
        direct = httpx.get(
            f"http://{backend}/v1/debug/requests", params={"id": rid},
            timeout=10,
        )
        [btr] = direct.json()["requests"]
        assert btr["hop"] == 1 and btr["hop_purpose"] == "primary"
        # assembly joins the router + backend sides
        out = httpx.get(
            f.router_url + f"/v1/debug/trace/{rid}", timeout=10
        )
        assert out.status_code == 200, out.text
        doc = out.json()
        assert doc["id"] == rid
        assert backend in doc["backends"]
        assert doc["missing"] == []
        names = {s["name"] for s in doc["timeline"]}
        assert "attempt" in names  # the router side
        assert "backend_request" in names  # the backend summary
        # server-side pipeline spans made it into the merged view
        assert names & {"decode", "device", "dispatch", "encode"}
        att = next(
            s for s in doc["timeline"]
            if s["name"] == "attempt" and s["source"] == "router"
        )
        assert att["backend"] == backend and att["status"] == 200
        summary = next(
            s for s in doc["timeline"] if s["name"] == "backend_request"
        )
        # wall clocks of two processes on one host: the backend's
        # server-side life must sit inside the router's attempt window
        # (generous skew allowance — same machine)
        assert abs(summary["start_ms"] - att["start_ms"]) < 1000.0
        # the federation endpoint sees both backends with one TYPE per
        # family and a true histogram to aggregate
        fed = httpx.get(f.router_url + "/v1/metrics/fleet", timeout=10)
        families, samples = lint_exposition(fed.text)
        assert families["deconv_request_duration_seconds"] == "histogram"
        for p in f.ports:
            assert samples[
                ("fleet_scrape_ok", f'backend="127.0.0.1:{p}"')
            ] == 1.0
