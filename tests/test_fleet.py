"""Fleet tier tests (round 14, serving/fleet.py): hash-ring properties,
health-gated membership lifecycle, and end-to-end routing over real
backend services — byte parity, request-id continuity, peer cache fill."""

import asyncio
import base64
import json
import threading
import time

import httpx
import numpy as np
import pytest

import jax

from deconv_api_tpu import errors
from deconv_api_tpu.config import ServerConfig
from deconv_api_tpu.models.spec import init_params
from deconv_api_tpu.serving import fleet
from deconv_api_tpu.serving.app import DeconvService
from deconv_api_tpu.serving.fleet import (
    BackendMember,
    FleetRouter,
    HashRing,
)
from deconv_api_tpu.serving.http import Request
from deconv_api_tpu.serving.trace import RID_RE
from tests.test_engine_parity import TINY


# ------------------------------------------------------------------- ring


def _keys(n: int) -> list[str]:
    import random

    return [f"{random.Random(i).getrandbits(160):040x}" for i in range(n)]


def test_ring_deterministic_and_order_independent():
    members = ["h0:8000", "h1:8001", "h2:8002", "h3:8003"]
    a = HashRing(members, 64)
    b = HashRing(list(reversed(members)), 64)
    ks = _keys(512)
    assert [a.owner(k) for k in ks] == [b.owner(k) for k in ks]
    # stable across instances (pure function of names + key)
    c = HashRing(members, 64)
    assert [a.owner(k) for k in ks] == [c.owner(k) for k in ks]


def test_ring_evenness_across_64_vnodes():
    members = [f"h{i}:80{i:02d}" for i in range(4)]
    ring = HashRing(members, 64)
    ks = _keys(8000)
    from collections import Counter

    counts = Counter(ring.owner(k) for k in ks)
    assert set(counts) == set(members)  # nobody starved
    mean = len(ks) / len(members)
    assert max(counts.values()) / mean <= 1.35
    assert min(counts.values()) / mean >= 0.65


def test_ring_bounded_movement_on_remove():
    members = [f"h{i}:80{i:02d}" for i in range(4)]
    full = HashRing(members, 64)
    less = HashRing(members[:3], 64)
    ks = _keys(6000)
    moved_collateral = lost = 0
    for k in ks:
        was = full.owner(k)
        now = less.owner(k)
        if was == members[3]:
            lost += 1
        elif was != now:
            moved_collateral += 1
    # consistent hashing's defining property: ONLY the removed member's
    # keys move; every other key keeps its owner
    assert moved_collateral == 0
    assert 0 < lost / len(ks) <= 1.5 / 4


def test_ring_bounded_movement_on_add():
    members = [f"h{i}:80{i:02d}" for i in range(4)]
    ring = HashRing(members, 64)
    grown = HashRing(members + ["h4:8004"], 64)
    ks = _keys(6000)
    remapped = sum(1 for k in ks if ring.owner(k) != grown.owner(k))
    # ~1/(N+1) of keys move to the new member; vnodes bound the variance
    assert 0.5 / 5 <= remapped / len(ks) <= 1.5 / 5
    # everything that moved moved TO the new member
    assert all(
        grown.owner(k) == "h4:8004"
        for k in ks
        if ring.owner(k) != grown.owner(k)
    )


def test_ring_empty_and_owners_walk():
    assert HashRing((), 64).owner("ab" * 20) is None
    ring = HashRing(["a:1", "b:2", "c:3"], 32)
    for k in _keys(64):
        walk = ring.owners(k)
        assert walk[0] == ring.owner(k)
        assert sorted(walk) == ["a:1", "b:2", "c:3"]  # all distinct members


def test_backend_member_name_validation():
    for bad in ("nohost", "http://h:80", "h:0", "h:99999", "h:80/x", "h :80"):
        with pytest.raises(ValueError):
            BackendMember(bad)
    m = BackendMember("node-3.rack_1:8080")
    assert (m.host, m.port) == ("node-3.rack_1", 8080)


# -------------------------------------------------- membership lifecycle


class _FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _router(clock, **kw):
    kw.setdefault("eject_threshold", 2)
    kw.setdefault("cooldown_s", 5.0)
    return FleetRouter(
        ["b0:8000", "b1:8001"], clock=clock, **kw
    )


def _probe_script(monkeypatch, responses):
    """monkeypatch fleet.raw_request with a per-backend response script:
    responses[name] is a callable -> (status, headers, body) or raises."""

    async def fake(host, port, method, target, headers, body, timeout_s):
        return responses[f"{host}:{port}"]()

    monkeypatch.setattr(fleet, "raw_request", fake)


def _ready_200():
    return 200, {}, json.dumps({"ready": True}).encode()


def _draining_503():
    return 503, {}, json.dumps(
        {"ready": False, "checks": {"not_draining": False, "warmed": True}}
    ).encode()


def _down():
    raise fleet._BackendError("connection refused")


def test_health_gate_admit_eject_and_half_open_readmit(monkeypatch):
    clock = _FakeClock()
    router = _router(clock)
    script = {"b0:8000": _ready_200, "b1:8001": _ready_200}
    _probe_script(monkeypatch, script)

    async def go():
        await router.probe_once()
        assert {m.name for m in router.members.values() if m.in_ring} == {
            "b0:8000", "b1:8001",
        }
        # b1 starts failing: first failure keeps it in the ring (a blip
        # is not death), the threshold'th ejects it
        script["b1:8001"] = _down
        await router.probe_once()
        assert router.members["b1:8001"].in_ring
        await router.probe_once()
        m = router.members["b1:8001"]
        assert m.state == "ejected" and not m.in_ring
        assert router.ring.members == ("b0:8000",)
        # cooling: probes are skipped entirely (no half-open claim yet)
        script["b1:8001"] = _ready_200
        await router.probe_once()
        assert router.members["b1:8001"].state == "ejected"
        # cooldown elapses -> exactly one half-open probe -> re-admit
        clock.t += 5.1
        await router.probe_once()
        assert router.members["b1:8001"].state == "healthy"
        assert router.ring.members == ("b0:8000", "b1:8001")

    asyncio.run(go())


def test_health_gate_failed_half_open_probe_reopens(monkeypatch):
    clock = _FakeClock()
    router = _router(clock)
    script = {"b0:8000": _ready_200, "b1:8001": _down}
    _probe_script(monkeypatch, script)

    async def go():
        await router.probe_once()
        await router.probe_once()
        assert router.members["b1:8001"].state == "ejected"
        clock.t += 5.1  # half-open window opens...
        await router.probe_once()  # ...probe runs, still down: reopen
        assert router.members["b1:8001"].state == "ejected"
        # a fresh cooldown is required before the next probe
        clock.t += 2.0
        await router.probe_once()
        assert router.members["b1:8001"].state == "ejected"
        script["b1:8001"] = _ready_200
        clock.t += 3.2
        await router.probe_once()
        assert router.members["b1:8001"].state == "healthy"

    asyncio.run(go())


def test_health_gate_drain_leaves_gracefully(monkeypatch):
    clock = _FakeClock()
    router = _router(clock)
    script = {"b0:8000": _ready_200, "b1:8001": _ready_200}
    _probe_script(monkeypatch, script)

    async def go():
        await router.probe_once()
        script["b1:8001"] = _draining_503
        await router.probe_once()
        m = router.members["b1:8001"]
        # graceful: out of the ring IMMEDIATELY (no threshold wait), no
        # breaker state accrued
        assert m.state == "draining" and not m.in_ring
        assert m.breaker.state_name == "closed"
        assert router.ring.members == ("b0:8000",)
        # the restarted backend rejoins on its first healthy probe
        script["b1:8001"] = _ready_200
        await router.probe_once()
        assert m.state == "healthy" and m.in_ring

    asyncio.run(go())


def test_passive_forward_failures_eject(monkeypatch):
    clock = _FakeClock()
    router = _router(clock)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )

    async def go():
        await router.probe_once()
        m = router.members["b1:8001"]
        router._note_forward_result(m, ok=False)
        assert m.in_ring  # one blip
        router._note_forward_result(m, ok=False)
        assert m.state == "ejected" and router.ring.members == ("b0:8000",)
        # a success resets the streak for healthy members
        b0 = router.members["b0:8000"]
        router._note_forward_result(b0, ok=False)
        router._note_forward_result(b0, ok=True)
        router._note_forward_result(b0, ok=False)
        assert b0.in_ring

    asyncio.run(go())


def test_rebalance_accounting_and_peer_hint(monkeypatch):
    clock = _FakeClock()
    router = _router(clock)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )

    async def go():
        await router.probe_once()
        # boot churn is NOT a rebalance: the staggered admission sweep
        # must leave no previous-ring window (nothing has served yet,
        # so there is nothing to fill from and nothing "moved")
        assert router._prev_ring is None
        # mark the ring as serving (rebalance accounting only engages
        # once there is traffic whose cache residency could move)
        router.members["b0:8000"].requests_total += 1
        ks = _keys(400)
        owner = {k: router.ring.owner(k) for k in ks}
        # eject b1: its keys move to b0 and carry NO hint (a crashed
        # peer cannot serve a fill) — but each moved key still counts
        # once toward router_rebalanced_keys_total
        m = router.members["b1:8001"]
        router._note_forward_result(m, ok=False)
        router._note_forward_result(m, ok=False)
        moved = [k for k in ks if owner[k] == "b1:8001"]
        for k in moved:
            assert router._peer_hint(k, "b0:8000") is None
        assert router.metrics.counter("rebalanced_keys_total") == len(moved)
        # same keys again: counted once, not twice
        for k in moved:
            router._peer_hint(k, "b0:8000")
        assert router.metrics.counter("rebalanced_keys_total") == len(moved)
        # a DRAINING previous owner CAN serve fills: re-admit, then drain
        router._note_forward_result(m, ok=True)
        m.state = "healthy"
        router._rebuild_ring("test_readmit")
        router._set_state(m, "draining", "test_drain")
        hinted = [
            k for k in ks
            if router.ring.owner(k) is not None
            and router._peer_hint(k, router.ring.owner(k)) == "b1:8001"
        ]
        assert hinted  # every key b1 owned now hints at it
        # hints expire with the window
        clock.t += fleet.PEER_FILL_WINDOW_S + 1
        assert all(
            router._peer_hint(k, "b0:8000") is None for k in hinted
        )

    asyncio.run(go())


def test_proxy_strips_client_supplied_peer_fill_hint(monkeypatch):
    # x-peer-fill is router-authoritative: a client-forged hint would
    # point a trusting backend's peer-fill fetch at an arbitrary
    # host:port (cache poisoning / SSRF on a trusted mesh)
    clock = _FakeClock()
    router = _router(clock)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    seen = {}

    async def capture(host, port, method, target, headers, body, timeout_s):
        seen.update(headers)
        return 200, {}, b"{}"

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", capture)
        req = Request(
            method="POST", path="/v1/deconv", query={},
            headers={"x-peer-fill": "evil.host:80", "x-tenant": "t1"},
            body=b"layer=block5_conv1", id="rid-peer-forge",
        )
        resp = await router._proxy(req)
        assert resp.status == 200
        assert "x-peer-fill" not in seen
        assert seen["x-tenant"] == "t1"  # legit headers still pass

    asyncio.run(go())


def test_proxy_requotes_decoded_path_in_forwarded_request_line(monkeypatch):
    # http.py percent-decodes the path at parse; the forward must
    # re-quote it or a %0d%0a path injects headers into the backend hop
    clock = _FakeClock()
    router = _router(clock)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    seen = {}

    async def capture(host, port, method, target, headers, body, timeout_s):
        seen["target"] = target
        return 200, {}, b"{}"

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", capture)
        req = Request(
            method="GET", path="/\r\nx-api-key: admin\r\n", query={},
            headers={}, body=b"", id="rid-crlf",
        )
        await router._proxy(req)
        assert "\r" not in seen["target"] and "\n" not in seen["target"]
        assert " " not in seen["target"]

    asyncio.run(go())


# ------------------------------------------------------------------- e2e


class FleetFixture:
    """N real backend services + one router, all on a background loop."""

    def __init__(self, n_backends=2, cfg=None, router_kw=None, registry=None):
        self.cfg = cfg or ServerConfig(
            image_size=16,
            max_batch=4,
            batch_window_ms=1.0,
            compilation_cache_dir="",
            fleet_peer_fill=True,
        )
        self.registry = registry  # extra models every backend serves
        self.n_backends = n_backends
        self.router_kw = dict(
            probe_interval_s=0.2, probe_timeout_s=2.0,
            eject_threshold=2, cooldown_s=1.0,
        )
        self.router_kw.update(router_kw or {})
        self.services: list[DeconvService] = []
        self.ports: list[int] = []
        self.router: FleetRouter | None = None
        self.router_port: int | None = None
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._started = threading.Event()

    def _run(self):
        asyncio.set_event_loop(self._loop)

        async def boot():
            params = init_params(TINY, jax.random.PRNGKey(3))
            for _ in range(self.n_backends):
                svc = DeconvService(
                    self.cfg, spec=TINY, params=params,
                    registry=self.registry,
                )
                port = await svc.start("127.0.0.1", 0)
                svc.ready = True
                self.services.append(svc)
                self.ports.append(port)
            self.router = FleetRouter(
                [f"127.0.0.1:{p}" for p in self.ports], **self.router_kw
            )
            self.router_port = await self.router.start("127.0.0.1", 0)
            self._started.set()

        self._loop.run_until_complete(boot())
        self._loop.run_forever()

    def __enter__(self):
        self._thread.start()
        assert self._started.wait(30)
        return self

    def __exit__(self, *exc):
        async def shutdown():
            await self.router.stop()
            for svc in self.services:
                if not svc.draining:
                    await svc.stop()

        fut = asyncio.run_coroutine_threadsafe(shutdown(), self._loop)
        fut.result(20)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(10)

    def on_loop(self, coro, timeout=20):
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(
            timeout
        )

    @property
    def router_url(self):
        return f"http://127.0.0.1:{self.router_port}"

    def backend_url(self, i):
        return f"http://127.0.0.1:{self.ports[i]}"


@pytest.fixture(scope="module")
def fleet2():
    with FleetFixture(n_backends=2) as f:
        yield f


def _data_url(rng_seed=0, size=16):
    import cv2

    rng = np.random.default_rng(rng_seed)
    img = (rng.random((size, size, 3)) * 255).astype(np.uint8)
    ok, buf = cv2.imencode(".png", img)
    assert ok
    return "data:image/png;base64," + base64.b64encode(buf.tobytes()).decode()


def test_e2e_byte_parity_and_request_id_end_to_end(fleet2):
    form = {"file": _data_url(11), "layer": "b2c1"}
    r1 = httpx.post(
        fleet2.router_url + "/", data=form,
        headers={"x-request-id": "fleet-parity-1"}, timeout=60,
    )
    assert r1.status_code == 200, r1.text
    backend = r1.headers["x-backend"]
    assert backend in {f"127.0.0.1:{p}" for p in fleet2.ports}
    # the inbound id survives router -> backend -> response untouched
    assert r1.headers["x-request-id"] == "fleet-parity-1"
    # byte parity: the same request DIRECT to the chosen backend
    direct = httpx.post(f"http://{backend}/", data=form, timeout=60)
    assert direct.status_code == 200
    assert direct.content == r1.content


def test_e2e_affinity_makes_one_logical_cache(fleet2):
    form = {"file": _data_url(12), "layer": "b2c1"}
    r1 = httpx.post(fleet2.router_url + "/", data=form, timeout=60)
    r2 = httpx.post(fleet2.router_url + "/", data=form, timeout=60)
    assert r1.status_code == r2.status_code == 200
    # identical requests land on the SAME backend and the second is a
    # cache hit there — the fleet-wide one-logical-cache contract
    assert r1.headers["x-backend"] == r2.headers["x-backend"]
    assert r2.headers["x-cache"] == "hit"
    assert r2.content == r1.content


def test_e2e_minted_request_id_matches_grammar(fleet2):
    r = httpx.post(
        fleet2.router_url + "/",
        data={"file": _data_url(13), "layer": "b2c1"},
        timeout=60,
    )
    assert r.status_code == 200
    assert RID_RE.match(r.headers["x-request-id"])


def test_e2e_cross_tier_trace_continuity(fleet2):
    """The satellite pin: a request's id joins the ROUTER's forward with
    the BACKEND's flight-recorder trace — `/v1/debug/requests?id=` on
    the stamped backend returns the request's span timeline."""
    rid = "fleet-trace-join-1"
    r = httpx.post(
        fleet2.router_url + "/",
        data={"file": _data_url(14), "layer": "b2c1"},
        headers={"x-request-id": rid}, timeout=60,
    )
    assert r.status_code == 200
    backend = r.headers["x-backend"]
    dbg = httpx.get(
        f"http://{backend}/v1/debug/requests", params={"id": rid}, timeout=30
    )
    assert dbg.status_code == 200
    traces = dbg.json()["requests"]
    assert len(traces) == 1 and traces[0]["id"] == rid
    assert traces[0]["status"] == 200
    assert any(s["name"] == "queue_wait" for s in traces[0]["spans"])


def test_e2e_cache_control_passthrough(fleet2):
    form = {"file": _data_url(15), "layer": "b2c1"}
    httpx.post(fleet2.router_url + "/", data=form, timeout=60)
    r = httpx.post(
        fleet2.router_url + "/", data=form,
        headers={"cache-control": "no-cache"}, timeout=60,
    )
    assert r.status_code == 200
    # the bypass header crossed the router: the backend recomputed
    assert r.headers["x-cache"] == "bypass"


def test_e2e_deadline_header_passthrough(fleet2):
    before = fleet2.router.metrics.counter("deadline_expired_total")
    r = httpx.post(
        fleet2.router_url + "/",
        data={"file": _data_url(16), "layer": "b2c1"},
        headers={"x-deadline-ms": "1"}, timeout=60,
    )
    # round 17: a budget already spent at the router 504s THERE —
    # no backend is consumed (no x-backend stamp), and the router's
    # own counter records it
    assert r.status_code == 504, r.text
    assert r.json()["error"] == "deadline_expired"
    assert "x-backend" not in r.headers
    assert fleet2.router.metrics.counter("deadline_expired_total") > before
    # a sane budget still passes through to the backend untouched
    r2 = httpx.post(
        fleet2.router_url + "/",
        data={"file": _data_url(16), "layer": "b2c1"},
        headers={"x-deadline-ms": "30000"}, timeout=60,
    )
    assert r2.status_code == 200, r2.text
    assert "x-backend" in r2.headers


def test_e2e_peer_cache_fill(fleet2):
    """Warm backend A with a key, then hand backend B the same request
    with an x-peer-fill hint at A: B must serve A's bytes (x-cache:
    peer-fill), store them, and serve its OWN hit next time."""
    form = {"file": _data_url(17), "layer": "b2c1"}
    a, b = fleet2.ports[0], fleet2.ports[1]
    warm = httpx.post(f"http://127.0.0.1:{a}/", data=form, timeout=60)
    assert warm.status_code == 200
    filled = httpx.post(
        f"http://127.0.0.1:{b}/", data=form,
        headers={"x-peer-fill": f"127.0.0.1:{a}"}, timeout=60,
    )
    assert filled.status_code == 200
    assert filled.headers["x-cache"] == "peer-fill"
    assert filled.content == warm.content
    again = httpx.post(f"http://127.0.0.1:{b}/", data=form, timeout=60)
    assert again.headers["x-cache"] == "hit"
    assert again.content == warm.content
    assert fleet2.services[1].metrics.counter("cache_peer_fills_total") >= 1


def test_e2e_internal_cache_route(fleet2):
    # a digest nobody computed: 404 cache_miss, never negative-cached
    r = httpx.get(
        fleet2.backend_url(0) + "/v1/internal/cache/" + "ab" * 20,
        timeout=30,
    )
    assert r.status_code == 404
    assert r.json()["error"] == "cache_miss"
    r = httpx.get(
        fleet2.backend_url(0) + "/v1/internal/cache/NOT-A-DIGEST",
        timeout=30,
    )
    assert r.status_code == 400


def test_e2e_router_surfaces(fleet2):
    ready = httpx.get(fleet2.router_url + "/readyz", timeout=30)
    assert ready.status_code == 200
    assert ready.json()["checks"]["backends_in_ring"] is True
    cfg = httpx.get(fleet2.router_url + "/v1/config", timeout=30)
    assert cfg.status_code == 200
    snap = cfg.json()
    assert snap["router"] is True and snap["vnodes"] == 64
    assert len(snap["members"]) == 2
    assert all(m["state"] == "healthy" for m in snap["members"].values())
    # per-member vnode counts and ring size line up
    assert snap["ring_points"] == 2 * 64
    hz = httpx.get(fleet2.router_url + "/healthz", timeout=30)
    assert hz.status_code == 200 and hz.json()["router"] is True


def test_e2e_router_metrics_lint(fleet2):
    from tests.test_metrics_exposition import lint_exposition

    # traffic exists from the earlier tests in this module
    text = httpx.get(fleet2.router_url + "/metrics", timeout=30).text
    families, samples = lint_exposition(text)
    assert families["router_requests_total"] == "counter"
    assert families["router_backend_state"] == "gauge"
    assert families["router_backends_in_ring"] == "gauge"
    assert any(
        name == "router_requests_total" and label.startswith("backend=")
        for name, label in samples
    )
    # non-core registry: the batching server's fixed families are absent
    assert "router_batches_total" not in families
    assert "router_images_total" not in families


def test_e2e_draining_backend_leaves_and_traffic_survives(fleet2):
    """Flip one backend into drain (the rolling-restart recipe): the
    router must move it out of the ring on the next probe and keep
    serving every request from the survivor."""
    victim = fleet2.services[1]
    victim_name = f"127.0.0.1:{fleet2.ports[1]}"

    fleet2.on_loop(_drain_and_probe(fleet2.router, victim))
    assert not fleet2.router.members[victim_name].in_ring
    assert fleet2.router.members[victim_name].state == "draining"
    for seed in (30, 31, 32):
        r = httpx.post(
            fleet2.router_url + "/",
            data={"file": _data_url(seed), "layer": "b2c1"},
            timeout=60,
        )
        assert r.status_code == 200
        assert r.headers["x-backend"] != victim_name
    # drain over (simulated restart): it rejoins on the next probe
    fleet2.on_loop(_undrain_and_probe(fleet2.router, victim))
    assert fleet2.router.members[victim_name].in_ring


async def _drain_and_probe(router, victim):
    victim.begin_drain()
    await router.probe_once()


async def _undrain_and_probe(router, victim):
    victim.draining = False
    victim.server.draining = False
    await router.probe_once()


def test_empty_ring_502_backend_unavailable():
    """A router whose backends never came up answers 502
    backend_unavailable with a Retry-After — the router error taxonomy
    contract (docs/API.md)."""

    async def go():
        router = FleetRouter(
            ["127.0.0.1:1"], probe_interval_s=30.0, probe_timeout_s=0.2,
            cooldown_s=3.0,
        )
        port = await router.server.start("127.0.0.1", 0)
        try:
            status, headers, body = await fleet.raw_request(
                "127.0.0.1", port, "POST", "/v1/deconv",
                {"content-type": "application/x-www-form-urlencoded"},
                b"layer=x", 10.0,
            )
            payload = json.loads(body)
            assert status == 502
            assert payload["error"] == "backend_unavailable"
            assert "request_id" in payload
            assert int(headers["retry-after"]) >= 1
        finally:
            await router.server.stop(0.5)

    asyncio.run(go())


def test_backend_unavailable_error_shape():
    e = errors.BackendUnavailable("gone", retry_after_s=2.5)
    assert e.status == 502 and e.code == "backend_unavailable"
    assert errors.retry_after_value(e.retry_after_s) == "3"


# ---------------------------------------------------------- job affinity


def test_job_affinity_sticky_and_fanout(monkeypatch):
    """/v1/jobs/{id} entity traffic follows the JOB, not the ring: the
    id is pinned to the backend whose 202 answered the submit, polls and
    cancels go there (round-robin would alternate), and a forgotten pin
    (router restart) degrades to the 404-walk that re-learns it."""
    clock = _FakeClock()
    router = _router(clock)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    jid = "job-abc123def456"
    owner: list[str] = []  # filled once the submit's 202 comes back

    async def fake(host, port, method, target, headers, body, timeout_s):
        name = f"{host}:{port}"
        if method == "POST" and target == "/v1/jobs":
            return (
                202,
                {"location": f"/v1/jobs/{jid}"},
                json.dumps({"id": jid}).encode(),
            )
        if target.startswith("/v1/jobs/"):
            if target.startswith(f"/v1/jobs/{jid}") and name == owner[0]:
                return 200, {}, json.dumps(
                    {"id": jid, "state": "running"}
                ).encode()
            return 404, {}, json.dumps({"error": "job_not_found"}).encode()
        return 200, {}, b"{}"

    def _req(method, path, i):
        return Request(
            method=method, path=path, query={}, headers={}, body=b"",
            id=f"rid-job-{i}",
        )

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", fake)
        resp = await router._proxy(
            Request(
                method="POST", path="/v1/jobs", query={},
                headers={"content-type": "application/json"},
                body=b'{"kind": "dream"}', id="rid-job-submit",
            )
        )
        assert resp.status == 202
        owner.append(resp.headers["x-backend"])
        assert router._job_owners[jid] == owner[0]
        # every poll lands on the owner (round-robin would alternate)
        for i in range(4):
            r = await router._proxy(_req("GET", f"/v1/jobs/{jid}", i))
            assert r.status == 200
            assert r.headers["x-backend"] == owner[0]
        # forgotten pin: the fan-out walk reads 404 job_not_found as
        # "not here, next", finds the owner, re-learns the pin
        router._job_owners.clear()
        r = await router._proxy(_req("GET", f"/v1/jobs/{jid}", "f"))
        assert r.status == 200 and r.headers["x-backend"] == owner[0]
        assert router._job_owners[jid] == owner[0]
        # DELETE follows the pin too
        r = await router._proxy(_req("DELETE", f"/v1/jobs/{jid}", "d"))
        assert r.status == 200 and r.headers["x-backend"] == owner[0]
        # an id NO member owns: an honest 404 through, never a 502
        r = await router._proxy(_req("GET", "/v1/jobs/job-000000000000", "n"))
        assert r.status == 404
        assert json.loads(r.body)["error"] == "job_not_found"

    asyncio.run(go())


def test_job_walk_infra_failure_is_502_not_404(monkeypatch):
    """If ANY walk candidate infra-fails, a 404 from the others is not
    conclusive — the silent member may be the one holding this durable
    job.  The client must see retryable unavailability, never a
    confident 404 that invites a duplicate re-submit."""
    clock = _FakeClock()
    router = _router(clock)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )

    async def fake(host, port, method, target, headers, body, timeout_s):
        if f"{host}:{port}" == "b0:8000":
            raise fleet._BackendError("b0:8000: ConnectionRefusedError")
        return 404, {}, json.dumps({"error": "job_not_found"}).encode()

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", fake)
        r = await router._proxy(
            Request(
                method="GET", path="/v1/jobs/job-aa11bb22cc33", query={},
                headers={}, body=b"", id="rid-job-infra",
            )
        )
        assert r.status == 502
        assert json.loads(r.body)["error"] == "backend_unavailable"

    asyncio.run(go())


def test_job_walk_asks_draining_member(monkeypatch):
    """A lost pin (router restart) during a rolling restart: the
    draining backend is out of the ring but still the only holder of
    its jobs' state — the fan-out walk must include it."""
    clock = _FakeClock()
    router = _router(clock)
    script = {"b0:8000": _ready_200, "b1:8001": _ready_200}
    _probe_script(monkeypatch, script)
    jid = "job-drainwalk01"

    async def fake(host, port, method, target, headers, body, timeout_s):
        if f"{host}:{port}" == "b1:8001" and target == f"/v1/jobs/{jid}":
            return 200, {}, json.dumps(
                {"id": jid, "state": "running"}
            ).encode()
        return 404, {}, json.dumps({"error": "job_not_found"}).encode()

    async def go():
        await router.probe_once()
        script["b1:8001"] = _draining_503
        await router.probe_once()
        assert router.members["b1:8001"].state == "draining"
        monkeypatch.setattr(fleet, "raw_request", fake)
        # no pin: the walk must reach the draining holder
        r = await router._proxy(
            Request(
                method="GET", path=f"/v1/jobs/{jid}", query={},
                headers={}, body=b"", id="rid-job-drainwalk",
            )
        )
        assert r.status == 200
        assert r.headers["x-backend"] == "b1:8001"

    asyncio.run(go())


def test_job_walk_jobs_disabled_member_does_not_mask_or_pin(monkeypatch):
    """A jobs-disabled member (no jobs_dir -> generic no-route 404) is
    not an authoritative answer: the walk must continue past it to the
    real holder and must never pin the id to it.  When jobs are
    disabled fleet-wide, the generic 404 passes through (not a 502)."""
    clock = _FakeClock()
    router = _router(clock)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    jid = "job-nomask12345"

    async def fake(host, port, method, target, headers, body, timeout_s):
        if f"{host}:{port}" == "b0:8000":
            return 404, {}, json.dumps(
                {"error": f"no route for /v1/jobs/{jid}"}
            ).encode()
        return 200, {}, json.dumps({"id": jid, "state": "running"}).encode()

    async def fake_all_disabled(
        host, port, method, target, headers, body, timeout_s
    ):
        return 404, {}, json.dumps(
            {"error": f"no route for /v1/jobs/{jid}"}
        ).encode()

    def _req(i):
        return Request(
            method="GET", path=f"/v1/jobs/{jid}", query={}, headers={},
            body=b"", id=f"rid-nomask-{i}",
        )

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", fake)
        r = await router._proxy(_req(1))
        assert r.status == 200 and r.headers["x-backend"] == "b1:8001"
        assert router._job_owners[jid] == "b1:8001"
        # jobs disabled everywhere: honest 404 through, not a 502
        router._job_owners.clear()
        monkeypatch.setattr(fleet, "raw_request", fake_all_disabled)
        r = await router._proxy(_req(2))
        assert r.status == 404
        assert "no route" in json.loads(r.body)["error"]
        assert jid not in router._job_owners

    asyncio.run(go())


def test_job_walk_bounds_timeout_for_unpinned_candidates(monkeypatch):
    """Blind-walk candidates get a short per-member bound (one wedged
    member must not stall an unknown-id poll for forward_timeout_s per
    hop); the pinned owner keeps the full forward timeout (its /result
    body may be large)."""
    clock = _FakeClock()
    router = _router(clock, forward_timeout_s=330.0, probe_timeout_s=2.0)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    jid = "job-timeoutwalk1"
    seen: dict[str, float] = {}

    async def fake(host, port, method, target, headers, body, timeout_s):
        seen[f"{host}:{port}"] = timeout_s
        if f"{host}:{port}" == "b1:8001":
            return 200, {}, json.dumps({"id": jid, "state": "done"}).encode()
        return 404, {}, json.dumps({"error": "job_not_found"}).encode()

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", fake)
        r = await router._proxy(
            Request(
                method="GET", path=f"/v1/jobs/{jid}", query={},
                headers={}, body=b"", id="rid-walk-to-1",
            )
        )
        assert r.status == 200
        # both hops were blind-walk candidates: short bound
        assert all(t == 10.0 for t in seen.values()), seen
        # now pinned: the owner gets the full forward timeout
        seen.clear()
        r = await router._proxy(
            Request(
                method="GET", path=f"/v1/jobs/{jid}", query={},
                headers={}, body=b"", id="rid-walk-to-2",
            )
        )
        assert r.status == 200 and seen == {"b1:8001": 330.0}

    asyncio.run(go())


def test_job_walk_ejected_holder_makes_404_inconclusive(monkeypatch):
    """An ejected member may be the durable job's only holder (its jobs
    survive on disk and resume after rejoin): while any member is
    unreachable, a fleet-wide job_not_found is inconclusive and must
    read as retryable 502, not a confident 404 — the pre-excluded
    twin of the in-walk infra-failure rule."""
    clock = _FakeClock()
    router = _router(clock)
    script = {"b0:8000": _ready_200, "b1:8001": _ready_200}
    _probe_script(monkeypatch, script)

    async def fake(host, port, method, target, headers, body, timeout_s):
        return 404, {}, json.dumps({"error": "job_not_found"}).encode()

    async def go():
        await router.probe_once()
        script["b1:8001"] = _down
        await router.probe_once()
        await router.probe_once()
        assert router.members["b1:8001"].state == "ejected"
        monkeypatch.setattr(fleet, "raw_request", fake)
        r = await router._proxy(
            Request(
                method="GET", path="/v1/jobs/job-ejectedhold1", query={},
                headers={}, body=b"", id="rid-job-ejected",
            )
        )
        assert r.status == 502
        assert json.loads(r.body)["error"] == "backend_unavailable"

    asyncio.run(go())


def test_jobs_collection_uses_walk_timeout(monkeypatch):
    """The collection gather barriers on its slowest member — each hop
    must be bounded by the short walk timeout, not forward_timeout_s,
    or one wedged member stalls every fleet view for minutes."""
    clock = _FakeClock()
    router = _router(clock, forward_timeout_s=330.0, probe_timeout_s=2.0)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    seen: dict[str, float] = {}

    async def fake(host, port, method, target, headers, body, timeout_s):
        seen[f"{host}:{port}"] = timeout_s
        return 200, {}, json.dumps(
            {"jobs": [], "counts": {}, "queue_depth": 0}
        ).encode()

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", fake)
        r = await router._proxy(
            Request(
                method="GET", path="/v1/jobs", query={}, headers={},
                body=b"", id="rid-coll-timeout",
            )
        )
        assert r.status == 200
        assert seen == {"b0:8000": 10.0, "b1:8001": 10.0}

    asyncio.run(go())


def test_jobs_collection_scatter_gather(monkeypatch):
    """GET /v1/jobs merges every member's collection: jobs concatenated
    in created order and stamped with their backend, counts summed, a
    failed member flagged as partial instead of failing the view."""
    clock = _FakeClock()
    router = _router(clock)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )

    async def fake(host, port, method, target, headers, body, timeout_s):
        if f"{host}:{port}" == "b0:8000":
            return 200, {}, json.dumps(
                {
                    "jobs": [{"id": "job-aa", "created_ts": 2.0}],
                    "counts": {"running": 1},
                    "queue_depth": 1,
                }
            ).encode()
        return 200, {}, json.dumps(
            {
                "jobs": [{"id": "job-bb", "created_ts": 1.0}],
                "counts": {"running": 2, "done": 1},
                "queue_depth": 0,
            }
        ).encode()

    async def fake_b0_down(host, port, method, target, headers, body, timeout_s):
        if f"{host}:{port}" == "b0:8000":
            raise fleet._BackendError("b0:8000: ConnectionRefusedError")
        return 200, {}, json.dumps(
            {"jobs": [], "counts": {}, "queue_depth": 0}
        ).encode()

    def _req(i):
        return Request(
            method="GET", path="/v1/jobs", query={}, headers={}, body=b"",
            id=f"rid-coll-{i}",
        )

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", fake)
        r = await router._proxy(_req(1))
        assert r.status == 200
        doc = json.loads(r.body)
        assert [j["id"] for j in doc["jobs"]] == ["job-bb", "job-aa"]
        assert doc["jobs"][0]["backend"] == "b1:8001"
        assert doc["jobs"][1]["backend"] == "b0:8000"
        assert doc["counts"] == {"running": 3, "done": 1}
        assert doc["queue_depth"] == 1
        assert doc["partial"] is False and doc["backends"] == 2
        assert r.headers["x-backend"] == "*"
        # the Prometheus family moves in lockstep with the /v1/config
        # per-member counter on fan-out traffic too
        fam = router.metrics.labeled("requests_total")
        assert fam.get("b0:8000") == 1 and fam.get("b1:8001") == 1
        # one member down: the view survives, flagged partial
        monkeypatch.setattr(fleet, "raw_request", fake_b0_down)
        r = await router._proxy(_req(2))
        assert r.status == 200
        assert json.loads(r.body)["partial"] is True

        # a malformed element (non-dict job, junk created_ts) from one
        # member must not 500 the whole view either
        async def fake_malformed(
            host, port, method, target, headers, body, timeout_s
        ):
            if f"{host}:{port}" == "b0:8000":
                return 200, {}, json.dumps(
                    {
                        "jobs": [None, {"id": "job-ok",
                                        "created_ts": "oops"}],
                        "counts": {},
                        "queue_depth": 0,
                    }
                ).encode()
            return 200, {}, json.dumps(
                {"jobs": [], "counts": {}, "queue_depth": 0}
            ).encode()

        monkeypatch.setattr(fleet, "raw_request", fake_malformed)
        r = await router._proxy(_req(3))
        assert r.status == 200
        doc = json.loads(r.body)
        assert doc["partial"] is True
        assert [j["id"] for j in doc["jobs"]] == ["job-ok"]

    asyncio.run(go())


def test_jobs_collection_includes_draining_member(monkeypatch):
    """A DRAINING backend is out of the ring but still the only holder
    of its jobs' state (its listener lives out the grace window) — the
    fleet view must keep asking it, or a rolling restart silently drops
    its jobs from GET /v1/jobs with partial: false."""
    clock = _FakeClock()
    router = _router(clock)
    script = {"b0:8000": _ready_200, "b1:8001": _ready_200}
    _probe_script(monkeypatch, script)

    async def fake_jobs(host, port, method, target, headers, body, timeout_s):
        name = f"{host}:{port}"
        jid = "job-drain" if name == "b1:8001" else "job-live"
        return 200, {}, json.dumps(
            {
                "jobs": [{"id": jid, "created_ts": 1.0}],
                "counts": {"running": 1},
                "queue_depth": 0,
            }
        ).encode()

    async def go():
        await router.probe_once()
        script["b1:8001"] = _draining_503
        await router.probe_once()
        assert router.members["b1:8001"].state == "draining"
        monkeypatch.setattr(fleet, "raw_request", fake_jobs)
        r = await router._proxy(
            Request(
                method="GET", path="/v1/jobs", query={}, headers={},
                body=b"", id="rid-drain-coll",
            )
        )
        assert r.status == 200
        doc = json.loads(r.body)
        assert {j["id"] for j in doc["jobs"]} == {"job-live", "job-drain"}
        assert doc["partial"] is False and doc["backends"] == 2

    asyncio.run(go())


# ----------------------------------------------------- raw client framing


def _one_shot_server(payload: bytes):
    """An asyncio TCP server that answers every connection with a fixed
    raw byte payload, then closes (graceful FIN)."""

    async def handle(reader, writer):
        await reader.read(4096)
        writer.write(payload)
        await writer.drain()
        writer.close()

    return asyncio.start_server(handle, "127.0.0.1", 0)


def test_raw_request_rejects_truncated_body():
    """A graceful FIN mid-body must read as an infra failure, not a
    complete response: without the content-length check a truncated 200
    would be forwarded to clients — and on the peer-fill path CACHED as
    a valid positive entry."""

    async def go():
        server = await _one_shot_server(
            b"HTTP/1.1 200 OK\r\ncontent-length: 100\r\n"
            b"connection: close\r\n\r\nonly twenty bytes!!!"
        )
        port = server.sockets[0].getsockname()[1]
        try:
            with pytest.raises(fleet._BackendError, match="truncated body"):
                await fleet.raw_request(
                    "127.0.0.1", port, "GET", "/x", {}, b"", 5.0
                )
        finally:
            server.close()
            await server.wait_closed()

    asyncio.run(go())


def test_raw_request_trims_bytes_past_content_length():
    """Bytes past content-length (a sloppy speaker) are dropped, not
    handed to the caller as part of the payload."""

    async def go():
        server = await _one_shot_server(
            b"HTTP/1.1 200 OK\r\ncontent-length: 4\r\n"
            b"connection: close\r\n\r\nbodyTRAILING-JUNK"
        )
        port = server.sockets[0].getsockname()[1]
        try:
            status, headers, body = await fleet.raw_request(
                "127.0.0.1", port, "GET", "/x", {}, b"", 5.0
            )
            assert status == 200 and body == b"body"
        finally:
            server.close()
            await server.wait_closed()

    asyncio.run(go())


# ------------------------------------------------------- SSE passthrough


def test_raw_request_stream_is_progressive():
    """The streaming client delivers each chunk as it arrives — the
    first SSE event must come through while the backend still holds the
    connection open (a buffered read-to-EOF would block until close)."""

    async def go():
        gate = asyncio.Event()

        async def handle(reader, writer):
            await reader.readuntil(b"\r\n\r\n")
            writer.write(
                b"HTTP/1.1 200 OK\r\n"
                b"content-type: text/event-stream\r\n"
                b"connection: close\r\n\r\n"
            )
            writer.write(b"data: one\n\n")
            await writer.drain()
            await gate.wait()
            writer.write(b"data: two\n\n")
            await writer.drain()
            writer.close()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        try:
            status, headers, chunks = await fleet.raw_request_stream(
                "127.0.0.1", port, "GET", "/v1/jobs/job-x/events", {},
                b"", 2.0,
            )
            assert status == 200
            assert headers["content-type"] == "text/event-stream"
            it = chunks.__aiter__()
            first = await asyncio.wait_for(it.__anext__(), 2.0)
            assert b"data: one" in first  # before the stream ended
            gate.set()
            rest = b""
            async for c in it:
                rest += c
            assert b"data: two" in rest
        finally:
            server.close()
            await server.wait_closed()

    asyncio.run(go())


def test_router_streams_job_events_past_forward_timeout():
    """/v1/jobs/{id}/events through the router: the response is a
    STREAM (head under the forward timeout, body an open pipe), a quiet
    period longer than the forward timeout neither truncates it nor
    feeds the ejection breaker — the round-14 review finding where a
    long job's SSE stream ejected its healthy backend."""

    async def go():
        async def handle(reader, writer):
            head = await reader.readuntil(b"\r\n\r\n")
            assert b"GET /v1/jobs/job-x/events" in head
            writer.write(
                b"HTTP/1.1 200 OK\r\n"
                b"content-type: text/event-stream\r\n"
                b"connection: close\r\n\r\n"
            )
            writer.write(b"data: one\n\n")
            await writer.drain()
            await asyncio.sleep(0.6)  # > forward_timeout_s below
            writer.write(b"data: two\n\n")
            await writer.drain()
            writer.close()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        name = f"127.0.0.1:{port}"
        try:
            router = FleetRouter(
                [name], probe_interval_s=30.0, forward_timeout_s=0.2,
            )
            m = router.members[name]
            router._set_state(m, "healthy", "test_admit")
            resp = await router._proxy(
                Request(
                    method="GET", path="/v1/jobs/job-x/events", query={},
                    headers={}, body=b"", id="rid-sse",
                )
            )
            assert resp.status == 200
            assert resp.stream is not None
            assert resp.headers["x-backend"] == name
            body = b""
            async for c in resp.stream:
                body += c
            assert b"data: one" in body and b"data: two" in body
            # the 0.6 s quiet period was NOT an infra failure
            assert m.in_ring and m.breaker.state_name == "closed"
        finally:
            server.close()
            await server.wait_closed()

    asyncio.run(go())


def test_router_blocks_internal_surface(monkeypatch):
    """/v1/internal/* is backend-to-backend (unauthenticated,
    QoS-unmetered by design): the router must answer 404 without
    forwarding, or the catch-all proxy re-exports the peer cache-read
    surface to external clients."""
    clock = _FakeClock()
    router = _router(clock)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    called = []

    async def fake(host, port, method, target, headers, body, timeout_s):
        called.append(target)
        return 200, {}, b"{}"

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", fake)
        r = await router._proxy(
            Request(
                method="GET", path="/v1/internal/cache/" + "ab" * 20,
                query={}, headers={}, body=b"", id="rid-internal",
            )
        )
        assert r.status == 404
        assert called == []  # never left the router

    asyncio.run(go())


def test_job_submit_never_replays_on_failover(monkeypatch):
    """A torn POST /v1/jobs must NOT replay on the failover owner: the
    idempotency index is per-backend, so the replay would silently
    double-submit a durable job.  Compute POSTs still retry once."""
    clock = _FakeClock()
    router = _router(clock)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    calls: list[str] = []

    async def fake(host, port, method, target, headers, body, timeout_s):
        calls.append(f"{host}:{port}")
        raise fleet._BackendError(f"{host}:{port}: torn response (0B)")

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", fake)
        r = await router._proxy(
            Request(
                method="POST", path="/v1/jobs", query={},
                headers={"content-type": "application/json"},
                body=b'{"type": "dream"}', id="rid-noreplay",
            )
        )
        assert r.status == 502
        assert len(calls) == 1, calls  # exactly one attempt
        calls.clear()
        r = await router._proxy(
            Request(
                method="POST", path="/v1/deconv", query={},
                headers={"content-type": "application/json"},
                body=b'{"layer": "x"}', id="rid-compute",
            )
        )
        assert r.status == 502
        assert len(calls) == 2, calls  # compute replays once

    asyncio.run(go())


def test_job_events_stalled_error_head_is_infra_failure():
    """A backend that sends a non-200 head on the SSE path and then
    stalls (alive socket, no body) must read as an infra failure within
    the forward timeout — not hang the router request forever."""

    async def go():
        async def handle(reader, writer):
            await reader.readuntil(b"\r\n\r\n")
            writer.write(
                b"HTTP/1.1 503 Service Unavailable\r\n"
                b"content-type: application/json\r\n"
                b"connection: close\r\n\r\n"
            )
            await writer.drain()
            await asyncio.sleep(5)  # stall, holding the socket open
            writer.close()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        name = f"127.0.0.1:{port}"
        try:
            router = FleetRouter(
                [name], probe_interval_s=30.0, forward_timeout_s=0.2,
            )
            m = router.members[name]
            router._set_state(m, "healthy", "test_admit")
            t0 = time.perf_counter()
            resp = await router._proxy(
                Request(
                    method="GET", path="/v1/jobs/job-x/events", query={},
                    headers={}, body=b"", id="rid-stall",
                )
            )
            took = time.perf_counter() - t0
            assert resp.status == 502  # the only candidate infra-failed
            assert took < 2.0, took  # bounded by the drain timeout
        finally:
            # the stalled handler task dies with asyncio.run teardown
            server.close()
            await server.wait_closed()

    asyncio.run(go())


# ------------------------------------- peer-fill singleflight integrity


def test_peer_fill_cancel_does_not_poison_singleflight(fleet2):
    """Round-14 review regression: the leader awaits _peer_fill between
    flights.begin and the try that finishes the flight — a
    CancelledError escaping there (client gone mid-fetch) must finish
    the flight, or the key's future stays in the table forever and
    every later identical request coalesces onto it and hangs."""
    import urllib.parse as _up

    svc = fleet2.services[0]
    handler = svc.server._routes[("POST", "/v1/deconv")]
    body = _up.urlencode(
        {"file": _data_url(77), "layer": "b2c1"}
    ).encode()
    ctype = {"content-type": "application/x-www-form-urlencoded"}

    async def go():
        started = asyncio.Event()

        async def hang(req, key, tr):
            started.set()
            await asyncio.Event().wait()

        svc._peer_fill = hang  # instance attr shadows the bound method
        try:
            task = asyncio.ensure_future(
                handler(
                    Request(
                        method="POST", path="/v1/deconv", query={},
                        headers={**ctype, "x-peer-fill": "127.0.0.1:1"},
                        body=body, id="rid-poison-1",
                    )
                )
            )
            await asyncio.wait_for(started.wait(), 10)
            task.cancel()
            with pytest.raises(asyncio.CancelledError):
                await task
        finally:
            del svc.__dict__["_peer_fill"]
        # the key must be recomputable: a fresh identical request becomes
        # a NEW leader (pre-fix it coalesced onto the dead future forever)
        resp = await asyncio.wait_for(
            handler(
                Request(
                    method="POST", path="/v1/deconv", query={},
                    headers=dict(ctype), body=body, id="rid-poison-2",
                )
            ),
            30,
        )
        assert resp.status == 200

    fleet2.on_loop(go(), timeout=60)


def test_e2e_x_model_passes_through_and_affinity_holds():
    """Round 15 satellite pin: the router forwards `x-model` / `model=`
    UNCHANGED (it is not hop-by-hop), and because the `model` form
    field rides the body — and therefore the canonical digest the ring
    hashes — per-model cache affinity needs no router change: the same
    (body, model) request always lands on the same backend and its
    second send is that backend's cache hit."""
    from dataclasses import replace

    from deconv_api_tpu.models.spec import Layer, ModelSpec
    from deconv_api_tpu.serving.models import spec_bundle

    alt_spec = ModelSpec(
        name="alt_vgg",
        input_shape=(16, 16, 3),
        layers=(
            Layer("input_1", "input"),
            Layer("b1c1", "conv", activation="relu", filters=4),
            Layer("b1p", "pool"),
            Layer("b2c1", "conv", activation="relu", filters=6),
        ),
    )
    alt_params = init_params(alt_spec, jax.random.PRNGKey(9))
    cfg = ServerConfig(
        image_size=16,
        max_batch=4,
        batch_window_ms=1.0,
        compilation_cache_dir="",
        fleet_peer_fill=True,
        serve_models="tiny_vgg,alt_vgg",
    )
    registry = {"alt_vgg": lambda: spec_bundle(alt_spec, alt_params)}
    with FleetFixture(n_backends=2, cfg=cfg, registry=registry) as f:
        base = {"file": _data_url(31), "layer": "b2c1"}
        # default model through the router
        r_def = httpx.post(f.router_url + "/", data=base, timeout=60)
        assert r_def.status_code == 200, r_def.text
        # model= form field: inside the body => inside the ring digest
        r1 = httpx.post(
            f.router_url + "/", data={**base, "model": "alt_vgg"},
            timeout=60,
        )
        assert r1.status_code == 200, r1.text
        assert r1.content != r_def.content, "alt model must differ"
        r2 = httpx.post(
            f.router_url + "/", data={**base, "model": "alt_vgg"},
            timeout=60,
        )
        assert r2.status_code == 200
        assert r2.headers["x-backend"] == r1.headers["x-backend"]
        assert r2.headers["x-cache"] == "hit"
        assert r2.content == r1.content
        # x-model HEADER: not in the body, so it rides the DEFAULT
        # body's ring key — same backend as the bare request, but the
        # backend resolves the header and serves the alt model's bytes
        # under the alt model's cache prefix
        rh = httpx.post(
            f.router_url + "/", data=base,
            headers={"x-model": "alt_vgg"}, timeout=60,
        )
        assert rh.status_code == 200
        assert rh.headers["x-backend"] == r_def.headers["x-backend"]
        assert rh.content == r1.content
        # unknown model 422s straight through the router
        rbad = httpx.post(
            f.router_url + "/", data={**base, "model": "ghost"}, timeout=60
        )
        assert rbad.status_code == 422
        assert rbad.json()["error"] == "unknown_model"
