"""Driver-contract tests: __graft_entry__.entry() traces and
dryrun_multichip() executes on the 8-device virtual CPU mesh."""

import importlib.util
import sys

import pytest
from pathlib import Path

import jax


def _load_graft():
    path = Path(__file__).resolve().parent.parent / "__graft_entry__.py"
    spec = importlib.util.spec_from_file_location("__graft_entry__", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["__graft_entry__"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_entry_is_traceable():
    mod = _load_graft()
    fn, args = mod.entry()
    # trace-only check: full VGG16 compile is exercised on TPU by the driver
    out = jax.eval_shape(fn, *args)
    assert "block5_conv1" in out
    assert out["block5_conv1"]["images"].shape == (8, 224, 224, 3)


@pytest.mark.slow  # 8-chip dryrun compile (~36s); the multichip dryrun path
# stays in tier-1 via test_dryrun_multichip_odd
def test_dryrun_multichip_8():
    mod = _load_graft()
    mod.dryrun_multichip(8)


def test_dryrun_multichip_odd():
    mod = _load_graft()
    mod.dryrun_multichip(5)
