"""serving/codec_pool.py: the bounded codec worker pool and the host
buffer ring (round 6's host I/O pipeline building blocks) — ordering,
error propagation, backpressure, sync fan-out, and ring reuse/retention."""

import asyncio
import threading
import time

import numpy as np
import pytest

from deconv_api_tpu.serving.codec_pool import (
    HostBufferRing,
    PoolClosed,
    WorkerPool,
)


def test_map_preserves_input_order():
    """Results come back in input order even when earlier items take
    longer than later ones (4 workers racing)."""
    pool = WorkerPool(4)

    def job(i):
        time.sleep(0.02 if i % 2 == 0 else 0.001)  # evens finish LAST
        return i * 10

    async def go():
        return await pool.map(job, list(range(12)))

    assert asyncio.run(go()) == [i * 10 for i in range(12)]
    pool.close()


def test_run_propagates_errors_and_pool_survives():
    pool = WorkerPool(2)

    def boom():
        raise RuntimeError("codec exploded")

    async def go():
        with pytest.raises(RuntimeError, match="codec exploded"):
            await pool.run(boom)
        # the worker that relayed the error keeps serving
        return await pool.run(lambda: "ok")

    assert asyncio.run(go()) == "ok"
    pool.close()


def test_map_propagates_first_error():
    pool = WorkerPool(2)

    def job(i):
        if i == 3:
            raise ValueError("bad tile")
        return i

    async def go():
        with pytest.raises(ValueError, match="bad tile"):
            await pool.map(job, range(6))

    asyncio.run(go())
    pool.close()


def test_backpressure_bounds_pending_jobs():
    """max_pending bounds queued-or-running jobs: excess run() callers
    wait for a slot instead of growing the queue without limit."""
    pool = WorkerPool(1, max_pending=2)
    gate = threading.Event()
    in_flight = []

    def job(i):
        in_flight.append(i)
        gate.wait(5)
        return i

    async def go():
        tasks = [asyncio.create_task(pool.run(job, i)) for i in range(5)]
        await asyncio.sleep(0.3)
        # 1 running + 1 queued admitted; the other three waited on the bound
        assert pool._depth <= 2
        assert len(in_flight) == 1  # single worker: one actually running
        gate.set()
        return await asyncio.gather(*tasks)

    assert asyncio.run(go()) == [0, 1, 2, 3, 4]
    pool.close()


def test_closed_pool_rejects_jobs():
    pool = WorkerPool(1)
    pool.close()
    pool.close()  # idempotent

    async def go():
        with pytest.raises(PoolClosed):
            await pool.run(lambda: 1)

    asyncio.run(go())


def test_map_sync_from_worker_thread():
    """The batch fetch thread fans per-request encodes through map_sync
    (ordered, blocking) without touching any event loop."""
    pool = WorkerPool(4)

    def encode(i):
        time.sleep(0.001)
        return f"jpeg-{i}"

    result = {}

    def fetch_thread():
        result["out"] = pool.map_sync(encode, list(range(8)))

    t = threading.Thread(target=fetch_thread)
    t.start()
    t.join(10)
    assert result["out"] == [f"jpeg-{i}" for i in range(8)]
    # after close, map_sync degrades to inline execution
    pool.close()
    assert pool.map_sync(encode, [1, 2]) == ["jpeg-1", "jpeg-2"]


def test_map_sync_propagates_errors():
    pool = WorkerPool(2)

    def job(i):
        if i == 1:
            raise RuntimeError("encode failed")
        return i

    with pytest.raises(RuntimeError, match="encode failed"):
        pool.map_sync(job, [0, 1, 2])
    pool.close()


def test_gauge_tracks_depth():
    class FakeMetrics:
        def __init__(self):
            self.values = []

        def set_gauge(self, name, value):
            self.values.append((name, value))

    m = FakeMetrics()
    pool = WorkerPool(2, name="codec", metrics=m)

    async def go():
        await pool.run(lambda: 1)

    asyncio.run(go())
    names = {n for n, _ in m.values}
    # depth gauge plus the round-9 live-workers gauge (the /readyz
    # quorum input, published from construction on)
    assert names == {"codec_queue_depth", "codec_workers_live"}
    depth = [v for n, v in m.values if n == "codec_queue_depth"]
    assert any(v >= 1 for v in depth)  # saw the job pending
    assert depth[-1] == 0  # and its completion
    assert [v for n, v in m.values if n == "codec_workers_live"][-1] == 2
    pool.close()


# --------------------------------------------------------------- buffer ring


def test_ring_reuses_released_buffers():
    ring = HostBufferRing(depth=2)
    a = ring.acquire((4, 8, 8, 3), np.float32)
    ring.release(a)
    b = ring.acquire((4, 8, 8, 3), np.float32)
    assert b is a  # same storage, no fresh allocation
    c = ring.acquire((4, 8, 8, 3), np.float32)
    assert c is not a  # a is handed out; a second acquire allocates


def test_ring_retention_bounded():
    ring = HostBufferRing(depth=2)
    bufs = [ring.acquire((2, 2), np.float32) for _ in range(5)]
    for b in bufs:
        ring.release(b)
    key = ring._key((2, 2), np.float32)
    assert len(ring._free[key]) == 2  # retains at most `depth`


def test_ring_keys_on_shape_and_dtype():
    ring = HostBufferRing(depth=2)
    a = ring.acquire((2, 2), np.float32)
    ring.release(a)
    b = ring.acquire((2, 2), np.uint8)
    assert b is not a and b.dtype == np.uint8


def test_assemble_pads_with_last_image():
    ring = HostBufferRing(depth=2)
    imgs = [np.full((3, 3, 3), i, np.float32) for i in range(3)]
    buf = ring.assemble(imgs, bucket=8)
    assert buf.shape == (8, 3, 3, 3)
    for i in range(3):
        np.testing.assert_array_equal(buf[i], imgs[i])
    for i in range(3, 8):
        np.testing.assert_array_equal(buf[i], imgs[-1])
    ring.release(buf)
    # the reused buffer assembles a fresh batch without ghosts of the old
    imgs2 = [np.full((3, 3, 3), 9, np.float32)] * 2
    buf2 = ring.assemble(imgs2, bucket=8)
    assert buf2 is buf
    np.testing.assert_array_equal(buf2[7], imgs2[-1])
