"""Pallas switch-pool kernels vs the XLA reference ops (interpret mode).

The kernels compile on real TPU (verified on v5e, incl. bf16 and VGG
shapes); here they run under the pallas interpreter so CPU CI covers the
same code path bar Mosaic lowering.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deconv_api_tpu.ops.pallas_pool import (
    maxpool_argmax_pallas,
    unpool_argmax_pallas,
)
from deconv_api_tpu.ops.pool import maxpool_with_argmax, unpool_with_argmax


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


@pytest.mark.parametrize(
    "shape,pool",
    [
        ((2, 8, 8, 16), (2, 2)),
        ((1, 12, 8, 4), (2, 2)),
        ((2, 6, 9, 8), (3, 3)),
        ((1, 4, 6, 128), (2, 3)),
    ],
)
def test_pool_matches_xla_reference(rng, shape, pool):
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    x = jnp.round(x * 2) / 2  # ties: exercise first-occurrence tie-break
    p_ref, i_ref = maxpool_with_argmax(x, pool)
    p, i = maxpool_argmax_pallas(x, pool, True)
    np.testing.assert_array_equal(np.asarray(p_ref), np.asarray(p))
    np.testing.assert_array_equal(np.asarray(i_ref), np.asarray(i))

    g = jnp.asarray(rng.standard_normal(p.shape).astype(np.float32))
    u_ref = unpool_with_argmax(g, i_ref, pool)
    u = unpool_argmax_pallas(g, i, pool, True)
    np.testing.assert_array_equal(np.asarray(u_ref), np.asarray(u))


def test_unpool_shared_idx_replay(rng):
    """y batch = rep * idx batch: each switch block replayed for rep
    consecutive y slices (the engine's K-filters-per-image layout)."""
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 16)).astype(np.float32))
    _, idx = maxpool_with_argmax(x, (2, 2))
    y = jnp.asarray(rng.standard_normal((6, 4, 4, 16)).astype(np.float32))
    got = unpool_argmax_pallas(y, idx, (2, 2), True)
    for k in range(6):
        want = unpool_with_argmax(y[k : k + 1], idx[k // 3 : k // 3 + 1], (2, 2))
        np.testing.assert_array_equal(np.asarray(want[0]), np.asarray(got[k]))


def test_unpool_fused_relu(rng):
    y = jnp.asarray(rng.standard_normal((2, 4, 4, 8)).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 8)).astype(np.float32))
    _, idx = maxpool_with_argmax(x, (2, 2))
    fused = unpool_argmax_pallas(y, idx, (2, 2), True, True)
    want = jnp.maximum(unpool_with_argmax(y, idx, (2, 2)), 0.0)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(fused))


def test_bf16_roundtrip_exact(rng):
    """bf16 I/O computes in fp32 internally — lossless for bf16 values."""
    x = jnp.asarray(rng.standard_normal((1, 8, 8, 16)).astype(np.float32))
    xb = x.astype(jnp.bfloat16)
    p, i = maxpool_argmax_pallas(xb, (2, 2), True)
    p_ref, i_ref = maxpool_with_argmax(xb, (2, 2))
    np.testing.assert_array_equal(
        np.asarray(p_ref, np.float32), np.asarray(p, np.float32)
    )
    np.testing.assert_array_equal(np.asarray(i_ref), np.asarray(i))


def test_vmap_composition_matches_xla(rng):
    """The custom_vmap rules (batch-collapse + idx replay) must agree with
    plain vmap over the XLA ops — nested (B, K) exactly as the engine."""
    import deconv_api_tpu.ops.pallas_pool as pp

    x = jnp.asarray(rng.standard_normal((3, 8, 8, 4)).astype(np.float32))
    _, idx = maxpool_with_argmax(x, (2, 2))
    y = jnp.asarray(rng.standard_normal((3, 5, 4, 4, 4)).astype(np.float32))

    def xla_one(yk, idxb):
        return unpool_with_argmax(yk[None], idxb[None], (2, 2))[0]

    want = jax.vmap(lambda yb, ib: jax.vmap(lambda yk: xla_one(yk, ib))(yb))(y, idx)

    pallas_op = pp._unpool_op(2, 2)

    def pl_one(yk, idxb):
        return pallas_op(yk[None], idxb[None])[0]

    got = jax.vmap(lambda yb, ib: jax.vmap(lambda yk: pl_one(yk, ib[0]))(yb))(
        y, idx[:, None]
    )
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


@pytest.mark.parametrize("idx_batch,rep", [(4, 1), (4, 2)])
def test_vmap_unbatched_idx_with_own_batch(rng, idx_batch, rep):
    """ADVICE r1 regression: idx closed over (unbatched) by vmap while
    carrying its own batch > 1 must pair switch blocks vmap-axis-major.
    The old rule passed idx through raw, so the kernel's `i // rep` map
    paired y slice vi*b+k with idx block (vi*b+k)//rep — consecutive
    blocks — instead of replaying idx per vmap slice."""
    import deconv_api_tpu.ops.pallas_pool as pp

    x = jnp.asarray(
        rng.standard_normal((idx_batch, 8, 8, 4)).astype(np.float32)
    )
    _, idx = maxpool_with_argmax(x, (2, 2))  # (idx_batch, 4, 4, 4)
    v, b = 2, idx_batch * rep
    y = jnp.asarray(rng.standard_normal((v, b, 4, 4, 4)).astype(np.float32))

    op = pp._unpool_op(2, 2)
    got = jax.vmap(lambda yv: op(yv, idx))(y)
    want = jax.vmap(lambda yv: unpool_with_argmax(yv, jnp.repeat(idx, rep, 0), (2, 2)))(y)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))
