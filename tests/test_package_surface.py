"""Top-level package API surface (lazy PEP 562 exports)."""

import pytest


def test_top_level_lazy_exports():
    """The package's convenience surface resolves lazily and __dir__ lists
    it; unknown attributes raise AttributeError normally."""
    import deconv_api_tpu as d

    assert "visualize" in dir(d) and "DeconvService" in dir(d)
    assert d.ServerConfig().model == "vgg16"
    assert callable(d.get_visualizer)
    with pytest.raises(AttributeError):
        d.definitely_not_an_export
