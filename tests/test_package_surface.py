"""Top-level package API surface (lazy PEP 562 exports)."""

import pytest


def test_top_level_lazy_exports():
    """The package's convenience surface resolves lazily and __dir__ lists
    it; unknown attributes raise AttributeError normally."""
    import deconv_api_tpu as d

    assert "visualize" in dir(d) and "DeconvService" in dir(d)
    assert d.ServerConfig().model == "vgg16"
    assert callable(d.get_visualizer)
    with pytest.raises(AttributeError):
        d.definitely_not_an_export


def test_exports_are_actually_lazy():
    """Importing the package must NOT import the engine/jax stack — the
    property the PEP 562 indirection exists to provide."""
    import subprocess
    import sys

    code = (
        "import sys\n"
        "import deconv_api_tpu\n"
        "assert 'deconv_api_tpu.engine' not in sys.modules\n"
        "assert 'deconv_api_tpu.serving.app' not in sys.modules\n"
        "deconv_api_tpu.ServerConfig()  # light export works\n"
        "assert 'deconv_api_tpu.engine' not in sys.modules\n"
        "print('lazy')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         timeout=120)
    assert out.returncode == 0, out.stderr.decode()[-400:]
    assert b"lazy" in out.stdout
