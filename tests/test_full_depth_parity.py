"""Full-depth parity as a reproducible `-m slow` test (VERDICT r2 item 8).

Runs the fp64 NumPy oracle (the reference algorithm, SURVEY §2.2 quirks
included — tests/reference_numpy.py) at FULL VGG16 depth and resolution
(224x224, block5_conv1, top-8) with fixed seeds, and pins the engine's
parity against it to committed bounds.  The round-2 one-off artifact
measured fp32 70.3 dB / bf16-backward 58.1 dB deprocessed (BASELINE.md);
the bounds below leave margin for cross-platform reduction-order noise
but catch any real regression (a semantics change shows up as tens of dB).

~90s of fp64 NumPy: opt in with `pytest -m slow`.
"""

import importlib.util
import os

import pytest

_TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "full_depth_parity.py",
)


def _load_tool():
    spec = importlib.util.spec_from_file_location("full_depth_parity", _TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_full_depth_parity_bounds():
    results = _load_tool().run("block5_conv1", 8)

    # top-8 selection must match the oracle exactly in both configs
    assert results["fp32"]["indices_match"]
    assert results["bf16_backward"]["indices_match"]

    # committed PSNR floors (r2 measurements minus margin); the >40 dB
    # north-star bar must clear with room in the serving (bf16) config
    assert results["fp32"]["deprocessed_psnr_db"] >= 65.0
    assert results["fp32"]["raw_psnr_db"] >= 67.0
    assert results["bf16_backward"]["deprocessed_psnr_db"] >= 52.0
    assert results["bf16_backward"]["raw_psnr_db"] >= 58.0

    # bf16 FORWARD as well (DECONV_DTYPE=bfloat16, the round-4c opt-in:
    # 417.5 img/s vs the 400.3 same-session fp32-fwd control on a v5e-1).
    # Measured 2026-07-31: raw 36.9 dB / deprocessed 35.3 dB — BELOW the
    # north-star 40 dB bar, which is why it is NOT the default; the floors
    # pin the variant so an engine change cannot silently turn "slightly
    # under the bar" into "broken".  A selection or switch regression
    # craters PSNR to <10 dB, so these floors also cover per-channel
    # stability (images pair BY CHANNEL, so a pure near-tie rank swap
    # cannot flake the floor); the count pins catch tail-filter loss and
    # selection drift, which the paired PSNR alone would not.
    assert results["bf16_full"]["valid_count"] == 8
    assert results["bf16_full"]["paired_count"] >= 7
    assert results["bf16_full"]["deprocessed_psnr_db"] >= 30.0
    assert results["bf16_full"]["raw_psnr_db"] >= 31.0

    # Partial bf16 forward (DECONV_FWD_LOWC_BF16=128): bf16 only in the
    # C<=128 block1/2 segments.  Measured 2026-07-31: raw 38.3 dB /
    # deprocessed 36.7 dB — the best perf opt-in (439.3 img/s vs the
    # 411.5 same-session control at batch 64) and slightly better parity
    # than whole-chain bf16, but STILL below the 40 dB bar: the PSNR loss
    # is dominated by pool-switch near-tie flips, which any forward
    # perturbation triggers, not by seed precision.  Hence also opt-in.
    assert results["bf16_lowc_fwd"]["valid_count"] == 8
    assert results["bf16_lowc_fwd"]["paired_count"] >= 7
    assert results["bf16_lowc_fwd"]["deprocessed_psnr_db"] >= 31.0
    assert results["bf16_lowc_fwd"]["raw_psnr_db"] >= 33.0


@pytest.mark.slow
def test_full_depth_parity_bounds_max_mode():
    """VERDICT r3 item 8: the reference's visualize_mode='max' pixel
    semantics (only the argmax positions project, ties included —
    app/deepdream.py:454-457) pinned at FULL depth alongside mode='all'.
    Measured 2026-07-30: fp32 155.5 dB raw / 108.9 dB deprocessed,
    bf16-backward 74.6 / 64.4 (sparser seeds accumulate less rounding
    than 'all'); floors leave cross-platform margin."""
    results = _load_tool().run("block5_conv1", 8, mode="max")

    assert results["fp32"]["indices_match"]
    assert results["bf16_backward"]["indices_match"]

    assert results["fp32"]["deprocessed_psnr_db"] >= 95.0
    assert results["fp32"]["raw_psnr_db"] >= 140.0
    assert results["bf16_backward"]["deprocessed_psnr_db"] >= 55.0
    assert results["bf16_backward"]["raw_psnr_db"] >= 65.0

    # bf16-forward opt-in, max mode (measured 2026-07-31: raw 47.3 dB /
    # deprocessed 38.8 dB, channel-paired) — the sparse seeds accumulate
    # less forward rounding than mode='all'.
    assert results["bf16_full"]["valid_count"] == 8
    assert results["bf16_full"]["paired_count"] >= 7
    assert results["bf16_full"]["deprocessed_psnr_db"] >= 32.0
    assert results["bf16_full"]["raw_psnr_db"] >= 40.0
