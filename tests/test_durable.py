"""The round-24 durable-store layer (serving/durable.py): the one write
idiom, the per-surface degradation contracts, the fs.* fault sites, the
versioned artifact framing, and the uniform boot-time .tmp sweep."""

import json
import os

import pytest

from deconv_api_tpu.serving import durable, faults
from deconv_api_tpu.serving.metrics import Metrics


@pytest.fixture(autouse=True)
def _no_registry():
    """Each test arms its own registry; none leaks across tests."""
    yield
    faults.uninstall()


def _arm(spec_str: str, seed: int = 0) -> faults.FaultRegistry:
    reg = faults.FaultRegistry(seed=seed)
    reg.arm_string(spec_str)
    faults.install(reg)
    return reg


def _surface(name: str, metrics=None) -> durable.Surface:
    return durable.Surface(name, metrics=metrics)


# ------------------------------------------------------------ write idiom


def test_atomic_write_roundtrip_and_no_tmp(tmp_path):
    path = str(tmp_path / "a.bin")
    s = _surface("cache.l2")
    assert durable.atomic_write(path, b"payload", surface=s) is True
    with open(path, "rb") as f:
        assert f.read() == b"payload"
    assert not os.path.exists(path + ".tmp")
    assert s.degraded is False


def test_atomic_write_overwrites_whole_file(tmp_path):
    path = str(tmp_path / "a.bin")
    s = _surface("cache.l2")
    durable.atomic_write(path, b"x" * 100, surface=s)
    durable.atomic_write(path, b"y", surface=s)
    with open(path, "rb") as f:
        assert f.read() == b"y"


def test_append_bytes_fsyncs_and_appends(tmp_path):
    path = str(tmp_path / "j.log")
    s = _surface("cache.l2")
    with open(path, "ab") as f:
        assert durable.append_bytes(f, b"one\n", surface=s) is True
        assert durable.append_bytes(f, b"two\n", surface=s) is True
    with open(path, "rb") as f:
        assert f.read() == b"one\ntwo\n"


def test_undeclared_surface_is_a_programming_error():
    with pytest.raises(ValueError, match="undeclared durable surface"):
        durable.Surface("not.a.surface")


# ----------------------------------------------------- degradation split


def test_best_effort_enospc_counts_and_degrades_not_raises(tmp_path):
    m = Metrics()
    s = _surface("cache.l2", metrics=m)
    _arm("fs.enospc=p1@cache.l2")
    path = str(tmp_path / "a.bin")
    assert durable.atomic_write(path, b"data", surface=s) is False
    assert not os.path.exists(path)
    assert not os.path.exists(path + ".tmp")
    assert s.degraded is True
    assert s.write_errors == 1
    assert m.labeled("durable_write_errors_total")["cache.l2"] == 1
    assert m.labeled_gauge("durable_degraded")["cache.l2"] == 1.0


def test_best_effort_recovery_clears_degraded(tmp_path):
    m = Metrics()
    s = _surface("cache.l2", metrics=m)
    _arm("fs.enospc=n1@cache.l2")
    assert durable.atomic_write(str(tmp_path / "a"), b"x", surface=s) is False
    assert s.degraded is True
    # the n1 spec self-disarmed: the next write succeeds and clears
    assert durable.atomic_write(str(tmp_path / "a"), b"x", surface=s) is True
    assert s.degraded is False
    assert m.labeled_gauge("durable_degraded")["cache.l2"] == 0.0
    # the error count is monotone — recovery never un-counts
    assert m.labeled("durable_write_errors_total")["cache.l2"] == 1


def test_fail_loud_fsync_error_raises_durable_write_error(tmp_path):
    s = _surface("jobs.journal")
    _arm("fs.fsync_error=p1@jobs.journal")
    with open(str(tmp_path / "j.log"), "ab") as f:
        with pytest.raises(durable.DurableWriteError) as ei:
            durable.append_bytes(f, b"rec\n", surface=s)
    assert ei.value.surface == "jobs.journal"
    assert isinstance(ei.value, OSError)  # legacy except-OSError holds
    assert s.degraded is True


def test_fault_targets_exactly_one_surface(tmp_path):
    _arm("fs.enospc=p1@cache.l2")
    l2 = _surface("cache.l2")
    aot = _surface("aot.store")
    assert durable.atomic_write(str(tmp_path / "a"), b"x", surface=l2) is False
    assert durable.atomic_write(str(tmp_path / "b"), b"x", surface=aot) is True


def test_short_write_caught_by_digest_at_read_time(tmp_path):
    path = str(tmp_path / "a.bin")
    s = _surface("cache.l2")
    _arm("fs.short_write=n1@cache.l2")
    # the writer believes it succeeded — that is the lie short writes tell
    assert durable.atomic_write(
        path, durable.frame("cache.l2", 1, b"p" * 64), surface=s
    ) is True
    assert durable.read_framed(path, "cache.l2", 1, surface="cache.l2") is None


def test_eio_read_reads_as_absent(tmp_path):
    path = str(tmp_path / "a.bin")
    s = _surface("cache.l2")
    durable.atomic_write(path, b"data", surface=s)
    _arm("fs.eio_read=n1@cache.l2")
    assert durable.read_bytes(path, "cache.l2") is None
    # one-shot consumed: the file is intact underneath
    assert durable.read_bytes(path, "cache.l2") == b"data"


def test_degraded_log_once_per_episode(tmp_path):
    """Persistent failure flips the gauge once, not once per write."""
    m = Metrics()
    s = _surface("cache.l2", metrics=m)
    _arm("fs.enospc=p1@cache.l2")
    for i in range(5):
        durable.atomic_write(str(tmp_path / "a"), b"x", surface=s)
    assert m.labeled("durable_write_errors_total")["cache.l2"] == 5
    assert m.labeled_gauge("durable_degraded")["cache.l2"] == 1.0


def test_register_metrics_present_at_zero_for_all_eight():
    m = Metrics()
    durable.register_metrics(m)
    errs = m.labeled("durable_write_errors_total")
    degr = m.labeled_gauge("durable_degraded")
    assert set(errs) == set(durable.SURFACES)
    assert set(degr) == set(durable.SURFACES)
    assert all(v == 0 for v in errs.values())
    assert all(v == 0.0 for v in degr.values())


# ------------------------------------------------------------ crashpoints


def test_crash_points_leave_old_or_new_file_never_torn(tmp_path, monkeypatch):
    """At every atomic crashpoint the visible file is either the OLD
    complete artifact or the NEW complete artifact — never a mix."""
    crashes: list[int] = []
    monkeypatch.setattr(
        durable, "_CRASH_HOOK", lambda: (_ for _ in ()).throw(_Crash())
    )
    for point in durable.ATOMIC_CRASH_POINTS:
        root = tmp_path / f"p{point}"
        root.mkdir()
        path = str(root / "a.bin")
        s = _surface("cache.l2")
        old = durable.frame("cache.l2", 1, b"old")
        new = durable.frame("cache.l2", 1, b"new")
        durable.atomic_write(path, old, surface=s)
        _arm(f"fs.crash_point=n1:{point}@cache.l2")
        with pytest.raises(_Crash):
            durable.atomic_write(path, new, surface=s)
        crashes.append(point)
        faults.uninstall()
        # simulate the restart: boot sweep, then verified read
        durable.sweep_tmp(str(root))
        assert not any(
            fn.endswith(".tmp") for fn in os.listdir(root)
        ), f"debris at point {point}"
        got = durable.read_framed(path, "cache.l2", 1, surface="cache.l2")
        assert got is not None, f"torn file at point {point}"
        want = b"old" if point < durable.CRASH_ATOMIC_RENAMED else b"new"
        assert got[1] == want, f"wrong edge at point {point}"
    assert crashes == list(durable.ATOMIC_CRASH_POINTS)


class _Crash(BaseException):
    """Stands in for SIGKILL under the monkeypatched hook."""


def test_append_crash_points_replay_to_fsynced_edge(tmp_path, monkeypatch):
    monkeypatch.setattr(
        durable, "_CRASH_HOOK", lambda: (_ for _ in ()).throw(_Crash())
    )
    for point in durable.APPEND_CRASH_POINTS:
        path = str(tmp_path / f"j{point}.log")
        s = _surface("jobs.journal")
        j = durable.Journal(path, s, fmt="jobs.journal", version=1)
        j.append({"rec": "one"})
        _arm(f"fs.crash_point=n1:{point}@jobs.journal")
        with pytest.raises(_Crash):
            j.append({"rec": "two"})
        faults.uninstall()
        j.close()
        records, torn = durable.Journal.replay(path, "jobs.journal", 1)
        recs = [r["rec"] for r in records]
        if point == durable.CRASH_APPEND_PRE:
            assert recs == ["one"] and torn == 0
        else:
            # written-not-fsynced (6) may or may not survive a REAL
            # crash; under the in-process hook the bytes are in the
            # file, so replay sees both — the invariant is no torn
            # record and at least the fsynced edge
            assert recs[: 1] == ["one"] and torn == 0


def test_real_crash_hook_is_sigkill():
    assert durable._CRASH_HOOK is durable._crash


# ---------------------------------------------------------------- framing


def test_frame_unframe_roundtrip_with_extras():
    data = durable.frame("cache.l2", 1, b"body", extra={"status": 200})
    meta, body = durable.unframe(data, "cache.l2", 1)
    assert body == b"body"
    assert meta["status"] == 200
    assert meta["format"] == "cache.l2"
    assert meta["version"] == 1
    assert meta["len"] == 4
    assert meta["digest"] == durable.digest(b"body")


@pytest.mark.parametrize(
    "mutate",
    [
        lambda d: d[:-1],                      # truncated body
        lambda d: d + b"x",                    # appended garbage
        lambda d: b"not json\n" + d.split(b"\n", 1)[1],  # torn header
        lambda d: d.replace(b"cache.l2", b"other.fmt"),  # wrong format
        lambda d: d.replace(b"body", b"bodz"),           # flipped byte
    ],
)
def test_unframe_any_defect_reads_as_none(mutate):
    data = durable.frame("cache.l2", 1, b"body")
    assert durable.unframe(mutate(data), "cache.l2", 1) is None


def test_unframe_future_version_raises_before_digest_check():
    head = json.dumps(
        {"format": "cache.l2", "version": 2, "len": 0, "digest": "nope"}
    ).encode()
    with pytest.raises(durable.FutureVersionError):
        durable.unframe(head + b"\n", "cache.l2", 1)


def test_read_framed_future_version_reads_as_absent(tmp_path):
    path = str(tmp_path / "a.bin")
    s = _surface("cache.l2")
    durable.atomic_write(
        path, durable.frame("cache.l2", 2, b"body"), surface=s
    )
    assert durable.read_framed(path, "cache.l2", 1, surface="cache.l2") is None
    # fail-static: absent, not destroyed
    assert os.path.exists(path)


# ---------------------------------------------------------------- journal


def test_journal_header_written_with_first_append(tmp_path):
    path = str(tmp_path / "j.log")
    j = durable.Journal(
        path, _surface("jobs.journal"), fmt="jobs.journal", version=1
    )
    j.append({"rec": "a"})
    j.close()
    with open(path, "rb") as f:
        first = json.loads(f.readline())
    assert first == {"format": "jobs.journal", "version": 1}
    records, torn = durable.Journal.replay(path, "jobs.journal", 1)
    assert [r["rec"] for r in records] == ["a"]
    assert torn == 0


def test_journal_replay_refuses_future_version(tmp_path):
    path = str(tmp_path / "j.log")
    with open(path, "wb") as f:
        f.write(b'{"format":"jobs.journal","version":2}\n{"rec":"a"}\n')
    with pytest.raises(durable.FutureVersionError):
        durable.Journal.replay(path, "jobs.journal", 1)


def test_journal_legacy_headerless_file_replays_as_v1(tmp_path):
    path = str(tmp_path / "j.log")
    with open(path, "wb") as f:
        f.write(b'{"rec":"a"}\n{"rec":"b"}\n')
    records, torn = durable.Journal.replay(path, "jobs.journal", 1)
    assert [r["rec"] for r in records] == ["a", "b"]


def test_journal_rewrite_is_atomic_and_keeps_header(tmp_path):
    path = str(tmp_path / "j.log")
    j = durable.Journal(
        path, _surface("jobs.journal"), fmt="jobs.journal", version=1
    )
    for i in range(4):
        j.append({"rec": i})
    j.rewrite([{"rec": "only"}])
    j.close()
    with open(path, "rb") as f:
        first = json.loads(f.readline())
    assert first == {"format": "jobs.journal", "version": 1}
    records, _ = durable.Journal.replay(path, "jobs.journal", 1)
    assert [r["rec"] for r in records] == ["only"]
    assert not os.path.exists(path + ".tmp")


# ---------------------------------------- satellite: uniform boot sweeps


def test_boot_sweeps_shed_stale_tmp_across_all_eight_surfaces(tmp_path):
    """Seed stale .tmp debris in every surface's directory; every
    store's boot path sheds it — one sweep idiom, eight users."""
    m = Metrics()
    dirs = {}
    for name in (
        "jobs", "l2", "membership", "aot", "autoscale", "incidents",
        "calib", "spill",
    ):
        d = tmp_path / name
        d.mkdir()
        (d / "stale.tmp").write_bytes(b"debris")
        dirs[name] = str(d)

    # jobs.journal (JobManager owns jobs_dir: whole-dir sweep at boot,
    # exercised here exactly as the manager runs it) + jobs.spill
    from deconv_api_tpu.serving.jobs import JobJournal, SpillStore

    durable.sweep_tmp(dirs["jobs"])
    JobJournal(os.path.join(dirs["jobs"], "journal.jsonl")).close()
    SpillStore(dirs["spill"])
    # cache.l2
    from deconv_api_tpu.serving.cache import L2Store

    l2 = L2Store(dirs["l2"], 0, metrics=m)
    l2.close()
    # aot.store
    from deconv_api_tpu.serving.aot import ArtifactStore

    ArtifactStore(dirs["aot"], 0, metrics=m)
    # alerts.incidents
    from deconv_api_tpu.serving.alerts import IncidentStore

    IncidentStore(dirs["incidents"], metrics=m)
    # autoscale.journal (single-file sweep of <path>.tmp)
    from deconv_api_tpu.serving.autoscale import DecisionJournal

    aj_path = os.path.join(dirs["autoscale"], "decisions.jsonl")
    open(aj_path + ".tmp", "wb").write(b"")
    DecisionJournal(aj_path, metrics=m).close()
    assert not os.path.exists(aj_path + ".tmp")
    # fleet.membership (single-file sweep — shared dir, own .tmp only)
    mpath = os.path.join(dirs["membership"], "members.json")
    open(mpath + ".tmp", "wb").write(b"")
    durable.sweep_tmp_file(mpath)
    assert not os.path.exists(mpath + ".tmp")
    # quant.calib (dir sweep at save/boot)
    from deconv_api_tpu.engine.quant import save_calibration

    save_calibration(dirs["calib"], "m", {"b1c1": 1.0})

    for name, d in dirs.items():
        if name in ("membership", "autoscale"):
            # shared-dir contract: these single-file artifacts live at
            # operator-chosen paths, so only their own <path>.tmp is
            # swept (asserted above) — a sibling file is never touched
            continue
        assert not any(
            fn.endswith(".tmp") for fn in os.listdir(d)
        ), f"stale .tmp survives boot in {name}"


def test_membership_sweep_never_touches_foreign_tmp(tmp_path):
    """The membership file lives in a shared directory: the sweep may
    only shed OUR <path>.tmp, never a sibling application's files."""
    mpath = str(tmp_path / "members.json")
    open(mpath + ".tmp", "wb").write(b"")
    foreign = str(tmp_path / "other-app.tmp")
    open(foreign, "wb").write(b"")
    durable.sweep_tmp_file(mpath)
    assert not os.path.exists(mpath + ".tmp")
    assert os.path.exists(foreign)


# ----------------------------------- satellite: exposition lint coverage


def test_durable_families_pass_exposition_lint():
    """The new durable_* and fs.*-fed families hold the exposition
    contract: one TYPE per family, present at zero, escaped labels."""
    from tests.test_metrics_exposition import lint_exposition

    m = Metrics()
    durable.register_metrics(m)
    reg = faults.FaultRegistry(seed=0, metrics=m)
    reg.arm_string("fs.enospc=p1@cache.l2,fs.eio_read=p1@aot.store")
    faults.install(reg)
    s = _surface("cache.l2", metrics=m)
    # drive one failure so a labeled stream moves off zero
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        durable.atomic_write(os.path.join(d, "a"), b"x", surface=s)
    families, samples = lint_exposition(m.prometheus())
    assert families["deconv_durable_write_errors_total"] == "counter"
    assert families["deconv_durable_degraded"] == "gauge"
    assert families["deconv_faults_injected_total"] == "counter"
    # present at zero for every declared surface from the first scrape
    for name in durable.SURFACES:
        key = ("deconv_durable_write_errors_total", f'surface="{name}"')
        assert key in samples, f"missing zero stream for {name}"
    assert samples[
        ("deconv_durable_write_errors_total", 'surface="cache.l2"')
    ] == 1.0
    assert samples[
        ("deconv_durable_degraded", 'surface="cache.l2"')
    ] == 1.0
    # armed fs.* sites pre-register their injected counter at... one
    # here (the enospc fired); the merely-armed eio_read site shows 0
    assert samples[
        ("deconv_faults_injected_total", 'site="fs.enospc"')
    ] == 1.0
    assert samples[
        ("deconv_faults_injected_total", 'site="fs.eio_read"')
    ] == 0.0


# ------------------------------------ satellite: ENOSPC-on-L2 e2e contract


def test_e2e_enospc_on_l2_serves_byte_identical_200s(tmp_path):
    """The best-effort contract end to end: starve ONLY the L2 tier's
    disk and the server keeps answering byte-identical 200s — the only
    things that move are durable_write_errors_total, durable_degraded,
    and a frozen cache_l2_stores_total."""
    import asyncio
    import time as _time

    from tests.test_fleet_ha import _boot_backend, _form_body, _ha_cfg, _post

    async def go():
        svc, port = await _boot_backend(
            _ha_cfg(l2_dir=str(tmp_path / "l2"), fault_injection=True)
        )
        body = _form_body(31)
        status, h1, p1 = await _post(port, body)
        assert status == 200 and h1.get("x-cache") == "miss"
        # wait for the async writer to land the healthy store
        deadline = _time.monotonic() + 5.0
        while svc.metrics.counter("cache_l2_stores_total") < 1:
            assert _time.monotonic() < deadline, "healthy store never landed"
            await asyncio.sleep(0.01)
        stores_before = svc.metrics.counter("cache_l2_stores_total")

        svc.faults.arm_string("fs.enospc=p1@cache.l2")
        # a forced recompute writes through to the (now starved) L2
        status, h2, p2 = await _post(port, body, {"cache-control": "no-cache"})
        assert status == 200
        assert p2 == p1  # byte-identical under the fault
        # and a brand-new key computes + 200s with the store failing
        body3 = _form_body(32)
        status, _h3, p3 = await _post(port, body3)
        assert status == 200 and len(p3) > 0
        deadline = _time.monotonic() + 5.0
        while svc.metrics.labeled_gauge("durable_degraded").get(
            "cache.l2", 0
        ) != 1.0:
            assert _time.monotonic() < deadline, "degraded gauge never flipped"
            await asyncio.sleep(0.01)
        # only counters moved: no store landed under ENOSPC
        assert svc.metrics.counter("cache_l2_stores_total") == stores_before
        assert svc.metrics.labeled("durable_write_errors_total")[
            "cache.l2"
        ] >= 1
        # the readiness probe carries the durability block — degraded
        # best-effort tier, still ready
        from deconv_api_tpu.serving import fleet

        st, _h, rz = await fleet.raw_request(
            "127.0.0.1", port, "GET", "/readyz", {}, b"", 10.0
        )
        doc = json.loads(rz)
        assert st == 200, "a degraded best-effort tier must NOT fail readiness"
        blk = doc["durability"]
        assert blk["ok"] is False
        assert blk["surfaces"]["cache.l2"]["degraded"] is True
        assert blk["surfaces"]["cache.l2"]["policy"] == "best_effort"

        # recovery: disarm, force one more write-through, gauge clears
        svc.faults.disarm("fs.enospc")
        status, _h4, p4 = await _post(port, body, {"cache-control": "no-cache"})
        assert status == 200 and p4 == p1
        deadline = _time.monotonic() + 5.0
        while svc.metrics.labeled_gauge("durable_degraded").get(
            "cache.l2"
        ) != 0.0:
            assert _time.monotonic() < deadline, "gauge never cleared"
            await asyncio.sleep(0.01)
        await svc.stop()

    asyncio.run(go())


# --------------------------------- fail-loud: 503 on an undurable submit


def test_e2e_submit_answers_503_when_journal_fsync_fails(tmp_path):
    """The fail-loud contract end to end: a job submit whose journal
    append cannot reach disk answers 503 + Retry-After — never a 202
    the server could not honour across a crash — and leaves no job
    behind.  Pins errors.UndurableWrite flowing through the generic
    error path with its retry hint."""
    import asyncio

    from deconv_api_tpu.serving import fleet
    from tests.test_fleet_ha import _boot_backend, _form_body, _ha_cfg

    async def go():
        svc, port = await _boot_backend(
            _ha_cfg(jobs_dir=str(tmp_path / "jobs"), fault_injection=True)
        )
        body = _form_body(41) + b"&type=deconv"
        hdrs = {"content-type": "application/x-www-form-urlencoded"}
        svc.faults.arm_string("fs.fsync_error=n1@jobs.journal")
        st, h, payload = await fleet.raw_request(
            "127.0.0.1", port, "POST", "/v1/jobs", hdrs, body, 60.0
        )
        assert st == 503, payload[:200]
        doc = json.loads(payload)
        assert doc["error"] == "undurable_write"
        assert h.get("retry-after") == "1"
        assert svc.jobs.jobs_snapshot() == []  # nothing kept behind the 503
        assert svc.metrics.labeled("durable_write_errors_total")[
            "jobs.journal"
        ] >= 1

        # one-shot fault spent: the SAME submit now lands durably
        st2, h2, payload2 = await fleet.raw_request(
            "127.0.0.1", port, "POST", "/v1/jobs", hdrs, body, 60.0
        )
        assert st2 == 202, payload2[:200]
        assert len(svc.jobs.jobs_snapshot()) == 1
        await svc.stop()

    asyncio.run(go())


# ------------------------------- fail-loud: 503 on an undurable register


def test_register_answers_503_when_membership_persist_fails(tmp_path):
    """The router's registration route is durable-or-refused: when the
    membership file cannot be persisted, the backend gets 503 +
    Retry-After — never an acknowledgment the router would forget on
    restart.  Periodic rewrites merely log; only the register route
    escalates."""
    import asyncio

    from deconv_api_tpu.serving.fleet import FleetRouter
    from deconv_api_tpu.serving.http import Request

    token = "durable-fleet-token"
    mf = str(tmp_path / "members.json")
    router = FleetRouter([], membership_file=mf, fleet_token=token)
    _arm("fs.fsync_error=n1@fleet.membership")

    def req():
        return Request(
            method="POST", path="/v1/internal/register", query={},
            headers={
                "content-type": "application/x-www-form-urlencoded",
                "x-fleet-token": token,
            },
            body=b"backend=127.0.0.1:9001&action=register", id="rid-503",
        )

    async def go():
        r = await router._register(req())
        assert r.status == 503
        assert r.headers.get("retry-after") == "1"
        assert json.loads(r.body)["error"] == "undurable_write"
        assert router.metrics.labeled("durable_write_errors_total")[
            "fleet.membership"
        ] >= 1
        # the n1 fault is spent: the SAME announcement now lands durably
        r = await router._register(req())
        assert r.status == 200
        assert os.path.exists(mf)

    asyncio.run(go())


# ------------------------------------- satellite: legacy fault-site alias


def test_legacy_journal_write_error_aliases_to_fs_fsync(tmp_path):
    """Pre-round-24 drill scripts arm jobs.journal_write_error; it must
    keep firing — now through fs.fsync_error@jobs.journal."""
    reg = faults.FaultRegistry(seed=0)
    reg.arm_string("jobs.journal_write_error=n1")
    faults.install(reg)
    assert reg.snapshot()["armed"] == {"fs.fsync_error": "n1@jobs.journal"}
    s = _surface("jobs.journal")
    with open(str(tmp_path / "j.log"), "ab") as f:
        with pytest.raises(durable.DurableWriteError):
            durable.append_bytes(f, b"rec\n", surface=s)
    # targeted: the same arm never fires for another surface
    reg.arm_string("jobs.journal_write_error=n1")
    other = _surface("cache.l2")
    with open(str(tmp_path / "x.log"), "ab") as f:
        assert durable.append_bytes(f, b"rec\n", surface=other) is True
