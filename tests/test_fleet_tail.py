"""Tail-tolerance tests (round 17).

Covers the gray-failure layer in serving/fleet.py: the windowed
latency digests (forwards + probe RTTs in, SSE heads excluded), the
``slow`` outlier state (peer-median comparison, min-sample/absolute
floors, hysteresis + min-hold, last-fast-member valve), routing
demotion (round-robin skip, keyed last-resort with the peer-fill hint
back at the warm primary, jobs walks still answered), hedged requests
(delay-gated, first-wins, loser closed, token-bucket budget, the
never-hedged pins), the deadline-derived per-forward timeout, the
``fleet.*`` network-fault sites with the ``@target`` grammar, the
exposition lint for every new family, the ``tail_tolerance=False``
round-16 pin, and an e2e gray-backend drill over real backends.
"""

from __future__ import annotations

import asyncio
import base64
import json
import time
import urllib.parse

import httpx
import numpy as np
import pytest

from deconv_api_tpu.serving import faults as faults_mod
from deconv_api_tpu.serving import fleet
from deconv_api_tpu.serving.cache import canonical_digest
from deconv_api_tpu.serving.fleet import (
    FleetRouter,
    HedgeBudget,
    LatencyDigest,
)
from deconv_api_tpu.serving.http import Request
from deconv_api_tpu.serving.metrics import Metrics
from tests.test_metrics_exposition import lint_exposition


class _FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _ready_200():
    return 200, {}, json.dumps({"ready": True}).encode()


def _probe_script(monkeypatch, responses):
    async def fake(host, port, method, target, headers, body, timeout_s):
        return responses[f"{host}:{port}"]()

    monkeypatch.setattr(fleet, "raw_request", fake)


def _post_req(body: bytes, path="/v1/deconv", headers=None, **kw) -> Request:
    return Request(
        method="POST", path=path, query={},
        headers={
            "content-type": "application/x-www-form-urlencoded",
            **(headers or {}),
        },
        body=body, id="rid-tail", **kw,
    )


# ------------------------------------------------------------- digests


def test_latency_digest_window_cap_and_quantiles():
    clock = _FakeClock()
    d = LatencyDigest(window_s=10.0, cap=16, clock=clock)
    assert d.quantile(0.95) == 0.0 and len(d) == 0
    for v in range(1, 11):
        d.add(float(v))
    assert len(d) == 10
    assert d.quantile(0.50) == 6.0  # index int(0.5*10)=5 -> value 6
    assert d.quantile(0.95) == 10.0
    # cap: oldest evicted first
    for v in range(11, 31):
        d.add(float(v))
    assert len(d) == 16
    assert d.quantile(0.0) == 15.0
    # window: everything ages out
    clock.t += 10.1
    assert len(d) == 0 and d.quantile(0.95) == 0.0
    d.add(5.0)
    snap = d.snapshot()
    assert snap == {"n": 1, "p50_ms": 5.0, "p95_ms": 5.0}


def test_hedge_budget_is_a_request_fraction():
    b = HedgeBudget(pct=5.0, burst=2.0)
    assert b.try_spend() and b.try_spend()  # burst
    assert not b.try_spend()  # empty
    # 5% of 20 requests = 1 token
    for _ in range(20):
        b.on_request()
    assert b.try_spend()
    assert not b.try_spend()
    # deposits cap at burst
    for _ in range(10_000):
        b.on_request()
    assert b.tokens == 2.0


# ---------------------------------------------------- slow state machine


def _router3(clock, monkeypatch, **kw):
    kw.setdefault("eject_threshold", 2)
    kw.setdefault("slow_min_samples", 10)
    kw.setdefault("slow_hold_s", 10.0)
    kw.setdefault("slow_floor_ms", 10.0)
    kw.setdefault("latency_window_s", 2.0)
    router = FleetRouter(
        ["b0:8000", "b1:8001", "b2:8002"], clock=clock, **kw
    )
    _probe_script(
        monkeypatch,
        {n: _ready_200 for n in ("b0:8000", "b1:8001", "b2:8002")},
    )
    return router


def _feed(router, name, ms, n=20):
    m = router.members[name]
    for _ in range(n):
        router._observe_latency(m, ms)


def test_slow_promote_demote_hysteresis_and_min_hold(monkeypatch):
    clock = _FakeClock()
    router = _router3(clock, monkeypatch)

    async def go():
        await router.probe_once()
        ring_before = router.ring.members
        _feed(router, "b0:8000", 5.0)
        _feed(router, "b1:8001", 6.0)
        _feed(router, "b2:8002", 300.0)
        router._update_slow_states()
        gray = router.members["b2:8002"]
        assert gray.state == "slow" and gray.in_ring
        # placement NEVER moves on a slow transition: recovery restores
        # cache affinity with zero rebalance
        assert router.ring.members == ring_before
        assert router.metrics.labeled("slow_ejections_total") == {
            "b2:8002": 1
        }
        gauges = router.metrics.labeled_gauge("backend_latency_p95_ms")
        assert gauges["b2:8002"] == pytest.approx(300.0)
        # hysteresis: p95 recovered into the band (between restore_k
        # and eject_k x ref) does NOT restore...
        clock.t += 2.1  # age the 300ms samples out of the window
        _feed(router, "b0:8000", 5.0)
        _feed(router, "b1:8001", 6.0)
        _feed(router, "b2:8002", 15.0)  # ~2.7x the peer median of 5.5
        router._update_slow_states()
        assert gray.state == "slow"
        # ...and a FULL recovery inside the min-hold stays slow too
        clock.t += 2.1
        _feed(router, "b0:8000", 5.0)
        _feed(router, "b1:8001", 6.0)
        _feed(router, "b2:8002", 6.0)
        assert clock.t - gray.slow_since < router.slow_hold_s
        router._update_slow_states()
        assert gray.state == "slow"  # no flap
        # past the hold with a recovered p95: restored
        clock.t += 8.0
        _feed(router, "b0:8000", 5.0)
        _feed(router, "b1:8001", 6.0)
        _feed(router, "b2:8002", 6.0)
        router._update_slow_states()
        assert gray.state == "healthy"
        assert router.ring.members == ring_before

    asyncio.run(go())


def test_slow_needs_floors_and_never_demotes_last_fast(monkeypatch):
    clock = _FakeClock()
    router = _router3(clock, monkeypatch)

    async def go():
        await router.probe_once()
        # absolute floor: a 40x ratio under slow_floor_ms is jitter
        _feed(router, "b0:8000", 0.1)
        _feed(router, "b1:8001", 0.1)
        _feed(router, "b2:8002", 4.0)
        router._update_slow_states()
        assert router.members["b2:8002"].state == "healthy"
        # min-sample floor: 3 huge samples convict nobody
        clock.t += 2.1
        _feed(router, "b0:8000", 5.0)
        _feed(router, "b1:8001", 5.0)
        _feed(router, "b2:8002", 500.0, n=3)
        router._update_slow_states()
        assert router.members["b2:8002"].state == "healthy"
        # last-fast-member valve (2-member fleet): with b1 already
        # slow, b0 can never be demoted no matter its ratio
        r2 = FleetRouter(
            ["b0:8000", "b1:8001"], clock=clock,
            slow_min_samples=10, slow_floor_ms=10.0,
            latency_window_s=2.0,
        )
        await r2.probe_once()
        _feed(r2, "b0:8000", 5.0)
        _feed(r2, "b1:8001", 300.0)
        r2._update_slow_states()
        assert r2.members["b1:8001"].state == "slow"
        clock.t += 2.1
        _feed(r2, "b0:8000", 3000.0)
        _feed(r2, "b1:8001", 300.0)
        r2._update_slow_states()
        assert r2.members["b0:8000"].state == "healthy"

    asyncio.run(go())


def test_restore_liveness_without_peer_references(monkeypatch):
    """Review fixes: a channel with no peer reference is SKIPPED in
    restore (judging a canary's legitimate compute against the bare
    absolute floor would pin a recovered member forever), and a slow
    member with no possible comparison at all (solo survivor) restores
    once the hold elapses."""
    clock = _FakeClock()
    router = _router3(clock, monkeypatch)

    async def go():
        # probe channel qualified everywhere (min_probe=2 here)
        for _ in range(4):
            await router.probe_once()
        gray = router.members["b2:8002"]
        gray.slow_since = clock.t - 60.0  # hold long elapsed
        router._set_state(gray, "slow", "test")
        # one legitimate 60ms canary forward, NO peer forward
        # reference: the fwd channel is skipped, the probe channel is
        # clean -> restored (pre-fix: 60 >= bare floor 10 pinned it)
        router._observe_latency(gray, 60.0)
        router._update_slow_states()
        assert gray.state == "healthy"
        # solo survivor: no peers in the ring at all -> no channel
        # offers a comparison -> restore after hold (demotion with
        # nobody to route to is meaningless)
        router._set_state(gray, "slow", "test2")
        gray.slow_since = clock.t - 60.0
        for n in ("b0:8000", "b1:8001"):
            router._set_state(router.members[n], "ejected", "test2")
        router._update_slow_states()
        assert gray.state == "healthy"

    asyncio.run(go())


def test_latency_gauges_zero_when_windows_empty(monkeypatch):
    """Review fix: an emptied (or cleared-on-ejection) window must
    publish 0, not freeze the last pre-crash value under an alerting
    rule's nose."""
    clock = _FakeClock()
    router = _router3(clock, monkeypatch)

    async def go():
        await router.probe_once()
        _feed(router, "b0:8000", 50.0)
        router._update_slow_states()
        g = router.metrics.labeled_gauge("backend_latency_p95_ms")
        assert g["b0:8000"] == pytest.approx(50.0)
        clock.t += 10.0  # everything ages out of the 2s window
        router._update_slow_states()
        g = router.metrics.labeled_gauge("backend_latency_p95_ms")
        assert g["b0:8000"] == 0.0

    asyncio.run(go())


def test_slow_skipped_by_rr_keyed_last_resort_and_jobs_walk(monkeypatch):
    clock = _FakeClock()
    router = _router3(clock, monkeypatch)
    forwards: list[tuple[str, str | None]] = []

    async def capture(host, port, method, target, headers, body, timeout_s):
        forwards.append((f"{host}:{port}", headers.get("x-peer-fill")))
        if target.startswith("/v1/jobs/"):
            return 200, {}, json.dumps({"id": "j1", "state": "done"}).encode()
        return 200, {}, b"{}"

    async def go():
        await router.probe_once()
        _feed(router, "b0:8000", 5.0)
        _feed(router, "b1:8001", 6.0)
        _feed(router, "b2:8002", 300.0)
        router._update_slow_states()
        assert router.members["b2:8002"].state == "slow"
        monkeypatch.setattr(fleet, "raw_request", capture)
        # round-robin (unkeyed GET) never lands on the slow member
        for _ in range(8):
            req = Request(
                method="GET", path="/v1/models", query={}, headers={},
                body=b"", id="rid-rr",
            )
            assert (await router._proxy(req)).status == 200
        assert "b2:8002" not in {b for b, _h in forwards}
        # keyed: a body owned by the slow member demotes to the next
        # fast owner, with an x-peer-fill hint back at the warm primary
        body = None
        for i in range(200):
            cand = f"layer=c3&file=probe{i}".encode()
            key = canonical_digest(
                "fleet|/v1/deconv",
                "application/x-www-form-urlencoded", cand,
            )
            if router.ring.owner(key) == "b2:8002":
                body = cand
                key_owned = key
                break
        assert body is not None
        routed_before = router.metrics.counter("slow_routed_around_total")
        forwards.clear()
        resp = await router._proxy(_post_req(body))
        assert resp.status == 200
        served, hint = forwards[0]
        assert served != "b2:8002"
        assert served == next(
            n for n in router.ring.owners(key_owned) if n != "b2:8002"
        )
        assert hint == "b2:8002"
        assert (
            router.metrics.counter("slow_routed_around_total")
            == routed_before + 1
        )
        # every Nth demoted pick is a CANARY back to the slow primary
        # — the restore-evidence channel for device-level grays whose
        # probes stay fast (and it is never hedged: a winning hedge
        # would cancel the very observation it exists to collect)
        canary_router = _router3(
            clock, monkeypatch, slow_canary_every=4
        )
        await canary_router.probe_once()
        canary_router.members["b2:8002"].state = "slow"
        canary_router._slow_epoch += 1
        hedge_before = canary_router.metrics.counter("hedges_fired_total")
        picks = [
            canary_router._pick(key_owned, set()).name for _ in range(8)
        ]
        assert picks.count("b2:8002") == 2  # every 4th
        assert (
            canary_router.metrics.counter("slow_canary_forwards_total")
            == 2
        )
        assert (
            canary_router.metrics.counter("hedges_fired_total")
            == hedge_before
        )
        # (_router3 re-pointed the transport at its probe script)
        monkeypatch.setattr(fleet, "raw_request", capture)
        # ALL slow: the fleet still serves — primary is last resort
        for n in ("b0:8000", "b1:8001"):
            router.members[n].state = "slow"
        router._slow_epoch += 1
        forwards.clear()
        resp = await router._proxy(_post_req(body))
        assert resp.status == 200
        assert forwards[0][0] == "b2:8002"
        for n in ("b0:8000", "b1:8001"):
            router.members[n].state = "healthy"
        router._slow_epoch += 1
        # the jobs ENTITY walk still asks a slow member — it may be the
        # only holder of the job's durable state
        router._learn_job_owner("j1", "b2:8002")
        forwards.clear()
        req = Request(
            method="GET", path="/v1/jobs/j1", query={}, headers={},
            body=b"", id="rid-job",
        )
        resp = await router._proxy(req)
        assert resp.status == 200
        assert forwards[0][0] == "b2:8002"
        # and the collection fan-out includes it (it is in the ring)
        forwards.clear()
        req = Request(
            method="GET", path="/v1/jobs", query={}, headers={},
            body=b"", id="rid-coll",
        )
        await router._proxy(req)
        assert "b2:8002" in {b for b, _h in forwards}

    asyncio.run(go())


# -------------------------------------------------------------- hedging


def _seed_fleet_latency(router, ms=10.0, n=4):
    m = next(iter(router.members.values()))
    for _ in range(n):
        router._observe_latency(m, ms)


def test_hedge_fires_after_delay_first_wins_loser_closed(monkeypatch):
    router = FleetRouter(
        ["b0:8000", "b1:8001"], eject_threshold=2,
        slow_min_samples=2, hedge_min_delay_ms=20.0,
    )
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    body = b"layer=c3&file=hedge-me"
    key = canonical_digest(
        "fleet|/v1/deconv", "application/x-www-form-urlencoded", body
    )
    calls: list[str] = []
    cancelled: dict[str, bool] = {}
    stall: set[str] = set()

    async def fake(host, port, method, target, headers, body, timeout_s):
        name = f"{host}:{port}"
        calls.append(name)
        if name in stall:
            try:
                await asyncio.sleep(30.0)
            except asyncio.CancelledError:
                cancelled[name] = True
                raise
        return 200, {}, name.encode()

    async def go():
        await router.probe_once()
        owner = router.ring.owner(key)
        other = next(n for n in router.members if n != owner)
        _seed_fleet_latency(router)
        monkeypatch.setattr(fleet, "raw_request", fake)
        # a primary answering WITHIN the delay never hedges
        resp = await router._proxy(_post_req(body))
        assert resp.status == 200 and calls == [owner]
        assert router.metrics.counter("hedges_fired_total") == 0
        # a stalled primary: the duplicate fires to the next distinct
        # owner, its response wins, the loser's connection is closed
        calls.clear()
        stall.add(owner)
        t0 = time.perf_counter()
        resp = await router._proxy(_post_req(body))
        dt = time.perf_counter() - t0
        assert resp.status == 200
        assert resp.body == other.encode()
        assert resp.headers["x-backend"] == other
        assert calls == [owner, other]
        assert dt < 5.0  # the 30s stall never held the client
        assert router.metrics.counter("hedges_fired_total") == 1
        assert router.metrics.counter("hedges_won_total") == 1
        await asyncio.sleep(0.05)  # let the cancel land
        assert cancelled.get(owner) is True
        # the hedge cost a whole token
        assert router.hedge_budget.tokens < router.hedge_budget.burst

    asyncio.run(go())


def test_hedge_budget_exhaustion_denies(monkeypatch):
    router = FleetRouter(
        ["b0:8000", "b1:8001"], slow_min_samples=2,
        hedge_min_delay_ms=10.0,
    )
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    body = b"layer=c3&file=deny-me"
    slow_everyone = {"delay": 0.05}

    async def fake(host, port, method, target, headers, body, timeout_s):
        await asyncio.sleep(slow_everyone["delay"])
        return 200, {}, f"{host}:{port}".encode()

    async def go():
        await router.probe_once()
        _seed_fleet_latency(router, ms=1.0)
        monkeypatch.setattr(fleet, "raw_request", fake)
        router.hedge_budget._tokens = 0.0  # drained bucket
        resp = await router._proxy(_post_req(body))
        assert resp.status == 200
        assert router.metrics.counter("hedges_fired_total") == 0
        assert (
            router.metrics.counter("hedges_budget_denied_total") == 1
        )

    asyncio.run(go())


def test_job_submit_sse_and_no_cache_never_hedged(monkeypatch):
    router = FleetRouter(
        ["b0:8000", "b1:8001"], slow_min_samples=2,
        hedge_min_delay_ms=10.0,
    )
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    calls: list[str] = []
    stream_calls: list[str] = []

    async def slow_ok(host, port, method, target, headers, body, timeout_s):
        calls.append(f"{host}:{port}")
        await asyncio.sleep(0.05)  # well past the hedge delay
        if target == "/v1/jobs":
            return 202, {"location": "/v1/jobs/j9"}, b"{}"
        return 200, {}, b"{}"

    async def fake_stream(
        host, port, method, target, headers, body, head_timeout_s
    ):
        stream_calls.append(f"{host}:{port}")

        async def chunks():
            yield b"data: x\n\n"

        return 200, {"content-type": "text/event-stream"}, chunks()

    async def go():
        await router.probe_once()
        _seed_fleet_latency(router, ms=1.0)
        monkeypatch.setattr(fleet, "raw_request", slow_ok)
        monkeypatch.setattr(fleet, "raw_request_stream", fake_stream)
        # job submit: one attempt, one backend, zero hedges
        resp = await router._proxy(_post_req(b"type=dream", path="/v1/jobs"))
        assert resp.status == 202 and len(calls) == 1
        # forced recompute: a WRITE is never duplicated
        calls.clear()
        resp = await router._proxy(
            _post_req(
                b"layer=c3&file=x", headers={"cache-control": "no-cache"}
            )
        )
        assert resp.status == 200 and len(calls) == 1
        # SSE: the stream path never races, and its head is EXCLUDED
        # from the latency digest
        router._learn_job_owner("j9", "b0:8000")
        digest_before = len(router._fleet_latency)
        req = Request(
            method="GET", path="/v1/jobs/j9/events", query={},
            headers={}, body=b"", id="rid-sse",
        )
        resp = await router._proxy(req)
        assert resp.stream is not None and len(stream_calls) == 1
        assert len(router._fleet_latency) == digest_before
        assert router.metrics.counter("hedges_fired_total") == 0

    asyncio.run(go())


def test_probe_channel_floor_clamped_to_probe_supply():
    """Review fix: the probe CHANNEL's sample floor must be reachable
    by probes alone (window/interval per window), or an idle fleet
    could never detect a network gray and a demoted member — fed
    almost only by probes — could never testify to its own recovery.
    The forward channel keeps the honest slow_min_samples floor."""
    r = FleetRouter(["b0:8000"])  # defaults: 30s window / 2s probes
    assert r.slow_min_samples == 20  # forwards: unclamped
    assert r._min_probe_samples == 14  # 15 probe samples/window - 1
    r = FleetRouter(
        ["b0:8000"], probe_interval_s=0.25, latency_window_s=6.0,
        slow_min_samples=8,
    )
    assert r._min_probe_samples == 8  # supply (24) exceeds the floor
    # even a degenerate cadence keeps the member judgeable
    r = FleetRouter(
        ["b0:8000"], probe_interval_s=10.0, latency_window_s=30.0,
        slow_min_samples=20,
    )
    assert r._min_probe_samples == 2


def test_busy_member_not_demoted_against_idle_probe_windows(monkeypatch):
    """Review fix: forwards carry compute + queue wait, probe RTTs
    carry neither — judged per channel, a skewed workload (all compute
    on one member, peers idle) shows no outlier: the forward channel
    has no peer reference and the probe channel is symmetric."""
    clock = _FakeClock()
    router = _router3(clock, monkeypatch)

    async def go():
        for _ in range(4):  # probe channel qualified on all members
            await router.probe_once()
        # b0 alone carries real traffic at a legitimate 80ms
        _feed(router, "b0:8000", 80.0)
        router._update_slow_states()
        assert all(
            m.state == "healthy" for m in router.members.values()
        )

    asyncio.run(go())


def test_restore_not_blocked_by_sub_floor_jitter(monkeypatch):
    """Review fix: restore gates on the window MAX, but a max under
    slow_floor_ms could never have convicted anyone — on a sub-ms
    fleet one small blip per window must not pin `slow` forever."""
    clock = _FakeClock()
    router = _router3(clock, monkeypatch)

    async def go():
        await router.probe_once()
        _feed(router, "b0:8000", 1.0)
        _feed(router, "b1:8001", 1.0)
        _feed(router, "b2:8002", 300.0)
        router._update_slow_states()
        gray = router.members["b2:8002"]
        assert gray.state == "slow"
        clock.t += 12.0  # past hold, old samples aged out
        _feed(router, "b0:8000", 1.0)
        _feed(router, "b1:8001", 1.0)
        # recovered, but one 3ms blip: 3 > restore_k(2) x ref(1) —
        # yet 3 < slow_floor_ms(10), so it restores
        _feed(router, "b2:8002", 1.0)
        router._observe_latency(gray, 3.0)
        router._update_slow_states()
        assert gray.state == "healthy"

    asyncio.run(go())


def test_probe_rtts_stay_out_of_the_hedge_delay_digest(monkeypatch):
    """Review fix: probe RTTs (~1ms, always flowing) must not define
    the "live fleet p95" the hedge delay derives from — a lightly
    loaded fleet would otherwise hedge healthy compute requests."""
    router = FleetRouter(["b0:8000", "b1:8001"], slow_min_samples=2)
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )

    async def go():
        for _ in range(4):
            await router.probe_once()
        m = router.members["b0:8000"]
        assert len(m.latency) >= 4  # member digest: probes counted
        assert len(router._fleet_latency) == 0  # hedge source: not
        assert router._hedge_delay_s() is None  # no forwards, no hedge

    asyncio.run(go())


def test_hot_key_replica_cache_invalidated_by_slow_transition(monkeypatch):
    """Review fix: a healthy<->slow transition changes WHICH owners may
    serve a hot key without changing ring identity or the hot set — the
    cached replica list must not keep spreading reads onto the demoted
    member."""
    router = FleetRouter(
        ["b0:8000", "b1:8001", "b2:8002"],
        hot_key_top_k=1, hot_key_replicas=2, hot_key_min_rate=2.0,
        slow_min_samples=2,
    )
    _probe_script(
        monkeypatch,
        {n: _ready_200 for n in ("b0:8000", "b1:8001", "b2:8002")},
    )
    forwards: list[str] = []

    async def capture(host, port, method, target, headers, body, timeout_s):
        forwards.append(f"{host}:{port}")
        return 200, {}, b"{}"

    body = b"layer=c3&file=hot-slow"

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", capture)
        for _ in range(6):
            await router._proxy(_post_req(body))
        router.hot_keys.recompute()
        key = next(iter(router.hot_keys.hot_keys))
        primary = router.ring.owner(key)
        replica = router.ring.owners(key)[1]
        # warm the replica cache with the healthy spread
        forwards.clear()
        for _ in range(4):
            await router._proxy(_post_req(body))
        assert set(forwards) == {primary, replica}
        # the replica goes slow THROUGH the real transition: reads
        # must stop spreading onto it immediately
        router._set_state(
            router.members[replica], "slow", "test_slow"
        )
        forwards.clear()
        for _ in range(6):
            await router._proxy(_post_req(body))
        assert set(forwards) == {primary}
        # restore: the spread resumes
        router._set_state(
            router.members[replica], "healthy", "test_restore"
        )
        forwards.clear()
        for _ in range(6):
            await router._proxy(_post_req(body))
        assert set(forwards) == {primary, replica}
        # a slow PRIMARY collapses the spread entirely: the key falls
        # to the normal keyed demotion path — stand-in serves, with
        # the x-peer-fill hint back at the warm primary
        router._set_state(
            router.members[primary], "slow", "test_slow_primary"
        )
        forwards.clear()
        for _ in range(6):
            await router._proxy(_post_req(body))
        assert primary not in set(forwards)

    asyncio.run(go())


# ------------------------------------------------------------- deadlines


def test_deadline_expired_at_router_and_capped_timeout(monkeypatch):
    router = FleetRouter(["b0:8000", "b1:8001"])
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    seen_timeouts: list[float] = []

    async def capture(host, port, method, target, headers, body, timeout_s):
        seen_timeouts.append(timeout_s)
        return 200, {}, b"{}"

    async def go():
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", capture)
        # already expired: 504 at the router, NO backend consumed
        resp = await router._proxy(
            _post_req(b"layer=c3", deadline=time.perf_counter() - 1.0)
        )
        assert resp.status == 504
        assert json.loads(resp.body)["error"] == "deadline_expired"
        assert "x-backend" not in resp.headers
        assert seen_timeouts == []
        assert router.metrics.counter("deadline_expired_total") == 1
        # live budget: the per-forward timeout is min(forward timeout,
        # remaining budget) — never the flat 330 s
        resp = await router._proxy(
            _post_req(b"layer=c3", deadline=time.perf_counter() + 0.2)
        )
        assert resp.status == 200
        assert 0.0 < seen_timeouts[0] <= 0.2

        # a deadline-capped forward that TIMES OUT is the caller's
        # budget lapsing, not backend death: 504 deadline_expired, no
        # breaker/ejection state, no blind retry against the budget
        async def timeout_raise(
            host, port, method, target, headers, body, timeout_s
        ):
            seen_timeouts.append(timeout_s)
            try:
                raise asyncio.TimeoutError()
            except asyncio.TimeoutError as te:
                raise fleet._BackendError(
                    f"{host}:{port}: TimeoutError"
                ) from te

        monkeypatch.setattr(fleet, "raw_request", timeout_raise)
        n_before = len(seen_timeouts)
        resp = await router._proxy(
            _post_req(b"layer=c3", deadline=time.perf_counter() + 0.05)
        )
        assert resp.status == 504
        assert json.loads(resp.body)["error"] == "deadline_expired"
        assert len(seen_timeouts) == n_before + 1  # exactly one attempt
        for m in router.members.values():
            assert m.in_ring and m.breaker.state_name == "closed"

    asyncio.run(go())


def test_deadline_capped_timeouts_stay_clean_in_hedge_and_job_walk(
    monkeypatch,
):
    """Review fixes: a deadline-capped timeout is the CALLER's budget
    lapsing everywhere it can happen — inside the hedge race and on
    the jobs walks too, not just the plain keyed forward.  504
    deadline_expired, breakers untouched."""
    router = FleetRouter(
        ["b0:8000", "b1:8001"], eject_threshold=2,
        slow_min_samples=2, hedge_min_delay_ms=5.0,
    )
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )

    async def slow_then_timeout(
        host, port, method, target, headers, body, timeout_s
    ):
        await asyncio.sleep(0.03)
        try:
            raise asyncio.TimeoutError()
        except asyncio.TimeoutError as te:
            raise fleet._BackendError(
                f"{host}:{port}: TimeoutError"
            ) from te

    async def go():
        await router.probe_once()
        _seed_fleet_latency(router, ms=1.0)
        monkeypatch.setattr(fleet, "raw_request", slow_then_timeout)
        # hedged: both legs fire (delay 5ms < the 30ms stall), both
        # time out under the deadline cap -> 504, no breaker state
        resp = await router._proxy(
            _post_req(b"layer=c3", deadline=time.perf_counter() + 0.08)
        )
        assert resp.status == 504
        assert json.loads(resp.body)["error"] == "deadline_expired"
        for m in router.members.values():
            assert m.in_ring and m.breaker.state_name == "closed"
        # jobs entity walk: pinned owner times out under the cap
        router._learn_job_owner("jd", "b0:8000")
        req = Request(
            method="GET", path="/v1/jobs/jd", query={}, headers={},
            body=b"", id="rid-jd",
            deadline=time.perf_counter() + 0.05,
        )
        resp = await router._proxy(req)
        assert resp.status == 504
        assert json.loads(resp.body)["error"] == "deadline_expired"
        for m in router.members.values():
            assert m.in_ring and m.breaker.state_name == "closed"

    asyncio.run(go())


# ------------------------------------------------------ fleet.* fault sites


def test_fault_spec_target_grammar_and_targeted_firing():
    spec = faults_mod.parse_spec("p0.5:150@b0:8000")
    assert (spec.p, spec.param, spec.target) == (0.5, 150.0, "b0:8000")
    assert str(spec) == "p0.5:150@b0:8000"
    spec = faults_mod.parse_spec("n2@10.0.0.1:9999")
    assert (spec.n, spec.param, spec.target) == (2, None, "10.0.0.1:9999")
    with pytest.raises(ValueError):
        faults_mod.parse_spec("p0.5@")
    # a targeted one-shot never fires — or burns its count — for
    # anyone but its target
    reg = faults_mod.FaultRegistry()
    reg.arm("fleet.torn_body", "n1@b0:8000")
    assert reg.check("fleet.torn_body", who="b1:8001") is None
    assert reg.check("fleet.torn_body", who=None) is None
    assert reg.snapshot()["armed"] == {"fleet.torn_body": "n1@b0:8000"}
    assert reg.check("fleet.torn_body", who="b0:8000") is not None
    assert reg.check("fleet.torn_body", who="b0:8000") is None  # spent


def test_fleet_fault_sites_shape_the_transport(monkeypatch):
    clock = _FakeClock()
    router = FleetRouter(
        ["b0:8000", "b1:8001"], eject_threshold=2, cooldown_s=5.0,
        probe_timeout_s=0.05, fault_injection=True, clock=clock,
    )
    _probe_script(
        monkeypatch, {"b0:8000": _ready_200, "b1:8001": _ready_200}
    )
    forwards: list[str] = []

    async def capture(host, port, method, target, headers, body, timeout_s):
        forwards.append(f"{host}:{port}")
        return 200, {}, b"{}"

    body = None

    async def go():
        nonlocal body
        await router.probe_once()
        monkeypatch.setattr(fleet, "raw_request", capture)
        # torn body on b0: the keyed forward fails over to b1 with zero
        # client-visible error
        for i in range(200):
            cand = f"layer=c3&file=torn{i}".encode()
            key = canonical_digest(
                "fleet|/v1/deconv",
                "application/x-www-form-urlencoded", cand,
            )
            if router.ring.owner(key) == "b0:8000":
                body = cand
                break
        router.faults.arm("fleet.torn_body", "n1@b0:8000")
        resp = await router._proxy(_post_req(body))
        assert resp.status == 200
        assert resp.headers["x-backend"] == "b1:8001"
        assert forwards == ["b0:8000", "b1:8001"]
        assert router.metrics.labeled("faults_injected_total") == {
            "fleet.torn_body": 1
        }
        # head delay on b0: probe-200 survives but the RTT lands in the
        # digest — the gray signature the slow machinery reads
        router.faults.arm("fleet.head_delay_ms", "p1:80@b0:8000")
        resp = await router._proxy(_post_req(body))
        assert resp.status == 200
        assert (
            router.members["b0:8000"].latency.quantile(0.95) >= 80.0
        )
        router.faults.disarm("fleet.head_delay_ms")
        # blackhole on b1: probes burn their timeout and fail — two
        # consecutive ticks eject it through the NORMAL breaker path
        router.faults.arm("fleet.blackhole", "p1@b1:8001")
        await router.probe_once()
        await router.probe_once()
        assert router.members["b1:8001"].state == "ejected"
        router.faults.disarm("fleet.blackhole")
        clock.t += 5.1
        await router.probe_once()
        assert router.members["b1:8001"].state == "healthy"

    asyncio.run(go())


# -------------------------------------------------- escape hatch + lint


def test_tail_off_pins_round16_topology(monkeypatch):
    clock = _FakeClock()
    router = FleetRouter(
        ["b0:8000", "b1:8001", "b2:8002"], tail_tolerance=False,
        clock=clock,
    )
    _probe_script(
        monkeypatch,
        {n: _ready_200 for n in ("b0:8000", "b1:8001", "b2:8002")},
    )
    forwards: list[str] = []

    async def capture(host, port, method, target, headers, body, timeout_s):
        forwards.append(f"{host}:{port}")
        return 200, {}, b"{}"

    async def go():
        await router.probe_once()
        assert router.hedge_budget is None
        assert router._hedge_delay_s() is None
        # digests are never fed — the layer leaves ZERO state
        m = router.members["b0:8000"]
        router._observe_latency(m, 500.0)
        assert len(m.latency) == 0
        monkeypatch.setattr(fleet, "raw_request", capture)
        for i in range(32):
            cand = f"layer=c3&file=off{i}".encode()
            key = canonical_digest(
                "fleet|/v1/deconv",
                "application/x-www-form-urlencoded", cand,
            )
            resp = await router._proxy(_post_req(cand))
            assert resp.status == 200
            # placement is EXACTLY the round-16 pure ring function
            assert forwards[-1] == router.ring.owner(key)
        # forwards fed nothing, judged nothing
        assert all(len(m.latency) == 0 for m in router.members.values())
        router._update_slow_states()
        assert all(
            m.state == "healthy" for m in router.members.values()
        )
        assert router.metrics.counter("hedges_fired_total") == 0
        cfg = json.loads(
            (await router._config(None)).body
        )
        assert cfg["tail_tolerance"]["enabled"] is False

    asyncio.run(go())


def test_new_metric_families_lint():
    r = Metrics(prefix="router", core=False)
    r.inc_labeled("slow_ejections_total", "backend", "b0:8000")
    r.set_labeled_gauge("backend_latency_p50_ms", "backend", "b0:8000", 4.2)
    r.set_labeled_gauge("backend_latency_p95_ms", "backend", "b0:8000", 9.9)
    for c in (
        "hedges_fired_total",
        "hedges_won_total",
        "hedges_budget_denied_total",
        "slow_routed_around_total",
        "slow_canary_forwards_total",
        "deadline_expired_total",
    ):
        r.inc_counter(c, 2)
    reg = faults_mod.FaultRegistry(metrics=r)
    reg.arm("fleet.blackhole", "n1@b0:8000")
    assert reg.check("fleet.blackhole", who="b0:8000") is not None
    families, samples = lint_exposition(r.prometheus())
    assert families["router_slow_ejections_total"] == "counter"
    assert families["router_backend_latency_p50_ms"] == "gauge"
    assert families["router_backend_latency_p95_ms"] == "gauge"
    assert families["router_hedges_fired_total"] == "counter"
    assert families["router_hedges_won_total"] == "counter"
    assert families["router_hedges_budget_denied_total"] == "counter"
    assert families["router_slow_routed_around_total"] == "counter"
    assert families["router_slow_canary_forwards_total"] == "counter"
    assert families["router_deadline_expired_total"] == "counter"
    assert families["router_faults_injected_total"] == "counter"
    assert (
        samples[("router_slow_ejections_total", 'backend="b0:8000"')]
        == 1.0
    )
    assert (
        samples[("router_backend_latency_p95_ms", 'backend="b0:8000"')]
        == 9.9
    )


# ----------------------------------------------------------------- e2e


@pytest.mark.parametrize("n", [2])
def test_e2e_gray_backend_detected_routed_around_and_restored(n):
    """The whole round in one drill: a REAL backend made gray through
    the router-side ``fleet.head_delay_ms`` site (its /readyz stays
    200 — only the network path is slow), detected by probe RTTs
    alone, demoted from routing with zero client errors, and restored
    after disarm."""
    from tests.test_fleet import FleetFixture, _data_url

    with FleetFixture(
        n_backends=n,
        router_kw=dict(
            probe_interval_s=0.1,
            probe_timeout_s=2.0,
            slow_min_samples=4,
            latency_window_s=4.0,
            slow_hold_s=0.3,
            slow_floor_ms=5.0,
            # narrow the restore band: the test's own compute traffic
            # jitters the healthy peer's p95, and a 2-member fleet's
            # reference is exactly that one peer — 1.5 keeps the slow
            # dwell stable under host-load noise without blocking the
            # post-disarm restore
            slow_restore_k=1.5,
            fault_injection=True,
        ),
    ) as f:
        gray = f"127.0.0.1:{f.ports[0]}"
        healthy = f"127.0.0.1:{f.ports[1]}"
        # pre-warm BOTH backends (first-request XLA compiles cost
        # seconds; a compile-era forward sample would inflate the
        # healthy peer's p95 and let the gray member restore early),
        # then let the compile-era samples age out of the window
        for i in range(6):
            resp = httpx.post(
                f.router_url + "/",
                data={"file": _data_url(200 + i), "layer": "b2c1"},
                timeout=120,
            )
            assert resp.status_code == 200, resp.text
        time.sleep(4.5)
        # arm through the router's own debug surface
        r = httpx.post(
            f.router_url + "/v1/debug/faults",
            data={"arm": f"fleet.head_delay_ms=p1:250@{gray}"},
            timeout=10,
        )
        assert r.status_code == 200, r.text
        assert "fleet.head_delay_ms" in r.json()["faults"]["armed"]

        def slow_set():
            rz = httpx.get(f.router_url + "/readyz", timeout=10)
            return (rz.json().get("tail") or {}).get("slow", [])

        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and gray not in slow_set():
            time.sleep(0.2)
        assert gray in slow_set(), "gray backend never detected"
        assert (
            f.router.members[gray].breaker.state_name == "closed"
        ), "latency must never feed the ejection breaker"
        # /v1/config shows the state + per-member windows (read NOW,
        # before any compute traffic can jitter the peer reference)
        cfg = httpx.get(f.router_url + "/v1/config", timeout=10).json()
        assert cfg["members"][gray]["state"] == "slow"
        assert cfg["members"][gray]["latency"]["p95_ms"] >= 100.0
        assert cfg["tail_tolerance"]["enabled"] is True
        # traffic routes around the gray member with zero errors WHILE
        # it is slow.  The member may legitimately restore mid-phase
        # (host-load noise inflates the 2-member peer reference; the
        # 250ms probes re-convict it within ticks) — only posts made
        # while demoted count toward the routed-around pin.
        routed = 0
        for i in range(20):
            if gray not in slow_set():
                time.sleep(0.3)
                continue
            resp = httpx.post(
                f.router_url + "/",
                data={"file": _data_url(100 + i), "layer": "b2c1"},
                timeout=60,
            )
            assert resp.status_code == 200, resp.text
            assert resp.headers["x-backend"] == healthy
            routed += 1
            if routed >= 4:
                break
        assert routed >= 4, "never observed demoted routing while slow"
        # disarm: probe RTTs recover, the member is restored
        r = httpx.post(
            f.router_url + "/v1/debug/faults",
            data={"disarm": "fleet.head_delay_ms"},
            timeout=10,
        )
        assert r.status_code == 200
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and gray in slow_set():
            time.sleep(0.2)
        assert gray not in slow_set(), "gray backend never restored"
        assert f.router.members[gray].state == "healthy"
        assert (
            f.router.metrics.labeled("slow_ejections_total")[gray] >= 1
        )
