"""Fault-injection registry + deadline propagation (round 9,
serving/faults.py): spec grammar, deterministic firing, the
zero-overhead disabled hook, the guarded debug endpoint, x-deadline-ms
end to end, and the singleflight waiter's independent deadline.
Fast-lane by design — clocks are short or injected."""

import asyncio
import time

import httpx
import pytest

from deconv_api_tpu import errors
from deconv_api_tpu.config import ServerConfig
from deconv_api_tpu.serving import faults
from deconv_api_tpu.serving.cache import Singleflight
from deconv_api_tpu.serving.faults import (
    FaultRegistry,
    parse_fault_specs,
    parse_spec,
)
from deconv_api_tpu.serving.metrics import Metrics
from deconv_api_tpu.serving.trace import deadline_from
from tests.test_serving import ServiceFixture, _data_url

# ------------------------------------------------------------ spec grammar


def test_parse_spec_forms():
    assert parse_spec("p0.05").p == 0.05
    assert parse_spec("0.25").p == 0.25
    s = parse_spec("n3")
    assert s.n == 3 and s.p == 1.0
    s = parse_spec("p0.5:100")
    assert s.p == 0.5 and s.param == 100.0
    assert parse_spec("n2:250").param == 250.0
    assert str(parse_spec("p0.05")) == "p0.05"
    assert str(parse_spec("n2:250")) == "n2:250"


@pytest.mark.parametrize("bad", ["", "p0", "p1.5", "n0", "n-1", "xyz", "p:5"])
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_spec(bad)


def test_parse_fault_specs_multi_and_unknown_site():
    specs = parse_fault_specs(
        "codec.worker_raise=p0.05,device.dispatch_delay_ms=n2:100"
    )
    assert set(specs) == {"codec.worker_raise", "device.dispatch_delay_ms"}
    assert specs["device.dispatch_delay_ms"].param == 100.0
    with pytest.raises(ValueError, match="unknown fault site"):
        parse_fault_specs("codec.worker_rais=p1")
    with pytest.raises(ValueError, match="site=spec"):
        parse_fault_specs("codec.worker_raise")


# ------------------------------------------------------------- registry


def test_one_shot_fires_exactly_n_then_disarms():
    reg = FaultRegistry()
    reg.arm("device.dispatch_error", "n3")
    fired = [reg.check("device.dispatch_error") for _ in range(10)]
    assert sum(a is not None for a in fired) == 3
    assert all(a is not None for a in fired[:3])  # p=1: the FIRST three
    assert reg.snapshot()["armed"] == {}  # self-disarmed at zero
    assert reg.snapshot()["injected"] == {"device.dispatch_error": 3}


def test_probabilistic_firing_deterministic_under_seed():
    def sequence(seed):
        reg = FaultRegistry(seed=seed)
        reg.arm("codec.worker_raise", "p0.5")
        return [reg.check("codec.worker_raise") is not None for _ in range(64)]

    a, b = sequence(7), sequence(7)
    assert a == b  # same seed -> same firing sequence (replayable chaos)
    assert 5 < sum(a) < 59  # and it actually is probabilistic


def test_disabled_hook_is_inert():
    """The zero-cost path: no registry installed -> one global load, no
    action, no accounting.  A registry with the site DISARMED is also
    side-effect free."""
    assert faults.installed() is None
    assert faults.check("codec.worker_raise") is None
    m = Metrics()
    reg = FaultRegistry(metrics=m)
    faults.install(reg)
    try:
        assert faults.check("codec.worker_raise") is None
        assert m.labeled("faults_injected_total") == {}
    finally:
        faults.uninstall(reg)
    assert faults.installed() is None


def test_uninstall_only_evicts_own_registry():
    a, b = FaultRegistry(), FaultRegistry()
    faults.install(a)
    faults.install(b)
    try:
        faults.uninstall(a)  # stale owner: must NOT evict b
        assert faults.installed() is b
    finally:
        faults.uninstall(b)


def test_injection_counter_labeled_by_site():
    m = Metrics()
    reg = FaultRegistry(metrics=m)
    reg.arm("device.dispatch_error", "n2")
    reg.arm("http.slow_write", "n1:10")
    for _ in range(3):
        reg.check("device.dispatch_error")
    reg.check("http.slow_write")
    assert m.labeled("faults_injected_total") == {
        "device.dispatch_error": 2,
        "http.slow_write": 1,
    }
    text = m.prometheus()
    assert '# TYPE deconv_faults_injected_total counter' in text
    assert 'deconv_faults_injected_total{site="device.dispatch_error"} 2' in text


def test_registry_rejects_unknown_site():
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultRegistry().arm("nope.bad_site", "p1")


# -------------------------------------------------------- deadline parsing


def test_deadline_from_sane_and_insane():
    now = 100.0
    assert deadline_from("250", now=now) == pytest.approx(100.25)
    assert deadline_from(None) is None
    assert deadline_from("") is None
    assert deadline_from("abc") is None
    assert deadline_from("-5") is None
    assert deadline_from("0") is None
    assert deadline_from(str(10**9)) is None  # > a day: client bug, ignored


def test_singleflight_waiter_honors_own_deadline():
    """A coalesced waiter 504s on ITS deadline while the shared flight
    (and the leader) live on — the flight future is neither cancelled
    nor resolved by the timed-out waiter."""

    async def go():
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        t0 = time.perf_counter()
        with pytest.raises(errors.DeadlineExpired):
            await Singleflight.wait(fut, deadline=t0 + 0.05)
        assert time.perf_counter() - t0 < 1.0
        assert not fut.cancelled() and not fut.done()
        # an already-lapsed deadline fails without awaiting at all
        with pytest.raises(errors.DeadlineExpired):
            await Singleflight.wait(fut, deadline=time.perf_counter() - 1)
        fut.set_result("late")  # flight completes normally for others
        assert await Singleflight.wait(fut) == "late"

    asyncio.run(go())


# ------------------------------------------------------------- e2e service


@pytest.fixture(scope="module")
def chaos_server():
    cfg = ServerConfig(
        image_size=16,
        max_batch=4,
        batch_window_ms=1.0,
        compilation_cache_dir="",
        fault_injection=True,
        fault_seed=0,
    )
    with ServiceFixture(cfg) as s:
        yield s
        # tests arm one-shot (n) faults; anything left is a test bug
        assert s.service.faults.snapshot()["armed"] == {}


def _arm(server, spec: str):
    r = httpx.post(server.base_url + "/v1/debug/faults", data={"arm": spec})
    assert r.status_code == 200, r.text
    return r.json()


def test_debug_faults_404_when_disabled():
    cfg = ServerConfig(
        image_size=16, max_batch=4, batch_window_ms=1.0,
        compilation_cache_dir="",
    )
    with ServiceFixture(cfg) as s:
        r = httpx.post(s.base_url + "/v1/debug/faults", data={"arm": "x=p1"})
        assert r.status_code == 404  # invisible unless fault_injection on


def test_debug_faults_arm_snapshot_disarm(chaos_server):
    snap = _arm(chaos_server, "device.dispatch_delay_ms=p0.5:100")["faults"]
    assert snap["armed"] == {"device.dispatch_delay_ms": "p0.5:100"}
    r = httpx.post(
        chaos_server.base_url + "/v1/debug/faults", data={"disarm": "all"}
    )
    assert r.status_code == 200
    assert r.json()["faults"]["armed"] == {}
    # bad specs answer 400, not a crashed handler
    r = httpx.post(
        chaos_server.base_url + "/v1/debug/faults", data={"arm": "bogus=p1"}
    )
    assert r.status_code == 400
    assert r.json()["error"] == "bad_request"


def test_device_dispatch_error_maps_to_fault_injected_500(chaos_server):
    _arm(chaos_server, "device.dispatch_error=n1")
    r = httpx.post(
        chaos_server.base_url + "/",
        data={"file": _data_url(), "layer": "b2c1"},
        headers={"cache-control": "no-store"},
        timeout=30,
    )
    assert r.status_code == 500
    assert r.json()["error"] == "fault_injected"
    # one-shot: the very next identical request computes fine
    r = httpx.post(
        chaos_server.base_url + "/",
        data={"file": _data_url(), "layer": "b2c1"},
        headers={"cache-control": "no-store"},
        timeout=30,
    )
    assert r.status_code == 200, r.text


def test_http_slow_write_delays_response(chaos_server):
    # n2: the arm endpoint's OWN response is also a tracked write and
    # consumes the first shot; the probed GET consumes the second
    _arm(chaos_server, "http.slow_write=n2:120")
    t0 = time.perf_counter()
    r = httpx.get(chaos_server.base_url + "/health-check", timeout=10)
    dt = time.perf_counter() - t0
    assert r.status_code == 200
    assert dt >= 0.1  # the injected write stall is client-visible
    t0 = time.perf_counter()
    httpx.get(chaos_server.base_url + "/health-check", timeout=10)
    assert time.perf_counter() - t0 < 0.1  # one-shot: back to fast


def test_deadline_expired_504_end_to_end(chaos_server):
    """An x-deadline-ms the server cannot possibly meet 504s with the
    deadline taxonomy code, carries the request id, and bumps the
    deadline_expired_total counter — without burning the 60 s timeout."""
    before = chaos_server.service.metrics.counter("deadline_expired_total")
    t0 = time.perf_counter()
    r = httpx.post(
        chaos_server.base_url + "/",
        data={"file": _data_url(), "layer": "b2c1"},
        headers={"x-deadline-ms": "0.01", "cache-control": "no-store"},
        timeout=30,
    )
    assert time.perf_counter() - t0 < 5.0
    assert r.status_code == 504
    assert r.json()["error"] == "deadline_expired"
    assert r.headers["x-request-id"]
    after = chaos_server.service.metrics.counter("deadline_expired_total")
    assert after > before
    # a generous deadline serves normally
    r = httpx.post(
        chaos_server.base_url + "/",
        data={"file": _data_url(), "layer": "b2c1"},
        headers={"x-deadline-ms": "30000", "cache-control": "no-store"},
        timeout=30,
    )
    assert r.status_code == 200, r.text


def test_leader_deadline_does_not_poison_coalesced_waiters(chaos_server):
    """A flight leader whose PERSONAL x-deadline-ms lapses fails with
    504 deadline_expired; coalesced waiters (who sent no deadline) get a
    retryable 503 unavailable — never a 504 that is not theirs."""
    import threading

    _arm(chaos_server, "device.dispatch_delay_ms=n1:500")
    form = {"file": _data_url(rng_seed=77), "layer": "b2c1"}
    results = {}

    def leader():
        results["leader"] = httpx.post(
            chaos_server.base_url + "/", data=form,
            headers={"x-deadline-ms": "150"}, timeout=30,
        )

    def waiter():
        results["waiter"] = httpx.post(
            chaos_server.base_url + "/", data=form, timeout=30
        )

    tl = threading.Thread(target=leader)
    tl.start()
    time.sleep(0.1)  # leader owns the flight before the waiter arrives
    tw = threading.Thread(target=waiter)
    tw.start()
    tl.join(20)
    tw.join(20)
    lr, wr = results["leader"], results["waiter"]
    assert lr.status_code == 504 and lr.json()["error"] == "deadline_expired"
    assert wr.status_code == 503, wr.text
    assert wr.json()["error"] == "unavailable"
    assert wr.headers.get("x-cache") == "coalesced"


def test_config_reports_fault_state(chaos_server):
    cfg = httpx.get(chaos_server.base_url + "/v1/config").json()
    assert cfg["fault_injection_active"] is True
    assert cfg["breaker_active"] is True
    assert cfg["breaker_state"] == "closed"
    assert cfg["draining"] is False
    assert cfg["codec_workers_live"] >= 1
    assert "injected" in cfg["faults_state"]


def test_live_metrics_exposition_lints_with_fault_series(chaos_server):
    from tests.test_metrics_exposition import lint_exposition

    _arm(chaos_server, "device.dispatch_error=n1")
    httpx.post(
        chaos_server.base_url + "/",
        data={"file": _data_url(), "layer": "b2c1"},
        headers={"cache-control": "no-store"},
        timeout=30,
    )
    text = httpx.get(chaos_server.base_url + "/v1/metrics").text
    families, samples = lint_exposition(text)
    assert families["deconv_faults_injected_total"] == "counter"
    assert families["deconv_breaker_state"] == "gauge"
    assert families["deconv_codec_workers_live"] == "gauge"
    assert (
        "deconv_faults_injected_total",
        'site="device.dispatch_error"',
    ) in samples
